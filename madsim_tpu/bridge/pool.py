# tracelint: hot-loop
"""Forked worker pool: parallel Python task bodies behind the device kernel.

docs/bridge.md pins the bridge's Amdahl ceiling: the device decision
kernel is ~5-15% of a lockstep round, the rest is the single serial
CPython interpreter running task bodies plus the per-world pack loop
(224 ms of a 295 ms round at W=4096). Per-slot state is independent by
construction and the kernel already batches W slots, so the serial
fraction is embarrassingly parallel — this module cracks it:

- ``sweep_pooled(world_fn, seeds, jobs=J)`` shards the W kernel slots
  across J forked workers. Each worker owns a CONTIGUOUS slot slice —
  its ``Runtime`` object graphs live only in that worker (the W=4096
  cache collapse fix) — and drives it with the same
  :class:`~madsim_tpu.bridge.runtime.SliceDriver` seam the serial loop
  uses, so bit-identity is structural, not re-implemented.
- Workers are forked, not spawned: the parent has already imported this
  package (and holds the ``world_fn`` closure), so per-worker warmup is
  ONE fork, not an interpreter boot — and ``world_fn``/``configs`` need
  no pickling. Workers never touch jax; the device kernel lives only in
  the parent (forking a jax-live parent is safe exactly because the
  children never re-enter the inherited XLA state).
- Each worker packs its slice DIRECTLY into a shared-memory (W, ...)
  batch region (one ``multiprocessing.shared_memory`` segment per
  (T, C, S) bucket, masks-only clears preserved), so the parent does
  zero per-world Python work: it barriers the round, hands the shared
  batch to the jitted kernel step, scatters the StepOut into a shared
  output region, and the workers settle their own rows. Drain rounds
  keep PR 4's dispatch-ahead overlap: drain r+1 is in the device queue
  while the workers fire drain r's events.

Determinism is the contract and the test: per-seed traces, send
accounting, and mixed-outcome attribution are bit-identical to
``jobs=1`` and to the serial bridge for every J and every W%J remainder
(tests/test_bridge_pool.py, tools/bridge_pool_demo.py), exactly as
``bridge.sweep(batch=N)`` gates batching. Worker death mid-round raises
a pointed :class:`BridgePoolError` naming the worker, its slot range,
and the round — no hangs, no partial batches, and every shared-memory
segment is unlinked on the way out.

Sync discipline (DET008/DET009): the parent round loop's only blocking
device->host reads are the kernel step/drain materializations, routed
through the sanctioned :func:`_fetch` seam below so the static pass and
the counted-fetch tests see one auditable site.
"""
from __future__ import annotations

import os
import pickle
import warnings
from collections import OrderedDict
from typing import List, NamedTuple, Optional, Tuple

import numpy as np

from .kernel import BridgeKernel, HostBatch, StepOut, bucket
from .runtime import Outcome, SliceDriver


def _fetch(x) -> np.ndarray:
    """THE sanctioned blocking device->host seam of the pool round loop
    (the `_fetch` discipline of docs/perf.md "Pipelined orchestration"):
    drain outputs are dispatched ahead and materialized here, after the
    next drain is already in the device queue. Tests monkeypatch this to
    count syncs."""
    return np.asarray(x)


class BridgePoolError(RuntimeError):
    """A pool worker died (or errored) mid-sweep.

    Carries ``worker`` (index), ``slots`` (the worker's (lo, hi) global
    slot range, half-open), and ``round_no`` so the failure names exactly
    which slice of which lockstep round was lost. The parent kills the
    remaining workers and unlinks every shared-memory segment before
    raising — no hangs, no partial batches, no orphaned segments.
    """

    def __init__(self, message: str, *, worker: Optional[int] = None,
                 slots: Optional[Tuple[int, int]] = None,
                 round_no: Optional[int] = None):
        super().__init__(message)
        self.worker = worker
        self.slots = slots
        self.round_no = round_no


# ---------------------------------------------------------------------------
# Shared-memory layout
# ---------------------------------------------------------------------------

# One segment per (T, C, S) bucket holds the whole 18-array HostBatch,
# field order matching bridge/kernel.py HostBatch. Axis letters map to
# the padded widths: t/c/s -> T/C/S columns, w -> the flat [W] lanes.
_BATCH_SPECS = (
    ("t_slot", "t", np.int32), ("t_dl", "t", np.int64),
    ("t_seq", "t", np.int64), ("t_mask", "t", np.bool_),
    ("c_slot", "c", np.int32), ("c_mask", "c", np.bool_),
    ("s_ctr", "s", np.uint64), ("s_base", "s", np.int64),
    ("s_slot", "s", np.int32), ("s_seq", "s", np.int64),
    ("s_thr", "s", np.uint64), ("s_lossall", "s", np.bool_),
    ("s_lat_lo", "s", np.int64), ("s_lat_w", "s", np.int64),
    ("s_mask", "s", np.bool_), ("s_live", "s", np.bool_),
    ("clock", "w", np.int64), ("advance", "w", np.bool_),
)


class PoolOut(NamedTuple):
    """The shared step/drain output region (one segment per S bucket).

    ``drain_fire`` is the drain-round fire mask: the PREVIOUS round's
    more_due — which worlds this drain was dispatched for — written by
    the parent before each drain broadcast (StepOut's own ``more_due``
    is the post-pop flag the settle phase reads for woke detection)."""

    clock: np.ndarray        # i64[W]
    deadlock: np.ndarray     # bool[W]
    send_ok: np.ndarray      # bool[W, S]
    event_seq: np.ndarray    # i64[W, K]
    event_valid: np.ndarray  # bool[W, K]
    more_due: np.ndarray     # bool[W]
    drain_fire: np.ndarray   # bool[W]


def _carve(buf, specs) -> Tuple[list, int]:
    """Carve 8-byte-aligned numpy views out of one flat buffer."""
    views, off = [], 0
    for shape, dt in specs:
        off = (off + 7) & ~7
        a = np.ndarray(shape, dt, buffer=buf, offset=off)
        views.append(a)
        off += a.nbytes
    return views, off


def _batch_shapes(W: int, T: int, C: int, S: int) -> list:
    dims = {"t": T, "c": C, "s": S}
    return [((W,) if ax == "w" else (W, dims[ax]), dt)
            for _name, ax, dt in _BATCH_SPECS]


def _out_shapes(W: int, S: int, K: int) -> list:
    return [((W,), np.int64), ((W,), np.bool_), ((W, S), np.bool_),
            ((W, K), np.int64), ((W, K), np.bool_), ((W,), np.bool_),
            ((W,), np.bool_)]


def _nbytes(specs) -> int:
    off = 0
    for shape, dt in specs:
        off = (off + 7) & ~7
        off += int(np.prod(shape)) * np.dtype(dt).itemsize
    return max(off, 1)


def _attach(name: str):
    """Worker-side attach to a parent-owned segment.

    CPython 3.10's ``SharedMemory(name=...)`` registers even pure
    attachments with the resource tracker as if they were owned. That is
    benign here BECAUSE the workers are forked: they share the parent's
    tracker process, whose per-name cache is a set — the worker's
    register dedupes against the parent's, and the parent's unlink
    unregisters once for everyone. (Unregistering here instead would
    strip the parent's entry and make its own unlink warn.)"""
    from multiprocessing import shared_memory

    return shared_memory.SharedMemory(name=name)


_SHM_PREFIX = "msbp"
_POOL_SEQ = [0]  # per-process pool counter (unique segment names)


class _SegmentStore:
    """Parent-owned named shared-memory segments: batch regions per
    (T, C, S) bucket and output regions per S bucket, LRU-bounded like
    the serial pack-buffer cache — evicted segments are closed and
    unlinked immediately (workers' live attachments keep the mapping
    valid; names are never reused)."""

    def __init__(self, W: int, k_events: int, maxsize: int = 8):
        self.W = W
        self.K = k_events
        self.maxsize = maxsize
        _POOL_SEQ[0] += 1
        self._uid = f"{_SHM_PREFIX}-{os.getpid()}-{_POOL_SEQ[0]}"
        self._seq = 0
        self._batch: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._out: "OrderedDict[int, tuple]" = OrderedDict()

    def _create(self, specs):
        from multiprocessing import shared_memory

        self._seq += 1
        name = f"{self._uid}-{self._seq}"
        shm = shared_memory.SharedMemory(create=True, size=_nbytes(specs),
                                         name=name)
        views, _ = _carve(shm.buf, specs)
        return name, shm, views

    @staticmethod
    def _evict(cache, maxsize):
        while len(cache) > maxsize:
            _key, (_name, shm, _views) = cache.popitem(last=False)
            shm.close()
            shm.unlink()

    def batch(self, T: int, C: int, S: int) -> Tuple[str, list]:
        key = (T, C, S)
        ent = self._batch.get(key)
        if ent is None:
            ent = self._create(_batch_shapes(self.W, T, C, S))
            self._batch[key] = ent
            self._evict(self._batch, self.maxsize)
        else:
            self._batch.move_to_end(key)
        return ent[0], ent[2]

    def out(self, S: int) -> Tuple[str, PoolOut]:
        ent = self._out.get(S)
        if ent is None:
            name, shm, views = self._create(_out_shapes(self.W, S, self.K))
            ent = (name, shm, PoolOut(*views))
            self._out[S] = ent
            self._evict(self._out, self.maxsize)
        else:
            self._out.move_to_end(S)
        return ent[0], ent[2]

    def close(self) -> None:
        """Unlink everything (idempotent) — the no-orphaned-segments
        contract of BridgePoolError holds through this."""
        for cache in (self._batch, self._out):
            for _name, shm, _views in cache.values():
                try:
                    shm.close()
                    shm.unlink()
                except FileNotFoundError:  # pragma: no cover — already gone
                    pass
            cache.clear()


# ---------------------------------------------------------------------------
# Worker side
# ---------------------------------------------------------------------------


class _WorkerSegs:
    """Worker-side attachment cache (name -> (shm, views)), LRU-bounded;
    names are parent-unique so a cached view can never alias a stale
    segment."""

    def __init__(self, maxsize: int = 16):
        self.maxsize = maxsize
        self._segs: "OrderedDict[str, tuple]" = OrderedDict()

    def get(self, name: str, make_views):
        ent = self._segs.get(name)
        if ent is None:
            shm = _attach(name)
            ent = (shm, make_views(shm.buf))
            self._segs[name] = ent
            while len(self._segs) > self.maxsize:
                _n, (old, _v) = self._segs.popitem(last=False)
                old.close()
        else:
            self._segs.move_to_end(name)
        return ent[1]


def _picklable(outs: List[Outcome]) -> List[Outcome]:
    """Outcomes cross the pipe pickled; errors that cannot pickle are
    re-wrapped as RuntimeError with the original repr (same contract as
    the pre-pool forked shards)."""
    safe = []
    for o in outs:
        try:
            pickle.dumps(o)
            safe.append(o)
        except Exception:
            safe.append(Outcome(o.seed, None,
                                RuntimeError(f"unpicklable outcome: {o!r}")))
    return safe


def _worker_main(conn, idx: int, slot_lo: int, n_slots: int, seeds,
                 world_fn, k_events: int, kw: dict) -> None:
    """One forked worker: drive slots [slot_lo, slot_lo+n_slots) with a
    SliceDriver, barriered by the parent's round messages. Never touches
    jax — the decision kernel lives only in the parent."""
    try:
        drv = SliceDriver(world_fn, seeds, slot_lo=slot_lo, n_slots=n_slots,
                          **kw)
        segs = _WorkerSegs()
        W = None  # learned from the first pack (global batch width)

        def ready():
            resets = drv.top_up()
            t_n, c_n, s_n = drv.take_rounds()
            conn.send(("ready", (t_n, c_n, s_n), resets, drv.live_slots(),
                       drv.left))

        ready()
        while True:
            msg = conn.recv()
            tag = msg[0]
            if tag == "pack":
                _tag, W, T, C, S, name = msg
                views = segs.get(
                    name, lambda b: _carve(b, _batch_shapes(W, T, C, S))[0])
                drv.pack_into(views)
                conn.send(("packed",))
            elif tag == "settle":
                _tag, S, name = msg
                out = segs.get(
                    name,
                    lambda b: PoolOut(*_carve(
                        b, _out_shapes(W, S, k_events))[0]))
                drv.settle(out)
                conn.send(("settled", drv.live_slots()))
            elif tag == "drain":
                _tag, S, name = msg
                out = segs.get(
                    name,
                    lambda b: PoolOut(*_carve(
                        b, _out_shapes(W, S, k_events))[0]))
                drv.drain_assert(out.drain_fire)
                drv.fire_drain(out.event_valid, out.event_seq,
                               out.drain_fire)
                conn.send(("drained",))
            elif tag == "settle_host":
                # Merged fast path: the parent proved no drain round can
                # fire (no live world had >K events due), so settle,
                # woke host bursts, and admission collapse into ONE
                # barrier — the common round costs two round trips, not
                # three.
                _tag, S, name = msg
                out = segs.get(
                    name,
                    lambda b: PoolOut(*_carve(
                        b, _out_shapes(W, S, k_events))[0]))
                drv.settle(out)
                drv.run_woke()
                ready()
            elif tag == "host":
                drv.run_woke()
                ready()
            elif tag == "finish":
                conn.send(("outcomes", _picklable(drv.outcomes),
                           drv.traces))
                conn.close()
                return
            else:  # pragma: no cover — parent protocol bug
                raise RuntimeError(f"unknown pool message {tag!r}")
    except (EOFError, OSError, BrokenPipeError):  # parent gone
        os._exit(1)
    except BaseException as exc:  # noqa: BLE001 — report, then die loudly
        import traceback

        try:
            conn.send(("error", f"{type(exc).__name__}: {exc}",
                       traceback.format_exc()))
        except Exception:
            pass
        os._exit(1)


# ---------------------------------------------------------------------------
# Parent side
# ---------------------------------------------------------------------------


class _Worker(NamedTuple):
    idx: int
    proc: object          # multiprocessing.Process (fork context)
    conn: object          # parent end of the duplex pipe
    slot_lo: int
    n_slots: int
    pos_lo: int
    pos_hi: int


def _shard_plan(n: int, W: int, J: int) -> List[Tuple[int, int, int, int]]:
    """(slot_lo, n_slots, pos_lo, pos_hi) per worker: contiguous slot
    slices (first W%J workers take the extra slot) and proportional
    contiguous seed shards. ``pos = (n * slot_off) // W`` keeps every
    shard's seed count >= its slot count (n >= W), so every slot spawns
    a world on the initial fill, exactly like the serial loop."""
    base, extra = divmod(W, J)
    plan, off = [], 0
    for j in range(J):
        w_j = base + (1 if j < extra else 0)
        plan.append((off, w_j, (n * off) // W, (n * (off + w_j)) // W))
        off += w_j
    return plan


def _fork_worker(ctx, idx, slot_lo, n_slots, seeds, world_fn, k_events, kw):
    parent_conn, child_conn = ctx.Pipe()
    with warnings.catch_warnings():
        # jax warns on ANY os.fork() in a process with live XLA threads;
        # the hazard is a child re-entering inherited XLA state, which
        # pool workers never do (they run pure-Python task bodies).
        warnings.filterwarnings("ignore", message=".*os\\.fork\\(\\).*",
                                category=RuntimeWarning)
        p = ctx.Process(target=_worker_main,
                        args=(child_conn, idx, slot_lo, n_slots, seeds,
                              world_fn, k_events, kw),
                        daemon=True)
        p.start()
    child_conn.close()
    return parent_conn, p


def sweep_pooled(world_fn, seeds, *, jobs: int, config=None, configs=None,
                 cap: int = 128, k_events: int = 4, time_limit=None,
                 trace: bool = False, device: Optional[str] = None,
                 batch: Optional[int] = None,
                 stats: Optional[dict] = None
                 ) -> Tuple[List[Outcome], List[list]]:
    """One lockstep sweep, task bodies sharded across ``jobs`` forked
    workers behind ONE shared device decision kernel.

    Returns ``(outcomes, traces)`` exactly like the serial
    ``_sweep_impl`` — and bit-identically to it, per seed, for every
    ``jobs``/``batch`` split. ``stats`` (optional dict) receives the
    parent-observed per-phase wall windows for bench.py
    (``host_s``/``pack_s``/``dispatch_s``/``settle_s``/``parent_s``/
    ``rounds``/``drain_rounds``/``resets``).
    """
    import multiprocessing as mp

    seeds = [int(s) for s in seeds]
    n = len(seeds)
    if n == 0:
        return [], []
    W = n if batch is None else max(1, min(int(batch), n))
    J = max(1, min(int(jobs), W))
    plan = _shard_plan(n, W, J)
    kw = dict(cap=cap, time_limit=time_limit, trace=trace, config=config)

    if stats is not None:
        from time import perf_counter

        stats.update(rounds=0, drain_rounds=0, resets=0, host_s=0.0,
                     pack_s=0.0, dispatch_s=0.0, settle_s=0.0,
                     parent_s=0.0, workers=J, w=W)

        def _clk():
            # Wall-clock phase windows of the pool driver (bench only).
            return perf_counter()  # detlint: allow[DET001]
    else:
        def _clk():
            return 0.0

    # Fork FIRST (fork-server discipline: modules + world_fn are already
    # in this image, so each worker costs one fork), then build the
    # kernel — the children never re-enter the parent's jax state. The
    # resource tracker must be live BEFORE the fork: children then share
    # it, their attach-registrations dedupe against the parent's (set
    # semantics), and the parent's unlink unregisters once for everyone —
    # a child-spawned tracker would instead warn about "leaked" segments
    # it never owned.
    from multiprocessing import resource_tracker

    resource_tracker.ensure_running()
    ctx = mp.get_context("fork")
    workers: List[_Worker] = []
    for idx, (slot_lo, n_slots, pos_lo, pos_hi) in enumerate(plan):
        wkw = dict(kw)
        wkw["configs"] = (configs[pos_lo:pos_hi]
                          if configs is not None else None)
        conn, p = _fork_worker(ctx, idx, slot_lo, n_slots,
                               seeds[pos_lo:pos_hi], world_fn, k_events,
                               wkw)
        workers.append(_Worker(idx, p, conn, slot_lo, n_slots,
                               pos_lo, pos_hi))

    # Kernel slot keys = each worker's initial fill, in slot order (the
    # SliceDriver free list admits its first n_slots seeds into local
    # slots 0..n_slots-1).
    kernel_seeds = []
    for w in workers:
        kernel_seeds.extend(seeds[w.pos_lo:w.pos_lo + w.n_slots])
    kernel = BridgeKernel(kernel_seeds, cap=cap, k_events=k_events,
                          device=device)
    segs = _SegmentStore(W, k_events)
    live = np.zeros(W, np.bool_)
    round_no = 0

    def fail(w: _Worker, phase: str, remote: Optional[tuple] = None):
        if remote is not None:
            raise BridgePoolError(
                f"bridge pool worker {w.idx} (slots {w.slot_lo}.."
                f"{w.slot_lo + w.n_slots - 1}) failed during round "
                f"{round_no} ({phase}): {remote[0]}\n{remote[1]}",
                worker=w.idx, slots=(w.slot_lo, w.slot_lo + w.n_slots),
                round_no=round_no)
        w.proc.join(timeout=1.0)  # reap, so the exitcode names the signal
        raise BridgePoolError(
            f"bridge pool worker {w.idx} (slots {w.slot_lo}.."
            f"{w.slot_lo + w.n_slots - 1}) died during round {round_no} "
            f"({phase} phase, exitcode {w.proc.exitcode})",
            worker=w.idx, slots=(w.slot_lo, w.slot_lo + w.n_slots),
            round_no=round_no)

    def gather(expect: str, phase: str) -> dict:
        """Collect one ``expect`` message per worker; a worker dying (or
        reporting an error) raises the pointed BridgePoolError instead of
        hanging the barrier."""
        from multiprocessing.connection import wait as conn_wait

        got: dict = {}
        remaining = {w.conn: w for w in workers}
        while remaining:
            ready = conn_wait(list(remaining), timeout=0.25)
            if not ready:
                for conn, w in list(remaining.items()):
                    if not w.proc.is_alive():
                        fail(w, phase)
                continue
            for conn in ready:
                w = remaining[conn]
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    fail(w, phase)
                if msg[0] == "error":
                    fail(w, phase, remote=(msg[1], msg[2]))
                got[w.idx] = msg[1:]
                del remaining[conn]
        return got

    def broadcast(msg) -> None:
        for w in workers:
            try:
                w.conn.send(msg)
            except (OSError, BrokenPipeError):
                fail(w, msg[0])

    def apply_live(w: _Worker, live_rows: List[int]) -> None:
        live[w.slot_lo:w.slot_lo + w.n_slots] = False
        if live_rows:
            live[live_rows] = True

    try:
        t0 = _clk()
        ready = gather("ready", "host")
        if stats is not None:
            stats["host_s"] += _clk() - t0
        while True:
            t0 = _clk()
            t_n = c_n = s_n = left = 0
            resets: List[Tuple[int, int]] = []
            for w in workers:
                counts, rs, live_rows, w_left = ready[w.idx]
                t_n, c_n, s_n = (max(t_n, counts[0]), max(c_n, counts[1]),
                                 max(s_n, counts[2]))
                resets.extend(rs)
                apply_live(w, live_rows)
                left += w_left
            if not live.any() and left == 0:
                break
            # Re-key recycled slots before the step that ships the fresh
            # worlds' first recorded activity — the same dispatch point
            # the serial loop resets at, one batched device write.
            kernel.reset_slots(resets)
            T, C, S = bucket(t_n), bucket(c_n), bucket(s_n)
            name, views = segs.batch(T, C, S)
            oname, out_views = segs.out(S)
            if stats is not None:
                stats["parent_s"] += _clk() - t0
                stats["rounds"] += 1
                stats["resets"] += len(resets)
            t0 = _clk()
            broadcast(("pack", W, T, C, S, name))
            gather("packed", "pack")
            if stats is not None:
                stats["pack_s"] += _clk() - t0
            t0 = _clk()
            # The whole (W, ...) round batch goes to the device straight
            # from shared memory; the StepOut scatters straight back
            # (kernel.step(out=...) — the shared-memory egress seam).
            out = kernel.step(
                HostBatch(*views),
                out=StepOut(clock=out_views.clock,
                            deadlock=out_views.deadlock,
                            send_ok=out_views.send_ok, event_slot=None,
                            event_seq=out_views.event_seq,
                            event_valid=out_views.event_valid,
                            more_due=out_views.more_due))
            if stats is not None:
                stats["dispatch_s"] += _clk() - t0
            more = out.more_due
            if not bool((live & more).any()):
                # No drain round can fire (live only shrinks during a
                # settle, so the pre-settle mask is a safe upper bound):
                # settle + woke host bursts + admission collapse into one
                # barrier.
                t0 = _clk()
                broadcast(("settle_host", S, oname))
                round_no += 1
                ready = gather("ready", "settle_host")
                if stats is not None:
                    stats["host_s"] += _clk() - t0
                continue
            t0 = _clk()
            broadcast(("settle", S, oname))
            settled = gather("settled", "settle")
            for w in workers:
                apply_live(w, settled[w.idx][0])
            # Drain chain: pop-only kernel, dispatch-ahead — drain r+1
            # enters the device queue before the workers fire round r's
            # events; the speculative tail round pops nothing.
            more = more.copy()
            inflight = kernel.drain() if bool((live & more).any()) else None
            while inflight is not None:
                if stats is not None:
                    stats["drain_rounds"] += 1
                cur = inflight
                inflight = kernel.drain()
                out_views.drain_fire[:] = more
                out_views.event_seq[:] = _fetch(cur.event_seq)
                out_views.event_valid[:] = _fetch(cur.event_valid)
                more = _fetch(cur.more_due)
                broadcast(("drain", S, oname))
                gather("drained", "drain")
                if not bool((live & more).any()):
                    break  # the in-flight round is the no-op tail
            if stats is not None:
                stats["settle_s"] += _clk() - t0
            t0 = _clk()
            broadcast(("host",))
            round_no += 1
            ready = gather("ready", "host")
            if stats is not None:
                stats["host_s"] += _clk() - t0

        broadcast(("finish",))
        finals = gather("outcomes", "finish")
        outcomes: List[Optional[Outcome]] = [None] * n
        traces: List[list] = [[] for _ in range(n)]
        for w in workers:
            outs, trs = finals[w.idx]
            outcomes[w.pos_lo:w.pos_hi] = outs
            traces[w.pos_lo:w.pos_hi] = trs
        for w in workers:
            w.proc.join(timeout=10.0)
        return outcomes, traces
    finally:
        for w in workers:
            if w.proc.is_alive():
                w.proc.terminate()
        for w in workers:
            w.proc.join(timeout=5.0)
            w.conn.close()
        segs.close()
