"""The device decision kernel of the host↔device bridge.

One jitted XLA step advances W independent simulation worlds at once:
it integrates the timers and sends the host recorded while executing task
bodies, samples every message's loss/latency from the per-world NET
Threefry stream by *counter* (bit-identical to the host engine's own
draws, see `core/rng.py` stream map), selects each world's next event,
advances its virtual clock, and pops the due events in the exact
``(deadline, seq)`` order the host timer wheel would have used
(`core/timewheel.py:135-161`).

This is SURVEY §7 stage 4 as designed: the decision kernel — next-event
selection, clock, RNG, link sampling — is data-parallel over seeds and
lives on the device; arbitrary Python task bodies stay on the host
(`madsim_tpu/bridge/runtime.py` drives them in lockstep). Reference
behavior being batched: `madsim/src/sim/time/mod.rs:45-60`
(advance_to_next_event) and `net/network.rs:249-257` (test_link), for all
W seeds per step instead of one at a time.

State layout (arrays carry a leading W axis):
- ``clock``        i64[W]        virtual ns, host-advanced between steps
- ``lane_dl``      i64[W, CAP+1] timer deadlines (INF = empty; the last
                                 column is a scatter dump for masked ops)
- ``lane_seq``     i64[W, CAP+1] creation order, the heap tie-breaker

Network config travels *per send* (loss threshold, latency bounds): each
world carries its own ``Config``, so one compiled sweep explores a
(seeds × loss × latency) grid — a batched axis the reference cannot have
(its config is one global per run, `network.rs:74-94`) — and hot
``update_config`` calls take effect at exactly the same send the host
engine would apply them.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import numpy as np

from ..core.timewheel import TIMER_MAX_NS

# Empty-lane sentinel. Deadlines clamp at TIMER_MAX_NS = 2^62-1, and the
# clock can creep slightly past a clamped deadline (advance epsilon, poll
# jitter), so the sentinel must sit far above any *reachable clock*, not
# just above any deadline — otherwise empty lanes read as due and the
# drain loop never terminates. i64 max gives 2^61 ns of headroom.
INF_NS = (1 << 63) - 1
_EPSILON_NS = 50  # core/timewheel.py ADVANCE_EPSILON_NS


class BridgeState(NamedTuple):
    clock: object     # i64[W]
    lane_dl: object   # i64[W, CAP+1]
    lane_seq: object  # i64[W, CAP+1]


class BridgeMetrics(NamedTuple):
    """Per-slot observability counters (obs/metrics.py's block, shaped
    for the bridge: i64[W] lanes accumulated ON DEVICE inside the jitted
    step — the host never pays a per-round pull for them).

    Counters are per *slot*, cumulative across recycled seeds
    (``reset_slot`` leaves them running): the fleet-aggregate frame the
    profiled sweep reports (``sweep_profiled``'s ``sim_metrics``) is
    exact either way, and zeroing on recycle would force a device
    read-back per retirement. Write-only within the step — the
    bitwise-invisibility contract of the device engine's MetricsBlock
    holds here too (metrics-on trajectories are bit-identical,
    tests/test_obs.py).
    """

    timers_set: object    # i64[W] — lane adds shipped to the device
    cancels: object       # i64[W]
    msgs_sent: object     # i64[W] — send attempts (loss drawn on device)
    msgs_lost: object     # i64[W] — sends the loss draw dropped
    events_fired: object  # i64[W] — due events popped (step + drain)
    vtime_ns: object      # i64[W] — device-observed clock advance


class StepOut(NamedTuple):
    clock: object        # i64[W] — after advance
    deadlock: object     # bool[W] — advance requested but no timers pending
    send_ok: object      # bool[W, S] — send passed the loss draw
    event_slot: object   # i32[W, K] — popped lane slots (host frees them)
    event_seq: object    # i64[W, K] — popped seqs (host dispatch key)
    event_valid: object  # bool[W, K]
    more_due: object     # bool[W] — >K events were due; drain before polls


class HostBatch(NamedTuple):
    """One lockstep round of recorded host activity, padded to bucketed
    shapes (numpy; converted at the device boundary)."""

    t_slot: np.ndarray   # i32[W, T] new-timer lane slots
    t_dl: np.ndarray     # i64[W, T] absolute deadlines
    t_seq: np.ndarray    # i64[W, T]
    t_mask: np.ndarray   # bool[W, T]
    c_slot: np.ndarray   # i32[W, C] cancelled lane slots
    c_mask: np.ndarray   # bool[W, C]
    s_ctr: np.ndarray    # u64[W, S] NET-stream counter of the loss draw
    s_base: np.ndarray   # i64[W, S] elapsed_ns at the send
    s_slot: np.ndarray   # i32[W, S] delivery lane slot (live sends)
    s_seq: np.ndarray    # i64[W, S]
    s_thr: np.ndarray    # u64[W, S] loss threshold (per-send config)
    s_lossall: np.ndarray  # bool[W, S] loss rate >= 1.0
    s_lat_lo: np.ndarray   # i64[W, S] latency lower bound (ns)
    s_lat_w: np.ndarray    # i64[W, S] latency width (ns, >= 1)
    s_mask: np.ndarray   # bool[W, S]
    s_live: np.ndarray   # bool[W, S] has a destination socket (schedule it)
    clock: np.ndarray    # i64[W]
    advance: np.ndarray  # bool[W] advance to next event (False = drain only)


def _u64_block(k0, k1, ctr):
    """threefry block ``ctr`` (u64 counter) → u64; GlobalRng.next_u64
    parity ((x1 << 32) | x0 at counter split lo/hi)."""
    import jax.numpy as jnp

    from ..ops.threefry import threefry2x32_jax

    c0 = (ctr & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    c1 = (ctr >> jnp.uint64(32)).astype(jnp.uint32)
    x0, x1 = threefry2x32_jax(k0, k1, c0, c1)
    return x0.astype(jnp.uint64) | (x1.astype(jnp.uint64) << jnp.uint64(32))


def _step(state: BridgeState, mb, net_k0, net_k1,
          t_slot, t_dl, t_seq, t_mask,
          c_slot, c_mask,
          s_ctr, s_base, s_slot, s_seq, s_thr, s_lossall,
          s_lat_lo, s_lat_w, s_mask, s_live,
          clock_in, advance, *, cap: int, k_events: int,
          metrics: bool = False):
    import jax.numpy as jnp

    W = clock_in.shape[0]
    rows = jnp.arange(W)[:, None]
    dump = jnp.int32(cap)  # the scatter dump column

    lane_dl, lane_seq = state.lane_dl, state.lane_seq

    # 1. Cancels first: a slot cancelled and reused within one host batch
    #    must end up holding the new timer (runtime.py dedups the rest).
    c_slot = jnp.where(c_mask, c_slot, dump)
    lane_dl = lane_dl.at[rows, c_slot].set(jnp.int64(INF_NS))

    # 2. New timers.
    t_slot = jnp.where(t_mask, t_slot, dump)
    lane_dl = lane_dl.at[rows, t_slot].set(t_dl)
    lane_seq = lane_seq.at[rows, t_slot].set(t_seq)

    # 3. Sends: loss draw at ctr, latency draw at ctr+1 — the counters the
    #    host's own Network.test_link would have consumed (network.py:182).
    u_loss = _u64_block(net_k0[:, None], net_k1[:, None], s_ctr)
    u_lat = _u64_block(net_k0[:, None], net_k1[:, None],
                       s_ctr + jnp.uint64(1))
    lost = (u_loss < s_thr) | s_lossall
    ok = s_mask & ~lost
    latency = s_lat_lo + (u_lat % s_lat_w.astype(jnp.uint64)).astype(jnp.int64)
    deliver = ok & s_live
    s_slot = jnp.where(deliver, s_slot, dump)
    # Same horizon clamp as the host wheel's add_timer_at: a delivery
    # scheduled past TIMER_MAX_NS must land on the same clamped instant.
    send_dl = jnp.minimum(s_base + latency, jnp.int64(TIMER_MAX_NS))
    lane_dl = lane_dl.at[rows, s_slot].set(send_dl)
    lane_seq = lane_seq.at[rows, s_slot].set(s_seq)

    # 4. Advance each world's clock to its next event
    #    (time/mod.rs:45-60: target = max(earliest + ε, now)).
    live_dl = lane_dl[:, :cap]
    min_dl = live_dl.min(axis=1)
    has_timer = min_dl < INF_NS
    do_adv = advance & has_timer
    new_clock = jnp.where(do_adv,
                          jnp.maximum(clock_in, min_dl + _EPSILON_NS),
                          clock_in)
    deadlock = advance & ~has_timer

    # 5. Pop due entries (deadline <= clock) in (deadline, seq) order —
    #    exactly the host heap's pop order. k_events iterative argmin pops
    #    (two-level: min deadline, then min seq among ties) are ~17x
    #    cheaper than a full lexicographic sort of the lanes, and due
    #    clusters are small in practice (the drain path covers the rest).
    row = jnp.arange(W)
    ev_slot, ev_seq, ev_valid = [], [], []
    for _ in range(k_events):
        live = lane_dl[:, :cap]
        m = live.min(axis=1)
        is_due = m <= new_clock
        cand = jnp.where(live == m[:, None], lane_seq[:, :cap],
                         jnp.int64(INF_NS))
        j = jnp.argmin(cand, axis=1)
        ev_slot.append(j.astype(jnp.int32))
        ev_seq.append(lane_seq[row, j])
        ev_valid.append(is_due)
        lane_dl = lane_dl.at[row, jnp.where(is_due, j, cap)].set(
            jnp.int64(INF_NS))
    event_slot = jnp.stack(ev_slot, axis=1)
    event_seq = jnp.stack(ev_seq, axis=1)
    event_valid = jnp.stack(ev_valid, axis=1)
    more_due = lane_dl[:, :cap].min(axis=1) <= new_clock

    new_state = BridgeState(clock=new_clock, lane_dl=lane_dl,
                            lane_seq=lane_seq)
    if metrics:
        # Observability accumulation (BridgeMetrics): sums of masks the
        # step already computed — write-only, so the metrics-on step's
        # StepOut is bit-identical to metrics-off.
        i64 = jnp.int64
        mb = BridgeMetrics(
            timers_set=mb.timers_set + t_mask.sum(axis=1, dtype=i64),
            cancels=mb.cancels + c_mask.sum(axis=1, dtype=i64),
            msgs_sent=mb.msgs_sent + s_mask.sum(axis=1, dtype=i64),
            msgs_lost=mb.msgs_lost + (s_mask & lost).sum(axis=1, dtype=i64),
            events_fired=mb.events_fired
            + event_valid.sum(axis=1, dtype=i64),
            vtime_ns=mb.vtime_ns + (new_clock - state.clock),
        )
    return new_state, mb, StepOut(clock=new_clock, deadlock=deadlock,
                                  send_ok=ok, event_slot=event_slot,
                                  event_seq=event_seq,
                                  event_valid=event_valid,
                                  more_due=more_due)


class DrainOut(NamedTuple):
    """Outputs of a pop-only drain round, as DEVICE arrays (lazy): the
    driver materializes them with ``np.asarray`` at use, after the next
    drain is already in the queue."""

    event_seq: object    # i64[W, K] — popped seqs (host dispatch key)
    event_valid: object  # bool[W, K]
    more_due: object     # bool[W] — still >K events due


def _drain_step(state: BridgeState, mb, *, cap: int, k_events: int,
                metrics: bool = False):
    """Pop-only kernel for drain rounds: no cancels, no timers, no sends,
    no clock advance — exactly what a zero-width ``advance=False``
    :func:`_step` round did, minus the dead scatter machinery.

    Every input is device-resident (the kernel state), which is what lets
    the sweep driver dispatch drain round r+1 BEFORE round r's popped
    events are unpacked and fired on the host (dispatch-ahead): a drain
    dispatched when nothing is due pops nothing and leaves the lanes
    semantically untouched, so the one speculative round at the end of a
    drain chain is a no-op by construction.
    """
    import jax.numpy as jnp

    W = state.clock.shape[0]
    lane_dl, lane_seq = state.lane_dl, state.lane_seq
    clock = state.clock
    row = jnp.arange(W)
    ev_seq, ev_valid = [], []
    for _ in range(k_events):
        live = lane_dl[:, :cap]
        m = live.min(axis=1)
        is_due = m <= clock
        cand = jnp.where(live == m[:, None], lane_seq[:, :cap],
                         jnp.int64(INF_NS))
        j = jnp.argmin(cand, axis=1)
        ev_seq.append(lane_seq[row, j])
        ev_valid.append(is_due)
        lane_dl = lane_dl.at[row, jnp.where(is_due, j, cap)].set(
            jnp.int64(INF_NS))
    event_seq = jnp.stack(ev_seq, axis=1)
    event_valid = jnp.stack(ev_valid, axis=1)
    more_due = lane_dl[:, :cap].min(axis=1) <= clock
    new_state = BridgeState(clock=clock, lane_dl=lane_dl, lane_seq=lane_seq)
    if metrics:
        mb = mb._replace(events_fired=mb.events_fired
                         + event_valid.sum(axis=1, dtype=jnp.int64))
    return new_state, mb, DrainOut(event_seq=event_seq,
                                   event_valid=event_valid,
                                   more_due=more_due)


# One jitted step per (cap, k_events), shared by every kernel instance:
# a fresh jax.jit object per sweep would re-trace and re-compile (~0.8 s
# on CPU XLA for this unrolled kernel) on every sweep() call in a process.
# The step is pure (all state is passed in), so sharing is sound. The
# BridgeState argument is DONATED: XLA updates the W×(CAP+1) timer lanes
# in place instead of double-buffering them per step — sound because
# ``BridgeKernel.step`` immediately rebinds ``self.state`` to the output
# and nothing else holds the previous state (``reset_slot`` only ever
# touches the current one).
_STEP_CACHE: dict = {}
_DRAIN_CACHE: dict = {}


class BridgeKernel:
    """Device-side half of the bridge: owns the batched decision state.

    The host driver calls :meth:`step` once per lockstep round with padded
    numpy batches; pad widths are bucketed (powers of two) so XLA's
    per-shape retraces stay bounded.
    """

    def __init__(self, seeds, *, cap: int = 128, k_events: int = 4,
                 device: str = None, metrics: bool = False):
        import os

        import jax
        import jax.numpy as jnp

        from ..core.rng import STREAM_NET
        from ..ops.threefry import derive_stream_np

        self._jax = jax
        # jax.enable_x64 moved to the top level after 0.4.x; reach the
        # experimental home on older installs so the bridge runs on both.
        self._enable_x64 = getattr(jax, "enable_x64", None)
        if self._enable_x64 is None:
            from jax.experimental import enable_x64 as _x64

            self._enable_x64 = _x64
        self.W = len(seeds)
        self.cap = cap
        self.k_events = k_events
        self.metrics_enabled = bool(metrics)
        # The lockstep protocol is dispatch-latency bound (one step per
        # event cluster), so the kernel defaults to the LOCAL XLA backend:
        # a co-located accelerator amortizes at large W, but a tunneled
        # remote TPU (hundreds of ms per dispatch) never can. Override
        # with device= or MADSIM_BRIDGE_DEVICE to place the kernel on an
        # accelerator whose dispatch latency you have measured.
        name = device or os.environ.get("MADSIM_BRIDGE_DEVICE", "cpu")
        self.device = jax.local_devices(backend=name)[0]
        seeds = np.asarray(seeds, dtype=np.uint64)
        k0 = (seeds & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        k1 = (seeds >> np.uint64(32)).astype(np.uint32)
        nk0, nk1 = derive_stream_np(k0, k1, STREAM_NET)
        with jax.default_device(self.device), self._enable_x64():
            self._net_k0 = jnp.asarray(np.atleast_1d(nk0))
            self._net_k1 = jnp.asarray(np.atleast_1d(nk1))
            self.state = BridgeState(
                clock=jnp.zeros((self.W,), jnp.int64),
                lane_dl=jnp.full((self.W, cap + 1), INF_NS, jnp.int64),
                lane_seq=jnp.zeros((self.W, cap + 1), jnp.int64),
            )
            # The per-slot observability block (device-resident; donated
            # through the step alongside the lane state).
            self._mb = (BridgeMetrics(*[jnp.zeros((self.W,), jnp.int64)
                                        for _ in BridgeMetrics._fields])
                        if self.metrics_enabled else None)
            # One jitted step; XLA re-traces per padded batch shape.
            # Process-cached so repeated sweeps reuse the compilation.
            # Metrics-on compiles its own entry (the block is an extra
            # donated argument); metrics-off is the unchanged program.
            donate = (0, 1) if self.metrics_enabled else (0,)
            key = (cap, k_events, self.metrics_enabled)
            self._fn = _STEP_CACHE.get(key)
            if self._fn is None:
                self._fn = jax.jit(
                    functools.partial(_step, cap=cap, k_events=k_events,
                                      metrics=self.metrics_enabled),
                    donate_argnums=donate)
                _STEP_CACHE[key] = self._fn
            self._drain_fn = _DRAIN_CACHE.get(key)
            if self._drain_fn is None:
                self._drain_fn = jax.jit(
                    functools.partial(_drain_step, cap=cap,
                                      k_events=k_events,
                                      metrics=self.metrics_enabled),
                    donate_argnums=donate)
                _DRAIN_CACHE[key] = self._drain_fn

    def reset_slot(self, slot: int, seed: int) -> None:
        """Recycle one world slot for a fresh seed: re-derive its NET
        stream key and clear its device rows (clock zero, all timer lanes
        empty). After the reset the slot is indistinguishable from row
        ``slot`` of a freshly built kernel keyed on ``seed``, so a world
        spawned into it keeps the bit-identical per-seed contract — this
        is what lets bounded-width sweeps (``sweep(batch=...)``) stream
        seeds through a fixed batch instead of sizing W to the seed list.
        """
        from ..core.rng import STREAM_NET
        from ..ops.threefry import derive_stream_np, seed_to_key

        import jax.numpy as jnp

        nk0, nk1 = derive_stream_np(*seed_to_key(int(seed)), STREAM_NET)
        with self._jax.default_device(self.device), self._enable_x64():
            self._net_k0 = self._net_k0.at[slot].set(jnp.uint32(nk0))
            self._net_k1 = self._net_k1.at[slot].set(jnp.uint32(nk1))
            st = self.state
            self.state = BridgeState(
                clock=st.clock.at[slot].set(0),
                lane_dl=st.lane_dl.at[slot].set(jnp.int64(INF_NS)),
                lane_seq=st.lane_seq.at[slot].set(0),
            )

    def reset_slots(self, pairs) -> None:
        """Batched :meth:`reset_slot`: re-key ALL of a round's recycled
        slots in one device write per lane array instead of one dispatch
        chain per slot — the pool parent's refill path
        (`bridge/pool.py`), where a wide recycled sweep can retire many
        slots per round. Bit-identical to sequential ``reset_slot``
        calls: the slots are distinct, and each row gets exactly the
        values a fresh kernel keyed on its seed would hold."""
        if not pairs:
            return
        if len(pairs) == 1:
            self.reset_slot(*pairs[0])
            return
        from ..core.rng import STREAM_NET
        from ..ops.threefry import derive_stream_np

        import jax.numpy as jnp

        slots = np.asarray([int(s) for s, _ in pairs], np.int32)
        seeds = np.asarray([int(x) for _, x in pairs], np.uint64)
        k0 = (seeds & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        k1 = (seeds >> np.uint64(32)).astype(np.uint32)
        nk0, nk1 = derive_stream_np(k0, k1, STREAM_NET)
        with self._jax.default_device(self.device), self._enable_x64():
            self._net_k0 = self._net_k0.at[slots].set(jnp.asarray(nk0))
            self._net_k1 = self._net_k1.at[slots].set(jnp.asarray(nk1))
            st = self.state
            self.state = BridgeState(
                clock=st.clock.at[slots].set(0),
                lane_dl=st.lane_dl.at[slots].set(jnp.int64(INF_NS)),
                lane_seq=st.lane_seq.at[slots].set(0),
            )

    def drain(self) -> DrainOut:
        """Dispatch one pop-only drain round and return LAZY device
        outputs (materialize with ``np.asarray`` at use). The round's
        only input is the device-resident kernel state, so the driver can
        enqueue drain r+1 before unpacking round r's events — and a
        speculatively dispatched round that finds nothing due is a
        semantic no-op on the lanes."""
        with self._jax.default_device(self.device), self._enable_x64():
            state, mb, out = self._drain_fn(self.state, self._mb)
            self.state = state
            self._mb = mb
            return out

    def step(self, batch: HostBatch, out: Optional[StepOut] = None
             ) -> StepOut:
        """One lockstep round. ``batch`` arrays may be backed by ANY
        buffer — the pool parent hands shared-memory views straight in
        (the H2D copy reads them in place). ``out``, when given, is a
        StepOut of caller-owned destination arrays (``None`` fields
        skipped): the results are scattered into them after
        materialization — the shared-memory egress seam of
        `bridge/pool.py`, whose workers read their slice rows without
        any per-world parent work."""
        import jax.numpy as jnp

        with self._jax.default_device(self.device), self._enable_x64():
            state, mb, res = self._fn(
                self.state, self._mb, self._net_k0, self._net_k1,
                jnp.asarray(batch.t_slot), jnp.asarray(batch.t_dl),
                jnp.asarray(batch.t_seq), jnp.asarray(batch.t_mask),
                jnp.asarray(batch.c_slot), jnp.asarray(batch.c_mask),
                jnp.asarray(batch.s_ctr), jnp.asarray(batch.s_base),
                jnp.asarray(batch.s_slot), jnp.asarray(batch.s_seq),
                jnp.asarray(batch.s_thr), jnp.asarray(batch.s_lossall),
                jnp.asarray(batch.s_lat_lo), jnp.asarray(batch.s_lat_w),
                jnp.asarray(batch.s_mask), jnp.asarray(batch.s_live),
                jnp.asarray(batch.clock), jnp.asarray(batch.advance))
            self.state = state
            self._mb = mb
            res = StepOut(*[np.asarray(x) for x in res])
            if out is not None:
                for dst, src in zip(out, res):
                    if dst is not None:
                        np.copyto(dst, src)
            return res

    def metrics(self):
        """Host copy of the per-slot :class:`BridgeMetrics` block (dict of
        i64[W] numpy arrays), or ``None`` when metrics are off. One
        explicit pull — call at sweep end, not per round."""
        if self._mb is None:
            return None
        vals = self._jax.device_get(self._mb)
        return {k: np.asarray(v) for k, v in vals._asdict().items()}


def bucket(n: int, minimum: int = 4) -> int:
    """Round a per-step count up to a power of two so jit shapes repeat."""
    b = minimum
    while b < n:
        b <<= 1
    return b
