"""Backend bit-exactness check: the same seeds must trace identically on
every XLA backend.

This is the device-engine analog of the host determinism checker
(`madsim/src/sim/rand.rs:84-107` / `runtime/mod.rs:164-189`): the engine
contract (engine/core.py docstring) says (seed, config) ⇒ bit-exact
trajectories, *re-runnable anywhere*. Everything in the step function is
integer or exactly-representable f32 arithmetic, so TPU and CPU must agree
to the last bit — any divergence is an engine bug (e.g. a reduction order
leak or a fast-math rewrite), not noise. bench.py runs this in --smoke mode
every round on the real accelerator.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np

from .core import DeviceEngine


def run_on(eng: DeviceEngine, device, seeds, faults=None, max_steps: int = 100_000):
    """init + run a seed batch with all arrays placed on ``device``."""
    with jax.default_device(device):
        state = eng.init(np.asarray(seeds), faults=faults)
        state = eng.run(state, max_steps=max_steps)
        jax.block_until_ready(state)
    return jax.tree.map(np.asarray, state)


def crosscheck_backends(eng: DeviceEngine, seeds, faults=None,
                        max_steps: int = 100_000,
                        device_a=None, device_b=None) -> Dict[str, int]:
    """Run the same batch on two backends and assert leafwise bit-equality.

    Defaults: device_a = the default backend (TPU when present),
    device_b = host CPU. Returns a small summary dict; raises AssertionError
    with the first differing leaf on any mismatch.
    """
    device_a = device_a if device_a is not None else jax.devices()[0]
    device_b = device_b if device_b is not None else jax.devices("cpu")[0]

    state_a = run_on(eng, device_a, seeds, faults, max_steps)
    state_b = run_on(eng, device_b, seeds, faults, max_steps)

    leaves_a, treedef_a = jax.tree.flatten(state_a)
    leaves_b, treedef_b = jax.tree.flatten(state_b)
    assert treedef_a == treedef_b
    mismatched = []
    paths = [jax.tree_util.keystr(p)
             for p, _ in jax.tree_util.tree_flatten_with_path(state_a)[0]]
    for path, a, b in zip(paths, leaves_a, leaves_b):
        if a.dtype != b.dtype or a.shape != b.shape or not np.array_equal(a, b):
            diffs = int(np.sum(a != b)) if a.shape == b.shape else -1
            mismatched.append(f"{path}: {diffs} differing elements "
                              f"({a.dtype}{list(a.shape)})")
    assert not mismatched, (
        f"{device_a.platform} vs {device_b.platform} trajectories diverged "
        f"on {len(mismatched)} leaves:\n  " + "\n  ".join(mismatched[:10]))

    obs_a = {k: np.asarray(v) for k, v in eng.observe(state_a).items()}
    obs_b = {k: np.asarray(v) for k, v in eng.observe(state_b).items()}
    for k in obs_a:
        assert np.array_equal(obs_a[k], obs_b[k]), f"observe[{k}] diverged"

    return {
        "n_worlds": int(np.asarray(seeds).shape[0]),
        "n_leaves": len(leaves_a),
        "platform_a": device_a.platform,
        "platform_b": device_b.platform,
        "bitwise_equal": 1,
    }
