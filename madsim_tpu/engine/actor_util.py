"""Shared actor-side helpers: payload padding and the outbox layout.

EVERY actor family assembles the same (N peer messages + 1 timer)
Outbox shape through :func:`make_outbox` — the hand-written craft
reference (raft_actor) calls it directly, and the actor compiler
(madsim_tpu/actorc/compile.py) emits exactly one call per compiled
step for the spec-defined families (tpc, pb, paxos). Keeping the
layout in one place means a change to it cannot silently diverge the
actors — and the compiled/host-twin crosscheck (actorc/conformance.py)
now pins the layout bitwise per event on top.
"""
from __future__ import annotations

import jax.numpy as jnp

from .core import EngineConfig, Outbox


def pad_payload(cfg: EngineConfig, words) -> jnp.ndarray:
    """(P,) payload row: the given words, zero-padded."""
    vals = [jnp.asarray(w, jnp.int32) for w in words]
    vals += [jnp.int32(0)] * (cfg.payload_words - len(vals))
    return jnp.stack(vals)


def bcast_payload(cfg: EngineConfig, n: int, words) -> jnp.ndarray:
    """(N, P) payload with the same words in every row."""
    return jnp.broadcast_to(pad_payload(cfg, words), (n, cfg.payload_words))


def make_outbox(cfg: EngineConfig, n: int, msg_valid, msg_kind, msg_payload,
                timer_valid, timer_kind, timer_dst, timer_delay,
                timer_payload) -> Outbox:
    """Assemble the (N peers + 1 timer) outbox layout."""
    app = lambda xs, x: jnp.concatenate(  # noqa: E731
        [jnp.asarray(xs), jnp.asarray(x)[None]], axis=0)
    return Outbox(
        valid=app(msg_valid, timer_valid),
        is_timer=app(jnp.zeros((n,), bool), jnp.asarray(True)),
        kind=app(msg_kind, timer_kind),
        dst=app(jnp.arange(n, dtype=jnp.int32),
                jnp.asarray(timer_dst, jnp.int32)),
        delay_us=app(jnp.zeros((n,), jnp.int32),
                     jnp.asarray(timer_delay, jnp.int32)),
        payload=jnp.concatenate([msg_payload, timer_payload[None]], axis=0),
    )
