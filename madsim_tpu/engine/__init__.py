"""Batched device engine: the simulation decision kernel on TPU.

This is the TPU-native answer to the reference's one-seed-per-thread sweep
(`madsim/src/sim/runtime/builder.rs:118-136`, env ``MADSIM_TEST_JOBS``): the
discrete-event core — next-event selection, virtual-clock advance, RNG draws,
network latency/loss/partition sampling, fault schedules
(`madsim/src/sim/time/mod.rs`, `net/network.rs:249-257`, `rand.rs:63-108`) —
is lifted into a pure JAX step function over arrays with a leading *world*
(seed) axis, ``vmap``'d over thousands of seeds, and sharded across a TPU mesh
via :mod:`madsim_tpu.parallel`.

Workloads for this engine are *actors*: node logic written as pure JAX
functions over fixed-size state (see :class:`madsim_tpu.engine.raft_actor.RaftActor`),
in contrast to the host engine which runs arbitrary Python coroutines one
seed at a time. Both engines draw from the same counter-based Threefry
streams (:mod:`madsim_tpu.ops.threefry`).
"""
from .core import (
    DeviceEngine,
    EngineConfig,
    Event,
    Outbox,
    WorldState,
    tree_select_worlds,
    FAULT_KILL,
    FAULT_RESTART,
    FAULT_CLOG_NODE,
    FAULT_UNCLOG_NODE,
    FAULT_CLOG_LINK,
    FAULT_UNCLOG_LINK,
    FAULT_SET_LATENCY,
    FAULT_SET_LOSS,
    FAULT_PAUSE,
    FAULT_RESUME,
    INF_TIME,
)
from .conformance import ConformanceError, check_actor
from .lanes import PACKED, WIDE, Lanes
from .checkpoint import CheckpointError
from .checkpoint import load as load_checkpoint
from .checkpoint import save as save_checkpoint
from .raft_actor import RaftActor, RaftDeviceConfig

# The compiled families (tpc, pb) resolve lazily: their modules import
# the actor compiler (madsim_tpu.actorc), which itself builds on the
# engine submodules — eager imports here would close an import cycle
# whenever actorc is imported first. PEP 562 keeps
# ``from madsim_tpu.engine import TPCActor`` working unchanged.
_LAZY = {"TPCActor": ".tpc_actor", "TPCDeviceConfig": ".tpc_actor",
         "PBActor": ".pb_actor", "PBDeviceConfig": ".pb_actor"}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        return getattr(importlib.import_module(_LAZY[name], __name__),
                       name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "DeviceEngine", "EngineConfig", "Event", "Outbox", "WorldState",
    "tree_select_worlds",
    "RaftActor", "RaftDeviceConfig", "PBActor", "PBDeviceConfig",
    "TPCActor", "TPCDeviceConfig",
    "check_actor", "ConformanceError",
    "Lanes", "PACKED", "WIDE",
    "save_checkpoint", "load_checkpoint", "CheckpointError",
    "FAULT_KILL", "FAULT_RESTART", "FAULT_CLOG_NODE", "FAULT_UNCLOG_NODE",
    "FAULT_CLOG_LINK", "FAULT_UNCLOG_LINK", "FAULT_SET_LATENCY",
    "FAULT_SET_LOSS", "FAULT_PAUSE", "FAULT_RESUME", "INF_TIME",
]
