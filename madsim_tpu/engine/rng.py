"""Per-world counter-based RNG for the device engine.

Functional cursor over the Threefry stream in :mod:`madsim_tpu.ops.threefry`
— the device-side sibling of the host engine's
:class:`madsim_tpu.core.rng.GlobalRng`. Every draw is a pure function of
``(seed, stream, counter)``; the cursor is carried through the step function
as part of the world state, so batched runs are bit-reproducible from the
seed vector alone (the property the reference gets from its global seeded
SmallRng, `madsim/src/sim/rand.rs:50-108`).
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp

from ..ops.threefry import threefry2x32_jax


class DevRng(NamedTuple):
    """A named Threefry stream plus a draw counter (all uint32 scalars)."""

    k0: jnp.ndarray
    k1: jnp.ndarray
    counter: jnp.ndarray


def make_rng(seed_lo, seed_hi, stream: int) -> DevRng:
    """Derive the per-(seed, stream) key; mirrors ``derive_stream_np``."""
    k0, k1 = threefry2x32_jax(seed_lo, seed_hi,
                              jnp.uint32(stream & 0xFFFFFFFF),
                              jnp.uint32((stream >> 32) & 0xFFFFFFFF))
    return DevRng(k0=k0, k1=k1, counter=jnp.uint32(0))


def next_u32(rng: DevRng) -> Tuple[jnp.ndarray, DevRng]:
    """One uint32 draw; advances the counter."""
    x0, _ = threefry2x32_jax(rng.k0, rng.k1, rng.counter, jnp.uint32(0))
    return x0, rng._replace(counter=rng.counter + jnp.uint32(1))


def next_u32_vec(rng: DevRng, k: int) -> Tuple[jnp.ndarray, DevRng]:
    """``k`` draws in one Threefry evaluation, at counters
    ``counter + 0 .. counter + k-1`` — bit-identical to ``k`` sequential
    :func:`next_u32` calls, but one vectorized block instead of ``k``
    scalar ones (the engine's per-step draws all batch through this)."""
    counters = rng.counter + jnp.arange(k, dtype=jnp.uint32)
    xs, _ = threefry2x32_jax(rng.k0, rng.k1, counters, jnp.zeros((k,), jnp.uint32))
    return xs, rng._replace(counter=rng.counter + jnp.uint32(k))


def _u32_to_range(x, low, high) -> jnp.ndarray:
    """Map uint32 draw(s) to [low, high) int32 — the ONE copy of the modulo
    method (host GlobalRng.gen_range parity); scalar and vector draws must
    share it or bit-identical replay breaks."""
    width = jnp.uint32(jnp.asarray(high, jnp.int32) - jnp.asarray(low, jnp.int32))
    return jnp.asarray(low, jnp.int32) + (x % width).astype(jnp.int32)


def _u32_to_unit_f32(x) -> jnp.ndarray:
    """Map uint32 draw(s) to [0, 1) float32 from the top 24 bits."""
    return (x >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(2.0 ** -24)


def uniform_u32(rng: DevRng, low, high) -> Tuple[jnp.ndarray, DevRng]:
    """Uniform integer in [low, high) as int32 (modulo method, like the host
    GlobalRng.gen_range). ``high`` must be > ``low``."""
    x, rng = next_u32(rng)
    return _u32_to_range(x, low, high), rng


def uniform_f32(rng: DevRng) -> Tuple[jnp.ndarray, DevRng]:
    """Uniform float32 in [0, 1) from the top 24 bits of one draw."""
    x, rng = next_u32(rng)
    return _u32_to_unit_f32(x), rng


def bernoulli(rng: DevRng, p) -> Tuple[jnp.ndarray, DevRng]:
    """Bernoulli(p) draw. Always consumes exactly one counter tick so control
    flow never changes the stream (matches GlobalRng.gen_bool)."""
    u, rng = uniform_f32(rng)
    return u < jnp.asarray(p, jnp.float32), rng
