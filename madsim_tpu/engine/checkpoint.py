"""Device-engine checkpoint/resume: WorldState ↔ npz on disk.

Absent from the reference (SURVEY §5: recovery there is always "restart the
node from its init closure") but cheap in this architecture — the entire
batched simulation state is one fixed-shape array pytree, so a checkpoint
is a flatten + savez and resume is bit-exact: a sweep split across a
save/load boundary produces the same trajectories as an unbroken run
(asserted in tests/test_checkpoint.py). This is what lets 100k-world
sweeps survive TPU preemption.

Format: ``leaf_00000..leaf_NNNNN`` arrays in flatten order plus a
``meta`` JSON header (leaf count, engine-config fingerprint, world count).
The pytree *structure* is supplied by the engine at load time (structure
is config-determined, never data-dependent), so nothing opaque is pickled.
"""
from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Dict, Optional, Union

import jax
import numpy as np

FORMAT_VERSION = 1

# npz entry prefix for auxiliary arrays (sweep-level state riding beside
# the WorldState leaves: slot->seed index, refill cursor, retired
# observations, coverage ledger — see parallel/sweep.py recycled
# checkpointing). Aux entries are opt-in per save and invisible to loads
# that do not ask for them, so pre-aux checkpoints stay readable.
_AUX_PREFIX = "aux_"


class CheckpointError(RuntimeError):
    pass


def _config_fingerprint(engine) -> str:
    """Engine identity a checkpoint must match to resume: actor class AND
    its configuration (vars covers e.g. RaftActor.rcfg — two actors with
    different timings must not swap checkpoints) plus the EngineConfig."""
    return (f"{type(engine.actor).__name__}/{vars(engine.actor)!r}"
            f"/{engine.cfg!r}")


def save(engine, state, path: Union[str, Path],
         extra_meta: Optional[Dict[str, str]] = None,
         extra_arrays: Optional[Dict[str, np.ndarray]] = None) -> None:
    """Write a WorldState (any world count) to ``path`` (npz), atomically:
    a preemption mid-write must never destroy the previous checkpoint, so
    the bytes land in a temp file that is fsync'd and then os.replace()d
    onto ``path`` — without the fsync, a crash between write and rename
    can publish a name pointing at unflushed (torn) bytes.

    ``extra_arrays``: named host arrays saved beside the state leaves
    (``aux_<name>`` entries) — sweep-level bookkeeping such as the
    slot→seed index and refill cursor of a recycled sweep. Read back via
    ``load(..., with_aux=True)``.

    Scope: single-process (all shards addressable from this host) — any
    mesh within one process, including the virtual multihost one. Real
    multi-process checkpointing needs per-host shard files (an orbax-style
    layout); rather than crash mid-save inside np.savez, that case is
    rejected up front."""
    leaves = jax.tree.leaves(state)
    for leaf in leaves:
        if hasattr(leaf, "is_fully_addressable") and \
                not leaf.is_fully_addressable:
            raise CheckpointError(
                "state is sharded across processes: single-file "
                "checkpointing needs all shards addressable from this "
                "host (gather first, or checkpoint per-process)")
    # jax.device_get, not np.asarray: the __array__ protocol path copies
    # at single-digit MB/s on jax CPU arrays (measured 46 s for a 205 MB
    # leaf), while device_get takes the zero-copy/bulk-transfer path.
    host_leaves, now = jax.device_get((leaves, state.now))
    arrays = {f"leaf_{i:05d}": np.asarray(leaf)
              for i, leaf in enumerate(host_leaves)}
    aux = {f"{_AUX_PREFIX}{k}": np.asarray(v)
           for k, v in (extra_arrays or {}).items()}
    meta = {
        "version": FORMAT_VERSION,
        "n_leaves": len(leaves),
        "n_worlds": int(now.shape[0]) if now.ndim else 0,
        "config": _config_fingerprint(engine),
        "extra": dict(extra_meta or {}),
        "aux": sorted(extra_arrays or {}),
    }
    path = Path(path)
    tmp = path.with_name(path.name + ".tmp")
    with open(tmp, "wb") as f:
        # Uncompressed: zlib over a few hundred MB of state costs ~15 s per
        # snapshot (measured — it made per-chunk checkpointing 15x slower
        # than the sweep itself), while the raw write is disk-speed and
        # overlaps the next chunk under the async writer. np.load reads
        # both formats, so old compressed checkpoints keep resuming.
        np.savez(f, meta=np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8), **arrays, **aux)
        # Durability before visibility: os.replace only swaps the NAME.
        # If the data blocks are still in the page cache when the rename
        # lands and the host dies, the published path holds a torn npz —
        # exactly the crash window the atomic-rename dance exists to
        # close. flush+fsync first, so the rename never points at
        # unflushed bytes.
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _corrupt(path, exc: BaseException) -> CheckpointError:
    """Wrap a low-level decode failure in an actionable CheckpointError.

    Raw ``zipfile.BadZipFile`` / numpy internals say nothing about WHICH
    file broke or what to do about it; a resuming sweep must report both
    (the fleet's crash-recovery path hits this whenever a host died
    mid-write on a pre-fsync kernel or the disk itself tore the file).
    """
    return CheckpointError(
        f"corrupt or truncated checkpoint {os.fspath(path)!r}: "
        f"{type(exc).__name__}: {exc}\n"
        "recovery options: delete the file (or run with resume=False) to "
        "restart this range from its seeds — re-execution is "
        "deterministic, so nothing but time is lost — or point at an "
        "older intact checkpoint")


def load(engine, path: Union[str, Path],
         expect_extra: Optional[Dict[str, str]] = None,
         with_aux: bool = False):
    """Read a WorldState saved by :func:`save` back onto the device.

    The pytree structure comes from the engine (one-world init template —
    structure depends only on (actor, config), not on data), so a
    checkpoint from any process resumes in any other, bit-exactly.
    ``expect_extra``: key/value pairs that must match the checkpoint's
    extra metadata (e.g. a seed-vector hash, so results can never be
    attributed to the wrong seeds).

    ``with_aux=True`` returns ``(state, aux)`` where ``aux`` maps the
    names passed to ``save(extra_arrays=...)`` to host arrays (``{}`` for
    checkpoints written without aux).

    Truncated or corrupt files (crash mid-write, torn disk) raise
    :class:`CheckpointError` naming the path and the recovery options —
    never a bare ``zipfile``/numpy internal error.
    """
    try:
        with np.load(Path(path)) as z:
            try:
                meta = json.loads(bytes(z["meta"]).decode())
            except Exception as exc:
                raise _corrupt(path, exc) from exc
            if meta.get("version") != FORMAT_VERSION:
                raise CheckpointError(
                    f"unsupported checkpoint version {meta.get('version')}")
            fp = _config_fingerprint(engine)
            if meta["config"] != fp:
                raise CheckpointError(
                    "checkpoint was written by a different engine config:\n"
                    f"  checkpoint: {meta['config']}\n  this engine: {fp}")
            stored_extra = meta.get("extra", {})
            for key, value in (expect_extra or {}).items():
                if stored_extra.get(key) != value:
                    raise CheckpointError(
                        f"checkpoint metadata mismatch for {key!r}: "
                        f"checkpoint has {stored_extra.get(key)!r}, "
                        f"caller expects {value!r}")
            leaves = [z[f"leaf_{i:05d}"] for i in range(meta["n_leaves"])]
            aux = {name: z[f"{_AUX_PREFIX}{name}"]
                   for name in meta.get("aux", [])}
    except CheckpointError:
        raise
    except Exception as exc:
        # np.load raises zipfile.BadZipFile on garbage, OSError/EOFError
        # on truncation, KeyError/ValueError on missing or half-written
        # members — all the same operational fact: this file cannot be
        # resumed from.
        raise _corrupt(path, exc) from exc
    treedef = jax.tree.structure(engine.init(np.zeros(1, np.uint64)))
    if treedef.num_leaves != len(leaves):
        raise CheckpointError(
            f"checkpoint has {len(leaves)} leaves, engine state has "
            f"{treedef.num_leaves} — incompatible engine version")
    state = jax.tree.unflatten(treedef,
                               [jax.numpy.asarray(a) for a in leaves])
    return (state, aux) if with_aux else state


def read_meta(path: Union[str, Path]) -> Dict[str, object]:
    """The checkpoint's meta header alone (no state decode) — cheap
    inspection for coordinators deciding whether a released lease
    checkpoint is worth handing to the next worker."""
    try:
        with np.load(Path(path)) as z:
            return json.loads(bytes(z["meta"]).decode())
    except Exception as exc:
        raise _corrupt(path, exc) from exc
