"""The batched device engine core: world state + step function.

Design (SURVEY §7 stage 4): one *world* = one seeded simulation, all of whose
engine-level state — virtual clock, pending-event queue, RNG cursor, node
liveness/generation, link partition matrices, counters — is fixed-shape
arrays. The per-world ``step`` is a pure function (pop earliest event →
apply fault / dispatch to the actor via its handler → sample network
latency/loss for the outbox → push), ``vmap``'d over the world axis so
thousands of seeds advance per XLA dispatch. Worlds that finish (empty queue,
time limit, or bug with ``stop_on_bug``) are frozen by a select — the
step-synchronous masking that replaces the reference's one-OS-thread-per-seed
sweep (`madsim/src/sim/runtime/builder.rs:118-136`).

Semantics carried over from the reference host engine:
- message sends sample clog/loss/latency at *send* time
  (`madsim/src/sim/net/network.rs:249-257`);
- node kill bumps a generation counter so pending timers die with the node
  (the lazy-drop of queued runnables, `task.rs:211-226`), while in-flight
  messages are delivered iff the destination is alive at delivery time;
- restart re-runs the actor's init hook (`task.rs:229-240`);
- every random decision draws from the per-world counter-based Threefry
  stream, so (seed, config) ⇒ bit-exact trajectories, re-runnable anywhere.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.blackbox import (
    BB_DROP_DEAD,
    BB_DROP_STALE,
    BB_FAULT,
    BB_MARKER,
    BB_RAISE,
    BB_TIMER,
    FAULT_NAMES,
    BlackboxRing,
)
from ..obs.metrics import NUM_FAULT_KINDS, MetricsBlock
from .lanes import (
    PACKED,
    WIDE,
    Lanes,
    join_wide,
    narrow,
    narrow_wrap,
    onehot,
    split_wide,
    take_small,
    upd,
    upd2,
    widen,
)
from .queue import (
    Event,
    EventQueue,
    FLAG_FAULT,
    FLAG_TIMER,
    GEN_MASK,
    INF_TIME,
    depth as queue_depth,
    eligible_mask,
    empty_queue,
    insert_metrics,
    next_deadline,
    pop,
    pop_indexed,
    push,
    push_many,
)
from .rng import (
    DevRng,
    _u32_to_range,
    _u32_to_unit_f32,
    make_rng,
    next_u32_vec,
    uniform_f32,
    uniform_u32,
)

# Device-engine RNG stream id (host streams occupy 0..3, see core/rng.py).
STREAM_DEVICE = 16

# Fault-injection ops (event kind when FLAG_FAULT is set). The analogs of
# Handle::kill/restart (`runtime/mod.rs:241-258`) and NetSim::clog_node /
# clog_link (`net/mod.rs:147-170`, `network.rs:159-190`).
FAULT_KILL = 0
FAULT_RESTART = 1
FAULT_CLOG_NODE = 2
FAULT_UNCLOG_NODE = 3
FAULT_CLOG_LINK = 4
FAULT_UNCLOG_LINK = 5
# Hot network-config updates (NetSim::update_config, `net/mod.rs:127-130`,
# `network.rs:74-94`): net parameters are runtime data in WorldState, so a
# schedule row can change them mid-run without recompiling.
# FAULT_SET_LATENCY: a = new min µs, b = new max µs.
# FAULT_SET_LOSS:    a = new loss rate in parts-per-million, b unused.
FAULT_SET_LATENCY = 6
FAULT_SET_LOSS = 7
# Pause/resume (Handle::pause/resume, `runtime/mod.rs:251-268`,
# `task.rs:243-261`): a paused node's deliveries and timers are BUFFERED
# (skipped by pop, untouched in the queue), then flush in (time, slot)
# order on resume. Kill/restart clear the pause, like the reference's
# fresh NodeInfo.
FAULT_PAUSE = 8
FAULT_RESUME = 9


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Static (compile-time) engine parameters. Hashable: part of jit keys."""

    n_nodes: int
    queue_cap: int = 128
    payload_words: int = 8
    outbox_cap: Optional[int] = None  # default n_nodes + 1
    # Network model DEFAULTS (reference defaults: 1-10 ms latency, 0 loss;
    # `net/network.rs:74-94`). Times are int32 microseconds. These seed
    # WorldState.{lat_min,lat_max,loss} — runtime data, per world — so one
    # compiled sweep can explore a (seeds × loss × latency) grid via
    # ``init(seeds, configs=...)`` and schedules can hot-update them
    # (FAULT_SET_LATENCY / FAULT_SET_LOSS), with zero recompiles.
    latency_min_us: int = 1_000
    latency_max_us: int = 10_000
    loss_rate: float = 0.0
    t_limit_us: int = 10_000_000
    stop_on_bug: bool = True
    # Equivalence-testing knob: keep the pre-round-7 statically unrolled
    # push chain instead of the fused queue.push_many pass. The two paths
    # are bitwise identical by contract (tests/test_queue_insert.py runs
    # whole trajectories both ways); sequential exists ONLY to pin that
    # contract — it pays ~M full-queue rewrites per step.
    sequential_insert: bool = False
    # Observability: carry a per-world MetricsBlock (obs/metrics.py) in
    # WorldState.metrics and update it every step. The block is a
    # separate pytree leaf the step WRITES but never reads for any
    # simulation decision, so metrics-on trajectories are bit-identical
    # to metrics-off (tier-1, tests/test_obs.py); with False (default)
    # the field is None and the compiled step is the exact pre-metrics
    # program — the op budget in tests/test_queue_insert.py is untouched.
    metrics: bool = False
    # Flight recorder (obs/blackbox.py): carry a per-world ring buffer
    # of the last K recorded step events in WorldState.blackbox and
    # write one packed record per processed step. Same contract as
    # ``metrics``: a separate write-only pytree leaf, so blackbox-on
    # trajectories are bit-identical to blackbox-off (tier-1,
    # tests/test_obs.py) and 0 (default) leaves the field None — the
    # compiled step is the exact pre-recorder program.
    blackbox: int = 0
    # Packed lane dtypes (engine/lanes.py Lanes registry, docs/perf.md
    # "Roofline round 2"): node ids, role/decision codes, queue slot
    # indices and payload words ride i8/i16 at rest instead of i32 —
    # ~0.6x the state bytes per world, which compounds directly with
    # buffer donation into worlds-per-chip. Virtual time, RNG cursors
    # and unbounded counters stay wide. False is the reference i32
    # path, kept alive for bitwise crosscheck (the sequential_insert
    # pattern); trajectories are bit-identical between the two profiles
    # as long as no narrow lane saturates (tier-1, tests/test_obs.py).
    packed: bool = True
    # Fused Pallas step kernel (engine/pallas_step.py): run the batched
    # pop -> eligible-mask -> dispatch -> push step as ONE
    # pl.pallas_call, so the queue scatter, mask and lane updates share
    # one VMEM residency on TPU instead of round-tripping HBM between
    # XLA fusions. Off by default: CPU tier-1 compiles the existing lax
    # programs unchanged. Bitwise identical to the lax step (the kernel
    # body IS the step function, gated in tests and `make smoke`).
    pallas: bool = False
    # World-axis block per Pallas grid step (None = whole batch in one
    # kernel invocation). Must divide the batch width when set;
    # otherwise the call falls back to the single-block form.
    pallas_block: Optional[int] = None
    # Force/disable interpreter-mode Pallas (None = auto: interpret
    # everywhere except on real TPU backends). Interpret mode keeps the
    # kernel runnable — and the bitwise-identity gate green — on CPU.
    pallas_interpret: Optional[bool] = None

    def __post_init__(self):
        if self.packed:
            if self.n_nodes > 127:
                raise ValueError(
                    f"EngineConfig(packed=True) stores node ids in int8: "
                    f"n_nodes={self.n_nodes} exceeds 127. Use "
                    f"packed=False (the int32 reference profile) for "
                    f"wider clusters.")
            if self.queue_cap > 32767:
                raise ValueError(
                    f"EngineConfig(packed=True) carries queue depths in "
                    f"int16: queue_cap={self.queue_cap} exceeds 32767. "
                    f"Use packed=False for deeper queues.")
        if self.pallas_block is not None and self.pallas_block <= 0:
            raise ValueError("pallas_block must be a positive world count")
        if self.blackbox < 0:
            raise ValueError("blackbox must be 0 (off) or a positive ring "
                             "depth K (events/world)")

    @property
    def lanes(self) -> Lanes:
        """The lane dtype registry this config compiles against."""
        return PACKED if self.packed else WIDE

    @property
    def m(self) -> int:
        return self.outbox_cap if self.outbox_cap is not None else self.n_nodes + 1


class Outbox(NamedTuple):
    """Fixed-capacity send buffer an actor returns from a handler.

    Slot fields are (M,) arrays ((M, P) for payload). Timers are delivered to
    ``dst`` after ``delay_us`` and are generation-checked; messages get
    engine-sampled latency/loss/partition treatment instead.
    """

    valid: jnp.ndarray     # (M,) bool
    is_timer: jnp.ndarray  # (M,) bool
    kind: jnp.ndarray      # (M,) int32
    dst: jnp.ndarray       # (M,) int32
    delay_us: jnp.ndarray  # (M,) int32 — timers only
    payload: jnp.ndarray   # (M, P) int32

    @staticmethod
    def empty(cfg: EngineConfig) -> "Outbox":
        m = cfg.m
        return Outbox(
            valid=jnp.zeros((m,), bool),
            is_timer=jnp.zeros((m,), bool),
            kind=jnp.zeros((m,), jnp.int32),
            dst=jnp.zeros((m,), jnp.int32),
            delay_us=jnp.zeros((m,), jnp.int32),
            payload=jnp.zeros((m, cfg.payload_words), jnp.int32),
        )


class WorldState(NamedTuple):
    """All state of one world (or, with a leading axis, of W worlds)."""

    now: jnp.ndarray          # int32 µs
    queue: EventQueue
    rng: DevRng
    alive: jnp.ndarray        # (N,) bool
    gen: jnp.ndarray          # (N,) code lane (i8 packed / i32 wide) —
                              # bumped on kill/restart, compared mod 256
    paused: jnp.ndarray       # (N,) bool — deliveries buffered while set
    clog_node: jnp.ndarray    # (N,) bool
    clog_link: jnp.ndarray    # (N, N) bool, [src, dst]
    astate: Any               # actor pytree
    active: jnp.ndarray       # bool — False ⇒ frozen
    steps: jnp.ndarray        # int32
    delivered: jnp.ndarray    # int32
    dropped: jnp.ndarray      # int32
    overflow: jnp.ndarray     # bool — event queue overflowed (diagnostic)
    qdepth: jnp.ndarray       # slot lane (i16 packed / i32 wide) — carried
                              # queue depth (== depth(queue); maintained by
                              # pop/push_many, so qmax needs no O(Q)
                              # reduction per step)
    qmax: jnp.ndarray         # slot lane — queue depth high-water mark
    bug: jnp.ndarray          # bool — invariant violation observed
    bug_time: jnp.ndarray     # int32 µs of first bug, INF_TIME if none
    # Per-world network model (runtime data — the batched sweep axis and
    # hot-update target the reference's global config cannot be,
    # `network.rs:74-94`).
    lat_min: jnp.ndarray      # int32 µs
    lat_max: jnp.ndarray      # int32 µs
    loss: jnp.ndarray         # float32 loss probability
    # Observability counters (obs/metrics.py MetricsBlock) when
    # EngineConfig.metrics, else None (an empty pytree subtree — the
    # leaf list, and therefore every compiled program and checkpoint
    # layout, is unchanged with metrics off). Write-only within the
    # step: nothing below ever reads it — the bitwise-invisibility
    # contract.
    metrics: Any = None
    # Flight-recorder ring (obs/blackbox.py BlackboxRing) when
    # EngineConfig.blackbox > 0, else None — the same empty-subtree
    # trick as ``metrics``, with the same write-only contract.
    blackbox: Any = None


def tree_select(pred, a, b):
    """Per-world select over two identical pytrees (pred is a scalar bool)."""
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def tree_select_worlds(mask, a, b):
    """Slot-wise select over two identically batched pytrees.

    ``mask`` is a (W,) bool vector over the leading world axis; it
    broadcasts over each leaf's trailing axes, so whole worlds are taken
    from ``a`` where True and from ``b`` where False. This is the
    device-side primitive behind world recycling: fresh worlds are
    selected into retired slots without the batch ever leaving the chip.
    """
    def pick(x, y):
        m = mask.reshape(mask.shape + (1,) * (x.ndim - 1))
        return jnp.where(m, x, y)

    return jax.tree.map(pick, a, b)


class DeviceEngine:
    """Compiles (actor, config) into jit-ready batched simulation functions.

    Usage::

        eng = DeviceEngine(RaftActor(rcfg), EngineConfig(n_nodes=3))
        state = eng.init(np.arange(10_000))          # one world per seed
        state = eng.run(state, max_steps=5_000)       # jitted while_loop
        out = eng.observe(state)                      # host-side dict
    """

    def __init__(self, actor, cfg: EngineConfig):
        # Packed-meta width limits (queue.pack_meta): 8-bit node ids,
        # 6-bit event kinds. num_kinds is required so the kind-width
        # guard actually covers every actor.
        if cfg.n_nodes > 256:
            raise ValueError("DeviceEngine supports at most 256 nodes/world")
        num_kinds = getattr(actor, "num_kinds", None)
        if num_kinds is None:
            raise ValueError("actor must declare num_kinds (its event-kind "
                             "count; packed event kinds are 6 bits)")
        if num_kinds > 64:
            raise ValueError("actor.num_kinds must be <= 64")
        self.actor = actor
        self.cfg = cfg
        self._step_one = self._build_step()
        # The batched step the run loops iterate: a plain vmap of the
        # per-world step, or — with cfg.pallas — the same step fused
        # into one pl.pallas_call (engine/pallas_step.py) so every lane
        # update shares one VMEM residency. Bitwise identical by
        # construction: the kernel body IS the vmapped step.
        if cfg.pallas:
            from .pallas_step import make_pallas_step

            self._batched_step = make_pallas_step(self._step_one, cfg)
        else:
            self._batched_step = jax.vmap(self._step_one)
        self.step = jax.jit(self._batched_step)
        # The run loops DONATE their input state: XLA aliases the output
        # onto the argument buffers and updates the 200-400 MB world state
        # in place instead of double-buffering it — roughly doubling the W
        # that fits in HBM (docs/perf.md "Single-pass insert + donation").
        # Contract for callers: the state you pass in is DEAD afterwards
        # (reading it raises); rebind, as every in-repo caller does.
        self._run_steps = jax.jit(self._run_steps_impl, static_argnums=1,
                                  donate_argnums=0)
        self._run = jax.jit(self._run_impl, static_argnums=1,
                            donate_argnums=0)
        # Built once: jit's own cache keys on the fault-array shape, so
        # repeated init() calls (and every sweep) reuse the compilation
        # instead of paying a fresh trace per call.
        self._init_batched = jax.jit(jax.vmap(self._init_one))
        # refill's select donates the old state (the merged batch aliases
        # it in place); the fresh batch is NOT donated — the select can
        # only alias one source, and donating both just trips XLA's
        # "donated buffer not usable" warning for the loser.
        self._refill_select = jax.jit(tree_select_worlds,
                                      donate_argnums=(2,))

    # ------------------------------------------------------------------
    # Initialization
    # ------------------------------------------------------------------
    def init(self, seeds, faults: Optional[np.ndarray] = None,
             configs: Optional[np.ndarray] = None) -> WorldState:
        """Build W worlds from a vector of u64 seeds.

        ``faults``: optional int32 array of fault-schedule rows
        ``[time_us, op, a, b]``, shape (F, 4) (same schedule every world) or
        (W, F, 4) (per-world schedules). Rows with time < 0 are disabled —
        use that to give worlds ragged schedules under one static F.

        ``configs``: optional per-world network config, shape (3,) (every
        world) or (W, 3) (per world): columns ``[latency_min_us,
        latency_max_us, loss_rate]`` (latencies int µs, loss a float
        probability). Defaults to the EngineConfig values. This is the
        (seeds × loss × latency) sweep axis: one compiled function explores
        the whole fault-model grid because net config is world *data*, not
        a jit constant (reference analog: a fresh run per config,
        `network.rs:74-94`).
        """
        seeds = np.asarray(seeds, dtype=np.uint64)
        if seeds.ndim != 1:
            raise ValueError("seeds must be a 1-D vector (one world per seed)")
        w = seeds.shape[0]
        lo = (seeds & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        hi = (seeds >> np.uint64(32)).astype(np.uint32)
        if faults is None:
            faults = np.zeros((w, 0, 4), np.int32)
        else:
            faults = np.asarray(faults, np.int32)
            if faults.ndim == 2:
                faults = np.broadcast_to(faults, (w,) + faults.shape)
            # Validate enabled rows here, at the API boundary: the packed
            # queue stores node ids in 8 bits, so an out-of-range id would
            # otherwise alias onto a real node (a=256 would kill node 0)
            # instead of erroring.
            live = faults[..., 0] >= 0
            ops = faults[..., 1]
            a, b = faults[..., 2], faults[..., 3]
            node_op = (ops <= FAULT_UNCLOG_LINK) | (ops >= FAULT_PAUSE)
            if np.any(live & ((ops < FAULT_KILL) | (ops > FAULT_RESUME))):
                raise ValueError("fault op must be one of FAULT_KILL.."
                                 "FAULT_RESUME")
            node_params = np.stack([a, b], axis=-1)
            if np.any((live & node_op)[..., None]
                      & ((node_params < 0)
                         | (node_params >= self.cfg.n_nodes))):
                raise ValueError(
                    f"fault-row node ids must be in [0, {self.cfg.n_nodes})")
            set_lat = live & (ops == FAULT_SET_LATENCY)
            if np.any(set_lat & ((a < 0) | (b <= a))):
                raise ValueError("FAULT_SET_LATENCY needs 0 <= min < max µs")
            set_loss = live & (ops == FAULT_SET_LOSS)
            if np.any(set_loss & ((a < 0) | (a > 1_000_000))):
                raise ValueError("FAULT_SET_LOSS rate must be 0..1e6 ppm")
            # Packed payload words are int16, so each full-width net
            # param spans two words (lanes.split_wide): [a_lo, a_hi,
            # b_lo, b_hi] instead of [a, b].
            need_words = 4 if self.cfg.packed else 2
            if np.any(set_lat | set_loss) and \
                    self.cfg.payload_words < need_words:
                raise ValueError("net-config fault rows carry their params "
                                 f"in the payload: payload_words must be "
                                 f">= {need_words} (packed={self.cfg.packed})")

        if configs is None:
            configs = np.array([self.cfg.latency_min_us,
                                self.cfg.latency_max_us,
                                self.cfg.loss_rate], np.float64)
        configs = np.asarray(configs, np.float64)
        configs = np.broadcast_to(configs, (w, 3))
        lat_min = configs[:, 0].astype(np.int32)
        lat_max = configs[:, 1].astype(np.int32)
        loss = configs[:, 2].astype(np.float32)
        if np.any(lat_min < 0) or np.any(lat_max <= lat_min):
            raise ValueError("configs need 0 <= latency_min < latency_max µs")
        if np.any((loss < 0.0) | (loss > 1.0)):
            raise ValueError("configs loss_rate must be in [0, 1]")

        return self._init_batched(jnp.asarray(lo), jnp.asarray(hi),
                                  jnp.asarray(faults), jnp.asarray(lat_min),
                                  jnp.asarray(lat_max), jnp.asarray(loss))

    def _net_fault_payload_batch(self, rows, n_faults):
        """(F, P) int32 payload table for fault rows: net-config params
        ride the payload (src/dst are 8-bit packed and would truncate
        µs). Packed profile: each param splits across two int16-range
        words (lanes.split_wide) since the at-rest payload lane is i16."""
        cfg = self.cfg
        is_net = (rows[:, 1] == FAULT_SET_LATENCY) \
            | (rows[:, 1] == FAULT_SET_LOSS)
        a = jnp.where(is_net, rows[:, 2], 0)
        b = jnp.where(is_net, rows[:, 3], 0)
        pay = jnp.zeros((n_faults, cfg.payload_words), jnp.int32)
        if cfg.packed:
            a_lo, a_hi = split_wide(a)
            pay = pay.at[:, 0].set(a_lo)
            if cfg.payload_words >= 2:
                pay = pay.at[:, 1].set(a_hi)
            if cfg.payload_words >= 4:
                b_lo, b_hi = split_wide(b)
                pay = pay.at[:, 2].set(b_lo).at[:, 3].set(b_hi)
        else:
            pay = pay.at[:, 0].set(a)
            if cfg.payload_words >= 2:
                pay = pay.at[:, 1].set(b)
        return is_net, pay

    def _init_one(self, seed_lo, seed_hi, fault_rows, lat_min, lat_max, loss):
        cfg = self.cfg
        n_faults = fault_rows.shape[0]  # static under jit (shape-keyed cache)
        rng = make_rng(seed_lo, seed_hi, STREAM_DEVICE)
        q = empty_queue(cfg.queue_cap, cfg.payload_words,
                        payload_dtype=cfg.lanes.payload)
        astate, events, rng = self.actor.init(cfg, rng)
        overflow = jnp.asarray(False)
        if cfg.sequential_insert:
            for ev in events:
                q, ok = push(q, ev)
                overflow = overflow | ~ok
        elif events:
            q, oks, _ = push_many(
                q, jax.tree.map(lambda *xs: jnp.stack(xs), *events))
            overflow = overflow | ~jnp.all(oks)
        if n_faults and not cfg.sequential_insert:
            rows = fault_rows
            # Net-config params exceed the packed 8-bit src/dst fields, so
            # they ride the payload; node ops keep using src/dst, whose
            # 8 bits the init-time validation guards.
            is_net, pay = self._net_fault_payload_batch(rows, n_faults)
            zeros = jnp.zeros((n_faults,), jnp.int32)
            fevs = Event(time=rows[:, 0], kind=rows[:, 1],
                         flags=jnp.full((n_faults,), FLAG_FAULT, jnp.int32),
                         src=jnp.where(is_net, zeros, rows[:, 2]),
                         dst=jnp.where(is_net, zeros, rows[:, 3]),
                         gen=zeros, payload=pay)
            q, oks, _ = push_many(q, fevs, enable=rows[:, 0] >= 0)
            overflow = overflow | ~jnp.all(oks)
        elif n_faults:
            # Static unroll (sequential_insert); the payload layout is
            # shared with the batched branch above.
            is_net_all, pay_all = self._net_fault_payload_batch(
                fault_rows, n_faults)
            for f in range(n_faults):
                row = fault_rows[f]
                zero = jnp.int32(0)
                fev = Event(time=row[0], kind=row[1],
                            flags=jnp.int32(FLAG_FAULT),
                            src=jnp.where(is_net_all[f], zero, row[2]),
                            dst=jnp.where(is_net_all[f], zero, row[3]),
                            gen=jnp.int32(0), payload=pay_all[f])
                q, ok = push(q, fev, enable=row[0] >= 0)
                overflow = overflow | ~ok
        n = cfg.n_nodes
        # One O(Q) reduction at init seeds the carried depth; every step
        # after this maintains it incrementally (pop/push_many deltas).
        # The carried lane rides the (int16-capable) slot dtype; the
        # metrics block keeps the wide count.
        qd32 = queue_depth(q)
        qd = narrow(qd32, cfg.lanes.slot)
        # Metrics start from the init-time queue contents: the actor's
        # seed events and the fault rows count as enqueued.
        mb = (MetricsBlock.zeros(self.actor.num_kinds)._replace(enqueued=qd32)
              if cfg.metrics else None)
        bb = BlackboxRing.zeros(cfg.blackbox, cfg.lanes) \
            if cfg.blackbox else None
        return WorldState(
            now=jnp.int32(0),
            queue=q,
            rng=rng,
            alive=jnp.ones((n,), bool),
            # Generations compare mod 256 (queue.GEN_MASK), so the lane
            # rides the i8 code dtype with WRAP semantics.
            gen=jnp.zeros((n,), cfg.lanes.code),
            paused=jnp.zeros((n,), bool),
            clog_node=jnp.zeros((n,), bool),
            clog_link=jnp.zeros((n, n), bool),
            astate=astate,
            active=jnp.asarray(True),
            steps=jnp.int32(0),
            delivered=jnp.int32(0),
            dropped=jnp.int32(0),
            overflow=overflow,
            qdepth=qd,
            qmax=qd,
            bug=jnp.asarray(False),
            bug_time=INF_TIME,
            lat_min=lat_min,
            lat_max=lat_max,
            loss=loss,
            metrics=mb,
            blackbox=bb,
        )

    def refill(self, state: WorldState, slot_mask, new_seeds,
               faults: Optional[np.ndarray] = None,
               configs: Optional[np.ndarray] = None) -> WorldState:
        """Recycle retired batch slots: select freshly initialized worlds
        into the masked positions, on device.

        ``slot_mask`` is a (W,) bool vector over the batch; True slots
        receive the world initialized from the matching row of
        ``new_seeds`` (length W — rows outside the mask are initialized
        and immediately discarded by the select, so any placeholder seed
        works there). ``faults``/``configs`` follow :meth:`init`, plus
        one refill-specific form: a first-class PER-SLOT schedule
        override, ``(W, F, 4)`` with one fault block per refill slot —
        the shape the guided-search generator emits (search/generate.py).
        A per-slot ``faults`` may be a **device array** (``jax.Array``):
        that path skips the host-side row-value validation — no device
        sync ever happens inside the refill — under the documented
        contract that device schedules are valid by construction (the
        search mutation operators preserve validity; the seeded template
        was validated by ``init`` at sweep start). Host arrays validate
        as in ``init``.

        Worlds are position-independent, so a refilled slot's trajectory
        is bit-identical to an independent ``init``+run of that seed —
        the recycled-sweep contract (tests/test_parallel.py). When
        ``state`` is mesh-sharded, the fresh worlds are placed onto the
        same sharding first so the select is a device-side program, not
        an implicit reshard through the host.

        ``state`` (and the internal fresh batch) are **donated** into the
        select: the argument is dead after the call — rebind the result.
        """
        w = int(np.asarray(new_seeds).shape[0])
        if faults is not None and getattr(faults, "ndim", 0) == 3:
            # Validate the per-slot leading dim HERE, naming both dims:
            # a mismatched (m, F, 4) would otherwise surface as an
            # opaque vmap shape error deep inside _init_batched.
            if faults.shape[-1] != 4:
                raise ValueError(
                    f"per-slot fault schedules must be (n_slots, F, 4) "
                    f"rows of [time_us, op, a, b]; got shape "
                    f"{tuple(faults.shape)}")
            if faults.shape[0] != w:
                raise ValueError(
                    f"per-slot fault schedules carry one (F, 4) block "
                    f"per batch slot: got leading dim {faults.shape[0]} "
                    f"but the refill batch holds {w} slots")
        if isinstance(faults, jax.Array) and not isinstance(
                faults, np.ndarray):
            if faults.ndim != 3:
                raise ValueError(
                    f"a device-resident fault override must be per-slot "
                    f"(n_slots, F, 4); got {faults.ndim}-D shape "
                    f"{tuple(faults.shape)} — pass host arrays for the "
                    "shared-schedule form")
            fresh = self._init_device(new_seeds, faults, configs)
        else:
            fresh = self.init(new_seeds, faults=faults, configs=configs)
        mask = jnp.asarray(np.asarray(slot_mask, bool))
        sharding = getattr(state.now, "sharding", None)
        if isinstance(sharding, jax.sharding.NamedSharding):
            fresh, mask = jax.device_put((fresh, mask), sharding)
        return self._refill_select(mask, fresh, state)

    def _init_device(self, seeds, faults, configs=None) -> WorldState:
        """:meth:`init` for device-resident per-world fault schedules.

        Identical program (the same jitted ``_init_batched``), but the
        ``(W, F, 4)`` faults array stays on device — no value
        validation, because ``np.any`` over a ``jax.Array`` would force
        a blocking device→host sync in the middle of the sweep loop.
        Callers own the validity contract (see :meth:`refill`).
        """
        seeds = np.asarray(seeds, dtype=np.uint64)
        w = seeds.shape[0]
        lo = (seeds & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        hi = (seeds >> np.uint64(32)).astype(np.uint32)
        if configs is None:
            configs = np.array([self.cfg.latency_min_us,
                                self.cfg.latency_max_us,
                                self.cfg.loss_rate], np.float64)
        configs = np.broadcast_to(np.asarray(configs, np.float64), (w, 3))
        return self._init_batched(
            jnp.asarray(lo), jnp.asarray(hi),
            jnp.asarray(faults, jnp.int32),
            jnp.asarray(configs[:, 0].astype(np.int32)),
            jnp.asarray(configs[:, 1].astype(np.int32)),
            jnp.asarray(configs[:, 2].astype(np.float32)))

    # ------------------------------------------------------------------
    # The per-world step
    # ------------------------------------------------------------------
    def _build_step(self) -> Callable[[WorldState], WorldState]:
        cfg = self.cfg
        actor = self.actor
        num_kinds = int(actor.num_kinds)  # kind_hist width (metrics)

        def net_params(payload):
            """Net-config fault params from an event payload — [a, b]
            full-width in the wide profile, [a_lo, a_hi, b_lo, b_hi]
            int16-range halves in the packed one (the at-rest payload
            lane is i16; _net_fault_payload_batch is the encoder).
            Short payloads return zeros: init() rejects net rows that
            would not fit, so the params are never read then."""
            if cfg.packed:
                if cfg.payload_words >= 4:
                    return (join_wide(payload[0], payload[1]),
                            join_wide(payload[2], payload[3]))
                return jnp.int32(0), jnp.int32(0)
            if cfg.payload_words >= 2:
                return payload[0], payload[1]
            return payload[0], jnp.int32(0)

        def apply_fault(ws: WorldState, ev: Event) -> Tuple[WorldState, Outbox]:
            op, a, b = ev.kind, ev.src, ev.dst
            is_kill = op == FAULT_KILL
            is_restart = op == FAULT_RESTART
            alive = upd(ws.alive, a, jnp.where(
                is_kill, False,
                jnp.where(is_restart, True, take_small(ws.alive, a))))
            # Wide read, wrapping narrow write: generations are mod-256
            # by contract (GEN_MASK), so the i8 lane wraps — never
            # saturates (lanes.narrow_wrap, not narrow).
            gen = upd(ws.gen, a, narrow_wrap(
                widen(take_small(ws.gen, a))
                + (is_kill | is_restart).astype(jnp.int32), ws.gen.dtype))
            # Pause buffers; resume releases. Kill/restart clear the pause
            # (the reference swaps in a fresh NodeInfo, `task.rs:211-240`).
            paused = upd(ws.paused, a, jnp.where(
                op == FAULT_PAUSE, True,
                jnp.where((op == FAULT_RESUME) | is_kill | is_restart,
                          False, take_small(ws.paused, a))))
            clog_node = upd(ws.clog_node, a, jnp.where(
                op == FAULT_CLOG_NODE, True,
                jnp.where(op == FAULT_UNCLOG_NODE, False,
                          take_small(ws.clog_node, a))))
            clog_link = upd2(ws.clog_link, a, b, jnp.where(
                op == FAULT_CLOG_LINK, True,
                jnp.where(op == FAULT_UNCLOG_LINK, False,
                          take_small(take_small(ws.clog_link, a), b))))
            # Hot net-config updates take effect at exactly this virtual
            # instant: sends after this event sample the new model
            # (update_config parity, `net/mod.rs:127-130`). Params arrive in
            # the payload — src/dst are 8-bit packed and would truncate µs.
            set_lat = op == FAULT_SET_LATENCY
            set_loss = op == FAULT_SET_LOSS
            pa, pb = net_params(ev.payload)
            lat_min = jnp.where(set_lat, pa, ws.lat_min)
            lat_max = jnp.where(set_lat, pb, ws.lat_max)
            loss = jnp.where(set_loss,
                             pa.astype(jnp.float32) * jnp.float32(1e-6),
                             ws.loss)
            astate_r, ob_r, rng_r = actor.on_restart(cfg, ws.astate, a, ws.now, ws.rng)
            astate = tree_select(is_restart, astate_r, ws.astate)
            rng = tree_select(is_restart, rng_r, ws.rng)
            ob = tree_select(is_restart, ob_r, Outbox.empty(cfg))
            return ws._replace(alive=alive, gen=gen, paused=paused,
                               clog_node=clog_node, clog_link=clog_link,
                               astate=astate, rng=rng, lat_min=lat_min,
                               lat_max=lat_max, loss=loss), ob

        def push_outbox(ws: WorldState, src, ob: Outbox, pre_q: EventQueue,
                        clear) -> WorldState:
            m = cfg.m
            loss = ws.loss  # per-world runtime data, not a jit constant
            # Two draws per slot regardless of validity, batched into one
            # Threefry block: the draw count per step is static, so RNG
            # counters depend only on step index — replayable and
            # backend-independent. Counters (and therefore values) are
            # bit-identical to the per-slot sequential draws.
            xs, rng = next_u32_vec(ws.rng, 2 * m)
            lat = _u32_to_range(xs[0::2], ws.lat_min, ws.lat_max)  # (M,)
            u = _u32_to_unit_f32(xs[1::2])                         # (M,)
            dst = jnp.clip(ob.dst, 0, cfg.n_nodes - 1)             # (M,)
            clogged = take_small(ws.clog_node, src) \
                | take_small(ws.clog_node, dst) \
                | take_small(take_small(ws.clog_link, src), dst)   # (M,)
            dropped = (~ob.is_timer) & (clogged | (u < loss))
            # Saturating schedule time: now + delay can wrap int32 when
            # t_limit_us or an actor delay is near 2^31. Both operands
            # are <= INF_TIME, so min-before-add cannot overflow.
            delay = jnp.maximum(jnp.where(ob.is_timer, ob.delay_us, lat), 0)
            t = ws.now + jnp.minimum(delay, INF_TIME - ws.now)
            flags = jnp.where(ob.is_timer, FLAG_TIMER, 0).astype(jnp.int32)
            gen_dst = widen(take_small(ws.gen, dst))  # wide in flight
            # Gated on the world's (pre-step) active flag: frozen worlds
            # write nothing into the queue, which is what lets the step's
            # tail skip the whole-state frozen-world restore select.
            enable = ob.valid & ~dropped & ws.active
            if cfg.sequential_insert:
                # The pre-fusion path, kept verbatim as the equivalence
                # reference: M statically unrolled full-queue rewrites.
                q, overflow = ws.queue, ws.overflow
                for i in range(m):  # static unroll
                    ev = Event(time=t[i], kind=ob.kind[i], flags=flags[i],
                               src=jnp.asarray(src, jnp.int32), dst=dst[i],
                               gen=gen_dst[i], payload=ob.payload[i])
                    q, ok = push(q, ev, enable=enable[i])
                    overflow = overflow | ~ok
                qd32 = queue_depth(q)
                # Inserted count via the carried-depth invariant (the
                # chain exposes no n_ins): metrics stay path-independent.
                n_ins = qd32 - widen(ws.qdepth)
                qdepth = narrow(qd32, ws.qdepth.dtype)
            else:
                # Single fused pass (queue.push_many): rank-matched M-row
                # scatter of the compacted outbox — M·(2+P) element
                # writes instead of M full-queue rewrites, bitwise
                # identical to the unrolled chain above (docs/perf.md
                # r7). This replaces the r2-era (Q, M) matching-matrix
                # design the old comment here rejected: no matrices, only
                # the (M, M) compaction index and popcount slot math.
                evs = Event(
                    time=t, kind=ob.kind, flags=flags,
                    src=jnp.broadcast_to(jnp.asarray(src, jnp.int32), (m,)),
                    dst=dst, gen=gen_dst, payload=ob.payload)
                # pre_q + clear rather than ws.queue: push_many fuses the
                # pop's clear into its own time-lane write, so every lane
                # read is a materialized state buffer (see its docstring)
                # and the pop's separate cleared lane becomes dead code.
                q, oks, n_ins = push_many(pre_q, evs, enable, clear=clear)
                overflow = ws.overflow | ~jnp.all(oks)
                # n_ins <= M by construction, so the narrowing cast into
                # the carried slot lane cannot saturate.
                qdepth = ws.qdepth + narrow(n_ins, ws.qdepth.dtype)
            qmax = jnp.maximum(ws.qmax, qdepth)
            metrics = ws.metrics
            if cfg.metrics:
                # Send-side counters (obs/metrics.py). Strictly write-only:
                # nothing above reads the block, so the metrics-on step is
                # bit-identical to metrics-off on every other leaf.
                i32 = jnp.int32
                _n_req, n_inf, n_over = insert_metrics(t, enable, n_ins)
                # dtype-pinned sums: under jax_enable_x64 a plain
                # jnp.sum(i32) widens its accumulator to i64, which would
                # make the metrics block's dtypes depend on a process
                # flag (tracelint TRC003).
                metrics = metrics._replace(
                    msgs_sent=metrics.msgs_sent + jnp.sum(
                        (ob.valid & ~ob.is_timer & ws.active), dtype=i32),
                    drop_loss=metrics.drop_loss + jnp.sum(
                        (ob.valid & dropped & ws.active), dtype=i32),
                    enqueued=metrics.enqueued + jnp.asarray(n_ins, i32),
                    drop_overflow=metrics.drop_overflow + n_over,
                    drop_inf=metrics.drop_inf + n_inf,
                )
            return ws._replace(queue=q, rng=rng, overflow=overflow,
                               qdepth=qdepth, qmax=qmax, metrics=metrics)

        def step(ws: WorldState) -> WorldState:
            # The pop is gated on ws.active too (see push_outbox): a
            # frozen world pops nothing, so every queue lane, counter and
            # actor field below is left untouched through its own masked
            # dataflow — no end-of-step whole-state restore select.
            q, ev, found, slot = pop_indexed(
                ws.queue,
                eligible_mask(ws.queue, ws.paused, cfg.n_nodes) & ws.active)
            now = jnp.where(found, jnp.maximum(ws.now, ev.time), ws.now)
            in_time = now < jnp.int32(cfg.t_limit_us)
            ws1 = ws._replace(queue=q, now=now, steps=ws.steps + 1,
                              qdepth=ws.qdepth
                              - found.astype(ws.qdepth.dtype))

            dst = jnp.clip(ev.dst, 0, cfg.n_nodes - 1)
            is_fault = (ev.flags & FLAG_FAULT) != 0
            is_timer = (ev.flags & FLAG_TIMER) != 0
            # Generations compare modulo the packed width (queue.GEN_MASK).
            stale = is_timer & (ev.gen != (widen(take_small(ws1.gen, dst))
                                           & GEN_MASK))
            dead = ~take_small(ws1.alive, dst)
            deliver = found & in_time & ~is_fault & ~stale & ~dead
            do_fault = found & in_time & is_fault

            fault_ws, fault_ob = apply_fault(ws1, ev)
            astate2, act_ob, rng2, hbug = actor.handle(cfg, ws1.astate, ev, now, ws1.rng)
            act_ws = ws1._replace(astate=astate2, rng=rng2)

            ws2 = tree_select(do_fault, fault_ws,
                              tree_select(deliver, act_ws, ws1))
            ob = tree_select(do_fault, fault_ob,
                             tree_select(deliver, act_ob, Outbox.empty(cfg)))
            src = jnp.where(do_fault, jnp.clip(ev.src, 0, cfg.n_nodes - 1), dst)
            ws3 = push_outbox(ws2, src, ob, ws.queue, (slot, found))

            bug_now = (deliver & hbug) | actor.invariant(cfg, ws3.astate)
            bug = ws3.bug | bug_now
            bug_time = jnp.where(bug & ~ws3.bug, now, ws3.bug_time)
            active = found & in_time & ~(cfg.stop_on_bug & bug)
            ws4 = ws3._replace(
                bug=bug, bug_time=bug_time, active=active,
                delivered=ws3.delivered + deliver.astype(jnp.int32),
                dropped=ws3.dropped
                + (found & in_time & ~deliver & ~do_fault).astype(jnp.int32),
            )
            if cfg.metrics:
                # Pop-side counters (obs/metrics.py); ws3.metrics already
                # carries this step's send-side increments. Every
                # increment is gated on ``found`` (itself gated on
                # ws.active), so frozen worlds' blocks never move — no
                # restore needed in the tail below. Write-only: the
                # trajectory never reads these.
                i32 = jnp.int32
                mb = ws3.metrics
                mb = mb._replace(
                    msgs_delivered=mb.msgs_delivered
                    + (deliver & ~is_timer).astype(i32),
                    timer_fires=mb.timer_fires
                    + (deliver & is_timer).astype(i32),
                    drop_stale=mb.drop_stale
                    + (found & in_time & ~is_fault & stale).astype(i32),
                    drop_dead=mb.drop_dead
                    + (found & in_time & ~is_fault & ~stale
                       & dead).astype(i32),
                    drop_out_of_time=mb.drop_out_of_time
                    + (found & ~in_time).astype(i32),
                    vtime_us=mb.vtime_us + (now - ws.now),
                    # onehot's drop semantics cover wild kinds: an
                    # out-of-range index increments no bin.
                    fault_hist=mb.fault_hist
                    + (onehot(ev.kind, NUM_FAULT_KINDS)
                       & do_fault).astype(i32),
                    kind_hist=mb.kind_hist
                    + (onehot(ev.kind, num_kinds) & deliver).astype(i32),
                )
                ws4 = ws4._replace(metrics=mb)
            if cfg.blackbox:
                # Flight recorder (obs/blackbox.py): one packed record
                # per step trace() would record — a valid processed
                # event (found & in_time; ``found`` is already gated on
                # ws.active by the pop) or the ``invariant`` marker for
                # a raise on a step that processed no event. A frozen
                # world records nothing (found is False and its bug flag
                # cannot rise on unchanged state), so — like metrics —
                # the ring needs no restore in the tail below.
                # Write-only: the trajectory never reads these lanes.
                i32 = jnp.int32
                k = cfg.blackbox
                rb = ws3.blackbox
                valid = found & in_time
                raised = bug & ~ws3.bug
                marker = raised & ~valid
                rec = valid | marker
                # Record r lands at slot r % K; a disabled write aims at
                # slot K, which onehot's drop semantics turn into a
                # no-op (the upd-out-of-range idiom).
                cur = jnp.where(rec, jnp.remainder(rb.pos, k), i32(k))
                # Valid entries record the event's own time (trace's
                # t_us); the marker records the post-step clock.
                t_lo, t_hi = split_wide(jnp.where(marker, now, ev.time))
                fl = ((valid & is_timer).astype(i32) * BB_TIMER
                      + (valid & is_fault).astype(i32) * BB_FAULT
                      + (valid & ~is_fault & stale).astype(i32)
                      * BB_DROP_STALE
                      + (valid & ~is_fault & ~stale & dead).astype(i32)
                      * BB_DROP_DEAD
                      + raised.astype(i32) * BB_RAISE
                      + marker.astype(i32) * BB_MARKER)
                rb = rb._replace(
                    pos=rb.pos + rec.astype(i32),
                    # Step index wraps mod the slot-lane width by
                    # contract (decode reconstructs the high bits from
                    # pos) — pre-wrapped so upd's saturating narrow
                    # passes it through untouched (the gen-lane idiom).
                    step_lo=upd(rb.step_lo, cur,
                                narrow_wrap(ws.steps, rb.step_lo.dtype)),
                    t_lo=upd(rb.t_lo, cur, t_lo),
                    t_hi=upd(rb.t_hi, cur, t_hi),
                    kind=upd(rb.kind, cur, jnp.where(valid, ev.kind, 0)),
                    src=upd(rb.src, cur, jnp.where(valid, ev.src, -1)),
                    dst=upd(rb.dst, cur, jnp.where(valid, ev.dst, -1)),
                    flags=upd(rb.flags, cur, fl),
                )
                ws4 = ws4._replace(blackbox=rb)
            # Frozen worlds pass through untouched. Every lane write above
            # is already gated on ws.active (the pop found nothing, the
            # outbox was disabled, faults/delivery/bug flags all require
            # ``found``), so only the two unconditionally-advancing pieces
            # need an explicit restore: the RNG cursor (push_outbox draws
            # its static 2M block every step) and the step counter. This
            # replaces a whole-state select — ~1 op per state element per
            # step — with two scalar-sized ones (docs/perf.md r7).
            return ws4._replace(
                rng=tree_select(ws.active, ws4.rng, ws.rng),
                steps=jnp.where(ws.active, ws4.steps, ws.steps))

        return step

    # ------------------------------------------------------------------
    # Batched run loops
    # ------------------------------------------------------------------
    def _run_steps_impl(self, state: WorldState, k: int) -> WorldState:
        batched = self._batched_step

        def body(s, _):
            return batched(s), None

        state, _ = jax.lax.scan(body, state, None, length=k)
        return state

    def run_steps(self, state: WorldState, k: int) -> WorldState:
        """Advance every world by exactly ``k`` masked steps (fixed cost).

        ``state`` is **donated**: its buffers are updated in place and the
        passed-in pytree is dead after the call — rebind
        (``state = eng.run_steps(state, k)``), never reuse the argument.
        """
        return self._run_steps(state, k)

    def _superstep_impl(self, state: WorldState, stop_threshold,
                        stop_on_bug, k_chunks, *, chunk_steps: int,
                        k_max: int, reduce_sum, min_one: bool = False,
                        cov=None, cov_fold=None):
        """Up to ``k_chunks`` chunk bodies under ONE ``lax.while_loop``.

        This is the device half of the pipelined sweep orchestration
        (parallel/sweep.py): instead of one host dispatch per chunk, the
        host dispatches a *superstep* of K chunks and the early-exit
        decisions the serial loop made between chunks run ON DEVICE —
        the loop stops after the first chunk where the (reduced) active
        count drops to ``stop_threshold`` or, with ``stop_on_bug`` set,
        any world's bug flag rises. Threshold, stop flag AND ``k_chunks``
        are *traced scalars* (only the ``k_max`` history-buffer width is
        static), so ONE compiled program serves every threshold and
        superstep length the sweep cycles through — the loop bound of a
        ``lax.while_loop`` is dynamic anyway, and keying compiles on K
        would re-pay the whole step-body compile per ramp value.

        The condition is checked BEFORE the first chunk too: a superstep
        dispatched against a state that already satisfies a stop
        condition is a bitwise pass-through (zero chunks run). That
        no-op-by-construction property is what lets the sweep dispatch
        superstep k+1 before reading superstep k's scalars without ever
        advancing a world the serial loop would not have advanced.

        ``min_one`` (static) forces the FIRST chunk to run regardless of
        the entry condition — the serial loop's exact cadence right
        after a refill/shrink (it always runs one chunk before
        re-evaluating occupancy, even when the refilled count is already
        at the threshold). The sweep sets it on the first dispatch of
        each occupancy epoch; speculative dispatch-ahead supersteps keep
        ``min_one=False`` so stale ones stay pass-through no-ops.

        ``reduce_sum`` reduces a per-shard int32 scalar over the world
        axis — ``lax.psum`` inside a shard_mapped sweep, ``jnp.sum``'s
        identity under plain vmap use. Returns ``(state, any_bug,
        n_active, k_done, hist)`` where ``hist[j]`` is the active count
        measured after chunk ``j`` (-1 for chunks not run), exactly the
        per-chunk sequence the serial loop observed.

        ``cov``/``cov_fold`` (obs/coverage.py, set together or not at
        all): the retire-time coverage fold. ``cov`` is the behavior
        ledger carried through the loop; after each chunk body the fold
        callback receives ``(cov, pre_chunk_active, post_chunk_state)``
        and scatters the signatures of the worlds whose active flag fell
        during the chunk — each world folds exactly once, with no extra
        carried bookkeeping, and the fold *sequence* matches the serial
        loop's because both execute identical chunk bodies. Purely
        read-only over the simulation state (the bitwise-invisibility
        contract of ``MetricsBlock`` extends to it). With coverage on
        the return grows to ``(..., hist, cov, cov_hist)`` where
        ``cov_hist[j]`` is the cumulative distinct-behavior count after
        chunk ``j`` (-1 beyond ``k_done``) — the novelty curve sampled
        at exactly the ``hist`` cadence.
        """
        from ..obs.coverage import distinct_count

        def measure(s):
            any_bug = reduce_sum(jnp.any(s.bug).astype(jnp.int32)) > 0
            # dtype-pinned: jnp.sum(i32) widens to i64 under x64 (TRC003).
            n_active = reduce_sum(jnp.sum(s.active, dtype=jnp.int32))
            return any_bug, n_active

        stop_threshold = jnp.asarray(stop_threshold, jnp.int32)
        stop_on_bug = jnp.asarray(stop_on_bug, bool)
        k_chunks = jnp.minimum(jnp.asarray(k_chunks, jnp.int32), k_max)
        any_bug0, n_active0 = measure(state)
        hist0 = jnp.full((k_max,), -1, jnp.int32)
        with_cov = cov_fold is not None
        # The coverage slots ride the carry ONLY when the fold is on, so
        # the coverage-off superstep remains the exact pre-coverage
        # program (None is an empty pytree: zero extra carry leaves).
        cov_hist0 = jnp.full((k_max,), -1, jnp.int32) if with_cov else None

        def cond(carry):
            _s, i, any_bug, n_active, _hist, _cov, _ch = carry
            run_more = ((n_active > stop_threshold)
                        & ~(stop_on_bug & any_bug))
            if min_one:
                run_more = (i == 0) | run_more
            return (i < k_chunks) & run_more

        def body(carry):
            s, i, _any_bug, _n_active, hist, cv, ch = carry
            act0 = s.active
            s = self._run_steps_impl(s, chunk_steps)
            any_bug, n_active = measure(s)
            hist = jax.lax.dynamic_update_index_in_dim(hist, n_active, i, 0)
            if with_cov:
                cv = cov_fold(cv, act0, s)
                ch = jax.lax.dynamic_update_index_in_dim(
                    ch, distinct_count(cv[0]), i, 0)
            return s, i + 1, any_bug, n_active, hist, cv, ch

        state, k_done, any_bug, n_active, hist, cov, cov_hist = \
            jax.lax.while_loop(
                cond, body,
                (state, jnp.int32(0), any_bug0, n_active0, hist0,
                 cov, cov_hist0))
        if with_cov:
            return state, any_bug, n_active, k_done, hist, cov, cov_hist
        return state, any_bug, n_active, k_done, hist

    def _fused_superstep_impl(self, state: WorldState, extras, stop_on_bug,
                              k_chunks, *, chunk_steps: int, k_max: int,
                              post_chunk, entry_stop):
        """:meth:`_superstep_impl` with an in-loop epoch body: the
        whole-hunt device loop.

        Where the plain superstep EXITS when occupancy crosses a
        threshold (so the host can refill/compact between dispatches),
        this variant hands each chunk boundary to ``post_chunk`` — a
        traced callback that owns the epoch machinery the serial sweep
        loop ran on host: compaction, retiring-tail harvest, coverage/
        lineage folds, guided child generation, the refill select and
        the seed-cursor advance (parallel/sweep.py builds it). The loop
        itself never stops for occupancy; it stops only when the
        callback says the *hunt* is over (cursor dry and no world
        active, or a bug under ``stop_on_bug``) or the chunk budget
        ``k_chunks`` is spent.

        ``extras`` is an opaque pytree carried through the loop — the
        sweep threads the slot→seed index, the device seed cursor, the
        per-seed observation buffers, the coverage ledger and the search
        corpus through it. ``post_chunk(s, extras, act0, any_bug,
        n_active, i)`` returns ``(s, extras, stop)``; ``entry_stop(
        extras, any_bug0, n_active0)`` evaluates the same stop predicate
        BEFORE the first chunk, preserving the plain superstep's
        pass-through property (a dispatch against a finished hunt runs
        zero chunks bitwise).

        Reductions are full-array ``jnp`` ops, not ``psum``: the fused
        program is a plain ``jit`` partitioned by GSPMD (the
        ``_compactor`` precedent — its global stable argsort cannot run
        under ``shard_map``), so a dtype-pinned integer sum over the
        whole world axis is already the global count.
        """
        def measure(s):
            any_bug = jnp.any(s.bug)
            # dtype-pinned: jnp.sum(i32) widens to i64 under x64 (TRC003).
            n_active = jnp.sum(s.active, dtype=jnp.int32)
            return any_bug, n_active

        stop_on_bug = jnp.asarray(stop_on_bug, bool)
        k_chunks = jnp.minimum(jnp.asarray(k_chunks, jnp.int32), k_max)
        any_bug0, n_active0 = measure(state)
        hist0 = jnp.full((k_max,), -1, jnp.int32)
        stop0 = entry_stop(extras, any_bug0, n_active0)

        def cond(carry):
            _s, i, stop, _ab, _na, _hist, _extras = carry
            return (i < k_chunks) & ~stop

        def body(carry):
            s, i, _stop, _ab, _na, hist, extras = carry
            act0 = s.active
            s = self._run_steps_impl(s, chunk_steps)
            any_bug, n_active = measure(s)
            hist = jax.lax.dynamic_update_index_in_dim(hist, n_active, i, 0)
            s, extras, stop = post_chunk(s, extras, act0, any_bug,
                                         n_active, i)
            return s, i + 1, stop, any_bug, n_active, hist, extras

        state, k_done, _stop, any_bug, n_active, hist, extras = \
            jax.lax.while_loop(
                cond, body,
                (state, jnp.int32(0), stop0, any_bug0, n_active0, hist0,
                 extras))
        return state, extras, any_bug, n_active, k_done, hist

    def refill_traced(self, state: WorldState, slot_mask, seeds_lo,
                      seeds_hi, faults) -> WorldState:
        """:meth:`refill` as a pure traced program — the in-loop form.

        Built for the fused superstep's epoch body: no host validation,
        no ``device_put`` (everything already rides the enclosing
        program), no donation bookkeeping — just the same
        ``_init_one``-per-world init the jitted batched init runs,
        followed by the masked world select. ``seeds_lo``/``seeds_hi``
        are the split uint32 halves of the uint64 seeds (one row per
        batch slot; rows outside the mask initialize placeholder worlds
        the select discards, exactly like :meth:`refill`), ``faults`` is
        a per-slot ``(W, F, 4)`` int32 schedule block. Latency/loss
        configs come from the engine config — the only form the sweep's
        refill path ever uses. Bitwise contract: equal inputs produce
        worlds bit-identical to :meth:`refill`'s, because both run the
        same ``vmap``'d ``_init_one`` (jit does not change values).
        """
        w = state.active.shape[0]
        lat_min = jnp.full((w,), int(self.cfg.latency_min_us), jnp.int32)
        lat_max = jnp.full((w,), int(self.cfg.latency_max_us), jnp.int32)
        loss = jnp.full((w,), float(self.cfg.loss_rate), jnp.float32)
        fresh = jax.vmap(self._init_one)(seeds_lo, seeds_hi, faults,
                                         lat_min, lat_max, loss)
        return tree_select_worlds(slot_mask, fresh, state)

    def _run_impl(self, state: WorldState, max_steps: int) -> WorldState:
        batched = self._batched_step

        def cond(carry):
            s, i = carry
            return jnp.any(s.active) & (i < max_steps)

        def body(carry):
            s, i = carry
            return batched(s), i + 1

        state, _ = jax.lax.while_loop(cond, body, (state, jnp.int32(0)))
        return state

    def run(self, state: WorldState, max_steps: int = 100_000) -> WorldState:
        """Step until every world is inactive (or ``max_steps``).

        ``state`` is **donated** (see :meth:`run_steps`): the argument is
        dead after the call; rebind the return value. Peak device memory
        for the run is ~1× the state plus loop temporaries, not the 2×
        double-buffer of an undonated functional update (tier-1-tested
        via ``compiled.memory_analysis()``).
        """
        return self._run(state, max_steps)

    # ------------------------------------------------------------------
    # Single-seed tracing (repro tooling)
    # ------------------------------------------------------------------
    def trace(self, seed: int, max_steps: int = 2_000,
              faults: Optional[np.ndarray] = None) -> List[Dict[str, Any]]:
        """Replay ONE seed and return its full event trace.

        The device analog of re-running a failing seed with MADSIM_LOG on:
        feed a seed from ``SweepResult.failing_seeds`` (or
        ``device_first_failing_seed``) back in and get the ordered list of
        events — virtual time, kind, src→dst, fault/timer flags, payload,
        and the step at which the bug flag first rose. Runs as one scan on
        device; decoding happens on host afterwards.
        """
        state = jax.tree.map(lambda x: x[0],
                             self.init(np.asarray([seed], np.uint64),
                                       faults=faults))

        def body(s, _):
            # Pure peek of what step will pop, under the same pause-aware
            # eligibility the step itself uses.
            _q, ev, found = pop(
                s.queue, eligible_mask(s.queue, s.paused, self.cfg.n_nodes))
            s2 = self._step_one(s)
            # Mirror the step's own gates exactly: an event popped at/past
            # t_limit_us was not processed, and a stale timer or a message
            # to a dead node was popped-and-dropped, not delivered.
            in_time = jnp.maximum(s.now, ev.time) < jnp.int32(self.cfg.t_limit_us)
            dst_c = jnp.clip(ev.dst, 0, self.cfg.n_nodes - 1)
            is_fault = (ev.flags & FLAG_FAULT) != 0
            stale = ((ev.flags & FLAG_TIMER) != 0) & \
                (ev.gen != (widen(take_small(s2.gen, dst_c)) & GEN_MASK))
            dead = ~take_small(s2.alive, dst_c)
            delivered = ~is_fault & ~stale & ~dead
            rec = (found & s.active & in_time, ev.time, ev.kind, ev.flags,
                   ev.src, ev.dst, ev.payload, delivered, s2.bug, s2.now)
            return s2, rec

        final, recs = jax.lax.scan(body, state, None, length=max_steps)
        valid, time_us, kind, flags, src, dst, payload, delivered, bug, now_us = \
            (np.asarray(r) for r in recs)
        kind_names = getattr(self.actor, "kind_names", None)
        # Shared with the blackbox ring decoder (obs/blackbox.py) so the
        # two decoders cannot drift apart — the --crosscheck contract.
        fault_names = FAULT_NAMES
        out: List[Dict[str, Any]] = []
        bug_seen = False
        for i in range(max_steps):
            raised_here = bool(bug[i]) and not bug_seen
            if not valid[i]:
                if raised_here:
                    # The invariant rose on a step that processed no event
                    # (e.g. an out-of-time or empty-queue step): record it
                    # as its own marker so the raise point is never lost.
                    out.append({"step": i, "t_us": int(now_us[i]),
                                "kind": "invariant", "timer": False,
                                "src": -1, "dst": -1, "payload": [],
                                "bug_raised": True})
                    bug_seen = True
                continue
            is_fault = bool(flags[i] & FLAG_FAULT)
            k = int(kind[i])
            if is_fault:
                name = f"fault:{fault_names.get(k, k)}"
            elif kind_names is not None and 0 <= k < len(kind_names):
                name = kind_names[k]
            else:
                name = str(k)
            entry = {
                "step": i,
                "t_us": int(time_us[i]),
                "kind": name,
                "timer": bool(flags[i] & FLAG_TIMER),
                "src": int(src[i]),
                "dst": int(dst[i]),
                "payload": payload[i].tolist(),
            }
            if not is_fault and not delivered[i]:
                # Popped but NOT handled: stale timer (node generation
                # changed) or destination dead at delivery time.
                entry["dropped"] = True
            if raised_here:
                entry["bug_raised"] = True
                bug_seen = True
            out.append(entry)
        if bool(np.asarray(final.active)):
            # max_steps hit with the world still live: mark the cut
            # explicitly instead of silently ending the list — a consumer
            # (or a human) must never mistake a truncated timeline for a
            # retired world (obs/timeline.py renders the marker).
            out.append({"step": max_steps, "t_us": int(np.asarray(final.now)),
                        "kind": "truncated", "timer": False, "src": -1,
                        "dst": -1, "payload": [], "bug_seen": bug_seen})
            if not bug_seen:
                import warnings

                warnings.warn(
                    f"trace(seed={seed}) truncated at max_steps={max_steps} "
                    "before any bug_raised event — raise max_steps if you "
                    "expected the invariant violation in this window",
                    RuntimeWarning, stacklevel=2)
        return out

    # ------------------------------------------------------------------
    # Observation
    # ------------------------------------------------------------------
    def observe_device(self, state: WorldState) -> Dict[str, jnp.ndarray]:
        """The observation dict as device values — traceable under jit.

        Same fields as :meth:`observe` with no host conversion, so jitted
        programs (e.g. the sweep's frozen-tail retirement gather,
        parallel/sweep.py) can slice observations ON DEVICE and ship only
        the rows they need across the host boundary.
        """
        out = {
            "now_us": state.now,
            "active": state.active,
            "steps": state.steps,
            "delivered": state.delivered,
            "dropped": state.dropped,
            "overflow": state.overflow,
            "qmax": state.qmax,
            "bug": state.bug,
            "bug_time_us": state.bug_time,
            # The carried lane, not a recomputed reduction — the depth
            # invariant (carried == recomputed) is a tier-1 test.
            "queue_depth": state.qdepth,
        }
        if self.cfg.metrics and state.metrics is not None:
            # One ``m_<field>`` entry per MetricsBlock counter: the sweep's
            # retirement machinery then attributes metrics per seed exactly
            # like any other observation (slot→seed index, device-side tail
            # gathers), and SweepResult.metrics reassembles the frames.
            out.update({f"m_{name}": val for name, val
                        in state.metrics._asdict().items()})
        if self.cfg.blackbox and state.blackbox is not None:
            # One ``bb_<field>`` entry per ring lane: the flight
            # recorder then rides every existing observation surface —
            # retirement tail gathers, per-seed scatters, checkpoint
            # aux arrays, fleet merges — with zero recorder-specific
            # plumbing (obs/blackbox.py decodes the rows back).
            out.update({f"bb_{name}": val for name, val
                        in state.blackbox._asdict().items()})
        out.update(self.actor.observe(self.cfg, state.astate))
        return out

    def observe(self, state: WorldState) -> Dict[str, np.ndarray]:
        """Pull engine metrics (plus the actor's) to host as numpy arrays.

        One explicit ``device_get`` of the whole dict (not per-field
        ``np.asarray``), so the pull stays a single, *explicit* transfer
        under ``jax.transfer_guard`` — the sweep's sync-discipline test
        counts every device→host crossing.
        """
        out = jax.device_get(self.observe_device(state))
        return {k: np.asarray(v) for k, v in out.items()}
