"""Primary-backup replication actor for the batched device engine.

The second workload family (alongside :mod:`madsim_tpu.engine.raft_actor`),
proving the DeviceEngine actor protocol generalizes: a view-based
primary-backup log (VR/chain-replication style) — the primary of view v is
node ``v % n``; clients write to the primary, the primary replicates to
every backup and commits an entry once EVERY replica has acked it (static
membership, chain-replication-strength durability). There is deliberately
no retransmission, log repair, or reconfiguration: a replicate lost to a
dead backup or the network permanently caps the commit index (safety is
the subject under test, not liveness — madsim worlds are finite). Backups
that miss the primary's heartbeat long enough start a view change; the
primary of a view is fixed by construction (``v % n``), so single-primary
holds definitionally and is not separately checked.

On-device invariant (the bug flag): **durability of committed writes** —
every entry the old primary reported committed must exist in the new
primary's log after a failover. The
``buggy_commit_early`` switch makes the primary commit after the FIRST ack
instead of all acks; a fault schedule that kills the primary mid-window
then loses a committed write at failover, and seed sweeps catch it at the
view change. All state is fixed-shape int32 arrays via the one-hot lane
helpers (no gather/scatter), exactly like the Raft actor.
"""
from __future__ import annotations

import dataclasses
from typing import List, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .actor_util import bcast_payload, make_outbox, pad_payload
from .core import EngineConfig, Outbox
from .lanes import narrow, sel, sel2, upd, upd2, widen
from .queue import Event, FLAG_TIMER, INF_TIME
from .rng import DevRng, uniform_u32

# Event kinds.
K_WRITE = 0        # scheduled client write [cmd] (delivered to all; primary acts)
K_REPLICATE = 1    # primary -> backup [view, idx, cmd]
K_ACK = 2          # backup -> primary [view, idx, backup]
K_COMMIT = 3       # primary -> backup [view, commit_idx]
K_HEARTBEAT = 4    # timer on primary [view]
K_WATCHDOG = 5     # timer on backup [view] — primary silence detector
NUM_KINDS = 6


@dataclasses.dataclass(frozen=True)
class PBDeviceConfig:
    """Static primary-backup parameters."""

    n: int = 3
    log_cap: int = 16
    heartbeat_us: int = 50_000
    # A backup that hears nothing from the primary for this long starts the
    # next view (randomized per node to avoid symmetric races).
    watchdog_min_us: int = 200_000
    watchdog_max_us: int = 400_000
    n_writes: int = 4
    write_start_us: int = 100_000
    write_interval_us: int = 150_000
    # Injected bug: commit after the first ack instead of all acks.
    buggy_commit_early: bool = False


class PBState(NamedTuple):
    """Lane dtypes follow ``EngineConfig.lanes`` (engine/lanes.py):
    views/indices/epochs ride the slot lane (i16 packed), log commands
    the payload lane; ack bitmasks and the wide counters stay i32.
    Reads widen, writes saturate (the raft actor's discipline)."""

    view: jnp.ndarray        # (N,) slot lane — each node's current view
    log_len: jnp.ndarray     # (N,) slot lane
    log_cmd: jnp.ndarray     # (N, L) payload lane
    commit: jnp.ndarray      # (N,) slot lane — entries each node knows
                             # committed
    acks: jnp.ndarray        # (N, L) i32 bitmask of backup acks (primary rows)
    wd_epoch: jnp.ndarray    # (N,) slot lane — invalidates stale watchdogs
    committed_cmd: jnp.ndarray   # (L,) payload lane — globally committed
                                 # prefix record
    committed_max: jnp.ndarray   # slot lane — high-water committed index
    views_changed: jnp.ndarray   # i32
    writes_done: jnp.ndarray     # i32


class PBActor:
    """Primary-backup actor implementing the DeviceEngine protocol."""

    num_kinds = NUM_KINDS
    kind_names = ["Write", "Replicate", "Ack", "Commit", "Heartbeat",
                  "Watchdog"]

    def __init__(self, pcfg: PBDeviceConfig):
        self.pcfg = pcfg

    # ------------------------------------------------------------------
    def init(self, cfg: EngineConfig, rng: DevRng
             ) -> Tuple[PBState, List[Event], DevRng]:
        p = self.pcfg
        n, L = p.n, p.log_cap
        if cfg.n_nodes != n:
            raise ValueError("EngineConfig.n_nodes must match PBDeviceConfig.n")
        if cfg.m != n + 1:
            raise ValueError("PBActor needs outbox_cap == n + 1")
        if cfg.payload_words < 4:
            raise ValueError("PBActor needs payload_words >= 4")
        lt = cfg.lanes
        s = PBState(
            view=jnp.zeros((n,), lt.slot),
            log_len=jnp.zeros((n,), lt.slot),
            log_cmd=jnp.zeros((n, L), lt.payload),
            commit=jnp.zeros((n,), lt.slot),
            acks=jnp.zeros((n, L), jnp.int32),
            wd_epoch=jnp.zeros((n,), lt.slot),
            committed_cmd=jnp.zeros((L,), lt.payload),
            committed_max=jnp.zeros((), lt.slot),
            views_changed=jnp.int32(0),
            writes_done=jnp.int32(0),
        )
        events: List[Event] = []
        # Primary of view 0 (node 0) heartbeats; backups watch.
        events.append(Event.make(
            time=p.heartbeat_us, kind=K_HEARTBEAT,
            payload_words=cfg.payload_words, flags=FLAG_TIMER,
            src=0, dst=0, payload=[0]))
        for i in range(1, n):
            delay, rng = uniform_u32(rng, p.watchdog_min_us, p.watchdog_max_us)
            events.append(Event.make(
                time=delay, kind=K_WATCHDOG, payload_words=cfg.payload_words,
                flags=FLAG_TIMER, src=i, dst=i, payload=[0, 0]))
        for w in range(p.n_writes):
            t = p.write_start_us + w * p.write_interval_us
            for i in range(n):  # broadcast; only the current primary acts
                events.append(Event.make(
                    time=t, kind=K_WRITE, payload_words=cfg.payload_words,
                    src=i, dst=i, payload=[w + 1]))
        return s, events, rng

    # ------------------------------------------------------------------
    def on_restart(self, cfg: EngineConfig, s: PBState, node, now, rng: DevRng
                   ) -> Tuple[PBState, Outbox, DevRng]:
        p = self.pcfg
        n = p.n
        me = jnp.clip(node, 0, n - 1)
        # Log and commit are persistent (disk); view is too. Volatile ack
        # bookkeeping resets; the watchdog re-arms.
        epoch2 = widen(sel(s.wd_epoch, me)) + 1
        s2 = s._replace(
            acks=upd(s.acks, me, jnp.zeros((p.log_cap,), jnp.int32)),
            wd_epoch=upd(s.wd_epoch, me, epoch2),
        )
        delay, rng = uniform_u32(rng, p.watchdog_min_us, p.watchdog_max_us)
        ob = self._outbox(
            cfg,
            msg_valid=jnp.zeros((n,), bool),
            msg_kind=jnp.zeros((n,), jnp.int32),
            msg_payload=jnp.zeros((n, cfg.payload_words), jnp.int32),
            timer_valid=jnp.asarray(True), timer_kind=jnp.int32(K_WATCHDOG),
            timer_dst=me, timer_delay=delay,
            timer_payload=self._pad(cfg, [widen(sel(s2.view, me)), epoch2]))
        return s2, ob, rng

    # ------------------------------------------------------------------
    def handle(self, cfg: EngineConfig, s: PBState, ev: Event, now, rng: DevRng
               ) -> Tuple[PBState, Outbox, DevRng, jnp.ndarray]:
        """Merged handler (same rationale as RaftActor.handle: under vmap a
        switch runs every branch for every world, so shared work — views,
        log row reads, outbox assembly, the watchdog-delay draw — is
        computed once and combined with kind-masked writes). Bit-identical
        to the former six-branch ``lax.switch`` (verified state-for-state
        over fault-schedule workloads with the bug switch on and off)."""
        p = self.pcfg
        n, L = p.n, p.log_cap
        kind = jnp.clip(ev.kind, 0, NUM_KINDS - 1)
        me = jnp.clip(ev.dst, 0, n - 1)
        pl = ev.payload
        is_w = kind == K_WRITE
        is_rep = kind == K_REPLICATE
        is_ack = kind == K_ACK
        is_cm = kind == K_COMMIT
        is_hb = kind == K_HEARTBEAT
        is_wd = kind == K_WATCHDOG

        # Narrow-lane reads widen to i32 (the wide-in-flight discipline,
        # engine/lanes.py); writes saturate back through upd/upd2.
        view_me = widen(sel(s.view, me))
        llen = widen(sel(s.log_len, me))
        epoch_me = widen(sel(s.wd_epoch, me))
        commit_me = widen(sel(s.commit, me))
        arange_n = jnp.arange(n)
        i_am_primary = me == self._primary_of(view_me)

        # One watchdog-delay draw serves replicate and watchdog (same
        # range, same counter); the counter advances only for those kinds.
        delay, rng_drawn = uniform_u32(rng, p.watchdog_min_us, p.watchdog_max_us)
        rng = rng._replace(counter=jnp.where(is_rep | is_wd,
                                             rng_drawn.counter, rng.counter))

        # -- write (primary appends) --
        accept = is_w & i_am_primary & (llen < L)
        pos_w = jnp.clip(llen, 0, L - 1)
        llen_w = llen + accept.astype(jnp.int32)

        # -- replicate (backup appends in order, adopts view) --
        v_rep, idx_rep, cmd_rep = pl[0], pl[1], pl[2]
        current = is_rep & (v_rep >= view_me)
        view_rep = jnp.maximum(view_me, jnp.where(is_rep, v_rep, view_me))
        in_order = current & (idx_rep == llen + 1) & (idx_rep <= L)
        pos_r = jnp.clip(idx_rep - 1, 0, L - 1)

        # -- ack (primary counts; commit on quorum) --
        backup = jnp.clip(pl[2], 0, n - 1)
        live_ack = is_ack & (pl[0] == view_me) & i_am_primary & \
            (pl[1] >= 1) & (pl[1] <= L)
        pos_a = jnp.clip(pl[1] - 1, 0, L - 1)
        acks2 = sel2(s.acks, me, pos_a) | jnp.where(live_ack, 1 << backup, 0)
        if p.buggy_commit_early:
            # THE BUG: one ack is "enough". A fault schedule that kills
            # the primary before the rest replicate loses the entry.
            quorum = jax.lax.population_count(acks2) >= 2
        else:
            quorum = acks2 == jnp.int32((1 << n) - 1)
        committed = live_ack & quorum & (pl[1] > commit_me)
        commit_a = jnp.where(committed, pl[1], commit_me)
        krange = jnp.arange(L)
        fill = committed & (krange >= commit_me) & (krange < pl[1])

        # -- commit message (backup adopts commit index) --
        cm_current = is_cm & (pl[0] >= view_me)
        commit_c = jnp.where(cm_current,
                             jnp.maximum(commit_me, jnp.minimum(pl[1], llen)),
                             commit_me)

        # -- heartbeat --
        live_hb = is_hb & (pl[0] == view_me) & i_am_primary

        # -- watchdog (view change) --
        epoch_ok = is_wd & (pl[1] == epoch_me)
        fire = epoch_ok & ~(pl[0] < view_me) & ~i_am_primary
        cand = view_me + ((me - self._primary_of(view_me)) % n + n) % n
        view_wd = jnp.where(fire, jnp.maximum(cand, view_me + 1), view_me)
        became_primary = fire & (me == self._primary_of(view_wd))

        # -- combined single-position log/acks writes --
        pos = jnp.where(is_rep, pos_r, jnp.where(is_ack, pos_a, pos_w))
        cmd_at = widen(sel2(s.log_cmd, me, pos))
        ack_at = sel2(s.acks, me, pos)
        log_cmd_new = jnp.where(in_order, cmd_rep,
                                jnp.where(accept, pl[0], cmd_at))
        acks_new = jnp.where(is_ack, acks2,
                             jnp.where(accept, 1 << me, ack_at))

        view2 = jnp.where(is_rep, view_rep, jnp.where(is_wd, view_wd, view_me))
        epoch2 = epoch_me + current.astype(jnp.int32) + fire.astype(jnp.int32)

        s2 = s._replace(
            view=upd(s.view, me, view2),
            log_cmd=upd2(s.log_cmd, me, pos, log_cmd_new),
            log_len=upd(s.log_len, me, jnp.where(
                in_order, idx_rep, jnp.where(is_w, llen_w, llen))),
            acks=upd2(s.acks, me, pos, acks_new),
            commit=upd(s.commit, me, jnp.where(
                is_ack, commit_a, jnp.where(is_cm, commit_c, commit_me))),
            wd_epoch=upd(s.wd_epoch, me, jnp.where(
                is_rep | is_wd, epoch2, epoch_me)),
            # Same-dtype payload-lane select (no widen needed); the
            # high-water index is a direct _replace, so it narrows
            # explicitly rather than through upd.
            committed_cmd=jnp.where(fill, sel(s.log_cmd, me), s.committed_cmd),
            committed_max=narrow(
                jnp.maximum(widen(s.committed_max),
                            jnp.where(committed, pl[1], 0)),
                s.committed_max.dtype),
            views_changed=s.views_changed + fire.astype(jnp.int32),
            writes_done=s.writes_done + accept.astype(jnp.int32),
        )

        # -- combined outbox --
        primary_rep = self._primary_of(view_rep)
        msg_valid = jnp.where(
            is_rep, in_order & (arange_n == primary_rep),
            jnp.where(is_ack, committed & (arange_n != me),
                      (accept | live_hb | became_primary) & (arange_n != me)))
        msg_kind = jnp.full((n,), jnp.where(
            is_rep, K_ACK, jnp.where(is_ack, K_COMMIT, K_REPLICATE)),
            jnp.int32)
        w0 = jnp.where(is_rep | is_wd, view2, view_me)
        w1 = jnp.where(is_w, llen_w,
                       jnp.where(is_rep, idx_rep,
                                 jnp.where(is_ack, commit_a, 0)))
        w2 = jnp.where(is_w, pl[0], jnp.where(is_rep, me, 0))
        msg_payload = self._bcast(cfg, [w0, w1, w2, 0])

        timer_valid = current | live_hb | epoch_ok | fire
        hb_timer = live_hb | became_primary
        ob = self._outbox(
            cfg,
            msg_valid=msg_valid, msg_kind=msg_kind, msg_payload=msg_payload,
            timer_valid=timer_valid,
            timer_kind=jnp.where(hb_timer, K_HEARTBEAT,
                                 K_WATCHDOG).astype(jnp.int32),
            timer_dst=me,
            timer_delay=jnp.where(hb_timer, jnp.int32(p.heartbeat_us),
                                  delay).astype(jnp.int32),
            timer_payload=self._pad(cfg, [
                jnp.where(is_rep | is_wd, view2, view_me),
                jnp.where(is_rep | is_wd, epoch2, 0)]))
        return s2, ob, rng, jnp.asarray(False)

    # ------------------------------------------------------------------
    def invariant(self, cfg: EngineConfig, s: PBState) -> jnp.ndarray:
        """Durability: the current primary's log must contain every entry
        ever reported committed, verbatim."""
        p = self.pcfg
        n, L = p.n, p.log_cap
        primary = widen(jnp.max(s.view)) % n
        k = jnp.arange(L)
        mask = k < widen(s.committed_max)
        plog = sel(s.log_cmd, primary)                    # (L,) payload lane
        plen = widen(sel(s.log_len, primary))
        missing = jnp.any(mask & ((k >= plen) | (plog != s.committed_cmd)))
        return missing

    # ------------------------------------------------------------------
    def observe(self, cfg: EngineConfig, s: PBState) -> dict:
        # Called on BATCHED state (leading world axis): node-axis
        # reductions must keep the world axis (axis=-1), unlike
        # invariant(), which runs per-world under vmap.
        return {
            "max_view": jnp.max(s.view, axis=-1),
            "views_changed": s.views_changed,
            "committed_max": s.committed_max,
            "writes_done": s.writes_done,
            "min_commit": jnp.min(s.commit, axis=-1),
        }

    # ==================================================================
    # Helpers (same layout discipline as the Raft actor)
    # ==================================================================
    def _primary_of(self, view):
        return view % jnp.int32(self.pcfg.n)

    # ==================================================================
    # Helpers (same layout discipline as the Raft actor)
    # ==================================================================
    def _bcast(self, cfg, words):
        return bcast_payload(cfg, self.pcfg.n, words)

    def _pad(self, cfg, words) -> jnp.ndarray:
        return pad_payload(cfg, words)

    def _outbox(self, cfg, *args, **kwargs) -> Outbox:
        return make_outbox(cfg, self.pcfg.n, *args, **kwargs)
