"""Primary-backup replication actor for the batched device engine.

The second workload family (alongside :mod:`madsim_tpu.engine.raft_actor`),
proving the DeviceEngine actor protocol generalizes: a view-based
primary-backup log (VR/chain-replication style) — the primary of view v is
node ``v % n``; clients write to the primary, the primary replicates to
every backup and commits an entry once EVERY replica has acked it (static
membership, chain-replication-strength durability). There is deliberately
no retransmission, log repair, or reconfiguration: a replicate lost to a
dead backup or the network permanently caps the commit index (safety is
the subject under test, not liveness — madsim worlds are finite). Backups
that miss the primary's heartbeat long enough start a view change; the
primary of a view is fixed by construction (``v % n``), so single-primary
holds definitionally and is not separately checked.

On-device invariant (the bug flag): **durability of committed writes** —
every entry the old primary reported committed must exist in the new
primary's log after a failover. The
``buggy_commit_early`` switch makes the primary commit after the FIRST ack
instead of all acks; a fault schedule that kills the primary mid-window
then loses a committed write at failover, and seed sweeps catch it at the
view change. All state is fixed-shape int32 arrays via the one-hot lane
helpers (no gather/scatter), exactly like the Raft actor.
"""
from __future__ import annotations

import dataclasses
from typing import List, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .actor_util import bcast_payload, make_outbox, pad_payload
from .core import EngineConfig, Outbox
from .lanes import sel, sel2, upd, upd2
from .queue import Event, FLAG_TIMER, INF_TIME
from .rng import DevRng, uniform_u32

# Event kinds.
K_WRITE = 0        # scheduled client write [cmd] (delivered to all; primary acts)
K_REPLICATE = 1    # primary -> backup [view, idx, cmd]
K_ACK = 2          # backup -> primary [view, idx, backup]
K_COMMIT = 3       # primary -> backup [view, commit_idx]
K_HEARTBEAT = 4    # timer on primary [view]
K_WATCHDOG = 5     # timer on backup [view] — primary silence detector
NUM_KINDS = 6


@dataclasses.dataclass(frozen=True)
class PBDeviceConfig:
    """Static primary-backup parameters."""

    n: int = 3
    log_cap: int = 16
    heartbeat_us: int = 50_000
    # A backup that hears nothing from the primary for this long starts the
    # next view (randomized per node to avoid symmetric races).
    watchdog_min_us: int = 200_000
    watchdog_max_us: int = 400_000
    n_writes: int = 4
    write_start_us: int = 100_000
    write_interval_us: int = 150_000
    # Injected bug: commit after the first ack instead of all acks.
    buggy_commit_early: bool = False


class PBState(NamedTuple):
    view: jnp.ndarray        # (N,) i32 — each node's current view
    log_len: jnp.ndarray     # (N,) i32
    log_cmd: jnp.ndarray     # (N, L) i32
    commit: jnp.ndarray      # (N,) i32 — entries each node knows committed
    acks: jnp.ndarray        # (N, L) i32 bitmask of backup acks (primary rows)
    wd_epoch: jnp.ndarray    # (N,) i32 — invalidates stale watchdog timers
    committed_cmd: jnp.ndarray   # (L,) i32 — globally committed prefix record
    committed_max: jnp.ndarray   # i32 — high-water committed index
    views_changed: jnp.ndarray   # i32
    writes_done: jnp.ndarray     # i32


class PBActor:
    """Primary-backup actor implementing the DeviceEngine protocol."""

    num_kinds = NUM_KINDS
    kind_names = ["Write", "Replicate", "Ack", "Commit", "Heartbeat",
                  "Watchdog"]

    def __init__(self, pcfg: PBDeviceConfig):
        self.pcfg = pcfg

    # ------------------------------------------------------------------
    def init(self, cfg: EngineConfig, rng: DevRng
             ) -> Tuple[PBState, List[Event], DevRng]:
        p = self.pcfg
        n, L = p.n, p.log_cap
        if cfg.n_nodes != n:
            raise ValueError("EngineConfig.n_nodes must match PBDeviceConfig.n")
        if cfg.m != n + 1:
            raise ValueError("PBActor needs outbox_cap == n + 1")
        if cfg.payload_words < 4:
            raise ValueError("PBActor needs payload_words >= 4")
        s = PBState(
            view=jnp.zeros((n,), jnp.int32),
            log_len=jnp.zeros((n,), jnp.int32),
            log_cmd=jnp.zeros((n, L), jnp.int32),
            commit=jnp.zeros((n,), jnp.int32),
            acks=jnp.zeros((n, L), jnp.int32),
            wd_epoch=jnp.zeros((n,), jnp.int32),
            committed_cmd=jnp.zeros((L,), jnp.int32),
            committed_max=jnp.int32(0),
            views_changed=jnp.int32(0),
            writes_done=jnp.int32(0),
        )
        events: List[Event] = []
        # Primary of view 0 (node 0) heartbeats; backups watch.
        events.append(Event.make(
            time=p.heartbeat_us, kind=K_HEARTBEAT,
            payload_words=cfg.payload_words, flags=FLAG_TIMER,
            src=0, dst=0, payload=[0]))
        for i in range(1, n):
            delay, rng = uniform_u32(rng, p.watchdog_min_us, p.watchdog_max_us)
            events.append(Event.make(
                time=delay, kind=K_WATCHDOG, payload_words=cfg.payload_words,
                flags=FLAG_TIMER, src=i, dst=i, payload=[0, 0]))
        for w in range(p.n_writes):
            t = p.write_start_us + w * p.write_interval_us
            for i in range(n):  # broadcast; only the current primary acts
                events.append(Event.make(
                    time=t, kind=K_WRITE, payload_words=cfg.payload_words,
                    src=i, dst=i, payload=[w + 1]))
        return s, events, rng

    # ------------------------------------------------------------------
    def on_restart(self, cfg: EngineConfig, s: PBState, node, now, rng: DevRng
                   ) -> Tuple[PBState, Outbox, DevRng]:
        p = self.pcfg
        n = p.n
        me = jnp.clip(node, 0, n - 1)
        # Log and commit are persistent (disk); view is too. Volatile ack
        # bookkeeping resets; the watchdog re-arms.
        epoch2 = sel(s.wd_epoch, me) + 1
        s2 = s._replace(
            acks=upd(s.acks, me, jnp.zeros((p.log_cap,), jnp.int32)),
            wd_epoch=upd(s.wd_epoch, me, epoch2),
        )
        delay, rng = uniform_u32(rng, p.watchdog_min_us, p.watchdog_max_us)
        ob = self._outbox(
            cfg,
            msg_valid=jnp.zeros((n,), bool),
            msg_kind=jnp.zeros((n,), jnp.int32),
            msg_payload=jnp.zeros((n, cfg.payload_words), jnp.int32),
            timer_valid=jnp.asarray(True), timer_kind=jnp.int32(K_WATCHDOG),
            timer_dst=me, timer_delay=delay,
            timer_payload=self._pad(cfg, [sel(s2.view, me), epoch2]))
        return s2, ob, rng

    # ------------------------------------------------------------------
    def handle(self, cfg: EngineConfig, s: PBState, ev: Event, now, rng: DevRng
               ) -> Tuple[PBState, Outbox, DevRng, jnp.ndarray]:
        branches = [self._on_write, self._on_replicate, self._on_ack,
                    self._on_commit, self._on_heartbeat, self._on_watchdog]

        def mk(fn):
            return lambda a, e, t, r: fn(cfg, a, e, t, r)

        kind = jnp.clip(ev.kind, 0, NUM_KINDS - 1)
        return jax.lax.switch(kind, [mk(f) for f in branches], s, ev, now, rng)

    # ------------------------------------------------------------------
    def invariant(self, cfg: EngineConfig, s: PBState) -> jnp.ndarray:
        """Durability: the current primary's log must contain every entry
        ever reported committed, verbatim."""
        p = self.pcfg
        n, L = p.n, p.log_cap
        primary = jnp.max(s.view) % n
        k = jnp.arange(L)
        mask = k < s.committed_max
        plog = sel(s.log_cmd, primary)                    # (L,)
        plen = sel(s.log_len, primary)
        missing = jnp.any(mask & ((k >= plen) | (plog != s.committed_cmd)))
        return missing

    # ------------------------------------------------------------------
    def observe(self, cfg: EngineConfig, s: PBState) -> dict:
        # Called on BATCHED state (leading world axis): node-axis
        # reductions must keep the world axis (axis=-1), unlike
        # invariant(), which runs per-world under vmap.
        return {
            "max_view": jnp.max(s.view, axis=-1),
            "views_changed": s.views_changed,
            "committed_max": s.committed_max,
            "writes_done": s.writes_done,
            "min_commit": jnp.min(s.commit, axis=-1),
        }

    # ==================================================================
    # Handlers: (state, outbox, rng, bug)
    # ==================================================================
    def _primary_of(self, view):
        return view % jnp.int32(self.pcfg.n)

    def _on_write(self, cfg, s: PBState, ev: Event, now, rng):
        p = self.pcfg
        n, L = p.n, p.log_cap
        me = jnp.clip(ev.dst, 0, n - 1)
        cmd = ev.payload[0]
        view_me = sel(s.view, me)
        llen = sel(s.log_len, me)
        is_primary = me == self._primary_of(view_me)
        accept = is_primary & (llen < L)
        pos = jnp.clip(llen, 0, L - 1)
        llen2 = llen + accept.astype(jnp.int32)
        s2 = s._replace(
            log_cmd=upd2(s.log_cmd, me, pos, jnp.where(
                accept, cmd, sel2(s.log_cmd, me, pos))),
            log_len=upd(s.log_len, me, llen2),
            acks=upd2(s.acks, me, pos, jnp.where(
                accept, 1 << me, sel2(s.acks, me, pos))),
            writes_done=s.writes_done + accept.astype(jnp.int32),
        )
        payload = self._bcast(cfg, [view_me, llen2, cmd, 0])
        ob = self._outbox(
            cfg,
            msg_valid=accept & (jnp.arange(n) != me),
            msg_kind=jnp.full((n,), K_REPLICATE, jnp.int32),
            msg_payload=payload,
            timer_valid=jnp.asarray(False), timer_kind=jnp.int32(0),
            timer_dst=me, timer_delay=jnp.int32(0),
            timer_payload=self._pad(cfg, []))
        return s2, ob, rng, jnp.asarray(False)

    def _on_replicate(self, cfg, s: PBState, ev: Event, now, rng):
        p = self.pcfg
        n, L = p.n, p.log_cap
        me = jnp.clip(ev.dst, 0, n - 1)
        v, idx, cmd = ev.payload[0], ev.payload[1], ev.payload[2]
        view_me = sel(s.view, me)
        # Adopt newer views from the primary's traffic.
        view2 = jnp.maximum(view_me, v)
        current = v >= view_me
        # Append in order only (idx == len + 1); out-of-order is ignored
        # (the primary's retransmit-free pipeline keeps this dense).
        llen = sel(s.log_len, me)
        in_order = current & (idx == llen + 1) & (idx <= L)
        pos = jnp.clip(idx - 1, 0, L - 1)
        # Primary sign-of-life (current traffic only): reset the watchdog.
        epoch2 = sel(s.wd_epoch, me) + current.astype(jnp.int32)
        s2 = s._replace(
            view=upd(s.view, me, view2),
            log_cmd=upd2(s.log_cmd, me, pos, jnp.where(
                in_order, cmd, sel2(s.log_cmd, me, pos))),
            log_len=upd(s.log_len, me, jnp.where(in_order, idx, llen)),
            wd_epoch=upd(s.wd_epoch, me, epoch2),
        )
        payload = self._bcast(cfg, [view2, idx, me, 0])
        primary = self._primary_of(view2)
        delay, rng = uniform_u32(rng, p.watchdog_min_us, p.watchdog_max_us)
        ob = self._outbox(
            cfg,
            msg_valid=in_order & (jnp.arange(n) == primary),
            msg_kind=jnp.full((n,), K_ACK, jnp.int32),
            msg_payload=payload,
            timer_valid=current, timer_kind=jnp.int32(K_WATCHDOG),
            timer_dst=me, timer_delay=delay,
            timer_payload=self._pad(cfg, [view2, epoch2]))
        return s2, ob, rng, jnp.asarray(False)

    def _on_ack(self, cfg, s: PBState, ev: Event, now, rng):
        p = self.pcfg
        n, L = p.n, p.log_cap
        me = jnp.clip(ev.dst, 0, n - 1)
        v, idx, backup = ev.payload[0], ev.payload[1], \
            jnp.clip(ev.payload[2], 0, n - 1)
        view_me = sel(s.view, me)
        live = (v == view_me) & (me == self._primary_of(view_me)) & \
            (idx >= 1) & (idx <= L)
        pos = jnp.clip(idx - 1, 0, L - 1)
        acks2 = sel2(s.acks, me, pos) | jnp.where(live, 1 << backup, 0)
        all_mask = jnp.int32((1 << n) - 1)
        quorum = acks2 == all_mask
        if p.buggy_commit_early:
            # THE BUG: one ack is "enough". A fault schedule that kills
            # the primary before the rest replicate loses the entry.
            quorum = jax.lax.population_count(acks2) >= 2
        old_commit = sel(s.commit, me)
        committed = live & quorum & (idx > old_commit)
        commit2 = jnp.where(committed, idx, old_commit)
        # Record the global committed prefix at commit time from the
        # primary's own log — the WHOLE (old_commit, idx] range, not just
        # slot idx: acks can arrive out of order, so a commit may jump
        # several indices and every skipped slot is committed with it.
        krange = jnp.arange(L)
        fill = committed & (krange >= old_commit) & (krange < idx)
        committed_cmd2 = jnp.where(fill, sel(s.log_cmd, me), s.committed_cmd)
        s2 = s._replace(
            acks=upd2(s.acks, me, pos, acks2),
            commit=upd(s.commit, me, commit2),
            committed_cmd=committed_cmd2,
            committed_max=jnp.maximum(s.committed_max,
                                      jnp.where(committed, idx, 0)),
        )
        payload = self._bcast(cfg, [view_me, commit2, 0, 0])
        ob = self._outbox(
            cfg,
            msg_valid=committed & (jnp.arange(n) != me),
            msg_kind=jnp.full((n,), K_COMMIT, jnp.int32),
            msg_payload=payload,
            timer_valid=jnp.asarray(False), timer_kind=jnp.int32(0),
            timer_dst=me, timer_delay=jnp.int32(0),
            timer_payload=self._pad(cfg, []))
        return s2, ob, rng, jnp.asarray(False)

    def _on_commit(self, cfg, s: PBState, ev: Event, now, rng):
        p = self.pcfg
        n = p.n
        me = jnp.clip(ev.dst, 0, n - 1)
        v, cidx = ev.payload[0], ev.payload[1]
        current = v >= sel(s.view, me)
        commit2 = jnp.where(current,
                            jnp.maximum(sel(s.commit, me),
                                        jnp.minimum(cidx, sel(s.log_len, me))),
                            sel(s.commit, me))
        s2 = s._replace(commit=upd(s.commit, me, commit2))
        return s2, Outbox.empty(cfg), rng, jnp.asarray(False)

    def _on_heartbeat(self, cfg, s: PBState, ev: Event, now, rng):
        p = self.pcfg
        n = p.n
        me = jnp.clip(ev.dst, 0, n - 1)
        view_me = sel(s.view, me)
        live = (ev.payload[0] == view_me) & (me == self._primary_of(view_me))
        # Heartbeats ride the replicate channel with idx 0 (kept by backups
        # as a watchdog reset only).
        payload = self._bcast(cfg, [view_me, 0, 0, 0])
        ob = self._outbox(
            cfg,
            msg_valid=live & (jnp.arange(n) != me),
            msg_kind=jnp.full((n,), K_REPLICATE, jnp.int32),
            msg_payload=payload,
            timer_valid=live, timer_kind=jnp.int32(K_HEARTBEAT), timer_dst=me,
            timer_delay=jnp.int32(p.heartbeat_us),
            timer_payload=self._pad(cfg, [view_me]))
        return s, ob, rng, jnp.asarray(False)

    def _on_watchdog(self, cfg, s: PBState, ev: Event, now, rng):
        p = self.pcfg
        n = p.n
        me = jnp.clip(ev.dst, 0, n - 1)
        view_me = sel(s.view, me)
        # A watchdog is live only if nothing reset it since it was armed:
        # every primary sign-of-life bumps wd_epoch and arms a fresh timer,
        # so stale timers (old epoch or old view) are no-ops.
        epoch_ok = ev.payload[1] == sel(s.wd_epoch, me)
        stale = (ev.payload[0] < view_me) | ~epoch_ok
        fire = ~stale & (me != self._primary_of(view_me))
        # View change: bump until THIS node is primary of the new view
        # (deterministic successor rule — the node whose watchdog fires
        # first wins; others adopt its view from its heartbeats).
        cand = view_me + ((me - self._primary_of(view_me)) % n + n) % n
        view2 = jnp.where(fire, jnp.maximum(cand, view_me + 1), view_me)
        became_primary = fire & (me == self._primary_of(view2))
        s2 = s._replace(
            view=upd(s.view, me, view2),
            views_changed=s.views_changed + fire.astype(jnp.int32),
        )
        # New primary announces itself via heartbeat; a stale-timer holder
        # re-arms its watchdog against the current epoch.
        epoch2 = sel(s.wd_epoch, me) + fire.astype(jnp.int32)
        s2 = s2._replace(wd_epoch=upd(s2.wd_epoch, me, epoch2))
        payload = self._bcast(cfg, [view2, 0, 0, 0])
        delay, rng = uniform_u32(rng, p.watchdog_min_us, p.watchdog_max_us)
        timer_kind = jnp.where(became_primary, K_HEARTBEAT, K_WATCHDOG)
        timer_delay = jnp.where(became_primary, p.heartbeat_us, delay)
        ob = self._outbox(
            cfg,
            msg_valid=became_primary & (jnp.arange(n) != me),
            msg_kind=jnp.full((n,), K_REPLICATE, jnp.int32),
            msg_payload=payload,
            timer_valid=epoch_ok | fire,
            timer_kind=timer_kind.astype(jnp.int32), timer_dst=me,
            timer_delay=timer_delay.astype(jnp.int32),
            timer_payload=self._pad(cfg, [view2, epoch2]))
        return s2, ob, rng, jnp.asarray(False)

    # ==================================================================
    # Helpers (same layout discipline as the Raft actor)
    # ==================================================================
    def _bcast(self, cfg, words):
        return bcast_payload(cfg, self.pcfg.n, words)

    def _pad(self, cfg, words) -> jnp.ndarray:
        return pad_payload(cfg, words)

    def _outbox(self, cfg, *args, **kwargs) -> Outbox:
        return make_outbox(cfg, self.pcfg.n, *args, **kwargs)
