"""Primary-backup replication actor — the second workload family, now
compiled.

Since the actor compiler landed (docs/actorc.md), this module holds only
the config dataclass and a thin wrapper: the protocol lives as a
declarative spec in :mod:`madsim_tpu.actorc.families.pb`, lowered by
:class:`~madsim_tpu.actorc.compile.CompiledActor` to the DeviceEngine
protocol — bit-identical trajectories to the retired hand-written
implementation (this module's original test suite,
tests/test_pb_actor.py, runs unchanged). The protocol, its durability
invariant, and the restart (disk-vs-memory) annotations are documented
on the spec.

A view-based primary-backup log (VR/chain-replication style): the
primary of view v is node ``v % n``; writes commit once EVERY replica
acked. ``buggy_commit_early`` commits after the FIRST ack — a fault
schedule that kills the primary mid-window then loses a committed write
at failover, which the durability checker flags at the view change.
"""
from __future__ import annotations

import dataclasses

from ..actorc.compile import CompiledActor

# Event kinds (spec declaration order — kept for callers and tests).
K_WRITE = 0        # scheduled client write [cmd]
K_REPLICATE = 1    # primary -> backup [view, idx, cmd]
K_ACK = 2          # backup -> primary [view, idx, backup]
K_COMMIT = 3       # primary -> backup [view, commit_idx]
K_HEARTBEAT = 4    # timer on primary [view, epoch]
K_WATCHDOG = 5     # timer on backup [view, epoch]
NUM_KINDS = 6


@dataclasses.dataclass(frozen=True)
class PBDeviceConfig:
    """Static primary-backup parameters."""

    n: int = 3
    log_cap: int = 16
    heartbeat_us: int = 50_000
    # A backup that hears nothing from the primary for this long starts the
    # next view (randomized per node to avoid symmetric races).
    watchdog_min_us: int = 200_000
    watchdog_max_us: int = 400_000
    n_writes: int = 4
    write_start_us: int = 100_000
    write_interval_us: int = 150_000
    # Injected bug: commit after the first ack instead of all acks.
    buggy_commit_early: bool = False


class PBActor(CompiledActor):
    """Primary-backup replication, compiled from its actorc spec."""

    def __init__(self, pcfg: PBDeviceConfig = PBDeviceConfig()):
        from ..actorc.families.pb import pb_spec

        super().__init__(pb_spec(pcfg))
        self.pcfg = pcfg
