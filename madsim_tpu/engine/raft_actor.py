"""Pure-JAX Raft actor for the batched device engine.

The device-side MadRaft equivalent (see `madsim_tpu/models/raft.py` for the
host-engine version): leader election + single-entry-pipelined log
replication over the engine's simulated network, with on-device invariant
checking (election safety, log matching) producing the per-world *bug flag*
that BASELINE.json's time-to-first-bug metric measures. All state is
fixed-shape int32 arrays, all control flow is ``lax`` primitives, and all
node indexing goes through the one-hot helpers in engine/lanes.py (no
gather/scatter HLOs), so the whole cluster steps inside one fused XLA
program and vmaps over thousands of worlds.

Fault tolerance matches the host model: node kill drops timers via the
engine's generation counters; restart preserves persistent state
(term/voted_for/log — what ``RaftServer._persist`` writes to the simulated
disk) and resets volatile state, mirroring crash-recovery semantics.

The ``buggy_double_vote`` switch deliberately breaks the "one vote per term"
rule so seed sweeps have a real bug to find — the analog of the interleaving
bugs madsim exists to catch.
"""
from __future__ import annotations

import dataclasses
from typing import List, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .actor_util import bcast_payload, make_outbox, pad_payload
from .core import EngineConfig, Outbox
from .lanes import sel, sel2, sel_many, upd, upd2
from .queue import Event, FLAG_TIMER, INF_TIME
from .rng import DevRng, uniform_u32

FOLLOWER, CANDIDATE, LEADER = 0, 1, 2

# Words in the per-node won-terms bitset: 32*WON_WORDS distinct terms before
# the saturating top bit can alias two high terms into one.
WON_WORDS = 4

# Event kinds.
K_ELECTION = 0      # timer [epoch]
K_HEARTBEAT = 1     # timer [term]
K_REQVOTE = 2       # msg [term, candidate, last_idx, last_term]
K_VOTEREPLY = 3     # msg [term, granted, voter]
K_APPEND = 4        # msg [term, leader, prev_idx, prev_term, n, e_term, e_cmd, l_commit]
K_APPENDREPLY = 5   # msg [term, success, match_idx, follower]
K_PROPOSE = 6       # scheduled client proposal [cmd]
NUM_KINDS = 7


@dataclasses.dataclass(frozen=True)
class RaftDeviceConfig:
    """Static Raft parameters (host analog: models/raft.py RaftOptions)."""

    n: int = 3
    log_cap: int = 16
    elect_min_us: int = 150_000
    elect_max_us: int = 300_000
    heartbeat_us: int = 50_000
    # Client proposals broadcast to every node at fixed virtual times; only
    # the current leader appends. cmd of proposal i is i+1.
    n_proposals: int = 0
    propose_start_us: int = 800_000
    propose_interval_us: int = 100_000
    # Injected bug: grant votes ignoring the one-vote-per-term rule.
    buggy_double_vote: bool = False


class RaftState(NamedTuple):
    term: jnp.ndarray        # (N,) i32
    voted_for: jnp.ndarray   # (N,) i32, -1 = none
    role: jnp.ndarray        # (N,) i32
    votes: jnp.ndarray       # (N,) i32 bitmask of granted votes
    commit: jnp.ndarray      # (N,) i32
    log_len: jnp.ndarray     # (N,) i32
    log_term: jnp.ndarray    # (N, L) i32
    log_cmd: jnp.ndarray     # (N, L) i32
    next_idx: jnp.ndarray    # (N, N) i32 [leader, peer]
    match_idx: jnp.ndarray   # (N, N) i32 [leader, peer]
    elect_epoch: jnp.ndarray  # (N,) i32 — invalidates stale election timers
    first_leader_time: jnp.ndarray  # i32 µs, INF if never
    elections_won: jnp.ndarray      # i32
    # Historical election-safety record: bitset of terms each node has EVER
    # won (word w = terms 32w..32w+31; terms beyond the last word saturate
    # into its top bit, an over-approximation that can only fire after
    # WON_WORDS*32 real elections in one world). The device analog of the
    # host checker's full leaders_by_term dict (models/raft.py
    # InvariantChecker): a second win of an already-won term is flagged at
    # win time even if the first winner stepped down — or won newer terms —
    # since (a purely simultaneous check misses those).
    won_terms: jnp.ndarray          # (N, WON_WORDS) i32 bitmask


class RaftActor:
    """Actor implementing the DeviceEngine protocol for a Raft cluster."""

    num_kinds = NUM_KINDS
    # Event-kind names for DeviceEngine.trace output.
    kind_names = ["Election", "Heartbeat", "RequestVote", "VoteReply",
                  "AppendEntries", "AppendReply", "Propose"]

    def __init__(self, rcfg: RaftDeviceConfig):
        self.rcfg = rcfg

    # ------------------------------------------------------------------
    # Protocol: init
    # ------------------------------------------------------------------
    def init(self, cfg: EngineConfig, rng: DevRng
             ) -> Tuple[RaftState, List[Event], DevRng]:
        r = self.rcfg
        n, L = r.n, r.log_cap
        if cfg.n_nodes != n:
            raise ValueError("EngineConfig.n_nodes must match RaftDeviceConfig.n")
        if cfg.m != n + 1:
            raise ValueError("RaftActor needs outbox_cap == n + 1 "
                             "(n-1 peer messages + 1 timer per handler)")
        if cfg.payload_words < 8:
            raise ValueError("RaftActor needs payload_words >= 8")
        s = RaftState(
            term=jnp.zeros((n,), jnp.int32),
            voted_for=jnp.full((n,), -1, jnp.int32),
            role=jnp.zeros((n,), jnp.int32),
            votes=jnp.zeros((n,), jnp.int32),
            commit=jnp.zeros((n,), jnp.int32),
            log_len=jnp.zeros((n,), jnp.int32),
            log_term=jnp.zeros((n, L), jnp.int32),
            log_cmd=jnp.zeros((n, L), jnp.int32),
            next_idx=jnp.ones((n, n), jnp.int32),
            match_idx=jnp.zeros((n, n), jnp.int32),
            elect_epoch=jnp.zeros((n,), jnp.int32),
            first_leader_time=INF_TIME,
            elections_won=jnp.int32(0),
            won_terms=jnp.zeros((n, WON_WORDS), jnp.int32),
        )
        events: List[Event] = []
        for i in range(n):
            delay, rng = uniform_u32(rng, r.elect_min_us, r.elect_max_us)
            events.append(Event.make(
                time=delay, kind=K_ELECTION, payload_words=cfg.payload_words,
                flags=FLAG_TIMER, src=i, dst=i, payload=[0]))
        for p in range(r.n_proposals):
            t = r.propose_start_us + p * r.propose_interval_us
            for i in range(n):
                events.append(Event.make(
                    time=t, kind=K_PROPOSE, payload_words=cfg.payload_words,
                    src=i, dst=i, payload=[p + 1]))
        return s, events, rng

    # ------------------------------------------------------------------
    # Protocol: restart hook (persistent state survives; volatile resets)
    # ------------------------------------------------------------------
    def on_restart(self, cfg: EngineConfig, s: RaftState, node, now, rng: DevRng
                   ) -> Tuple[RaftState, Outbox, DevRng]:
        r = self.rcfg
        n = r.n
        me = jnp.clip(node, 0, n - 1)
        epoch2 = sel(s.elect_epoch, me) + 1
        s = s._replace(
            role=upd(s.role, me, FOLLOWER),
            votes=upd(s.votes, me, 0),
            commit=upd(s.commit, me, 0),
            next_idx=upd(s.next_idx, me, jnp.ones((n,), jnp.int32)),
            match_idx=upd(s.match_idx, me, jnp.zeros((n,), jnp.int32)),
            elect_epoch=upd(s.elect_epoch, me, epoch2),
        )
        delay, rng = uniform_u32(rng, r.elect_min_us, r.elect_max_us)
        ob = self._outbox(
            cfg,
            msg_valid=jnp.zeros((n,), bool),
            msg_kind=jnp.zeros((n,), jnp.int32),
            msg_payload=jnp.zeros((n, cfg.payload_words), jnp.int32),
            timer_valid=jnp.asarray(True), timer_kind=jnp.int32(K_ELECTION),
            timer_dst=me, timer_delay=delay,
            timer_payload=self._pad(cfg, [epoch2]),
        )
        return s, ob, rng

    # ------------------------------------------------------------------
    # Protocol: event dispatch
    # ------------------------------------------------------------------
    def handle(self, cfg: EngineConfig, s: RaftState, ev: Event, now, rng: DevRng
               ) -> Tuple[RaftState, Outbox, DevRng, jnp.ndarray]:
        branches = [
            self._on_election, self._on_heartbeat, self._on_reqvote,
            self._on_votereply, self._on_append, self._on_appendreply,
            self._on_propose,
        ]

        def mk(fn):
            return lambda a, e, t, r: fn(cfg, a, e, t, r)

        kind = jnp.clip(ev.kind, 0, NUM_KINDS - 1)
        return jax.lax.switch(kind, [mk(f) for f in branches], s, ev, now, rng)

    # ------------------------------------------------------------------
    # Protocol: invariants (the bug flag)
    # ------------------------------------------------------------------
    def invariant(self, cfg: EngineConfig, s: RaftState) -> jnp.ndarray:
        n = self.rcfg.n
        # Election safety: at most one leader per term (models/raft.py
        # InvariantChecker.on_become_leader).
        is_leader = s.role == LEADER
        same_term = s.term[:, None] == s.term[None, :]
        pair = is_leader[:, None] & is_leader[None, :] & same_term
        off_diag = ~jnp.eye(n, dtype=bool)
        two_leaders = jnp.any(pair & off_diag)
        # Log matching on committed prefixes (on_commit analog).
        L = self.rcfg.log_cap
        k = jnp.arange(L)
        lim = jnp.minimum(s.commit[:, None], s.commit[None, :])  # (N, N)
        mask = k[None, None, :] < lim[:, :, None]
        diff = (s.log_term[:, None, :] != s.log_term[None, :, :]) | \
               (s.log_cmd[:, None, :] != s.log_cmd[None, :, :])
        log_mismatch = jnp.any(mask & diff)
        return two_leaders | log_mismatch

    # ------------------------------------------------------------------
    # Protocol: observation
    # ------------------------------------------------------------------
    def observe(self, cfg: EngineConfig, s: RaftState) -> dict:
        return {
            "leader_elected": s.first_leader_time < INF_TIME,
            "first_leader_time_us": s.first_leader_time,
            "elections_won": s.elections_won,
            "max_commit": jnp.max(s.commit, axis=-1),
            "max_term": jnp.max(s.term, axis=-1),
        }

    # ==================================================================
    # Handlers. Each returns (state, outbox, rng, bug).
    # ==================================================================
    def _on_election(self, cfg, s: RaftState, ev: Event, now, rng):
        r = self.rcfg
        n = r.n
        me = jnp.clip(ev.dst, 0, n - 1)
        epoch_ok = ev.payload[0] == sel(s.elect_epoch, me)
        fire = epoch_ok & (sel(s.role, me) != LEADER)
        term_me = sel(s.term, me)
        term2 = term_me + 1
        s2 = s._replace(
            term=upd(s.term, me, jnp.where(fire, term2, term_me)),
            voted_for=upd(s.voted_for, me,
                          jnp.where(fire, me, sel(s.voted_for, me))),
            role=upd(s.role, me, jnp.where(fire, CANDIDATE, sel(s.role, me))),
            votes=upd(s.votes, me, jnp.where(fire, 1 << me, sel(s.votes, me))),
        )
        last_idx = sel(s.log_len, me)
        last_term = self._log_term_at(s, me, last_idx)
        payload = self._bcast_payload(cfg, [term2, me, last_idx, last_term])
        peers = jnp.arange(n) != me
        delay, rng = uniform_u32(rng, r.elect_min_us, r.elect_max_us)
        ob = self._outbox(
            cfg,
            msg_valid=fire & peers,
            msg_kind=jnp.full((n,), K_REQVOTE, jnp.int32),
            msg_payload=payload,
            timer_valid=epoch_ok,  # keep exactly one live election timer
            timer_kind=jnp.int32(K_ELECTION), timer_dst=me, timer_delay=delay,
            timer_payload=self._pad(cfg, [sel(s.elect_epoch, me)]),
        )
        return s2, ob, rng, jnp.asarray(False)

    def _on_heartbeat(self, cfg, s: RaftState, ev: Event, now, rng):
        r = self.rcfg
        n = r.n
        me = jnp.clip(ev.dst, 0, n - 1)
        live = (sel(s.role, me) == LEADER) & (sel(s.term, me) == ev.payload[0])
        msg_valid, msg_payload = self._append_msgs(cfg, s, me)
        ob = self._outbox(
            cfg,
            msg_valid=live & msg_valid,
            msg_kind=jnp.full((n,), K_APPEND, jnp.int32),
            msg_payload=msg_payload,
            timer_valid=live, timer_kind=jnp.int32(K_HEARTBEAT), timer_dst=me,
            timer_delay=jnp.int32(r.heartbeat_us),
            timer_payload=self._pad(cfg, [ev.payload[0]]),
        )
        return s, ob, rng, jnp.asarray(False)

    def _on_reqvote(self, cfg, s: RaftState, ev: Event, now, rng):
        r = self.rcfg
        n = r.n
        me = jnp.clip(ev.dst, 0, n - 1)
        t, cand = ev.payload[0], jnp.clip(ev.payload[1], 0, n - 1)
        last_idx, last_term = ev.payload[2], ev.payload[3]
        s = self._maybe_step_down(s, me, t)
        term_me = sel(s.term, me)
        voted_me = sel(s.voted_for, me)
        reject = t < term_me
        my_last = sel(s.log_len, me)
        my_last_term = self._log_term_at(s, me, my_last)
        up_to_date = (last_term > my_last_term) | \
                     ((last_term == my_last_term) & (last_idx >= my_last))
        if r.buggy_double_vote:
            can_vote = jnp.asarray(True)
        else:
            can_vote = (voted_me == -1) | (voted_me == cand)
        grant = ~reject & up_to_date & can_vote
        epoch2 = sel(s.elect_epoch, me) + 1
        s2 = s._replace(
            voted_for=upd(s.voted_for, me, jnp.where(grant, cand, voted_me)),
            elect_epoch=upd(s.elect_epoch, me,
                            jnp.where(grant, epoch2, sel(s.elect_epoch, me))),
        )
        payload = self._bcast_payload(cfg, [term_me, grant.astype(jnp.int32), me, 0])
        delay, rng = uniform_u32(rng, r.elect_min_us, r.elect_max_us)
        ob = self._outbox(
            cfg,
            msg_valid=jnp.arange(n) == cand,
            msg_kind=jnp.full((n,), K_VOTEREPLY, jnp.int32),
            msg_payload=payload,
            timer_valid=grant,  # granting resets the election timer
            timer_kind=jnp.int32(K_ELECTION), timer_dst=me, timer_delay=delay,
            timer_payload=self._pad(cfg, [epoch2]),
        )
        return s2, ob, rng, jnp.asarray(False)

    def _on_votereply(self, cfg, s: RaftState, ev: Event, now, rng):
        r = self.rcfg
        n = r.n
        me = jnp.clip(ev.dst, 0, n - 1)
        t, granted, voter = ev.payload[0], ev.payload[1], jnp.clip(ev.payload[2], 0, n - 1)
        s = self._maybe_step_down(s, me, t)
        term_me = sel(s.term, me)
        counted = (granted != 0) & (sel(s.role, me) == CANDIDATE) & (t == term_me)
        votes2 = jnp.where(counted, sel(s.votes, me) | (1 << voter),
                           sel(s.votes, me))
        win = counted & (jax.lax.population_count(votes2) > n // 2)
        # Historical election safety, checked at win time (the host
        # checker's on_become_leader semantics): another node already won
        # this same term ⇒ violation, even if it stepped down — or won
        # newer terms — since. won_terms is the full per-term bitset, so
        # no later win can erase the record.
        bit_index = jnp.clip(term_me, 0, 32 * WON_WORDS - 1)
        word = bit_index // 32
        term_mask = jnp.where(jnp.arange(WON_WORDS) == word,
                              jnp.int32(1) << (bit_index % 32),
                              jnp.int32(0))                       # (W,)
        node_won_term = jnp.any((s.won_terms & term_mask[None, :]) != 0,
                                axis=1)                           # (N,)
        other_won_same = jnp.any((jnp.arange(n) != me) & node_won_term)
        hist_bug = win & other_won_same
        my_won = sel(s.won_terms, me)                             # (W,)
        llen = sel(s.log_len, me)
        s2 = s._replace(
            votes=upd(s.votes, me, votes2),
            won_terms=upd(s.won_terms, me,
                          jnp.where(win, my_won | term_mask, my_won)),
            role=upd(s.role, me, jnp.where(win, LEADER, sel(s.role, me))),
            next_idx=upd(s.next_idx, me, jnp.where(
                win, jnp.full((n,), 1, jnp.int32) + llen, sel(s.next_idx, me))),
            match_idx=upd(s.match_idx, me, jnp.where(
                win,
                jnp.where(jnp.arange(n) == me, llen, 0),
                sel(s.match_idx, me))),
            first_leader_time=jnp.where(
                win, jnp.minimum(s.first_leader_time, jnp.asarray(now, jnp.int32)),
                s.first_leader_time),
            elections_won=s.elections_won + win.astype(jnp.int32),
        )
        msg_valid, msg_payload = self._append_msgs(cfg, s2, me)
        ob = self._outbox(
            cfg,
            msg_valid=win & msg_valid,
            msg_kind=jnp.full((n,), K_APPEND, jnp.int32),
            msg_payload=msg_payload,
            timer_valid=win, timer_kind=jnp.int32(K_HEARTBEAT), timer_dst=me,
            timer_delay=jnp.int32(r.heartbeat_us),
            timer_payload=self._pad(cfg, [sel(s2.term, me)]),
        )
        return s2, ob, rng, hist_bug

    def _on_append(self, cfg, s: RaftState, ev: Event, now, rng):
        r = self.rcfg
        n, L = r.n, r.log_cap
        me = jnp.clip(ev.dst, 0, n - 1)
        t, leader = ev.payload[0], jnp.clip(ev.payload[1], 0, n - 1)
        prev_idx, prev_term = ev.payload[2], ev.payload[3]
        n_ent, e_term, e_cmd, l_commit = (ev.payload[4], ev.payload[5],
                                          ev.payload[6], ev.payload[7])
        s = self._maybe_step_down(s, me, t, follower_on_equal=True)
        term_me = sel(s.term, me)
        llen_me = sel(s.log_len, me)
        log_term_row = sel(s.log_term, me)   # (L,)
        log_cmd_row = sel(s.log_cmd, me)     # (L,)
        reject = t < term_me
        prev_ok = (prev_idx <= llen_me) & \
                  (self._row_term_at(log_term_row, prev_idx) == prev_term)
        success = ~reject & prev_ok
        idx = prev_idx + 1
        has_room = idx <= L
        write = success & (n_ent > 0) & has_room
        pos = jnp.clip(idx - 1, 0, L - 1)
        same = (idx <= llen_me) & (sel(log_term_row, pos) == e_term) & \
               (sel(log_cmd_row, pos) == e_cmd)
        new_len = jnp.where(write, jnp.where(same, llen_me, idx), llen_me)
        log_term2 = upd2(s.log_term, me, pos,
                         jnp.where(write, e_term, sel(log_term_row, pos)))
        log_cmd2 = upd2(s.log_cmd, me, pos,
                        jnp.where(write, e_cmd, sel(log_cmd_row, pos)))
        match = jnp.where(write, idx, jnp.where(success, prev_idx, 0))
        commit2 = jnp.where(success,
                            jnp.maximum(sel(s.commit, me),
                                        jnp.minimum(l_commit, new_len)),
                            sel(s.commit, me))
        epoch2 = sel(s.elect_epoch, me) + 1
        s2 = s._replace(
            log_term=log_term2, log_cmd=log_cmd2,
            log_len=upd(s.log_len, me, new_len),
            commit=upd(s.commit, me, commit2),
            elect_epoch=upd(s.elect_epoch, me,
                            jnp.where(reject, sel(s.elect_epoch, me), epoch2)),
        )
        payload = self._bcast_payload(
            cfg, [term_me, success.astype(jnp.int32), match, me])
        delay, rng = uniform_u32(rng, r.elect_min_us, r.elect_max_us)
        ob = self._outbox(
            cfg,
            msg_valid=jnp.arange(n) == leader,
            msg_kind=jnp.full((n,), K_APPENDREPLY, jnp.int32),
            msg_payload=payload,
            timer_valid=~reject,  # a valid AppendEntries is a heartbeat
            timer_kind=jnp.int32(K_ELECTION), timer_dst=me, timer_delay=delay,
            timer_payload=self._pad(cfg, [epoch2]),
        )
        return s2, ob, rng, jnp.asarray(False)

    def _on_appendreply(self, cfg, s: RaftState, ev: Event, now, rng):
        r = self.rcfg
        n, L = r.n, r.log_cap
        me = jnp.clip(ev.dst, 0, n - 1)
        t, success = ev.payload[0], ev.payload[1]
        match, follower = ev.payload[2], jnp.clip(ev.payload[3], 0, n - 1)
        s = self._maybe_step_down(s, me, t)
        term_me = sel(s.term, me)
        live = (sel(s.role, me) == LEADER) & (t == term_me)
        ok = live & (success != 0)
        fail = live & (success == 0)
        cur_match = sel2(s.match_idx, me, follower)
        cur_next = sel2(s.next_idx, me, follower)
        match2 = jnp.maximum(cur_match, match)
        s2 = s._replace(
            match_idx=upd2(s.match_idx, me, follower,
                           jnp.where(ok, match2, cur_match)),
            next_idx=upd2(s.next_idx, me, follower, jnp.where(
                ok, match2 + 1,
                jnp.where(fail, jnp.maximum(1, cur_next - 1), cur_next))),
        )
        # Advance commit: the largest n with majority match and current-term
        # entry (models/raft.py _advance_commit).
        match_row = sel(s2.match_idx, me)        # (N,)
        log_term_row = sel(s2.log_term, me)      # (L,)
        llen_me = sel(s2.log_len, me)
        ns = jnp.arange(1, L + 1)
        counts = jnp.sum(match_row[:, None] >= ns[None, :], axis=0)
        okn = (ns <= llen_me) & (counts > n // 2) & (log_term_row == term_me)
        best = jnp.max(jnp.where(okn, ns, 0))
        commit_me = sel(s2.commit, me)
        commit2 = jnp.where(live, jnp.maximum(commit_me, best), commit_me)
        s3 = s2._replace(commit=upd(s2.commit, me, commit2))
        return s3, Outbox.empty(cfg), rng, jnp.asarray(False)

    def _on_propose(self, cfg, s: RaftState, ev: Event, now, rng):
        r = self.rcfg
        n, L = r.n, r.log_cap
        me = jnp.clip(ev.dst, 0, n - 1)
        cmd = ev.payload[0]
        llen_me = sel(s.log_len, me)
        accept = (sel(s.role, me) == LEADER) & (llen_me < L)
        pos = jnp.clip(llen_me, 0, L - 1)
        llen2 = llen_me + accept.astype(jnp.int32)
        s2 = s._replace(
            log_term=upd2(s.log_term, me, pos, jnp.where(
                accept, sel(s.term, me), sel2(s.log_term, me, pos))),
            log_cmd=upd2(s.log_cmd, me, pos, jnp.where(
                accept, cmd, sel2(s.log_cmd, me, pos))),
            log_len=upd(s.log_len, me, llen2),
            match_idx=upd2(s.match_idx, me, me, jnp.where(
                accept, llen2, sel2(s.match_idx, me, me))),
        )
        msg_valid, msg_payload = self._append_msgs(cfg, s2, me)
        ob = self._outbox(
            cfg,
            msg_valid=accept & msg_valid,
            msg_kind=jnp.full((n,), K_APPEND, jnp.int32),
            msg_payload=msg_payload,
            timer_valid=jnp.asarray(False), timer_kind=jnp.int32(0),
            timer_dst=me, timer_delay=jnp.int32(0),
            timer_payload=self._pad(cfg, []),
        )
        return s2, ob, rng, jnp.asarray(False)

    # ==================================================================
    # Helpers
    # ==================================================================
    def _maybe_step_down(self, s: RaftState, me, t, follower_on_equal=False):
        """Adopt a higher term (→ follower, clear vote); optionally also
        step down from CANDIDATE on an equal-term AppendEntries."""
        term_me = sel(s.term, me)
        higher = t > term_me
        demote = higher | (follower_on_equal & (t == term_me) &
                           (sel(s.role, me) == CANDIDATE))
        return s._replace(
            term=upd(s.term, me, jnp.where(higher, t, term_me)),
            voted_for=upd(s.voted_for, me,
                          jnp.where(higher, -1, sel(s.voted_for, me))),
            role=upd(s.role, me, jnp.where(demote, FOLLOWER, sel(s.role, me))),
        )

    def _log_term_at(self, s: RaftState, me, idx):
        """Term of entry ``idx`` (1-based); 0 for idx == 0."""
        return self._row_term_at(sel(s.log_term, me), idx)

    def _row_term_at(self, log_term_row, idx):
        L = self.rcfg.log_cap
        pos = jnp.clip(idx - 1, 0, L - 1)
        return jnp.where(idx <= 0, 0, sel(log_term_row, pos))

    def _append_msgs(self, cfg, s: RaftState, me):
        """Per-peer AppendEntries payloads from the leader's next_idx row."""
        r = self.rcfg
        n, L = r.n, r.log_cap
        llen_me = sel(s.log_len, me)
        log_term_row = sel(s.log_term, me)            # (L,)
        log_cmd_row = sel(s.log_cmd, me)              # (L,)
        nxt = jnp.clip(sel(s.next_idx, me), 1, L + 1)  # (N,)
        prev = nxt - 1
        prev_term = jnp.where(
            prev <= 0, 0, sel_many(log_term_row, jnp.clip(prev - 1, 0, L - 1)))
        have = nxt <= llen_me                          # entry to ship?
        pos = jnp.clip(nxt - 1, 0, L - 1)
        e_term = jnp.where(have, sel_many(log_term_row, pos), 0)
        e_cmd = jnp.where(have, sel_many(log_cmd_row, pos), 0)
        term = jnp.full((n,), sel(s.term, me), jnp.int32)
        payload = jnp.stack([
            term, jnp.full((n,), me, jnp.int32), prev, prev_term,
            have.astype(jnp.int32), e_term, e_cmd,
            jnp.full((n,), sel(s.commit, me), jnp.int32),
        ], axis=1)
        pad = jnp.zeros((n, cfg.payload_words - 8), jnp.int32)
        return jnp.arange(n) != me, jnp.concatenate([payload, pad], axis=1)

    def _bcast_payload(self, cfg, words):
        return bcast_payload(cfg, self.rcfg.n, words)

    def _pad(self, cfg, words) -> jnp.ndarray:
        return pad_payload(cfg, words)

    def _outbox(self, cfg, *args, **kwargs) -> Outbox:
        return make_outbox(cfg, self.rcfg.n, *args, **kwargs)
