"""Pure-JAX Raft actor for the batched device engine.

The device-side MadRaft equivalent (see `madsim_tpu/models/raft.py` for the
host-engine version): leader election + single-entry-pipelined log
replication over the engine's simulated network, with on-device invariant
checking (election safety, log matching) producing the per-world *bug flag*
that BASELINE.json's time-to-first-bug metric measures. All state is
fixed-shape int32 arrays, all control flow is ``lax`` primitives, and all
node-indexed *writes* go through the one-hot helpers in engine/lanes.py
(no scatter HLOs) while *reads* use tiny-source gathers
(:func:`~madsim_tpu.engine.lanes.take_small` — same values bitwise, a
fraction of the one-hot contraction's op count), so the whole cluster
steps inside one fused XLA program and vmaps over thousands of worlds.

Fault tolerance matches the host model: node kill drops timers via the
engine's generation counters; restart preserves persistent state
(term/voted_for/log — what ``RaftServer._persist`` writes to the simulated
disk) and resets volatile state, mirroring crash-recovery semantics.

The ``buggy_double_vote`` switch deliberately breaks the "one vote per term"
rule so seed sweeps have a real bug to find — the analog of the interleaving
bugs madsim exists to catch.
"""
from __future__ import annotations

import dataclasses
from typing import List, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .actor_util import bcast_payload, make_outbox, pad_payload
from .core import EngineConfig, Outbox
from .lanes import take_small, upd, upd2, widen
from .queue import Event, FLAG_TIMER, INF_TIME
from .rng import DevRng, uniform_u32

FOLLOWER, CANDIDATE, LEADER = 0, 1, 2

# Words in the per-node won-terms bitset: 32*WON_WORDS distinct terms before
# the saturating top bit can alias two high terms into one.
WON_WORDS = 4

# Event kinds.
K_ELECTION = 0      # timer [epoch]
K_HEARTBEAT = 1     # timer [term]
K_REQVOTE = 2       # msg [term, candidate, last_idx, last_term]
K_VOTEREPLY = 3     # msg [term, granted, voter]
K_APPEND = 4        # msg [term, leader, prev_idx, prev_term, n, e_term, e_cmd, l_commit]
K_APPENDREPLY = 5   # msg [term, success, match_idx, follower]
K_PROPOSE = 6       # scheduled client proposal [cmd]
NUM_KINDS = 7


@dataclasses.dataclass(frozen=True)
class RaftDeviceConfig:
    """Static Raft parameters (host analog: models/raft.py RaftOptions)."""

    n: int = 3
    log_cap: int = 16
    elect_min_us: int = 150_000
    elect_max_us: int = 300_000
    heartbeat_us: int = 50_000
    # Client proposals broadcast to every node at fixed virtual times; only
    # the current leader appends. cmd of proposal i is i+1.
    n_proposals: int = 0
    propose_start_us: int = 800_000
    propose_interval_us: int = 100_000
    # Injected bug: grant votes ignoring the one-vote-per-term rule.
    buggy_double_vote: bool = False


class RaftState(NamedTuple):
    """Lane dtypes follow ``EngineConfig.lanes`` (engine/lanes.py): the
    packed profile rides terms/indices/epochs on the i16 slot lane,
    node ids on i8, role codes on i8, and log commands on the i16
    payload lane; bitmask lanes (``votes``, ``won_terms``) and the wide
    time/counter scalars stay i32. Reads widen (lanes.widen), writes
    saturate through upd/upd2."""

    term: jnp.ndarray        # (N,) slot lane
    voted_for: jnp.ndarray   # (N,) node lane, -1 = none
    role: jnp.ndarray        # (N,) code lane
    votes: jnp.ndarray       # (N,) i32 bitmask of granted votes
    commit: jnp.ndarray      # (N,) slot lane
    log_len: jnp.ndarray     # (N,) slot lane
    log_term: jnp.ndarray    # (N, L) slot lane
    log_cmd: jnp.ndarray     # (N, L) payload lane
    next_idx: jnp.ndarray    # (N, N) slot lane [leader, peer]
    match_idx: jnp.ndarray   # (N, N) slot lane [leader, peer]
    elect_epoch: jnp.ndarray  # (N,) slot lane — invalidates stale election
                              # timers
    first_leader_time: jnp.ndarray  # i32 µs, INF if never
    elections_won: jnp.ndarray      # i32
    # Historical election-safety record: bitset of terms each node has EVER
    # won (word w = terms 32w..32w+31; terms beyond the last word saturate
    # into its top bit, an over-approximation that can only fire after
    # WON_WORDS*32 real elections in one world). The device analog of the
    # host checker's full leaders_by_term dict (models/raft.py
    # InvariantChecker): a second win of an already-won term is flagged at
    # win time even if the first winner stepped down — or won newer terms —
    # since (a purely simultaneous check misses those).
    won_terms: jnp.ndarray          # (N, WON_WORDS) i32 bitmask


class RaftActor:
    """Actor implementing the DeviceEngine protocol for a Raft cluster."""

    num_kinds = NUM_KINDS
    # Event-kind names for DeviceEngine.trace output.
    kind_names = ["Election", "Heartbeat", "RequestVote", "VoteReply",
                  "AppendEntries", "AppendReply", "Propose"]

    def __init__(self, rcfg: RaftDeviceConfig):
        self.rcfg = rcfg

    # ------------------------------------------------------------------
    # Protocol: init
    # ------------------------------------------------------------------
    def init(self, cfg: EngineConfig, rng: DevRng
             ) -> Tuple[RaftState, List[Event], DevRng]:
        r = self.rcfg
        n, L = r.n, r.log_cap
        if cfg.n_nodes != n:
            raise ValueError("EngineConfig.n_nodes must match RaftDeviceConfig.n")
        if cfg.m != n + 1:
            raise ValueError("RaftActor needs outbox_cap == n + 1 "
                             "(n-1 peer messages + 1 timer per handler)")
        if cfg.payload_words < 8:
            raise ValueError("RaftActor needs payload_words >= 8")
        lt = cfg.lanes
        s = RaftState(
            term=jnp.zeros((n,), lt.slot),
            voted_for=jnp.full((n,), -1, lt.node),
            role=jnp.zeros((n,), lt.code),
            votes=jnp.zeros((n,), jnp.int32),
            commit=jnp.zeros((n,), lt.slot),
            log_len=jnp.zeros((n,), lt.slot),
            log_term=jnp.zeros((n, L), lt.slot),
            log_cmd=jnp.zeros((n, L), lt.payload),
            next_idx=jnp.ones((n, n), lt.slot),
            match_idx=jnp.zeros((n, n), lt.slot),
            elect_epoch=jnp.zeros((n,), lt.slot),
            first_leader_time=INF_TIME,
            elections_won=jnp.int32(0),
            won_terms=jnp.zeros((n, WON_WORDS), jnp.int32),
        )
        events: List[Event] = []
        for i in range(n):
            delay, rng = uniform_u32(rng, r.elect_min_us, r.elect_max_us)
            events.append(Event.make(
                time=delay, kind=K_ELECTION, payload_words=cfg.payload_words,
                flags=FLAG_TIMER, src=i, dst=i, payload=[0]))
        for p in range(r.n_proposals):
            t = r.propose_start_us + p * r.propose_interval_us
            for i in range(n):
                events.append(Event.make(
                    time=t, kind=K_PROPOSE, payload_words=cfg.payload_words,
                    src=i, dst=i, payload=[p + 1]))
        return s, events, rng

    # ------------------------------------------------------------------
    # Protocol: restart hook (persistent state survives; volatile resets)
    # ------------------------------------------------------------------
    def on_restart(self, cfg: EngineConfig, s: RaftState, node, now, rng: DevRng
                   ) -> Tuple[RaftState, Outbox, DevRng]:
        r = self.rcfg
        n = r.n
        me = jnp.clip(node, 0, n - 1)
        epoch2 = widen(take_small(s.elect_epoch, me)) + 1
        s = s._replace(
            role=upd(s.role, me, FOLLOWER),
            votes=upd(s.votes, me, 0),
            commit=upd(s.commit, me, 0),
            next_idx=upd(s.next_idx, me, jnp.ones((n,), jnp.int32)),
            match_idx=upd(s.match_idx, me, jnp.zeros((n,), jnp.int32)),
            elect_epoch=upd(s.elect_epoch, me, epoch2),
        )
        delay, rng = uniform_u32(rng, r.elect_min_us, r.elect_max_us)
        ob = self._outbox(
            cfg,
            msg_valid=jnp.zeros((n,), bool),
            msg_kind=jnp.zeros((n,), jnp.int32),
            msg_payload=jnp.zeros((n, cfg.payload_words), jnp.int32),
            timer_valid=jnp.asarray(True), timer_kind=jnp.int32(K_ELECTION),
            timer_dst=me, timer_delay=delay,
            timer_payload=self._pad(cfg, [epoch2]),
        )
        return s, ob, rng

    # ------------------------------------------------------------------
    # Protocol: event dispatch
    # ------------------------------------------------------------------
    def handle(self, cfg: EngineConfig, s: RaftState, ev: Event, now, rng: DevRng
               ) -> Tuple[RaftState, Outbox, DevRng, jnp.ndarray]:
        """One *merged* handler instead of a ``lax.switch`` over seven.

        Under ``vmap`` a switch computes every branch for every world and
        selects — so seven structurally-similar handlers each paid for
        their own step-down logic, AppendEntries construction, outbox
        assembly, and full-state select. This merged form computes each
        shared piece once and combines per-kind values with masked writes;
        measured ~20% faster end-to-end on TPU, and bit-identical to the
        branch version (verified state-for-state over fault/loss/proposal
        workloads): every field write and the RNG counter advance are
        gated on exactly the kinds that performed them in branch form.
        All drawing kinds sample the same (elect_min, elect_max) range at
        the same counter, so one draw serves them all; the counter
        advances only when the taken kind actually drew.
        """
        r = self.rcfg
        n, L = r.n, r.log_cap
        kind = jnp.clip(ev.kind, 0, NUM_KINDS - 1)
        me = jnp.clip(ev.dst, 0, n - 1)
        p = ev.payload
        t = p[0]

        is_elec = kind == K_ELECTION
        is_hb = kind == K_HEARTBEAT
        is_rv = kind == K_REQVOTE
        is_vr = kind == K_VOTEREPLY
        is_ap = kind == K_APPEND
        is_ar = kind == K_APPENDREPLY
        is_pr = kind == K_PROPOSE

        # -- shared step-down (the four message kinds carrying a term) --
        # Narrow-lane reads widen to i32 here (lanes.widen — the
        # wide-in-flight discipline, tracelint TRC005); the upd writes
        # below saturate back into the packed lanes.
        sd = is_rv | is_vr | is_ap | is_ar
        term_pre = widen(take_small(s.term, me))
        role_pre = widen(take_small(s.role, me))
        higher = sd & (t > term_pre)
        demote = higher | (is_ap & (t == term_pre) & (role_pre == CANDIDATE))
        s = s._replace(
            term=upd(s.term, me, jnp.where(higher, t, term_pre)),
            voted_for=upd(s.voted_for, me,
                          jnp.where(higher, -1,
                                    widen(take_small(s.voted_for, me)))),
            role=upd(s.role, me, jnp.where(demote, FOLLOWER, role_pre)),
        )

        # -- shared views of the post-step-down row (widened; see above) --
        term_me = widen(take_small(s.term, me))
        role_me = widen(take_small(s.role, me))
        voted_me = widen(take_small(s.voted_for, me))
        votes_me = take_small(s.votes, me)          # bitmask lane: i32
        commit_me = widen(take_small(s.commit, me))
        llen_me = widen(take_small(s.log_len, me))
        epoch_me = widen(take_small(s.elect_epoch, me))
        log_term_row = widen(take_small(s.log_term, me))   # (L,)
        log_cmd_row = widen(take_small(s.log_cmd, me))     # (L,)
        my_last_term = self._row_term_at(log_term_row, llen_me)
        reject = t < term_me  # rv/ap stale-term test

        # One randomized-election-delay draw serves every kind that draws.
        delay, rng_drawn = uniform_u32(rng, r.elect_min_us, r.elect_max_us)
        draws = is_elec | is_rv | is_ap
        rng = rng._replace(counter=jnp.where(draws, rng_drawn.counter,
                                             rng.counter))

        # -- election fire --
        fire = is_elec & (p[0] == epoch_me) & (role_me != LEADER)
        term2 = term_me + 1

        # -- reqvote grant --
        cand = jnp.clip(p[1], 0, n - 1)
        up_to_date = (p[3] > my_last_term) | \
                     ((p[3] == my_last_term) & (p[2] >= llen_me))
        if r.buggy_double_vote:
            can_vote = jnp.asarray(True)
        else:
            can_vote = (voted_me == -1) | (voted_me == cand)
        grant = is_rv & ~reject & up_to_date & can_vote
        epoch2 = epoch_me + 1

        # -- votereply win + historical election safety --
        voter = jnp.clip(p[2], 0, n - 1)
        counted = is_vr & (p[1] != 0) & (role_me == CANDIDATE) & (t == term_me)
        votes2 = jnp.where(counted, votes_me | (1 << voter), votes_me)
        win = counted & (jax.lax.population_count(votes2) > n // 2)
        bit_index = jnp.clip(term_me, 0, 32 * WON_WORDS - 1)
        word = bit_index // 32
        term_mask = jnp.where(jnp.arange(WON_WORDS) == word,
                              jnp.int32(1) << (bit_index % 32),
                              jnp.int32(0))                       # (W,)
        node_won_term = jnp.any((s.won_terms & term_mask[None, :]) != 0,
                                axis=1)                           # (N,)
        hist_bug = win & jnp.any((jnp.arange(n) != me) & node_won_term)
        my_won = take_small(s.won_terms, me)                      # (W,)

        # -- append --
        leader = jnp.clip(p[1], 0, n - 1)
        prev_idx, prev_term = p[2], p[3]
        n_ent, e_term, e_cmd, l_commit = p[4], p[5], p[6], p[7]
        prev_ok = (prev_idx <= llen_me) & \
                  (self._row_term_at(log_term_row, prev_idx) == prev_term)
        success = is_ap & ~reject & prev_ok
        idx = prev_idx + 1
        write = success & (n_ent > 0) & (idx <= L)
        pos_ap = jnp.clip(idx - 1, 0, L - 1)
        same = (idx <= llen_me) & \
               (take_small(log_term_row, pos_ap) == e_term) & \
               (take_small(log_cmd_row, pos_ap) == e_cmd)
        new_len_ap = jnp.where(write, jnp.where(same, llen_me, idx), llen_me)
        match_ap = jnp.where(write, idx, jnp.where(success, prev_idx, 0))
        commit_ap = jnp.where(success,
                              jnp.maximum(commit_me,
                                          jnp.minimum(l_commit, new_len_ap)),
                              commit_me)

        # -- propose --
        accept = is_pr & (role_me == LEADER) & (llen_me < L)
        pos_pr = jnp.clip(llen_me, 0, L - 1)
        llen_pr = llen_me + accept.astype(jnp.int32)

        # -- appendreply --
        follower = jnp.clip(p[3], 0, n - 1)
        live_ar = is_ar & (role_me == LEADER) & (t == term_me)
        ok_ar = live_ar & (p[1] != 0)
        fail_ar = live_ar & (p[1] == 0)
        cur_match = widen(take_small(take_small(s.match_idx, me), follower))
        cur_next = widen(take_small(take_small(s.next_idx, me), follower))
        match2 = jnp.maximum(cur_match, p[2])

        # -- one combined log write (append XOR propose position) --
        pos = jnp.where(is_ap, pos_ap, pos_pr)
        lt_at = take_small(log_term_row, pos)
        lc_at = take_small(log_cmd_row, pos)
        lt_new = jnp.where(write, e_term,
                           jnp.where(accept, term_me, lt_at))
        lc_new = jnp.where(write, e_cmd, jnp.where(accept, p[0], lc_at))

        # -- per-row combines --
        arange_n = jnp.arange(n)
        oh_follower = arange_n == follower
        match_row0 = widen(take_small(s.match_idx, me))
        next_row0 = widen(take_small(s.next_idx, me))
        match_row = jnp.where(
            win, jnp.where(arange_n == me, llen_me, 0),
            jnp.where(is_ar & oh_follower,
                      jnp.where(ok_ar, match2, cur_match),
                      jnp.where(is_pr & (arange_n == me) & accept,
                                llen_pr, match_row0)))
        next_row = jnp.where(
            win, 1 + llen_me,
            jnp.where(is_ar & oh_follower,
                      jnp.where(ok_ar, match2 + 1,
                                jnp.where(fail_ar,
                                          jnp.maximum(1, cur_next - 1),
                                          cur_next)),
                      next_row0))

        # -- appendreply commit advance (uses the updated match row) --
        ns = jnp.arange(1, L + 1)
        counts = jnp.sum(match_row[:, None] >= ns[None, :], axis=0)
        okn = (ns <= llen_me) & (counts > n // 2) & (log_term_row == term_me)
        best = jnp.max(jnp.where(okn, ns, 0))
        commit_ar = jnp.where(live_ar, jnp.maximum(commit_me, best), commit_me)

        # -- final state: one masked write per field --
        s2 = s._replace(
            term=upd(s.term, me, jnp.where(fire, term2, term_me)),
            voted_for=upd(s.voted_for, me, jnp.where(
                fire, me, jnp.where(grant, cand, voted_me))),
            role=upd(s.role, me, jnp.where(
                fire, CANDIDATE, jnp.where(win, LEADER, role_me))),
            votes=upd(s.votes, me, jnp.where(
                fire, 1 << me, jnp.where(is_vr, votes2, votes_me))),
            won_terms=upd(s.won_terms, me,
                          jnp.where(win, my_won | term_mask, my_won)),
            elect_epoch=upd(s.elect_epoch, me, jnp.where(
                grant | (is_ap & ~reject), epoch2, epoch_me)),
            log_term=upd2(s.log_term, me, pos, lt_new),
            log_cmd=upd2(s.log_cmd, me, pos, lc_new),
            log_len=upd(s.log_len, me, jnp.where(
                is_ap, new_len_ap, jnp.where(is_pr, llen_pr, llen_me))),
            commit=upd(s.commit, me, jnp.where(
                is_ap, commit_ap, jnp.where(is_ar, commit_ar, commit_me))),
            match_idx=upd(s.match_idx, me, match_row),
            next_idx=upd(s.next_idx, me, next_row),
            first_leader_time=jnp.where(
                win,
                jnp.minimum(s.first_leader_time, jnp.asarray(now, jnp.int32)),
                s.first_leader_time),
            elections_won=s.elections_won + win.astype(jnp.int32),
        )

        # -- one AppendEntries construction for heartbeat/win/propose --
        # The me-row views are rebuilt from values already in hand (the
        # combined log write above) instead of gathered back out of s2:
        # a gather operand must materialize, and re-reading the freshly
        # written (N, L) log arrays was pinning two extra full log
        # buffers into the step's peak memory (docs/perf.md r7).
        oh_pos = jnp.arange(L) == pos
        log_term_row2 = jnp.where(oh_pos, lt_new, log_term_row)
        log_cmd_row2 = jnp.where(oh_pos, lc_new, log_cmd_row)
        llen_me2 = jnp.where(is_ap, new_len_ap,
                             jnp.where(is_pr, llen_pr, llen_me))
        term_me2 = jnp.where(fire, term2, term_me)
        commit_me2 = jnp.where(is_ap, commit_ap,
                               jnp.where(is_ar, commit_ar, commit_me))
        am_valid, am_payload = self._append_msgs(
            cfg, me, llen_me2, log_term_row2, log_cmd_row2, next_row,
            term_me2, commit_me2)
        live_hb = is_hb & (role_me == LEADER) & (term_me == p[0])

        # -- outbox: one combined build --
        use_am = live_hb | win | accept
        msg_valid = jnp.where(
            use_am, am_valid,
            jnp.where(fire, arange_n != me,
                      jnp.where(is_rv, arange_n == cand,
                                jnp.where(is_ap, arange_n == leader,
                                          jnp.zeros((n,), bool)))))
        msg_kind = jnp.full((n,), jnp.where(
            is_elec, K_REQVOTE,
            jnp.where(is_rv, K_VOTEREPLY,
                      jnp.where(is_ap, K_APPENDREPLY, K_APPEND))), jnp.int32)
        w0 = jnp.where(is_elec, term2, term_me)
        w1 = jnp.where(is_elec, me,
                       jnp.where(is_rv, grant.astype(jnp.int32),
                                 success.astype(jnp.int32)))
        w2 = jnp.where(is_elec, llen_me,
                       jnp.where(is_rv, me, match_ap))
        w3 = jnp.where(is_elec, my_last_term,
                       jnp.where(is_rv, 0, me))
        small = self._bcast_payload(cfg, [w0, w1, w2, w3])
        msg_payload = jnp.where(use_am, am_payload, small)

        timer_valid = (is_elec & (p[0] == epoch_me)) | live_hb | grant | win \
            | (is_ap & ~reject)
        hb_timer = is_hb | is_vr
        timer_kind = jnp.where(hb_timer, K_HEARTBEAT, K_ELECTION) \
            .astype(jnp.int32)
        timer_delay = jnp.where(hb_timer, jnp.int32(r.heartbeat_us), delay)
        tp = jnp.where(is_elec, epoch_me,
                       jnp.where(is_rv | is_ap, epoch2,
                                 jnp.where(is_hb, p[0], term_me)))
        ob = self._outbox(
            cfg,
            msg_valid=msg_valid, msg_kind=msg_kind, msg_payload=msg_payload,
            timer_valid=timer_valid, timer_kind=timer_kind, timer_dst=me,
            timer_delay=timer_delay, timer_payload=self._pad(cfg, [tp]),
        )
        return s2, ob, rng, hist_bug

    # ------------------------------------------------------------------
    # Protocol: invariants (the bug flag)
    # ------------------------------------------------------------------
    def invariant(self, cfg: EngineConfig, s: RaftState) -> jnp.ndarray:
        # Election safety is enforced at win time by the won_terms bitset
        # check in handle() (the host checker's on_become_leader
        # semantics): a second win of any term raises the bug flag on the
        # very step it happens, which strictly subsumes a per-step
        # two-current-leaders scan — two live leaders in term T requires
        # two wins of T, and roles only become LEADER via a win. Dropping
        # the pairwise scan here saves O(N^2) per step with identical bug
        # flags and timing (verified bitwise against the scanning version).
        # Log matching on committed prefixes (on_commit analog). The
        # check is symmetric and trivially true on the diagonal, so it
        # runs over the N(N-1)/2 ordered pairs (a static unroll) instead
        # of the full (N, N, L) broadcast — same bug flag, under half the
        # per-step lanes. This runs on EVERY step (it is the bug flag),
        # so its op count is hot-loop cost (docs/perf.md r7).
        n = self.rcfg.n
        k = jnp.arange(self.rcfg.log_cap)
        bad = jnp.asarray(False)
        for i in range(n):
            for j in range(i + 1, n):
                # Same-dtype compares stay narrow; only the arange
                # comparison needs the widened commit bound.
                lim = widen(jnp.minimum(s.commit[i], s.commit[j]))
                diff = (s.log_term[i] != s.log_term[j]) | \
                       (s.log_cmd[i] != s.log_cmd[j])
                bad = bad | jnp.any((k < lim) & diff)
        return bad

    # ------------------------------------------------------------------
    # Protocol: observation
    # ------------------------------------------------------------------
    def observe(self, cfg: EngineConfig, s: RaftState) -> dict:
        return {
            "leader_elected": s.first_leader_time < INF_TIME,
            "first_leader_time_us": s.first_leader_time,
            "elections_won": s.elections_won,
            "max_commit": jnp.max(s.commit, axis=-1),
            "max_term": jnp.max(s.term, axis=-1),
        }

    # ==================================================================
    # Helpers
    # ==================================================================
    def _row_term_at(self, log_term_row, idx):
        L = self.rcfg.log_cap
        pos = jnp.clip(idx - 1, 0, L - 1)
        return jnp.where(idx <= 0, 0, take_small(log_term_row, pos))

    def _append_msgs(self, cfg, me, llen_me, log_term_row, log_cmd_row,
                     next_row, term_me, commit_me):
        """Per-peer AppendEntries payloads from the leader's next_idx row.

        Takes the leader's post-update row VIEWS (scalars and (L,)/(N,)
        rows the handler already holds) rather than the whole state — see
        the call site for why re-gathering them from the updated (N, L)
        arrays costs peak memory."""
        r = self.rcfg
        n, L = r.n, r.log_cap
        nxt = jnp.clip(next_row, 1, L + 1)             # (N,)
        prev = nxt - 1
        prev_term = jnp.where(
            prev <= 0, 0, take_small(log_term_row, jnp.clip(prev - 1, 0, L - 1)))
        have = nxt <= llen_me                          # entry to ship?
        pos = jnp.clip(nxt - 1, 0, L - 1)
        e_term = jnp.where(have, take_small(log_term_row, pos), 0)
        e_cmd = jnp.where(have, take_small(log_cmd_row, pos), 0)
        term = jnp.full((n,), term_me, jnp.int32)
        payload = jnp.stack([
            term, jnp.full((n,), me, jnp.int32), prev, prev_term,
            have.astype(jnp.int32), e_term, e_cmd,
            jnp.full((n,), commit_me, jnp.int32),
        ], axis=1)
        pad = jnp.zeros((n, cfg.payload_words - 8), jnp.int32)
        return jnp.arange(n) != me, jnp.concatenate([payload, pad], axis=1)

    def _bcast_payload(self, cfg, words):
        return bcast_payload(cfg, self.rcfg.n, words)

    def _pad(self, cfg, words) -> jnp.ndarray:
        return pad_payload(cfg, words)

    def _outbox(self, cfg, *args, **kwargs) -> Outbox:
        return make_outbox(cfg, self.rcfg.n, *args, **kwargs)
