"""Pure-JAX Raft actor for the batched device engine.

The device-side MadRaft equivalent (see `madsim_tpu/models/raft.py` for the
host-engine version): leader election + single-entry-pipelined log
replication over the engine's simulated network, with on-device invariant
checking (election safety, log matching) producing the per-world *bug flag*
that BASELINE.json's time-to-first-bug metric measures. All state is
fixed-shape int32 arrays, all control flow is ``lax`` primitives, so the
whole cluster steps inside one XLA program and vmaps over thousands of
worlds.

Fault tolerance matches the host model: node kill drops timers via the
engine's generation counters; restart preserves persistent state
(term/voted_for/log — what ``RaftServer._persist`` writes to the simulated
disk) and resets volatile state, mirroring crash-recovery semantics.

The ``buggy_double_vote`` switch deliberately breaks the "one vote per term"
rule so seed sweeps have a real bug to find — the analog of the interleaving
bugs madsim exists to catch.
"""
from __future__ import annotations

import dataclasses
from typing import List, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .core import EngineConfig, Outbox
from .queue import Event, FLAG_TIMER, INF_TIME
from .rng import DevRng, uniform_u32

FOLLOWER, CANDIDATE, LEADER = 0, 1, 2

# Event kinds.
K_ELECTION = 0      # timer [epoch]
K_HEARTBEAT = 1     # timer [term]
K_REQVOTE = 2       # msg [term, candidate, last_idx, last_term]
K_VOTEREPLY = 3     # msg [term, granted, voter]
K_APPEND = 4        # msg [term, leader, prev_idx, prev_term, n, e_term, e_cmd, l_commit]
K_APPENDREPLY = 5   # msg [term, success, match_idx, follower]
K_PROPOSE = 6       # scheduled client proposal [cmd]
NUM_KINDS = 7


@dataclasses.dataclass(frozen=True)
class RaftDeviceConfig:
    """Static Raft parameters (host analog: models/raft.py RaftOptions)."""

    n: int = 3
    log_cap: int = 16
    elect_min_us: int = 150_000
    elect_max_us: int = 300_000
    heartbeat_us: int = 50_000
    # Client proposals broadcast to every node at fixed virtual times; only
    # the current leader appends. cmd of proposal i is i+1.
    n_proposals: int = 0
    propose_start_us: int = 800_000
    propose_interval_us: int = 100_000
    # Injected bug: grant votes ignoring the one-vote-per-term rule.
    buggy_double_vote: bool = False


class RaftState(NamedTuple):
    term: jnp.ndarray        # (N,) i32
    voted_for: jnp.ndarray   # (N,) i32, -1 = none
    role: jnp.ndarray        # (N,) i32
    votes: jnp.ndarray       # (N,) i32 bitmask of granted votes
    commit: jnp.ndarray      # (N,) i32
    log_len: jnp.ndarray     # (N,) i32
    log_term: jnp.ndarray    # (N, L) i32
    log_cmd: jnp.ndarray     # (N, L) i32
    next_idx: jnp.ndarray    # (N, N) i32 [leader, peer]
    match_idx: jnp.ndarray   # (N, N) i32 [leader, peer]
    elect_epoch: jnp.ndarray  # (N,) i32 — invalidates stale election timers
    first_leader_time: jnp.ndarray  # i32 µs, INF if never
    elections_won: jnp.ndarray      # i32


class RaftActor:
    """Actor implementing the DeviceEngine protocol for a Raft cluster."""

    num_kinds = NUM_KINDS

    def __init__(self, rcfg: RaftDeviceConfig):
        self.rcfg = rcfg

    # ------------------------------------------------------------------
    # Protocol: init
    # ------------------------------------------------------------------
    def init(self, cfg: EngineConfig, rng: DevRng
             ) -> Tuple[RaftState, List[Event], DevRng]:
        r = self.rcfg
        n, L = r.n, r.log_cap
        if cfg.n_nodes != n:
            raise ValueError("EngineConfig.n_nodes must match RaftDeviceConfig.n")
        if cfg.m != n + 1:
            raise ValueError("RaftActor needs outbox_cap == n + 1 "
                             "(n-1 peer messages + 1 timer per handler)")
        if cfg.payload_words < 8:
            raise ValueError("RaftActor needs payload_words >= 8")
        s = RaftState(
            term=jnp.zeros((n,), jnp.int32),
            voted_for=jnp.full((n,), -1, jnp.int32),
            role=jnp.zeros((n,), jnp.int32),
            votes=jnp.zeros((n,), jnp.int32),
            commit=jnp.zeros((n,), jnp.int32),
            log_len=jnp.zeros((n,), jnp.int32),
            log_term=jnp.zeros((n, L), jnp.int32),
            log_cmd=jnp.zeros((n, L), jnp.int32),
            next_idx=jnp.ones((n, n), jnp.int32),
            match_idx=jnp.zeros((n, n), jnp.int32),
            elect_epoch=jnp.zeros((n,), jnp.int32),
            first_leader_time=INF_TIME,
            elections_won=jnp.int32(0),
        )
        events: List[Event] = []
        for i in range(n):
            delay, rng = uniform_u32(rng, r.elect_min_us, r.elect_max_us)
            events.append(Event.make(
                time=delay, kind=K_ELECTION, payload_words=cfg.payload_words,
                flags=FLAG_TIMER, src=i, dst=i, payload=[0]))
        for p in range(r.n_proposals):
            t = r.propose_start_us + p * r.propose_interval_us
            for i in range(n):
                events.append(Event.make(
                    time=t, kind=K_PROPOSE, payload_words=cfg.payload_words,
                    src=i, dst=i, payload=[p + 1]))
        return s, events, rng

    # ------------------------------------------------------------------
    # Protocol: restart hook (persistent state survives; volatile resets)
    # ------------------------------------------------------------------
    def on_restart(self, cfg: EngineConfig, s: RaftState, node, now, rng: DevRng
                   ) -> Tuple[RaftState, Outbox, DevRng]:
        r = self.rcfg
        n = r.n
        me = jnp.clip(node, 0, n - 1)
        epoch2 = s.elect_epoch[me] + 1
        s = s._replace(
            role=s.role.at[me].set(FOLLOWER),
            votes=s.votes.at[me].set(0),
            commit=s.commit.at[me].set(0),
            next_idx=s.next_idx.at[me].set(jnp.ones((n,), jnp.int32)),
            match_idx=s.match_idx.at[me].set(jnp.zeros((n,), jnp.int32)),
            elect_epoch=s.elect_epoch.at[me].set(epoch2),
        )
        delay, rng = uniform_u32(rng, r.elect_min_us, r.elect_max_us)
        ob = self._outbox(
            cfg,
            msg_valid=jnp.zeros((n,), bool),
            msg_kind=jnp.zeros((n,), jnp.int32),
            msg_payload=jnp.zeros((n, cfg.payload_words), jnp.int32),
            timer_valid=jnp.asarray(True), timer_kind=jnp.int32(K_ELECTION),
            timer_dst=me, timer_delay=delay,
            timer_payload=self._pad(cfg, [epoch2]),
        )
        return s, ob, rng

    # ------------------------------------------------------------------
    # Protocol: event dispatch
    # ------------------------------------------------------------------
    def handle(self, cfg: EngineConfig, s: RaftState, ev: Event, now, rng: DevRng
               ) -> Tuple[RaftState, Outbox, DevRng, jnp.ndarray]:
        branches = [
            self._on_election, self._on_heartbeat, self._on_reqvote,
            self._on_votereply, self._on_append, self._on_appendreply,
            self._on_propose,
        ]

        def mk(fn):
            return lambda a, e, t, r: fn(cfg, a, e, t, r)

        kind = jnp.clip(ev.kind, 0, NUM_KINDS - 1)
        return jax.lax.switch(kind, [mk(f) for f in branches], s, ev, now, rng)

    # ------------------------------------------------------------------
    # Protocol: invariants (the bug flag)
    # ------------------------------------------------------------------
    def invariant(self, cfg: EngineConfig, s: RaftState) -> jnp.ndarray:
        n = self.rcfg.n
        # Election safety: at most one leader per term (models/raft.py
        # InvariantChecker.on_become_leader).
        is_leader = s.role == LEADER
        same_term = s.term[:, None] == s.term[None, :]
        pair = is_leader[:, None] & is_leader[None, :] & same_term
        off_diag = ~jnp.eye(n, dtype=bool)
        two_leaders = jnp.any(pair & off_diag)
        # Log matching on committed prefixes (on_commit analog).
        L = self.rcfg.log_cap
        k = jnp.arange(L)
        lim = jnp.minimum(s.commit[:, None], s.commit[None, :])  # (N, N)
        mask = k[None, None, :] < lim[:, :, None]
        diff = (s.log_term[:, None, :] != s.log_term[None, :, :]) | \
               (s.log_cmd[:, None, :] != s.log_cmd[None, :, :])
        log_mismatch = jnp.any(mask & diff)
        return two_leaders | log_mismatch

    # ------------------------------------------------------------------
    # Protocol: observation
    # ------------------------------------------------------------------
    def observe(self, cfg: EngineConfig, s: RaftState) -> dict:
        return {
            "leader_elected": s.first_leader_time < INF_TIME,
            "first_leader_time_us": s.first_leader_time,
            "elections_won": s.elections_won,
            "max_commit": jnp.max(s.commit, axis=-1),
            "max_term": jnp.max(s.term, axis=-1),
        }

    # ==================================================================
    # Handlers. Each returns (state, outbox, rng, bug).
    # ==================================================================
    def _on_election(self, cfg, s: RaftState, ev: Event, now, rng):
        r = self.rcfg
        n = r.n
        me = jnp.clip(ev.dst, 0, n - 1)
        epoch_ok = ev.payload[0] == s.elect_epoch[me]
        fire = epoch_ok & (s.role[me] != LEADER)
        term2 = s.term[me] + 1
        s2 = s._replace(
            term=s.term.at[me].set(jnp.where(fire, term2, s.term[me])),
            voted_for=s.voted_for.at[me].set(jnp.where(fire, me, s.voted_for[me])),
            role=s.role.at[me].set(jnp.where(fire, CANDIDATE, s.role[me])),
            votes=s.votes.at[me].set(jnp.where(fire, 1 << me, s.votes[me])),
        )
        last_idx = s.log_len[me]
        last_term = self._log_term_at(s, me, last_idx)
        payload = self._bcast_payload(cfg, [term2, me, last_idx, last_term])
        peers = jnp.arange(n) != me
        delay, rng = uniform_u32(rng, r.elect_min_us, r.elect_max_us)
        ob = self._outbox(
            cfg,
            msg_valid=fire & peers,
            msg_kind=jnp.full((n,), K_REQVOTE, jnp.int32),
            msg_payload=payload,
            timer_valid=epoch_ok,  # keep exactly one live election timer
            timer_kind=jnp.int32(K_ELECTION), timer_dst=me, timer_delay=delay,
            timer_payload=self._pad(cfg, [s.elect_epoch[me]]),
        )
        return s2, ob, rng, jnp.asarray(False)

    def _on_heartbeat(self, cfg, s: RaftState, ev: Event, now, rng):
        r = self.rcfg
        n = r.n
        me = jnp.clip(ev.dst, 0, n - 1)
        live = (s.role[me] == LEADER) & (s.term[me] == ev.payload[0])
        msg_valid, msg_payload = self._append_msgs(cfg, s, me)
        ob = self._outbox(
            cfg,
            msg_valid=live & msg_valid,
            msg_kind=jnp.full((n,), K_APPEND, jnp.int32),
            msg_payload=msg_payload,
            timer_valid=live, timer_kind=jnp.int32(K_HEARTBEAT), timer_dst=me,
            timer_delay=jnp.int32(r.heartbeat_us),
            timer_payload=self._pad(cfg, [ev.payload[0]]),
        )
        return s, ob, rng, jnp.asarray(False)

    def _on_reqvote(self, cfg, s: RaftState, ev: Event, now, rng):
        r = self.rcfg
        n = r.n
        me = jnp.clip(ev.dst, 0, n - 1)
        t, cand = ev.payload[0], jnp.clip(ev.payload[1], 0, n - 1)
        last_idx, last_term = ev.payload[2], ev.payload[3]
        s = self._maybe_step_down(s, me, t)
        reject = t < s.term[me]
        my_last = s.log_len[me]
        my_last_term = self._log_term_at(s, me, my_last)
        up_to_date = (last_term > my_last_term) | \
                     ((last_term == my_last_term) & (last_idx >= my_last))
        if r.buggy_double_vote:
            can_vote = jnp.asarray(True)
        else:
            can_vote = (s.voted_for[me] == -1) | (s.voted_for[me] == cand)
        grant = ~reject & up_to_date & can_vote
        epoch2 = s.elect_epoch[me] + 1
        s2 = s._replace(
            voted_for=s.voted_for.at[me].set(
                jnp.where(grant, cand, s.voted_for[me])),
            elect_epoch=s.elect_epoch.at[me].set(
                jnp.where(grant, epoch2, s.elect_epoch[me])),
        )
        payload = self._bcast_payload(cfg, [s.term[me], grant.astype(jnp.int32), me, 0])
        delay, rng = uniform_u32(rng, r.elect_min_us, r.elect_max_us)
        ob = self._outbox(
            cfg,
            msg_valid=jnp.arange(n) == cand,
            msg_kind=jnp.full((n,), K_VOTEREPLY, jnp.int32),
            msg_payload=payload,
            timer_valid=grant,  # granting resets the election timer
            timer_kind=jnp.int32(K_ELECTION), timer_dst=me, timer_delay=delay,
            timer_payload=self._pad(cfg, [epoch2]),
        )
        return s2, ob, rng, jnp.asarray(False)

    def _on_votereply(self, cfg, s: RaftState, ev: Event, now, rng):
        r = self.rcfg
        n = r.n
        me = jnp.clip(ev.dst, 0, n - 1)
        t, granted, voter = ev.payload[0], ev.payload[1], jnp.clip(ev.payload[2], 0, n - 1)
        s = self._maybe_step_down(s, me, t)
        counted = (granted != 0) & (s.role[me] == CANDIDATE) & (t == s.term[me])
        votes2 = jnp.where(counted, s.votes[me] | (1 << voter), s.votes[me])
        win = counted & (jax.lax.population_count(votes2) > n // 2)
        llen = s.log_len[me]
        s2 = s._replace(
            votes=s.votes.at[me].set(votes2),
            role=s.role.at[me].set(jnp.where(win, LEADER, s.role[me])),
            next_idx=s.next_idx.at[me].set(jnp.where(
                win, jnp.full((n,), llen + 1, jnp.int32), s.next_idx[me])),
            match_idx=s.match_idx.at[me].set(jnp.where(
                win,
                jnp.zeros((n,), jnp.int32).at[me].set(llen),
                s.match_idx[me])),
            first_leader_time=jnp.where(
                win, jnp.minimum(s.first_leader_time, jnp.asarray(now, jnp.int32)),
                s.first_leader_time),
            elections_won=s.elections_won + win.astype(jnp.int32),
        )
        msg_valid, msg_payload = self._append_msgs(cfg, s2, me)
        ob = self._outbox(
            cfg,
            msg_valid=win & msg_valid,
            msg_kind=jnp.full((n,), K_APPEND, jnp.int32),
            msg_payload=msg_payload,
            timer_valid=win, timer_kind=jnp.int32(K_HEARTBEAT), timer_dst=me,
            timer_delay=jnp.int32(r.heartbeat_us),
            timer_payload=self._pad(cfg, [s2.term[me]]),
        )
        return s2, ob, rng, jnp.asarray(False)

    def _on_append(self, cfg, s: RaftState, ev: Event, now, rng):
        r = self.rcfg
        n, L = r.n, r.log_cap
        me = jnp.clip(ev.dst, 0, n - 1)
        t, leader = ev.payload[0], jnp.clip(ev.payload[1], 0, n - 1)
        prev_idx, prev_term = ev.payload[2], ev.payload[3]
        n_ent, e_term, e_cmd, l_commit = (ev.payload[4], ev.payload[5],
                                          ev.payload[6], ev.payload[7])
        s = self._maybe_step_down(s, me, t, follower_on_equal=True)
        reject = t < s.term[me]
        prev_ok = (prev_idx <= s.log_len[me]) & \
                  (self._log_term_at(s, me, prev_idx) == prev_term)
        success = ~reject & prev_ok
        idx = prev_idx + 1
        has_room = idx <= L
        write = success & (n_ent > 0) & has_room
        pos = jnp.clip(idx - 1, 0, L - 1)
        same = (idx <= s.log_len[me]) & \
               (s.log_term[me, pos] == e_term) & (s.log_cmd[me, pos] == e_cmd)
        new_len = jnp.where(write, jnp.where(same, s.log_len[me], idx),
                            s.log_len[me])
        log_term2 = s.log_term.at[me, pos].set(
            jnp.where(write, e_term, s.log_term[me, pos]))
        log_cmd2 = s.log_cmd.at[me, pos].set(
            jnp.where(write, e_cmd, s.log_cmd[me, pos]))
        match = jnp.where(write, idx, jnp.where(success, prev_idx, 0))
        commit2 = jnp.where(success,
                            jnp.maximum(s.commit[me],
                                        jnp.minimum(l_commit, new_len)),
                            s.commit[me])
        epoch2 = s.elect_epoch[me] + 1
        s2 = s._replace(
            log_term=log_term2, log_cmd=log_cmd2,
            log_len=s.log_len.at[me].set(new_len),
            commit=s.commit.at[me].set(commit2),
            elect_epoch=s.elect_epoch.at[me].set(
                jnp.where(reject, s.elect_epoch[me], epoch2)),
        )
        payload = self._bcast_payload(
            cfg, [s.term[me], success.astype(jnp.int32), match, me])
        delay, rng = uniform_u32(rng, r.elect_min_us, r.elect_max_us)
        ob = self._outbox(
            cfg,
            msg_valid=jnp.arange(n) == leader,
            msg_kind=jnp.full((n,), K_APPENDREPLY, jnp.int32),
            msg_payload=payload,
            timer_valid=~reject,  # a valid AppendEntries is a heartbeat
            timer_kind=jnp.int32(K_ELECTION), timer_dst=me, timer_delay=delay,
            timer_payload=self._pad(cfg, [epoch2]),
        )
        return s2, ob, rng, jnp.asarray(False)

    def _on_appendreply(self, cfg, s: RaftState, ev: Event, now, rng):
        r = self.rcfg
        n, L = r.n, r.log_cap
        me = jnp.clip(ev.dst, 0, n - 1)
        t, success = ev.payload[0], ev.payload[1]
        match, follower = ev.payload[2], jnp.clip(ev.payload[3], 0, n - 1)
        s = self._maybe_step_down(s, me, t)
        live = (s.role[me] == LEADER) & (t == s.term[me])
        ok = live & (success != 0)
        fail = live & (success == 0)
        match2 = jnp.maximum(s.match_idx[me, follower], match)
        s2 = s._replace(
            match_idx=s.match_idx.at[me, follower].set(
                jnp.where(ok, match2, s.match_idx[me, follower])),
            next_idx=s.next_idx.at[me, follower].set(jnp.where(
                ok, match2 + 1,
                jnp.where(fail,
                          jnp.maximum(1, s.next_idx[me, follower] - 1),
                          s.next_idx[me, follower]))),
        )
        # Advance commit: the largest n with majority match and current-term
        # entry (models/raft.py _advance_commit).
        ns = jnp.arange(1, L + 1)
        counts = jnp.sum(s2.match_idx[me][:, None] >= ns[None, :], axis=0)
        okn = (ns <= s2.log_len[me]) & (counts > n // 2) & \
              (s2.log_term[me] == s2.term[me])
        best = jnp.max(jnp.where(okn, ns, 0))
        commit2 = jnp.where(live, jnp.maximum(s2.commit[me], best), s2.commit[me])
        s3 = s2._replace(commit=s2.commit.at[me].set(commit2))
        return s3, Outbox.empty(cfg), rng, jnp.asarray(False)

    def _on_propose(self, cfg, s: RaftState, ev: Event, now, rng):
        r = self.rcfg
        n, L = r.n, r.log_cap
        me = jnp.clip(ev.dst, 0, n - 1)
        cmd = ev.payload[0]
        accept = (s.role[me] == LEADER) & (s.log_len[me] < L)
        pos = jnp.clip(s.log_len[me], 0, L - 1)
        llen2 = s.log_len[me] + accept.astype(jnp.int32)
        s2 = s._replace(
            log_term=s.log_term.at[me, pos].set(
                jnp.where(accept, s.term[me], s.log_term[me, pos])),
            log_cmd=s.log_cmd.at[me, pos].set(
                jnp.where(accept, cmd, s.log_cmd[me, pos])),
            log_len=s.log_len.at[me].set(llen2),
            match_idx=s.match_idx.at[me, me].set(
                jnp.where(accept, llen2, s.match_idx[me, me])),
        )
        msg_valid, msg_payload = self._append_msgs(cfg, s2, me)
        ob = self._outbox(
            cfg,
            msg_valid=accept & msg_valid,
            msg_kind=jnp.full((n,), K_APPEND, jnp.int32),
            msg_payload=msg_payload,
            timer_valid=jnp.asarray(False), timer_kind=jnp.int32(0),
            timer_dst=me, timer_delay=jnp.int32(0),
            timer_payload=self._pad(cfg, []),
        )
        return s2, ob, rng, jnp.asarray(False)

    # ==================================================================
    # Helpers
    # ==================================================================
    def _maybe_step_down(self, s: RaftState, me, t, follower_on_equal=False):
        """Adopt a higher term (→ follower, clear vote); optionally also
        step down from CANDIDATE on an equal-term AppendEntries."""
        higher = t > s.term[me]
        demote = higher | (follower_on_equal & (t == s.term[me]) &
                           (s.role[me] == CANDIDATE))
        return s._replace(
            term=s.term.at[me].set(jnp.where(higher, t, s.term[me])),
            voted_for=s.voted_for.at[me].set(
                jnp.where(higher, -1, s.voted_for[me])),
            role=s.role.at[me].set(jnp.where(demote, FOLLOWER, s.role[me])),
        )

    def _log_term_at(self, s: RaftState, me, idx):
        """Term of entry ``idx`` (1-based); 0 for idx == 0."""
        L = self.rcfg.log_cap
        pos = jnp.clip(idx - 1, 0, L - 1)
        return jnp.where(idx <= 0, 0, s.log_term[me, pos])

    def _append_msgs(self, cfg, s: RaftState, me):
        """Per-peer AppendEntries payloads from the leader's next_idx row."""
        r = self.rcfg
        n, L = r.n, r.log_cap
        nxt = jnp.clip(s.next_idx[me], 1, L + 1)      # (N,)
        prev = nxt - 1
        prev_pos = jnp.clip(prev - 1, 0, L - 1)
        prev_term = jnp.where(prev <= 0, 0, s.log_term[me, prev_pos])
        have = nxt <= s.log_len[me]                   # entry to ship?
        pos = jnp.clip(nxt - 1, 0, L - 1)
        e_term = jnp.where(have, s.log_term[me, pos], 0)
        e_cmd = jnp.where(have, s.log_cmd[me, pos], 0)
        term = jnp.full((n,), s.term[me], jnp.int32)
        payload = jnp.stack([
            term, jnp.full((n,), me, jnp.int32), prev, prev_term,
            have.astype(jnp.int32), e_term, e_cmd,
            jnp.full((n,), s.commit[me], jnp.int32),
        ], axis=1)
        pad = jnp.zeros((n, cfg.payload_words - 8), jnp.int32)
        return jnp.arange(n) != me, jnp.concatenate([payload, pad], axis=1)

    def _bcast_payload(self, cfg, words):
        """(N, P) payload with the same words in every row."""
        n = self.rcfg.n
        row = self._pad(cfg, words)
        return jnp.broadcast_to(row, (n, cfg.payload_words))

    def _pad(self, cfg, words) -> jnp.ndarray:
        vals = [jnp.asarray(wd, jnp.int32) for wd in words]
        vals += [jnp.int32(0)] * (cfg.payload_words - len(words))
        return jnp.stack(vals)

    def _outbox(self, cfg, msg_valid, msg_kind, msg_payload, timer_valid,
                timer_kind, timer_dst, timer_delay, timer_payload) -> Outbox:
        """Assemble the (N peers + 1 timer) outbox layout."""
        n = self.rcfg.n
        app = lambda xs, x: jnp.concatenate(  # noqa: E731
            [jnp.asarray(xs), jnp.asarray(x)[None]], axis=0)
        return Outbox(
            valid=app(msg_valid, timer_valid),
            is_timer=app(jnp.zeros((n,), bool), jnp.asarray(True)),
            kind=app(msg_kind, timer_kind),
            dst=app(jnp.arange(n, dtype=jnp.int32), jnp.asarray(timer_dst, jnp.int32)),
            delay_us=app(jnp.zeros((n,), jnp.int32), jnp.asarray(timer_delay, jnp.int32)),
            payload=jnp.concatenate([msg_payload, timer_payload[None]], axis=0),
        )
