"""The fused Pallas step kernel (``EngineConfig(pallas=True)``).

Why: the lax step is a *sequence* of XLA fusions — queue pop (min +
gather), eligible mask, actor dispatch, outbox scatter — and on TPU each
fusion boundary is an HBM round trip for the world-state lanes it
touches. The per-step compute is tiny (thousands of int ops per world);
the cost is the state bytes crossing HBM several times per step, which
is exactly the ceiling the packed lane dtypes attack from the other
side (docs/perf.md "Roofline round 2"). Fusing the whole step into ONE
``pl.pallas_call`` keeps every lane — queue time/meta/payload, node
liveness, actor state — resident in VMEM for the duration of the step:
one load, one store, instead of one per fusion.

How: the kernel body *is* the engine's vmapped per-world step function.
Pallas kernels trace ordinary JAX ops over values loaded from refs, so
the same ``_build_step`` closure that defines the lax path defines the
kernel — which makes bitwise identity a construction property, not a
porting exercise, and it is gated anyway (tests/test_pallas_step.py,
the ``make smoke`` pallas-interpret leg) because a lowering bug would
break exactly this contract.

Deployment shape:

- **CPU / tier-1**: ``interpret=True`` (the auto default off-TPU) runs
  the kernel through the Pallas interpreter — same primitive sequence,
  bit-identical results, no Mosaic lowering required. This is what
  keeps the gate green in CI.
- **TPU**: real lowering, whole batch in one kernel invocation by
  default (state blocks resident in VMEM), or gridded over the world
  axis via ``EngineConfig(pallas_block=B)`` when W worlds exceed VMEM —
  each grid step owns a ``(B, ...)`` block of every state leaf
  (worlds are independent, so the block split is semantics-free).
- ``input_output_aliases`` maps every state leaf onto its output slot,
  the in-kernel analog of the run loop's buffer donation: the state is
  updated in place, not double-buffered.

The kernel is a registered tracelint program (``engine.pallas_step``)
with its own budget-ledger entries, and is TRC005-checked like the lax
packed step.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def _interpret_default() -> bool:
    """Interpret everywhere but on a real TPU backend: the interpreter
    is the portable (and CPU tier-1) execution mode; Mosaic lowering is
    the TPU one."""
    return jax.default_backend() != "tpu"


def make_pallas_step(step_one: Callable, cfg) -> Callable:
    """Build the batched step: ``WorldState[W] -> WorldState[W]`` as one
    ``pl.pallas_call``. ``step_one`` is the engine's per-world step
    closure (``DeviceEngine._build_step``); ``cfg`` supplies the
    ``pallas_block`` / ``pallas_interpret`` knobs."""
    from jax.experimental import pallas as pl

    batched_step = jax.vmap(step_one)

    def pallas_batched_step(state):
        leaves, treedef = jax.tree_util.tree_flatten(state)
        n = len(leaves)
        w = leaves[0].shape[0]
        interpret = cfg.pallas_interpret
        if interpret is None:
            interpret = _interpret_default()

        def flat_step(*ls):
            s = jax.tree_util.tree_unflatten(treedef, ls)
            return jax.tree_util.tree_leaves(batched_step(s))

        block = cfg.pallas_block
        gridded = block is not None and block < w and w % block == 0
        bw = block if gridded else w

        # The step closure carries constant tables (the popcount
        # power-of-two vectors in lanes.prefix_count/queue.push_many,
        # arange masks, ...). Pallas kernels cannot capture constants —
        # and closure_convert only hoists *differentiable* ones, which
        # these integer tables are not — so the step is staged to a
        # jaxpr here (at the per-grid-step block width) and its consts
        # become explicit kernel inputs, re-bound from refs inside the
        # kernel body.
        closed = jax.make_jaxpr(flat_step)(
            *[jax.ShapeDtypeStruct((bw,) + l.shape[1:], l.dtype)
              for l in leaves])
        consts = [jnp.asarray(c) for c in closed.consts]
        nc = len(consts)

        def kernel(*refs):
            state_vals = [r[...] for r in refs[:n]]
            const_vals = [r[...] for r in refs[n:n + nc]]
            outs = jax.core.eval_jaxpr(closed.jaxpr, const_vals,
                                       *state_vals)
            for ref, val in zip(refs[n + nc:], outs):
                ref[...] = val

        kwargs = dict(
            out_shape=[jax.ShapeDtypeStruct(l.shape, l.dtype)
                       for l in leaves],
            # Every state leaf aliases its output slot: in-place update
            # inside the kernel, the donation story of the lax path.
            input_output_aliases={i: i for i in range(n)},
            interpret=bool(interpret),
        )
        if gridded:
            # Grid over the world axis: grid step i owns worlds
            # [i*B, (i+1)*B) of every leaf. Worlds are independent, so
            # the blocked kernel is bitwise-identical to the monolithic
            # one; the index_map pins all trailing axes to block 0
            # (each block spans them whole). Hoisted constants have no
            # world axis: every grid step sees them whole.
            def spec(leaf):
                rest = leaf.shape[1:]
                return pl.BlockSpec(
                    (block,) + rest,
                    lambda i, _nr=len(rest): (i,) + (0,) * _nr)

            def const_spec(c):
                return pl.BlockSpec(
                    c.shape, lambda i, _nr=c.ndim: (0,) * _nr)

            kwargs.update(grid=(w // block,),
                          in_specs=[spec(l) for l in leaves]
                          + [const_spec(c) for c in consts],
                          out_specs=[spec(l) for l in leaves])
        out_leaves = pl.pallas_call(kernel, **kwargs)(*leaves, *consts)
        return jax.tree_util.tree_unflatten(treedef, out_leaves)

    return pallas_batched_step
