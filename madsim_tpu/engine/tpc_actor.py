"""Two-phase-commit actor — the third workload family, now compiled.

Since the actor compiler landed (docs/actorc.md), this module holds only
the config dataclass and a thin wrapper: the protocol itself lives as a
declarative spec in :mod:`madsim_tpu.actorc.families.tpc`, and
:class:`~madsim_tpu.actorc.compile.CompiledActor` lowers it to the
DeviceEngine protocol — same lanes at the same packed dtypes (now
derived from declared ranges), same merged-handler dispatch, same
single ``make_outbox`` assembly, bit-identical trajectories to the
retired hand-written implementation (this module's original test suite,
tests/test_tpc_actor.py, runs unchanged). The protocol description and
the atomicity invariant are documented on the spec.

Node 0 is the transaction coordinator; 1..n-1 participate. PREPARE /
VOTE / DECIDE with a vote timeout; ``buggy_presumed_commit`` decides
COMMIT on timeout (the "presumed commit" shortcut applied where it is
unsound) and seed sweeps catch the atomicity divergence at apply time.
"""
from __future__ import annotations

import dataclasses

from ..actorc.compile import CompiledActor

# Event kinds (spec declaration order — kept for callers and tests).
K_TXN = 0       # scheduled at the coordinator [txn]
K_PREPARE = 1   # coord -> participant [txn]
K_VOTE = 2      # participant -> coord [txn, yes, voter]
K_DECIDE = 3    # coord -> participant [txn, decision]
K_TIMEOUT = 4   # coord timer [txn]
NUM_KINDS = 5

# Decision codes.
NONE, COMMIT, ABORT = 0, 1, 2

COORD = 0  # node 0 coordinates; 1..n-1 participate


@dataclasses.dataclass(frozen=True)
class TPCDeviceConfig:
    """Static two-phase-commit parameters."""

    n: int = 4                     # 1 coordinator + n-1 participants
    n_txns: int = 8
    txn_start_us: int = 50_000
    txn_interval_us: int = 120_000
    vote_timeout_us: int = 60_000
    # Each participant votes no on a txn with probability ~ no_vote_num/256
    # (drawn from the world's RNG stream at PREPARE time).
    no_vote_num: int = 32
    # Injected bug: decide COMMIT on vote timeout instead of ABORT.
    buggy_presumed_commit: bool = False


class TPCActor(CompiledActor):
    """Two-phase commit, compiled from its actorc spec."""

    def __init__(self, tcfg: TPCDeviceConfig = TPCDeviceConfig()):
        from ..actorc.families.tpc import tpc_spec

        super().__init__(tpc_spec(tcfg))
        self.tcfg = tcfg
