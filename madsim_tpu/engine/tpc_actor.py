"""Two-phase-commit actor: the third device-engine workload family.

Alongside consensus (:mod:`madsim_tpu.engine.raft_actor`) and primary-backup
replication (:mod:`madsim_tpu.engine.pb_actor`), this covers the third
classic distributed-systems protocol class: atomic commitment. Node 0 is
the transaction coordinator; nodes 1..n-1 are participants. Transactions
arrive on a schedule at the coordinator, which runs textbook 2PC: PREPARE
to every participant, collect votes, COMMIT iff every vote is yes, ABORT
otherwise or on timeout. A participant that votes no aborts unilaterally
(it holds no locks for a transaction it rejected); one that votes yes is
*blocked* until the coordinator's decision arrives — 2PC's famous blocking
window, which fault schedules (coordinator kill, partitions) make visible
in the ``blocked`` observable.

On-device invariant (the bug flag): **atomicity** — no transaction may be
applied as COMMIT at one node and ABORT at another. The
``buggy_presumed_commit`` switch makes the coordinator decide COMMIT on
vote timeout (the "presumed commit" shortcut applied where it is unsound):
a participant whose no-vote (or whose PREPARE) was lost to the network
then aborts unilaterally while everyone else commits, and seed sweeps
catch the divergence at apply time.

All state is fixed-shape int32 via the one-hot lane helpers; the handler is
merged (kind-masked writes, one outbox build) per docs/ACTORS.md.
"""
from __future__ import annotations

import dataclasses
from typing import List, NamedTuple, Tuple

import jax.numpy as jnp

from .actor_util import bcast_payload, make_outbox, pad_payload
from .core import EngineConfig, Outbox
from .lanes import sel, sel2, upd, upd2, widen
from .queue import Event
from .rng import DevRng, next_u32

# Event kinds.
K_TXN = 0       # scheduled at the coordinator [txn]
K_PREPARE = 1   # coord -> participant [txn]
K_VOTE = 2      # participant -> coord [txn, yes, voter]
K_DECIDE = 3    # coord -> participant [txn, decision]
K_TIMEOUT = 4   # coord timer [txn]
NUM_KINDS = 5

# Decision codes.
NONE, COMMIT, ABORT = 0, 1, 2

COORD = 0  # node 0 coordinates; 1..n-1 participate


@dataclasses.dataclass(frozen=True)
class TPCDeviceConfig:
    """Static two-phase-commit parameters."""

    n: int = 4                     # 1 coordinator + n-1 participants
    n_txns: int = 8
    txn_start_us: int = 50_000
    txn_interval_us: int = 120_000
    vote_timeout_us: int = 60_000
    # Each participant votes no on a txn with probability ~ no_vote_num/256
    # (drawn from the world's RNG stream at PREPARE time).
    no_vote_num: int = 32
    # Injected bug: decide COMMIT on vote timeout instead of ABORT.
    buggy_presumed_commit: bool = False


class TPCState(NamedTuple):
    """Decision/vote codes ride the i8 code lane under the packed
    profile (``EngineConfig.lanes``); the yes-bitmask and counters stay
    i32. Reads widen, writes saturate (the raft actor's discipline)."""

    decision: jnp.ndarray    # (N, T) code lane — applied outcome per node
    voted: jnp.ndarray       # (N, T) code lane — participant's sent vote
                             # (NONE/COMMIT=yes/ABORT=no)
    votes_yes: jnp.ndarray   # (T,) i32 — coordinator's yes bitmask
    decided: jnp.ndarray     # (T,) code lane — coordinator's decision record
    txns_seen: jnp.ndarray   # i32
    commits: jnp.ndarray     # i32 — coordinator-side COMMIT decisions
    aborts: jnp.ndarray      # i32


class TPCActor:
    """Two-phase commit implementing the DeviceEngine actor protocol."""

    num_kinds = NUM_KINDS
    kind_names = ["Txn", "Prepare", "Vote", "Decide", "Timeout"]

    def __init__(self, tcfg: TPCDeviceConfig):
        self.tcfg = tcfg

    # ------------------------------------------------------------------
    def init(self, cfg: EngineConfig, rng: DevRng
             ) -> Tuple[TPCState, List[Event], DevRng]:
        t = self.tcfg
        n, T = t.n, t.n_txns
        if cfg.n_nodes != n:
            raise ValueError("EngineConfig.n_nodes must match TPCDeviceConfig.n")
        if cfg.m != n + 1:
            raise ValueError("TPCActor needs outbox_cap == n + 1")
        if cfg.payload_words < 3:
            raise ValueError("TPCActor needs payload_words >= 3")
        if n < 2 or n > 31:
            raise ValueError("TPCActor needs 2..31 nodes (int32 vote bitmask)")
        lt = cfg.lanes
        s = TPCState(
            decision=jnp.zeros((n, T), lt.code),
            voted=jnp.zeros((n, T), lt.code),
            votes_yes=jnp.zeros((T,), jnp.int32),
            decided=jnp.zeros((T,), lt.code),
            txns_seen=jnp.int32(0),
            commits=jnp.int32(0),
            aborts=jnp.int32(0),
        )
        events = [Event.make(
            time=t.txn_start_us + i * t.txn_interval_us, kind=K_TXN,
            payload_words=cfg.payload_words, src=COORD, dst=COORD,
            payload=[i]) for i in range(t.n_txns)]
        return s, events, rng

    # ------------------------------------------------------------------
    def on_restart(self, cfg: EngineConfig, s: TPCState, node, now, rng: DevRng
                   ) -> Tuple[TPCState, Outbox, DevRng]:
        # Decisions, votes, and the coordinator's decision log are durable
        # (the 2PC write-ahead records); the coordinator's in-flight yes
        # bitmasks for UNdecided txns are volatile — those txns stay
        # pending until their timeout fires (or forever if it already
        # did: the blocking window).
        volatile = (s.decided == NONE)
        s2 = s._replace(
            votes_yes=jnp.where((node == COORD) & volatile, 0, s.votes_yes))
        return s2, Outbox.empty(cfg), rng

    # ------------------------------------------------------------------
    def handle(self, cfg: EngineConfig, s: TPCState, ev: Event, now, rng: DevRng
               ) -> Tuple[TPCState, Outbox, DevRng, jnp.ndarray]:
        t = self.tcfg
        n, T = t.n, t.n_txns
        kind = jnp.clip(ev.kind, 0, NUM_KINDS - 1)
        me = jnp.clip(ev.dst, 0, n - 1)
        txn = jnp.clip(ev.payload[0], 0, T - 1)
        arange_n = jnp.arange(n)
        is_txn = kind == K_TXN
        is_prep = kind == K_PREPARE
        is_vote = kind == K_VOTE
        is_dec = kind == K_DECIDE
        is_to = kind == K_TIMEOUT

        at_coord = me == COORD
        # Narrow-lane reads widen to i32 (engine/lanes.py discipline).
        decided_t = widen(sel(s.decided, txn))

        # One draw per step (static shape); only PREPARE consumes it.
        u, rng_drawn = next_u32(rng)
        rng = rng._replace(counter=jnp.where(is_prep, rng_drawn.counter,
                                             rng.counter))

        # -- K_TXN (coordinator): start 2PC for txn --
        start = is_txn & at_coord & (decided_t == NONE)

        # -- K_PREPARE (participant): vote once, abort locally on no --
        my_vote = widen(sel2(s.voted, me, txn))
        fresh = is_prep & ~at_coord & (my_vote == NONE) & \
            (widen(sel2(s.decision, me, txn)) == NONE)
        vote_no = (u % jnp.uint32(256)) < jnp.uint32(t.no_vote_num)
        vote_val = jnp.where(vote_no, ABORT, COMMIT)  # ABORT code == "no"
        # A no-voter aborts unilaterally at vote time.
        abort_local = fresh & vote_no

        # -- K_VOTE (coordinator): collect; all-yes => COMMIT --
        voter = jnp.clip(ev.payload[2], 0, n - 1)
        yes = ev.payload[1] == 1
        live_vote = is_vote & at_coord & (decided_t == NONE)
        mask_all = jnp.int32((1 << n) - 2)  # bits 1..n-1
        yes2 = sel(s.votes_yes, txn) | jnp.where(
            live_vote & yes, 1 << voter, 0)
        all_yes = live_vote & (yes2 == mask_all)
        any_no = live_vote & ~yes
        # -- K_TIMEOUT (coordinator): decide for the stragglers --
        fire_to = is_to & at_coord & (decided_t == NONE)
        to_decision = COMMIT if t.buggy_presumed_commit else ABORT

        decide_now = all_yes | any_no | fire_to
        decision_val = jnp.where(all_yes, COMMIT,
                                 jnp.where(any_no, ABORT,
                                           jnp.int32(to_decision)))

        # -- K_DECIDE (participant): apply, unless it aborted unilaterally
        # and the coordinator says COMMIT — that conflict IS the apply-time
        # state; the invariant reads it.
        applied = widen(sel2(s.decision, me, txn))
        apply_dec = is_dec & ~at_coord & (applied == NONE)

        # -- state writes (one per field) --
        dec_mine = jnp.where(
            abort_local, ABORT,
            jnp.where(apply_dec, ev.payload[1],
                      jnp.where(decide_now & at_coord, decision_val, applied)))
        write_dec = abort_local | apply_dec | (decide_now & at_coord)
        s2 = s._replace(
            decision=upd2(s.decision, me, txn,
                          jnp.where(write_dec, dec_mine, applied)),
            voted=upd2(s.voted, me, txn, jnp.where(fresh, vote_val, my_vote)),
            votes_yes=upd(s.votes_yes, txn, yes2),
            decided=upd(s.decided, txn,
                        jnp.where(decide_now, decision_val, decided_t)),
            txns_seen=s.txns_seen + start.astype(jnp.int32),
            commits=s.commits
            + (decide_now & (decision_val == COMMIT)).astype(jnp.int32),
            aborts=s.aborts
            + (decide_now & (decision_val == ABORT)).astype(jnp.int32),
        )

        # -- outbox --
        participants = arange_n != COORD
        msg_valid = jnp.where(
            start, participants,
            jnp.where(fresh, arange_n == COORD,
                      jnp.where(decide_now, participants,
                                jnp.zeros((n,), bool))))
        msg_kind = jnp.full((n,), jnp.where(
            start, K_PREPARE, jnp.where(fresh, K_VOTE, K_DECIDE)), jnp.int32)
        w1 = jnp.where(fresh, (vote_val == COMMIT).astype(jnp.int32),
                       jnp.where(decide_now, decision_val, 0))
        payload = bcast_payload(cfg, n, [txn, w1, me])
        ob = make_outbox(
            cfg, n,
            msg_valid=msg_valid, msg_kind=msg_kind, msg_payload=payload,
            timer_valid=start, timer_kind=jnp.int32(K_TIMEOUT),
            timer_dst=jnp.int32(COORD),
            timer_delay=jnp.int32(t.vote_timeout_us),
            timer_payload=pad_payload(cfg, [txn]),
        )
        return s2, ob, rng, jnp.asarray(False)

    # ------------------------------------------------------------------
    def invariant(self, cfg: EngineConfig, s: TPCState) -> jnp.ndarray:
        """Atomicity: no txn both committed and aborted across nodes."""
        committed = jnp.any(s.decision == COMMIT, axis=0)  # (T,)
        aborted = jnp.any(s.decision == ABORT, axis=0)     # (T,)
        return jnp.any(committed & aborted)

    # ------------------------------------------------------------------
    def observe(self, cfg: EngineConfig, s: TPCState) -> dict:
        # Batched state: node axis is -2, txn axis is -1.
        applied = s.decision[..., 1:, :]  # participants only
        return {
            "txns_seen": s.txns_seen,
            "commits": s.commits,
            "aborts": s.aborts,
            "blocked": jnp.sum(
                jnp.any((s.voted[..., 1:, :] == COMMIT)
                        & (applied == NONE), axis=-2).astype(jnp.int32),
                axis=-1),
        }
