"""The actor-family registry: every workload family the repo ships, in
one place.

Three consumers read this table:

- the obs replay CLI (``python -m madsim_tpu.obs replay --actor <name>``
  and bundle replay) resolves ``name -> (actor class, config class)``;
- triage names the family inside repro bundles
  (:func:`madsim_tpu.triage.corpus._actor_bundle_info`);
- the conformance tier-1 test (tests/test_conformance.py) runs
  ``engine.conformance.check_actor`` over EVERY entry — hand-written
  and compiled alike — via each entry's canonical ``conformance()``
  shape, instead of the per-actor opt-in it used to be.

Imports are lazy per entry: building the table costs nothing until a
family is actually constructed.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Family:
    """One registered actor family."""

    name: str
    load: Callable[[], Tuple[type, type]]   # -> (actor_cls, config_cls)
    # Canonical (actor, EngineConfig) for the conformance sweep — the
    # clean (bug switches off) shape check_actor validates.
    conformance: Callable[[], Tuple[Any, Any]]
    compiled: bool = False                   # actorc-spec family?
    # Synthetic fixture families are deliberately schedule-driven: the
    # fault-free trajectory is seed-invariant, so check_actor's
    # distinct-seeds-diverge requirement is waived for them.
    divergent: bool = True

    @property
    def actor_cls(self) -> type:
        return self.load()[0]

    @property
    def config_cls(self) -> type:
        return self.load()[1]


def _raft() -> Family:
    def load():
        from .raft_actor import RaftActor, RaftDeviceConfig

        return RaftActor, RaftDeviceConfig

    def conf():
        from .core import EngineConfig

        cls, cfg = load()
        return cls(cfg(n=3, n_proposals=2)), EngineConfig(
            n_nodes=3, outbox_cap=4, queue_cap=64, t_limit_us=2_000_000)
    return Family("raft", load, conf)


def _pb() -> Family:
    def load():
        from .pb_actor import PBActor, PBDeviceConfig

        return PBActor, PBDeviceConfig

    def conf():
        from .core import EngineConfig

        cls, cfg = load()
        return cls(cfg(n=3, n_writes=3)), EngineConfig(
            n_nodes=3, outbox_cap=4, queue_cap=64, t_limit_us=2_000_000)
    return Family("pb", load, conf, compiled=True)


def _tpc() -> Family:
    def load():
        from .tpc_actor import TPCActor, TPCDeviceConfig

        return TPCActor, TPCDeviceConfig

    def conf():
        from .core import EngineConfig

        cls, cfg = load()
        return cls(cfg(n=4, n_txns=4)), EngineConfig(
            n_nodes=4, outbox_cap=5, queue_cap=64, t_limit_us=2_000_000)
    return Family("tpc", load, conf, compiled=True)


def _paxos() -> Family:
    def load():
        from ..actorc.families.paxos import PaxosActor, PaxosConfig

        return PaxosActor, PaxosConfig

    def conf():
        from ..actorc.families.paxos import engine_config

        cls, cfg = load()
        acfg = cfg()
        return cls(acfg), engine_config(acfg)
    return Family("paxos", load, conf, compiled=True)


def _pair_restart() -> Family:
    def load():
        from ..triage.synthetic import PairRestartActor, PairRestartConfig

        return PairRestartActor, PairRestartConfig

    def conf():
        from ..triage.synthetic import engine_config

        cls, cfg = load()
        acfg = cfg()
        return cls(acfg), engine_config(acfg)
    return Family("pair_restart", load, conf, divergent=False)


def _guided_pair() -> Family:
    def load():
        from ..search.family import GuidedPairActor, GuidedPairConfig

        return GuidedPairActor, GuidedPairConfig

    def conf():
        from ..search.family import engine_config

        cls, cfg = load()
        acfg = cfg()
        return cls(acfg), engine_config(acfg)
    return Family("guided_pair", load, conf, divergent=False)


def actor_families() -> Dict[str, Family]:
    """name -> :class:`Family`, for every shipped workload family."""
    fams = [_raft(), _pb(), _tpc(), _paxos(), _pair_restart(),
            _guided_pair()]
    return {f.name: f for f in fams}


def family(name: str) -> Optional[Family]:
    return actor_families().get(name)
