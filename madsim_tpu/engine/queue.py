"""Fixed-capacity masked event queue (per world; vmapped over the seed axis).

The device analog of the host timer wheel + NetSim delivery queue
(`madsim/src/sim/time/mod.rs:159-214`, `net/mod.rs:173-197`): every pending
future occurrence in a world — timer expiry, message delivery, fault
injection — is one slot in a flat array. ``pop`` is a masked argmin over the
time lane (a single vectorized reduction, which is exactly the shape TPUs
like); ``push`` scatters into the first free slot. No pointer heap: priority
order is recomputed per pop, which for capacities ~64-256 is cheaper on TPU
than maintaining heap invariants with data-dependent control flow.

Storage is two lanes plus payload: the time lane (``INF_TIME`` ⇔ slot free —
there is no separate valid lane) and a *packed meta* lane holding
kind/flags/src/dst/gen in one int32. The queue is rewritten wholesale every
step (functional update under ``vmap``), so queue bytes/slot directly set
the engine's HBM traffic — packing the five meta fields and dropping the
valid lane cuts that by ~35% vs one-lane-per-field. Width limits (asserted
at :func:`~madsim_tpu.engine.core.DeviceEngine.init` time): kind < 64,
flags < 4, src/dst < 256 nodes, and generations compare modulo 256
(``GEN_MASK``) — a node must be killed 256 times within one pending timer's
lifetime to alias, far beyond any fault schedule.

Tie-break: equal deadlines pop in *slot order*, and freed slots are reused
lowest-first, so the order is deterministic but not FIFO — the host engine
breaks ties by insertion sequence instead. Schedules are engine-specific;
determinism-per-seed is the contract (see engine/__init__ docstring).
An event scheduled exactly at ``INF_TIME`` (delay saturation) is dropped at
push time — it could never fire before any time limit anyway.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp

from .lanes import onehot, sel, sel_many

INF_TIME = jnp.int32(2**31 - 1)

# Event flag bits.
FLAG_TIMER = 1  # gen-checked against the destination node's generation
FLAG_FAULT = 2  # engine-handled fault-injection event (kind = fault op)

# Generation comparisons wrap at this mask (8 packed bits).
GEN_MASK = 0xFF


def pack_meta(kind, flags, src, dst, gen) -> jnp.ndarray:
    """kind[0:6] | flags[6:8] | src[8:16] | dst[16:24] | gen[24:32]."""
    return ((kind & 0x3F) | ((flags & 0x3) << 6) | ((src & 0xFF) << 8)
            | ((dst & 0xFF) << 16) | ((gen & 0xFF) << 24)).astype(jnp.int32)


def unpack_meta(meta):
    """→ (kind, flags, src, dst, gen), each int32."""
    return (meta & 0x3F, (meta >> 6) & 0x3, (meta >> 8) & 0xFF,
            (meta >> 16) & 0xFF, (meta >> 24) & 0xFF)


class Event(NamedTuple):
    """One scheduled occurrence. All fields int32; payload is (P,) int32."""

    time: jnp.ndarray
    kind: jnp.ndarray
    flags: jnp.ndarray
    src: jnp.ndarray
    dst: jnp.ndarray
    gen: jnp.ndarray
    payload: jnp.ndarray

    @staticmethod
    def make(time, kind, payload_words: int, flags=0, src=0, dst=0, gen=0,
             payload=()) -> "Event":
        """Build a concrete event, zero-padding the payload to P words."""
        pad = list(payload) + [0] * (payload_words - len(payload))
        return Event(
            time=jnp.asarray(time, jnp.int32),
            kind=jnp.asarray(kind, jnp.int32),
            flags=jnp.asarray(flags, jnp.int32),
            src=jnp.asarray(src, jnp.int32),
            dst=jnp.asarray(dst, jnp.int32),
            gen=jnp.asarray(gen, jnp.int32),
            payload=jnp.asarray(pad, jnp.int32),
        )


class EventQueue(NamedTuple):
    """Struct-of-arrays event store: time/meta are (Q,), payload is (Q, P).
    A slot is free ⇔ its time is ``INF_TIME``; meta packs the five scalar
    fields (:func:`pack_meta`)."""

    time: jnp.ndarray
    meta: jnp.ndarray
    payload: jnp.ndarray


def empty_queue(capacity: int, payload_words: int) -> EventQueue:
    return EventQueue(
        time=jnp.full((capacity,), INF_TIME, jnp.int32),
        meta=jnp.zeros((capacity,), jnp.int32),
        payload=jnp.zeros((capacity, payload_words), jnp.int32),
    )


def valid_mask(q: EventQueue) -> jnp.ndarray:
    """(Q,) bool: which slots hold a pending event."""
    return q.time != INF_TIME


def depth(q: EventQueue) -> jnp.ndarray:
    """Number of pending events."""
    return jnp.sum(valid_mask(q).astype(jnp.int32))


def push(q: EventQueue, ev: Event, enable=True) -> Tuple[EventQueue, jnp.ndarray]:
    """Insert ``ev`` into the first free slot. Returns (queue, ok).

    ``enable`` masks the push (False ⇒ no-op, ok=True) so callers can keep a
    single static code path for conditional sends. ok=False ⇒ overflow.
    An event with time == INF_TIME is dropped (ok=True): it could never
    fire, and storing it would alias the free-slot sentinel.

    Scatter-free: the slot is addressed by a one-hot mask so the whole
    insert is elementwise over the Q lanes and fuses under vmap (see
    engine/lanes.py for why this beats ``.at[slot].set`` on TPU).
    """
    enable = jnp.asarray(enable, bool) & (jnp.asarray(ev.time, jnp.int32)
                                          < INF_TIME)
    free = q.time == INF_TIME
    free_any = jnp.any(free)
    # First free slot: one-hot of the argmax over free (first True).
    mask = onehot(jnp.argmax(free), q.time.shape[0])
    do = mask & enable & free_any
    ok = ~enable | free_any
    q = EventQueue(
        time=jnp.where(do, jnp.asarray(ev.time, jnp.int32), q.time),
        meta=jnp.where(do, pack_meta(ev.kind, ev.flags, ev.src, ev.dst,
                                     ev.gen), q.meta),
        payload=jnp.where(do[:, None], ev.payload[None, :], q.payload),
    )
    return q, ok


def pop(q: EventQueue, eligible=None) -> Tuple[EventQueue, Event, jnp.ndarray]:
    """Remove and return the earliest valid event. Returns (queue, ev, found).

    When the queue is empty, ``found`` is False and the event contents are
    arbitrary (time INF_TIME) — callers must mask on ``found``.

    ``eligible`` (optional (Q,) bool) masks slots out of *this* pop without
    disturbing them: ineligible events stay queued at their original time.
    This is how node pause buffers deliveries on the device — events to a
    paused node are skipped until resume clears the mask, then flush in
    (time, slot) order (`task.rs:243-261` park/unpark analog). With every
    slot ineligible, ``found`` is False even for a non-empty queue.

    Scatter/gather-free: the min slot is read back via a one-hot masked
    reduction and cleared via an elementwise select.
    """
    times = q.time if eligible is None else jnp.where(eligible, q.time,
                                                      INF_TIME)
    slot = jnp.argmin(times)
    mask = onehot(slot, q.time.shape[0])
    tmin = jnp.min(times)
    found = tmin < INF_TIME
    kind, flags, src, dst, gen = unpack_meta(sel(q.meta, slot))
    ev = Event(
        time=tmin, kind=kind, flags=flags, src=src, dst=dst, gen=gen,
        payload=sel(q.payload, slot),
    )
    q = q._replace(time=jnp.where(mask & found, INF_TIME, q.time))
    return q, ev, found


def next_deadline(q: EventQueue) -> jnp.ndarray:
    """Earliest pending time, or INF_TIME when empty."""
    return jnp.min(q.time)


def eligible_mask(q: EventQueue, paused, n_nodes: int) -> jnp.ndarray:
    """(Q,) pop-eligibility under node pause: events to a paused node are
    buffered (skipped in place); faults always fire — the matching resume
    must be able to reach the paused node. Lives here, next to
    pack_meta/unpack_meta, so the bit layout has exactly one home."""
    _kind, flags_q, _src, dst_q, _gen = unpack_meta(q.meta)
    dst_q = jnp.clip(dst_q, 0, n_nodes - 1)
    is_fault_q = (flags_q & FLAG_FAULT) != 0
    return is_fault_q | ~sel_many(paused, dst_q)
