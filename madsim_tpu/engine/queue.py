"""Fixed-capacity masked event queue (per world; vmapped over the seed axis).

The device analog of the host timer wheel + NetSim delivery queue
(`madsim/src/sim/time/mod.rs:159-214`, `net/mod.rs:173-197`): every pending
future occurrence in a world — timer expiry, message delivery, fault
injection — is one slot in a flat array. ``pop`` is a masked argmin over the
time lane (a single vectorized reduction, which is exactly the shape TPUs
like); ``push`` fills the first free slot, and ``push_many`` inserts a whole
outbox of events in one fused pass (bitwise identical to chained pushes —
see its docstring). No pointer heap: priority order is recomputed per pop,
which for capacities ~64-256 is cheaper on TPU than maintaining heap
invariants with data-dependent control flow.

Storage is two lanes plus payload: the time lane (``INF_TIME`` ⇔ slot free —
there is no separate valid lane) and a *packed meta* lane holding
kind/flags/src/dst/gen in one int32. Since round 7 the per-step update is a
sparse in-place one — ``push_many`` scatters M rows and, under the run
loop's buffer donation, XLA aliases the queue in place — but the lanes are
still read wholesale every step (pop's min, the free mask), so queue
bytes/slot
remain the engine's HBM-traffic knob — packing the five meta fields and
dropping the valid lane cuts that by ~35% vs one-lane-per-field. Width
limits (asserted
at :func:`~madsim_tpu.engine.core.DeviceEngine.init` time): kind < 64,
flags < 4, src/dst < 256 nodes, and generations compare modulo 256
(``GEN_MASK``) — a node must be killed 256 times within one pending timer's
lifetime to alias, far beyond any fault schedule.

Tie-break: equal deadlines pop in *slot order*, and freed slots are reused
lowest-first, so the order is deterministic but not FIFO — the host engine
breaks ties by insertion sequence instead. Schedules are engine-specific;
determinism-per-seed is the contract (see engine/__init__ docstring).
An event scheduled exactly at ``INF_TIME`` (delay saturation) is dropped at
push time — it could never fire before any time limit anyway.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from .lanes import narrow, onehot, prefix_count, take_small, widen

INF_TIME = jnp.int32(2**31 - 1)

# Event flag bits.
FLAG_TIMER = 1  # gen-checked against the destination node's generation
FLAG_FAULT = 2  # engine-handled fault-injection event (kind = fault op)

# Generation comparisons wrap at this mask (8 packed bits).
GEN_MASK = 0xFF


def pack_meta(kind, flags, src, dst, gen) -> jnp.ndarray:
    """kind[0:6] | flags[6:8] | src[8:16] | dst[16:24] | gen[24:32]."""
    return ((kind & 0x3F) | ((flags & 0x3) << 6) | ((src & 0xFF) << 8)
            | ((dst & 0xFF) << 16) | ((gen & 0xFF) << 24)).astype(jnp.int32)


def unpack_meta(meta):
    """→ (kind, flags, src, dst, gen), each int32."""
    return (meta & 0x3F, (meta >> 6) & 0x3, (meta >> 8) & 0xFF,
            (meta >> 16) & 0xFF, (meta >> 24) & 0xFF)


class Event(NamedTuple):
    """One scheduled occurrence. All fields int32; payload is (P,) int32."""

    time: jnp.ndarray
    kind: jnp.ndarray
    flags: jnp.ndarray
    src: jnp.ndarray
    dst: jnp.ndarray
    gen: jnp.ndarray
    payload: jnp.ndarray

    @staticmethod
    def make(time, kind, payload_words: int, flags=0, src=0, dst=0, gen=0,
             payload=()) -> "Event":
        """Build a concrete event, zero-padding the payload to P words."""
        pad = list(payload) + [0] * (payload_words - len(payload))
        return Event(
            time=jnp.asarray(time, jnp.int32),
            kind=jnp.asarray(kind, jnp.int32),
            flags=jnp.asarray(flags, jnp.int32),
            src=jnp.asarray(src, jnp.int32),
            dst=jnp.asarray(dst, jnp.int32),
            gen=jnp.asarray(gen, jnp.int32),
            payload=jnp.asarray(pad, jnp.int32),
        )


class EventQueue(NamedTuple):
    """Struct-of-arrays event store: time/meta are (Q,), payload is (Q, P).
    A slot is free ⇔ its time is ``INF_TIME``; meta packs the five scalar
    fields (:func:`pack_meta`)."""

    time: jnp.ndarray
    meta: jnp.ndarray
    payload: jnp.ndarray


def empty_queue(capacity: int, payload_words: int,
                payload_dtype=jnp.int32) -> EventQueue:
    """``payload_dtype``: the at-rest payload lane dtype — int16 under
    the packed profile (``EngineConfig.lanes``), int32 in the reference
    path and for standalone callers. The time and meta lanes are always
    int32 (time is a wide lane; meta is already bit-packed)."""
    return EventQueue(
        time=jnp.full((capacity,), INF_TIME, jnp.int32),
        meta=jnp.zeros((capacity,), jnp.int32),
        payload=jnp.zeros((capacity, payload_words), payload_dtype),
    )


def valid_mask(q: EventQueue) -> jnp.ndarray:
    """(Q,) bool: which slots hold a pending event."""
    return q.time != INF_TIME


def depth(q: EventQueue) -> jnp.ndarray:
    """Number of pending events. dtype pinned: under jax_enable_x64 an
    unpinned integer sum accumulates as int64, which would fork the
    metrics lane dtype between init-built and refill-built worlds."""
    return jnp.sum(valid_mask(q), dtype=jnp.int32)


def push(q: EventQueue, ev: Event, enable=True) -> Tuple[EventQueue, jnp.ndarray]:
    """Insert ``ev`` into the first free slot. Returns (queue, ok).

    ``enable`` masks the push (False ⇒ no-op, ok=True) so callers can keep a
    single static code path for conditional sends. ok=False ⇒ overflow.
    An event with time == INF_TIME is dropped (ok=True): it could never
    fire, and storing it would alias the free-slot sentinel.

    Scatter-free: the slot is addressed by a one-hot mask so the whole
    insert is elementwise over the Q lanes and fuses under vmap (see
    engine/lanes.py for why this beats ``.at[slot].set`` on TPU).
    """
    enable = jnp.asarray(enable, bool) & (jnp.asarray(ev.time, jnp.int32)
                                          < INF_TIME)
    free = q.time == INF_TIME
    free_any = jnp.any(free)
    # First free slot: one-hot of the argmax over free (first True).
    mask = onehot(jnp.argmax(free), q.time.shape[0])
    do = mask & enable & free_any
    ok = ~enable | free_any
    q = EventQueue(
        time=jnp.where(do, jnp.asarray(ev.time, jnp.int32), q.time),
        meta=jnp.where(do, pack_meta(ev.kind, ev.flags, ev.src, ev.dst,
                                     ev.gen), q.meta),
        # In-flight payloads are int32; the write saturates into the
        # at-rest lane dtype (a no-op cast on the wide profile).
        payload=jnp.where(do[:, None],
                          narrow(ev.payload, q.payload.dtype)[None, :],
                          q.payload),
    )
    return q, ok


def push_many(q: EventQueue, evs: Event, enable=None,
              clear=None) -> Tuple[EventQueue, jnp.ndarray, jnp.ndarray]:
    """Insert up to M events in ONE pass over the queue lanes.
    Returns ``(queue, ok, n_inserted)``; ``ok`` is (M,) bool per event.

    ``evs`` is a batched :class:`Event` (every field carries a leading
    (M,) axis; payload is (M, P)); ``enable`` an optional (M,) bool mask.
    Semantics are **bitwise identical** to the sequential chain
    ``for i in range(M): q, ok[i] = push(q, evs[i], enable[i])`` — the
    contract the engine's trajectory-equivalence tests pin
    (tests/test_queue_insert.py, via ``EngineConfig.sequential_insert``):

    - events keep their order: the i-th *enabled* event (after the
      time < INF_TIME drop filter) lands in the i-th lowest free slot;
    - overflow matches: once the free slots run out, every remaining
      enabled event reports ok=False and writes nothing;
    - an event at INF_TIME is dropped (ok=True) and consumes no slot.

    Why one pass: each sequential ``push`` recomputes the free mask, an
    argmax and a one-hot, then rewrites all three lanes — M·Q·(2+P)
    selects per call site, the single largest int-op consumer in the
    step (docs/perf.md "Single-pass insert"). Here the assignment is
    closed-form — the i-th enabled event's cumulative-sum *rank* names
    the free slot it gets — so the insert is M row writes, not M lane
    rewrites: the free mask packs into Q/32 uint32 words, each rank's
    target slot is the word's lowest set bit (clear-lowest-bit +
    ``population_count``, a handful of scalar ops per rank), and the
    compacted events scatter into those slots. With the run loop's
    buffer donation the scatter updates the queue in place: per step the
    queue costs M·(2+P) element writes instead of Q·(2+P). (The first
    build used the issue's (Q,)-gather-driven select; measurement moved
    it to this scatter form — the batched gather materializes a (Q, 2)
    index buffer per world that dominated peak temp memory, while the
    scatter's index buffer is (M, 2). Same rank assignment either way,
    and the M-row scatter is also strictly less write traffic.)

    ``clear``: optional ``(slot, found)`` from :func:`pop_indexed` over
    THIS ``q``. When given, slot ``slot`` is treated as freed (and its
    time lane rewritten to INF unless re-filled) — i.e. the result equals
    pushing into the pop-cleared queue. The step uses this to fuse the
    pop's clear into the insert's own scatter pass, so the pop never
    rewrites the time lane at all: routing the cleared lane through a
    separate elementwise write makes CPU XLA clone the whole pop chain
    into every downstream reader of the free mask (measured ~2×
    over-pricing of the insert, docs/perf.md r7).
    """
    m = evs.time.shape[0]
    qcap = q.time.shape[0]
    t = jnp.asarray(evs.time, jnp.int32)
    en = jnp.ones((m,), bool) if enable is None else jnp.asarray(enable, bool)
    en = en & (t < INF_TIME)
    # rank[i]: how many enabled events precede i == which free slot (in
    # lowest-first order) the sequential chain would hand event i.
    rank = prefix_count(en)
    base_time = q.time
    free = base_time == INF_TIME
    if clear is not None:
        cslot, cfound = clear
        free = free | (onehot(cslot, qcap) & cfound)
        base_time = base_time.at[jnp.where(cfound, cslot, qcap)].set(
            INF_TIME, mode="drop")
    # Pack the free mask into uint32 words: bit s of word w ⇔ slot
    # 32w + s is free. Everything below runs on these scalars.
    words = []
    for w in range((qcap + 31) // 32):
        lanes = min(32, qcap - 32 * w)
        pow2 = jnp.asarray(np.uint32(1) << np.arange(lanes, dtype=np.uint32),
                           jnp.uint32)
        words.append(jnp.sum(jnp.where(free[32 * w:32 * w + lanes], pow2,
                                       jnp.uint32(0))))
    n_free = sum(lax.population_count(w).astype(jnp.int32) for w in words)
    n_en = rank[-1] + en[-1].astype(jnp.int32)
    ok = ~en | (rank < n_free)
    # Order-preserving compaction of the enabled events to the front:
    # row r of the compacted table is the event with rank r. The (M, M)
    # one-hot collapses to an M-long *index* vector and the field tables
    # are gathered rows (tiny-source gathers, lanes.take_small).
    cm = en[None, :] & (rank[None, :] == jnp.arange(m)[:, None])
    ev_idx = jnp.sum(jnp.where(cm, jnp.arange(m)[None, :], 0), axis=1)
    meta = pack_meta(evs.kind, evs.flags, evs.src, evs.dst, evs.gen)
    ct = take_small(t, ev_idx)
    cmeta = take_small(meta, ev_idx)
    cpay = take_small(evs.payload, ev_idx)
    # Target slot of rank r = lowest set bit still standing; clear it and
    # move on. Ranks past n_en aim at slot Q and are dropped.
    slots = []
    for r in range(m):
        pos = jnp.int32(qcap)
        placed = jnp.asarray(False)
        nxt = []
        for wi, w in enumerate(words):
            lsb = w & (~w + jnp.uint32(1))
            p = lax.population_count(lsb - jnp.uint32(1)).astype(jnp.int32) \
                + 32 * wi
            use = ~placed & (w != 0)
            pos = jnp.where(use, p, pos)
            nxt.append(jnp.where(use, w & (w - jnp.uint32(1)), w))
            placed = placed | use
        words = nxt
        slots.append(jnp.where(r < n_en, pos, qcap))
    slots = jnp.stack(slots)
    # Slots are distinct (dropped ranks all aim at the same out-of-range
    # Q, which "drop" discards), so the scatters are order-independent;
    # XLA chains the clear scatter and this one through a single buffer.
    q = EventQueue(
        time=base_time.at[slots].set(ct, mode="drop"),
        meta=q.meta.at[slots].set(cmeta, mode="drop"),
        # Saturating narrow at the scatter boundary (packed payload
        # lane); engine-split wide params (lanes.split_wide) are in
        # range by construction, so the clip never bites them.
        payload=q.payload.at[slots].set(narrow(cpay, q.payload.dtype),
                                        mode="drop"),
    )
    return q, ok, jnp.minimum(n_en, n_free)


def insert_metrics(times, enable, n_inserted):
    """Insert-path counters for the observability block
    (:mod:`madsim_tpu.obs.metrics`): given the push batch's ``times`` and
    ``enable`` mask plus the count actually inserted (``push_many``'s
    ``n_inserted``, or a carried-depth delta on the sequential path),
    return ``(n_requested, n_inf_dropped, n_overflow)`` — attempts,
    deadline-saturated drops (the INF_TIME contract above), and inserts
    refused by a full queue. Lives here, next to the INF/overflow
    semantics it mirrors, so the drop taxonomy has exactly one home.
    Pure bookkeeping: never feeds the insert itself (the bitwise-
    invisibility contract of metrics-on runs).
    """
    en = jnp.asarray(enable, bool)
    # dtype-pinned sums: a bare jnp.sum would widen to i64 under the x64
    # flag, leaking a process setting into the metrics dtypes (TRC003).
    n_req = jnp.sum(en, dtype=jnp.int32)
    n_inf = jnp.sum(en & (jnp.asarray(times, jnp.int32) >= INF_TIME),
                    dtype=jnp.int32)
    return n_req, n_inf, n_req - n_inf - jnp.asarray(n_inserted, jnp.int32)


def pop_indexed(q: EventQueue, eligible=None
                ) -> Tuple[EventQueue, Event, jnp.ndarray, jnp.ndarray]:
    """:func:`pop` that also returns the popped ``slot`` index, so the
    caller can hand ``(slot, found)`` to :func:`push_many`'s ``clear``
    and fuse the clear into the insert's single time-lane write (the
    engine step does; the returned queue is then dead code and XLA drops
    its redundant clear write)."""
    times = q.time if eligible is None else jnp.where(eligible, q.time,
                                                      INF_TIME)
    n = q.time.shape[0]
    tmin = jnp.min(times)
    found = tmin < INF_TIME
    # First slot holding the min — argmin's first-occurrence tie-break,
    # but min-priced: argmin's tuple comparator costs ~8 flops/element,
    # while "max of (n-1-slot) over the min positions" is a where + max.
    slot = (n - 1) - jnp.max(jnp.where(times == tmin,
                                       (n - 1) - jnp.arange(n), -1))
    mask = onehot(slot, n)
    kind, flags, src, dst, gen = unpack_meta(take_small(q.meta, slot))
    ev = Event(
        time=tmin, kind=kind, flags=flags, src=src, dst=dst, gen=gen,
        # Wide in flight: the popped row is widened back to int32 here
        # (lanes.widen — one (P,)-sized convert per step), so handlers
        # and apply_fault never see a narrow payload.
        payload=widen(take_small(q.payload, slot)),
    )
    q = q._replace(time=jnp.where(mask & found, INF_TIME, q.time))
    return q, ev, found, slot


def pop(q: EventQueue, eligible=None) -> Tuple[EventQueue, Event, jnp.ndarray]:
    """Remove and return the earliest valid event. Returns (queue, ev, found).

    When the queue is empty, ``found`` is False and the event contents are
    arbitrary (time INF_TIME) — callers must mask on ``found``.

    ``eligible`` (optional (Q,) bool) masks slots out of *this* pop without
    disturbing them: ineligible events stay queued at their original time.
    This is how node pause buffers deliveries on the device — events to a
    paused node are skipped until resume clears the mask, then flush in
    (time, slot) order (`task.rs:243-261` park/unpark analog). With every
    slot ineligible, ``found`` is False even for a non-empty queue.

    Scatter-free: the min slot comes from an argmin (first-occurrence
    tie-break), the clear is an elementwise select, and the meta/payload
    read-back is a single-row gather at that slot
    (:func:`~madsim_tpu.engine.lanes.take_small` — one element per world,
    priced at zero by the cost model, vs 2 ops/element over the whole
    meta+payload footprint for the old one-hot masked reduction). When
    the queue is empty the gathered row is arbitrary — covered by the
    "mask on ``found``" contract above.
    """
    q, ev, found, _slot = pop_indexed(q, eligible)
    return q, ev, found


def next_deadline(q: EventQueue) -> jnp.ndarray:
    """Earliest pending time, or INF_TIME when empty."""
    return jnp.min(q.time)


def eligible_mask(q: EventQueue, paused, n_nodes: int) -> jnp.ndarray:
    """(Q,) pop-eligibility under node pause: events to a paused node are
    buffered (skipped in place); faults always fire — the matching resume
    must be able to reach the paused node. Lives here, next to
    pack_meta/unpack_meta, so the bit layout has exactly one home.

    Reads the two needed fields straight off the packed bits (one masked
    compare for the fault flag) instead of a full :func:`unpack_meta` —
    this runs over the whole (Q,) meta lane every step."""
    is_fault_q = (q.meta & jnp.int32(FLAG_FAULT << 6)) != 0
    dst_q = (q.meta >> 16) & 0xFF  # take_small clamps to [0, n_nodes)
    del n_nodes
    return is_fault_q | ~take_small(paused, dst_q)
