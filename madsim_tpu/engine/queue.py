"""Fixed-capacity masked event queue (per world; vmapped over the seed axis).

The device analog of the host timer wheel + NetSim delivery queue
(`madsim/src/sim/time/mod.rs:159-214`, `net/mod.rs:173-197`): every pending
future occurrence in a world — timer expiry, message delivery, fault
injection — is one slot in a flat array. ``pop`` is a masked argmin over the
time lane (a single vectorized reduction, which is exactly the shape TPUs
like); ``push`` scatters into the first free slot. No pointer heap: priority
order is recomputed per pop, which for capacities ~64-256 is cheaper on TPU
than maintaining heap invariants with data-dependent control flow.

Tie-break: equal deadlines pop in *slot order*, and freed slots are reused
lowest-first, so the order is deterministic but not FIFO — the host engine
breaks ties by insertion sequence instead. Schedules are engine-specific;
determinism-per-seed is the contract (see engine/__init__ docstring).
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp

INF_TIME = jnp.int32(2**31 - 1)

# Event flag bits.
FLAG_TIMER = 1  # gen-checked against the destination node's generation
FLAG_FAULT = 2  # engine-handled fault-injection event (kind = fault op)


class Event(NamedTuple):
    """One scheduled occurrence. All fields int32; payload is (P,) int32."""

    time: jnp.ndarray
    kind: jnp.ndarray
    flags: jnp.ndarray
    src: jnp.ndarray
    dst: jnp.ndarray
    gen: jnp.ndarray
    payload: jnp.ndarray

    @staticmethod
    def make(time, kind, payload_words: int, flags=0, src=0, dst=0, gen=0,
             payload=()) -> "Event":
        """Build a concrete event, zero-padding the payload to P words."""
        pad = list(payload) + [0] * (payload_words - len(payload))
        return Event(
            time=jnp.asarray(time, jnp.int32),
            kind=jnp.asarray(kind, jnp.int32),
            flags=jnp.asarray(flags, jnp.int32),
            src=jnp.asarray(src, jnp.int32),
            dst=jnp.asarray(dst, jnp.int32),
            gen=jnp.asarray(gen, jnp.int32),
            payload=jnp.asarray(pad, jnp.int32),
        )


class EventQueue(NamedTuple):
    """Struct-of-arrays event store: scalars are (Q,), payload is (Q, P)."""

    time: jnp.ndarray
    kind: jnp.ndarray
    flags: jnp.ndarray
    src: jnp.ndarray
    dst: jnp.ndarray
    gen: jnp.ndarray
    payload: jnp.ndarray
    valid: jnp.ndarray  # (Q,) bool


def empty_queue(capacity: int, payload_words: int) -> EventQueue:
    z = jnp.zeros((capacity,), jnp.int32)
    return EventQueue(
        time=jnp.full((capacity,), INF_TIME, jnp.int32),
        kind=z, flags=z, src=z, dst=z, gen=z,
        payload=jnp.zeros((capacity, payload_words), jnp.int32),
        valid=jnp.zeros((capacity,), bool),
    )


def push(q: EventQueue, ev: Event, enable=True) -> Tuple[EventQueue, jnp.ndarray]:
    """Insert ``ev`` into the first free slot. Returns (queue, ok).

    ``enable`` masks the push (False ⇒ no-op, ok=True) so callers can keep a
    single static code path for conditional sends. ok=False ⇒ overflow.
    """
    enable = jnp.asarray(enable, bool)
    # First free slot: argmin over valid (False < True).
    slot = jnp.argmin(q.valid)
    free = ~q.valid[slot]
    do = enable & free
    ok = ~enable | free

    def put(lane, value):
        return lane.at[slot].set(jnp.where(do, value, lane[slot]))

    q = EventQueue(
        time=put(q.time, ev.time),
        kind=put(q.kind, ev.kind),
        flags=put(q.flags, ev.flags),
        src=put(q.src, ev.src),
        dst=put(q.dst, ev.dst),
        gen=put(q.gen, ev.gen),
        payload=q.payload.at[slot].set(
            jnp.where(do, ev.payload, q.payload[slot])),
        valid=put(q.valid, jnp.asarray(True)),
    )
    return q, ok


def pop(q: EventQueue) -> Tuple[EventQueue, Event, jnp.ndarray]:
    """Remove and return the earliest valid event. Returns (queue, ev, found).

    When the queue is empty, ``found`` is False and the event contents are
    arbitrary (time INF_TIME) — callers must mask on ``found``.
    """
    keyed = jnp.where(q.valid, q.time, INF_TIME)
    slot = jnp.argmin(keyed)
    found = q.valid[slot]
    ev = Event(
        time=keyed[slot],
        kind=q.kind[slot],
        flags=q.flags[slot],
        src=q.src[slot],
        dst=q.dst[slot],
        gen=q.gen[slot],
        payload=q.payload[slot],
    )
    q = q._replace(
        valid=q.valid.at[slot].set(jnp.where(found, False, q.valid[slot])),
        time=q.time.at[slot].set(jnp.where(found, INF_TIME, q.time[slot])),
    )
    return q, ev, found


def next_deadline(q: EventQueue) -> jnp.ndarray:
    """Earliest pending time, or INF_TIME when empty."""
    return jnp.min(jnp.where(q.valid, q.time, INF_TIME))
