"""Fixed-capacity masked event queue (per world; vmapped over the seed axis).

The device analog of the host timer wheel + NetSim delivery queue
(`madsim/src/sim/time/mod.rs:159-214`, `net/mod.rs:173-197`): every pending
future occurrence in a world — timer expiry, message delivery, fault
injection — is one slot in a flat array. ``pop`` is a masked argmin over the
time lane (a single vectorized reduction, which is exactly the shape TPUs
like); ``push`` scatters into the first free slot. No pointer heap: priority
order is recomputed per pop, which for capacities ~64-256 is cheaper on TPU
than maintaining heap invariants with data-dependent control flow.

Tie-break: equal deadlines pop in *slot order*, and freed slots are reused
lowest-first, so the order is deterministic but not FIFO — the host engine
breaks ties by insertion sequence instead. Schedules are engine-specific;
determinism-per-seed is the contract (see engine/__init__ docstring).
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp

from .lanes import onehot, sel

INF_TIME = jnp.int32(2**31 - 1)

# Event flag bits.
FLAG_TIMER = 1  # gen-checked against the destination node's generation
FLAG_FAULT = 2  # engine-handled fault-injection event (kind = fault op)


class Event(NamedTuple):
    """One scheduled occurrence. All fields int32; payload is (P,) int32."""

    time: jnp.ndarray
    kind: jnp.ndarray
    flags: jnp.ndarray
    src: jnp.ndarray
    dst: jnp.ndarray
    gen: jnp.ndarray
    payload: jnp.ndarray

    @staticmethod
    def make(time, kind, payload_words: int, flags=0, src=0, dst=0, gen=0,
             payload=()) -> "Event":
        """Build a concrete event, zero-padding the payload to P words."""
        pad = list(payload) + [0] * (payload_words - len(payload))
        return Event(
            time=jnp.asarray(time, jnp.int32),
            kind=jnp.asarray(kind, jnp.int32),
            flags=jnp.asarray(flags, jnp.int32),
            src=jnp.asarray(src, jnp.int32),
            dst=jnp.asarray(dst, jnp.int32),
            gen=jnp.asarray(gen, jnp.int32),
            payload=jnp.asarray(pad, jnp.int32),
        )


class EventQueue(NamedTuple):
    """Struct-of-arrays event store: scalars are (Q,), payload is (Q, P)."""

    time: jnp.ndarray
    kind: jnp.ndarray
    flags: jnp.ndarray
    src: jnp.ndarray
    dst: jnp.ndarray
    gen: jnp.ndarray
    payload: jnp.ndarray
    valid: jnp.ndarray  # (Q,) bool


def empty_queue(capacity: int, payload_words: int) -> EventQueue:
    z = jnp.zeros((capacity,), jnp.int32)
    return EventQueue(
        time=jnp.full((capacity,), INF_TIME, jnp.int32),
        kind=z, flags=z, src=z, dst=z, gen=z,
        payload=jnp.zeros((capacity, payload_words), jnp.int32),
        valid=jnp.zeros((capacity,), bool),
    )


def push(q: EventQueue, ev: Event, enable=True) -> Tuple[EventQueue, jnp.ndarray]:
    """Insert ``ev`` into the first free slot. Returns (queue, ok).

    ``enable`` masks the push (False ⇒ no-op, ok=True) so callers can keep a
    single static code path for conditional sends. ok=False ⇒ overflow.

    Scatter-free: the slot is addressed by a one-hot mask so the whole
    insert is elementwise over the Q lanes and fuses under vmap (see
    engine/lanes.py for why this beats ``.at[slot].set`` on TPU).
    """
    enable = jnp.asarray(enable, bool)
    free_any = ~jnp.all(q.valid)
    # First free slot: one-hot of the argmin over valid (False < True).
    mask = onehot(jnp.argmin(q.valid), q.valid.shape[0])
    do = mask & enable & free_any
    ok = ~enable | free_any

    def put(lane, value):
        return jnp.where(do, jnp.asarray(value, lane.dtype), lane)

    q = EventQueue(
        time=put(q.time, ev.time),
        kind=put(q.kind, ev.kind),
        flags=put(q.flags, ev.flags),
        src=put(q.src, ev.src),
        dst=put(q.dst, ev.dst),
        gen=put(q.gen, ev.gen),
        payload=jnp.where(do[:, None], ev.payload[None, :], q.payload),
        valid=q.valid | do,
    )
    return q, ok


def pop(q: EventQueue) -> Tuple[EventQueue, Event, jnp.ndarray]:
    """Remove and return the earliest valid event. Returns (queue, ev, found).

    When the queue is empty, ``found`` is False and the event contents are
    arbitrary (time INF_TIME) — callers must mask on ``found``.

    Scatter/gather-free: the min slot is read back via a one-hot masked
    reduction and cleared via an elementwise select.
    """
    keyed = jnp.where(q.valid, q.time, INF_TIME)
    slot = jnp.argmin(keyed)
    mask = onehot(slot, q.valid.shape[0])
    found = jnp.any(mask & q.valid)
    ev = Event(
        time=jnp.where(found, sel(keyed, slot), INF_TIME),
        kind=sel(q.kind, slot),
        flags=sel(q.flags, slot),
        src=sel(q.src, slot),
        dst=sel(q.dst, slot),
        gen=sel(q.gen, slot),
        payload=sel(q.payload, slot),
    )
    clear = mask & found
    q = q._replace(
        valid=q.valid & ~clear,
        time=jnp.where(clear, INF_TIME, q.time),
    )
    return q, ev, found


def next_deadline(q: EventQueue) -> jnp.ndarray:
    """Earliest pending time, or INF_TIME when empty."""
    return jnp.min(jnp.where(q.valid, q.time, INF_TIME))
