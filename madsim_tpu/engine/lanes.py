"""Indexing primitives for the device engine: one-hot writes, tiny gathers.

The doctrine, refined by measurement over two perf rounds
(docs/perf.md):

- **Single-slot writes** (``upd``/``upd2``) stay one-hot mask + select:
  a lone ``x.at[i].set(v)`` with a traced index lowers to a scatter XLA
  cannot fuse, while the mask write fuses into the surrounding kernel
  and vectorizes over the world axis for free.
- **Reads** use real gathers (``take_small``) when the source axis is
  tiny (nodes N ≤ 8, log rows L ≤ 64, outbox M ≤ 8): the one-hot
  contraction costs k·m·width ops per read — measured as one of the
  step's dominant flop consumers — while the gather is priced at ~zero
  and its operand is a state buffer that is materialized anyway.
- **The queue insert** (``queue.push_many``) is the one deliberate
  scatter: M rows, computed slots, in-place under buffer donation — see
  its docstring for why it beats both the unrolled one-hot chain and a
  (Q,)-gather-driven rewrite.

Anything not covered above goes through these helpers rather than raw
``x[i]`` / ``.at[i]`` so the layout decisions keep exactly one home.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax


def onehot(i, n: int) -> jnp.ndarray:
    """(n,) bool mask selecting index ``i``.

    Out-of-range ``i`` selects *nothing* (drop semantics: sel yields 0/False,
    upd is a no-op) — unlike jit-mode ``x[i]``, which clamps to the edge.
    Callers with possibly-wild indices must clip first.
    """
    return jnp.arange(n) == jnp.asarray(i, jnp.int32)


def _shaped(mask: jnp.ndarray, ndim: int) -> jnp.ndarray:
    """Reshape a (n,) mask to broadcast over trailing dims of an ndim array."""
    return mask.reshape(mask.shape + (1,) * (ndim - 1))


def sel(x: jnp.ndarray, i) -> jnp.ndarray:
    """``x[i]`` over axis 0 without a gather. x: (n, ...) → (...)."""
    m = _shaped(onehot(i, x.shape[0]), x.ndim)
    if x.dtype == jnp.bool_:
        return jnp.any(x & m, axis=0)
    return jnp.sum(jnp.where(m, x, 0), axis=0).astype(x.dtype)


def sel2(x: jnp.ndarray, i, j) -> jnp.ndarray:
    """``x[i, j]`` over the two leading axes. x: (n, m, ...) → (...)."""
    return sel(sel(x, i), j)


def sel_many(x: jnp.ndarray, idxs: jnp.ndarray) -> jnp.ndarray:
    """``x[idxs]`` for a 1-D ``x`` and a vector of indices, gather-free.

    x: (n,), idxs: (k,) → (k,). The (k, n) one-hot matrix contracts over n;
    for the engine's tiny n this fuses into the surrounding elementwise work.
    """
    m = jnp.arange(x.shape[0])[None, :] == idxs[:, None]
    return jnp.sum(jnp.where(m, x[None, :], 0), axis=1).astype(x.dtype)


def prefix_count(mask: jnp.ndarray) -> jnp.ndarray:
    """Exclusive prefix count: how many True lanes strictly precede each
    lane.

    For the engine's queue widths (n ≤ 64) the mask packs into one or
    two uint32 words (a ``where`` against the constant powers-of-two
    vector + a sum); each lane then ANDs the word with a *constant*
    below-me bitmask and ``population_count``s it. Two subtleties make
    this the cheapest form in practice, not just on paper:

    - XLA CPU *clones* elementwise producer chains into every consumer
      fusion, so the chain is pinned behind an identity gather (a
      materialization point fusion cannot clone through) — without it,
      the queue's three lane writes would each re-price the whole
      prefix (docs/perf.md r7).
    - The alternative, ``jnp.cumsum``, prices flat but its hierarchical
      scan lowering allocates ~1 KB/world of scratch inside the step —
      the difference between fitting 1.2× state in peak memory and not.

    Larger n falls back to ``jnp.cumsum`` (the word trick scales as
    n·(n/32) and stops winning past two words).
    """
    n = mask.shape[0]
    if n > 64:
        inc = jnp.cumsum(mask.astype(jnp.int32))
        return jnp.concatenate([jnp.zeros((1,), jnp.int32), inc[:-1]])
    counts = jnp.zeros((n,), jnp.int32)
    for w in range((n + 31) // 32):
        lanes = min(32, n - 32 * w)
        pow2 = jnp.asarray(np.uint32(1) << np.arange(lanes, dtype=np.uint32),
                           jnp.uint32)
        word = jnp.sum(jnp.where(mask[32 * w:32 * w + lanes], pow2,
                                 jnp.uint32(0)))
        # below[s]: bits of word w strictly below lane s (zero before the
        # word, all-ones once past it) — a host-built constant vector.
        rel = np.clip(np.arange(n) - 32 * w, 0, 32)
        partial = (np.uint32(1) << np.minimum(rel, 31).astype(np.uint32)) \
            - np.uint32(1)
        below = jnp.asarray(np.where(rel < 32, partial,
                                     np.uint32(0xFFFFFFFF)), jnp.uint32)
        counts = counts + lax.population_count(word & below) \
            .astype(jnp.int32)
    # Identity gather = an explicit materialization point (see docstring).
    return jnp.take(counts, jnp.arange(n), axis=0)


def take_small(x: jnp.ndarray, idxs: jnp.ndarray) -> jnp.ndarray:
    """``x[idxs]`` as a REAL gather — for tiny leading axes only.

    x: (m, ...), idxs: (k,) → (k, ...). The one place the engine prefers a
    gather over a one-hot contraction: when the *source* axis is tiny
    (m ≲ 8, e.g. an outbox-sized table) but the index vector is long
    (k = queue capacity) and the rows are wide (payload words), the
    one-hot select costs k·m·width ops — the very per-slot rewrite cost
    :func:`~madsim_tpu.engine.queue.push_many` exists to eliminate —
    while the gather reads each destination row once.

    Out-of-range indices clamp to the edge ("clip" mode — measured
    cheaper post-fusion than both ``promise_in_bounds``'s at-get lowering
    and "wrap"); callers with possibly-wild indices get edge values and
    must mask the result.
    """
    return jnp.take(x, jnp.asarray(idxs, jnp.int32), axis=0, mode="clip")


def upd(x: jnp.ndarray, i, v) -> jnp.ndarray:
    """``x.at[i].set(v)`` over axis 0 without a scatter."""
    m = _shaped(onehot(i, x.shape[0]), x.ndim)
    return jnp.where(m, jnp.asarray(v, x.dtype), x)


def upd2(x: jnp.ndarray, i, j, v) -> jnp.ndarray:
    """``x.at[i, j].set(v)`` over the two leading axes."""
    m = (_shaped(onehot(i, x.shape[0]), x.ndim)
         & _shaped(onehot(j, x.shape[1]), x.ndim - 1)[None])
    return jnp.where(m, jnp.asarray(v, x.dtype), x)
