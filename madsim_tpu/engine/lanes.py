"""Indexing primitives for the device engine: one-hot writes, tiny gathers.

The doctrine, refined by measurement over two perf rounds
(docs/perf.md):

- **Single-slot writes** (``upd``/``upd2``) stay one-hot mask + select:
  a lone ``x.at[i].set(v)`` with a traced index lowers to a scatter XLA
  cannot fuse, while the mask write fuses into the surrounding kernel
  and vectorizes over the world axis for free.
- **Reads** use real gathers (``take_small``) when the source axis is
  tiny (nodes N ≤ 8, log rows L ≤ 64, outbox M ≤ 8): the one-hot
  contraction costs k·m·width ops per read — measured as one of the
  step's dominant flop consumers — while the gather is priced at ~zero
  and its operand is a state buffer that is materialized anyway.
- **The queue insert** (``queue.push_many``) is the one deliberate
  scatter: M rows, computed slots, in-place under buffer donation — see
  its docstring for why it beats both the unrolled one-hot chain and a
  (Q,)-gather-driven rewrite.

Anything not covered above goes through these helpers rather than raw
``x[i]`` / ``.at[i]`` so the layout decisions keep exactly one home.

Round 2 (docs/perf.md "Roofline round 2") adds the **lane dtype
registry**: most engine lanes carry values that fit 8 or 16 bits —
node ids, role/decision codes, queue slot indices and depths, log
positions, payload words — but historically rode int32, so the step's
HBM traffic (and the worlds-per-chip ceiling) was ~2x what the data
needs. :class:`Lanes` names one dtype per lane *category*; the packed
profile (``EngineConfig(packed=True)``, the default) narrows them,
while virtual time, RNG cursors and unbounded counters stay wide.
Discipline, enforced by tracelint TRC005 on the registered packed
programs:

- **wide in flight, narrow at rest**: queue/outbox events and all
  handler arithmetic stay int32; storage lanes narrow. Every narrow
  *read* is widened HERE (:func:`widen` — the one sanctioned
  narrow-to-wide conversion site), every narrow *write* goes through a
  saturating :func:`narrow` (or the wrapping :func:`narrow_wrap` for
  the mod-256 generation lane), so overflow behavior is explicit at
  every boundary rather than an accident of two's-complement wrap.
- the reference int32 profile stays alive behind
  ``EngineConfig(packed=False)`` for bitwise crosscheck, exactly like
  ``sequential_insert`` does for the fused queue insert.
"""
from __future__ import annotations

from typing import Any, NamedTuple

import jax.numpy as jnp
import numpy as np
from jax import lax


class Lanes(NamedTuple):
    """Dtype registry for the engine's state lanes, by category.

    - ``node``: node ids (``src``/``dst``/``voted_for``; -1 sentinels
      included). Packed: int8 — EngineConfig rejects ``n_nodes > 127``.
    - ``code``: small enumerations — event kinds, fault ops, drop-cause
      codes, role/decision codes, the mod-256 generation lane. Packed:
      int8 (event kinds are already capped at 64 by DeviceEngine).
    - ``slot``: queue slot indices and depths, log indices, terms,
      views, epochs — anything bounded by a capacity knob. Packed:
      int16 — EngineConfig rejects ``queue_cap > 32767``.
    - ``payload``: queue payload words *at rest*. Packed: int16; wide
      values the engine itself stores (net-config fault params) are
      split across two words (:func:`split_wide`/:func:`join_wide`),
      actor payloads saturate at the push boundary.
    - ``time`` / ``counter``: virtual-time microseconds and unbounded
      counters — ALWAYS int32 (as are RNG lanes, uint32). Listed so the
      registry names every category, not just the narrowed ones.

    Bitmask lanes (vote/ack sets, ``won_terms`` words) stay int32 in
    both profiles: their width is the bit capacity, not a value range.
    """

    node: Any
    code: Any
    slot: Any
    payload: Any
    time: Any = jnp.int32
    counter: Any = jnp.int32


#: Reference profile: every lane rides int32 (the pre-round-2 layout).
WIDE = Lanes(node=jnp.int32, code=jnp.int32, slot=jnp.int32,
             payload=jnp.int32)

#: Packed profile: ~0.6x the state bytes of :data:`WIDE` on the
#: canonical raft config (the ledgered ``state_bytes_per_world``).
PACKED = Lanes(node=jnp.int8, code=jnp.int8, slot=jnp.int16,
               payload=jnp.int16)


def widen(x) -> jnp.ndarray:
    """Narrow-lane read: widen to int32.

    THE sanctioned narrow-to-wide conversion site (tracelint TRC005
    flags any i8/i16-to-i32 convert in a registered packed program that
    does not originate here): all handler arithmetic runs int32, so
    every narrow state read passes through this exactly once. Pinned to
    int32 explicitly — never a weak Python int — so the x64 flag cannot
    widen it further (TRC003).
    """
    return jnp.asarray(x).astype(jnp.int32)


def narrow(x, dtype) -> jnp.ndarray:
    """Narrow-lane write: saturate into ``dtype``.

    The explicit guard at every narrow write boundary: values are
    clipped to the target's representable range before the cast, so an
    out-of-range value (a term past 32767, an oversized actor payload
    word) pins at the rail instead of wrapping silently. When ``dtype``
    is not strictly narrower (the WIDE profile), this is a plain cast —
    the reference path pays zero extra ops.
    """
    x = jnp.asarray(x)
    dt = jnp.dtype(dtype)
    if x.dtype == dt:
        return x
    if (jnp.issubdtype(x.dtype, jnp.integer) and jnp.issubdtype(dt, jnp.integer)
            and jnp.iinfo(dt).bits < jnp.iinfo(x.dtype).bits):
        info = jnp.iinfo(dt)
        x = jnp.clip(x, info.min, info.max)
    return x.astype(dt)


def narrow_wrap(x, dtype) -> jnp.ndarray:
    """Narrow-lane write with WRAP semantics — for lanes whose contract
    is modular arithmetic (the generation lane compares mod 256,
    ``queue.GEN_MASK``): a two's-complement truncating cast, explicit at
    the call site so wrap-vs-saturate is a stated decision, never a
    default."""
    return jnp.asarray(x).astype(dtype)


def split_wide(v):
    """Split an int32 value into two int16-range words ``(lo, hi)``.

    The engine's own wide payloads (net-config fault params: latency µs
    up to 2^31, loss ppm up to 1e6) ride the packed payload lane as two
    words. The low half is sign-folded into [-32768, 32767] so it
    passes the saturating :func:`narrow` untouched; :func:`join_wide`
    reassembles exactly.
    """
    v = jnp.asarray(v, jnp.int32)
    lo = ((v & 0xFFFF) ^ 0x8000) - 0x8000
    hi = v >> 16
    return lo, hi


def join_wide(lo, hi) -> jnp.ndarray:
    """Inverse of :func:`split_wide` (operands already widened int32)."""
    return (jnp.asarray(lo, jnp.int32) & 0xFFFF) \
        | (jnp.asarray(hi, jnp.int32) << 16)


def onehot(i, n: int) -> jnp.ndarray:
    """(n,) bool mask selecting index ``i``.

    Out-of-range ``i`` selects *nothing* (drop semantics: sel yields 0/False,
    upd is a no-op) — unlike jit-mode ``x[i]``, which clamps to the edge.
    Callers with possibly-wild indices must clip first.
    """
    return jnp.arange(n) == jnp.asarray(i, jnp.int32)


def _shaped(mask: jnp.ndarray, ndim: int) -> jnp.ndarray:
    """Reshape a (n,) mask to broadcast over trailing dims of an ndim array."""
    return mask.reshape(mask.shape + (1,) * (ndim - 1))


def sel(x: jnp.ndarray, i) -> jnp.ndarray:
    """``x[i]`` over axis 0 without a gather. x: (n, ...) → (...)."""
    m = _shaped(onehot(i, x.shape[0]), x.ndim)
    if x.dtype == jnp.bool_:
        return jnp.any(x & m, axis=0)
    return jnp.sum(jnp.where(m, x, 0), axis=0).astype(x.dtype)


def sel2(x: jnp.ndarray, i, j) -> jnp.ndarray:
    """``x[i, j]`` over the two leading axes. x: (n, m, ...) → (...)."""
    return sel(sel(x, i), j)


def sel_many(x: jnp.ndarray, idxs: jnp.ndarray) -> jnp.ndarray:
    """``x[idxs]`` for a 1-D ``x`` and a vector of indices, gather-free.

    x: (n,), idxs: (k,) → (k,). The (k, n) one-hot matrix contracts over n;
    for the engine's tiny n this fuses into the surrounding elementwise work.
    """
    m = jnp.arange(x.shape[0])[None, :] == idxs[:, None]
    return jnp.sum(jnp.where(m, x[None, :], 0), axis=1).astype(x.dtype)


def prefix_count(mask: jnp.ndarray) -> jnp.ndarray:
    """Exclusive prefix count: how many True lanes strictly precede each
    lane.

    For the engine's queue widths (n ≤ 64) the mask packs into one or
    two uint32 words (a ``where`` against the constant powers-of-two
    vector + a sum); each lane then ANDs the word with a *constant*
    below-me bitmask and ``population_count``s it. Two subtleties make
    this the cheapest form in practice, not just on paper:

    - XLA CPU *clones* elementwise producer chains into every consumer
      fusion, so the chain is pinned behind an identity gather (a
      materialization point fusion cannot clone through) — without it,
      the queue's three lane writes would each re-price the whole
      prefix (docs/perf.md r7).
    - The alternative, ``jnp.cumsum``, prices flat but its hierarchical
      scan lowering allocates ~1 KB/world of scratch inside the step —
      the difference between fitting 1.2× state in peak memory and not.

    Larger n falls back to ``jnp.cumsum`` (the word trick scales as
    n·(n/32) and stops winning past two words).
    """
    n = mask.shape[0]
    if n > 64:
        inc = jnp.cumsum(mask.astype(jnp.int32))
        return jnp.concatenate([jnp.zeros((1,), jnp.int32), inc[:-1]])
    counts = jnp.zeros((n,), jnp.int32)
    for w in range((n + 31) // 32):
        lanes = min(32, n - 32 * w)
        pow2 = jnp.asarray(np.uint32(1) << np.arange(lanes, dtype=np.uint32),
                           jnp.uint32)
        word = jnp.sum(jnp.where(mask[32 * w:32 * w + lanes], pow2,
                                 jnp.uint32(0)))
        # below[s]: bits of word w strictly below lane s (zero before the
        # word, all-ones once past it) — a host-built constant vector.
        rel = np.clip(np.arange(n) - 32 * w, 0, 32)
        partial = (np.uint32(1) << np.minimum(rel, 31).astype(np.uint32)) \
            - np.uint32(1)
        below = jnp.asarray(np.where(rel < 32, partial,
                                     np.uint32(0xFFFFFFFF)), jnp.uint32)
        counts = counts + lax.population_count(word & below) \
            .astype(jnp.int32)
    # Identity gather = an explicit materialization point (see docstring).
    return jnp.take(counts, jnp.arange(n), axis=0)


def take_small(x: jnp.ndarray, idxs: jnp.ndarray) -> jnp.ndarray:
    """``x[idxs]`` as a REAL gather — for tiny leading axes only.

    x: (m, ...), idxs: (k,) → (k, ...). The one place the engine prefers a
    gather over a one-hot contraction: when the *source* axis is tiny
    (m ≲ 8, e.g. an outbox-sized table) but the index vector is long
    (k = queue capacity) and the rows are wide (payload words), the
    one-hot select costs k·m·width ops — the very per-slot rewrite cost
    :func:`~madsim_tpu.engine.queue.push_many` exists to eliminate —
    while the gather reads each destination row once.

    Out-of-range indices clamp to the edge ("clip" mode — measured
    cheaper post-fusion than both ``promise_in_bounds``'s at-get lowering
    and "wrap"); callers with possibly-wild indices get edge values and
    must mask the result.
    """
    return jnp.take(x, jnp.asarray(idxs, jnp.int32), axis=0, mode="clip")


def upd(x: jnp.ndarray, i, v) -> jnp.ndarray:
    """``x.at[i].set(v)`` over axis 0 without a scatter.

    The written value passes through the saturating :func:`narrow` when
    ``x`` carries a packed lane dtype — every one-hot write is thereby a
    guarded narrow-write boundary for free (wrap-semantics lanes
    pre-wrap via :func:`narrow_wrap` before calling)."""
    m = _shaped(onehot(i, x.shape[0]), x.ndim)
    return jnp.where(m, narrow(v, x.dtype), x)


def upd2(x: jnp.ndarray, i, j, v) -> jnp.ndarray:
    """``x.at[i, j].set(v)`` over the two leading axes (saturating like
    :func:`upd`)."""
    m = (_shaped(onehot(i, x.shape[0]), x.ndim)
         & _shaped(onehot(j, x.shape[1]), x.ndim - 1)[None])
    return jnp.where(m, narrow(v, x.dtype), x)
