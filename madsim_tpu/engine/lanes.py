"""One-hot select/update primitives for the device engine.

TPU-first data movement: under ``vmap``, ``x[i]`` and ``x.at[i].set(v)`` with
traced indices lower to gather/scatter HLOs, which XLA cannot fuse and which
serialize badly on TPU. For the tiny per-world axes this engine indexes
(nodes N ≤ 8, queue slots Q ≤ 256), a one-hot mask + elementwise
select/reduce is strictly better: it fuses into the surrounding kernel and
vectorizes over the world axis for free. Every dynamic index in the engine
and its actors goes through these helpers.
"""
from __future__ import annotations

import jax.numpy as jnp


def onehot(i, n: int) -> jnp.ndarray:
    """(n,) bool mask selecting index ``i``.

    Out-of-range ``i`` selects *nothing* (drop semantics: sel yields 0/False,
    upd is a no-op) — unlike jit-mode ``x[i]``, which clamps to the edge.
    Callers with possibly-wild indices must clip first.
    """
    return jnp.arange(n) == jnp.asarray(i, jnp.int32)


def _shaped(mask: jnp.ndarray, ndim: int) -> jnp.ndarray:
    """Reshape a (n,) mask to broadcast over trailing dims of an ndim array."""
    return mask.reshape(mask.shape + (1,) * (ndim - 1))


def sel(x: jnp.ndarray, i) -> jnp.ndarray:
    """``x[i]`` over axis 0 without a gather. x: (n, ...) → (...)."""
    m = _shaped(onehot(i, x.shape[0]), x.ndim)
    if x.dtype == jnp.bool_:
        return jnp.any(x & m, axis=0)
    return jnp.sum(jnp.where(m, x, 0), axis=0).astype(x.dtype)


def sel2(x: jnp.ndarray, i, j) -> jnp.ndarray:
    """``x[i, j]`` over the two leading axes. x: (n, m, ...) → (...)."""
    return sel(sel(x, i), j)


def sel_many(x: jnp.ndarray, idxs: jnp.ndarray) -> jnp.ndarray:
    """``x[idxs]`` for a 1-D ``x`` and a vector of indices, gather-free.

    x: (n,), idxs: (k,) → (k,). The (k, n) one-hot matrix contracts over n;
    for the engine's tiny n this fuses into the surrounding elementwise work.
    """
    m = jnp.arange(x.shape[0])[None, :] == idxs[:, None]
    return jnp.sum(jnp.where(m, x[None, :], 0), axis=1).astype(x.dtype)


def upd(x: jnp.ndarray, i, v) -> jnp.ndarray:
    """``x.at[i].set(v)`` over axis 0 without a scatter."""
    m = _shaped(onehot(i, x.shape[0]), x.ndim)
    return jnp.where(m, jnp.asarray(v, x.dtype), x)


def upd2(x: jnp.ndarray, i, j, v) -> jnp.ndarray:
    """``x.at[i, j].set(v)`` over the two leading axes."""
    m = (_shaped(onehot(i, x.shape[0]), x.ndim)
         & _shaped(onehot(j, x.shape[1]), x.ndim - 1)[None])
    return jnp.where(m, jnp.asarray(v, x.dtype), x)
