"""Actor-protocol conformance checker.

``check_actor(actor, cfg)`` validates a DeviceEngine actor implementation
against the contract documented in docs/ACTORS.md, catching the mistakes
that otherwise surface as cryptic trace-time errors or — worse — as silent
nondeterminism deep inside a sweep:

- the engine accepts it (num_kinds declared and within packed width);
- state and outbox shapes are fixed and well-formed;
- ``handle``/``on_restart``/``invariant`` are pure: same inputs ⇒ bitwise
  same outputs across two traced evaluations;
- runs are seed-deterministic end-to-end (two identical sweeps agree
  bitwise) and distinct seeds actually diverge;
- restart resets are exercised (a kill/restart fault schedule runs clean);
- the RNG draw discipline holds on a sampled state: ``handle`` is
  call-pure and advances the counter forward by a small bounded amount
  per kind (state-dependent advances are legal — the merged-handler
  pattern — so this is a sanity bound, not a proof of world-invariance).

Returns a report dict; raises ``ConformanceError`` on the first violation.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from .core import DeviceEngine, EngineConfig, FAULT_KILL, FAULT_RESTART

__all__ = ["check_actor", "ConformanceError"]


class ConformanceError(AssertionError):
    pass


def _require(ok: bool, msg: str) -> None:
    if not ok:
        raise ConformanceError(msg)


def check_actor(actor, cfg: EngineConfig, n_worlds: int = 64,
                max_steps: int = 2_000,
                require_divergence: bool = True) -> Dict[str, Any]:
    """Validate ``actor`` against ``cfg``; see module docstring.

    ``require_divergence=False`` waives the distinct-seeds-diverge
    check for the synthetic fixture families (pair_restart,
    guided_pair) whose fault-free trajectory is deliberately
    schedule-driven and seed-invariant — every real protocol family
    keeps the default."""
    _require(hasattr(actor, "handle") and hasattr(actor, "init")
             and hasattr(actor, "invariant") and hasattr(actor, "observe")
             and hasattr(actor, "on_restart"),
             "actor must implement init/handle/on_restart/invariant/observe")
    eng = DeviceEngine(actor, cfg)  # raises on num_kinds problems

    seeds = np.arange(n_worlds)
    state = eng.init(seeds)

    # -- fixed shapes, int-family dtypes -------------------------------
    for i, leaf in enumerate(jax.tree.leaves(state.astate)):
        _require(leaf.shape[:1] == (n_worlds,),
                 f"astate leaf {i} lacks the leading world axis: {leaf.shape}")
        _require(jnp.issubdtype(leaf.dtype, jnp.integer)
                 or leaf.dtype == jnp.bool_,
                 f"astate leaf {i} has non-integer dtype {leaf.dtype} "
                 "(device state must be int/bool for bitwise replay)")

    # -- end-to-end determinism + seed sensitivity ---------------------
    try:
        final_a = eng.run(eng.init(seeds), max_steps=max_steps)
    except TypeError as exc:
        # A while-loop carry mismatch means handle()/on_restart changed a
        # leaf's shape or dtype mid-run — surface it as conformance.
        # Unrelated TypeErrors (wrong handle() signature, bad payload
        # indexing) re-raise untouched so the diagnosis stays accurate.
        text = str(exc)
        if any(marker in text for marker in
               ("carry", "body_fun", "while_loop", "same type structure",
                "pytree structure")):
            raise ConformanceError(
                "handle()/on_restart() changed the state pytree's "
                f"structure, shapes, or dtypes (jit carry mismatch): {exc}"
            ) from exc
        raise
    obs_clean = eng.observe(final_a)
    _require(not obs_clean["overflow"].any(),
             f"queue overflow in the clean run (qmax="
             f"{int(obs_clean['qmax'].max())}): raise cfg.queue_cap — all "
             "later checks would run on silently-lossy trajectories")
    final_b = eng.run(eng.init(seeds), max_steps=max_steps)
    leaves_a, leaves_b = jax.tree.leaves(final_a), jax.tree.leaves(final_b)
    for i, (a, b) in enumerate(zip(leaves_a, leaves_b)):
        _require(np.array_equal(np.asarray(a), np.asarray(b)),
                 f"two identical runs diverged at leaf {i}: "
                 "handle/init is impure (Python-level randomness, "
                 "iteration-order dependence, or global state)")
    # Seeds must actually diverge somewhere in the world TRAJECTORY (actor
    # state may legitimately converge to one canonical outcome — e.g. a
    # replication log with no timestamps — but per-world clocks, step
    # counts, queue contents, or counter advances must not all coincide).
    # The RNG keys are excluded: they are seed-derived and always
    # distinct, which would make this check vacuous.
    trajectory = ([final_a.now, final_a.steps, final_a.delivered,
                   final_a.qmax, final_a.rng.counter]
                  + jax.tree.leaves(final_a.astate)
                  + jax.tree.leaves(final_a.queue))
    distinct = any(
        len(np.unique(np.asarray(x).reshape(n_worlds, -1), axis=0)) > 1
        for x in trajectory)
    _require(distinct or not require_divergence,
             f"all {n_worlds} seeds produced bitwise-identical "
             "trajectories — nothing consumed randomness or virtual time; "
             "is init wiring the RNG through?")

    # -- RNG discipline: handle() is pure and its counter consumption per
    # kind is small and monotone (a handler may advance conditionally —
    # the merged-handler pattern — but never backwards or unboundedly).
    from .queue import Event
    from .rng import make_rng

    rng0 = make_rng(jnp.uint32(1), jnp.uint32(0), 99)
    astate0 = jax.tree.map(lambda x: x[0], final_a.astate)
    draws_per_kind = []
    for kind in range(actor.num_kinds):
        ev = Event.make(time=1000, kind=kind,
                        payload_words=cfg.payload_words, src=0, dst=0,
                        payload=[0])
        s1, ob1, rng_out, bug1 = actor.handle(cfg, astate0, ev,
                                              jnp.int32(1000), rng0)
        s2, ob2, rng_out2, bug2 = actor.handle(cfg, astate0, ev,
                                               jnp.int32(1000), rng0)
        for i, (a, b) in enumerate(zip(jax.tree.leaves((s1, ob1, rng_out, bug1)),
                                       jax.tree.leaves((s2, ob2, rng_out2, bug2)))):
            _require(np.array_equal(np.asarray(a), np.asarray(b)),
                     f"handle(kind={kind}) is impure: leaf {i} differs "
                     "between two calls on identical inputs")
        delta = int(np.asarray(rng_out.counter) - np.asarray(rng0.counter))
        _require(0 <= delta <= 64,
                 f"kind {kind} consumed {delta} draws — counter must "
                 "advance forward by a small bounded amount")
        draws_per_kind.append(delta)

    # -- restart path runs clean under a kill/restart schedule ---------
    faults = np.array([[cfg.t_limit_us // 4, FAULT_KILL, 0, 0],
                       [cfg.t_limit_us // 2, FAULT_RESTART, 0, 0]], np.int32)
    final_f = eng.run(eng.init(seeds, faults=faults), max_steps=max_steps)
    obs = eng.observe(final_f)
    _require(not obs["overflow"].any(),
             f"queue overflow under restart schedule (qmax="
             f"{int(obs['qmax'].max())}): raise cfg.queue_cap")
    _require(not obs["bug"].any(),
             f"invariant violated in {int(obs['bug'].sum())}/{n_worlds} "
             "worlds under a plain kill/restart schedule — on_restart "
             "corrupts durable state (or the clean config has a real bug)")

    # -- observe() respects the batch axis -----------------------------
    for key, val in obs.items():
        _require(np.asarray(val).shape[:1] == (n_worlds,),
                 f"observe()[{key!r}] lost the world axis "
                 f"(shape {np.asarray(val).shape}); reduce node axes with "
                 "axis=-1/-2, not axis=0")

    return {
        "n_worlds": n_worlds,
        "steps_mean": float(np.asarray(final_a.steps).mean()),
        "draws_per_kind": draws_per_kind,
        "bug_rate": float(np.asarray(final_a.bug).mean()),
        "qmax": int(np.asarray(obs["qmax"]).max()),
    }
