"""tracelint — pass 3: program-level static analysis of the compiled sweep.

detlint's AST passes see Python source; since PR 3-7 the determinism and
performance contracts moved INTO compiled programs — the superstep loop,
donated step buffers, the coverage fold, the bridge kernel — where an AST
walk cannot follow. This pass traces the repo's hot-path entry points to
their jaxprs (and, for the budget/donation gates, compiles them fresh)
and enforces four rule families, the same shape as compiler-level
sanitizer passes in a training stack (DrJAX's MapReduce-primitive
discipline, SCALE-Sim's cost-model validation — PAPERS.md):

- **TRC001** — no host callbacks (``pure_callback``/``io_callback``/
  ``debug_callback``) inside jitted sim programs: a callback re-enters
  the host mid-program, breaking both determinism (host state) and the
  dispatch-ahead pipeline (implicit sync).
- **TRC002** — no backend-variant or nondeterministic primitives:
  unstable sorts, float scatter-accumulation onto possibly-duplicate
  indices, approximate/stateful kernels.
- **TRC003** — no numerics that change under the x64 flag: each engine
  program is traced twice (plain and under ``enable_x64``) and must keep
  identical output dtypes and stay float64-free — otherwise a process
  that flips ``jax_enable_x64`` silently changes trajectories.
- **TRC004** — declared donation actually lands: JAX drops donation
  SILENTLY when an output cannot alias its input, which would quietly
  re-double-buffer the state PR 3 paid to alias (the 1.195x-of-state
  peak gate). Checked against the per-program ``alias_fraction`` floor
  recorded in the budget ledger, compiled FRESH (cache-deserialized
  executables lose alias statistics — :mod:`.budgets`).

Plus the **budget ledger** (``analysis/budgets.json``): per-program
``cost_analysis`` flops/bytes and ``memory_analysis`` temp/peak, diffed
against checked-in ceilings (BUD001/BUD002) so a hot program regressing
its op budget fails ``make lint`` before a bench round ever runs.

Entry points: ``python -m madsim_tpu.analysis trace`` (the ``make
tracelint`` / ``make lint`` gate), ``tools/update_budgets.py`` to
regenerate the ledger. Findings use the pseudo-path ``trace/<program>``
so allowlist prefixes and ``--format=github`` output compose unchanged.
"""
from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from . import budgets as _budgets
from .pragmas import Finding
from .rules import RULES

# -- rule tables -------------------------------------------------------------

# TRC001: primitives that re-enter the host from inside a program.
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback",
    # legacy host_callback spellings, in case a dependency resurrects them
    "outside_call", "host_callback",
})

# TRC002: outright-forbidden primitives (stateful/approximate kernels whose
# results are backend- or scheduling-dependent).
NONDET_PRIMS = frozenset({
    "rng_uniform",        # the old stateful lax RNG — backend-defined
    "rng_bit_generator",  # platform-keyed algorithm selection
    "approx_top_k",       # approximate by construction
})

# TRC002: scatter accumulation combiners that are order-sensitive in
# floating point (float add/mul are not associative; duplicate indices
# then make the result depend on reduction order, which backends choose).
SCATTER_ACCUM_PRIMS = frozenset({"scatter-add", "scatter-mul"})

# TRC005: narrow-lane dtypes (the packed profile of engine/lanes.py) and
# the wide integer dtypes an unannotated promotion would leak them into.
NARROW_INT_DTYPES = frozenset({"int8", "int16", "uint8", "uint16"})
WIDE_INT_DTYPES = frozenset({"int32", "int64", "uint32", "uint64"})
# The one sanctioned widening site: lanes.widen() (and the helpers in
# the same module — take_small's index cast, onehot's compare operand).
# Path-qualified: a bare "lanes.py" would also match e.g.
# tests/test_packed_lanes.py in the source summary.
SANCTIONED_WIDEN_FILE = "engine/lanes.py"


# -- jaxpr walking -----------------------------------------------------------

def _sub_jaxprs(value: Any) -> Iterator[Any]:
    vals = value if isinstance(value, (tuple, list)) else [value]
    for v in vals:
        if hasattr(v, "eqns"):               # open Jaxpr
            yield v
        elif hasattr(v, "jaxpr") and hasattr(getattr(v, "jaxpr"), "eqns"):
            yield v.jaxpr                    # ClosedJaxpr


def iter_eqns(jaxpr) -> Iterator[Any]:
    """Every equation in ``jaxpr`` and (recursively) every sub-jaxpr a
    param carries — while/scan/cond bodies, pjit calls, shard_map, custom
    derivative closures."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from iter_eqns(sub)


def _where(eqn) -> str:
    """Best-effort source attribution for an equation."""
    try:
        from jax._src import source_info_util

        s = source_info_util.summarize(eqn.source_info)
        return f" at {s}" if s else ""
    except Exception:  # pragma: no cover — jax internals drift
        return ""


def _aval_dtypes(jaxpr, acc: set) -> None:
    for eqn in jaxpr.eqns:
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            if aval is not None and hasattr(aval, "dtype"):
                acc.add(str(aval.dtype))
        for p in eqn.params.values():
            for sub in _sub_jaxprs(p):
                _aval_dtypes(sub, acc)


# -- the program registry ----------------------------------------------------

@dataclasses.dataclass
class Built:
    """A traceable/lowerable hot-path program instance.

    ``fn``/``args`` is the jitted entry used for ``lower().compile()``
    (donation declarations live there); ``trace_fn``/``trace_args``
    override it for ``make_jaxpr`` when the jit carries static argnums
    (``make_jaxpr`` traces every argument, so a static int would arrive
    as a tracer and fail to hash)."""

    fn: Callable                  # the jitted callable
    args: Tuple[Any, ...]         # small concrete example args
    ctx: Callable[[], Any] = contextlib.nullcontext  # trace/lower context
    trace_fn: Optional[Callable] = None
    trace_args: Optional[Tuple[Any, ...]] = None

    @property
    def for_trace(self) -> Tuple[Callable, Tuple[Any, ...]]:
        return (self.trace_fn or self.fn,
                self.args if self.trace_args is None else self.trace_args)


@dataclasses.dataclass
class TraceProgram:
    name: str
    title: str                    # one human line for --list-programs
    build: Callable[[], Built]
    x64: str = "off"              # "off": dual-trace diff; "required": bridge
    budget: bool = False          # compile fresh: TRC004 + ledger metrics
    donates: bool = False         # program declares input donation
    unit_div: Optional[int] = None  # world count for flops_per_world
    packed: bool = False          # TRC005 narrow-dtype discipline applies


_ENGINE_CACHE: Dict[str, Any] = {}


def _bug_engine(metrics: bool = False, blackbox: int = 0):
    """The canonical raft bug config every budget in the repo is pinned
    to (tests/test_queue_insert.py, bench time_to_first_bug)."""
    key = f"eng_m{int(metrics)}_b{blackbox}"
    if key not in _ENGINE_CACHE:
        from ..engine import (DeviceEngine, EngineConfig, RaftActor,
                              RaftDeviceConfig)

        cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                           t_limit_us=2_000_000, stop_on_bug=False,
                           metrics=metrics, blackbox=blackbox)
        _ENGINE_CACHE[key] = DeviceEngine(
            RaftActor(RaftDeviceConfig(n=3, buggy_double_vote=True)), cfg)
    return _ENGINE_CACHE[key]


def _mesh():
    if "mesh" not in _ENGINE_CACHE:
        from ..parallel.mesh import seed_mesh

        _ENGINE_CACHE["mesh"] = seed_mesh()
    return _ENGINE_CACHE["mesh"]


# Pinned shapes: every ledger number is "at this shape" — small enough to
# trace in seconds, large enough that per-world figures are meaningful.
RUN_WORLDS = 256          # matches the historical tier-1 op-budget shape
RUN_MAX_STEPS = 4_000
SWEEP_WORLDS = 64
SWEEP_CHUNK_STEPS = 16
SWEEP_K_MAX = 4


def _build_engine_run() -> Built:
    import numpy as np

    eng = _bug_engine()
    state = eng.init(np.arange(RUN_WORLDS))
    return Built(fn=eng._run, args=(state, RUN_MAX_STEPS),
                 trace_fn=lambda s: eng._run_impl(s, RUN_MAX_STEPS),
                 trace_args=(state,))


# Flight-recorder ring depth the budget is pinned at — the depth the
# docs recommend (docs/observability.md "The flight recorder").
BLACKBOX_K = 64


def _build_engine_run_blackbox() -> Built:
    import numpy as np

    eng = _bug_engine(blackbox=BLACKBOX_K)
    state = eng.init(np.arange(RUN_WORLDS))
    return Built(fn=eng._run, args=(state, RUN_MAX_STEPS),
                 trace_fn=lambda s: eng._run_impl(s, RUN_MAX_STEPS),
                 trace_args=(state,))


# Pallas kernel shape: smaller than RUN_WORLDS — the interpret-mode
# kernel is traced/compiled per check and the contract (one fused
# kernel, full donation, narrow lanes) is width-invariant.
PALLAS_WORLDS = 64


def _build_pallas_step() -> Built:
    import dataclasses as _dc

    import jax
    import numpy as np

    if "pallas_eng" not in _ENGINE_CACHE:
        from ..engine import DeviceEngine

        eng0 = _bug_engine()
        _ENGINE_CACHE["pallas_eng"] = DeviceEngine(
            eng0.actor, _dc.replace(eng0.cfg, pallas=True))
    eng = _ENGINE_CACHE["pallas_eng"]
    state = eng.init(np.arange(PALLAS_WORLDS))
    # One batched kernel invocation, donated like the run loop: the
    # jitted wrapper is what the ledger prices (alias_fraction must
    # show the input_output_aliases landing at the XLA level too).
    if "pallas_step_jit" not in _ENGINE_CACHE:
        _ENGINE_CACHE["pallas_step_jit"] = jax.jit(
            eng._batched_step, donate_argnums=0)
    return Built(fn=_ENGINE_CACHE["pallas_step_jit"], args=(state,),
                 trace_fn=eng._batched_step)


def _build_push_many() -> Built:
    import jax
    import jax.numpy as jnp

    from ..engine.queue import Event, empty_queue, push_many

    q = empty_queue(64, 2)
    m = 4
    evs = Event(time=jnp.zeros((m,), jnp.int32),
                kind=jnp.zeros((m,), jnp.int32),
                flags=jnp.zeros((m,), jnp.int32),
                src=jnp.zeros((m,), jnp.int32),
                dst=jnp.zeros((m,), jnp.int32),
                gen=jnp.zeros((m,), jnp.int32),
                payload=jnp.zeros((m, 2), jnp.int32))
    return Built(fn=jax.jit(push_many), args=(q, evs))


def _superstep_args(eng, mesh):
    import jax.numpy as jnp
    import numpy as np

    from ..parallel.mesh import shard_worlds

    state = shard_worlds(eng.init(np.arange(SWEEP_WORLDS)), mesh)
    return state, (jnp.int32(0), jnp.asarray(False),
                   jnp.int32(SWEEP_K_MAX))


def _build_superstep(min_one: bool) -> Built:
    def build():
        from ..parallel.sweep import sharded_superstep

        eng, mesh = _bug_engine(), _mesh()
        runner = sharded_superstep(eng, mesh, SWEEP_CHUNK_STEPS,
                                   SWEEP_K_MAX, donate=True,
                                   min_one=min_one)
        state, scalars = _superstep_args(eng, mesh)
        return Built(fn=runner, args=(state,) + scalars)
    return build


def _build_superstep_coverage() -> Built:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding

    from ..obs.coverage import ledger_zeros
    from ..parallel.mesh import scalar_spec, shard_worlds
    from ..parallel.sweep import sharded_superstep

    eng, mesh = _bug_engine(metrics=True), _mesh()
    cov_k = 64
    runner = sharded_superstep(eng, mesh, SWEEP_CHUNK_STEPS, SWEEP_K_MAX,
                               donate=True, min_one=False, coverage=cov_k)
    state = shard_worlds(eng.init(np.arange(SWEEP_WORLDS)), mesh)
    hits, first = jax.device_put(ledger_zeros(cov_k),
                                 NamedSharding(mesh, scalar_spec()))
    idx = shard_worlds(jnp.arange(SWEEP_WORLDS, dtype=jnp.int32), mesh)
    return Built(fn=runner, args=(
        state, hits, first, idx, jnp.int32(SWEEP_WORLDS), jnp.int32(0),
        jnp.asarray(False), jnp.int32(SWEEP_K_MAX)))


def _build_endfold() -> Built:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding

    from ..obs.coverage import ledger_zeros
    from ..parallel.mesh import scalar_spec, shard_worlds
    from ..parallel.sweep import _cov_endfolder

    eng, mesh = _bug_engine(metrics=True), _mesh()
    state = shard_worlds(eng.init(np.arange(SWEEP_WORLDS)), mesh)
    hits, first = jax.device_put(ledger_zeros(64),
                                 NamedSharding(mesh, scalar_spec()))
    idx = shard_worlds(jnp.arange(SWEEP_WORLDS, dtype=jnp.int32), mesh)
    return Built(fn=_cov_endfolder(eng, mesh), args=(
        state, hits, first, idx, jnp.int32(SWEEP_WORLDS),
        jnp.asarray(False)))


def _build_compactor() -> Built:
    import jax.numpy as jnp
    import numpy as np

    from ..parallel.mesh import shard_worlds
    from ..parallel.sweep import _compactor

    eng, mesh = _bug_engine(), _mesh()
    state = shard_worlds(eng.init(np.arange(SWEEP_WORLDS)), mesh)
    idx = shard_worlds(jnp.arange(SWEEP_WORLDS, dtype=jnp.int32), mesh)
    return Built(fn=_compactor(eng, mesh, SWEEP_WORLDS, SWEEP_WORLDS),
                 args=(state, idx))


def _build_refill_select() -> Built:
    import jax.numpy as jnp
    import numpy as np

    eng = _bug_engine()
    mask = jnp.zeros((SWEEP_WORLDS,), bool)
    fresh = eng.init(np.arange(SWEEP_WORLDS))
    state = eng.init(np.arange(SWEEP_WORLDS))
    return Built(fn=eng._refill_select, args=(mask, fresh, state))


# Guided-search generator shape (search/generate.py): the harvest +
# mutate program one guided refill dispatches — the "search superstep"
# of the closed fuzzer loop (docs/search.md), at the canonical family
# hunt shape.
SEARCH_WORLDS = 32
SEARCH_ROWS = 6


def _search_fixture():
    """Shared state of the guided-search builders: engine, mesh, the
    canonical template, and the per-slot arrays at the hunt shape."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding

    from ..parallel.mesh import scalar_spec, shard_worlds
    from ..search.corpus import corpus_init

    if "search_eng" not in _ENGINE_CACHE:
        from ..engine import DeviceEngine
        from ..search.family import (GuidedPairActor, GuidedPairConfig,
                                     engine_config)

        acfg = GuidedPairConfig(n=12)
        _ENGINE_CACHE["search_eng"] = DeviceEngine(
            GuidedPairActor(acfg), engine_config(acfg))
    from ..search.family import family_schedule, hunt_search_config
    from ..search.family import GuidedPairConfig as _GPC

    eng, mesh = _ENGINE_CACHE["search_eng"], _mesh()
    scfg = hunt_search_config(True)
    tmpl = family_schedule(SEARCH_ROWS, _GPC(n=12))
    w = SEARCH_WORLDS
    state = shard_worlds(eng.init(np.arange(w), faults=tmpl), mesh)
    sched = shard_worlds(jnp.asarray(
        np.broadcast_to(tmpl, (w,) + tmpl.shape).copy()), mesh)
    idx = shard_worlds(jnp.arange(w, dtype=jnp.int32), mesh)
    corpus = jax.device_put(corpus_init(int(scfg.corpus), tmpl),
                            NamedSharding(mesh, scalar_spec()))
    return eng, mesh, scfg, w, state, sched, idx, corpus


def _search_lineage_args(mesh, w):
    """The lineage-side searcher inputs (obs/lineage.py lanes + outcome
    table) at the hunt shape."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from ..obs.lineage import lanes_origin, table_zeros
    from ..parallel.mesh import scalar_spec, shard_worlds

    lin = shard_worlds(lanes_origin(w), mesh)
    op_tab = jax.device_put(table_zeros(),
                            NamedSharding(mesh, scalar_spec()))
    fill = shard_worlds(jnp.asarray(
        jnp.arange(w, dtype=jnp.int32) >= w // 2), mesh)
    return lin, op_tab, fill


def _build_search_generate() -> Built:
    import jax.numpy as jnp

    from ..search.generate import searcher

    eng, mesh, scfg, w, state, sched, idx, corpus = _search_fixture()
    runner = searcher(eng, mesh, scfg, w, SEARCH_ROWS)
    from ..parallel.mesh import shard_worlds

    ids = shard_worlds(jnp.arange(w, dtype=jnp.int32), mesh)
    lin, op_tab, fill = _search_lineage_args(mesh, w)
    return Built(fn=runner, args=(state, sched, idx, corpus,
                                  jnp.int32(w // 2), ids, fill, lin,
                                  op_tab, jnp.int32(0)))


def _build_fused_hunt() -> Built:
    """The whole-hunt fused program (parallel/sweep.py _fused_hunt) at
    its widest shape — guided + lineage + coverage — so the ledger
    budgets the full in-loop epoch body: chunk loop, stable compaction,
    retiring-tail scatter, coverage fold, harvest+generate, refill, and
    the device seed cursor, all inside ONE dispatch."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding

    from ..obs.coverage import ledger_zeros
    from ..obs.lineage import lanes_buffer
    from ..parallel.mesh import scalar_spec
    from ..parallel.sweep import _fused_hunt

    eng, mesh, scfg, w, state, sched, idx, corpus = _search_fixture()
    lin, op_tab, _fill = _search_lineage_args(mesh, w)
    del _fill
    rep = NamedSharding(mesh, scalar_spec())
    cov_k = 64
    runner = _fused_hunt(eng, mesh, scfg, w=w, n_ids_b=w,
                         f_rows=SEARCH_ROWS,
                         chunk_steps=SWEEP_CHUNK_STEPS,
                         k_bucket=SWEEP_K_MAX, cov_k=cov_k,
                         lineage_on=True, fault_mode="search",
                         recycle=True)
    hits, first = jax.device_put(ledger_zeros(cov_k), rep)
    obs_shapes = jax.eval_shape(eng.observe_device, state)
    bufs = jax.device_put(
        {k: jnp.zeros((w + 1,) + tuple(s.shape[1:]), s.dtype)
         for k, s in obs_shapes.items()}, rep)
    sb = np.full((w + 1, SEARCH_ROWS, 4), -1, np.int32)
    sb[:, :, 1:] = 0
    sched_buf = jax.device_put(jnp.asarray(sb), rep)
    lin_buf = jax.device_put(lanes_buffer(w), rep)
    seeds = np.arange(w, dtype=np.uint64)
    tabs = jax.device_put(
        {"lo": jnp.asarray((seeds & np.uint64(0xFFFFFFFF))
                           .astype(np.uint32)),
         "hi": jnp.asarray((seeds >> np.uint64(32)).astype(np.uint32))},
        rep)
    cursor = jax.device_put(jnp.int32(w), rep)
    epochs = jax.device_put(jnp.int32(0), rep)
    return Built(fn=runner, args=(
        state, idx, cursor, epochs, bufs, (hits, first),
        (sched, corpus, sched_buf, lin, op_tab, lin_buf), tabs,
        jnp.int32(w), jnp.int32(w), jnp.int32(0), jnp.asarray(False),
        jnp.int32(SWEEP_K_MAX)))


def _build_compactor_sched() -> Built:
    """The guided with_sched compactor: state + slot index + per-slot
    schedules + lineage lanes permuted in ONE dispatch (the widened
    PR 13 shape the guided sweep dispatches at every refill)."""
    import jax.numpy as jnp

    from ..parallel.mesh import shard_worlds
    from ..parallel.sweep import _compactor

    eng, mesh, _scfg, w, state, sched, idx, _corpus = _search_fixture()
    lin, _op_tab, _fill = _search_lineage_args(mesh, w)
    del _op_tab, _fill
    return Built(fn=_compactor(eng, mesh, w, w, with_sched=True),
                 args=(state, idx, sched) + tuple(lin))


# Triage candidate-eval shape (triage/minimize.py): one batch of
# candidate schedules of the known-minimal synthetic bug, evaluated by
# the superstep runner compiled for the pair_restart engine — a
# DISTINCT compiled program from sweep.superstep (different actor step),
# and the hot path every minimization round dispatches.
TRIAGE_CANDS = 32
TRIAGE_ROWS = 16


def _build_triage_candidate_eval() -> Built:
    import jax.numpy as jnp
    import numpy as np

    from ..engine import DeviceEngine
    from ..parallel.mesh import shard_worlds
    from ..parallel.sweep import sharded_superstep
    from ..triage.synthetic import (PairRestartActor, PairRestartConfig,
                                    engine_config, pair_schedule)

    if "triage_eng" not in _ENGINE_CACHE:
        acfg = PairRestartConfig()
        _ENGINE_CACHE["triage_eng"] = DeviceEngine(
            PairRestartActor(acfg), engine_config(acfg))
    eng, mesh = _ENGINE_CACHE["triage_eng"], _mesh()
    runner = sharded_superstep(eng, mesh, SWEEP_CHUNK_STEPS, SWEEP_K_MAX,
                               donate=True, min_one=False)
    cands = np.broadcast_to(
        pair_schedule(n_rows=TRIAGE_ROWS, need=(2, 11)),
        (TRIAGE_CANDS, TRIAGE_ROWS, 4))
    state = shard_worlds(
        eng.init(np.full(TRIAGE_CANDS, 7, np.uint64), faults=cands), mesh)
    return Built(fn=runner, args=(state, jnp.int32(0), jnp.asarray(False),
                                  jnp.int32(SWEEP_K_MAX)))


# Compiled-actor (actorc) run shapes: the whole point of registering
# these is TRC005 — the compiler CLAIMS its widen-on-read /
# narrow-on-write boundaries are placed by construction, and the
# narrow-discipline scan over a compiled family's full run program is
# what holds it to that. Small widths: the contract is width-invariant.
ACTORC_WORLDS = 64
ACTORC_MAX_STEPS = 4_000


def _build_actorc_run(family: str) -> Callable[[], Built]:
    def build() -> Built:
        import numpy as np

        key = f"actorc_{family}"
        if key not in _ENGINE_CACHE:
            from ..engine import DeviceEngine

            if family == "paxos":
                from ..actorc.families.paxos import (PaxosActor,
                                                     PaxosConfig,
                                                     engine_config)

                acfg = PaxosConfig()
                _ENGINE_CACHE[key] = DeviceEngine(PaxosActor(acfg),
                                                  engine_config(acfg))
            elif family == "pb":
                from ..engine import EngineConfig, PBActor, PBDeviceConfig

                _ENGINE_CACHE[key] = DeviceEngine(
                    PBActor(PBDeviceConfig()),
                    EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                                 t_limit_us=2_000_000))
            else:  # tpc — the migrated hand-written family
                from ..engine import EngineConfig, TPCActor, TPCDeviceConfig

                _ENGINE_CACHE[key] = DeviceEngine(
                    TPCActor(TPCDeviceConfig(n=4, n_txns=4)),
                    EngineConfig(n_nodes=4, outbox_cap=5, queue_cap=64,
                                 t_limit_us=2_000_000, stop_on_bug=False))
        eng = _ENGINE_CACHE[key]
        state = eng.init(np.arange(ACTORC_WORLDS))
        return Built(fn=eng._run, args=(state, ACTORC_MAX_STEPS),
                     trace_fn=lambda s: eng._run_impl(s, ACTORC_MAX_STEPS),
                     trace_args=(state,))
    return build


BRIDGE_SLOTS = 8
BRIDGE_CAP = 16
BRIDGE_K_EVENTS = 2
BRIDGE_PAD = 4


def _bridge_kernel():
    if "bridge" not in _ENGINE_CACHE:
        import numpy as np

        from ..bridge.kernel import BridgeKernel

        _ENGINE_CACHE["bridge"] = BridgeKernel(
            np.arange(1, BRIDGE_SLOTS + 1), cap=BRIDGE_CAP,
            k_events=BRIDGE_K_EVENTS)
    return _ENGINE_CACHE["bridge"]


def _bridge_batch_args(bk):
    """A zero HostBatch at the kernel's bucketed pad shapes, with the
    exact dtypes bridge/runtime.py feeds the jitted step."""
    import jax.numpy as jnp
    import numpy as np

    from ..bridge.kernel import HostBatch

    W, P = bk.W, BRIDGE_PAD
    batch = HostBatch(
        t_slot=np.zeros((W, P), np.int32), t_dl=np.zeros((W, P), np.int64),
        t_seq=np.zeros((W, P), np.int64), t_mask=np.zeros((W, P), bool),
        c_slot=np.zeros((W, P), np.int32), c_mask=np.zeros((W, P), bool),
        s_ctr=np.zeros((W, P), np.uint64), s_base=np.zeros((W, P), np.int64),
        s_slot=np.zeros((W, P), np.int32), s_seq=np.zeros((W, P), np.int64),
        s_thr=np.zeros((W, P), np.uint64),
        s_lossall=np.zeros((W, P), bool),
        s_lat_lo=np.zeros((W, P), np.int64),
        s_lat_w=np.ones((W, P), np.int64),
        s_mask=np.zeros((W, P), bool), s_live=np.zeros((W, P), bool),
        clock=np.zeros((W,), np.int64), advance=np.zeros((W,), bool))
    return tuple(jnp.asarray(x) for x in batch)


def _bridge_ctx():
    bk = _bridge_kernel()

    @contextlib.contextmanager
    def ctx():
        with bk._jax.default_device(bk.device), bk._enable_x64():
            yield
    return ctx


def _build_bridge_step() -> Built:
    bk = _bridge_kernel()
    ctx = _bridge_ctx()
    with ctx():
        args = (bk.state, bk._mb, bk._net_k0, bk._net_k1) \
            + _bridge_batch_args(bk)
    return Built(fn=bk._fn, args=args, ctx=ctx)


def _build_bridge_drain() -> Built:
    bk = _bridge_kernel()
    return Built(fn=bk._drain_fn, args=(bk.state, bk._mb),
                 ctx=_bridge_ctx())


def registry() -> Dict[str, TraceProgram]:
    """Every hot-path program the sweep actually dispatches, by name.
    Builders are lazy (nothing imports jax until a check runs)."""
    progs = [
        TraceProgram(
            "engine.run", "DeviceEngine.run while-loop (donated step "
            f"path, raft bug config, W={RUN_WORLDS})",
            _build_engine_run, budget=True, donates=True,
            unit_div=RUN_WORLDS, packed=True),
        TraceProgram(
            "engine.run_blackbox", "DeviceEngine.run with the flight "
            f"recorder aboard (EngineConfig(blackbox={BLACKBOX_K}), "
            f"raft bug config, W={RUN_WORLDS}) — the per-step ring "
            "writes must hold the packed narrow-lane discipline and "
            "the K=64 state_bytes_per_world ceiling",
            _build_engine_run_blackbox, budget=True, donates=True,
            unit_div=RUN_WORLDS, packed=True),
        TraceProgram(
            "engine.pallas_step", "fused Pallas step kernel "
            f"(interpret mode, raft bug config, W={PALLAS_WORLDS}, "
            "docs/perf.md Roofline round 2)", _build_pallas_step,
            budget=True, donates=True, unit_div=PALLAS_WORLDS,
            packed=True),
        TraceProgram(
            "engine.push_many", "single-pass outbox insert (queue "
            "scatter core of the step)", _build_push_many),
        TraceProgram(
            "engine.refill_select", "recycle-slot select (donated old "
            "batch)", _build_refill_select, budget=True, donates=True),
        TraceProgram(
            "sweep.superstep", "pipelined superstep runner "
            f"(W={SWEEP_WORLDS}, chunk_steps={SWEEP_CHUNK_STEPS}, "
            f"k_max={SWEEP_K_MAX})", _build_superstep(False),
            budget=True, donates=True),
        TraceProgram(
            "sweep.superstep_min_one", "superstep min_one variant (epoch-"
            "first dispatch cadence)", _build_superstep(True),
            budget=True, donates=True),
        TraceProgram(
            "sweep.superstep_coverage", "superstep with the retire-time "
            "coverage fold (metrics on)", _build_superstep_coverage),
        TraceProgram(
            "sweep.coverage_endfold", "boundary coverage fold (resume "
            "pre-pass / end-of-sweep)", _build_endfold),
        TraceProgram(
            "sweep.compactor", "on-device stable active-first compaction "
            "(deliberately undonated: gather outputs cannot alias)",
            _build_compactor, budget=True, donates=False),
        TraceProgram(
            "triage.candidate_eval", "batched ddmin candidate sweep "
            f"(C={TRIAGE_CANDS} candidate schedules x F={TRIAGE_ROWS} "
            "rows over the pair_restart engine, docs/triage.md)",
            _build_triage_candidate_eval, budget=True, donates=True),
        TraceProgram(
            "search.generate", "guided-search harvest + mutate program "
            f"(W={SEARCH_WORLDS} slots x F={SEARCH_ROWS} rows over the "
            "guided_pair family engine, docs/search.md; lineage lanes + "
            "operator outcome table aboard (obs/lineage.py); "
            "deliberately undonated: it only reads the state the refill "
            "then donates)", _build_search_generate, budget=True,
            donates=False, packed=True),
        TraceProgram(
            "sweep.compactor_sched", "guided compaction: state + "
            "per-slot schedules + lineage lanes permuted in one "
            "dispatch (undonated like sweep.compactor — gathers cannot "
            "alias)", _build_compactor_sched, budget=True,
            donates=False),
        TraceProgram(
            "sweep.fused_hunt", "whole-hunt fused program: the "
            "occupancy loop — compaction, retiring-tail harvest, "
            "coverage fold, guided generate, refill, seed cursor — in "
            f"ONE dispatch (W={SEARCH_WORLDS}, "
            f"chunk_steps={SWEEP_CHUNK_STEPS}, k={SWEEP_K_MAX}, guided "
            "pair family, lineage on; undonated v1 — per-seed buffers "
            "and loop state round-trip by value, docs/perf.md "
            "Whole-hunt residency)", _build_fused_hunt, budget=True,
            donates=False, packed=True),
        TraceProgram(
            "actorc.tpc_run", "compiled two-phase-commit run loop "
            f"(actorc spec, W={ACTORC_WORLDS}; TRC005 holds the "
            "compiler to its by-construction widen/narrow claim, "
            "docs/actorc.md)", _build_actorc_run("tpc"), budget=True,
            donates=True, unit_div=ACTORC_WORLDS, packed=True),
        TraceProgram(
            "actorc.pb_run", "compiled primary-backup run loop "
            f"(actorc spec, W={ACTORC_WORLDS}; closes the BUD002 gap — "
            "every shipped actorc family step program is in the "
            "budget ledger)", _build_actorc_run("pb"), budget=True,
            donates=True, unit_div=ACTORC_WORLDS, packed=True),
        TraceProgram(
            "actorc.paxos_run", "compiled multi-decree Paxos run loop "
            f"(DSL-only family, W={ACTORC_WORLDS})",
            _build_actorc_run("paxos"), budget=True, donates=True,
            unit_div=ACTORC_WORLDS, packed=True),
        TraceProgram(
            "bridge.step", "bridge decision-kernel lockstep round "
            f"(W={BRIDGE_SLOTS}, cap={BRIDGE_CAP})", _build_bridge_step,
            x64="required", budget=True, donates=True),
        TraceProgram(
            "bridge.drain", "bridge pop-only drain round",
            _build_bridge_drain, x64="required", budget=True,
            donates=True),
    ]
    return {p.name: p for p in progs}


# -- rule checks -------------------------------------------------------------

def _x64_ctx():
    import jax

    ctx = getattr(jax, "enable_x64", None)
    if ctx is None:  # pragma: no cover — newer jax
        from jax.experimental import enable_x64 as ctx
    return ctx


def _finding(program: str, rule: str, msg: str) -> Finding:
    r = RULES[rule]
    return Finding(f"trace/{program}", 0, rule,
                   f"{r.title}: {msg} — {r.suggestion}")


def check_jaxpr_rules(name: str, jaxpr) -> List[Finding]:
    """TRC001/TRC002 over one traced program."""
    findings: List[Finding] = []
    for eqn in iter_eqns(jaxpr):
        prim = eqn.primitive.name
        if prim in CALLBACK_PRIMS:
            cb = eqn.params.get("callback")
            what = f" ({cb!r})" if cb is not None else ""
            findings.append(_finding(
                name, "TRC001",
                f"`{prim}` primitive{what}{_where(eqn)}"))
        elif prim in NONDET_PRIMS:
            findings.append(_finding(
                name, "TRC002", f"`{prim}` primitive{_where(eqn)}"))
        elif prim == "sort" and eqn.params.get("is_stable") is False:
            # Equal keys then land in backend-chosen order.
            findings.append(_finding(
                name, "TRC002",
                f"unstable `sort` (is_stable=False){_where(eqn)}"))
        elif prim in SCATTER_ACCUM_PRIMS \
                and not eqn.params.get("unique_indices", False):
            import numpy as _np

            dt = getattr(eqn.outvars[0].aval, "dtype", None)
            if dt is not None and _np.issubdtype(dt, _np.floating):
                findings.append(_finding(
                    name, "TRC002",
                    f"float `{prim}` without unique_indices (reduction "
                    f"order is backend-chosen){_where(eqn)}"))
    return findings


def check_narrow_discipline(name: str, jaxpr) -> List[Finding]:
    """TRC005 over one traced *packed* program: every
    ``convert_element_type`` that widens a narrow integer lane
    (i8/i16 -> i32/i64) must originate in engine/lanes.py — the
    ``widen()`` helper and the module's own index casts are the
    sanctioned sites. Anything else is an implicit promotion: a narrow
    lane leaking wide through mixed-dtype arithmetic, exactly the
    regression the packed profile exists to prevent. (The dual-trace
    machinery that backs TRC003 exposes every equation's operand and
    result dtypes; this walk reuses it.)"""
    findings: List[Finding] = []
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        src = getattr(eqn.invars[0].aval, "dtype", None)
        dst = eqn.params.get("new_dtype")
        if src is None or dst is None:
            continue
        if str(src) not in NARROW_INT_DTYPES \
                or str(dst) not in WIDE_INT_DTYPES:
            continue
        where = _where(eqn)
        if not where:
            # No source attribution (e.g. a synthesized const cast):
            # nothing actionable to report, and no narrow lane of ours
            # lacks a source line.
            continue
        if SANCTIONED_WIDEN_FILE in where:
            continue
        findings.append(_finding(
            name, "TRC005", f"{src} -> {dst} widening{where}"))
    return findings


def check_x64_invariance(name: str, prog: TraceProgram,
                         built: Built) -> List[Finding]:
    """TRC003: trace twice — plain and under ``enable_x64`` — and demand
    identical output dtypes plus a float64-free x64 trace. (int64 index
    arithmetic under x64 is exact and tolerated; float64 intermediates
    round differently than the f32 they silently replace.)"""
    import jax

    findings: List[Finding] = []
    tfn, targs = built.for_trace
    with built.ctx():
        base = jax.make_jaxpr(tfn)(*targs)
    try:
        with built.ctx(), _x64_ctx()():
            wide = jax.make_jaxpr(tfn)(*targs)
    except Exception as exc:  # the program cannot even trace under x64
        return [_finding(name, "TRC003",
                         f"fails to trace under jax_enable_x64: "
                         f"{type(exc).__name__}: {exc}")]
    b_out = [str(v.aval.dtype) for v in base.jaxpr.outvars]
    w_out = [str(v.aval.dtype) for v in wide.jaxpr.outvars]
    if b_out != w_out:
        diff = [(a, b) for a, b in zip(b_out, w_out) if a != b][:4]
        findings.append(_finding(
            name, "TRC003",
            f"output dtypes change with the x64 flag: {diff} "
            f"({sum(a != b for a, b in zip(b_out, w_out))} outputs)"))
    acc: set = set()
    _aval_dtypes(wide.jaxpr, acc)
    bad = sorted(d for d in acc if d in ("float64", "complex128"))
    if bad:
        findings.append(_finding(
            name, "TRC003",
            f"{'/'.join(bad)} intermediates appear under jax_enable_x64 "
            "(an unpinned float dtype — f32 math silently widens)"))
    return findings


def check_trace_rules(name: str, prog: TraceProgram,
                      built: Optional[Built] = None) -> List[Finding]:
    """The trace-only rule families (no XLA compile): TRC001/002 on the
    program's jaxpr, TRC003 via the dual trace for non-x64 programs."""
    import jax

    built = built or prog.build()
    findings: List[Finding] = []
    tfn, targs = built.for_trace
    if prog.x64 == "required":
        with built.ctx(), _x64_ctx()():
            jaxpr = jax.make_jaxpr(tfn)(*targs)
        findings.extend(check_jaxpr_rules(name, jaxpr.jaxpr))
        acc: set = set()
        _aval_dtypes(jaxpr.jaxpr, acc)
        if "complex128" in acc:
            findings.append(_finding(
                name, "TRC003", "complex128 intermediates in an x64 "
                "program"))
    else:
        with built.ctx():
            jaxpr = jax.make_jaxpr(tfn)(*targs)
        findings.extend(check_jaxpr_rules(name, jaxpr.jaxpr))
        if prog.packed:
            findings.extend(check_narrow_discipline(name, jaxpr.jaxpr))
        findings.extend(check_x64_invariance(name, prog, built))
    return findings


def measure_program(name: str, prog: TraceProgram,
                    built: Optional[Built] = None) -> Dict[str, Any]:
    """Fresh-compile one budget program and extract its ledger metrics
    (:func:`budgets.measure_compiled`)."""
    built = built or prog.build()
    with built.ctx():
        lowered = built.fn.lower(*built.args)
        comp = _budgets.compile_fresh(lowered)
        return _budgets.measure_compiled(comp, unit_div=prog.unit_div)


# -- the pass entry ----------------------------------------------------------

def run_trace(programs: Optional[List[str]] = None,
              budget_check: bool = True,
              ledger_path: Optional[str] = None,
              ) -> Tuple[List[Finding], Dict[str, Dict[str, Any]]]:
    """Run tracelint over the registered programs.

    Returns ``(findings, measurements)``. Trace rules (TRC001-003) run on
    every selected program; with ``budget_check`` the budget programs are
    additionally compiled fresh and diffed against the ledger
    (TRC004/BUD001/BUD002). Measurements are returned either way (empty
    without ``budget_check``) so ``tools/update_budgets.py`` can reuse
    this exact code path for regeneration.
    """
    regs = registry()
    if programs:
        unknown = [p for p in programs if p not in regs]
        if unknown:
            raise KeyError(f"unknown program(s): {unknown}; known: "
                           f"{sorted(regs)}")
        regs = {k: v for k, v in regs.items() if k in programs}
    findings: List[Finding] = []
    measured: Dict[str, Dict[str, Any]] = {}
    for name, prog in regs.items():
        try:
            built = prog.build()
        except Exception as exc:
            findings.append(_finding(
                name, "BUD002",
                f"program failed to build: {type(exc).__name__}: {exc}"))
            continue
        findings.extend(check_trace_rules(name, prog, built))
        if budget_check and prog.budget:
            measured[name] = measure_program(name, prog, built)
    if budget_check:
        ledger = _budgets.load_ledger(ledger_path)
        regs_all = registry() if programs else regs
        findings.extend(_budgets.diff_ledger(
            measured, ledger,
            registered=sorted(regs_all) if not programs else None,
            donates={k: v.donates for k, v in regs.items()}))
    findings.sort(key=lambda f: (f.path, f.rule))
    return findings, measured
