"""The checked-in cost-budget ledger (``analysis/budgets.json``).

PR 3 pinned ONE number — flops per world-step of the engine run loop — as
a tier-1 constant in ``tests/test_queue_insert.py``. This module
generalizes that into a ledger covering every registered hot-path program
(:mod:`.tracelint`): per program, XLA's own ``cost_analysis()`` flops and
bytes, ``memory_analysis()`` temp/peak sizes, and the donation
``alias_fraction``, each paired with an explicit budget ceiling. The
tracelint gate re-measures and diffs on every ``make lint``, so an op- or
peak-regression in a hot program fails CI *before* a bench round ever
runs — the SCALE-Sim-style "validate the cost model per change" loop
(PAPERS.md), applied to the simulator itself.

Budgets RATCHET: ``tools/update_budgets.py`` keeps an existing ceiling
whenever the fresh measurement still fits (no churn when code merely
improves) and requires a ``--reason`` line to raise one, recorded in the
ledger's ``justification`` field.

Fresh-compile caveat (docs/detlint.md): executables deserialized from the
persistent compilation cache LOSE their cost/memory statistics
(``alias_size_in_bytes`` reads 0), so every measurement here compiles
fresh via :func:`compile_fresh`, exactly like the tier-1 budget tests.
"""
from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, List, Optional, Tuple

from .pragmas import Finding
from .rules import RULES

DEFAULT_LEDGER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "budgets.json")
LEDGER_SCHEMA = "madsim.tracelint.budgets/1"

# Headroom factor applied when a budget must be (re)established: wide
# enough to absorb XLA version noise, tight enough that a real op-count
# regression (the fusion-cloning failure mode of docs/perf.md r7) trips.
HEADROOM = 1.15

# Relative tolerance on the donation fraction: replicated scalar args
# shift the per-device ratio by O(bytes_scalar / bytes_state).
ALIAS_TOL = 0.005


def compile_fresh(lowered):
    """Compile BYPASSING the persistent compilation cache: an executable
    deserialized from the cache loses parts of its cost/memory statistics
    (``alias_size_in_bytes`` reads 0), which would let the budget gates
    silently pass-or-fail on cache state instead of on the program. The
    cache singleton initializes once per process and then ignores config
    updates, so it must be reset around the config flip (and reset back
    after, so later compiles re-attach to the directory cache)."""
    import jax

    try:
        from jax._src import compilation_cache as _cc
        reset = _cc.reset_cache
    except (ImportError, AttributeError):  # pragma: no cover — jax drift
        reset = lambda: None  # noqa: E731

    prev = jax.config.jax_compilation_cache_dir
    reset()
    jax.config.update("jax_compilation_cache_dir", None)
    try:
        return lowered.compile()
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
        reset()


def measure_compiled(comp, unit_div: Optional[int] = None) -> Dict[str, Any]:
    """Extract the ledger metrics from a (freshly) compiled executable.

    All sizes are per-device (XLA reports the per-shard module); ratios
    — ``alias_fraction``, ``peak_over_arg`` — are therefore
    shard-invariant and the ones the gates compare. ``unit_div`` divides
    flops into a per-world figure for programs with a world axis.
    """
    ca = comp.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax: one dict per device
        ca = ca[0] if ca else {}
    ma = comp.memory_analysis()
    if isinstance(ma, (list, tuple)):  # pragma: no cover — jax drift
        ma = ma[0]
    arg = int(ma.argument_size_in_bytes)
    out_b = int(ma.output_size_in_bytes)
    temp = int(ma.temp_size_in_bytes)
    alias = int(ma.alias_size_in_bytes)
    peak = arg + out_b + temp - alias
    m: Dict[str, Any] = {
        "flops": float(ca.get("flops", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        "arg_bytes": arg,
        "out_bytes": out_b,
        "temp_bytes": temp,
        "alias_bytes": alias,
        "alias_fraction": round(alias / arg, 4) if arg else 0.0,
        "peak_over_arg": round(peak / arg, 4) if arg else 0.0,
    }
    if unit_div:
        m["flops_per_world"] = round(m["flops"] / unit_div, 2)
        # The packed-lane regression surface (docs/perf.md "Roofline
        # round 2"): bytes of world state per world, straight from
        # XLA's argument accounting. A lane silently widening back to
        # i32 shows up here before any bench round runs.
        m["state_bytes_per_world"] = round(arg / unit_div, 2)
    return m


# Metrics gated as ceilings (measured must stay <= budget) and the one
# gated as a floor (donation must keep landing).
CEILING_METRICS = ("flops", "flops_per_world", "state_bytes_per_world",
                   "bytes_accessed", "temp_bytes", "peak_over_arg")
FLOOR_METRICS = ("alias_fraction",)


def load_ledger(path: Optional[str] = None) -> Dict[str, Any]:
    path = path or DEFAULT_LEDGER
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("schema") != LEDGER_SCHEMA:
        raise ValueError(f"{path}: not a {LEDGER_SCHEMA} ledger "
                         f"(schema={doc.get('schema')!r})")
    return doc


def budget_for(ledger: Dict[str, Any], program: str,
               metric: str) -> Optional[float]:
    """One budget ceiling (or ``alias_fraction`` floor) from the ledger;
    None when absent. The tier-1 budget tests read through this, so the
    ledger is the single source of truth for every gate."""
    entry = ledger.get("programs", {}).get(program, {})
    field = entry.get(metric)
    if not isinstance(field, dict):
        return None
    key = "min" if metric in FLOOR_METRICS else "budget"
    return field.get(key)


def diff_ledger(measured: Dict[str, Dict[str, Any]],
                ledger: Dict[str, Any],
                registered: Optional[List[str]] = None,
                donates: Optional[Dict[str, bool]] = None) -> List[Finding]:
    """Compare fresh measurements against the checked-in ledger.

    - ``BUD001`` — a ceiling metric exceeds its budget.
    - ``TRC004`` — ``alias_fraction`` fell below its recorded floor on a
      program that declares donation (XLA dropped the aliasing).
    - ``BUD002`` — the ledger and the program registry drifted apart
      (measured/registered program missing from the ledger, or a ledger
      entry no registered program backs).
    """
    findings: List[Finding] = []
    programs = ledger.get("programs", {})
    donates = donates or {}

    def _f(program: str, rule: str, msg: str) -> None:
        r = RULES[rule]
        findings.append(Finding(f"trace/{program}", 0, rule,
                                f"{r.title}: {msg} — {r.suggestion}"))

    for name, m in sorted(measured.items()):
        entry = programs.get(name)
        if entry is None:
            _f(name, "BUD002", "program has no ledger entry in "
               "analysis/budgets.json")
            continue
        for metric in CEILING_METRICS:
            budget = budget_for(ledger, name, metric)
            if budget is None or metric not in m:
                continue
            if float(m[metric]) > float(budget):
                _f(name, "BUD001",
                   f"{metric} measured {m[metric]} > budget {budget} "
                   f"(ledger measured {entry[metric].get('measured')})")
        floor = budget_for(ledger, name, "alias_fraction")
        if floor is not None and donates.get(name, True):
            if float(m.get("alias_fraction", 0.0)) < float(floor) - ALIAS_TOL:
                _f(name, "TRC004",
                   f"alias_fraction measured {m.get('alias_fraction')} < "
                   f"recorded floor {floor}: a declared donation stopped "
                   "landing (peak memory now double-buffers)")
    if registered is not None:
        for name in sorted(programs):
            if name not in registered:
                _f(name, "BUD002",
                   "ledger entry names a program the registry no longer "
                   "registers")
    return findings


def make_entry(m: Dict[str, Any], note: str,
               prev: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """One ledger entry from a measurement, ratcheting existing budgets:
    a ceiling survives regeneration while the fresh measurement fits
    under it; otherwise it re-bases to ``measured * HEADROOM``."""
    prev = prev or {}
    entry: Dict[str, Any] = {"note": note}
    for metric in CEILING_METRICS:
        if metric not in m:
            continue
        val = float(m[metric])
        old = prev.get(metric, {}).get("budget") if isinstance(
            prev.get(metric), dict) else None
        if old is not None and val <= float(old):
            budget = float(old)
        elif metric == "peak_over_arg":
            budget = round(val * 1.05 + 1e-9, 3)
        elif metric == "state_bytes_per_world":
            # Arg bytes are a pure function of shapes/dtypes — no XLA
            # version noise — so the ceiling sits tight: one narrow
            # lane regressing to i32 must trip it.
            budget = float(math.ceil(val * 1.02))
        else:
            budget = float(math.ceil(val * HEADROOM))
        entry[metric] = {"measured": val, "budget": budget}
    af = float(m.get("alias_fraction", 0.0))
    old_min = prev.get("alias_fraction", {}).get("min") if isinstance(
        prev.get("alias_fraction"), dict) else None
    # The floor ratchets UP as well: if donation improved, keep the win.
    floor = round(max(float(old_min or 0.0), af - ALIAS_TOL), 4)
    entry["alias_fraction"] = {"measured": af, "min": floor}
    for k in ("arg_bytes", "out_bytes", "temp_bytes", "alias_bytes"):
        if k in m and k not in entry:
            entry[k] = m[k]
    return entry


def write_ledger(entries: Dict[str, Dict[str, Any]], reason: str,
                 path: Optional[str] = None) -> str:
    path = path or DEFAULT_LEDGER
    doc = {"schema": LEDGER_SCHEMA, "justification": reason,
           "programs": {k: entries[k] for k in sorted(entries)}}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")
    os.replace(tmp, path)
    return path
