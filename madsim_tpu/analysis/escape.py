"""Pass 1 — nondeterminism-escape detection.

An AST walk over every module in the target tree, flagging calls that
bypass the sim's interception layer (rules.py tables). The walk resolves
import aliases first (``import time as _walltime`` must not hide
``_walltime.time()``), then matches call sites:

- fully-qualified names against ``EXACT_CALLS`` / ``PREFIX_CALLS``
  (``time.time``, ``os.urandom``, ``secrets.*``, ...),
- bare method names against ``ATTR_CALLS`` for receivers with no static
  type (``loop.run_in_executor``),
- ``sorted``/``min``/``max``/``.sort`` whose key is ``id``/``hash`` —
  identity-keyed ordering of task or node collections varies with the
  process's allocation history, not the seed (DET006).

Scanning whole files over-approximates "reachable from @ms.test/@ms.main
bodies": it is sound (no reachable escape is missed) at the price of also
linting never-imported code, which is what a framework lint wants — users'
sim code that CI never executes is exactly the code dynamic checking
(tools/determinism_sweep.py) cannot protect.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional

from .pragmas import Allowlist, Finding, apply_pragmas, extract_pragmas
from .rules import (ATTR_CALLS, CLOCK_DEFAULT_CALLS, EXACT_CALLS,
                    PREFIX_CALLS, RULES)

_SORT_BUILTINS = {"sorted", "min", "max"}


class _ImportTable(ast.NodeVisitor):
    """alias -> fully-qualified dotted target, collected module-wide."""

    def __init__(self):
        self.names: Dict[str, str] = {}

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            bound = alias.asname or alias.name.split(".")[0]
            # `import a.b` binds `a`; `import a.b as c` binds c -> a.b.
            self.names[bound] = alias.name if alias.asname else alias.name.split(".")[0]

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.level or node.module is None:
            return  # relative imports stay in-package: never an stdlib escape
        for alias in node.names:
            if alias.name == "*":
                continue
            bound = alias.asname or alias.name
            self.names[bound] = f"{node.module}.{alias.name}"


def _dotted(node: ast.expr) -> Optional[List[str]]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


def _is_identity_key(expr: ast.expr) -> bool:
    """key=id / key=hash, or a lambda whose body is id(...)/hash(...)."""
    if isinstance(expr, ast.Name) and expr.id in ("id", "hash"):
        return True
    if isinstance(expr, ast.Lambda):
        body = expr.body
        return (isinstance(body, ast.Call) and isinstance(body.func, ast.Name)
                and body.func.id in ("id", "hash"))
    return False


class _CallScanner(ast.NodeVisitor):
    def __init__(self, path: str, imports: Dict[str, str]):
        self.path = path
        self.imports = imports
        self.findings: List[Finding] = []

    def _flag(self, node: ast.AST, rule: str, what: str) -> None:
        r = RULES[rule]
        self.findings.append(Finding(
            self.path, node.lineno, rule,
            f"{r.title}: `{what}` — {r.suggestion}"))

    def visit_Call(self, node: ast.Call) -> None:
        self._check_call(node)
        self.generic_visit(node)

    def _check_call(self, node: ast.Call) -> None:
        # Identity-keyed ordering (DET006).
        func = node.func
        is_sort_method = isinstance(func, ast.Attribute) and func.attr == "sort"
        is_sort_builtin = isinstance(func, ast.Name) and func.id in _SORT_BUILTINS
        if is_sort_method or is_sort_builtin:
            for kw in node.keywords:
                if kw.arg == "key" and _is_identity_key(kw.value):
                    name = func.attr if is_sort_method else func.id
                    self._flag(node, "DET006", f"{name}(key=id/hash)")
                    return

        parts = _dotted(func)
        if parts is None:
            return
        head = parts[0]
        resolved = self.imports.get(head)
        if resolved is not None:
            full = ".".join([resolved] + parts[1:])
        elif len(parts) > 1:
            full = ".".join(parts)
        else:
            full = None
        if full is not None:
            rule = EXACT_CALLS.get(full)
            if rule is None:
                for prefix, prule in PREFIX_CALLS.items():
                    if full.startswith(prefix) or full == prefix[:-1]:
                        rule = prule
                        break
            if rule is not None and (resolved is not None or _looks_stdlib(parts[0])):
                self._flag(node, rule, f"{full}()")
                return
            # Clock-DEFAULT decode calls (DET001 extension): escape only
            # when the time operand is omitted — time.ctime(virtual_us)
            # is a pure converter, time.ctime() reads the host clock.
            # *args makes the operand count unknowable: stay conservative
            # and treat the call as supplied.
            entry = CLOCK_DEFAULT_CALLS.get(full)
            if entry is not None and (resolved is not None
                                      or _looks_stdlib(parts[0])):
                crule, max_args = entry
                starred = any(isinstance(a, ast.Starred) for a in node.args)
                if len(node.args) <= max_args and not starred:
                    self._flag(node, crule, f"{full}() with the time "
                                            "operand defaulted")
                    return
        # Method-name-only table: receivers with no static type.
        if isinstance(func, ast.Attribute) and func.attr in ATTR_CALLS:
            self._flag(node, ATTR_CALLS[func.attr], f".{func.attr}()")


def _looks_stdlib(head: str) -> bool:
    """Unimported dotted heads still worth matching: the modules our call
    tables cover (handles the common `import x` collected at module top —
    already in the table — and guards against flagging `self.time()` etc.,
    whose head is a local object, not a module)."""
    return head in ("time", "os", "random", "uuid", "secrets", "socket",
                    "threading", "multiprocessing", "datetime", "concurrent",
                    "jax")


def scan_source(source: str, path: str) -> List[Finding]:
    """Lint one module's source; returns post-pragma findings."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return [Finding(path, exc.lineno or 1, "DET000",
                        f"syntax error: {exc.msg}")]
    table = _ImportTable()
    table.visit(tree)
    scanner = _CallScanner(path, table.names)
    scanner.visit(tree)
    return apply_pragmas(scanner.findings, extract_pragmas(source), path)


def iter_py_files(root: str, paths: List[str]) -> List[str]:
    """Expand files/directories under ``root`` into a sorted .py file list
    of root-relative paths."""
    out: List[str] = []
    for p in paths:
        full = os.path.join(root, p)
        if os.path.isfile(full) and p.endswith(".py"):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__" and not d.startswith("."))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn), root)
                    out.append(rel.replace(os.sep, "/"))
    return sorted(set(out))


def run_escape_pass(root: str, paths: List[str],
                    allowlist: Optional[Allowlist] = None) -> List[Finding]:
    allowlist = allowlist or Allowlist.empty()
    findings: List[Finding] = []
    for rel in iter_py_files(root, paths):
        with open(os.path.join(root, rel), encoding="utf-8") as f:
            source = f.read()
        findings.extend(scan_source(source, rel))
    return allowlist.filter(findings)
