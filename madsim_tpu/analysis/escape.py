"""Pass 1 — nondeterminism-escape detection.

An AST walk over every module in the target tree, flagging calls that
bypass the sim's interception layer (rules.py tables). The walk resolves
import aliases first (``import time as _walltime`` must not hide
``_walltime.time()``), then matches call sites:

- fully-qualified names against ``EXACT_CALLS`` / ``PREFIX_CALLS``
  (``time.time``, ``os.urandom``, ``secrets.*``, ...),
- bare method names against ``ATTR_CALLS`` for receivers with no static
  type (``loop.run_in_executor``),
- ``sorted``/``min``/``max``/``.sort`` whose key is ``id``/``hash`` —
  identity-keyed ordering of task or node collections varies with the
  process's allocation history, not the seed (DET006).

Scanning whole files over-approximates "reachable from @ms.test/@ms.main
bodies": it is sound (no reachable escape is missed) at the price of also
linting never-imported code, which is what a framework lint wants — users'
sim code that CI never executes is exactly the code dynamic checking
(tools/determinism_sweep.py) cannot protect.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional

from .pragmas import Allowlist, Finding, apply_pragmas, extract_pragmas
from .rules import (ATTR_CALLS, CLOCK_DEFAULT_CALLS, CONVERT_BUILTINS,
                    CONVERT_NP, DEVICE_CALLS, EXACT_CALLS, FETCH_NAMES,
                    HOT_LOOP_MARKER, HOT_LOOP_MODULES, LOOP_ATTR_CALLS,
                    PREFIX_CALLS, RULES, SYNC_CALLS, SYNC_METHODS)

_SORT_BUILTINS = {"sorted", "min", "max"}


class _ImportTable(ast.NodeVisitor):
    """alias -> fully-qualified dotted target, collected module-wide."""

    def __init__(self):
        self.names: Dict[str, str] = {}

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            bound = alias.asname or alias.name.split(".")[0]
            # `import a.b` binds `a`; `import a.b as c` binds c -> a.b.
            self.names[bound] = alias.name if alias.asname else alias.name.split(".")[0]

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.level or node.module is None:
            return  # relative imports stay in-package: never an stdlib escape
        for alias in node.names:
            if alias.name == "*":
                continue
            bound = alias.asname or alias.name
            self.names[bound] = f"{node.module}.{alias.name}"


def _dotted(node: ast.expr) -> Optional[List[str]]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


def _is_identity_key(expr: ast.expr) -> bool:
    """key=id / key=hash, or a lambda whose body is id(...)/hash(...)."""
    if isinstance(expr, ast.Name) and expr.id in ("id", "hash"):
        return True
    if isinstance(expr, ast.Lambda):
        body = expr.body
        return (isinstance(body, ast.Call) and isinstance(body.func, ast.Name)
                and body.func.id in ("id", "hash"))
    return False


class _CallScanner(ast.NodeVisitor):
    def __init__(self, path: str, imports: Dict[str, str]):
        self.path = path
        self.imports = imports
        self.findings: List[Finding] = []

    def _flag(self, node: ast.AST, rule: str, what: str) -> None:
        r = RULES[rule]
        self.findings.append(Finding(
            self.path, node.lineno, rule,
            f"{r.title}: `{what}` — {r.suggestion}"))

    def visit_Call(self, node: ast.Call) -> None:
        self._check_call(node)
        self.generic_visit(node)

    def _check_call(self, node: ast.Call) -> None:
        # Identity-keyed ordering (DET006).
        func = node.func
        is_sort_method = isinstance(func, ast.Attribute) and func.attr == "sort"
        is_sort_builtin = isinstance(func, ast.Name) and func.id in _SORT_BUILTINS
        if is_sort_method or is_sort_builtin:
            for kw in node.keywords:
                if kw.arg == "key" and _is_identity_key(kw.value):
                    name = func.attr if is_sort_method else func.id
                    self._flag(node, "DET006", f"{name}(key=id/hash)")
                    return

        parts = _dotted(func)
        if parts is None:
            return
        head = parts[0]
        resolved = self.imports.get(head)
        if resolved is not None:
            full = ".".join([resolved] + parts[1:])
        elif len(parts) > 1:
            full = ".".join(parts)
        else:
            full = None
        if full is not None:
            rule = EXACT_CALLS.get(full)
            if rule is None:
                for prefix, prule in PREFIX_CALLS.items():
                    if full.startswith(prefix) or full == prefix[:-1]:
                        rule = prule
                        break
            if rule is not None and (resolved is not None or _looks_stdlib(parts[0])):
                self._flag(node, rule, f"{full}()")
                return
            # Clock-DEFAULT decode calls (DET001 extension): escape only
            # when the time operand is omitted — time.ctime(virtual_us)
            # is a pure converter, time.ctime() reads the host clock.
            # *args makes the operand count unknowable: stay conservative
            # and treat the call as supplied.
            entry = CLOCK_DEFAULT_CALLS.get(full)
            if entry is not None and (resolved is not None
                                      or _looks_stdlib(parts[0])):
                crule, max_args = entry
                starred = any(isinstance(a, ast.Starred) for a in node.args)
                if len(node.args) <= max_args and not starred:
                    self._flag(node, crule, f"{full}() with the time "
                                            "operand defaulted")
                    return
        # Method-name-only table: receivers with no static type.
        if isinstance(func, ast.Attribute) and func.attr in ATTR_CALLS:
            self._flag(node, ATTR_CALLS[func.attr], f".{func.attr}()")
            return
        # Receiver-scoped method table: `loop.time()` reads the host
        # monotonic clock, but the method name alone is far too common
        # to flag (`self.time()` is the shim loop's own virtual clock) —
        # the receiver must be a bare name that IS an event-loop handle
        # by naming convention (`loop`, `event_loop`, ...).
        if isinstance(func, ast.Attribute) and func.attr in LOOP_ATTR_CALLS \
                and isinstance(func.value, ast.Name):
            lrule, receivers = LOOP_ATTR_CALLS[func.attr]
            rid = func.value.id
            if rid in receivers or any(rid.endswith("_" + r)
                                       for r in receivers):
                self._flag(node, lrule, f"{rid}.{func.attr}()")


def _looks_stdlib(head: str) -> bool:
    """Unimported dotted heads still worth matching: the modules our call
    tables cover (handles the common `import x` collected at module top —
    already in the table — and guards against flagging `self.time()` etc.,
    whose head is a local object, not a module)."""
    return head in ("time", "os", "random", "uuid", "secrets", "socket",
                    "threading", "multiprocessing", "datetime", "concurrent",
                    "jax")


# ---------------------------------------------------------------------------
# Sync-discipline pass (DET008/DET009) — hot-loop modules only
# ---------------------------------------------------------------------------

def _root_name(expr: ast.expr) -> Optional[str]:
    """The base Name of an Attribute/Subscript-free attribute chain
    (``a.b.c`` -> ``a``); None for anything rooted elsewhere."""
    while isinstance(expr, ast.Attribute):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


class _SyncScanner:
    """One *scope* (module body or one function body) of the hot-loop
    sync-discipline pass.

    The pass replays the scope's statements in source order, keeping a
    set of names last assigned from a device-producing expression
    (``jnp.*`` calls, ``jax.device_put``, ``shard_worlds``, or anything
    mentioning an already-tainted name) — and cleared again by
    assignment from the sanctioned ``_fetch`` hook (or any host
    expression). Conversions of tainted names (DET009) and the explicit
    blocking-sync APIs (DET008) are flagged wherever they appear.

    Source-order replay over a tree is a heuristic, not a dataflow
    analysis: branches and loops are linearized, and closures start
    untainted. That is the right price for a lint — it is exact on the
    straight-line hot loops it guards, and a miss only ever defers to
    the runtime counted-``_fetch`` tests.
    """

    def __init__(self, path: str, imports: Dict[str, str],
                 findings: List[Finding]):
        self.path = path
        self.imports = imports
        self.findings = findings
        self.tainted: set = set()

    # -- name resolution ----------------------------------------------------
    def _full(self, expr: ast.expr) -> Optional[str]:
        parts = _dotted(expr)
        if parts is None:
            return None
        head = self.imports.get(parts[0])
        return ".".join([head] + parts[1:]) if head else ".".join(parts)

    def _is_fetch_expr(self, expr: ast.expr) -> bool:
        """Does the expression materialize HOST data (contain a `_fetch`
        or `jax.device_get` call)?"""
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Name) and \
                        node.func.id in FETCH_NAMES:
                    return True
                full = self._full(node.func)
                if full in SYNC_CALLS:
                    return True
        return False

    def _is_device_call(self, call: ast.Call) -> bool:
        full = self._full(call.func)
        if full is None:
            return False
        if full in DEVICE_CALLS or full.startswith("jax.numpy."):
            return True
        return isinstance(call.func, ast.Name) and call.func.id in DEVICE_CALLS

    def _is_device_expr(self, expr: ast.expr) -> bool:
        """Does the expression produce device-resident data?

        True when it contains a device-producing call (``jnp.*``,
        ``jax.device_put``, ``shard_worlds``) anywhere, or when it IS a
        direct alias of a tainted name (bare name / tuple of names /
        ternary between them). A call *mentioning* a tainted name does
        NOT propagate taint — most such calls (``eng.observe(state)``,
        ``ckpt_aux(...)``) return host data, and the conversions the rule
        hunts re-materialize a device value someone just computed.
        """
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) and self._is_device_call(node):
                return True
        return self._is_alias_of_tainted(expr)

    def _is_alias_of_tainted(self, expr: ast.expr) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in self.tainted
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self._is_alias_of_tainted(e) for e in expr.elts)
        if isinstance(expr, ast.Starred):
            return self._is_alias_of_tainted(expr.value)
        if isinstance(expr, ast.IfExp):
            return (self._is_alias_of_tainted(expr.body)
                    or self._is_alias_of_tainted(expr.orelse))
        return False

    # -- taint bookkeeping --------------------------------------------------
    def _assign_targets(self, target: ast.expr, device: bool) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign_targets(elt, device)
        elif isinstance(target, ast.Starred):
            self._assign_targets(target.value, device)
        elif isinstance(target, ast.Name):
            (self.tainted.add if device
             else self.tainted.discard)(target.id)
        # Attribute/Subscript targets: container mutation, no name to track.

    def _classify_and_assign(self, targets: List[ast.expr],
                             value: ast.expr) -> None:
        device = (not self._is_fetch_expr(value)) \
            and self._is_device_expr(value)
        for t in targets:
            self._assign_targets(t, device)

    # -- findings -----------------------------------------------------------
    def _flag(self, node: ast.AST, rule: str, what: str) -> None:
        r = RULES[rule]
        self.findings.append(Finding(
            self.path, node.lineno, rule,
            f"{r.title}: {what} — {r.suggestion}"))

    def _check_expr(self, node: ast.AST) -> None:
        if isinstance(node, ast.Attribute):
            full = self._full(node)
            if full in SYNC_CALLS:
                self._flag(node, "DET008", f"`{full}`")
            return
        if not isinstance(node, ast.Call):
            return
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in SYNC_METHODS \
                and not node.args:
            self._flag(node, "DET008", f"`.{func.attr}()`")
            return
        # Host conversions: np.asarray/np.array/np.copy, float/int/bool.
        is_np = False
        full = self._full(func)
        if full is not None and full.startswith("numpy.") \
                and full.split(".", 1)[1] in CONVERT_NP:
            is_np = True
        is_builtin = (isinstance(func, ast.Name)
                      and func.id in CONVERT_BUILTINS
                      and func.id not in self.imports)
        if not (is_np or is_builtin) or len(node.args) < 1:
            return
        arg = node.args[0]
        if isinstance(arg, ast.Call):
            inner = self._full(arg.func)
            if inner is not None and (inner.startswith("jax.numpy.")
                                      or inner.startswith("jax.")
                                      and inner not in SYNC_CALLS
                                      and not inner.startswith("jax.tree")):
                self._flag(node, "DET008",
                           f"`{'np.' if is_np else ''}"
                           f"{func.attr if is_np else func.id}"
                           f"({inner}(...))` materializes a fresh device "
                           "computation inline")
            return
        root = _root_name(arg)
        if root is not None and root in self.tainted and \
                not isinstance(arg, ast.Subscript):
            name = func.attr if is_np else func.id
            self._flag(node, "DET009",
                       f"`{name}({ast.unparse(arg)})` — `{root}` was last "
                       "bound to a device value")

    # -- ordered replay -----------------------------------------------------
    def run(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _visit_exprs(self, node: ast.AST) -> None:
        """Flag candidates in an expression tree, skipping nested
        function/lambda bodies (their own scopes)."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _SyncScanner(self.path, self.imports, self.findings).run(node.body)
            return
        if isinstance(node, ast.Lambda):
            return
        self._check_expr(node)
        for child in ast.iter_child_nodes(node):
            self._visit_exprs(child)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sub = _SyncScanner(self.path, self.imports, self.findings)
            sub.run(stmt.body)
            return
        if isinstance(stmt, ast.ClassDef):
            for s in stmt.body:
                self._stmt(s)
            return
        if isinstance(stmt, ast.Assign):
            self._visit_exprs(stmt.value)
            self._classify_and_assign(stmt.targets, stmt.value)
            return
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._visit_exprs(stmt.value)
            self._classify_and_assign([stmt.target], stmt.value)
            return
        if isinstance(stmt, ast.AugAssign):
            self._visit_exprs(stmt.value)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._visit_exprs(stmt.iter)
            self._classify_and_assign([stmt.target], stmt.iter)
            for s in stmt.body + stmt.orelse:
                self._stmt(s)
            return
        if isinstance(stmt, (ast.While,)):
            self._visit_exprs(stmt.test)
            for s in stmt.body + stmt.orelse:
                self._stmt(s)
            return
        if isinstance(stmt, ast.If):
            self._visit_exprs(stmt.test)
            for s in stmt.body + stmt.orelse:
                self._stmt(s)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._visit_exprs(item.context_expr)
                if item.optional_vars is not None:
                    self._classify_and_assign([item.optional_vars],
                                              item.context_expr)
            for s in stmt.body:
                self._stmt(s)
            return
        if isinstance(stmt, ast.Try):
            for s in stmt.body:
                self._stmt(s)
            for h in stmt.handlers:
                for s in h.body:
                    self._stmt(s)
            for s in stmt.orelse + stmt.finalbody:
                self._stmt(s)
            return
        # Expression statements, return/raise/assert/del/import/...: flag
        # candidates in any embedded expressions, no taint updates.
        self._visit_exprs(stmt)


def is_hot_loop_module(path: str, source: str) -> bool:
    """Hot-loop modules get the sync-discipline pass: the repo's known
    orchestration loops plus any file opting in via a first-line
    ``# tracelint: hot-loop`` marker."""
    if path in HOT_LOOP_MODULES:
        return True
    head = source.split("\n", 2)[:2]
    return any(HOT_LOOP_MARKER in line for line in head)


def run_sync_pass(tree: ast.Module, path: str,
                  imports: Dict[str, str]) -> List[Finding]:
    findings: List[Finding] = []
    _SyncScanner(path, imports, findings).run(tree.body)
    return findings


def scan_source(source: str, path: str,
                hot: Optional[bool] = None) -> List[Finding]:
    """Lint one module's source; returns post-pragma findings. ``hot``
    forces the sync-discipline pass on/off (default: auto-detect via
    :func:`is_hot_loop_module`)."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return [Finding(path, exc.lineno or 1, "DET000",
                        f"syntax error: {exc.msg}")]
    table = _ImportTable()
    table.visit(tree)
    scanner = _CallScanner(path, table.names)
    scanner.visit(tree)
    findings = scanner.findings
    if hot if hot is not None else is_hot_loop_module(path, source):
        findings = findings + run_sync_pass(tree, path, table.names)
    findings.sort(key=lambda f: (f.line, f.rule))
    # Pass 1 owns DET/TRC/BUD/PAR pragma codes for staleness (DET900);
    # SPC codes belong to pass 4 (speclint), which runs its own
    # staleness check over the spec's source files — an allow[SPC...]
    # on a handler line must not read as stale from here.
    return apply_pragmas(findings, extract_pragmas(source), path,
                         owned_prefixes=("DET", "TRC", "BUD", "PAR"))


def iter_py_files(root: str, paths: List[str]) -> List[str]:
    """Expand files/directories under ``root`` into a sorted .py file list
    of root-relative paths."""
    out: List[str] = []
    for p in paths:
        full = os.path.join(root, p)
        if os.path.isfile(full) and p.endswith(".py"):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__" and not d.startswith("."))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn), root)
                    out.append(rel.replace(os.sep, "/"))
    return sorted(set(out))


def run_escape_pass(root: str, paths: List[str],
                    allowlist: Optional[Allowlist] = None) -> List[Finding]:
    allowlist = allowlist or Allowlist.empty()
    findings: List[Finding] = []
    for rel in iter_py_files(root, paths):
        with open(os.path.join(root, rel), encoding="utf-8") as f:
            source = f.read()
        findings.extend(scan_source(source, rel))
    return allowlist.filter(findings)
