"""speclint — pass 4: a protocol verifier over actorc specs (SPC0xx).

Passes 1–2 police *source lines* and pass 3 polices *compiled jaxprs*;
this pass polices the layer in between: the protocol state machine an
:class:`~madsim_tpu.actorc.spec.ActorSpec` declares, BEFORE
:mod:`~madsim_tpu.actorc.compile` lowers it to packed lanes. The
premise is the same ahead-of-time argument the whole repo is built on
(PRISM-style model checking vs observed-run sampling, PAPERS.md): an
unhandled message kind, a counter that overflows its packed lane, or a
transition leaning on a DSL feature the lowering silently flattens are
all *spec* bugs — no fault schedule needs to find them, and no seed
sweep should have to.

How it works
------------
The compiler's craft is that the SAME transition callable runs under a
jnp backend (device) and a plain-int backend (host twin). speclint adds
a third backend: :class:`_LintCtx` executes every handler ONCE under an
*interval abstract domain* — reads return the lane's declared ``[lo,
hi]`` range, payload words return their declared word range, and
arithmetic propagates bounds — while recording the transition's writes,
sends, timer arms, RNG draws and lane reads, each tagged with the real
source line of the ctx call. The recorded effects feed the rule
families:

- **reachability** (SPC010/SPC012): kinds nobody seeds or emits;
  transitions with no effects at all;
- **exhaustiveness** (SPC011): every declared kind handled or
  explicitly listed in ``ActorSpec.ignore``;
- **timer discipline** (SPC020/SPC021): timers handled but never armed;
  multiple arms in one transition without a static disjointness proof
  (the single-timer-row lowering is last-write-wins);
- **lane-capacity proofs** (SPC030): a written value's static bound
  exceeds the packed at-rest dtype rail chosen by
  :func:`~madsim_tpu.actorc.spec.lane_dtype` — the overflow class
  tracelint's TRC005 cannot see because the saturating ``narrow`` is
  placed *by design*;
- **payload-bound proofs** (SPC031): a sent/armed/init word's static
  bound escapes the receiver's declared word range (which is exactly
  what the receiving handler's ``arg()`` read assumes);
- **RNG/effect budgets** (SPC040/SPC041): more than one send per
  transition without disjoint conditions (the single message row
  broadcasts ONE payload — per-destination payloads are a known DSL
  gap), and more than one RNG draw per event;
- **durability flow** (SPC050): a ``durable=False`` lane read by a
  handler in a spec with no ``on_restart`` hook — post-restart reads
  see the reset value with nothing to reconstruct it.

Disjointness is proved, not guessed: every abstract boolean carries the
set of literals it implies (itself, both operands of ``&``, the negated
operand of ``~``); two conditions are disjoint iff one implies a
literal the other implies negated. That is enough to accept the pb
family's watchdog/heartbeat re-arm split and reject everything the
lowering would silently last-write-wins.

Suppression follows the house rules: ``# detlint: allow[SPC...]``
pragmas on the offending handler line (stale ones are DET900, checked
by THIS pass — pass 1 does not own SPC codes), plus a spec-level
``lint_allow`` tuple for intentionally-buggy variants (the forgetful-
acceptor Paxos config allows SPC050 — the amnesia IS the experiment);
a ``lint_allow`` code that suppresses nothing is SPC900. ``("*",)`` is
the fixture escape hatch: it waives the whole pass.

``compile_actor``/``CompiledActor`` call :func:`gate_spec` right after
``validate_spec`` — a spec with findings does not lower. The CLI entry
(``python -m madsim_tpu.analysis spec``) lints the shipped families and
prints per-spec *protocol cards* (:func:`protocol_card`): the kinds ×
handlers matrix, the timer graph and the lane budget table, rendered
byte-stably so CI can diff two runs and repro bundles can carry their
protocol's static profile.
"""
from __future__ import annotations

import itertools
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from .pragmas import Finding, apply_pragmas, extract_pragmas
from .rules import RULES

__all__ = [
    "lint_spec", "gate_spec", "protocol_card", "run_spec_pass",
    "shipped_specs", "main_spec",
]

# int32 timer-delay / payload rails of the lowering (engine/lanes.py).
_I32 = (1 << 31) - 1

_IDS = itertools.count(1)


def _rail(dtype) -> Tuple[int, int]:
    """Inclusive saturation rails of a packed at-rest dtype."""
    import numpy as np

    info = np.iinfo(np.dtype(dtype))
    return int(info.min), int(info.max)


# ---------------------------------------------------------------------------
# The abstract domain
# ---------------------------------------------------------------------------

class _Abs:
    """An integer interval ``[lo, hi]`` (scalars and vectors alike —
    a vector is the interval of its elements)."""

    __slots__ = ("lo", "hi")

    def __init__(self, lo: int, hi: int):
        self.lo, self.hi = int(lo), int(hi)

    def __repr__(self):
        return f"[{self.lo}, {self.hi}]"

    def __bool__(self):
        from ..actorc.spec import SpecError

        raise SpecError(
            "Python control flow on a traced spec value (if/while/and/or "
            "on a ctx read) — use c.where()/when= instead; the compiler "
            "cannot lower a host branch")

    # -- arithmetic ---------------------------------------------------
    def __add__(self, o):
        o = _lift(o)
        return _Abs(self.lo + o.lo, self.hi + o.hi)

    __radd__ = __add__

    def __sub__(self, o):
        o = _lift(o)
        return _Abs(self.lo - o.hi, self.hi - o.lo)

    def __rsub__(self, o):
        return _lift(o).__sub__(self)

    def __mul__(self, o):
        o = _lift(o)
        ps = (self.lo * o.lo, self.lo * o.hi, self.hi * o.lo,
              self.hi * o.hi)
        return _Abs(min(ps), max(ps))

    __rmul__ = __mul__

    def __neg__(self):
        return _Abs(-self.hi, -self.lo)

    def __floordiv__(self, o):
        o = _lift(o)
        if o.lo <= 0:
            return _TOP
        return _Abs(self.lo // o.lo if self.lo < 0 else self.lo // o.hi,
                    self.hi // o.lo if self.hi > 0 else self.hi // o.hi)

    def __mod__(self, o):
        o = _lift(o)
        if o.lo <= 0:
            return _TOP
        # Python/device semantics: result in [0, divisor-1] for a
        # positive divisor, regardless of the dividend's sign.
        return _Abs(0, o.hi - 1)

    def __rmod__(self, o):
        return _lift(o).__mod__(self)

    def __rfloordiv__(self, o):
        return _lift(o).__floordiv__(self)

    # -- bitwise (non-negative operands; mixed signs widen) -----------
    def _bits_join(self, o):
        if self.lo < 0 or o.lo < 0:
            return _TOP
        hi = max(self.hi, o.hi)
        return _Abs(0, (1 << hi.bit_length()) - 1)

    def __or__(self, o):
        return self._bits_join(_lift(o))

    __ror__ = __or__
    __xor__ = __or__
    __rxor__ = __or__

    def __and__(self, o):
        o = _lift(o)
        if self.lo < 0 or o.lo < 0:
            return _TOP
        return _Abs(0, min(self.hi, o.hi))

    __rand__ = __and__

    def __invert__(self):
        return _Abs(-self.hi - 1, -self.lo - 1)

    def __lshift__(self, o):
        o = _lift(o)
        if self.lo < 0 or o.lo < 0 or o.hi > 63:
            return _TOP
        return _Abs(self.lo << o.lo, self.hi << o.hi)

    def __rlshift__(self, o):
        return _lift(o).__lshift__(self)

    def __rshift__(self, o):
        o = _lift(o)
        if self.lo < 0 or o.lo < 0 or o.hi > 63:
            return _TOP
        return _Abs(self.lo >> o.hi, self.hi >> o.lo)

    def __rrshift__(self, o):
        return _lift(o).__rshift__(self)

    # -- comparisons: fresh condition literals ------------------------
    def _cmp(self, _o):
        return _Cond()

    __lt__ = __le__ = __gt__ = __ge__ = __eq__ = __ne__ = _cmp
    __hash__ = None


_TOP = _Abs(-(1 << 31), (1 << 31) - 1)


def _lift(v) -> _Abs:
    if isinstance(v, _Abs):
        return v
    if isinstance(v, _Cond):
        return _Abs(0, 1)
    if isinstance(v, bool):
        return _Abs(int(v), int(v))
    if isinstance(v, int):
        return _Abs(v, v)
    from ..actorc.spec import SpecError

    raise SpecError(f"value {v!r} is outside the spec expression surface "
                    "(ints and ctx values only)")


class _Cond:
    """An abstract boolean, carrying the set of literals it *implies*:
    itself, both conjuncts of ``&``, the negated operand of ``~`` — the
    minimal machinery needed to PROVE two emission conditions disjoint
    (one implies a literal the other implies negated)."""

    __slots__ = ("id", "lits", "false")

    def __init__(self, lits=(), false: bool = False):
        self.id = next(_IDS)
        self.false = false
        self.lits = frozenset(lits) | {(self.id, True)}

    def __bool__(self):
        from ..actorc.spec import SpecError

        raise SpecError(
            "Python control flow on a traced spec condition — use "
            "c.where()/when= instead; the compiler cannot lower a host "
            "branch")

    def __and__(self, o):
        if o is True:
            return self
        if o is False:
            return _Cond(false=True)
        if isinstance(o, _Abs):
            return _Cond(self.lits)
        return _Cond(self.lits | o.lits, false=self.false or o.false)

    __rand__ = __and__

    def __or__(self, o):
        if o is True or isinstance(o, _Abs):
            return _Cond()
        if o is False:
            return _Cond(self.lits, false=self.false)
        return _Cond(self.lits & o.lits, false=self.false and o.false)

    __ror__ = __or__

    def __xor__(self, o):
        return _Cond()

    __rxor__ = __xor__

    def __invert__(self):
        return _Cond({(self.id, False)})

    def _cmp(self, _o):
        return _Cond()

    __lt__ = __le__ = __gt__ = __ge__ = __eq__ = __ne__ = _cmp
    __hash__ = None


def _disjoint(a, b) -> bool:
    """True iff conditions ``a`` and ``b`` provably never hold together."""
    if a is False or b is False:
        return True
    if a is True or b is True or not isinstance(a, _Cond) \
            or not isinstance(b, _Cond):
        return False
    if a.false or b.false:
        return True
    neg_b = {(i, not p) for i, p in b.lits}
    return bool(a.lits & neg_b)


# ---------------------------------------------------------------------------
# The lint backend (third Ctx implementation: abstract evaluation)
# ---------------------------------------------------------------------------

def _callsite() -> Tuple[str, int]:
    """(filename, line) of the spec code that invoked the ctx method:
    the first frame outside this module and the compiler."""
    f = sys._getframe(1)
    skip = (os.path.abspath(__file__),)
    while f is not None:
        fn = os.path.abspath(f.f_code.co_filename)
        if fn not in skip and not fn.endswith(os.sep + "compile.py"):
            return f.f_code.co_filename, f.f_lineno
        f = f.f_back
    return "<spec>", 0


class _LintNp:
    """Placeholder ``c.np`` backend tag: identical to neither jnp nor
    numpy, so backend-branching handlers take a deterministic arm."""


def _make_lint_ctx(spec, me_hi: int, msg=None):
    from ..actorc.compile import Ctx
    from ..actorc.spec import SCOPE_NODE, SCOPE_NODE_TABLE, SCOPE_WORLD, \
        SCOPE_WORLD_VEC, SpecError

    class _LintCtx(Ctx):
        np = _LintNp()

        def __init__(self):
            n = spec.n_nodes
            super().__init__(spec, 8, me=_Abs(0, me_hi), now=_Abs(0, _I32),
                             src=_Abs(0, n - 1), msg=msg)
            self._draws = 0
            self._reads: Dict[str, Tuple[str, int]] = {}
            self._sites: Dict[int, Tuple[str, int]] = {}

        # -- reads: the declared range IS the abstraction --------------
        def _read(self, lane: str, scope: str) -> _Abs:
            ln = self._spec.lane(lane)
            if ln.scope != scope:
                raise SpecError(
                    f"spec {self._spec.name!r}: lane {lane!r} has scope "
                    f"{ln.scope!r}; this read form needs {scope!r}")
            self._reads.setdefault(ln.name, _callsite())
            return _Abs(ln.lo, ln.hi)

        def read(self, lane):
            return self._read(lane, SCOPE_NODE)

        def read_node(self, lane, node):
            return self._read(lane, SCOPE_NODE)

        def read_at(self, lane, col):
            return self._read(lane, SCOPE_NODE_TABLE)

        def read_row(self, lane):
            return self._read(lane, SCOPE_NODE_TABLE)

        def read_vec_at(self, lane, idx):
            return self._read(lane, SCOPE_WORLD_VEC)

        def read_vec(self, lane):
            return self._read(lane, SCOPE_WORLD_VEC)

        def read_scalar(self, lane):
            return self._read(lane, SCOPE_WORLD)

        # -- expression helpers ----------------------------------------
        @staticmethod
        def where(c, a, b):
            if isinstance(a, _Cond) or isinstance(b, _Cond):
                return _Cond()
            a, b = _lift(a), _lift(b)
            return _Abs(min(a.lo, b.lo), max(a.hi, b.hi))

        @staticmethod
        def maximum(a, b):
            a, b = _lift(a), _lift(b)
            return _Abs(max(a.lo, b.lo), max(a.hi, b.hi))

        @staticmethod
        def minimum(a, b):
            a, b = _lift(a), _lift(b)
            return _Abs(min(a.lo, b.lo), min(a.hi, b.hi))

        @staticmethod
        def clip(x, lo, hi):
            x, lo, hi = _lift(x), _lift(lo), _lift(hi)
            return _Abs(min(max(x.lo, lo.lo), hi.hi),
                        min(max(x.hi, lo.lo), hi.hi))

        @staticmethod
        def popcount(_x):
            return _Abs(0, 32)

        @staticmethod
        def arange(k: int):
            return _Abs(0, max(int(k) - 1, 0))

        def others(self):
            return _Cond()

        # -- effect recording (call sites remembered per record) -------
        def _record(self, op, lane, idx, value, when):
            super()._record(op, lane, idx, value, when)
            self._sites[len(self._writes) - 1] = _callsite()

        def send(self, msg_name, dst, words=(), when=True):
            super().send(msg_name, dst, words, when)
            self._check_words(msg_name, tuple(words))
            self._sites[-len(self._sends)] = _callsite()

        def broadcast(self, msg_name, words=(), when=True, to=None):
            super().broadcast(msg_name, words, when, to)
            self._check_words(msg_name, tuple(words))
            self._sites[-len(self._sends)] = _callsite()

        def arm(self, timer, delay, words=(), when=True, dst=None):
            super().arm(timer, delay, words, when, dst)
            self._check_words(timer, tuple(words))
            self._sites[1_000_000 + len(self._arms)] = _callsite()

        # -- payload words / RNG ---------------------------------------
        def _payload_word(self, i: int):
            wd = self._msg.words[i]
            return _Abs(wd.lo, wd.hi)

        def _mark_draw(self):
            self._draws += 1
            self._sites[2_000_000 + self._draws] = _callsite()

        def _raw_u32(self):
            return _Abs(0, (1 << 32) - 1)

        def _uniform(self, lo, hi):
            return _Abs(int(lo), int(hi) - 1)  # engine parity: [lo, hi)

    return _LintCtx()


class _LintInitCtx:
    """Abstract ``init`` backend: records the world's seed events."""

    np = _LintNp()

    def __init__(self, spec):
        self._spec = spec
        self.events: List[Tuple[str, Tuple[Any, ...], Tuple[str, int]]] = []

    def event(self, msg: str, time, dst=0, src=None, words=()):
        from ..actorc.spec import SpecError

        m = self._spec.message(msg)
        if len(words) != len(m.words):
            raise SpecError(
                f"spec {self._spec.name!r}: init event {msg!r} needs "
                f"{len(m.words)} words ({[w.name for w in m.words]}); "
                f"got {len(words)}")
        self.events.append((msg, tuple(words), _callsite()))

    def uniform(self, lo: int, hi: int):
        return _Abs(int(lo), int(hi) - 1)

    def u32(self):
        return _Abs(0, (1 << 32) - 1)


# ---------------------------------------------------------------------------
# The pass
# ---------------------------------------------------------------------------

def _f(path: str, line: int, rule: str, msg: str) -> Finding:
    r = RULES[rule]
    return Finding(path, line, rule, f"{r.title}: {msg} — {r.suggestion}")


def _src(fn) -> Tuple[str, int]:
    code = getattr(fn, "__code__", None)
    if code is None:  # functools.partial / callables: best effort
        return "<spec>", 0
    return code.co_filename, code.co_firstlineno


def _word_bound_findings(spec, msg_name: str, words, site,
                         where: str) -> List[Finding]:
    """SPC031: each sent/armed/seeded word's interval must stay inside
    the declared word range — the receiving ``arg()`` read assumes it."""
    out = []
    m = spec.message(msg_name)
    for wd, val in zip(m.words, words):
        try:
            v = _lift(val)
        except Exception:
            continue
        if v.lo < wd.lo or v.hi > wd.hi:
            out.append(_f(site[0], site[1], "SPC031",
                          f"spec {spec.name!r}: {where} sends "
                          f"{msg_name!r} word {wd.name!r} with static "
                          f"bound [{v.lo}, {v.hi}], outside its declared "
                          f"range [{wd.lo}, {wd.hi}]"))
    return out


def _capacity_findings(spec, ctx, who: str) -> List[Finding]:
    """SPC030 over one transition's recorded writes: value interval vs
    the packed at-rest rail of the target lane's dtype."""
    from ..actorc.spec import lane_dtype
    from ..engine.lanes import PACKED

    out = []
    for i, (op, lane, _idx, value, _when) in enumerate(ctx._writes):
        ln = spec.lane(lane)
        lo, hi = _rail(lane_dtype(ln, PACKED))
        try:
            v = _lift(value)
        except Exception:
            continue
        if v.lo < lo or v.hi > hi:
            site = ctx._sites.get(i, ("<spec>", 0))
            out.append(_f(site[0], site[1], "SPC030",
                          f"spec {spec.name!r}: {who} writes lane "
                          f"{ln.name!r} with static bound "
                          f"[{v.lo}, {v.hi}], past the packed "
                          f"{'int8' if hi == 127 else 'int16' if hi == 32767 else 'int32'} "
                          f"rail [{lo}, {hi}] its declared range "
                          f"[{ln.lo}, {ln.hi}] selected"))
    for j, a in enumerate(ctx._arms, start=1):
        try:
            d = _lift(a.delay)
        except Exception:
            continue
        if d.lo < 0 or d.hi > _I32:
            site = ctx._sites.get(1_000_000 + j, ("<spec>", 0))
            out.append(_f(site[0], site[1], "SPC030",
                          f"spec {spec.name!r}: {who} arms {a.msg!r} "
                          f"with delay bound [{d.lo}, {d.hi}], outside "
                          f"the int32 timer-delay lane [0, {_I32}]"))
    return out


def _emission_findings(spec, ctx, who: str) -> List[Finding]:
    """SPC040/SPC021: >1 send (or arm) in one transition needs a static
    disjointness proof — the lowering has ONE message row and ONE timer
    row per step (last-write-wins ``where`` chains), and the message
    row broadcasts ONE payload to every destination."""
    out = []
    for kind, items, rule, gap in (
            ("send", ctx._sends, "SPC040",
             "the single merged message row broadcasts one payload — "
             "per-destination payloads and concurrent sends are a known "
             "DSL gap (docs/actorc.md)"),
            ("arm", ctx._arms, "SPC021",
             "the single merged timer row is last-write-wins — "
             "multi-timer arms are a known DSL gap (docs/actorc.md)")):
        for a in range(len(items)):
            for b in range(a + 1, len(items)):
                if _disjoint(items[a].when, items[b].when):
                    continue
                key = -(b + 1) if kind == "send" else 1_000_000 + b + 1
                site = ctx._sites.get(key, ("<spec>", 0))
                out.append(_f(site[0], site[1], rule,
                              f"spec {spec.name!r}: {who} {kind}s both "
                              f"{items[a].msg!r} and {items[b].msg!r} "
                              f"without provably-disjoint conditions; "
                              f"{gap}"))
    return out


def lint_spec(spec, root: Optional[str] = None) -> List[Finding]:
    """Run every SPC rule over ``spec``; returns pragma-filtered,
    allowance-filtered findings (the library entry — the compile gate,
    the CLI and the tests are shells over this).

    ``root``: paths in findings are rendered relative to it (default:
    cwd), matching the pass-1 convention.
    """
    from ..actorc.spec import SpecError, validate_spec

    root = os.path.abspath(root or os.getcwd())

    def rel(path: str) -> str:
        ap = os.path.abspath(path)
        if ap.startswith(root + os.sep):
            return os.path.relpath(ap, root).replace(os.sep, "/")
        return path.replace(os.sep, "/")

    findings: List[Finding] = []
    try:
        validate_spec(spec)
    except SpecError as exc:
        path, line = _src(spec.init)
        return [_f(rel(path), line, "SPC001", str(exc))]

    names = [m.name for m in spec.messages]
    spec_path, spec_line = _src(spec.init)
    ignore = tuple(getattr(spec, "ignore", ()))
    terminal = tuple(getattr(spec, "terminal", ()))
    for field, vals in (("ignore", ignore), ("terminal", terminal)):
        for nm in vals:
            if nm not in names:
                findings.append(_f(rel(spec_path), spec_line, "SPC013",
                                   f"spec {spec.name!r}: {field}=(...) "
                                   f"names unknown message {nm!r} "
                                   f"(declared: {names})"))

    # -- abstract init: the seed kinds --------------------------------
    seeded: Dict[str, Tuple[str, int]] = {}
    ictx = _LintInitCtx(spec)
    try:
        spec.init(ictx)
    except SpecError as exc:
        findings.append(_f(rel(spec_path), spec_line, "SPC001", str(exc)))
    except Exception as exc:  # abstract-eval escape: still pointed
        findings.append(_f(rel(spec_path), spec_line, "SPC001",
                           f"spec {spec.name!r}: init raised "
                           f"{type(exc).__name__} under abstract "
                           f"evaluation: {exc}"))
    for msg_name, words, site in ictx.events:
        seeded.setdefault(msg_name, site)
        for ff in _word_bound_findings(spec, msg_name, words, site,
                                       "init"):
            findings.append(ff._replace(path=rel(ff.path)))

    # -- abstract handlers ---------------------------------------------
    ctxs: Dict[str, Any] = {}
    for m in spec.messages:
        fn = spec.handlers.get(m.name)
        if fn is None:
            continue
        ctx = _make_lint_ctx(spec, spec.n_nodes - 1, msg=m)
        hpath, hline = _src(fn)
        try:
            fn(ctx)
        except SpecError as exc:
            findings.append(_f(rel(hpath), hline, "SPC001", str(exc)))
            continue
        except Exception as exc:
            findings.append(_f(rel(hpath), hline, "SPC001",
                               f"spec {spec.name!r}: handler for "
                               f"{m.name!r} raised {type(exc).__name__} "
                               f"under abstract evaluation: {exc}"))
            continue
        ctxs[m.name] = ctx

    rctx = None
    if spec.on_restart is not None:
        rctx = _make_lint_ctx(spec, spec.n_nodes - 1)
        rpath, rline = _src(spec.on_restart)
        try:
            spec.on_restart(rctx)
        except SpecError as exc:
            findings.append(_f(rel(rpath), rline, "SPC001", str(exc)))
            rctx = None
        except Exception as exc:
            findings.append(_f(rel(rpath), rline, "SPC001",
                               f"spec {spec.name!r}: on_restart raised "
                               f"{type(exc).__name__} under abstract "
                               f"evaluation: {exc}"))
            rctx = None

    # -- per-transition rules ------------------------------------------
    for m in spec.messages:
        ctx = ctxs.get(m.name)
        if ctx is None:
            continue
        hpath, hline = _src(spec.handlers[m.name])
        who = f"the {m.name!r} transition"
        for ff in _capacity_findings(spec, ctx, who) \
                + _emission_findings(spec, ctx, who):
            findings.append(ff._replace(path=rel(ff.path)))
        for k, snd in enumerate(ctx._sends):
            site = ctx._sites.get(-(k + 1), (hpath, hline))
            for ff in _word_bound_findings(spec, snd.msg, snd.words,
                                           site, who):
                findings.append(ff._replace(path=rel(ff.path)))
        for j, a in enumerate(ctx._arms, start=1):
            site = ctx._sites.get(1_000_000 + j, (hpath, hline))
            for ff in _word_bound_findings(spec, a.msg, a.words,
                                           site, who):
                findings.append(ff._replace(path=rel(ff.path)))
        if ctx._draws > 1:
            site = ctx._sites.get(2_000_000 + 2, (hpath, hline))
            findings.append(_f(
                rel(site[0]), site[1], "SPC041",
                f"spec {spec.name!r}: {who} draws {ctx._draws} times, "
                "but a transition may draw at most once per event (the "
                "static-draw-shape rule, docs/ACTORS.md); combine draws "
                "into one mapped value"))
        empty = not (ctx._writes or ctx._sends or ctx._arms
                     or ctx._bugs or ctx._draws)
        if empty and m.name not in terminal and m.name not in ignore:
            findings.append(_f(
                rel(hpath), hline, "SPC012",
                f"spec {spec.name!r}: the handler for {m.name!r} has no "
                "effects at all (no writes, sends, arms, bug flags or "
                "draws) — a dead transition; delete it, implement it, "
                "or declare the kind in terminal=(...)"))
        if m.name in terminal and (ctx._sends or ctx._arms):
            findings.append(_f(
                rel(hpath), hline, "SPC013",
                f"spec {spec.name!r}: {m.name!r} is declared terminal "
                "but its handler emits messages/timers — drop it from "
                "terminal=(...) or stop emitting"))

    if rctx is not None:
        rpath, rline = _src(spec.on_restart)
        who = "the on_restart hook"
        for ff in _capacity_findings(spec, rctx, who) \
                + _emission_findings(spec, rctx, who):
            findings.append(ff._replace(path=rel(ff.path)))
        for snd in rctx._sends:
            for ff in _word_bound_findings(spec, snd.msg, snd.words,
                                           (rpath, rline), who):
                findings.append(ff._replace(path=rel(ff.path)))
        for a in rctx._arms:
            for ff in _word_bound_findings(spec, a.msg, a.words,
                                           (rpath, rline), who):
                findings.append(ff._replace(path=rel(ff.path)))

    # -- exhaustiveness / reachability / timers ------------------------
    armed: Dict[str, str] = {}     # timer kind -> first armer
    for src_name, ctx in list(ctxs.items()) + \
            ([("on_restart", rctx)] if rctx is not None else []):
        for a in ctx._arms:
            armed.setdefault(a.msg, src_name)

    for m in spec.messages:
        handled = m.name in spec.handlers
        if not handled and m.name not in ignore:
            findings.append(_f(
                rel(spec_path), spec_line, "SPC011",
                f"spec {spec.name!r}: message {m.name!r} has no handler "
                "and is not listed in ignore=(...) — a delivered "
                f"{m.name!r} would be silently dropped (how real "
                "protocol bugs hide)"))
        if handled and m.name in ignore:
            findings.append(_f(
                rel(spec_path), spec_line, "SPC013",
                f"spec {spec.name!r}: {m.name!r} is both handled and "
                "listed in ignore=(...) — pick one"))

    # BFS over the kind graph from the seed events (+ restart arms).
    edges: Dict[str, List[str]] = {}
    for src_name, ctx in ctxs.items():
        outs = sorted({s.msg for s in ctx._sends}
                      | {a.msg for a in ctx._arms})
        edges[src_name] = outs
    roots = sorted(seeded)
    if rctx is not None:
        roots += sorted({s.msg for s in rctx._sends}
                        | {a.msg for a in rctx._arms})
    reach = set()
    frontier = [r for r in roots if r not in reach]
    while frontier:
        k = frontier.pop()
        if k in reach:
            continue
        reach.add(k)
        frontier.extend(edges.get(k, ()))
    for m in spec.messages:
        if m.name in reach or m.name in ignore:
            continue
        findings.append(_f(
            rel(spec_path), spec_line, "SPC010",
            f"spec {spec.name!r}: message {m.name!r} is unreachable — "
            "no init event seeds it and no reachable transition emits "
            "it; its handler is dead protocol"))
        # A timer is reachable only via arm()/init seeding (send to a
        # timer kind is a SpecError), so an unreachable handled timer
        # gets the sharper diagnosis too: the firing path is dead.
        if m.timer and m.name in spec.handlers and m.name not in armed \
                and m.name not in seeded:
            findings.append(_f(
                rel(spec_path), spec_line, "SPC020",
                f"spec {spec.name!r}: timer {m.name!r} is handled but "
                "never armed (no transition, on_restart hook or init "
                "event arms it) — the firing path is dead"))

    # -- durability flow -----------------------------------------------
    if spec.on_restart is None:
        volatile = {ln.name for ln in spec.lanes if not ln.durable}
        for m in spec.messages:
            ctx = ctxs.get(m.name)
            if ctx is None:
                continue
            for lane, site in sorted(ctx._reads.items()):
                if lane not in volatile:
                    continue
                findings.append(_f(
                    rel(site[0]), site[1], "SPC050",
                    f"spec {spec.name!r}: lane {lane!r} is volatile "
                    f"(durable=False) and read by the {m.name!r} "
                    "transition, but the spec has no on_restart hook — "
                    "a post-restart read sees the reset value with "
                    "nothing to reconstruct it (the classic "
                    "stable-storage violation)"))

    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))

    # -- suppression: source pragmas, then the spec-level allowance ----
    sources = {rel(p) for fn in list(spec.handlers.values())
               + [spec.init, spec.on_restart] if fn is not None
               for p in [_src(fn)[0]] if p != "<spec>"}
    out: List[Finding] = []
    by_path: Dict[str, List[Finding]] = {}
    for ff in findings:
        by_path.setdefault(ff.path, []).append(ff)
    for path in sorted(sources | set(by_path)):
        ap = os.path.join(root, path) if not os.path.isabs(path) else path
        try:
            with open(ap, encoding="utf-8") as fh:
                pragmas = extract_pragmas(fh.read())
        except OSError:
            pragmas = {}
        out.extend(apply_pragmas(by_path.get(path, []), pragmas, path,
                                 owned_prefixes=("SPC",)))

    allow = tuple(getattr(spec, "lint_allow", ()))
    if "*" in allow:  # the fixture escape hatch: waive the whole pass
        return []
    kept, used = [], {c: False for c in allow}
    for ff in out:
        if ff.rule in used:
            used[ff.rule] = True
            continue
        kept.append(ff)
    for code in sorted(c for c, u in used.items() if not u):
        kept.append(_f(rel(spec_path), spec_line, "SPC900",
                       f"spec {spec.name!r}: lint_allow names {code} "
                       "but the pass found nothing to suppress"))
    kept.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return kept


def gate_spec(spec) -> None:
    """The compile gate: raise :class:`SpecError` when ``spec`` has any
    speclint finding. ``CompiledActor`` calls this right after
    ``validate_spec`` — a spec with findings does not lower (escape
    hatch: ``lint_allow`` on the spec, per code or ``("*",)``)."""
    findings = lint_spec(spec)
    if not findings:
        return
    from ..actorc.spec import SpecError

    lines = "\n".join(f"  {f.render()}" for f in findings)
    raise SpecError(
        f"spec {spec.name!r} fails speclint (pass 4) with "
        f"{len(findings)} finding{'s' if len(findings) != 1 else ''} — "
        "fix the spec, suppress a deliberate site with `# detlint: "
        "allow[SPC...]`, or allow the code spec-wide via "
        f"lint_allow=(...):\n{lines}")


# ---------------------------------------------------------------------------
# Protocol cards
# ---------------------------------------------------------------------------

def protocol_card(spec) -> str:
    """A byte-stable static profile of ``spec``: the kinds × handlers
    matrix, the timer graph and the lane budget table. Printed by
    ``python -m madsim_tpu.analysis spec --card`` / ``make
    speclint-demo`` and attached to triage repro bundles so a minimized
    bug carries its protocol's shape."""
    from ..actorc.spec import lane_dtype
    from ..engine.lanes import PACKED

    ignore = set(getattr(spec, "ignore", ()))
    terminal = set(getattr(spec, "terminal", ()))

    ictx = _LintInitCtx(spec)
    try:
        spec.init(ictx)
    except Exception:
        pass
    seeded = sorted({m for m, _w, _s in ictx.events})

    ctxs: Dict[str, Any] = {}
    write_bounds: Dict[str, Tuple[int, int]] = {}
    runs = [(m.name, spec.handlers[m.name], m)
            for m in spec.messages if m.name in spec.handlers]
    if spec.on_restart is not None:
        runs.append(("on_restart", spec.on_restart, None))
    for name, fn, m in runs:
        ctx = _make_lint_ctx(spec, spec.n_nodes - 1, msg=m)
        try:
            fn(ctx)
        except Exception:
            continue
        ctxs[name] = ctx
        for _op, lane, _idx, value, _when in ctx._writes:
            try:
                v = _lift(value)
            except Exception:
                continue
            lo, hi = write_bounds.get(lane, (v.lo, v.hi))
            write_bounds[lane] = (min(lo, v.lo), max(hi, v.hi))

    lines = [f"protocol card: {spec.name} "
             f"(n_nodes={spec.n_nodes}, {len(spec.messages)} kinds, "
             f"{len(spec.lanes)} lanes)", ""]

    lines.append("kinds x handlers")
    lines.append(f"  {'kind':<12} {'role':<9} {'status':<9} "
                 f"{'emits':<28} draws")
    for m in spec.messages:
        role = "timer" if m.timer else "message"
        if m.name in ignore:
            status = "ignored"
        elif m.name in terminal:
            status = "terminal"
        elif m.name in spec.handlers:
            status = "handled"
        else:
            status = "UNHANDLED"
        ctx = ctxs.get(m.name)
        emits = "-"
        draws = 0
        if ctx is not None:
            outs = sorted({s.msg for s in ctx._sends}
                          | {a.msg for a in ctx._arms})
            emits = ",".join(outs) if outs else "-"
            draws = ctx._draws
        lines.append(f"  {m.name:<12} {role:<9} {status:<9} "
                     f"{emits:<28} {draws}")

    lines += ["", "timer graph"]
    any_timer = False
    for m in spec.messages:
        if not m.timer:
            continue
        any_timer = True
        armers = sorted(name for name, ctx in ctxs.items()
                        if any(a.msg == m.name for a in ctx._arms))
        seed = "yes" if m.name in seeded else "no"
        lines.append(f"  {m.name}: armed by "
                     f"{','.join(armers) if armers else '-'}; "
                     f"init-seeded: {seed}")
    if not any_timer:
        lines.append("  (no timers)")

    lines += ["", "lane budgets"]
    lines.append(f"  {'lane':<14} {'scope':<11} {'kind':<8} "
                 f"{'declared':<16} {'dtype':<6} {'durable':<8} "
                 "max-write")
    import numpy as np

    for ln in spec.lanes:
        dt = {1: "i8", 2: "i16", 4: "i32"}[
            np.dtype(lane_dtype(ln, PACKED)).itemsize]
        wb = write_bounds.get(ln.name)
        wtxt = f"[{wb[0]}, {wb[1]}]" if wb else "-"
        declared = f"[{ln.lo}, {ln.hi}]"
        lines.append(f"  {ln.name:<14} {ln.scope:<11} {ln.kind:<8} "
                     f"{declared:<16} {dt:<6} "
                     f"{str(ln.durable).lower():<8} {wtxt}")

    lines += ["", f"init seeds: {', '.join(seeded) if seeded else '-'}"]
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# The shipped families + CLI entry
# ---------------------------------------------------------------------------

def shipped_specs() -> Dict[str, Any]:
    """Name -> spec for every shipped actorc family (clean configs) —
    the surface ``make speclint`` keeps clean."""
    from ..actorc.families.paxos import PaxosConfig, paxos_spec
    from ..actorc.families.pb import pb_spec
    from ..actorc.families.tpc import tpc_spec
    from ..engine.pb_actor import PBDeviceConfig
    from ..engine.tpc_actor import TPCDeviceConfig

    return {
        "paxos": paxos_spec(PaxosConfig()),
        "pb": pb_spec(PBDeviceConfig()),
        "tpc": tpc_spec(TPCDeviceConfig()),
    }


def run_spec_pass(root: Optional[str] = None,
                  specs: Optional[Dict[str, Any]] = None) -> List[Finding]:
    """Pass 4 over a set of specs (default: the shipped families)."""
    specs = shipped_specs() if specs is None else specs
    findings: List[Finding] = []
    for _name in sorted(specs):
        findings.extend(lint_spec(specs[_name], root=root))
    return findings


def main_spec(argv: Optional[List[str]] = None) -> int:
    import argparse

    from .cli import _add_format_args, _fmt, render_findings

    ap = argparse.ArgumentParser(
        prog="detlint spec",
        description="speclint: pass 4 — protocol-level static "
                    "verification of actorc specs (reachability, "
                    "exhaustiveness, timer discipline, lane-capacity "
                    "proofs, RNG/effect budgets, durability flow)")
    ap.add_argument("--families", default=None,
                    help="comma-separated subset of the shipped "
                         "families (default: all)")
    ap.add_argument("--card", default=None, metavar="FAMILY",
                    help="print FAMILY's protocol card and exit")
    ap.add_argument("--list-families", action="store_true")
    _add_format_args(ap)
    args = ap.parse_args(argv)

    specs = shipped_specs()
    if args.list_families:
        for name in sorted(specs):
            print(name)
        return 0
    if args.card is not None:
        if args.card not in specs:
            print(f"speclint: unknown family {args.card!r} "
                  f"(shipped: {sorted(specs)})", file=sys.stderr)
            return 2
        sys.stdout.write(protocol_card(specs[args.card]))
        return 0
    if args.families:
        sel = [f.strip() for f in args.families.split(",") if f.strip()]
        unknown = [f for f in sel if f not in specs]
        if unknown:
            print(f"speclint: unknown families {unknown} "
                  f"(shipped: {sorted(specs)})", file=sys.stderr)
            return 2
        specs = {k: specs[k] for k in sel}

    findings = run_spec_pass(specs=specs)
    render_findings(findings, _fmt(args), label="speclint")
    return 1 if findings else 0
