"""detlint — static determinism analysis for madsim_tpu programs.

Two passes (docs/detlint.md):

1. **Nondeterminism-escape detection** (:mod:`.escape`): AST scan for
   calls that bypass the sim's interception layer — wall clock, ambient
   entropy, real threads, host introspection, raw sockets, identity-keyed
   ordering. The static twin of the dynamic RNG log/replay checker
   (tools/determinism_sweep.py): the sweep proves the seeds it ran were
   deterministic; the lint proves the code *cannot* escape, including
   paths no seed exercised.
2. **Sim/real API parity** (:mod:`.parity`): the dual-tree convention
   (``net``/``fs`` vs ``real/``, inline ``is_real()`` dispatch in
   ``time``) enforced as signatures, so one program keeps compiling
   against both backends — the reference's ``--cfg madsim`` contract.

3. **Program-level tracelint** (:mod:`.tracelint`): the hot-path entry
   points traced to jaxprs and compiled fresh — host-callback and
   nondeterministic-primitive rules (TRC001/002), x64-invariance
   (TRC003), donation contracts (TRC004), and the checked-in cost-budget
   ledger ``analysis/budgets.json`` (BUD001/002, :mod:`.budgets`).

CLI: ``python -m madsim_tpu.analysis`` / ``... trace`` (or
``tools/detlint.py``); ``make lint`` is the repo gate (detlint +
tracelint). Suppression: ``# detlint: allow[RULE]`` pragmas (stale ones
are errors; DET008/009 waivers need ``reason=``) + the checked-in
``detlint-allow.txt`` (stale lines are DET901 errors).
"""
from .cli import main, main_trace, run_lint
from .escape import run_escape_pass, scan_source
from .parity import run_parity_pass
from .pragmas import Allowlist, Finding
from .rules import RULES, Rule

__all__ = [
    "main", "main_trace", "run_lint", "run_escape_pass", "run_parity_pass",
    "scan_source", "Allowlist", "Finding", "RULES", "Rule",
]
