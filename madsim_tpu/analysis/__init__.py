"""detlint — static determinism analysis for madsim_tpu programs.

Two passes (docs/detlint.md):

1. **Nondeterminism-escape detection** (:mod:`.escape`): AST scan for
   calls that bypass the sim's interception layer — wall clock, ambient
   entropy, real threads, host introspection, raw sockets, identity-keyed
   ordering. The static twin of the dynamic RNG log/replay checker
   (tools/determinism_sweep.py): the sweep proves the seeds it ran were
   deterministic; the lint proves the code *cannot* escape, including
   paths no seed exercised.
2. **Sim/real API parity** (:mod:`.parity`): the dual-tree convention
   (``net``/``fs`` vs ``real/``, inline ``is_real()`` dispatch in
   ``time``) enforced as signatures, so one program keeps compiling
   against both backends — the reference's ``--cfg madsim`` contract.

CLI: ``python -m madsim_tpu.analysis`` (or ``tools/detlint.py``);
``make lint`` is the repo gate. Suppression: ``# detlint: allow[RULE]``
pragmas (stale ones are errors) + the checked-in ``detlint-allow.txt``.
"""
from .cli import main, run_lint
from .escape import run_escape_pass, scan_source
from .parity import run_parity_pass
from .pragmas import Allowlist, Finding
from .rules import RULES, Rule

__all__ = [
    "main", "run_lint", "run_escape_pass", "run_parity_pass", "scan_source",
    "Allowlist", "Finding", "RULES", "Rule",
]
