"""detlint — static determinism analysis for madsim_tpu programs.

Four passes (docs/detlint.md):

1. **Nondeterminism-escape detection** (:mod:`.escape`): AST scan for
   calls that bypass the sim's interception layer — wall clock, ambient
   entropy, real threads, host introspection, raw sockets, identity-keyed
   ordering. The static twin of the dynamic RNG log/replay checker
   (tools/determinism_sweep.py): the sweep proves the seeds it ran were
   deterministic; the lint proves the code *cannot* escape, including
   paths no seed exercised.
2. **Sim/real API parity** (:mod:`.parity`): the dual-tree convention
   (``net``/``fs`` vs ``real/``, inline ``is_real()`` dispatch in
   ``time``) enforced as signatures, so one program keeps compiling
   against both backends — the reference's ``--cfg madsim`` contract.

3. **Program-level tracelint** (:mod:`.tracelint`): the hot-path entry
   points traced to jaxprs and compiled fresh — host-callback and
   nondeterministic-primitive rules (TRC001/002), x64-invariance
   (TRC003), donation contracts (TRC004), and the checked-in cost-budget
   ledger ``analysis/budgets.json`` (BUD001/002, :mod:`.budgets`).

4. **Protocol-level speclint** (:mod:`.speclint`): static verification
   of ``actorc.spec`` state machines before the compiler lowers them —
   reachability/exhaustiveness over the kind graph, timer discipline,
   interval proofs that written values fit their packed lane dtypes and
   emitted payload words fit their declared ranges, per-transition
   RNG/send/arm budgets against what the lowering supports, and the
   durability-flow check (SPC0xx). ``CompiledActor`` runs it as a hard
   compile gate (docs/speclint.md).

CLI: ``python -m madsim_tpu.analysis`` / ``... trace`` / ``... spec``
(or ``tools/detlint.py``); ``make lint`` is the repo gate (detlint +
tracelint + speclint). Suppression: ``# detlint: allow[RULE]`` pragmas
(stale ones are errors; DET008/009 waivers need ``reason=``) + the
checked-in ``detlint-allow.txt`` (stale lines are DET901 errors) + the
spec-level ``lint_allow`` tuple for SPC codes (stale entries are
SPC900 errors).
"""
from .cli import main, main_trace, run_lint
from .escape import run_escape_pass, scan_source
from .parity import run_parity_pass
from .pragmas import Allowlist, Finding
from .rules import RULES, Rule


def main_spec(argv=None):
    """Pass-4 CLI entry (lazy: speclint pulls in jax via the specs)."""
    from .speclint import main_spec as _main_spec

    return _main_spec(argv)


def lint_spec(spec, root=None):
    """Pass-4 library entry (lazy import, same reason as main_spec)."""
    from .speclint import lint_spec as _lint_spec

    return _lint_spec(spec, root=root)


__all__ = [
    "main", "main_trace", "main_spec", "run_lint", "lint_spec",
    "run_escape_pass", "run_parity_pass",
    "scan_source", "Allowlist", "Finding", "RULES", "Rule",
]
