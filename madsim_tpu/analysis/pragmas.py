"""Finding suppression: inline pragmas + the checked-in allowlist.

Two escape hatches, for two shapes of intent:

- ``# detlint: allow[DET001]`` on (or immediately above) the offending line
  — for a *single deliberate site* (e.g. ``testing.py``'s wall-clock default
  seed). A pragma that suppresses nothing is itself an error (DET900), so
  allow-comments cannot rot in place after the code they excused changes.
  Sync-discipline waivers (DET008/DET009) must additionally carry a
  machine-readable justification — ``allow[DET008] reason=...`` — because a
  sanctioned blocking site is an architectural claim, not a style choice.
- an allowlist file (default ``detlint-allow.txt`` at the scan root) with
  ``path-prefix[:RULE]`` lines — for *whole intentional trees* (all of
  ``madsim_tpu/real/`` IS the nondeterministic backend; flagging it would
  be flagging the design). An entry that stops matching any finding is
  flagged DET901 by the CLI (when the scan surface covers its prefix), so
  the file cannot rot silently either.
"""
from __future__ import annotations

import io
import re
import tokenize
from typing import Dict, List, NamedTuple, Optional, Set, Tuple


class Finding(NamedTuple):
    path: str       # scan-root-relative, '/' separators
    line: int       # 1-based
    rule: str       # e.g. "DET001"
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


_PRAGMA_RE = re.compile(
    r"#\s*detlint:\s*allow\[([A-Za-z0-9_,\s]+)\](?:\s+reason=(\S[^#]*))?")

# Rules whose pragmas must carry a reason= tail: waiving the hot-loop sync
# discipline without saying why defeats the point of counting fetches.
REASON_REQUIRED = frozenset({"DET008", "DET009"})


def extract_pragmas(source: str) -> Dict[int, Tuple[int, Dict[str, Optional[str]]]]:
    """Map *effective* line -> (pragma line, {rule code: reason or None}).

    Tokenized, not line-grepped: only real COMMENT tokens count, so a
    pragma example quoted inside a docstring is documentation, not a
    suppression. A pragma trailing code covers its own line; a pragma on
    a comment-only line covers the next line (the decorator-friendly
    form). The optional ``reason=...`` tail is captured per pragma and
    applies to every code the bracket names.
    """
    out: Dict[int, Tuple[int, Dict[str, Optional[str]]]] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if m is None:
                continue
            reason = m.group(2).strip() if m.group(2) else None
            codes = {c.strip().upper(): reason
                     for c in m.group(1).split(",") if c.strip()}
            line = tok.start[0]
            comment_only = tok.line[:tok.start[1]].strip() == ""
            target = line + 1 if comment_only else line
            prev_line, prev_codes = out.get(target, (line, {}))
            merged = dict(prev_codes)
            merged.update(codes)
            out[target] = (prev_line, merged)
    except (tokenize.TokenError, IndentationError):
        pass  # unparseable source surfaces as DET000 from the AST pass
    return out


def apply_pragmas(findings: List[Finding],
                  pragmas: Dict[int, Tuple[int, Dict[str, Optional[str]]]],
                  path: str,
                  owned_prefixes: Optional[Tuple[str, ...]] = None,
                  ) -> List[Finding]:
    """Drop findings covered by a pragma; emit DET900 for unused codes and
    for sync-discipline waivers missing their ``reason=`` tail.

    ``owned_prefixes`` scopes the staleness check across passes: each
    pass suppresses any code, but reports DET900 only for codes whose
    prefix it OWNS (pass 1 owns DET/TRC/BUD/PAR, pass 4 owns SPC) —
    otherwise a legitimate ``allow[SPC...]`` pragma would read as stale
    to pass 1, which never produces SPC findings. ``None`` keeps the
    single-pass behavior: every code is checked.
    """
    used: Dict[Tuple[int, str], bool] = {}
    for line, (_pline, codes) in pragmas.items():
        for code in codes:
            used[(line, code)] = False
    kept: List[Finding] = []
    for f in findings:
        entry = pragmas.get(f.line)
        if entry is not None and f.rule in entry[1]:
            used[(f.line, f.rule)] = True
            continue
        kept.append(f)
    for line, (pline, codes) in sorted(pragmas.items()):
        for code in sorted(codes):
            if owned_prefixes is not None and \
                    not code.startswith(owned_prefixes):
                continue
            if not used.get((line, code), False):
                kept.append(Finding(
                    path, pline, "DET900",
                    f"pragma allows {code} but line {line} has no {code} "
                    f"finding — delete the stale pragma"))
            elif code in REASON_REQUIRED and not codes[code]:
                kept.append(Finding(
                    path, pline, "DET900",
                    f"allow[{code}] waives the hot-loop sync discipline "
                    f"and must carry a machine-readable justification: "
                    f"`detlint: allow[{code}] reason=...`"))
    kept.sort(key=lambda f: (f.line, f.rule))
    return kept


class AllowEntry(NamedTuple):
    prefix: str
    rule: Optional[str]
    line: int   # 1-based line in the allowlist file (0: built in code)


class Allowlist:
    """``path-prefix[:RULE]`` entries; '#' starts a comment.

    ``filter`` records which entries matched, so the CLI can flag entries
    that excuse nothing (DET901) once a scan has covered their prefix.
    """

    def __init__(self, entries: List[Tuple[str, Optional[str]]]):
        self._entries = [
            e if isinstance(e, AllowEntry) else AllowEntry(e[0], e[1], 0)
            for e in entries]
        self._matched: Set[AllowEntry] = set()

    @classmethod
    def parse(cls, text: str) -> "Allowlist":
        entries: List[AllowEntry] = []
        for lineno, raw in enumerate(text.splitlines(), start=1):
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            prefix, _, rule = line.partition(":")
            entries.append(AllowEntry(prefix.strip(),
                                      rule.strip().upper() or None, lineno))
        return cls(entries)

    @classmethod
    def load(cls, path: str) -> "Allowlist":
        with open(path, encoding="utf-8") as f:
            return cls.parse(f.read())

    @classmethod
    def empty(cls) -> "Allowlist":
        return cls([])

    @property
    def entries(self) -> List[AllowEntry]:
        return list(self._entries)

    def allows(self, finding: Finding) -> bool:
        hit = False
        for entry in self._entries:
            if finding.path.startswith(entry.prefix) and \
                    (entry.rule is None or entry.rule == finding.rule):
                self._matched.add(entry)
                hit = True
        return hit

    def filter(self, findings: List[Finding]) -> List[Finding]:
        return [f for f in findings if not self.allows(f)]

    def stale_entries(self, scanned_paths: List[str]) -> List[AllowEntry]:
        """Entries no ``filter`` call matched, restricted to prefixes the
        scan surface actually covered (an entry for an unscanned tree is
        unknown, not stale). Call after filtering raw findings."""
        out = []
        for entry in self._entries:
            if entry in self._matched:
                continue
            covered = any(entry.prefix.startswith(p.rstrip("/") + "/")
                          or entry.prefix.rstrip("/") == p.rstrip("/")
                          or p.startswith(entry.prefix)
                          for p in scanned_paths)
            if covered:
                out.append(entry)
        return out
