"""Finding suppression: inline pragmas + the checked-in allowlist.

Two escape hatches, for two shapes of intent:

- ``# detlint: allow[DET001]`` on (or immediately above) the offending line
  — for a *single deliberate site* (e.g. ``testing.py``'s wall-clock default
  seed). A pragma that suppresses nothing is itself an error (DET900), so
  allow-comments cannot rot in place after the code they excused changes.
- an allowlist file (default ``detlint-allow.txt`` at the scan root) with
  ``path-prefix[:RULE]`` lines — for *whole intentional trees* (all of
  ``madsim_tpu/real/`` IS the nondeterministic backend; flagging it would
  be flagging the design).
"""
from __future__ import annotations

import io
import re
import tokenize
from typing import Dict, List, NamedTuple, Optional, Set, Tuple


class Finding(NamedTuple):
    path: str       # scan-root-relative, '/' separators
    line: int       # 1-based
    rule: str       # e.g. "DET001"
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


_PRAGMA_RE = re.compile(r"#\s*detlint:\s*allow\[([A-Za-z0-9_,\s]+)\]")


def extract_pragmas(source: str) -> Dict[int, Tuple[int, Set[str]]]:
    """Map *effective* line -> (pragma line, allowed rule codes).

    Tokenized, not line-grepped: only real COMMENT tokens count, so a
    pragma example quoted inside a docstring is documentation, not a
    suppression. A pragma trailing code covers its own line; a pragma on
    a comment-only line covers the next line (the decorator-friendly
    form).
    """
    out: Dict[int, Tuple[int, Set[str]]] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if m is None:
                continue
            codes = {c.strip().upper()
                     for c in m.group(1).split(",") if c.strip()}
            line = tok.start[0]
            comment_only = tok.line[:tok.start[1]].strip() == ""
            target = line + 1 if comment_only else line
            prev_line, prev_codes = out.get(target, (line, set()))
            out[target] = (prev_line, prev_codes | codes)
    except (tokenize.TokenError, IndentationError):
        pass  # unparseable source surfaces as DET000 from the AST pass
    return out


def apply_pragmas(findings: List[Finding],
                  pragmas: Dict[int, Tuple[int, Set[str]]],
                  path: str) -> List[Finding]:
    """Drop findings covered by a pragma; emit DET900 for unused codes."""
    used: Dict[Tuple[int, str], bool] = {}
    for line, (_pline, codes) in pragmas.items():
        for code in codes:
            used[(line, code)] = False
    kept: List[Finding] = []
    for f in findings:
        entry = pragmas.get(f.line)
        if entry is not None and f.rule in entry[1]:
            used[(f.line, f.rule)] = True
            continue
        kept.append(f)
    for line, (pline, codes) in sorted(pragmas.items()):
        for code in sorted(codes):
            if not used.get((line, code), False):
                kept.append(Finding(
                    path, pline, "DET900",
                    f"pragma allows {code} but line {line} has no {code} "
                    f"finding — delete the stale pragma"))
    kept.sort(key=lambda f: (f.line, f.rule))
    return kept


class Allowlist:
    """``path-prefix[:RULE]`` entries; '#' starts a comment."""

    def __init__(self, entries: List[Tuple[str, Optional[str]]]):
        self._entries = entries

    @classmethod
    def parse(cls, text: str) -> "Allowlist":
        entries: List[Tuple[str, Optional[str]]] = []
        for raw in text.splitlines():
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            prefix, _, rule = line.partition(":")
            entries.append((prefix.strip(), rule.strip().upper() or None))
        return cls(entries)

    @classmethod
    def load(cls, path: str) -> "Allowlist":
        with open(path, encoding="utf-8") as f:
            return cls.parse(f.read())

    @classmethod
    def empty(cls) -> "Allowlist":
        return cls([])

    def allows(self, finding: Finding) -> bool:
        return any(
            finding.path.startswith(prefix)
            and (rule is None or rule == finding.rule)
            for prefix, rule in self._entries)

    def filter(self, findings: List[Finding]) -> List[Finding]:
        return [f for f in findings if not self.allows(f)]
