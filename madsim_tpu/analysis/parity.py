"""Pass 2 — sim/real API-parity check.

The reference's contract is that one program compiles against both trees
(``--cfg madsim`` swaps the whole crate surface, `madsim-tokio/src/lib.rs`).
This repo's twin convention is ``madsim_tpu/{net,fs}`` vs
``madsim_tpu/real/``, plus modules whose real backend is an inline
``is_real()`` branch (``time.py``). Both are conventions until something
enforces them; this pass turns them into invariants:

- ``TWIN_CLASSES`` / ``TWIN_FUNCTIONS``: the public signatures (member
  names, parameter names, defaults, async-ness) of each sim type must
  equal its real twin's, both directions — a method added to one tree
  only is drift (PAR001), because code written against it deadlocks or
  AttributeErrors on the other backend.
- ``DISPATCH_MODULES``: every public module function must reach an
  ``is_real()`` dispatch (directly or through calls to module-local
  helpers/classes), so it *has* a real behavior at all (PAR002).

Everything is pure AST — the check needs no imports, so it runs against a
copied/patched tree (the drift-injection test) as easily as the repo.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, NamedTuple, Optional, Sequence, Set, Tuple

from .pragmas import Finding

# (sim file, sim class, real file, real class) — root-relative paths.
TWIN_CLASSES: Sequence[Tuple[str, str, str, str]] = (
    ("madsim_tpu/net/endpoint.py", "Endpoint",
     "madsim_tpu/real/net.py", "RealEndpoint"),
    ("madsim_tpu/net/tcp.py", "TcpListener",
     "madsim_tpu/real/tcp.py", "RealTcpListener"),
    ("madsim_tpu/net/tcp.py", "TcpStream",
     "madsim_tpu/real/tcp.py", "RealTcpStream"),
    ("madsim_tpu/net/netsim.py", "ChannelSender",
     "madsim_tpu/real/net.py", "RealChannelSender"),
    ("madsim_tpu/net/netsim.py", "ChannelReceiver",
     "madsim_tpu/real/net.py", "RealChannelReceiver"),
    ("madsim_tpu/fs.py", "File", "madsim_tpu/real/fs.py", "RealFile"),
    ("madsim_tpu/fs.py", "Metadata", "madsim_tpu/real/fs.py", "Metadata"),
)

# (sim file, function names, real file) — module-level twins.
TWIN_FUNCTIONS: Sequence[Tuple[str, Sequence[str], str]] = (
    ("madsim_tpu/fs.py", ("read", "write", "metadata", "remove_file"),
     "madsim_tpu/real/fs.py"),
)

# Modules whose real backend is inline: every __all__ function must reach
# is_real() through the module-local call graph.
DISPATCH_MODULES: Sequence[str] = ("madsim_tpu/time.py",)

# Context-manager dunders are part of the usable surface; other dunders
# (__del__, __init__, __await__) are implementation detail.
_SURFACE_DUNDERS = {"__enter__", "__exit__", "__aenter__", "__aexit__"}


class Signature(NamedTuple):
    is_async: bool
    params: Tuple[str, ...]     # positional + kw-only names, self/cls stripped
    n_defaults: int
    has_vararg: bool
    has_kwarg: bool
    line: int

    def describe(self) -> str:
        kind = "async def" if self.is_async else "def"
        return f"{kind}({', '.join(self.params)})"


def _signature(fn) -> Signature:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    names += [p.arg for p in a.kwonlyargs]
    n_defaults = len(a.defaults) + sum(d is not None for d in a.kw_defaults)
    return Signature(isinstance(fn, ast.AsyncFunctionDef), tuple(names),
                     n_defaults, a.vararg is not None, a.kwarg is not None,
                     fn.lineno)


def _is_public(name: str) -> bool:
    return not name.startswith("_") or name in _SURFACE_DUNDERS


def _parse(root: str, rel: str) -> Optional[ast.Module]:
    full = os.path.join(root, rel)
    if not os.path.isfile(full):
        return None
    with open(full, encoding="utf-8") as f:
        return ast.parse(f.read(), filename=rel)


def _class_api(tree: ast.Module, cls_name: str) -> Optional[Dict[str, Signature]]:
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            return {
                item.name: _signature(item)
                for item in node.body
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                and _is_public(item.name)
            }
    return None


def _module_api(tree: ast.Module, names: Sequence[str]) -> Dict[str, Signature]:
    return {
        node.name: _signature(node)
        for node in tree.body
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and node.name in names
    }


def _diff_member(findings: List[Finding], path: str, owner: str, name: str,
                 sim: Signature, real: Signature, real_path: str) -> None:
    if sim.is_async != real.is_async:
        findings.append(Finding(
            path, sim.line, "PAR001",
            f"{owner}.{name} async-ness differs: sim is "
            f"{sim.describe()}, real ({real_path}) is {real.describe()}"))
        return
    if (sim.params != real.params or sim.n_defaults != real.n_defaults
            or sim.has_vararg != real.has_vararg
            or sim.has_kwarg != real.has_kwarg):
        findings.append(Finding(
            path, sim.line, "PAR001",
            f"{owner}.{name} signature differs: sim {sim.describe()} vs "
            f"real {real.describe()} ({real_path})"))


def _diff_apis(findings: List[Finding], owner: str,
               sim_path: str, sim_api: Dict[str, Signature],
               real_path: str, real_api: Dict[str, Signature]) -> None:
    for name, sim_sig in sorted(sim_api.items()):
        real_sig = real_api.get(name)
        if real_sig is None:
            findings.append(Finding(
                sim_path, sim_sig.line, "PAR001",
                f"{owner}.{name} exists in sim but not in the real twin "
                f"({real_path}) — real-backend code would AttributeError"))
        else:
            _diff_member(findings, sim_path, owner, name, sim_sig, real_sig,
                         real_path)
    for name, real_sig in sorted(real_api.items()):
        if name not in sim_api:
            findings.append(Finding(
                real_path, real_sig.line, "PAR001",
                f"{owner}.{name} exists in the real twin but not in sim "
                f"({sim_path}) — sim-tested code cannot cover it"))


def _all_names(tree: ast.Module) -> List[str]:
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "__all__":
                    if isinstance(node.value, (ast.List, ast.Tuple)):
                        return [e.value for e in node.value.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, str)]
    return []


def _check_dispatch(findings: List[Finding], path: str,
                    tree: ast.Module) -> None:
    """PAR002: each __all__ function must reach an is_real() branch via the
    module-local call graph (classes count through their methods)."""
    funcs: Dict[str, ast.AST] = {}
    classes: Dict[str, List[ast.AST]] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs[node.name] = node
        elif isinstance(node, ast.ClassDef):
            classes[node.name] = [
                item for item in node.body
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))]

    def direct(node: ast.AST) -> bool:
        return any(isinstance(n, ast.Name) and n.id == "is_real"
                   for n in ast.walk(node))

    def callees(node: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name):
                out.add(n.func.id)
        return out

    aware: Set[str] = set()
    for name, node in funcs.items():
        if direct(node):
            aware.add(name)
    for name, methods in classes.items():
        if any(direct(m) for m in methods):
            aware.add(name)
    changed = True
    while changed:
        changed = False
        for name, node in list(funcs.items()):
            if name not in aware and callees(node) & aware:
                aware.add(name)
                changed = True
        for name, methods in classes.items():
            if name not in aware and any(callees(m) & aware for m in methods):
                aware.add(name)
                changed = True

    for name in _all_names(tree):
        node = funcs.get(name)
        if node is not None and name not in aware:
            findings.append(Finding(
                path, node.lineno, "PAR002",
                f"public function {name}() never reaches an is_real() "
                f"dispatch — it has no real-backend behavior"))


def run_parity_pass(root: str) -> List[Finding]:
    findings: List[Finding] = []
    for sim_path, sim_cls, real_path, real_cls in TWIN_CLASSES:
        sim_tree = _parse(root, sim_path)
        if sim_tree is None:
            continue  # target tree doesn't carry this module (fixture scans)
        real_tree = _parse(root, real_path)
        sim_api = _class_api(sim_tree, sim_cls)
        if sim_api is None:
            findings.append(Finding(sim_path, 1, "PAR001",
                                    f"class {sim_cls} not found"))
            continue
        real_api = _class_api(real_tree, real_cls) if real_tree else None
        if real_api is None:
            findings.append(Finding(
                sim_path, 1, "PAR001",
                f"{sim_cls}: real twin class {real_cls} missing from "
                f"{real_path}"))
            continue
        _diff_apis(findings, sim_cls, sim_path, sim_api, real_path, real_api)
    for sim_path, names, real_path in TWIN_FUNCTIONS:
        sim_tree = _parse(root, sim_path)
        real_tree = _parse(root, real_path)
        if sim_tree is None or real_tree is None:
            continue
        _diff_apis(findings, os.path.basename(sim_path)[:-3], sim_path,
                   _module_api(sim_tree, names), real_path,
                   _module_api(real_tree, names))
    for path in DISPATCH_MODULES:
        tree = _parse(root, path)
        if tree is not None:
            _check_dispatch(findings, path, tree)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
