"""detlint rule catalog.

Every rule names a class of *determinism escape*: a call that reaches the
host OS (clock, entropy, scheduler, NIC) without going through the sim's
interception layer, so the same seed can produce different trajectories.
The catalog is the static twin of the dynamic interception table in
:mod:`madsim_tpu.shims.aio` (``install()``'s patch list) — anything that
table patches at runtime, this table flags at lint time, because code paths
the sweep never executes are exactly where escapes hide (the ahead-of-time
argument of PRISM-style modeling vs observed-run sampling, PAPERS.md).

``PAR`` rules belong to pass 2 (sim/real API parity); ``DET9xx`` codes are
lint-hygiene errors (stale pragmas, stale allowlist lines), so an
allow-comment can never silently rot into a blanket waiver.

``TRC``/``BUD`` rules belong to pass 3 (tracelint, :mod:`.tracelint`):
they fire on *compiled programs* — the traced jaxprs and XLA executables
of the hot-path entry points — not on source lines, because the
determinism and performance contracts of the superstep loop, donated
buffers, and the coverage fold live below the Python AST.

``SPC`` rules belong to pass 4 (speclint, :mod:`.speclint`): they fire
on *protocol state machines* — the ``actorc.spec`` declarations —
before the compiler lowers them to packed lanes. Where passes 1–3
police how code executes, pass 4 polices what the protocol *says*:
unreachable kinds, unhandled deliveries, unarmed timers, counters whose
static bound escapes their packed dtype, transitions leaning on DSL
features the lowering flattens (multi-send payloads, multi-timer arms,
>1 RNG draw), and volatile state read with no restart reconstruction.
SPC900 is the pass's own hygiene code (a stale ``lint_allow`` entry),
mirroring DET900/DET901.
"""
from __future__ import annotations

from typing import Dict, NamedTuple, Tuple


class Rule(NamedTuple):
    code: str
    title: str
    suggestion: str


RULES: Dict[str, Rule] = {r.code: r for r in [
    Rule("DET001", "wall-clock read escapes virtual time",
         "use madsim_tpu.time (system_time/monotonic/sleep) — virtual, seeded"),
    Rule("DET002", "ambient entropy escapes the seeded RNG",
         "use madsim_tpu.rand.thread_rng() (per-world, derived from the seed)"),
    Rule("DET003", "real concurrency inside the single-threaded simulation",
         "use madsim_tpu.task.spawn / spawn_blocking (deterministic tasks)"),
    Rule("DET004", "host introspection used for sizing",
         "use madsim_tpu.task.available_parallelism() (the node's cores)"),
    Rule("DET005", "raw socket bypasses the simulated network",
         "use madsim_tpu.net (Endpoint/TcpStream) or the eventloop shim"),
    Rule("DET006", "id()/hash()-keyed ordering depends on allocation history",
         "sort by a stable field (node id, tag, name), never object identity"),
    Rule("DET007", "device profiler / wall-clock capture inside sim code",
         "profile from the observatory layer (madsim_tpu.obs.observatory "
         "ProfilerWindow / sweep(profile_dir=...)) — step code must stay "
         "free of host-time observation"),
    Rule("DET008", "blocking device sync in an orchestration hot-loop module",
         "route every device->host pull through the counted `_fetch` hook "
         "(parallel/sweep.py) so the sync-discipline tests stay honest; a "
         "deliberate site needs `detlint: allow[DET008] reason=...`"),
    Rule("DET009", "device value converted to host without going through "
         "`_fetch`",
         "fetch first (`x_h = _fetch(x)`), then convert the host copy — "
         "int()/np.asarray() on a device array is a hidden blocking sync"),
    Rule("DET900", "stale pragma: allow[...] names a rule with no finding",
         "delete the pragma (or the code that made it necessary came back)"),
    Rule("DET901", "stale allowlist entry: its path[:rule] matches no finding",
         "delete the detlint-allow.txt line — the tree it excused is clean "
         "now (or was renamed out from under it)"),
    Rule("TRC001", "host callback primitive inside a jitted sim program",
         "pure_callback/io_callback/debug_callback re-enter the host mid-"
         "program: remove it (debug prints belong in obs/, not the step)"),
    Rule("TRC002", "backend-variant or nondeterministic primitive",
         "unstable sorts, float scatter-accumulation onto duplicate "
         "indices, approximate/stateful kernels vary across backends — "
         "use a stable, exact formulation"),
    Rule("TRC003", "numerics that change under the x64 flag",
         "pin every dtype explicitly (jnp.int32/float32) so the program "
         "is bit-identical whether or not jax_enable_x64 is set"),
    Rule("TRC004", "declared buffer donation was dropped by XLA",
         "restructure so the output can alias the donated input (XLA "
         "drops donation SILENTLY; peak memory then double-buffers)"),
    Rule("TRC005", "unannotated narrow-to-wide dtype conversion in a "
         "packed program",
         "an i8/i16 lane widened outside engine/lanes.py — an implicit "
         "promotion is leaking a narrow lane wide; read it through "
         "lanes.widen() (and write back via the saturating lanes.narrow "
         "path) so every width change is a stated decision"),
    Rule("BUD001", "program exceeds its checked-in cost budget",
         "if intentional, re-measure and regenerate analysis/budgets.json "
         "via tools/update_budgets.py --reason '...' in the same PR"),
    Rule("BUD002", "budget ledger out of sync with the program registry",
         "run tools/update_budgets.py to add/remove the program entry"),
    Rule("PAR001", "sim/real API parity drift",
         "mirror the signature in both trees — the same program must compile "
         "against either backend"),
    Rule("PAR002", "public sim API without a real-backend dispatch",
         "branch on core.backend.is_real() (directly or via a helper) so the "
         "function works outside the simulation too"),
    Rule("SPC001", "spec fails validation or abstract evaluation",
         "fix the declaration/handler the message names — the spec cannot "
         "lower until its own model is well-formed"),
    Rule("SPC010", "unreachable message kind",
         "seed it from init, emit it from a reachable transition, or delete "
         "the dead kind (and its handler)"),
    Rule("SPC011", "message kind delivered but not handled",
         "add a handler, or declare the drop deliberate via ignore=(...) on "
         "the spec — implicit drops are how real protocol bugs hide"),
    Rule("SPC012", "transition with no effects (dead no-op handler)",
         "implement it, delete it, or declare the kind in terminal=(...) if "
         "absorbing is the point"),
    Rule("SPC013", "spec declaration hygiene (ignore/terminal misuse)",
         "ignore/terminal must name declared kinds, an ignored kind cannot "
         "also be handled, and a terminal kind's handler must not emit"),
    Rule("SPC020", "timer handled but never armed on any path",
         "arm it from a transition, the on_restart hook or an init event — "
         "or delete the dead timer"),
    Rule("SPC021", "multiple timer arms without provably-disjoint conditions",
         "the lowering's single merged timer row is last-write-wins; make "
         "the arm conditions disjoint (when=cond / when=~cond) or split the "
         "transition"),
    Rule("SPC030", "written value can exceed the packed lane dtype",
         "the static bound escapes the rail lane_dtype() chose from the "
         "declared range — widen the declared range (costs a wider lane), "
         "clip the expression, or tighten the inputs"),
    Rule("SPC031", "emitted payload word can escape its declared range",
         "the receiver's arg() read assumes the declared word range; widen "
         "the Word declaration or narrow the sent expression"),
    Rule("SPC040", "multiple sends without provably-disjoint conditions",
         "the single merged message row broadcasts ONE payload per step — "
         "per-destination payloads/concurrent sends are a known DSL gap; "
         "make the send conditions disjoint or split across kinds"),
    Rule("SPC041", "more than one RNG draw in a single transition",
         "the static-draw-shape rule allows one draw per event; combine "
         "draws into one mapped value or move a draw to another kind"),
    Rule("SPC050", "volatile lane read with no on_restart reconstruction",
         "a post-restart read sees the reset value; mark the lane durable, "
         "or add an on_restart hook that rebuilds it"),
    Rule("SPC900", "stale lint_allow entry: its code suppressed nothing",
         "delete the code from the spec's lint_allow tuple (or the defect "
         "it excused came back)"),
]}


# -- pass-1 call tables ------------------------------------------------------
# Fully-qualified call name (after import-alias resolution) -> rule code.

_RANDOM_GLOBALS = (
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "uniform", "getrandbits", "sample", "randbytes", "gauss", "betavariate",
    "expovariate", "normalvariate", "triangular", "vonmisesvariate", "seed",
)

EXACT_CALLS: Dict[str, str] = {
    # DET001 — wall clock
    "time.time": "DET001",
    "time.time_ns": "DET001",
    "time.monotonic": "DET001",
    "time.monotonic_ns": "DET001",
    "time.perf_counter": "DET001",
    "time.perf_counter_ns": "DET001",
    "time.process_time": "DET001",
    "time.thread_time": "DET001",
    "time.thread_time_ns": "DET001",
    "time.sleep": "DET001",
    "datetime.datetime.now": "DET001",
    "datetime.datetime.utcnow": "DET001",
    "datetime.datetime.today": "DET001",
    "datetime.date.today": "DET001",
    # DET002 — ambient entropy
    "os.urandom": "DET002",
    "os.getrandom": "DET002",
    "uuid.uuid1": "DET002",
    "uuid.uuid4": "DET002",
    "random.SystemRandom": "DET002",
    # DET003 — real concurrency
    "threading.Thread": "DET003",
    "threading.Timer": "DET003",
    "concurrent.futures.ThreadPoolExecutor": "DET003",
    "concurrent.futures.ProcessPoolExecutor": "DET003",
    "multiprocessing.Process": "DET003",
    "multiprocessing.Pool": "DET003",
    # DET004 — host introspection used for sizing
    "os.cpu_count": "DET004",
    "os.process_cpu_count": "DET004",
    "os.sched_getaffinity": "DET004",
    "multiprocessing.cpu_count": "DET004",
    # DET005 — raw sockets
    "socket.socket": "DET005",
    "socket.create_connection": "DET005",
    "socket.socketpair": "DET005",
    "socket.create_server": "DET005",
}
EXACT_CALLS.update({f"random.{fn}": "DET002" for fn in _RANDOM_GLOBALS})

# Dotted-prefix matches (any call under the module escapes).
PREFIX_CALLS: Dict[str, str] = {
    "secrets.": "DET002",
    # DET007 — jax.profiler trace capture (and its wall-clock timeline)
    # started from simulation/engine code: the capture observes HOST
    # time and scheduling, so any code path that branches on it (or a
    # trace accidentally left running across a step) is a sim-visible
    # nondeterminism escape. The observatory's host-side emitter
    # (obs/observatory.py) is the sanctioned site, pragma'd per line.
    "jax.profiler.": "DET007",
}

# Clock-DEFAULT calls (DET001, decode-path extension for obs/ timeline
# code): these read the wall clock only when the time operand is omitted
# — with an explicit seconds/struct_time argument they are pure
# converters a timeline renderer may legitimately use on *virtual*
# timestamps. Value = (rule, max positional args at which the call still
# defaults to "now"): ``time.ctime()`` escapes, ``time.ctime(t_us)`` is
# clean; ``time.strftime(fmt)`` escapes, ``strftime(fmt, tm)`` is clean.
# Motivated by obs/timeline.py: exported timelines must be byte-stable
# across replays, so every timestamp comes from virtual time.
CLOCK_DEFAULT_CALLS: Dict[str, Tuple[str, int]] = {
    "time.ctime": ("DET001", 0),
    "time.asctime": ("DET001", 0),
    "time.localtime": ("DET001", 0),
    "time.gmtime": ("DET001", 0),
    "time.strftime": ("DET001", 1),
}

# Attribute-name matches on an unresolvable receiver: `loop` in
# `loop.run_in_executor(...)` has no static type, but the method name alone
# identifies the escape (real threads behind the event loop).
ATTR_CALLS: Dict[str, str] = {
    "run_in_executor": "DET003",
}

# Attribute calls that escape only on an *event-loop* receiver: the bare
# method name is too common to flag everywhere (`self.time()` is the shim
# loop's own virtual clock), but `loop.time()` on an asyncio loop handle
# reads the host monotonic clock. Keyed by method name; the value's
# receiver set is matched against a bare-name receiver (exact name, or a
# `_`-suffix match like `event_loop`).
LOOP_ATTR_CALLS: Dict[str, Tuple[str, Tuple[str, ...]]] = {
    "time": ("DET001", ("loop",)),
}


# -- sync-discipline tables (DET008/DET009) ----------------------------------
# The orchestration hot loops live by a counted-fetch contract (docs/perf.md
# "Pipelined orchestration"): the ONLY device->host pull per superstep is the
# `_fetch` hook, which the tier-1 sync tests monkeypatch and count. These
# modules get the extra pass; everywhere else a blocking read is just slow,
# here it silently breaks the dispatch-ahead pipeline.
HOT_LOOP_MODULES = frozenset({
    "madsim_tpu/parallel/sweep.py",
    "madsim_tpu/fleet/worker.py",
    # The fabric scheduler drives every worker quantum (ISSUE 17: the
    # per-round loop is now the fleet's only serial section) — a stray
    # device pull here would stall every worker's pipeline at once.
    "madsim_tpu/fleet/fabric.py",
    "madsim_tpu/obs/observatory.py",
    "madsim_tpu/bridge/pool.py",
})

# First-line marker opting any other file into the hot-loop pass (fixtures,
# user orchestration code): `# tracelint: hot-loop`.
HOT_LOOP_MARKER = "tracelint: hot-loop"

# Fully-qualified jax APIs that ARE a blocking sync (or hand one out).
SYNC_CALLS = frozenset({
    "jax.device_get",
    "jax.block_until_ready",
    "jax.effects_barrier",
})

# Method names that force materialization on an arbitrary receiver.
SYNC_METHODS = frozenset({"item", "block_until_ready"})

# Host-conversion callables: np.asarray(x)/np.array(x)/float(x)/... block
# when x is a device array. Flagged (DET008) when applied directly to a
# fresh jnp./jax. call result, or (DET009) to a name the module-order taint
# scan marked device-resident and never `_fetch`ed.
CONVERT_NP = frozenset({"asarray", "array", "copy"})
CONVERT_BUILTINS = frozenset({"float", "int", "bool"})

# The sanctioned pull hook: assignments FROM it mark their targets as host
# values, and calls THROUGH it are never findings.
FETCH_NAMES = frozenset({"_fetch"})

# Callees whose results are device-resident (taint sources for DET009);
# `jnp.`-rooted calls are device-typed by construction, the rest are the
# repo's device-placement helpers.
DEVICE_CALL_HEADS = frozenset({"jnp"})
DEVICE_CALLS = frozenset({
    "jax.device_put",
    "shard_worlds",
})
