"""detlint/tracelint CLI: ``python -m madsim_tpu.analysis [trace] [...]``.

Two entry shapes, one exit-code contract (0 clean, 1 findings, 2
usage/config error — the Makefile/CI gate is just the exit code):

- ``python -m madsim_tpu.analysis [paths...]`` — the AST passes
  (nondeterminism escapes + sim/real parity + hot-loop sync discipline).
- ``python -m madsim_tpu.analysis trace`` — pass 3 (tracelint): jaxpr
  rules over the registered hot-path programs plus the budget-ledger
  diff (``--no-budgets`` for the trace rules alone).
- ``python -m madsim_tpu.analysis spec`` — pass 4 (speclint): protocol
  verification of the shipped actorc specs (``--card FAMILY`` prints a
  family's protocol card instead of linting).

Output: human text (default), ``--json`` machine-readable findings, or
``--format=github`` workflow-annotation lines so CI findings surface as
inline annotations instead of buried log text.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from .escape import run_escape_pass
from .parity import run_parity_pass
from .pragmas import Allowlist, Finding
from .rules import RULES

DEFAULT_ALLOWLIST = "detlint-allow.txt"
DEFAULT_PATHS = ["madsim_tpu", "tools"]


def run_lint(root: str, paths: List[str],
             allowlist: Optional[Allowlist] = None,
             escape: bool = True, parity: bool = True,
             check_allowlist: bool = True,
             allowlist_name: str = DEFAULT_ALLOWLIST) -> List[Finding]:
    """Both AST passes over ``paths`` under ``root``; the library entry
    tests and embedders use (the CLI is a thin shell over this).

    ``check_allowlist``: after filtering, flag allowlist entries that
    matched no finding (DET901) — but only when both passes ran (a
    skipped pass could be the entry's whole audience) and only for
    entries whose path prefix the scan surface covered.
    """
    allowlist = allowlist or Allowlist.empty()
    findings: List[Finding] = []
    if escape:
        findings.extend(run_escape_pass(root, paths, allowlist))
    if parity:
        findings.extend(allowlist.filter(run_parity_pass(root)))
    if check_allowlist and escape and parity:
        for entry in allowlist.stale_entries(paths):
            rule = f":{entry.rule}" if entry.rule else ""
            findings.append(Finding(
                allowlist_name, entry.line, "DET901",
                f"stale allowlist entry: `{entry.prefix}{rule}` matches no "
                f"finding under the scanned surface — delete the line (the "
                "tree it excused is clean, or was renamed)"))
    return findings


def render_findings(findings: List[Finding], fmt: str,
                    label: str = "detlint") -> None:
    """Print findings in the chosen format; the summary line goes to
    stderr so stdout stays machine-parseable."""
    if fmt == "json":
        print(json.dumps([f._asdict() for f in findings]))
        return
    for f in findings:
        if fmt == "github":
            # GitHub workflow-annotation command: renders as an inline
            # file annotation on the PR diff.
            msg = f.message.replace("%", "%25").replace("\r", "%0D") \
                .replace("\n", "%0A")
            print(f"::error file={f.path},line={max(f.line, 1)},"
                  f"title={f.rule}::{msg}")
        else:
            print(f.render())
    n = len(findings)
    print(f"{label}: {n} finding{'s' if n != 1 else ''}"
          if n else f"{label}: clean", file=sys.stderr)


def _add_format_args(ap: argparse.ArgumentParser) -> None:
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout "
                         "(alias for --format=json)")
    ap.add_argument("--format", choices=("text", "json", "github"),
                    default="text",
                    help="output format; `github` emits workflow-"
                         "annotation lines for inline CI annotations")


def _fmt(args) -> str:
    return "json" if args.json else args.format


# ---------------------------------------------------------------------------
# `trace` subcommand — pass 3 (tracelint)
# ---------------------------------------------------------------------------

def _prepare_trace_env() -> None:
    """Default the JAX platform to the virtual 8-device CPU mesh the
    ledger shapes are pinned to — BEFORE jax is first imported. A jax
    already imported with different devices is left alone (the caller
    opted into their own topology)."""
    if "jax" in sys.modules:
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def main_trace(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="detlint trace",
        description="tracelint: program-level static analysis of the "
                    "compiled sweep — jaxpr rules (TRC001-003), donation "
                    "contracts (TRC004), and the checked-in cost-budget "
                    "ledger (BUD001/BUD002)")
    ap.add_argument("--programs", default=None,
                    help="comma-separated subset of registered programs")
    ap.add_argument("--list-programs", action="store_true")
    ap.add_argument("--no-budgets", action="store_true",
                    help="trace rules only: skip the fresh compiles and "
                         "the ledger diff (fast)")
    ap.add_argument("--budgets", default=None, metavar="PATH",
                    help="ledger file (default: analysis/budgets.json "
                         "inside the package)")
    ap.add_argument("--allowlist", default=None,
                    help="allowlist file applied to trace/<program> "
                         "pseudo-paths (default: ./detlint-allow.txt "
                         "when present)")
    _add_format_args(ap)
    args = ap.parse_args(argv)

    _prepare_trace_env()
    from .tracelint import registry, run_trace

    if args.list_programs:
        for name, prog in sorted(registry().items()):
            tags = []
            if prog.budget:
                tags.append("budget")
            if prog.donates:
                tags.append("donates")
            if prog.x64 == "required":
                tags.append("x64")
            tag = f" [{','.join(tags)}]" if tags else ""
            print(f"{name:28s} {prog.title}{tag}")
        return 0

    programs = ([p.strip() for p in args.programs.split(",") if p.strip()]
                if args.programs else None)
    try:
        findings, _measured = run_trace(
            programs=programs, budget_check=not args.no_budgets,
            ledger_path=args.budgets)
    except (KeyError, FileNotFoundError, ValueError) as exc:
        print(f"tracelint: {exc}", file=sys.stderr)
        return 2

    allowlist = Allowlist.empty()
    allow_path = args.allowlist or DEFAULT_ALLOWLIST
    if os.path.isfile(allow_path):
        allowlist = Allowlist.load(allow_path)
    elif args.allowlist is not None:
        print(f"tracelint: allowlist not found: {args.allowlist}",
              file=sys.stderr)
        return 2
    findings = allowlist.filter(findings)
    render_findings(findings, _fmt(args), label="tracelint")
    return 1 if findings else 0


# ---------------------------------------------------------------------------
# the AST passes (the original detlint entry)
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "trace":
        return main_trace(argv[1:])
    if argv and argv[0] == "spec":
        _prepare_trace_env()  # specs import jax the same way programs do
        from .speclint import main_spec

        return main_spec(argv[1:])

    ap = argparse.ArgumentParser(
        prog="detlint",
        description="madsim_tpu static analyzer: nondeterminism escapes "
                    "(pass 1) + sim/real API parity (pass 2); "
                    "`trace` subcommand for pass 3 (tracelint), `spec` "
                    "subcommand for pass 4 (speclint)")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to scan (default: {DEFAULT_PATHS})")
    ap.add_argument("--root", default=".",
                    help="tree root paths are relative to (default: cwd)")
    ap.add_argument("--allowlist", default=None,
                    help="allowlist file (default: <root>/detlint-allow.txt "
                         "when present)")
    ap.add_argument("--no-parity", action="store_true",
                    help="skip pass 2 (sim/real parity)")
    ap.add_argument("--no-escape", action="store_true",
                    help="skip pass 1 (nondeterminism escapes)")
    _add_format_args(ap)
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in RULES.values():
            print(f"{rule.code}  {rule.title}\n        fix: {rule.suggestion}")
        return 0

    root = os.path.abspath(args.root)
    if not os.path.isdir(root):
        print(f"detlint: root {args.root!r} is not a directory",
              file=sys.stderr)
        return 2
    paths = args.paths or [p for p in DEFAULT_PATHS
                           if os.path.exists(os.path.join(root, p))]
    if not paths:
        print(f"detlint: nothing to scan under {root!r} "
              f"(no paths given and none of {DEFAULT_PATHS} exist)",
              file=sys.stderr)
        return 2
    for p in paths:
        if not os.path.exists(os.path.join(root, p)):
            print(f"detlint: no such path under root: {p}", file=sys.stderr)
            return 2

    allowlist = Allowlist.empty()
    allow_path = args.allowlist or os.path.join(root, DEFAULT_ALLOWLIST)
    if os.path.isfile(allow_path):
        allowlist = Allowlist.load(allow_path)
    elif args.allowlist is not None:
        print(f"detlint: allowlist not found: {args.allowlist}",
              file=sys.stderr)
        return 2

    findings = run_lint(root, paths, allowlist,
                        escape=not args.no_escape,
                        parity=not args.no_parity,
                        check_allowlist=os.path.isfile(allow_path),
                        allowlist_name=os.path.basename(allow_path))
    render_findings(findings, _fmt(args))
    return 1 if findings else 0
