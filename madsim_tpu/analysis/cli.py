"""detlint CLI: ``python -m madsim_tpu.analysis [paths...]``.

Exit codes: 0 clean, 1 findings, 2 usage/config error — the Makefile/CI
gate is just the exit code.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from .escape import run_escape_pass
from .parity import run_parity_pass
from .pragmas import Allowlist, Finding
from .rules import RULES

DEFAULT_ALLOWLIST = "detlint-allow.txt"
DEFAULT_PATHS = ["madsim_tpu", "tools"]


def run_lint(root: str, paths: List[str],
             allowlist: Optional[Allowlist] = None,
             escape: bool = True, parity: bool = True) -> List[Finding]:
    """Both passes over ``paths`` under ``root``; the library entry tests
    and embedders use (the CLI is a thin shell over this)."""
    allowlist = allowlist or Allowlist.empty()
    findings: List[Finding] = []
    if escape:
        findings.extend(run_escape_pass(root, paths, allowlist))
    if parity:
        findings.extend(allowlist.filter(run_parity_pass(root)))
    return findings


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="detlint",
        description="madsim_tpu static analyzer: nondeterminism escapes "
                    "(pass 1) + sim/real API parity (pass 2)")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to scan (default: {DEFAULT_PATHS})")
    ap.add_argument("--root", default=".",
                    help="tree root paths are relative to (default: cwd)")
    ap.add_argument("--allowlist", default=None,
                    help="allowlist file (default: <root>/detlint-allow.txt "
                         "when present)")
    ap.add_argument("--no-parity", action="store_true",
                    help="skip pass 2 (sim/real parity)")
    ap.add_argument("--no-escape", action="store_true",
                    help="skip pass 1 (nondeterminism escapes)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in RULES.values():
            print(f"{rule.code}  {rule.title}\n        fix: {rule.suggestion}")
        return 0

    root = os.path.abspath(args.root)
    if not os.path.isdir(root):
        print(f"detlint: root {args.root!r} is not a directory",
              file=sys.stderr)
        return 2
    paths = args.paths or [p for p in DEFAULT_PATHS
                           if os.path.exists(os.path.join(root, p))]
    if not paths:
        print(f"detlint: nothing to scan under {root!r} "
              f"(no paths given and none of {DEFAULT_PATHS} exist)",
              file=sys.stderr)
        return 2
    for p in paths:
        if not os.path.exists(os.path.join(root, p)):
            print(f"detlint: no such path under root: {p}", file=sys.stderr)
            return 2

    allowlist = Allowlist.empty()
    allow_path = args.allowlist or os.path.join(root, DEFAULT_ALLOWLIST)
    if os.path.isfile(allow_path):
        allowlist = Allowlist.load(allow_path)
    elif args.allowlist is not None:
        print(f"detlint: allowlist not found: {args.allowlist}",
              file=sys.stderr)
        return 2

    findings = run_lint(root, paths, allowlist,
                        escape=not args.no_escape, parity=not args.no_parity)
    if args.json:
        print(json.dumps([f._asdict() for f in findings]))
    else:
        for f in findings:
            print(f.render())
        n = len(findings)
        print(f"detlint: {n} finding{'s' if n != 1 else ''}"
              if n else "detlint: clean", file=sys.stderr)
    return 1 if findings else 0
