"""Persistent XLA compilation cache for cold processes.

Every spawned fleet worker (``fleet/process.py``) and every CI
invocation pays the full program-set compile from scratch: jit caches
are per-process, and a fleet of N workers compiles the SAME sweep
runner N times. JAX's persistent compilation cache
(``jax_compilation_cache_dir``) is the fix — executables are stored on
disk keyed by HLO + compile flags, so the first process populates and
every later cold process (worker respawn after SIGKILL, the next CI
shard, the next ``make check``) loads instead of compiling.

Correctness-neutral by construction: the cache key covers the program
and the backend configuration, and result determinism is separately
pinned by the crosscheck/determinism suites — ``tests/
test_compile_cache.py`` additionally asserts cached-vs-fresh bitwise
equality end to end.

Opt-in surfaces:

- ``enable_compilation_cache(path)`` — call before tracing; idempotent.
- ``MADSIM_COMPILE_CACHE`` env var — honored by spawned fleet workers
  (set automatically by ``process_fleet_sweep`` when the fleet has a
  checkpoint dir: the cache lives beside the checkpoints, the one
  durable workdir a deployment already has) and by ``make check``.
"""
from __future__ import annotations

import os
from typing import Optional

ENV_VAR = "MADSIM_COMPILE_CACHE"

_enabled_dir: Optional[str] = None


def enable_compilation_cache(cache_dir: str) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Safe to call more than once (last path wins, matching
    ``jax.config`` semantics) and before OR after jax is first
    imported — but must run before the programs you want cached are
    compiled. Thresholds are zeroed so every program is eligible: this
    codebase's programs are few, large, and identical across processes,
    the exact shape the cache exists for.
    """
    global _enabled_dir
    import jax

    cache_dir = os.path.abspath(cache_dir)
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _enabled_dir = cache_dir
    return cache_dir


def enable_from_env() -> Optional[str]:
    """Enable the cache iff ``MADSIM_COMPILE_CACHE`` is set (worker-
    process entry hook). Returns the cache dir, or None if unset."""
    path = os.environ.get(ENV_VAR)
    if not path:
        return None
    return enable_compilation_cache(path)
