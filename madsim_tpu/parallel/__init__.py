"""Seed-parallelism over TPU meshes.

The reference's only multi-simulation parallelism is one OS thread per seed
(`madsim/src/sim/runtime/builder.rs:118-136`, ``MADSIM_TEST_JOBS``). Here the
world (seed) axis of the batched device engine is data-parallel state, so it
shards across a `jax.sharding.Mesh`: each chip advances its shard of worlds
with zero communication, and the only collectives are tiny reductions over
the bug/active flags riding ICI (`any`-reduce to answer "did any seed find a
bug?" without pulling per-world state to host). Multi-host sweeps extend the
same mesh over DCN — the sharded world axis simply spans processes.
"""
from .mesh import (multihost_mesh, seed_mesh, shard_worlds, world_sharding,
                   world_spec)
from .sweep import SweepResult, SweepSession, sharded_engine, sweep

__all__ = ["seed_mesh", "multihost_mesh", "shard_worlds", "world_spec",
           "world_sharding", "sharded_engine", "sweep", "SweepResult",
           "SweepSession"]
