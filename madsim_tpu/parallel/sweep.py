"""Sharded multi-seed sweeps: the TPU replacement for MADSIM_TEST_JOBS.

``sweep`` is the device-engine counterpart of the host test driver's seed
loop (`madsim/src/sim/runtime/builder.rs:110-148` / madsim_tpu.testing):
initialize one world per seed, shard the world axis over the mesh, advance
all worlds in fixed-step chunks, and after each chunk reduce two tiny scalars
over ICI — "any bug found?" and "how many worlds still active?" — so the host
loop makes progress/early-exit decisions without ever pulling per-world state
off device. Failing seeds (the repro banner of `runtime/mod.rs:192-199`)
are gathered once, at the end.

The loop is a slot-occupancy model (docs/perf.md "World recycling"): the
batch is a fixed set of world slots, compaction is an on-device stable
partition (no host pull of per-world state), and with ``recycle=True``
retired slots are refilled with fresh seeds from a host-side cursor so
the mesh stays full for open-ended hunts. Per-chunk occupancy telemetry
(``n_active_history`` / ``world_utilization``) rides every result.

Orchestration is *pipelined and superstepped* by default (docs/perf.md
"Pipelined orchestration"): up to ``superstep_max`` chunks fold into one
jitted ``lax.while_loop`` dispatch whose early-exit decisions (all
retired / occupancy at the recycle threshold / bug under
``stop_on_first_bug``) run ON DEVICE, and the host issues superstep k+1
before reading superstep k's scalars, so the device queue stays non-empty
while the host decides. A superstep dispatched past a stop/recycle point
is a bitwise pass-through (its entry condition is already false), which is
what makes one-dispatch-stale decisions exact rather than approximate:
results are bit-identical to the serial per-chunk loop (``pipeline=False``,
kept as the equivalence reference and tier-1-tested against).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding

try:  # jax >= 0.8 promotes shard_map out of experimental
    from jax import shard_map
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

from ..engine.core import DeviceEngine, EngineConfig, WorldState
from .mesh import (
    scalar_spec,
    seed_mesh,
    shard_worlds,
    world_sharding,
    world_spec,
)

# Every device→host pull the sweep loop makes goes through this hook, so
# the tier-1 sync-discipline test (tests/test_sweep_pipeline.py) can count
# host-boundary crossings per superstep by monkeypatching it, and the
# static twin (detlint DET008/DET009, docs/detlint.md) can treat any other
# blocking read in this module as a finding. Semantics: jax.device_get of
# an arbitrary pytree.
_fetch = jax.device_get  # detlint: allow[DET008] reason=the ONE sanctioned pull hook; runtime tests count calls through this exact name


def _cov_reducers(mesh: Mesh):
    """Mesh reductions for the coverage ledger: psum for bucket counts,
    pmin for first-seen seed ids (obs/coverage.py fold_retired)."""
    axes = tuple(mesh.axis_names)
    return (lambda x: jax.lax.psum(x, axes),
            lambda x: jax.lax.pmin(x, axes))


def sharded_engine(eng: DeviceEngine, mesh: Mesh, chunk_steps: int = 512,
                   donate: bool = False,
                   coverage: Optional[int] = None):
    """Compile a chunk runner: state → (state, any_bug, n_active).

    The body is `shard_map`'d so each device advances only its world shard
    (no resharding possible); the two scalar outputs are psum/any reductions
    over ALL mesh axes — ICI within a host, DCN across hosts on a 2-D
    ``multihost_mesh`` — the only cross-chip communication in a sweep.

    ``donate=True`` donates the input state: XLA updates the sharded
    batch in place instead of double-buffering it, which roughly doubles
    the W that fits in HBM — but the caller's reference is DEAD after
    each call. The sweep enables this exactly when no checkpoint writer
    is attached: the async checkpointer reads the pre-chunk state from a
    background thread, which donation would invalidate.

    ``coverage`` (bucket count, or None): the retire-time behavior fold
    (obs/coverage.py). The runner signature widens to
    ``(state, hits, first_seen, idx, n_real) → (state, any_bug,
    n_active, hits, first_seen, distinct)``: after the chunk body, the
    worlds whose active flag fell during the chunk scatter their
    behavior signatures into the replicated K-bucket ledger (psum/pmin
    over the mesh — the only additions; the chunk body itself is
    untouched, so trajectories stay bitwise identical and with
    ``coverage=None`` this compiles the exact pre-coverage program).

    Runners are cached per (mesh, chunk_steps, donate, coverage) on the
    engine, so repeated sweeps reuse the compiled program instead of
    paying a fresh XLA compile for an identical closure.
    """
    cache = eng.__dict__.setdefault("_sharded_runner_cache", {})
    key = (mesh, chunk_steps, donate, coverage)
    if key in cache:
        return cache[key]
    spec = world_spec(mesh)
    axes = tuple(mesh.axis_names)
    sp = scalar_spec()

    if coverage is None:
        def chunk(state: WorldState):
            state = eng._run_steps_impl(state, chunk_steps)
            any_bug = jax.lax.psum(
                jnp.any(state.bug).astype(jnp.int32), axes) > 0
            n_active = jax.lax.psum(
                jnp.sum(state.active, dtype=jnp.int32), axes)
            return state, any_bug, n_active

        in_specs, out_specs = (spec,), (spec, sp, sp)
    else:
        from ..obs.coverage import distinct_count, fold_retired

        rsum, rmin = _cov_reducers(mesh)

        def chunk(state: WorldState, hits, first, idx, n_real):
            act0 = state.active
            state = eng._run_steps_impl(state, chunk_steps)
            any_bug = jax.lax.psum(
                jnp.any(state.bug).astype(jnp.int32), axes) > 0
            n_active = jax.lax.psum(
                jnp.sum(state.active, dtype=jnp.int32), axes)
            mask = act0 & ~state.active & (idx >= 0) & (idx < n_real)
            hits, first = fold_retired(hits, first, state.metrics, mask,
                                       idx, rsum, rmin)
            return state, any_bug, n_active, hits, first, \
                distinct_count(hits)

        in_specs = (spec, sp, sp, spec, sp)
        out_specs = (spec, sp, sp, sp, sp, sp)

    try:  # jax >= 0.8 renamed check_rep -> check_vma
        mapped = shard_map(chunk, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
    except TypeError:  # pragma: no cover — older jax
        mapped = shard_map(chunk, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_rep=False)
    runner = jax.jit(mapped, donate_argnums=(0,) if donate else ())
    cache[key] = runner
    return runner


def sharded_superstep(eng: DeviceEngine, mesh: Mesh, chunk_steps: int,
                      k_max: int, donate: bool = False,
                      min_one: bool = False,
                      coverage: Optional[int] = None):
    """Compile a superstep runner:
    ``(state, stop_threshold, stop_on_bug, k_chunks) → (state, any_bug,
    n_active, k_done, hist)``.

    The superstep folds up to ``k_chunks`` chunk bodies into ONE jitted
    dispatch (`DeviceEngine._superstep_impl`): a ``lax.while_loop`` whose
    condition re-checks the psum'd occupancy/bug scalars after every
    chunk, so the early exits the serial loop made from the host run on
    device and the host pays one dispatch + one scalar read per K chunks.
    ``stop_threshold`` / ``stop_on_bug`` / ``k_chunks`` are traced
    scalars — ONE compiled program per (mesh, chunk_steps, k_max,
    donate, min_one) serves every threshold and superstep length the
    adaptive schedule cycles through; only the (k_max,)-shaped history
    buffer is compile-time static.

    ``hist[j]`` is the post-chunk active count for each chunk actually
    run (-1 beyond ``k_done``) — the same per-chunk sequence the serial
    loop's ``n_active_history`` records. ``min_one`` forces the first
    chunk regardless of the entry condition (the serial loop's cadence
    right after a refill/shrink — see ``_superstep_impl``). Donation
    follows :func:`sharded_engine` (on exactly when no checkpoint writer
    holds state references between dispatches).

    ``coverage`` (bucket count, or None) threads the retire-time
    behavior ledger (obs/coverage.py) through the on-device chunk loop:
    the runner widens to ``(state, hits, first_seen, idx, n_real,
    stop_threshold, stop_on_bug, k_chunks) → (state, any_bug, n_active,
    k_done, hist, hits, first_seen, cov_hist)``, where ``cov_hist[j]``
    is the cumulative distinct-behavior count after chunk ``j`` — the
    novelty curve at exactly the ``hist`` cadence, riding the SAME
    scalar fetch (zero extra device→host syncs). A pass-through
    superstep (entry condition already false) folds nothing, which is
    what keeps the ledger — like everything else — bitwise identical
    between the dispatch-ahead and serial loops.
    """
    cache = eng.__dict__.setdefault("_sharded_superstep_cache", {})
    key = (mesh, chunk_steps, k_max, donate, min_one, coverage)
    if key in cache:
        return cache[key]
    spec = world_spec(mesh)
    axes = tuple(mesh.axis_names)
    sp = scalar_spec()
    rsum = lambda x: jax.lax.psum(x, axes)  # noqa: E731

    if coverage is None:
        def sstep(state: WorldState, stop_threshold, stop_on_bug, k_chunks):
            return eng._superstep_impl(
                state, stop_threshold, stop_on_bug, k_chunks,
                chunk_steps=chunk_steps, k_max=k_max,
                reduce_sum=rsum, min_one=min_one)

        in_specs = (spec, sp, sp, sp)
        out_specs = (spec, sp, sp, sp, sp)
    else:
        from ..obs.coverage import fold_retired

        _, rmin = _cov_reducers(mesh)

        def sstep(state: WorldState, hits, first, idx, n_real,
                  stop_threshold, stop_on_bug, k_chunks):
            def fold(cov, act0, s):
                h, f = cov
                mask = act0 & ~s.active & (idx >= 0) & (idx < n_real)
                return fold_retired(h, f, s.metrics, mask, idx, rsum, rmin)

            state, any_bug, n_active, k_done, hist, (hits, first), ch = \
                eng._superstep_impl(
                    state, stop_threshold, stop_on_bug, k_chunks,
                    chunk_steps=chunk_steps, k_max=k_max,
                    reduce_sum=rsum, min_one=min_one,
                    cov=(hits, first), cov_fold=fold)
            return state, any_bug, n_active, k_done, hist, hits, first, ch

        in_specs = (spec, sp, sp, spec, sp, sp, sp, sp)
        out_specs = (spec, sp, sp, sp, sp, sp, sp, sp)

    try:  # jax >= 0.8 renamed check_rep -> check_vma
        mapped = shard_map(sstep, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
    except TypeError:  # pragma: no cover — older jax
        mapped = shard_map(sstep, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_rep=False)
    runner = jax.jit(mapped, donate_argnums=(0,) if donate else ())
    cache[key] = runner
    return runner


def _cov_endfolder(eng: DeviceEngine, mesh: Mesh):
    """Compile (and cache per engine) the boundary coverage fold.

    One shard_mapped program folding the worlds whose ``active`` flag
    equals ``fold_active`` into the ledger: the sweep runs it with
    ``fold_active=False`` on resume (worlds that retired before the
    checkpoint carry frozen histograms but will never transition
    active→inactive in THIS call) and with ``fold_active=True`` at sweep
    end (worlds still live at exit — a truncated behavior is a behavior
    too). Because ``hits``/``first_seen`` are fold-order invariant
    (counts and minima), a resumed sweep's final ledger is bit-identical
    to an unbroken run's (tests/test_obs.py). Shapes key jit's own
    retrace cache, so one entry serves every batch width.
    """
    cache = eng.__dict__.setdefault("_cov_endfolder_cache", {})
    if mesh in cache:
        return cache[mesh]
    from ..obs.coverage import fold_retired

    spec = world_spec(mesh)
    sp = scalar_spec()
    rsum, rmin = _cov_reducers(mesh)

    def fold_end(state, hits, first, idx, n_real, fold_active):
        mask = (state.active == fold_active) & (idx >= 0) & (idx < n_real)
        return fold_retired(hits, first, state.metrics, mask, idx,
                            rsum, rmin)

    in_specs = (spec, sp, sp, spec, sp, sp)
    out_specs = (sp, sp)
    try:  # jax >= 0.8 renamed check_rep -> check_vma
        mapped = shard_map(fold_end, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
    except TypeError:  # pragma: no cover — older jax
        mapped = shard_map(fold_end, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_rep=False)
    fn = jax.jit(mapped)
    cache[mesh] = fn
    return fn


class TriageContext(NamedTuple):
    """What :meth:`SweepResult.minimize` / ``triage.triage`` need to
    re-execute worlds from this sweep: the engine (compiled programs and
    all), the ORIGINAL fault schedule argument, and the mesh. Attached
    to every locally-run SweepResult; absent (None) on results
    reconstructed from checkpoints or merged across a fleet — those
    must re-run the sweep to minimize.

    Guided sweeps (``search=``) attach the MATERIALIZED per-seed
    ``(n, F, 4)`` schedules here instead of the template argument — each
    world ran a generated child schedule, and this is what lets every
    find pipe unchanged through ``triage.triage`` → ddmin → minimized
    bundles (docs/search.md)."""

    engine: Any                 # the DeviceEngine the sweep ran
    faults: Optional[Any]       # the faults= argument (or, under
                                # search=, the materialized per-seed
                                # schedules)
    mesh: Any                   # the mesh the sweep ran on


class _Flight(NamedTuple):
    """One dispatched-but-unread superstep: its scalar futures plus the
    host-side facts (plan, width, epoch) needed to interpret them."""

    any_bug: Any
    n_active: Any
    k_done: Any
    hist: Any
    planned: int          # chunks this dispatch may run (its K)
    w: int                # batch width at dispatch time
    epoch: int            # occupancy epoch at dispatch time
    out_state: Any        # output state ref — kept ONLY for the writer
    cov_hist: Any = None  # per-chunk novelty-curve lane (coverage on)
    # Ledger refs paired with out_state (writer + coverage only): the
    # loop's cov_hits/cov_first globals advance with dispatch-ahead, so
    # a checkpoint must snapshot the refs matching the state it writes —
    # else a resume would restore a ledger one superstep AHEAD of the
    # state and double-fold the replayed chunk's retirees.
    out_cov: Any = None


class _AsyncCheckpointer:
    """Background checkpoint writer: overlaps the device→host pull and the
    npz write with the next chunk's device work (VERDICT r4 item 7 — the
    synchronous save used to block the chunk loop for its full duration).

    Latest-wins coalescing: if the writer is still busy when the next
    snapshot arrives, the queued-but-unstarted one is replaced — for
    preemption survival only the newest durable state matters, and write
    cadence must not backpressure the sweep. Reading completed jax arrays
    from this thread is safe: whenever a writer is attached the sweep
    compiles its chunk runner WITHOUT input donation (donation would hand
    XLA the submitted buffers mid-read — see ``sharded_engine``), and
    the on-disk write stays atomic (engine/checkpoint.py tmp+rename).
    """

    def __init__(self, eng, path, extra_meta):
        import threading

        self._eng = eng
        self._path = path
        self._meta = extra_meta
        self._cond = threading.Condition()
        self._pending = None
        self._busy = False
        self._stop = False
        self._error: Optional[BaseException] = None
        # detlint: allow[DET003] — host-side checkpoint writer beside the device sweep
        self._thread = threading.Thread(
            target=self._run, name="madsim-checkpointer", daemon=True)
        self._thread.start()

    def submit(self, state, aux=None) -> None:
        """Queue a snapshot. ``aux`` (recycled sweeps) is a dict of
        sweep-level values saved beside the state: device arrays (the
        slot→seed index, the coverage ledger) are pulled by the writer
        thread, lists of host arrays (retired observation batches) are
        concatenated there — the loop thread never blocks on either."""
        with self._cond:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            self._pending = (state, aux)
            self._cond.notify_all()

    def _run(self) -> None:
        import jax as _jax

        from ..engine import checkpoint as ckpt

        while True:
            with self._cond:
                while self._pending is None and not self._stop:
                    self._cond.wait()
                if self._pending is None:
                    return
                (state, aux), self._pending = self._pending, None
                self._busy = True
            try:
                # Pull to host FIRST and drop the device reference: holding
                # the device pytree through the disk write would pin up to
                # a full extra state of HBM while the sweep runs ahead.
                # detlint: allow[DET008] reason=checkpoint writer THREAD; blocks itself, never the dispatch loop
                host_state, host_aux = _jax.device_get((state, aux))
                state = aux = None
                extra_arrays = None
                if host_aux is not None:
                    extra_arrays = {
                        k: (np.concatenate([np.asarray(a) for a in v],
                                           axis=0)
                            if isinstance(v, list) else np.asarray(v))
                        for k, v in host_aux.items()}
                ckpt.save(self._eng, host_state, self._path,
                          extra_meta=self._meta,
                          extra_arrays=extra_arrays)
                exc = None
            except BaseException as e:  # noqa: BLE001 — surfaced at submit/flush
                exc = e
            with self._cond:
                self._busy = False
                if exc is not None:
                    self._error = exc
                self._cond.notify_all()

    def flush_and_close(self, suppress_errors: bool = False) -> None:
        """Wait until every submitted snapshot is durable, then stop.

        ``suppress_errors`` logs a deferred writer failure instead of
        raising — for finally blocks where an in-flight exception must not
        be masked by a checkpoint-write error."""
        with self._cond:
            while self._pending is not None or self._busy:
                self._cond.wait()
            self._stop = True
            self._cond.notify_all()
        self._thread.join()
        if self._error is not None:
            if suppress_errors:
                import logging

                logging.getLogger("madsim_tpu.sweep").warning(
                    "checkpoint write failed during sweep teardown: %r",
                    self._error)
            else:
                raise self._error


@dataclasses.dataclass
class SweepResult:
    """Outcome of a sharded seed sweep."""

    seeds: np.ndarray            # the (unpadded) seed vector
    bug: np.ndarray              # per-seed bug flag
    observations: Dict[str, np.ndarray]  # engine + actor metrics, per seed
    steps_run: int               # executed chunks * chunk_steps
    n_devices: int
    # Occupancy telemetry (docs/perf.md "world recycling"): the active
    # world count after each chunk, and the fraction of issued slot-steps
    # that advanced a live world — useful/(sum over chunks of
    # batch_width*chunk_steps). Frozen worlds riding masked in the batch
    # are the difference; 1.0 means the mesh never ran a frozen slot.
    n_active_history: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.int64))
    world_utilization: float = 0.0
    # The chunk index each ``n_active_history`` entry was MEASURED at
    # (0-based count of executed chunks, aligned entrywise). Under the
    # pipelined loop the host reads a measurement only after dispatching
    # the next superstep, so the decision taken at dispatch d is based on
    # the entry measured at some chunk < d — up to one superstep behind.
    # The measurement sequence itself is per-chunk and identical to the
    # serial loop's; entries are strictly increasing (tier-1-tested).
    # The fused loop records the chunk index INSIDE the device program
    # (a lane of the mega-dispatch history alongside the occupancy
    # counts), so a K-chunk dispatch lands K correctly-indexed entries
    # — no skew relative to the serial sequence even though the host
    # only reads once per mega-dispatch (docs/perf.md "Whole-hunt
    # residency", measurement-skew caveat).
    n_active_chunks: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.int64))
    # Orchestration telemetry (docs/perf.md "Pipelined orchestration" /
    # "Whole-hunt residency"): dispatch counts, superstep fan-in, and
    # the host/device wall split of the chunk loop. Recorded into
    # bench_results.json under configs.*.sweep_loop. Keys: pipelined,
    # fused, chunks, dispatches, chunks_per_dispatch,
    # dispatches_per_seed, seeds_per_dispatch, epochs_on_device,
    # dispatch_depth, device_wait_s, host_decision_s, dispatch_s,
    # retire_wait_s, scalar_fetches, retire_fetches, loop_wall_s,
    # superstep_max, chunk_steps.
    loop_stats: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Fault-schedule fingerprint (sha256 over the padded rows, or of
    # b"none"): rides the result so repro banners and bundles can assert
    # the replay used the same schedule — a seed alone does not pin the
    # trajectory when schedules vary per run.
    faults_sha256: Optional[str] = None
    # Behavior-coverage ledger (obs/coverage.py SweepCoverage), present
    # when the engine ran ``EngineConfig(metrics=True)``: per-bucket hit
    # counts, lowest-seed-per-bucket attribution, and the per-chunk
    # ``novelty_curve`` (cumulative distinct behaviors, aligned
    # entrywise with ``n_active_history``/``n_active_chunks``).
    coverage: Optional[Any] = None
    # Guided-search report (search/__init__.py SearchReport), present
    # when the sweep ran ``search=SearchConfig(...)``: final corpus
    # contents, insert/generation counters, and the materialized
    # per-seed ``(n, F, 4)`` schedules each world actually ran (also
    # wired into ``triage_ctx.faults`` so triage needs no special
    # casing).
    search: Optional[Any] = None
    # Triage context (triage/): the engine/schedule/mesh refs
    # :meth:`minimize` and ``triage.triage`` re-execute worlds with.
    # None on reconstructed results (fleet merges, checkpoint loads).
    triage_ctx: Optional[TriageContext] = dataclasses.field(
        default=None, repr=False)

    @property
    def failing_seeds(self) -> List[int]:
        return [int(s) for s in self.seeds[self.bug]]

    def minimize(self, seed: Optional[int] = None, **kw):
        """Minimize a failing seed's fault schedule (triage/minimize.py).

        ``seed`` defaults to the first failing seed; ``kw`` forwards to
        :func:`madsim_tpu.triage.minimize` (``pipeline``, ``weaken``,
        ``tighten``, ``chunk_steps``, ``max_steps``, ...). Re-uses this
        sweep's engine — and its compiled programs — so the candidate
        sweeps pay no fresh actor compile. Returns a
        :class:`~madsim_tpu.triage.MinimizeResult` whose ``schedule``
        is the smallest still-failing row set, 1-minimal and
        deterministic (docs/triage.md)."""
        from ..triage import TriageError
        from ..triage import minimize as _minimize

        if self.triage_ctx is None:
            raise TriageError(
                "this SweepResult carries no triage context (merged or "
                "reconstructed result): re-run the sweep locally, or "
                "call triage.minimize(actor, cfg, seed, faults) with "
                "the original inputs")
        if seed is None:
            if not self.failing_seeds:
                raise TriageError("no failing seeds to minimize")
            seed = self.failing_seeds[0]
        rows = np.flatnonzero(np.asarray(self.seeds) == np.uint64(seed))
        if rows.size == 0:
            raise TriageError(f"seed {seed} was not part of this sweep")
        faults = self.triage_ctx.faults
        if faults is not None:
            faults = np.asarray(faults, np.int32)
            if faults.ndim == 3:  # per-world schedules: this seed's rows
                faults = faults[int(rows[0])]
        eng = self.triage_ctx.engine
        return _minimize(eng.actor, eng.cfg, int(seed), faults,
                         engine=eng, mesh=self.triage_ctx.mesh, **kw)

    @property
    def metrics(self) -> Optional[Dict[str, Any]]:
        """Simulation metrics frames (docs/observability.md), or ``None``
        when the sweep ran metrics-off: ``{"per_seed": {field: (n, ...)
        array}, "aggregate": {field: int | [int]}}``. Per-seed rows are
        attributed through the same slot→seed machinery as every other
        observation, so they survive recycling/compaction; the aggregate
        is the fleet sum (``bench.py`` records it as ``sim_metrics``)."""
        from ..obs.metrics import aggregate_metrics, metrics_from_observations

        per_seed = metrics_from_observations(self.observations)
        if per_seed is None:
            return None
        return {"per_seed": per_seed, "aggregate": aggregate_metrics(per_seed)}

    def blackbox(self, seed: Optional[int] = None) -> List[Dict[str, Any]]:
        """Decode one seed's flight-recorder ring (obs/blackbox.py) into
        trace-shaped event records — the last K step events of that
        world, oldest first, with the ``invariant`` raise in place.

        ``seed`` defaults to the first failing seed. Raises
        ``ValueError`` on a blackbox-off sweep (run with
        ``EngineConfig(blackbox=K)``) or an unknown seed. Render with
        ``obs.timeline.ring_to_chrome`` or crosscheck against a fresh
        ``trace()`` via ``obs.blackbox.ring_matches_trace`` (the
        ``obs replay --crosscheck`` CLI leg)."""
        from ..obs.blackbox import decode_ring, rings_from_observations

        rings = rings_from_observations(self.observations)
        if rings is None:
            raise ValueError(
                "this sweep ran blackbox-off: enable the flight recorder "
                "with EngineConfig(blackbox=K) (docs/observability.md)")
        if seed is None:
            if not self.failing_seeds:
                raise ValueError("no failing seeds — pass an explicit "
                                 "seed= to decode a passing world's ring")
            seed = self.failing_seeds[0]
        rows = np.flatnonzero(np.asarray(self.seeds) == np.uint64(seed))
        if rows.size == 0:
            raise ValueError(f"seed {seed} was not part of this sweep")
        row = int(rows[0])
        actor = getattr(getattr(self.triage_ctx, "engine", None),
                        "actor", None)
        return decode_ring({k: v[row] for k, v in rings.items()},
                           kind_names=getattr(actor, "kind_names", None))

    def summary(self) -> str:
        """One human paragraph of what the sweep did — seeds, bugs,
        utilization, coverage, top drop causes — so operators read prose
        instead of grepping a dataclass repr (examples/device_sweep.py
        and the repro banner both print it)."""
        n = len(self.seeds)
        n_bug = len(self.failing_seeds)
        parts = [f"swept {n} seed{'s' if n != 1 else ''} on "
                 f"{self.n_devices} device(s) in {self.steps_run} issued "
                 f"steps: {n_bug} failing"]
        if self.n_active_history.size:
            parts.append(f"world utilization "
                         f"{self.world_utilization:.0%} over "
                         f"{self.n_active_history.size} chunks")
        if self.coverage is not None:
            cov = self.coverage
            curve = cov.novelty_curve
            tail = (f" (novelty {int(curve[0])}→{int(curve[-1])} "
                    f"across the run)" if curve.size else "")
            parts.append(f"{cov.distinct_behaviors} distinct behaviors "
                         f"in {cov.n_buckets} buckets{tail}")
        if self.search is not None:
            # Guided hunts summarize their evolution too (obs/lineage.py):
            # corpus fill, insert pressure, generations, top operator.
            s = self.search
            line = (f"guided search: corpus {s.corpus_size}/"
                    f"{s.corpus_capacity}, {s.inserted} inserted over "
                    f"{s.generations} generations")
            if getattr(s, "operator_stats", None):
                from ..obs.lineage import top_operator

                top = top_operator(s.operator_stats)
                if top:
                    line += f", top operator {top}"
            parts.append(line)
        m = self.metrics
        if m is not None:
            agg = m["aggregate"]
            drops = sorted(((k, v) for k, v in agg.items()
                            if k.startswith("drop_") and isinstance(v, int)
                            and v > 0), key=lambda kv: -kv[1])
            if drops:
                parts.append("top drop causes: " + ", ".join(
                    f"{k[5:]}={v}" for k, v in drops[:3]))
        from ..obs.blackbox import ring_depth

        k_ring = ring_depth(self.observations)
        parts.append(f"black box: last {k_ring} events/world recorded"
                     if k_ring is not None else "black box: off")
        return "; ".join(parts) + "."

    def repro_banner(self) -> Optional[str]:
        """The failing-seed reproduction hint (`runtime/mod.rs:192-199`),
        prefixed with the human :meth:`summary` paragraph."""
        if not self.failing_seeds:
            return None
        banner = self.summary() + "\n"
        banner += ("note: run with environment variable "
                  f"MADSIM_TEST_SEED={self.failing_seeds[0]} to reproduce "
                  f"this failure ({len(self.failing_seeds)} failing seeds "
                  "total)")
        if self.faults_sha256 is not None:
            banner += (f"\nnote: fault-schedule sha256: "
                       f"{self.faults_sha256[:16]} (replay must use the "
                       "same schedule)")
        from ..obs.blackbox import ring_depth

        k_ring = ring_depth(self.observations)
        banner += ("\nnote: flight recorder "
                   + (f"K={k_ring} (SweepResult.blackbox(seed) decodes "
                      "the failing world's last events)" if k_ring
                      else "off (enable with EngineConfig(blackbox=K))"))
        return banner


def sweep(actor: Any, cfg: EngineConfig, seeds, faults: Optional[np.ndarray] = None,
          mesh: Optional[Mesh] = None, chunk_steps: int = 512,
          max_steps: int = 1_000_000, stop_on_first_bug: bool = False,
          engine: Optional[DeviceEngine] = None,
          checkpoint_path: Optional[str] = None,
          checkpoint_every_chunks: int = 0,
          resume: bool = False,
          compact: bool = False,
          recycle: bool = False,
          batch_worlds: Optional[int] = None,
          pipeline: bool = True,
          superstep_max: int = 16,
          fused: bool = False,
          observe: Any = None,
          profile_dir: Optional[str] = None,
          profile_window: Tuple[int, int] = (0, 4),
          coverage_buckets: Optional[int] = None,
          search: Optional[Any] = None,
          search_corpus: Optional[Any] = None,
          search_gen0: int = 0,
          search_lin_base: int = 0) -> SweepResult:
    """Run one simulation per seed, sharded over the mesh, to completion.

    The loop is a slot-occupancy model: the device batch is a fixed set of
    world *slots*, each holding a live world, a finished one awaiting
    retirement, or (after retirement) a recycled world for a fresh seed.
    Per chunk the host learns exactly two scalars — "any bug?" and "how
    many slots are active?" — and every occupancy decision (shrink,
    retire, refill) runs as an on-device program keyed off that count.

    ``pipeline`` (default True): dispatch-ahead, superstepped
    orchestration (docs/perf.md "Pipelined orchestration"). Up to
    ``superstep_max`` chunks fold into one jitted dispatch whose early
    exits (all retired, occupancy at the recycle/compact threshold, bug
    under ``stop_on_first_bug``) run on device, and the host issues the
    next superstep BEFORE reading the previous one's scalars, so XLA's
    async dispatch keeps the device queue non-empty while the host
    decides. K adapts to the observed retirement rate: it doubles
    (capped at ``superstep_max``) while supersteps run to plan and
    settles to the chunks a cut-short superstep actually ran — all
    inputs are sim outputs, so the dispatch schedule is deterministic
    per (seeds, config), and K rides as a traced scalar so the schedule
    never recompiles. A superstep dispatched past a stop/threshold point runs
    ZERO chunks (its entry condition is false), so one-dispatch-stale
    occupancy reads never advance, retire, or refill a world the serial
    loop would not have: results — including retirement attribution —
    are bitwise identical to ``pipeline=False`` (the serial per-chunk
    reference loop, tier-1-tested for every actor family). Decisions are
    additionally epoch-guarded: after a refill/shrink, occupancy reads
    from supersteps dispatched before it are ignored (they ran zero
    chunks), so a stale trigger can never re-fire on the slots it just
    refilled.

    ``fused`` (opt-in; docs/perf.md "Whole-hunt residency"): the
    whole-hunt fused program. The occupancy loop itself — compaction,
    retiring-tail harvest into per-seed device buffers, the coverage
    fold, guided generation, refill and the seed cursor — moves inside
    ONE ``lax.while_loop`` dispatch (:func:`_fused_hunt`), so the host
    issues O(1) mega-dispatches per batch instead of one dispatch per
    refill epoch. Mid-hunt host reads stay the sanctioned ``_fetch``
    scalar batch (one per mega-dispatch); the retired observations are
    pulled ONCE at the end. Results are bitwise identical to the
    serial/pipelined loops (ids, observations, ``m_*`` metrics,
    coverage ledger, lineage lanes, SearchReport — tier-1,
    tests/test_fused.py); only ``world_utilization`` may differ, since
    the fused tail skips the dry-cursor shrink (contract surfaces are
    shrink-invariant — the shrink exists to save flops, which the fused
    loop saves by not leaving the device instead). ``fused=True``
    refuses ``checkpoint_path`` and ``compact`` (see the ValueErrors
    below for the reasoning) and subsumes ``pipeline``.

    Preemption survival: with ``checkpoint_path`` set, the (padded) world
    state is written every ``checkpoint_every_chunks`` chunks (and at the
    end); with ``resume=True`` an existing checkpoint is loaded instead of
    re-initializing, and the sweep continues bit-exactly where it stopped —
    resumed trajectories equal an unbroken run's (the state carries every
    RNG cursor and queue). ``max_steps`` counts steps issued by THIS call.
    Under pipelining the snapshot cadence is superstep-granular (K caps at
    ``checkpoint_every_chunks``), and the submitted state is always a
    COMPLETED superstep output the writer can read while later supersteps
    run — donation stays disabled whenever a writer is attached, exactly
    as in the serial loop.

    Donation caveat: without checkpointing, the chunk runner DONATES its
    input state (XLA steps the batch in place — roughly double the W per
    HBM; a donated state is dead after the call). Checkpointing turns
    donation off, because the async writer still reads the submitted
    pre-chunk state while the next chunk runs — so a checkpointed sweep
    keeps the old double-buffered peak. Budget W accordingly when
    enabling ``checkpoint_path``.

    ``compact``: straggler compaction (docs/perf.md "the straggler
    tail"). A chunked batch runs until its SLOWEST world finishes, so
    once most worlds are done the chip mostly advances frozen state.
    When the active count drops below half the batch, the sweep gathers
    the active worlds to the front — a stable active-first ``argsort``
    computed INSIDE a jitted, mesh-resident program, so no per-world
    state (not even ``state.active``) crosses to the host and no reshard
    round trip follows — retires the frozen tail (its observations are
    sliced out ON DEVICE and pulled alone, never the full batch), and
    continues on a power-of-two-smaller batch. Worlds' trajectories are
    position-independent, so results are bitwise identical to the
    uncompacted run (tested). Disabled automatically when checkpointing
    (a shrunken state cannot resume into the full-shape contract).

    ``recycle`` + ``batch_worlds``: world recycling / seed streaming
    (docs/perf.md "world recycling"). Instead of only shrinking, retired
    slots are REFILLED with freshly initialized worlds for the next
    seeds from a host-side cursor: the sweep holds ``batch_worlds``
    slots (rounded to the mesh) and streams the full seed list through
    them, keeping utilization near 100% while any seeds remain; once the
    cursor is dry it falls back to shrink compaction for the tail. Each
    refilled world is bit-identical to an independent run of its seed
    (tested). This is the shape for open-ended hunts —
    ``stop_on_first_bug`` sweeps over huge seed spaces on a bounded
    memory footprint. On an early stop, seeds never admitted report
    zeroed observations (``bug=False``).

    Recycled sweeps CAN checkpoint (the hunt config a long-running fleet
    actually uses): the checkpoint carries, beside the world state, the
    device-resident slot→seed index, the refill cursor, the retired
    observations recorded so far, and (metrics on) the coverage ledger
    — everything a resume needs to re-attribute recycled slots. Resume
    requires the same ``batch_worlds`` (the padded-seed hash already
    pins seeds/faults; the slot width is checked explicitly — a
    shrunk-compacted state cannot resume into the full-shape contract
    and raises ``ValueError``). While a writer is attached the dry-
    cursor shrink fallback stays OFF (the tail runs at the full batch
    width) so every snapshot written is resumable. A resumed recycled
    sweep's per-seed observations, bug flags, and coverage ledger equal
    an unbroken run's exactly; refill *timing* after the resume point
    may differ by one chunk, so occupancy histories are telemetry, not
    part of the contract.

    Occupancy telemetry rides the result: ``SweepResult.n_active_history``
    (per-chunk active counts, with ``n_active_chunks`` recording the
    chunk index each entry was measured at), ``world_utilization``
    (live-world steps / issued slot-steps, mesh padding included), and
    ``loop_stats`` (the dispatch-count / host-stall breakdown of the
    orchestration loop).

    Observatory knobs (docs/observability.md "The sweep observatory"):

    ``observe``: a live telemetry sink — a callable receiving one dict
    per host read of the loop's scalars (per chunk on the serial path,
    per superstep when pipelined), or a file path for a JSONL stream
    (``python -m madsim_tpu.obs watch <file>`` tails/summarizes it).
    Records are built ONLY from values the loop already fetched plus
    host counters — zero extra device syncs (counted-``_fetch`` tested)
    — and cover seeds/s, occupancy, utilization, coverage growth,
    dispatch depth, and ETA.

    ``profile_dir`` + ``profile_window``: wrap a window of the loop's
    dispatches (by dispatch index, ``[start, stop)``) in
    ``jax.profiler`` trace capture, so a device timeline lands in
    ``profile_dir`` next to the virtual-time timelines of
    obs/timeline.py. Purely host-side observation: trajectories and the
    dispatch schedule are unchanged.

    ``coverage_buckets``: bucket count of the behavior-coverage ledger
    (obs/coverage.py; default ``DEFAULT_BUCKETS`` when the engine runs
    ``EngineConfig(metrics=True)``). The ledger folds each retiring
    world's metrics histograms into a device-resident K-bucket sketch —
    psum'd across the mesh inside the chunk/superstep programs, zero
    host pulls mid-loop — and lands on ``SweepResult.coverage`` with the
    per-chunk ``novelty_curve``. Requires metrics; passing an explicit
    value with a metrics-off engine raises ``ValueError``.

    ``search``: a :class:`~madsim_tpu.search.SearchConfig` — coverage-
    guided fault-schedule evolution (docs/search.md, the closed fuzzer
    loop of ROADMAP item 2). Requires ``recycle=True`` (the feedback
    edge IS the refill), ``EngineConfig(metrics=True)`` (novelty hashes
    the MetricsBlock), and a non-empty ``faults`` template (the fault
    vocabulary the operators perturb within). At every refill boundary
    one extra jitted program (search/generate.py, registry
    ``search.generate``) harvests the retiring slots' behavior
    signatures into a device-resident parent corpus and generates
    mutated/crossed-over children, which the refill installs via the
    per-slot device schedule path of ``DeviceEngine.refill`` — zero new
    mid-loop host pulls (corpus telemetry rides the retire pulls the
    loop already pays; tier-1-counted). The whole guided run is a pure
    function of (seeds, config, SearchConfig.seed): bitwise identical
    across re-runs and across ``pipeline=True/False``, and checkpoint→
    resume restores the corpus and per-slot schedules bit-exactly.
    Results gain ``SweepResult.search`` (final corpus + the
    materialized per-seed schedules), and ``triage_ctx.faults`` becomes
    that per-seed array, so ``triage.triage``/``minimize`` work on
    guided finds unchanged.

    ``search_corpus``: a host corpus snapshot
    (:class:`~madsim_tpu.search.corpus.HostCorpus`-shaped: ``sched``
    ``(K, F, 4)``, ``sig``/``score``/``filled`` ``(K,)``) that SEEDS the
    device corpus instead of the template-only ``corpus_init`` — the
    fleet's cross-range corpus exchange (fleet/exchange.py) passes the
    merged previous-epoch corpus here so a leased range continues the
    fleet's search instead of restarting from the template. One
    host→device transfer at sweep start; zero mid-loop syncs added.
    Seeding with the template-initialized corpus is bitwise identical
    to ``search_corpus=None`` (tested). A checkpoint resume overrides
    it (the snapshot's corpus wins — it already embeds the seed).

    ``search_gen0``: starting value of the corpus generation counter
    (default 0). The mutation lanes key children by ``(SearchConfig.
    seed, slot seed id, generation)``, so two sweeps over the same
    corpus at the same generations draw the SAME mutations; the
    exchange offsets each epoch's ranges by a fixed stride
    (fleet/exchange.py ``GEN_STRIDE``) so a seeded epoch explores fresh
    mutation streams instead of redrawing its parents' — deterministic
    per range, chaos-invariant. ``SweepResult.search.generations``
    still reports the generations THIS sweep ran (the offset is
    subtracted).

    ``search_lin_base``: base of the lineage entry-id space
    (obs/lineage.py; default 0). A world at seed position ``i`` whose
    schedule survives into the corpus is recorded under entry id
    ``search_lin_base + i + 1`` — a fleet range passes its ``lo`` so
    entry ids are globally unique across ranges and the merged report
    resolves cross-range ancestry with plain arithmetic. Pure
    accounting: it shifts ids only, never a corpus decision or a child
    byte.
    """
    from ..engine import checkpoint as ckpt

    eng = engine if engine is not None else DeviceEngine(actor, cfg)
    mesh = mesh if mesh is not None else seed_mesh()
    n_dev = mesh.devices.size
    seeds = np.asarray(seeds, np.uint64)
    n = seeds.shape[0]

    if superstep_max < 1:
        raise ValueError("superstep_max must be >= 1")

    if fused and checkpoint_path is not None:
        raise ValueError(
            "fused=True cannot checkpoint: the whole-hunt program "
            "retires and refills worlds inside one device dispatch, so "
            "no host-visible boundary exists mid-hunt where state, "
            "cursor, and retired observations are simultaneously "
            "consistent for a snapshot — run the pipelined path "
            "(fused=False) when checkpoint_path is set")
    if fused and compact:
        raise ValueError(
            "fused=True has no shrink path: compact=True saves flops "
            "by narrowing a mostly-frozen batch, but the fused loop "
            "already avoids the host round trips that made the "
            "straggler tail expensive, and every result surface is "
            "shrink-invariant — drop compact (or run fused=False)")

    # Behavior-coverage ledger (obs/coverage.py): on exactly when the
    # engine carries the MetricsBlock — signatures are hashes of it.
    from ..obs.coverage import (
        DEFAULT_BUCKETS,
        coverage_from_device,
        ledger_zeros,
    )
    cov_on = bool(eng.cfg.metrics)
    if coverage_buckets is not None and not cov_on:
        raise ValueError(
            "coverage_buckets requires EngineConfig(metrics=True): the "
            "behavior ledger hashes the MetricsBlock histograms of "
            "retiring worlds")
    cov_k = int(coverage_buckets) if coverage_buckets else DEFAULT_BUCKETS
    if cov_on and cov_k < 1:
        raise ValueError("coverage_buckets must be >= 1")

    # Guided schedule search (search/, docs/search.md): validated here,
    # wired in at the refill boundaries below.
    search_on = search is not None
    if search_on:
        if not recycle:
            raise ValueError(
                "search= needs recycle=True (and batch_worlds): guided "
                "children stream into recycled refill slots — a "
                "non-recycled sweep has no refill edge to feed")
        if not cov_on:
            raise ValueError(
                "search= requires EngineConfig(metrics=True): the "
                "novelty signal hashes the MetricsBlock histograms of "
                "retiring worlds (obs/coverage.py)")
        if faults is None:
            raise ValueError(
                "search= needs a fault-schedule template (faults=): the "
                "mutation operators perturb within the template's fault "
                "vocabulary — an empty schedule has nothing to evolve")
    if search_corpus is not None and not search_on:
        raise ValueError(
            "search_corpus= seeds the guided-search parent corpus and "
            "needs search=SearchConfig(...) — a plain sweep has no "
            "corpus to seed")
    if search_gen0 and not search_on:
        raise ValueError("search_gen0= offsets the guided mutation "
                         "streams and needs search=SearchConfig(...)")
    if search_gen0 < 0:
        raise ValueError("search_gen0 must be >= 0")
    if search_lin_base and not search_on:
        raise ValueError("search_lin_base= offsets the lineage entry-id "
                         "space and needs search=SearchConfig(...)")
    if search_lin_base < 0:
        raise ValueError("search_lin_base must be >= 0")
    lineage_on = bool(search_on and getattr(search, "lineage", False))

    # Batch width: a multiple of the mesh. Plain sweeps hold every seed at
    # once; recycled sweeps hold batch_worlds slots and stream the rest.
    full_w = n + ((-n) % n_dev)
    if recycle and batch_worlds is not None:
        w0 = min(max(1, int(batch_worlds)), max(n, 1))
        w0 += (-w0) % n_dev
        w0 = min(w0, full_w)
    else:
        w0 = full_w
    # Pad the seed-id space to the batch width (padded worlds are real
    # simulations of dummy seeds; their results are sliced off below).
    n_ids = max(n, w0)
    seeds_p = (np.concatenate([seeds, seeds[:1].repeat(n_ids - n)])
               if n_ids > n else seeds)

    faults_p = faults
    per_world_faults = False
    if faults is not None:
        faults_p = np.asarray(faults, np.int32)
        if faults_p.ndim == 2:
            if faults_p.shape[-1] != 4:
                raise ValueError(
                    f"shared fault schedule must be (F, 4) rows of "
                    f"[time_us, op, a, b]; got shape {faults_p.shape}")
        elif faults_p.ndim == 3:
            # Validate the leading dim EXPLICITLY against len(seeds):
            # without this, a mismatched (m, F, 4) would silently gather
            # via ``faults_p[ids]`` below — wrong-world schedules (m > n)
            # or an IndexError deep in a refill (m < n) instead of a
            # boundary error naming both dims.
            if faults_p.shape[-1] != 4:
                raise ValueError(
                    f"per-world fault schedules must be (n_seeds, F, 4) "
                    f"rows of [time_us, op, a, b]; got shape "
                    f"{faults_p.shape}")
            if faults_p.shape[0] != n:
                raise ValueError(
                    f"per-world fault schedules carry one (F, 4) block "
                    f"per seed: got leading dim {faults_p.shape[0]} but "
                    f"len(seeds)={n}")
            per_world_faults = True
            if n_ids > n:
                faults_p = np.concatenate(
                    [faults_p, faults_p[:1].repeat(n_ids - n, axis=0)],
                    axis=0)
        else:
            raise ValueError(
                f"faults must be (F, 4) or (n_seeds, F, 4); got "
                f"{faults_p.ndim}-D shape {faults_p.shape}")

    def batch_faults(ids: np.ndarray):
        """Fault rows for the worlds holding the given seed ids."""
        if faults_p is None:
            return None
        return faults_p[ids] if per_world_faults else faults_p

    import hashlib
    import os
    from time import perf_counter

    def _clk() -> float:
        # Wall-clock telemetry of the orchestration loop itself (host
        # side); never feeds a simulation decision.
        return perf_counter()  # detlint: allow[DET001]

    # World identity travels with the checkpoint: resuming under different
    # seeds OR fault schedules would silently attribute results (repro
    # banners!) to inputs that never produced them.
    faults_key = (np.ascontiguousarray(faults_p).tobytes()
                  if faults_p is not None else b"none")
    seeds_meta = {
        "seeds_sha256": hashlib.sha256(seeds_p.tobytes()).hexdigest(),
        "faults_sha256": hashlib.sha256(faults_key).hexdigest(),
    }

    resumed = False
    resume_aux: Dict[str, np.ndarray] = {}
    if resume and checkpoint_path and os.path.exists(checkpoint_path):
        state, resume_aux = ckpt.load(eng, checkpoint_path,
                                      expect_extra=seeds_meta, with_aux=True)
        w_file = int(np.asarray(state.now).shape[0])
        if recycle:
            # Recycled checkpoints carry the sweep-level aux (cursor,
            # slot→seed index, retired observations) — without it the
            # file is a plain full-batch snapshot this mode cannot
            # re-attribute.
            if "cursor" not in resume_aux:
                raise ckpt.CheckpointError(
                    f"checkpoint {checkpoint_path!r} was written by a "
                    "non-recycled sweep (no slot->seed aux): resume it "
                    "with recycle=False, or delete it to start the "
                    "recycled hunt fresh")
            if w_file != w0:
                raise ValueError(
                    f"cannot resume recycled sweep: checkpoint holds "
                    f"{w_file} world slots but batch_worlds implies {w0} "
                    "— a shrunk-compacted or differently-batched state "
                    "cannot resume into the full-shape contract; rerun "
                    "with the original batch_worlds")
        elif "cursor" in resume_aux:
            raise ckpt.CheckpointError(
                f"checkpoint {checkpoint_path!r} was written by a "
                "recycled sweep: pass recycle=True (and the original "
                "batch_worlds) to resume it")
        elif w_file != seeds_p.shape[0]:
            raise ckpt.CheckpointError(
                f"checkpoint holds {w_file} worlds, "
                f"sweep expects {seeds_p.shape[0]} (seeds + mesh padding)")
        state = shard_worlds(state, mesh)
        resumed = True
    else:
        state = shard_worlds(
            eng.init(seeds_p[:w0], faults=batch_faults(np.arange(w0))), mesh)

    writer = (_AsyncCheckpointer(eng, checkpoint_path, seeds_meta)
              if checkpoint_path else None)
    # Donate the chunk state unless a checkpoint writer holds references
    # to it between chunks (the writer reads the submitted pytree from a
    # background thread; donating would hand XLA its buffers mid-read).
    donate = writer is None
    compact = compact and writer is None  # shrunken state cannot resume
    steps = 0
    chunks = 0                         # executed chunk bodies
    c_max = -(-max_steps // chunk_steps)  # serial loop's chunk budget
    # Chunk counter at the last writer submission — a counter, not an
    # object ref: a pytree ref here would pin a full extra device state
    # between checkpoints. Compact stays disabled under a writer; a
    # recycled refill CAN change state without running a chunk, but every
    # snapshot is self-consistent (state+idx+cursor+retired captured
    # together), and a post-submit refill with no subsequent chunk simply
    # re-derives deterministically on resume — so chunk-count identity
    # remains a sound skip condition for the final submit.
    submitted_chunks = -1
    w_cur = w0                         # current batch width (slot count)
    cursor = w0                        # next seed id the stream admits
    # Slot→seed-id map, DEVICE-resident: compaction permutes it with the
    # state in the same on-device program, so the host never needs the
    # permutation (or state.active) to keep attribution straight. -1
    # marks a dead slot (retired world still riding in the batch).
    idx = shard_worlds(jnp.arange(w_cur, dtype=jnp.int32), mesh)
    reordered = False                  # batch rows still == seed order?
    retired: Dict[str, list] = {}      # field → retired observation batches
    retired_rows: List[np.ndarray] = []
    # -- guided-search state (search/, docs/search.md) --------------------
    # slot_sched: the (W, F, 4) schedule each slot is CURRENTLY running,
    # device-resident and permuted/refilled in lockstep with the state —
    # the attribution that makes generated children replayable. corpus:
    # the mesh-replicated parent pool (search/corpus.py).
    slot_sched = corpus = None
    retired_sched: List[np.ndarray] = []
    # -- lineage lanes + operator outcome table (obs/lineage.py) ----------
    # slot_lin: per-slot provenance (parent entry ids, applied-operator
    # bitmask, ancestry depth), permuted/split/refilled in lockstep with
    # slot_sched; op_tab: the per-operator produced/novel/survived/bug
    # counters, accumulated inside the searcher program.
    slot_lin = op_tab = None
    retired_lin: List[tuple] = []
    search_host = {"corpus_size": 1, "inserted": 0, "gen": 0,
                   "refill_novel": 0, "refill_inserted": 0}
    if search_on:
        from ..search.corpus import CorpusState, corpus_init
        from ..search.generate import searcher as _searcher
        from ..triage.shrink import normalize as _normalize_sched

        f_rows = int(faults_p.shape[-2])
        base0 = (faults_p[:w0] if per_world_faults
                 else np.broadcast_to(faults_p, (w0,) + faults_p.shape))
        slot_sched = shard_worlds(
            jnp.asarray(np.ascontiguousarray(base0), jnp.int32), mesh)
        if lineage_on:
            from ..obs.lineage import lanes_origin, table_zeros

            # The initial batch runs the template itself: generation-0
            # lanes (no parents, no operators, depth 0).
            slot_lin = shard_worlds(lanes_origin(w0), mesh)
            op_tab = jax.device_put(table_zeros(),
                                    NamedSharding(mesh, scalar_spec()))
        if search_corpus is not None:
            # Exchange seeding (fleet/exchange.py): start from a merged
            # host corpus instead of the template-only init. The per-
            # sweep gen/inserted counters still start at zero — they
            # count THIS sweep's refills/inserts.
            sc_sched = np.asarray(search_corpus.sched, np.int32)
            k = int(search.corpus)
            if sc_sched.shape != (k, f_rows, 4):
                raise ValueError(
                    f"search_corpus.sched must be (K, F, 4) = "
                    f"({k}, {f_rows}, 4) for SearchConfig.corpus={k} and "
                    f"the {f_rows}-row template; got {sc_sched.shape}")
            for name in ("sig", "score", "filled", "entry", "depth"):
                shp = np.asarray(getattr(search_corpus, name)).shape
                if shp != (k,):
                    raise ValueError(
                        f"search_corpus.{name} must be ({k},) for "
                        f"SearchConfig.corpus={k}; got {shp}")
            # gen starts at the epoch stream offset (fleet/exchange.py):
            # generation is the third key of the mutation lanes, so the
            # shift moves this sweep onto a fresh splitmix64 stream
            # family instead of redrawing the seed corpus's parents'.
            corpus = jax.device_put(CorpusState(
                sched=jnp.asarray(sc_sched),
                sig=jnp.asarray(np.asarray(search_corpus.sig, np.uint32)),
                score=jnp.asarray(np.asarray(search_corpus.score,
                                             np.int32)),
                filled=jnp.asarray(np.asarray(search_corpus.filled, bool)),
                gen=jnp.int32(search_gen0), inserted=jnp.int32(0),
                entry=jnp.asarray(np.asarray(search_corpus.entry,
                                             np.int32)),
                depth=jnp.asarray(np.asarray(search_corpus.depth,
                                             np.int32)),
            ), NamedSharding(mesh, scalar_spec()))
        else:
            # Corpus seeded with the (normalized) template: parents
            # always exist, so generation-1 children mutate the original
            # schedule.
            template = _normalize_sched(
                faults_p[0] if per_world_faults else faults_p)
            c0 = corpus_init(int(search.corpus), template)
            if search_gen0:
                c0 = c0._replace(gen=jnp.int32(search_gen0))
            corpus = jax.device_put(
                c0, NamedSharding(mesh, scalar_spec()))
    if resumed and recycle:
        # Rehydrate the sweep-level bookkeeping the checkpoint carried:
        # the slot→seed index (device-resident again), the refill
        # cursor, and the observations of every world retired before the
        # snapshot. With these restored, the continuation re-attributes
        # recycled slots exactly as the unbroken run would have.
        cursor = int(np.asarray(resume_aux["cursor"]))
        idx = shard_worlds(
            jnp.asarray(np.asarray(resume_aux["idx"], np.int32)), mesh)
        reordered = True
        if "ret_rows" in resume_aux:
            retired_rows.append(np.asarray(resume_aux["ret_rows"]))
            for key in resume_aux:
                if key.startswith("ret_") and key != "ret_rows":
                    retired[key[4:]] = [np.asarray(resume_aux[key])]
        if search_on != ("srch_sched" in resume_aux):
            raise ckpt.CheckpointError(
                f"checkpoint {checkpoint_path!r} was written by a "
                f"{'guided' if 'srch_sched' in resume_aux else 'plain'} "
                f"sweep but this resume is "
                f"{'guided (search=...)' if search_on else 'plain'}: "
                "the per-slot schedules and search corpus cannot be "
                "reconciled — resume with the original search setting")
        if search_on:
            # Restore the search state bit-exactly: the per-slot
            # schedules, the parent corpus (incl. its generation and
            # insert counters), and the retired-schedule attribution.
            from ..search.corpus import CorpusState

            if lineage_on != ("srch_lin_p1" in resume_aux):
                raise ckpt.CheckpointError(
                    f"checkpoint {checkpoint_path!r} was written with "
                    f"lineage "
                    f"{'on' if 'srch_lin_p1' in resume_aux else 'off'} "
                    f"but this resume runs SearchConfig(lineage="
                    f"{lineage_on}): the provenance lanes cannot be "
                    "reconciled — resume with the original lineage "
                    "setting")
            slot_sched = shard_worlds(jnp.asarray(
                np.asarray(resume_aux["srch_sched"], np.int32)), mesh)
            corpus = jax.device_put(CorpusState(
                sched=jnp.asarray(np.asarray(resume_aux["srch_c_sched"],
                                             np.int32)),
                sig=jnp.asarray(np.asarray(resume_aux["srch_c_sig"],
                                           np.uint32)),
                score=jnp.asarray(np.asarray(resume_aux["srch_c_score"],
                                             np.int32)),
                filled=jnp.asarray(np.asarray(resume_aux["srch_c_filled"],
                                              bool)),
                gen=jnp.asarray(np.asarray(resume_aux["srch_c_gen"],
                                           np.int32).reshape(())),
                inserted=jnp.asarray(np.asarray(
                    resume_aux["srch_c_inserted"], np.int32).reshape(())),
                entry=jnp.asarray(np.asarray(resume_aux["srch_c_entry"],
                                             np.int32)),
                depth=jnp.asarray(np.asarray(resume_aux["srch_c_depth"],
                                             np.int32)),
            ), NamedSharding(mesh, scalar_spec()))
            if "srch_ret" in resume_aux:
                retired_sched.append(
                    np.asarray(resume_aux["srch_ret"], np.int32))
            if lineage_on:
                # Lineage lanes + operator table ride the same aux
                # channel — a resumed hunt's ancestry and outcome
                # accounting equal an unbroken run's bit for bit.
                from ..obs.lineage import LineageLanes, OperatorTable

                slot_lin = shard_worlds(LineageLanes(
                    p1=jnp.asarray(np.asarray(resume_aux["srch_lin_p1"],
                                              np.int32)),
                    p2=jnp.asarray(np.asarray(resume_aux["srch_lin_p2"],
                                              np.int32)),
                    ops=jnp.asarray(np.asarray(resume_aux["srch_lin_ops"],
                                               np.int8)),
                    depth=jnp.asarray(np.asarray(
                        resume_aux["srch_lin_depth"], np.int32)),
                ), mesh)
                op_tab = jax.device_put(OperatorTable(
                    produced=jnp.asarray(np.asarray(
                        resume_aux["srch_op_produced"], np.int32)),
                    novel=jnp.asarray(np.asarray(
                        resume_aux["srch_op_novel"], np.int32)),
                    survived=jnp.asarray(np.asarray(
                        resume_aux["srch_op_survived"], np.int32)),
                ), NamedSharding(mesh, scalar_spec()))
                if "srch_ret_lin_p1" in resume_aux:
                    retired_lin.append(tuple(
                        np.asarray(resume_aux[f"srch_ret_lin_{k}"])
                        for k in ("p1", "p2", "ops", "depth")))
    n_active_hist: List[int] = []
    n_active_chunk: List[int] = []     # chunk index each entry measured at
    issued_slot_steps = 0              # sum over chunks of width*chunk_steps
    live_world_steps = 0               # steps that advanced a live world
    perf = {"device_wait_s": 0.0, "host_decision_s": 0.0, "dispatch_s": 0.0,
            "retire_wait_s": 0.0, "scalar_fetches": 0, "retire_fetches": 0,
            "dispatches": 0, "dispatch_depth": 0}
    t_loop0 = _clk()

    # -- observatory hooks (docs/observability.md) ------------------------
    # Telemetry emitter + profiler window are host-side observation only:
    # every record is built from scalars the loop already fetched, so the
    # sync discipline (one _fetch per superstep) is unchanged.
    from ..obs import observatory as _obsy

    emit_telemetry, close_telemetry = _obsy.make_observer(observe)
    prof = _obsy.ProfilerWindow(profile_dir, profile_window)
    novelty_hist: List[int] = []       # cumulative distinct, per chunk
    cov_hits = cov_first = n_real_dev = None
    if cov_on:
        cov_hits, cov_first = jax.device_put(
            ledger_zeros(cov_k), NamedSharding(mesh, scalar_spec()))
        n_real_dev = jnp.int32(n)
        if resumed and "cov_hits" in resume_aux:
            # Recycled checkpoints persist the ledger itself (retired-
            # and-refilled slots no longer carry their histograms, so a
            # pre-pass could not rebuild it): restore and continue.
            # Folds trigger on active FALLING within a chunk, so worlds
            # already inactive in the snapshot never re-fold.
            cov_hits, cov_first = jax.device_put(
                (jnp.asarray(np.asarray(resume_aux["cov_hits"], np.int32)),
                 jnp.asarray(np.asarray(resume_aux["cov_first"], np.int32))),
                NamedSharding(mesh, scalar_spec()))
        elif resumed:
            # Resume pre-pass: worlds that retired before the checkpoint
            # carry frozen histograms but will never transition
            # active→inactive in THIS call — fold them up front. The
            # ledger is fold-order invariant (counts + minima), so the
            # final hits/first_seen equal an unbroken run's bit for bit.
            cov_hits, cov_first = _cov_endfolder(eng, mesh)(
                state, cov_hits, cov_first, idx, n_real_dev,
                jnp.asarray(False))

    def emit_point(n_act: int, bug_seen: bool, depth: int) -> None:
        """One live-telemetry record per host read of the loop scalars
        (host data only — never a device pull)."""
        if emit_telemetry is None:
            return
        elapsed = _clk() - t_loop0
        done = int(min(max(cursor - n_act, 0), n))
        rate = done / elapsed if elapsed > 0 else 0.0
        remaining = n - done
        rec = {
            "schema": "madsim.sweep.telemetry/1",
            "elapsed_s": round(elapsed, 6),
            "chunks": int(chunks),
            "steps": int(steps),
            "batch_worlds": int(w_cur),
            "n_active": int(n_act),
            "occupancy": round(n_act / w_cur, 4) if w_cur else 0.0,
            "seeds_total": int(n),
            "seeds_admitted": int(min(cursor, n)),
            "seeds_done": done,
            "seeds_per_s": round(rate, 2),
            # Running lower bound: retired-tail attribution lands at the
            # next retirement pull, so mid-loop utilization trails the
            # final SweepResult.world_utilization slightly.
            "world_utilization": (round(
                live_world_steps / issued_slot_steps, 4)
                if issued_slot_steps else 0.0),
            "dispatch_depth": int(depth),
            "bug_seen": bool(bug_seen),
            "eta_s": (round(remaining / rate, 3) if rate > 0
                      and remaining > 0 else
                      (0.0 if remaining == 0 else None)),
        }
        if cov_on:
            rec["coverage_distinct"] = (int(novelty_hist[-1])
                                        if novelty_hist else 0)
            rec["coverage_buckets"] = cov_k
        if search_on:
            # Host mirrors of the corpus scalars, refreshed by the
            # retire pulls (never an extra device sync).
            rec["search_corpus"] = search_host["corpus_size"]
            rec["search_inserted"] = search_host["inserted"]
        emit_telemetry(rec)

    def retire(obs_slice: Dict[str, np.ndarray], rows: np.ndarray,
               sched_slice: Optional[np.ndarray] = None,
               lin_slice: Optional[tuple] = None) -> None:
        """Record final observations for rows leaving the batch (dead
        slots — already retired earlier — are filtered out by idx).
        ``sched_slice`` (guided sweeps) carries the retiring rows'
        materialized fault schedules; ``lin_slice`` (lineage on) their
        provenance lanes — both filtered identically."""
        nonlocal live_world_steps
        keep = rows >= 0
        if not keep.all():
            rows = rows[keep]
            obs_slice = {k: np.asarray(v)[keep] for k, v in obs_slice.items()}
            if sched_slice is not None:
                sched_slice = np.asarray(sched_slice)[keep]
            if lin_slice is not None:
                lin_slice = tuple(np.asarray(a)[keep] for a in lin_slice)
        if rows.size == 0:
            return
        live_world_steps += int(np.asarray(obs_slice["steps"]).sum())
        retired_rows.append(rows)
        for k, v in obs_slice.items():
            retired.setdefault(k, []).append(np.asarray(v))
        if sched_slice is not None:
            retired_sched.append(np.asarray(sched_slice, np.int32))
        if lin_slice is not None:
            retired_lin.append(tuple(np.asarray(a) for a in lin_slice))

    def emit_search_point(op_h) -> None:
        """One ``madsim.search.telemetry/1`` record per guided refill —
        built ONLY from the values the retire pull already fetched
        (zero extra device syncs, like every other telemetry record).
        ``op_h`` is the pulled OperatorTable (or None, lineage off)."""
        if emit_telemetry is None or not search_on:
            return
        from ..obs.lineage import OP_NAMES
        from ..obs.lineage import (
            SEARCH_TELEMETRY_SCHEMA as _SEARCH_SCHEMA,
        )

        rec = {
            "schema": _SEARCH_SCHEMA,
            "event": "refill",
            "elapsed_s": round(_clk() - t_loop0, 6),
            "generation": search_host["gen"],
            "corpus_size": search_host["corpus_size"],
            "corpus_inserted": search_host["inserted"],
            "refill_novel": search_host["refill_novel"],
            "refill_inserted": search_host["refill_inserted"],
        }
        if "epochs_on_device" in search_host:
            # Fused hunt: refills run ON DEVICE, so this record is the
            # per-MEGA-DISPATCH rollup of the last device refill, not a
            # per-refill sample. The label lets `obs watch` render the
            # collapsed cadence explicitly (docs/observability.md).
            rec["epochs_on_device"] = search_host["epochs_on_device"]
        if op_h is not None:
            for row, vals in zip(("produced", "novel", "survived"), op_h):
                arr = np.asarray(vals)
                for i, name in enumerate(OP_NAMES):
                    rec[f"op_{row}_{name}"] = int(arr[i])
        emit_telemetry(rec)

    def fetch_retire(handles) -> None:
        """Materialize a deferred on-device retirement slice and record
        it. The pull covers ONLY the (bucketed) frozen-tail rows — the
        full per-world observation arrays never cross to the host. On a
        guided sweep the same single ``_fetch`` additionally carries the
        tail's schedule rows, its lineage lanes, the corpus telemetry
        scalars, and the operator outcome table — the "corpus syncs
        ride the existing cadence" half of the zero-new-syncs contract
        (tests/test_search.py counts this)."""
        obs_t, idx_t, tail_len, sched_t, stats_t, lin_t, op_t = handles
        t0 = _clk()
        obs_h, idx_h, sched_h, stats_h, lin_h, op_h = _fetch(
            (obs_t, idx_t, sched_t, stats_t, lin_t, op_t))
        perf["retire_wait_s"] += _clk() - t0
        perf["retire_fetches"] += 1
        if stats_h is not None:
            search_host["corpus_size"] = int(stats_h[0])
            search_host["inserted"] = int(stats_h[1])
            if len(stats_h) > 2:           # lineage-on stats vector
                search_host["gen"] = int(stats_h[2])
                search_host["refill_novel"] = int(stats_h[3])
                search_host["refill_inserted"] = int(stats_h[4])
            emit_search_point(op_h)
        retire({k: np.asarray(v)[:tail_len] for k, v in obs_h.items()},
               np.asarray(idx_h)[:tail_len],
               (np.asarray(sched_h)[:tail_len]
                if sched_h is not None else None),
               (tuple(np.asarray(a)[:tail_len] for a in lin_h)
                if lin_h is not None else None))

    def do_refill(n_act: int):
        """World recycling: stable active-first partition on device,
        retire the frozen tail, refill it with the next seeds from the
        cursor. Only the n_active scalar (already on host) shapes the
        refill mask; the tail observations are sliced on device and
        returned as un-fetched handles so the pull can overlap later
        dispatches.

        Guided sweeps (``search=``) widen this boundary, still with zero
        host pulls: the per-slot schedule array compacts alongside the
        state, the retiring tail's schedules join the deferred handles,
        and ONE extra jitted dispatch (search/generate.py) harvests the
        tail into the corpus and generates the children the refill
        installs through ``DeviceEngine.refill``'s device-schedule
        path."""
        nonlocal state, idx, cursor, reordered, slot_sched, corpus, \
            slot_lin, op_tab
        if search_on and lineage_on:
            # The lineage lanes permute/split with the state in the SAME
            # compaction dispatch (the varargs sched group), so
            # provenance attribution travels with the worlds for free.
            (state, idx, slot_sched, l_p1, l_p2, l_ops, l_dep) = \
                _compactor(eng, mesh, w_cur, w_cur, with_sched=True)(
                    state, idx, slot_sched, *slot_lin)
            slot_lin = type(slot_lin)(l_p1, l_p2, l_ops, l_dep)
        elif search_on:
            state, idx, slot_sched = _compactor(
                eng, mesh, w_cur, w_cur, with_sched=True)(
                    state, idx, slot_sched)
        else:
            state, idx = _compactor(eng, mesh, w_cur, w_cur)(state, idx)
        reordered = True
        tail_len = w_cur - n_act
        rows = min(_pow2_at_least(tail_len), _pow2_at_least(w_cur))
        obs_t, idx_t = _tail_observer(eng, mesh, w_cur, rows)(
            state, idx, jnp.int32(n_act))
        take = min(tail_len, n_ids - cursor)
        repl = np.full(w_cur, -1, np.int32)
        repl[n_act:n_act + take] = np.arange(
            cursor, cursor + take, dtype=np.int32)
        cursor += take
        mask = np.zeros(w_cur, bool)
        mask[n_act:n_act + take] = True
        fill_ids = np.maximum(repl, 0)
        sched_t = stats_t = lin_t = op_t = None
        if search_on:
            new_ids = shard_worlds(
                jnp.asarray(fill_ids.astype(np.int32)), mesh)
            if lineage_on:
                # One tail gather covers the schedules AND the lanes
                # (same bucketed program, a wider pytree); it reads the
                # PRE-refill lanes — the retiring worlds' provenance —
                # before the children overwrite them below.
                sched_t, lt1, lt2, lto, ltd = _sched_tail(
                    eng, mesh, w_cur, rows)(
                        (slot_sched,) + tuple(slot_lin), jnp.int32(n_act))
                lin_t = (lt1, lt2, lto, ltd)
                fill_dev = shard_worlds(jnp.asarray(mask), mesh)
                children, child_lin, corpus, op_tab, stats_t = _searcher(
                    eng, mesh, search, w_cur, f_rows)(
                        state, slot_sched, idx, corpus, jnp.int32(n_act),
                        new_ids, fill_dev, slot_lin, op_tab,
                        jnp.int32(search_lin_base))
                op_t = op_tab
                slot_lin = type(slot_lin)(*(
                    jnp.where(jnp.asarray(mask), c, s)
                    for c, s in zip(child_lin, slot_lin)))
            else:
                sched_t = _sched_tail(eng, mesh, w_cur, rows)(
                    slot_sched, jnp.int32(n_act))
                children, corpus, stats_t = _searcher(
                    eng, mesh, search, w_cur, f_rows)(
                        state, slot_sched, idx, corpus, jnp.int32(n_act),
                        new_ids)
            state = shard_worlds(
                eng.refill(state, mask, seeds_p[fill_ids],
                           faults=children), mesh)
            slot_sched = jnp.where(
                jnp.asarray(mask)[:, None, None], children, slot_sched)
        else:
            state = shard_worlds(
                eng.refill(state, mask, seeds_p[fill_ids],
                           faults=batch_faults(fill_ids)), mesh)
        idx = jnp.where(jnp.asarray(np.arange(w_cur) >= n_act),
                        jnp.asarray(repl), idx)
        return obs_t, idx_t, tail_len, sched_t, stats_t, lin_t, op_t

    def do_shrink(new_w: int):
        """Shrink compaction, fully on device: permutation, split, and
        the live batch's mesh placement all happen inside one jitted
        program (out_shardings = the world sharding). Returns the frozen
        tail's observation handles, un-fetched. Guided sweeps split the
        per-slot schedule array with the state so the frozen tail keeps
        its schedule attribution."""
        nonlocal state, idx, reordered, w_cur, slot_sched, slot_lin
        flin = None
        if search_on and lineage_on:
            ((state, idx, slot_sched, l1, l2, lo_, ld),
             (frozen, fidx, fsched, f1, f2, fo, fd)) = \
                _compactor(eng, mesh, w_cur, new_w, with_sched=True)(
                    state, idx, slot_sched, *slot_lin)
            slot_lin = type(slot_lin)(l1, l2, lo_, ld)
            flin = (f1, f2, fo, fd)
        elif search_on:
            (state, idx, slot_sched), (frozen, fidx, fsched) = \
                _compactor(eng, mesh, w_cur, new_w, with_sched=True)(
                    state, idx, slot_sched)
        else:
            fsched = None
            (state, idx), (frozen, fidx) = \
                _compactor(eng, mesh, w_cur, new_w)(state, idx)
        reordered = True
        tail_len = w_cur - new_w
        w_cur = new_w
        obs_t, idx_t = _observer(eng)(frozen, fidx)
        return obs_t, idx_t, tail_len, fsched, None, flin, None

    def ckpt_aux(cov_pair):
        """Sweep-level aux for a recycled checkpoint, captured at submit
        time — the one point where host cursor/idx/retired are
        consistent with the submitted state (pending retires drained;
        pipelined submits additionally gated on epoch match). Device
        values (idx, ledger) ride as refs the writer thread pulls;
        retired observations as lists it concatenates — the loop thread
        never blocks here."""
        if not recycle:
            return None
        aux: Dict[str, Any] = {"cursor": np.int64(cursor), "idx": idx}
        if cov_pair is not None:
            aux["cov_hits"], aux["cov_first"] = cov_pair
        if retired_rows:
            aux["ret_rows"] = list(retired_rows)
            for k, v in retired.items():
                aux[f"ret_{k}"] = list(v)
        if search_on:
            # Search state rides the same aux channel: per-slot
            # schedules + the whole corpus (device refs the writer
            # thread pulls; consistent with the submitted state because
            # submits are epoch-gated and search state only changes at
            # epoch bumps), plus the retired-schedule attribution.
            aux["srch_sched"] = slot_sched
            aux["srch_c_sched"] = corpus.sched
            aux["srch_c_sig"] = corpus.sig
            aux["srch_c_score"] = corpus.score
            aux["srch_c_filled"] = corpus.filled
            aux["srch_c_gen"] = corpus.gen
            aux["srch_c_inserted"] = corpus.inserted
            aux["srch_c_entry"] = corpus.entry
            aux["srch_c_depth"] = corpus.depth
            if retired_sched:
                aux["srch_ret"] = list(retired_sched)
            if lineage_on:
                # Provenance lanes + outcome table (obs/lineage.py):
                # same epoch-gated consistency argument as slot_sched.
                for k, v in zip(("p1", "p2", "ops", "depth"), slot_lin):
                    aux[f"srch_lin_{k}"] = v
                for k, v in zip(("produced", "novel", "survived"),
                                op_tab):
                    aux[f"srch_op_{k}"] = v
                if retired_lin:
                    for i, k in enumerate(("p1", "p2", "ops", "depth")):
                        aux[f"srch_ret_lin_{k}"] = [t[i]
                                                    for t in retired_lin]
        return aux

    fused_epochs = 0                   # device refill epochs (fused path)
    fused_k_bucket = 0                 # chunk window per mega-dispatch
    fused_bufs = fused_sched_buf = fused_lin_buf = None
    try:
        if fused:
            # -- whole-hunt fused orchestration (docs/perf.md
            # "Whole-hunt residency"): the occupancy loop lives inside
            # ONE device program; the host's job shrinks to issuing
            # mega-dispatches and mirroring telemetry scalars. ---------
            from ..obs.lineage import lanes_buffer

            rep_sh = NamedSharding(mesh, scalar_spec())
            n_ids_b = _pow2_at_least(n_ids)
            fused_k_bucket = _pow2_at_least(max(min(c_max, _FUSED_K_CAP),
                                                1))
            # Replicated seed/fault tables the in-loop refill gathers
            # from, bucketed to a power of two: every seed count in a
            # bucket reuses ONE compiled program (the PR 3 zero-
            # recompile contract extended to fused). Rows past n_ids
            # are never gathered (the traced cursor clamps at the real
            # count), so zero/repeat padding is inert.
            lo = (seeds_p & np.uint64(0xFFFFFFFF)).astype(np.uint32)
            hi = (seeds_p >> np.uint64(32)).astype(np.uint32)
            if n_ids_b > n_ids:
                pad = n_ids_b - n_ids
                lo = np.concatenate([lo, np.zeros(pad, np.uint32)])
                hi = np.concatenate([hi, np.zeros(pad, np.uint32)])
            tabs = {"lo": jnp.asarray(lo), "hi": jnp.asarray(hi)}
            if search_on:
                fault_mode = "search"
            elif faults_p is None:
                fault_mode = "none"
            elif per_world_faults:
                fault_mode = "per_world"
                ftab = faults_p
                if n_ids_b > n_ids:
                    ftab = np.concatenate(
                        [ftab, ftab[:1].repeat(n_ids_b - n_ids, axis=0)],
                        axis=0)
                tabs["faults"] = jnp.asarray(ftab, jnp.int32)
            else:
                fault_mode = "shared"
                tabs["faults"] = jnp.asarray(faults_p, jnp.int32)
            tabs = jax.device_put(tabs, rep_sh)
            # Per-seed observation buffers (+ one dump row for masked
            # scatters): retiring rows land at retire time INSIDE the
            # loop, live rows at each mega-dispatch boundary, and the
            # host pulls the whole thing ONCE at the end. eval_shape
            # keeps buffer setup compile-free.
            obs_shapes = jax.eval_shape(eng.observe_device, state)
            fused_bufs = jax.device_put(
                {k: jnp.zeros((n_ids_b + 1,) + tuple(sh.shape[1:]),
                              sh.dtype)
                 for k, sh in obs_shapes.items()}, rep_sh)
            if search_on:
                sb = np.full((n_ids_b + 1, f_rows, 4), -1, np.int32)
                sb[:, :, 1:] = 0       # canonical disabled-row padding
                fused_sched_buf = jax.device_put(jnp.asarray(sb), rep_sh)
            if lineage_on:
                fused_lin_buf = jax.device_put(
                    lanes_buffer(n_ids_b), rep_sh)
            cursor_dev = jax.device_put(jnp.int32(cursor), rep_sh)
            epochs_dev = jax.device_put(jnp.int32(0), rep_sh)
            runner = _fused_hunt(
                eng, mesh, search, w=w_cur, n_ids_b=n_ids_b,
                f_rows=(f_rows if search_on else 0),
                chunk_steps=chunk_steps, k_bucket=fused_k_bucket,
                cov_k=(cov_k if cov_on else None),
                lineage_on=lineage_on, fault_mode=fault_mode,
                recycle=recycle)
            stop = False
            first = True
            # "first" forces one dispatch even when max_steps <= 0: a
            # zero-chunk pass still parks the live (init) observations
            # in the buffers, mirroring the serial loop's final
            # observe() of an unstepped batch.
            while first or (chunks < c_max and not stop):
                first = False
                k = max(0, min(fused_k_bucket, c_max - chunks))
                t0 = _clk()
                prof.before_dispatch()
                srch_in = ()
                if search_on:
                    srch_in = (slot_sched, corpus, fused_sched_buf)
                    if lineage_on:
                        srch_in += (slot_lin, op_tab, fused_lin_buf)
                with prof.annotate("madsim:fused_hunt"):
                    (state, idx, cursor_dev, epochs_dev, fused_bufs,
                     cov_pair, srch_out, any_bug, n_active, k_done,
                     hist, cov_h, stats_t) = runner(
                        state, idx, cursor_dev, epochs_dev, fused_bufs,
                        ((cov_hits, cov_first) if cov_on else ()),
                        srch_in, tabs, jnp.int32(n_ids), jnp.int32(n),
                        jnp.int32(search_lin_base),
                        jnp.asarray(bool(stop_on_first_bug)),
                        jnp.int32(k))
                perf["dispatch_s"] += _clk() - t0
                perf["dispatches"] += 1
                if cov_on:
                    cov_hits, cov_first = cov_pair
                if search_on:
                    slot_sched, corpus, fused_sched_buf = srch_out[:3]
                    if lineage_on:
                        slot_lin, op_tab, fused_lin_buf = srch_out[3:]
                t0 = _clk()
                # ONE scalar batch per mega-dispatch — the sanctioned
                # mid-hunt read (occupancy telemetry, novelty lane,
                # cursor/epoch mirrors, stop_on_first_bug).
                (bug_h, n_act_h, k_done_h, hist_h, cur_h, ep_h, cov_np,
                 stats_h) = _fetch(
                    (any_bug, n_active, k_done, hist, cursor_dev,
                     epochs_dev, cov_h if cov_on else None,
                     stats_t if search_on else None))
                perf["device_wait_s"] += _clk() - t0
                perf["scalar_fetches"] += 1
                prof.after_read()
                t0 = _clk()
                k_done = int(k_done_h)
                n_act = int(n_act_h)
                hist_np = np.asarray(hist_h)
                cov_arr = np.asarray(cov_np) if cov_on else None
                for j in range(k_done):
                    n_active_hist.append(int(hist_np[j]))
                    n_active_chunk.append(chunks + j)
                    if cov_on:
                        novelty_hist.append(int(cov_arr[j]))
                chunks += k_done
                steps = chunks * chunk_steps
                issued_slot_steps += w_cur * chunk_steps * k_done
                cursor = int(cur_h)
                if search_on and int(ep_h) > fused_epochs:
                    # Host mirrors of the corpus telemetry, refreshed
                    # from the LAST device refill's stats — once per
                    # mega-dispatch rather than once per refill (the
                    # per-refill cadence lives on device now; see
                    # docs/observability.md). The operator table is NOT
                    # pulled mid-hunt — its record rows fold at the end.
                    search_host["corpus_size"] = int(stats_h[0])
                    search_host["inserted"] = int(stats_h[1])
                    if lineage_on:
                        search_host["gen"] = int(stats_h[2])
                        search_host["refill_novel"] = int(stats_h[3])
                        search_host["refill_inserted"] = int(stats_h[4])
                    search_host["epochs_on_device"] = int(ep_h)
                    emit_search_point(None)
                if int(ep_h) > 0:
                    reordered = True
                fused_epochs = int(ep_h)
                more_seeds = cursor < n_ids
                if (n_act == 0 and not more_seeds) or \
                        (stop_on_first_bug and bool(bug_h)):
                    stop = True
                elif k_done < k:
                    # The device loop exits early only on its stop
                    # predicate; a short count means the predicate
                    # fired on-device — mirror it (the scalars above
                    # necessarily agree, but int rounding of a pulled
                    # bool keeps this branch as the belt to their
                    # suspenders).
                    stop = True
                perf["host_decision_s"] += _clk() - t0
                emit_point(n_act, bool(bug_h), 0)
        elif pipeline:
            # -- pipelined, superstepped orchestration ---------------------
            k_cur = 1                  # adaptive superstep size (chunks)
            epoch = 0                  # bumps on every refill/shrink
            epoch_fresh = True         # next dispatch is its epoch's first
            ckpt_mark = 0              # checkpoint cadence periods covered
            inflight: Optional[_Flight] = None
            pending_retires: list = []
            stop = False

            def threshold() -> int:
                """The on-device early-exit occupancy for the NEXT
                dispatch: the serial loop's trigger boundary (half the
                batch) whenever a refill or shrink could actually fire,
                else 0 (run until all retired). Under a checkpoint
                writer the dry-cursor shrink fallback is disabled (a
                shrunken snapshot could not resume), so the tail runs
                to all-retired at full width."""
                if recycle and cursor < n_ids:
                    return w_cur // 2
                if ((compact or (recycle and writer is None))
                        and w_cur % 2 == 0
                        and (w_cur // 2) % n_dev == 0):
                    return w_cur // 2
                return 0

            def dispatch(reserve: int = 0) -> None:
                """Issue one superstep on the CURRENT state (enqueue
                only — never blocks on device results). ``reserve`` is
                the planned chunk count of a superstep already in the
                device queue but not yet read: those chunks may still
                execute, so the budget must treat them as spent or a
                binding ``max_steps`` overruns the serial loop's
                ``c_max`` chunk ceiling."""
                nonlocal state, inflight, epoch_fresh, cov_hits, cov_first
                budget = c_max - chunks - reserve
                k = max(1, min(k_cur, budget, superstep_max))
                if writer is not None and checkpoint_every_chunks:
                    k = min(k, checkpoint_every_chunks)
                # The first dispatch of each occupancy epoch mirrors the
                # serial cadence exactly: one chunk runs before occupancy
                # is re-evaluated, even if a refill landed at/below the
                # threshold. Speculative dispatches keep min_one=False
                # so a stale one stays a pass-through no-op. K itself is
                # a traced scalar of the (per min_one variant) single
                # compiled runner, not a compile key.
                if epoch_fresh:
                    k = 1
                runner = sharded_superstep(
                    eng, mesh, chunk_steps, superstep_max, donate,
                    min_one=epoch_fresh,
                    coverage=cov_k if cov_on else None)
                epoch_fresh = False
                t0 = _clk()
                prof.before_dispatch()
                with prof.annotate("madsim:superstep"):
                    if cov_on:
                        (state, any_bug, n_active, k_done, hist, cov_hits,
                         cov_first, cov_h) = runner(
                            state, cov_hits, cov_first, idx, n_real_dev,
                            jnp.int32(threshold()),
                            jnp.asarray(bool(stop_on_first_bug)),
                            jnp.int32(k))
                    else:
                        cov_h = None
                        state, any_bug, n_active, k_done, hist = runner(
                            state, jnp.int32(threshold()),
                            jnp.asarray(bool(stop_on_first_bug)),
                            jnp.int32(k))
                perf["dispatch_s"] += _clk() - t0
                perf["dispatches"] += 1
                inflight = _Flight(
                    any_bug, n_active, k_done, hist, k, w_cur, epoch,
                    state if writer is not None else None, cov_h,
                    ((cov_hits, cov_first)
                     if writer is not None and cov_on else None))

            # max_steps <= 0 means a zero-chunk budget: the serial loop
            # never enters its body, so the pipelined loop must not
            # force a min_one first chunk either.
            if c_max > 0:
                dispatch()
            while inflight is not None:
                prev, inflight = inflight, None
                # Dispatch-ahead: superstep k+1 enters the device queue
                # BEFORE superstep k's scalars are read, so the device
                # never idles on host decision latency. If k's scalars
                # turn out to demand a stop/refill, k+1 is a bitwise
                # no-op (its entry condition is already false).
                if not stop and chunks + prev.planned < c_max:
                    dispatch(reserve=prev.planned)
                t0 = _clk()
                if cov_on:
                    # The novelty lane rides the SAME scalar batch — one
                    # _fetch per superstep either way (tier-1-counted).
                    bug_h, n_act_h, k_done_h, hist_h, cov_h = _fetch(
                        (prev.any_bug, prev.n_active, prev.k_done,
                         prev.hist, prev.cov_hist))
                else:
                    cov_h = None
                    bug_h, n_act_h, k_done_h, hist_h = _fetch(
                        (prev.any_bug, prev.n_active, prev.k_done,
                         prev.hist))
                perf["device_wait_s"] += _clk() - t0
                perf["scalar_fetches"] += 1
                prof.after_read()
                perf["dispatch_depth"] = max(
                    perf["dispatch_depth"], 1 if inflight is not None else 0)
                # Retirement pulls deferred from earlier refills/shrinks:
                # drain them here, where the loop blocks anyway.
                while pending_retires:
                    fetch_retire(pending_retires.pop(0))
                t0 = _clk()
                k_done = int(k_done_h)
                n_act = int(n_act_h)
                hist_np = np.asarray(hist_h)
                cov_np = np.asarray(cov_h) if cov_on else None
                for j in range(k_done):
                    n_active_hist.append(int(hist_np[j]))
                    n_active_chunk.append(chunks + j)
                    if cov_on:
                        novelty_hist.append(int(cov_np[j]))
                chunks += k_done
                steps = chunks * chunk_steps
                issued_slot_steps += prev.w * chunk_steps * k_done
                if prev.epoch == epoch:
                    # Superstep sizing adapts to the observed retirement
                    # rate: double while supersteps run to plan (slow
                    # start), and after an early exit settle on the
                    # chunks it actually ran — the measured
                    # chunks-per-decision of this workload. Deterministic
                    # — every input is a sim output; and since K is a
                    # traced scalar, the schedule costs no recompiles.
                    if k_done == prev.planned:
                        k_cur = min(k_cur * 2, superstep_max)
                    else:
                        k_cur = max(k_done, 1)
                if writer is not None and checkpoint_every_chunks and \
                        prev.epoch == epoch and \
                        chunks // checkpoint_every_chunks > ckpt_mark:
                    # Async: the pull + write overlap later supersteps'
                    # device work; the submitted state is a COMPLETED
                    # superstep output (donation is off with a writer).
                    # Epoch-gated: a stale pass-through superstep's state
                    # predates the refill the host idx/cursor already
                    # reflect — submitting it would tear the snapshot
                    # (the current epoch's next superstep submits soon).
                    writer.submit(prev.out_state, ckpt_aux(prev.out_cov))
                    submitted_chunks = chunks
                    ckpt_mark = chunks // checkpoint_every_chunks
                if prev.epoch == epoch and not stop:
                    more_seeds = cursor < n_ids
                    if n_act == 0 and not more_seeds:
                        stop = True
                    elif stop_on_first_bug and bool(bug_h):
                        stop = True
                    elif recycle and more_seeds and n_act <= w_cur // 2:
                        pending_retires.append(do_refill(n_act))
                        epoch += 1
                        epoch_fresh = True
                    else:
                        new_w = _compact_bucket(n_act, w_cur, n_dev)
                        # Dry-cursor shrink only without a writer: every
                        # snapshot written must stay full-shape-resumable.
                        if (compact or (recycle and not more_seeds
                                        and writer is None)) \
                                and new_w < w_cur:
                            pending_retires.append(do_shrink(new_w))
                            epoch += 1
                            epoch_fresh = True
                perf["host_decision_s"] += _clk() - t0
                emit_point(n_act, bool(bug_h),
                           1 if inflight is not None else 0)
                if stop:
                    break
                if inflight is None and chunks < c_max:
                    dispatch()
            while pending_retires:
                fetch_retire(pending_retires.pop(0))
        else:
            # -- serial per-chunk reference loop ---------------------------
            runner = sharded_engine(eng, mesh, chunk_steps, donate=donate,
                                    coverage=cov_k if cov_on else None)
            while steps < max_steps:
                t0 = _clk()
                prof.before_dispatch()
                with prof.annotate("madsim:chunk"):
                    if cov_on:
                        (state, any_bug, n_active, cov_hits, cov_first,
                         distinct) = runner(state, cov_hits, cov_first,
                                            idx, n_real_dev)
                    else:
                        distinct = None
                        state, any_bug, n_active = runner(state)
                perf["dispatch_s"] += _clk() - t0
                perf["dispatches"] += 1
                steps += chunk_steps
                chunks += 1
                issued_slot_steps += w_cur * chunk_steps
                if writer is not None and checkpoint_every_chunks and \
                        chunks % checkpoint_every_chunks == 0:
                    # Async: the pull + write overlap the next chunk's
                    # device work; the loop never blocks on the filesystem.
                    writer.submit(state, ckpt_aux(
                        (cov_hits, cov_first) if cov_on else None))
                    submitted_chunks = chunks
                t0 = _clk()
                if cov_on:
                    n_act_h, bug_h, dist_h = _fetch(
                        (n_active, any_bug, distinct))
                else:
                    n_act_h, bug_h = _fetch((n_active, any_bug))
                perf["device_wait_s"] += _clk() - t0
                perf["scalar_fetches"] += 1
                prof.after_read()
                n_act = int(n_act_h)
                if cov_on:
                    novelty_hist.append(int(dist_h))
                emit_point(n_act, bool(bug_h), 0)
                t0 = _clk()
                n_active_hist.append(n_act)
                n_active_chunk.append(chunks - 1)
                more_seeds = cursor < n_ids
                if n_act == 0 and not more_seeds:
                    perf["host_decision_s"] += _clk() - t0
                    break
                if stop_on_first_bug and bool(bug_h):
                    perf["host_decision_s"] += _clk() - t0
                    break
                if recycle and more_seeds and n_act <= w_cur // 2:
                    handles = do_refill(n_act)
                    perf["host_decision_s"] += _clk() - t0
                    fetch_retire(handles)
                    continue
                new_w = _compact_bucket(n_act, w_cur, n_dev)
                if (compact or (recycle and not more_seeds
                                and writer is None)) \
                        and new_w < w_cur:
                    handles = do_shrink(new_w)
                    perf["host_decision_s"] += _clk() - t0
                    fetch_retire(handles)
                else:
                    perf["host_decision_s"] += _clk() - t0
        if writer is not None and submitted_chunks != chunks:
            # The final state is always durable.
            writer.submit(state, ckpt_aux(
                (cov_hits, cov_first) if cov_on else None))
        if writer is not None:
            writer.flush_and_close()
            writer = None
    finally:
        prof.close()  # idempotent; stops a capture left open by an error
        if writer is not None:  # exception path: don't mask it
            writer.flush_and_close(suppress_errors=True)

    if cov_on:
        # End-of-sweep fold: worlds still live at exit (max_steps /
        # stop_on_first_bug truncation) contribute their partial-behavior
        # signatures, so distinct_behaviors accounts every admitted seed
        # exactly once. Identical between loops: both exit on the same
        # state (tier-1 bitwise contract).
        cov_hits, cov_first = _cov_endfolder(eng, mesh)(
            state, cov_hits, cov_first, idx, n_real_dev, jnp.asarray(True))

    sched_live_h = corpus_h = lin_live_h = op_tab_h = None
    sched_per_seed = lin_per_seed = None
    if fused:
        # Fused final read: retired AND live observations already sit in
        # the per-seed device buffers (retiring rows landed inside the
        # loop, live rows at the last mega-dispatch boundary), so the
        # whole result crosses in ONE pull — the "pulled once at the
        # end" half of the fused contract. Everything below is host
        # slicing of bucket padding.
        t0 = _clk()
        (bufs_h, cov_pack_h, sched_b_h, corpus_h, lin_b_h,
         op_tab_h) = _fetch(
            (fused_bufs, (cov_hits, cov_first) if cov_on else None,
             fused_sched_buf, corpus, fused_lin_buf, op_tab))
        perf["retire_wait_s"] += _clk() - t0
        perf["retire_fetches"] += 1
        if cov_on:
            cov_hits_h, cov_first_h = (np.asarray(x) for x in cov_pack_h)
        obs = {k: np.asarray(v)[:n_ids] for k, v in bufs_h.items()}
        live_world_steps += int(np.asarray(obs["steps"]).sum())
        if search_on:
            sched_per_seed = np.asarray(sched_b_h, np.int32)[:n_ids]
        if lineage_on:
            lin_per_seed = tuple(np.asarray(a, np.int32)[:n_ids]
                                 for a in lin_b_h)
    else:
        obs_live = eng.observe(state)
        if cov_on and search_on:
            # Search state rides the final ledger pull — still ONE _fetch.
            (idx_h, cov_hits_h, cov_first_h, sched_live_h, corpus_h,
             lin_live_h, op_tab_h) = _fetch(
                (idx, cov_hits, cov_first, slot_sched, corpus, slot_lin,
                 op_tab))
            idx_h, cov_hits_h, cov_first_h = (
                np.asarray(x) for x in (idx_h, cov_hits_h, cov_first_h))
            sched_live_h = np.asarray(sched_live_h, np.int32)
            if lin_live_h is not None:
                lin_live_h = tuple(np.asarray(a) for a in lin_live_h)
        elif cov_on:
            # The ledger rides the final slot-index pull — still ONE
            # _fetch.
            idx_h, cov_hits_h, cov_first_h = (
                np.asarray(x) for x in _fetch((idx, cov_hits, cov_first)))
        else:
            idx_h = np.asarray(_fetch(idx))
        live_keep = idx_h >= 0
        live_world_steps += int(
            np.asarray(obs_live["steps"])[live_keep].sum())
        # Scatter whenever the live batch does not cover the full id
        # space in seed order — after any reorder/retirement, OR when a
        # recycled sweep exited (stop_on_first_bug / max_steps) before
        # its first refill, so only the first w0 < n_ids seeds were
        # ever admitted.
        if reordered or retired_rows or w0 < n_ids:
            rows = np.concatenate(retired_rows + [idx_h[live_keep]])
            obs = {}
            for k, v_live in obs_live.items():
                v_live = np.asarray(v_live)[live_keep]
                merged = np.concatenate(retired.get(k, []) + [v_live],
                                        axis=0)
                # Zeros, not empty: an early stop (stop_on_first_bug)
                # can leave streamed seeds never admitted — they report
                # zeroed observations (bug=False) rather than garbage.
                out = np.zeros((n_ids,) + merged.shape[1:], merged.dtype)
                out[rows] = merged
                obs[k] = out
            if search_on:
                merged_s = np.concatenate(
                    retired_sched + [sched_live_h[live_keep]], axis=0)
                sched_out = np.full((n_ids,) + merged_s.shape[1:], -1,
                                    np.int32)
                sched_out[:, :, 1:] = 0  # canonical DISABLED_ROW padding
                sched_out[rows] = merged_s
                sched_per_seed = sched_out
            if lin_live_h is not None:
                # Per-seed lineage lanes scatter exactly like the
                # schedules; never-admitted seeds read as generation 0
                # (-1 parents, no operators, depth 0).
                lanes_out = []
                for i, dflt in enumerate((-1, -1, 0, 0)):
                    merged_l = np.concatenate(
                        [t[i] for t in retired_lin]
                        + [lin_live_h[i][live_keep]], axis=0)
                    out = np.full((n_ids,), dflt, np.int32)
                    out[rows] = np.asarray(merged_l, np.int32)
                    lanes_out.append(out)
                lin_per_seed = tuple(lanes_out)
        else:
            obs = obs_live
            if search_on:
                sched_per_seed = sched_live_h
            if lin_live_h is not None:
                lin_per_seed = tuple(np.asarray(a, np.int32)
                                     for a in lin_live_h)
    obs = {k: v[:n] for k, v in obs.items()}
    if sched_per_seed is not None:
        sched_per_seed = sched_per_seed[:n]
    if lin_per_seed is not None:
        lin_per_seed = tuple(a[:n] for a in lin_per_seed)
    util = (live_world_steps / issued_slot_steps if issued_slot_steps
            else 0.0)
    loop_stats = {
        "pipelined": bool(pipeline) and not fused,
        "fused": bool(fused),
        "superstep_max": (int(fused_k_bucket) if fused
                          else int(superstep_max) if pipeline else 1),
        "chunk_steps": int(chunk_steps),
        "chunks": int(chunks),
        "dispatches": int(perf["dispatches"]),
        "chunks_per_dispatch": round(
            chunks / max(perf["dispatches"], 1), 3),
        "dispatches_per_seed": round(
            perf["dispatches"] / max(n, 1), 6),
        # The fused headline (and its reciprocal): how many seeds one
        # host dispatch retires end to end. epochs_on_device counts the
        # refill epochs that ran INSIDE fused mega-dispatches (0 on the
        # host-orchestrated paths, where every epoch is its own
        # dispatch).
        "seeds_per_dispatch": round(
            n / max(perf["dispatches"], 1), 3),
        "epochs_on_device": int(fused_epochs),
        "dispatch_depth": int(perf["dispatch_depth"]),
        "device_wait_s": round(perf["device_wait_s"], 6),
        "host_decision_s": round(perf["host_decision_s"], 6),
        "dispatch_s": round(perf["dispatch_s"], 6),
        "retire_wait_s": round(perf["retire_wait_s"], 6),
        "scalar_fetches": int(perf["scalar_fetches"]),
        "retire_fetches": int(perf["retire_fetches"]),
        "loop_wall_s": round(_clk() - t_loop0, 6),
    }
    coverage = (coverage_from_device(cov_k, cov_hits_h, cov_first_h,
                                     novelty_hist) if cov_on else None)
    search_report = None
    triage_faults = faults
    if search_on:
        from ..search import SearchReport

        lineage_rep = op_stats = None
        if lin_per_seed is not None:
            from ..obs.lineage import (
                N_OPS,
                SearchLineage,
                host_credit,
                operator_stats,
            )

            lineage_rep = SearchLineage(
                parent1=lin_per_seed[0], parent2=lin_per_seed[1],
                ops=lin_per_seed[2], depth=lin_per_seed[3],
                entry_base=int(search_lin_base))
            # Bug credit folds HOST-side over the per-seed lanes: a find
            # that halted the sweep (or sat live at exit) never crossed
            # a harvest edge, so only this fold counts every find
            # exactly once (obs/lineage.py OperatorTable).
            op_bug = host_credit(np.zeros(N_OPS, np.int32),
                                 lineage_rep.ops,
                                 np.asarray(obs["bug"], bool))
            op_stats = operator_stats(*(tuple(op_tab_h) + (op_bug,)))
        c_filled = np.asarray(corpus_h.filled, bool)
        search_report = SearchReport(
            # Generations THIS sweep ran: the epoch stream offset
            # (search_gen0) is a key-space shift, not work done here.
            generations=int(np.asarray(corpus_h.gen)) - int(search_gen0),
            inserted=int(np.asarray(corpus_h.inserted)),
            corpus_size=int(c_filled.sum()),
            corpus_capacity=int(c_filled.shape[0]),
            corpus_sched=np.asarray(corpus_h.sched, np.int32),
            corpus_sig=np.asarray(corpus_h.sig, np.uint32),
            corpus_score=np.asarray(corpus_h.score, np.int32),
            corpus_filled=c_filled,
            schedules=sched_per_seed,
            corpus_entry=np.asarray(corpus_h.entry, np.int32),
            corpus_depth=np.asarray(corpus_h.depth, np.int32),
            lineage=lineage_rep,
            operator_stats=op_stats,
        )
        # Triage sees the MATERIALIZED per-seed schedules: a guided
        # find's minimize/triage path re-executes the child schedule
        # the world actually ran, not the template.
        triage_faults = sched_per_seed
    result = SweepResult(seeds=seeds, bug=obs["bug"], observations=obs,
                         steps_run=steps, n_devices=n_dev,
                         n_active_history=np.asarray(n_active_hist,
                                                     np.int64),
                         world_utilization=util,
                         n_active_chunks=np.asarray(n_active_chunk,
                                                    np.int64),
                         loop_stats=loop_stats,
                         faults_sha256=(seeds_meta["faults_sha256"]
                                        if faults is not None else None),
                         coverage=coverage,
                         search=search_report,
                         triage_ctx=TriageContext(engine=eng,
                                                  faults=triage_faults,
                                                  mesh=mesh))
    if emit_telemetry is not None:
        final = {
            # /2: seeds_per_dispatch + epochs_on_device surfaced top-
            # level (additive — docs/observability.md "Schema history").
            "schema": "madsim.sweep.telemetry/2",
            "event": "summary",
            "elapsed_s": loop_stats["loop_wall_s"],
            "seeds_total": int(n),
            "failing_seeds": len(result.failing_seeds),
            "world_utilization": round(util, 4),
            # Dispatch economics, surfaced TOP-LEVEL (schema /2 —
            # docs/observability.md): the Prometheus renderer exports
            # only top-level numerics, and these two are the fused
            # path's headline gauges. Duplicated from loop_stats, where
            # the full breakdown still lives.
            "seeds_per_dispatch": loop_stats["seeds_per_dispatch"],
            "epochs_on_device": loop_stats["epochs_on_device"],
            "loop_stats": loop_stats,
        }
        if coverage is not None:
            final["coverage"] = coverage.to_json()
        if search_report is not None:
            final["search"] = search_report.to_json()
            if search_report.lineage is not None and result.failing_seeds:
                # The finds' full derivations ride the summary record
                # (capped — a hunt's first few finds, not the seed
                # space), so `python -m madsim_tpu.obs lineage
                # <stream>` can render ancestry without the SweepResult.
                from ..obs.lineage import lineage_block

                rows = np.flatnonzero(np.asarray(result.bug))[:8]
                final["search"]["finds"] = [
                    lineage_block(search_report.lineage, int(r),
                                  seeds=np.asarray(result.seeds))
                    for r in rows]
        emit_telemetry(final)
    if close_telemetry is not None:
        close_telemetry()
    return result


def _compact_bucket(n_active: int, w_cur: int, n_dev: int) -> int:
    """Largest power-of-two shrink of ``w_cur`` that still holds every
    active world and stays a multiple of the mesh; ``w_cur`` when no
    halving is possible (compaction triggers only below half-occupancy)."""
    w = w_cur
    # w//2 % n_dev == 0 already implies the w//2 >= n_dev floor (any
    # positive value below n_dev fails the modulus test).
    while w % 2 == 0 and w // 2 >= max(n_active, 1) and w // 2 % n_dev == 0:
        w //= 2
    return w


def _pow2_at_least(n: int) -> int:
    """Smallest power of two >= n (>= 1): bucketed retirement-gather
    widths, so the tail observer compiles at most log2(W) programs."""
    b = 1
    while b < n:
        b <<= 1
    return b


@jax.jit
def _permute_worlds(state, perm):
    """Reorder the world axis of a whole state pytree on device."""
    return jax.tree.map(lambda x: x[perm], state)


def _compactor(eng: DeviceEngine, mesh: Mesh, w: int, new_w: int,
               with_sched: bool = False):
    """Compile (and cache per engine) the on-device compaction program.

    The program computes the stable active-first permutation of a
    width-``w`` batch with ``jnp.argsort`` ON DEVICE, applies it to the
    state and the slot→seed index vector via :func:`_permute_worlds`, and
    (for ``new_w < w``) splits off the frozen tail. ``out_shardings``
    pins every output to the mesh's world sharding, so compaction needs
    no host pull of ``state.active``, no host-built permutation, and no
    ``device_put`` reshard afterwards — the host contributes only the
    ``n_active`` scalar the chunk runner already returned. Shrink widths
    are power-of-two buckets, so at most log2(W) programs compile.

    ``with_sched`` (guided sweeps, search/): the program additionally
    permutes/splits the per-slot ``(W, F, 4)`` schedule array in the
    same dispatch, so schedule attribution travels with the worlds.
    A distinct cache key — ``search=None`` sweeps compile the exact
    pre-search program (tier-1, tests/test_search.py).

    Deliberately NOT donated: the permutation is a gather, whose output
    XLA can never alias onto its input (an in-place permute would read
    clobbered rows), so donating here frees nothing and trips the
    "donated buffer not usable" warning on every leaf. Compaction
    transiently holds two batches; the chunk runner — where the state
    lives 99% of the time — is the donated path.
    """
    cache = eng.__dict__.setdefault("_compactor_cache", {})
    key = (mesh, w, new_w, with_sched)
    if key in cache:
        return cache[key]

    def compacted(state, idx, *sched):
        order = jnp.argsort((~state.active).astype(jnp.int32), stable=True)
        group = (state, idx) + sched
        group = _permute_worlds(group, order)
        if new_w == w:
            return group
        live = jax.tree.map(lambda x: x[:new_w], group)
        frozen = jax.tree.map(lambda x: x[new_w:], group)
        return live, frozen

    fn = jax.jit(compacted, out_shardings=world_sharding(mesh))
    cache[key] = fn
    return fn


def _sched_tail(eng: DeviceEngine, mesh: Mesh, w: int, rows: int):
    """Compile (and cache per engine) the frozen-tail schedule gather —
    the :func:`_tail_observer` twin for the guided sweep's per-slot
    ``(W, F, 4)`` schedule array, sharing its bucketed-``rows`` compile
    bound and its clamp-and-slice contract. Accepts any pytree of
    ``(W, ...)`` arrays: with lineage on the sweep passes ``(sched,
    *LineageLanes)`` so the provenance lanes ride the SAME gather
    dispatch as the schedules."""
    cache = eng.__dict__.setdefault("_sched_tail_cache", {})
    key = (mesh, w, rows)
    if key in cache:
        return cache[key]

    def tail(group, start):
        take = jnp.clip(start + jnp.arange(rows, dtype=jnp.int32), 0, w - 1)
        return jax.tree.map(lambda x: jnp.take(x, take, axis=0), group)

    fn = jax.jit(tail)
    cache[key] = fn
    return fn


def _tail_observer(eng: DeviceEngine, mesh: Mesh, w: int, rows: int):
    """Compile (and cache per engine) the frozen-tail retirement gather.

    One jitted program slices ``rows`` observation rows starting at a
    dynamic ``start`` out of a width-``w`` batch — gathering INSIDE the
    device program via ``DeviceEngine.observe_device`` — so retirement
    pulls only the (bucketed) frozen-tail rows across the host boundary
    instead of the full per-world observation arrays. ``rows`` is a
    power-of-two bucket (bounded compiles); indices past the batch clamp
    to the last row and the caller slices the pull to the true tail
    length. The slot→seed index vector rides the same gather so
    attribution needs no second pull.
    """
    cache = eng.__dict__.setdefault("_tail_observer_cache", {})
    key = (mesh, w, rows)
    if key in cache:
        return cache[key]

    def tail(state, idx, start):
        take = jnp.clip(start + jnp.arange(rows, dtype=jnp.int32), 0, w - 1)
        obs = {k: jnp.take(v, take, axis=0)
               for k, v in eng.observe_device(state).items()}
        return obs, jnp.take(idx, take, axis=0)

    fn = jax.jit(tail)
    cache[key] = fn
    return fn


def _observer(eng: DeviceEngine):
    """Cached jit of ``observe_device`` for an already-split frozen batch
    (the shrink-compaction tail): builds the observation dict on device
    so the host pull covers exactly the retiring rows."""
    fn = eng.__dict__.get("_observer_fn")
    if fn is None:
        fn = jax.jit(lambda s, i: (eng.observe_device(s), i))
        eng.__dict__["_observer_fn"] = fn
    return fn


# Ceiling on the fused program's static per-dispatch chunk window (the
# hist-buffer width): every realistic hunt fits one mega-dispatch, and a
# ludicrous max_steps re-dispatches instead of compiling a huge history
# buffer. 4096 i32 entries = 16 KiB per lane — noise next to the state.
_FUSED_K_CAP = 4096


def _fused_hunt(eng: DeviceEngine, mesh: Mesh, scfg, *, w: int,
                n_ids_b: int, f_rows: int, chunk_steps: int,
                k_bucket: int, cov_k: Optional[int], lineage_on: bool,
                fault_mode: str, recycle: bool):
    """Compile (and cache per engine) the whole-hunt fused program.

    One plain-``jit`` dispatch runs the ENTIRE occupancy loop the serial
    sweep ran on host: chunk bodies under
    ``DeviceEngine._fused_superstep_impl``, and — inside the same
    ``lax.while_loop``, behind a ``lax.cond`` epoch trigger — the stable
    active-first compaction (the ``_compactor`` permutation), the
    retiring-tail scatter into per-seed observation buffers, the
    coverage fold, the guided harvest+generate
    (``search.generate_body``, the SAME callable the ``searcher``
    program jits), the in-loop refill (``DeviceEngine.refill_traced``)
    and the device-resident seed-cursor advance. Like ``_compactor``
    this is a plain ``jax.jit`` with mesh-pinned ``out_shardings`` (the
    global stable argsort cannot live under ``shard_map``); GSPMD
    partitions the loop body, and integer full-axis reductions equal
    the shard_mapped psums bitwise.

    Bit-exactness contract (tier-1: tests/test_fused.py): chunk bodies,
    the permutation, the harvest mask/order, the mutation streams and
    the refill init are all the exact programs/callables of the serial
    path evaluated on equal values, so ids, observations, m_* metrics,
    the coverage ledger, lineage lanes and the SearchReport are bitwise
    identical to ``fused=False``. The ONLY deliberate divergence is the
    dry-cursor shrink: contract surfaces are shrink-invariant, so the
    fused tail just runs at full width (``world_utilization`` is
    telemetry and may differ — docs/perf.md "Whole-hunt residency").

    Static geometry: ``w`` slots, ``n_ids_b`` power-of-two-bucketed
    seed-id space (+1 dump row on every per-seed buffer), ``k_bucket``
    history width per mega-dispatch. The real ``n_ids``/``n`` ride as
    traced scalars, so every seed count in a bucket reuses ONE compiled
    program (the PR 3 zero-recompile contract extended to fused).
    ``fault_mode``: ``search`` (children), ``per_world`` (gather the
    replicated table), ``shared`` (broadcast the template) or ``none``.
    """
    cache = eng.__dict__.setdefault("_fused_hunt_cache", {})
    key = (mesh, w, n_ids_b, f_rows, chunk_steps, k_bucket, cov_k,
           scfg, lineage_on, fault_mode, recycle)
    if key in cache:
        return cache[key]

    from ..obs.coverage import distinct_count, fold_retired_local

    search_on = scfg is not None
    cov_on = cov_k is not None
    if search_on:
        from ..search.generate import generate_body, generate_body_lineage

        gen_fn = (generate_body_lineage(eng.cfg, scfg, w) if lineage_on
                  else generate_body(eng.cfg, scfg, w))

    rep = NamedSharding(mesh, scalar_spec())
    ws = world_sharding(mesh)
    dump = jnp.int32(n_ids_b)         # trailing dump row of every buffer
    rows_r = jnp.arange(w, dtype=jnp.int32)

    def refill_epoch(s, ex, n_act, tabs, n_ids_real, lin_base):
        # (1) Stable active-first compaction — the _compactor program's
        # exact permutation, applied to the state, the slot→seed index
        # and (guided) the schedule/lane arrays in lockstep.
        order = jnp.argsort((~s.active).astype(jnp.int32), stable=True)
        perm = (s, ex["idx"])
        if search_on:
            perm = perm + (ex["sched"],)
        if lineage_on:
            perm = perm + (ex["lin"],)
        perm = jax.tree.map(lambda x: x[order], perm)
        s, idx = perm[0], perm[1]
        sched = perm[2] if search_on else None
        lin = perm[3] if lineage_on else None
        # (2) Retiring-tail harvest: scatter the frozen rows' final
        # observations by slot→seed idx into the per-seed buffers (the
        # serial loop's retire() attribution, kept on device). Dead
        # slots (idx < 0, dry-cursor leftovers already harvested) land
        # on the dump row.
        tail = (rows_r >= n_act) & (idx >= 0)
        tgt = jnp.where(tail, idx, dump)
        obs = eng.observe_device(s)
        ex = dict(ex, idx=idx)
        ex["bufs"] = {k: ex["bufs"][k].at[tgt].set(obs[k])
                      for k in ex["bufs"]}
        # (3) Admit the next seeds from the device-resident cursor —
        # the same take/repl/mask arithmetic do_refill ran on host.
        take = jnp.minimum(jnp.int32(w) - n_act,
                           n_ids_real - ex["cursor"])
        fill = (rows_r >= n_act) & (rows_r < n_act + take)
        repl = jnp.where(fill, ex["cursor"] + rows_r - n_act,
                         jnp.int32(-1))
        fill_ids = jnp.maximum(repl, 0)
        if search_on:
            # Park the retiring schedules (and provenance lanes) BEFORE
            # the children overwrite them — the pre-refill read order of
            # the serial _sched_tail gather.
            ex["sched_buf"] = ex["sched_buf"].at[tgt].set(sched)
            if lineage_on:
                ex["lin_buf"] = jax.tree.map(
                    lambda b, v: b.at[tgt].set(v), ex["lin_buf"], lin)
                (children, child_lin, ex["corpus"], ex["op_tab"],
                 ex["stats"]) = gen_fn(
                    s, sched, idx, ex["corpus"], n_act, fill_ids, fill,
                    lin, ex["op_tab"], lin_base)
                ex["lin"] = jax.tree.map(
                    lambda c, o: jnp.where(fill, c, o), child_lin, lin)
            else:
                children, ex["corpus"], ex["stats"] = gen_fn(
                    s, sched, idx, ex["corpus"], n_act, fill_ids)
            f_new = children
            ex["sched"] = jnp.where(fill[:, None, None], children, sched)
        elif fault_mode == "per_world":
            f_new = tabs["faults"][fill_ids]
        elif fault_mode == "shared":
            f_new = jnp.broadcast_to(tabs["faults"],
                                     (w,) + tabs["faults"].shape)
        else:
            f_new = jnp.zeros((w, 0, 4), jnp.int32)
        # (4) Re-key the refilled slots: the traced twin of
        # DeviceEngine.refill (same vmapped _init_one, same select).
        s = eng.refill_traced(s, fill, tabs["lo"][fill_ids],
                              tabs["hi"][fill_ids], f_new)
        ex["idx"] = jnp.where(rows_r >= n_act, repl, idx)
        ex["cursor"] = ex["cursor"] + take
        ex["epochs"] = ex["epochs"] + jnp.int32(1)
        return s, ex

    def run(state, idx, cursor, epochs, bufs, cov, srch, tabs,
            n_ids_real, n_real, lin_base, stop_on_bug, k_chunks):
        n_ids_real = jnp.asarray(n_ids_real, jnp.int32)
        n_real = jnp.asarray(n_real, jnp.int32)
        lin_base = jnp.asarray(lin_base, jnp.int32)
        stop_on_bug = jnp.asarray(stop_on_bug, bool)

        ex = {"idx": idx, "cursor": jnp.asarray(cursor, jnp.int32),
              "epochs": jnp.asarray(epochs, jnp.int32), "bufs": bufs}
        if cov_on:
            ex["cov"] = cov
            ex["cov_hist"] = jnp.full((k_bucket,), -1, jnp.int32)
        if search_on:
            ex["sched"], ex["corpus"], ex["sched_buf"] = srch[:3]
            if lineage_on:
                ex["lin"], ex["op_tab"], ex["lin_buf"] = srch[3:]
            ex["stats"] = tuple(jnp.int32(0)
                                for _ in range(5 if lineage_on else 2))

        def more_seeds(cursor):
            if not recycle:
                return jnp.asarray(False)
            return cursor < n_ids_real

        def entry_stop(ex, any_bug0, n_active0):
            # The pass-through property: a dispatch against a finished
            # hunt runs zero chunks, like the plain superstep's.
            return ((stop_on_bug & any_bug0)
                    | ((n_active0 == 0) & ~more_seeds(ex["cursor"])))

        def post_chunk(s, ex, act0, any_bug, n_active, i):
            if cov_on:
                hits, first = ex["cov"]
                fmask = (act0 & ~s.active & (ex["idx"] >= 0)
                         & (ex["idx"] < n_real))
                hits, first = fold_retired_local(hits, first, s.metrics,
                                                 fmask, ex["idx"])
                ex = dict(ex, cov=(hits, first))
                ex["cov_hist"] = jax.lax.dynamic_update_index_in_dim(
                    ex["cov_hist"], distinct_count(hits), i, 0)
            # The serial loop's exact decision order: hunt-over checks
            # first (a bug under stop_on_bug, or nothing active with a
            # dry cursor), THEN the refill trigger — a stop never
            # refills, a refill always runs one chunk before the next
            # evaluation (the body re-enters through the chunk).
            more = more_seeds(ex["cursor"])
            stop = ((n_active == 0) & ~more) | (stop_on_bug & any_bug)
            if recycle:
                trigger = ((~stop) & more
                           & (n_active <= jnp.int32(w // 2)))
                s, ex = jax.lax.cond(
                    trigger,
                    lambda op: refill_epoch(op[0], op[1], n_active, tabs,
                                            n_ids_real, lin_base),
                    lambda op: op,
                    (s, ex))
            return s, ex, stop

        state, ex, any_bug, n_active, k_done, hist = \
            eng._fused_superstep_impl(
                state, ex, stop_on_bug, k_chunks,
                chunk_steps=chunk_steps, k_max=k_bucket,
                post_chunk=post_chunk, entry_stop=entry_stop)

        # End-of-dispatch: park the LIVE slots' rows (never-retired and
        # dry-tail worlds alike) so the host's single end-of-hunt pull
        # is one buffer slice. Later dispatches overwrite with newer
        # values; retire-time scatters of refilled slots already moved
        # their idx, so no double attribution is possible.
        live_tgt = jnp.where(ex["idx"] >= 0, ex["idx"], dump)
        obs = eng.observe_device(state)
        bufs = {k: ex["bufs"][k].at[live_tgt].set(obs[k])
                for k in ex["bufs"]}
        cov_out = ex["cov"] if cov_on else ()
        ch_out = ex["cov_hist"] if cov_on else ()
        srch_out = ()
        stats_out = ex["stats"] if search_on else ()
        if search_on:
            sched_buf = ex["sched_buf"].at[live_tgt].set(ex["sched"])
            srch_out = (ex["sched"], ex["corpus"], sched_buf)
            if lineage_on:
                lin_buf = jax.tree.map(
                    lambda b, v: b.at[live_tgt].set(v), ex["lin_buf"],
                    ex["lin"])
                srch_out = srch_out + (ex["lin"], ex["op_tab"], lin_buf)
        return (state, ex["idx"], ex["cursor"], ex["epochs"], bufs,
                cov_out, srch_out, any_bug, n_active, k_done, hist,
                ch_out, stats_out)

    cov_sh = (rep, rep) if cov_on else ()
    srch_sh = ()
    stats_sh = ()
    if search_on:
        srch_sh = (ws, rep, rep)
        stats_sh = (rep,) * (5 if lineage_on else 2)
        if lineage_on:
            srch_sh = srch_sh + (ws, rep, rep)
    out_sh = (ws, ws, rep, rep, rep, cov_sh, srch_sh,
              rep, rep, rep, rep, (rep if cov_on else ()), stats_sh)
    fn = jax.jit(run, out_shardings=out_sh)
    cache[key] = fn
    return fn


class SweepSession:
    """A persistent sweep session: the fleet's answer to O(fresh-sweep)
    lease turnaround (docs/fleet.md "Fabric cost model").

    ``sweep()`` pays a per-call host tax — seed/fault padding and
    hashing, batch ``init``, compile-cache lookups, telemetry plumbing —
    that a fleet worker used to repeat for EVERY leased range. A session
    pins the (engine, mesh, chunk/superstep geometry) once and streams
    successive seed ranges through it:

    * :meth:`run` is a drop-in ``sweep()`` with the session's engine,
      mesh, and loop geometry pre-bound — checkpoint/resume, ``search=``
      corpus seeding, and every other sweep mode stay per-lease.
    * :meth:`run_group` takes SEVERAL ranges at once and advances them
      as ONE standing device batch (the widths the engine is actually
      efficient at), then splits per-range ``SweepResult``s that are
      bit-identical to one fresh ``sweep()`` per range. Worlds are
      position-independent and every range installs at chunk 0, so a
      grouped world's trajectory equals its solo counterpart's bit for
      bit; chunks past a range's retirement are on-device pass-throughs
      on inactive worlds. The standing slots are RECYCLED between
      groups: the next group's worlds enter through ``DeviceEngine.
      refill`` (all-slots mask, donating the dead batch in place)
      rather than a fresh double-buffered ``init``.

    Sync discipline matches the solo pipelined loop exactly: dispatch-
    ahead supersteps, ONE ``_fetch`` per superstep, and (coverage on)
    one final ledger pull covering every range — counted by the tier-1
    seam tests (tests/test_fleet.py) against the non-session path.

    NOT thread-safe; one session per worker.
    """

    #: sweep() kwargs run_group understands. A lease whose sweep kwargs
    #: leave this set (checkpointing, search, recycle, ...) must run
    #: solo through :meth:`run` — the worker enforces this split.
    GROUPABLE_KW = frozenset(
        {"chunk_steps", "max_steps", "superstep_max", "coverage_buckets"})

    def __init__(self, actor: Any = None, cfg: Optional[EngineConfig] = None,
                 *, engine: Optional[DeviceEngine] = None,
                 mesh: Optional[Mesh] = None, chunk_steps: int = 512,
                 max_steps: int = 1_000_000, superstep_max: int = 16,
                 coverage_buckets: Optional[int] = None):
        if engine is None:
            if cfg is None:
                raise ValueError(
                    "SweepSession needs engine=DeviceEngine(...) or "
                    "(actor, cfg) to build one")
            engine = DeviceEngine(actor, cfg)
        if superstep_max < 1:
            raise ValueError("superstep_max must be >= 1")
        self.engine = engine
        self.mesh = mesh if mesh is not None else seed_mesh()
        self.chunk_steps = int(chunk_steps)
        self.max_steps = int(max_steps)
        self.superstep_max = int(superstep_max)
        self.coverage_buckets = coverage_buckets
        #: Ranges served without paying a fresh per-lease sweep setup
        #: (bench.py fleet_sweep reports the fleet-wide sum).
        self.reuse_hits = 0
        self._runs = 0
        self._k_warm = 1          # adaptive-K carry across groups
        self._slot_state = None   # standing batch between groups
        self._slot_w = 0

    # -- solo path --------------------------------------------------------

    def run(self, seeds, faults: Optional[np.ndarray] = None,
            **kw) -> SweepResult:
        """One leased range through the full ``sweep()`` — session
        engine/mesh/geometry pre-bound, every per-lease mode
        (checkpoint/resume, ``search=``, recycling) available."""
        kw.setdefault("chunk_steps", self.chunk_steps)
        kw.setdefault("max_steps", self.max_steps)
        kw.setdefault("superstep_max", self.superstep_max)
        if self.coverage_buckets is not None:
            kw.setdefault("coverage_buckets", self.coverage_buckets)
        # A solo run does not leave the standing batch in a known state.
        self._slot_state = None
        first = self._runs == 0
        self._runs += 1
        if not first:
            self.reuse_hits += 1
        return sweep(None, self.engine.cfg, seeds, faults=faults,
                     engine=self.engine, mesh=self.mesh, **kw)

    # -- grouped path -----------------------------------------------------

    def _part_sha256(self, faults: Optional[np.ndarray]) -> Optional[str]:
        """Replicate the solo sweep's ``faults_sha256`` for one range:
        sha256 over the PADDED int32 rows (3-D schedules pad to the
        mesh-rounded id space with repeats of row 0, exactly as
        ``sweep()`` pads), so a grouped result's fingerprint equals its
        solo counterpart's byte for byte."""
        import hashlib
        if faults is None:
            return None
        fp = np.asarray(faults, np.int32)
        if fp.ndim == 3:
            n_i = fp.shape[0]
            pad = (-n_i) % self.mesh.devices.size
            if pad:
                fp = np.concatenate([fp, fp[:1].repeat(pad, axis=0)], axis=0)
        return hashlib.sha256(
            np.ascontiguousarray(fp).tobytes()).hexdigest()

    def run_group(self, parts: List[Dict[str, Any]],
                  observe: Any = None) -> List[SweepResult]:
        """Advance several seed ranges as one standing device batch;
        return one ``SweepResult`` per range, bit-identical to a fresh
        per-range ``sweep()`` (tier-1 contract, tests/test_fleet.py).

        ``parts``: ``[{"seeds": (n_i,) uint64, "faults": None | (F, 4)
        shared template | (n_i, F, 4) per-world}, ...]``. All parts must
        agree on the faults *form* (the worker groups only leases that
        slice one fleet-level schedule). ``observe``: the solo sweep's
        live-telemetry sink — one record per superstep scalar read,
        schema ``madsim.sweep.telemetry/1`` — which is what lets the
        fleet worker's heartbeat (and therefore every chaos preemption
        point) ride the grouped loop at the same cadence.
        """
        from time import perf_counter

        from ..obs import observatory as _obsy
        from ..obs.coverage import (
            DEFAULT_BUCKETS,
            coverage_from_device,
            ledger_zeros,
        )

        def _clk() -> float:
            # Loop wall telemetry only; never feeds a sim decision.
            return perf_counter()  # detlint: allow[DET001]

        if not parts:
            raise ValueError("run_group needs at least one range")
        eng, mesh = self.engine, self.mesh
        n_dev = mesh.devices.size
        chunk_steps, superstep_max = self.chunk_steps, self.superstep_max
        cov_on = bool(eng.cfg.metrics)
        cov_k = (int(self.coverage_buckets) if self.coverage_buckets
                 else DEFAULT_BUCKETS)

        # -- combine ranges into one batch --------------------------------
        seeds_list: List[np.ndarray] = []
        faults_list: List[Optional[np.ndarray]] = []
        for p in parts:
            s = np.asarray(p["seeds"], np.uint64)
            if s.shape[0] == 0:
                raise ValueError("run_group ranges must be non-empty")
            f = p.get("faults")
            if f is not None:
                f = np.asarray(f, np.int32)
                if f.ndim not in (2, 3) or f.shape[-1] != 4:
                    raise ValueError(
                        f"range fault schedules must be (F, 4) or "
                        f"(n_i, F, 4); got shape {f.shape}")
                if f.ndim == 3 and f.shape[0] != s.shape[0]:
                    raise ValueError(
                        f"per-world schedules carry one (F, 4) block per "
                        f"seed: got leading dim {f.shape[0]} for "
                        f"{s.shape[0]} seeds")
            seeds_list.append(s)
            faults_list.append(f)
        forms = {(None if f is None else f.ndim) for f in faults_list}
        if len(forms) > 1:
            raise ValueError(
                "run_group ranges must agree on the faults form "
                "(all None, all shared (F, 4), or all per-world)")
        form = forms.pop()

        n_list = [int(s.shape[0]) for s in seeds_list]
        offs = np.concatenate([[0], np.cumsum(n_list)]).astype(int)
        n_tot = int(offs[-1])
        w = n_tot + ((-n_tot) % n_dev)
        seeds_c = np.concatenate(seeds_list)
        if w > n_tot:  # mesh padding: dummy worlds, sliced off below
            seeds_c = np.concatenate([seeds_c, seeds_c[:1].repeat(w - n_tot)])
        if form is None:
            faults_init = None
        elif form == 2:
            faults_init = faults_list[0]
            for f in faults_list[1:]:
                if not np.array_equal(f, faults_init):
                    raise ValueError(
                        "shared (F, 4) templates must be identical "
                        "across grouped ranges")
        else:
            faults_init = np.concatenate(faults_list, axis=0)
            if w > n_tot:
                faults_init = np.concatenate(
                    [faults_init, faults_init[:1].repeat(w - n_tot, axis=0)],
                    axis=0)

        # -- install: recycle the standing slots, else fresh init ---------
        reused = self._slot_state is not None and self._slot_w == w
        if reused:
            prev_state, self._slot_state = self._slot_state, None
            state = shard_worlds(
                eng.refill(prev_state, np.ones(w, bool), seeds_c,
                           faults=faults_init), mesh)
        else:
            self._slot_state = None
            state = shard_worlds(eng.init(seeds_c, faults=faults_init), mesh)
        first = self._runs == 0
        self._runs += 1
        self.reuse_hits += len(parts) - (1 if first else 0)

        emit_telemetry, close_telemetry = _obsy.make_observer(observe)
        t_loop0 = _clk()
        perf = {"dispatches": 0, "scalar_fetches": 0, "device_wait_s": 0.0,
                "dispatch_s": 0.0, "dispatch_depth": 0}

        # -- pipelined dispatch-ahead loop (the solo loop, minus the
        # refill/shrink/search edges grouped mode never takes) ------------
        c_max = -(-self.max_steps // chunk_steps)
        chunks = 0
        k_cur = max(1, min(self._k_warm, superstep_max))
        epoch_fresh = True
        inflight: Optional[_Flight] = None
        stop = False
        n_act = n_tot

        def dispatch(reserve: int = 0) -> None:
            nonlocal state, inflight, epoch_fresh
            k = max(1, min(k_cur, c_max - chunks - reserve, superstep_max))
            if epoch_fresh:
                k = 1
            runner = sharded_superstep(
                eng, mesh, chunk_steps, superstep_max, donate=True,
                min_one=epoch_fresh, coverage=None)
            epoch_fresh = False
            t0 = _clk()
            state, any_bug, n_active, k_done, hist = runner(
                state, jnp.int32(0), jnp.asarray(False), jnp.int32(k))
            perf["dispatch_s"] += _clk() - t0
            perf["dispatches"] += 1
            inflight = _Flight(any_bug, n_active, k_done, hist, k, w, 0, None)

        try:
            if c_max > 0:
                dispatch()
            while inflight is not None:
                prev, inflight = inflight, None
                if not stop and chunks + prev.planned < c_max:
                    dispatch(reserve=prev.planned)
                t0 = _clk()
                bug_h, n_act_h, k_done_h, _hist_h = _fetch(
                    (prev.any_bug, prev.n_active, prev.k_done, prev.hist))
                perf["device_wait_s"] += _clk() - t0
                perf["scalar_fetches"] += 1
                perf["dispatch_depth"] = max(
                    perf["dispatch_depth"], 1 if inflight is not None else 0)
                k_done = int(k_done_h)
                n_act = int(n_act_h)
                chunks += k_done
                if k_done == prev.planned:
                    k_cur = min(k_cur * 2, superstep_max)
                else:
                    k_cur = max(k_done, 1)
                if not stop and n_act == 0:
                    stop = True
                if emit_telemetry is not None:
                    elapsed = _clk() - t_loop0
                    done = max(n_tot - n_act, 0)
                    emit_telemetry({
                        "schema": "madsim.sweep.telemetry/1",
                        "elapsed_s": round(elapsed, 6),
                        "chunks": int(chunks),
                        "steps": int(chunks * chunk_steps),
                        "batch_worlds": int(w),
                        "n_active": int(n_act),
                        "occupancy": round(n_act / w, 4) if w else 0.0,
                        "seeds_total": int(n_tot),
                        "seeds_done": int(done),
                        "bug_seen": bool(bug_h),
                        "session_group": len(parts),
                        "dispatch_depth": 1 if inflight is not None else 0,
                    })
                if stop:
                    break
                if inflight is None and chunks < c_max:
                    dispatch()
        except BaseException:
            # A kill/preemption mid-group leaves donated buffers in an
            # unknown state: drop the standing batch, never resume it.
            self._slot_state = None
            self._slot_w = 0
            if close_telemetry is not None:
                close_telemetry()
            raise

        # -- per-range extraction -----------------------------------------
        # One eng.observe pull (its own single device_get, exactly the
        # solo end-of-sweep read) + (coverage on) ONE _fetch batching
        # every range's end-folded ledger.
        ledgers_h = None
        if cov_on:
            folder = _cov_endfolder(eng, mesh)
            sharding = NamedSharding(mesh, scalar_spec())
            ledgers = []
            for i, n_i in enumerate(n_list):
                idx_np = np.full(w, -1, np.int32)
                idx_np[offs[i]:offs[i + 1]] = np.arange(n_i, dtype=np.int32)
                idx_r = shard_worlds(jnp.asarray(idx_np), mesh)
                hits, first = jax.device_put(ledger_zeros(cov_k), sharding)
                n_real = jnp.int32(n_i)
                # Two boundary folds per range: worlds that retired
                # during the group (frozen histograms — the resume
                # pre-pass precedent), then worlds still live at exit.
                # hits/first_seen are fold-order invariant, so the pair
                # equals the solo sweep's mid-loop + end folds exactly.
                hits, first = folder(state, hits, first, idx_r, n_real,
                                     jnp.asarray(False))
                hits, first = folder(state, hits, first, idx_r, n_real,
                                     jnp.asarray(True))
                ledgers.append((hits, first))
            ledgers_h = _fetch(ledgers)
        obs_all = eng.observe(state)

        self._slot_state = state
        self._slot_w = w
        self._k_warm = k_cur

        steps = chunks * chunk_steps
        issued = w * chunk_steps * chunks
        live_steps = int(np.asarray(obs_all["steps"])[:n_tot].sum())
        util = live_steps / issued if issued else 0.0
        loop_stats_base = {
            "pipelined": True,
            "session": True,
            "session_group": len(parts),
            "session_reused_slots": bool(reused),
            "superstep_max": int(superstep_max),
            "chunk_steps": int(chunk_steps),
            "chunks": int(chunks),
            "dispatches": int(perf["dispatches"]),
            "chunks_per_dispatch": round(
                chunks / max(perf["dispatches"], 1), 3),
            "dispatch_depth": int(perf["dispatch_depth"]),
            "device_wait_s": round(perf["device_wait_s"], 6),
            "dispatch_s": round(perf["dispatch_s"], 6),
            "scalar_fetches": int(perf["scalar_fetches"]),
            "loop_wall_s": round(_clk() - t_loop0, 6),
        }

        results: List[SweepResult] = []
        for i, (s, f) in enumerate(zip(seeds_list, faults_list)):
            lo, hi = int(offs[i]), int(offs[i + 1])
            obs = {k: np.asarray(v)[lo:hi] for k, v in obs_all.items()}
            coverage = None
            if cov_on:
                hits_h, first_h = ledgers_h[i]
                coverage = coverage_from_device(
                    cov_k, np.asarray(hits_h), np.asarray(first_h), [])
            results.append(SweepResult(
                seeds=s, bug=obs["bug"], observations=obs,
                steps_run=steps, n_devices=n_dev,
                world_utilization=util,
                loop_stats=dict(loop_stats_base),
                faults_sha256=self._part_sha256(f),
                coverage=coverage,
                triage_ctx=TriageContext(engine=eng, faults=f, mesh=mesh)))
        if close_telemetry is not None:
            close_telemetry()
        return results
