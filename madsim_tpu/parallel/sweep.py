"""Sharded multi-seed sweeps: the TPU replacement for MADSIM_TEST_JOBS.

``sweep`` is the device-engine counterpart of the host test driver's seed
loop (`madsim/src/sim/runtime/builder.rs:110-148` / madsim_tpu.testing):
initialize one world per seed, shard the world axis over the mesh, advance
all worlds in fixed-step chunks, and after each chunk reduce two tiny scalars
over ICI — "any bug found?" and "how many worlds still active?" — so the host
loop makes progress/early-exit decisions without ever pulling per-world state
off device. Failing seeds (the repro banner of `runtime/mod.rs:192-199`)
are gathered once, at the end.

The loop is a slot-occupancy model (docs/perf.md "World recycling"): the
batch is a fixed set of world slots, compaction is an on-device stable
partition (no host pull of per-world state), and with ``recycle=True``
retired slots are refilled with fresh seeds from a host-side cursor so
the mesh stays full for open-ended hunts. Per-chunk occupancy telemetry
(``n_active_history`` / ``world_utilization``) rides every result.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8 promotes shard_map out of experimental
    from jax import shard_map
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

from ..engine.core import DeviceEngine, EngineConfig, WorldState
from .mesh import seed_mesh, shard_worlds, world_sharding, world_spec


def sharded_engine(eng: DeviceEngine, mesh: Mesh, chunk_steps: int = 512,
                   donate: bool = False):
    """Compile a chunk runner: state → (state, any_bug, n_active).

    The body is `shard_map`'d so each device advances only its world shard
    (no resharding possible); the two scalar outputs are psum/any reductions
    over ALL mesh axes — ICI within a host, DCN across hosts on a 2-D
    ``multihost_mesh`` — the only cross-chip communication in a sweep.

    ``donate=True`` donates the input state: XLA updates the sharded
    batch in place instead of double-buffering it, which roughly doubles
    the W that fits in HBM — but the caller's reference is DEAD after
    each call. The sweep enables this exactly when no checkpoint writer
    is attached: the async checkpointer reads the pre-chunk state from a
    background thread, which donation would invalidate.

    Runners are cached per (mesh, chunk_steps, donate) on the engine, so
    repeated sweeps reuse the compiled program instead of paying a fresh
    XLA compile for an identical closure.
    """
    cache = eng.__dict__.setdefault("_sharded_runner_cache", {})
    key = (mesh, chunk_steps, donate)
    if key in cache:
        return cache[key]
    spec = world_spec(mesh)
    axes = tuple(mesh.axis_names)

    def chunk(state: WorldState):
        state = eng._run_steps_impl(state, chunk_steps)
        any_bug = jax.lax.psum(
            jnp.any(state.bug).astype(jnp.int32), axes) > 0
        n_active = jax.lax.psum(
            jnp.sum(state.active.astype(jnp.int32)), axes)
        return state, any_bug, n_active

    try:  # jax >= 0.8 renamed check_rep -> check_vma
        mapped = shard_map(chunk, mesh=mesh, in_specs=(spec,),
                           out_specs=(spec, P(), P()), check_vma=False)
    except TypeError:  # pragma: no cover — older jax
        mapped = shard_map(chunk, mesh=mesh, in_specs=(spec,),
                           out_specs=(spec, P(), P()), check_rep=False)
    runner = jax.jit(mapped, donate_argnums=(0,) if donate else ())
    cache[key] = runner
    return runner


class _AsyncCheckpointer:
    """Background checkpoint writer: overlaps the device→host pull and the
    npz write with the next chunk's device work (VERDICT r4 item 7 — the
    synchronous save used to block the chunk loop for its full duration).

    Latest-wins coalescing: if the writer is still busy when the next
    snapshot arrives, the queued-but-unstarted one is replaced — for
    preemption survival only the newest durable state matters, and write
    cadence must not backpressure the sweep. Reading completed jax arrays
    from this thread is safe: whenever a writer is attached the sweep
    compiles its chunk runner WITHOUT input donation (donation would hand
    XLA the submitted buffers mid-read — see ``sharded_engine``), and
    the on-disk write stays atomic (engine/checkpoint.py tmp+rename).
    """

    def __init__(self, eng, path, extra_meta):
        import threading

        self._eng = eng
        self._path = path
        self._meta = extra_meta
        self._cond = threading.Condition()
        self._pending = None
        self._busy = False
        self._stop = False
        self._error: Optional[BaseException] = None
        # detlint: allow[DET003] — host-side checkpoint writer beside the device sweep
        self._thread = threading.Thread(
            target=self._run, name="madsim-checkpointer", daemon=True)
        self._thread.start()

    def submit(self, state) -> None:
        with self._cond:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            self._pending = state
            self._cond.notify_all()

    def _run(self) -> None:
        import jax as _jax

        from ..engine import checkpoint as ckpt

        while True:
            with self._cond:
                while self._pending is None and not self._stop:
                    self._cond.wait()
                if self._pending is None:
                    return
                state, self._pending = self._pending, None
                self._busy = True
            try:
                # Pull to host FIRST and drop the device reference: holding
                # the device pytree through the disk write would pin up to
                # a full extra state of HBM while the sweep runs ahead.
                host_state = _jax.device_get(state)
                state = None
                ckpt.save(self._eng, host_state, self._path,
                          extra_meta=self._meta)
                exc = None
            except BaseException as e:  # noqa: BLE001 — surfaced at submit/flush
                exc = e
            with self._cond:
                self._busy = False
                if exc is not None:
                    self._error = exc
                self._cond.notify_all()

    def flush_and_close(self, suppress_errors: bool = False) -> None:
        """Wait until every submitted snapshot is durable, then stop.

        ``suppress_errors`` logs a deferred writer failure instead of
        raising — for finally blocks where an in-flight exception must not
        be masked by a checkpoint-write error."""
        with self._cond:
            while self._pending is not None or self._busy:
                self._cond.wait()
            self._stop = True
            self._cond.notify_all()
        self._thread.join()
        if self._error is not None:
            if suppress_errors:
                import logging

                logging.getLogger("madsim_tpu.sweep").warning(
                    "checkpoint write failed during sweep teardown: %r",
                    self._error)
            else:
                raise self._error


@dataclasses.dataclass
class SweepResult:
    """Outcome of a sharded seed sweep."""

    seeds: np.ndarray            # the (unpadded) seed vector
    bug: np.ndarray              # per-seed bug flag
    observations: Dict[str, np.ndarray]  # engine + actor metrics, per seed
    steps_run: int               # chunks * chunk_steps issued
    n_devices: int
    # Occupancy telemetry (docs/perf.md "world recycling"): the active
    # world count after each chunk, and the fraction of issued slot-steps
    # that advanced a live world — useful/(sum over chunks of
    # batch_width*chunk_steps). Frozen worlds riding masked in the batch
    # are the difference; 1.0 means the mesh never ran a frozen slot.
    n_active_history: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.int64))
    world_utilization: float = 0.0

    @property
    def failing_seeds(self) -> List[int]:
        return [int(s) for s in self.seeds[self.bug]]

    def repro_banner(self) -> Optional[str]:
        """The failing-seed reproduction hint (`runtime/mod.rs:192-199`)."""
        if not self.failing_seeds:
            return None
        return ("note: run with environment variable "
                f"MADSIM_TEST_SEED={self.failing_seeds[0]} to reproduce "
                f"this failure ({len(self.failing_seeds)} failing seeds total)")


def sweep(actor: Any, cfg: EngineConfig, seeds, faults: Optional[np.ndarray] = None,
          mesh: Optional[Mesh] = None, chunk_steps: int = 512,
          max_steps: int = 1_000_000, stop_on_first_bug: bool = False,
          engine: Optional[DeviceEngine] = None,
          checkpoint_path: Optional[str] = None,
          checkpoint_every_chunks: int = 0,
          resume: bool = False,
          compact: bool = False,
          recycle: bool = False,
          batch_worlds: Optional[int] = None) -> SweepResult:
    """Run one simulation per seed, sharded over the mesh, to completion.

    The loop is a slot-occupancy model: the device batch is a fixed set of
    world *slots*, each holding a live world, a finished one awaiting
    retirement, or (after retirement) a recycled world for a fresh seed.
    Per chunk the host learns exactly two scalars — "any bug?" and "how
    many slots are active?" — and every occupancy decision (shrink,
    retire, refill) runs as an on-device program keyed off that count.

    Preemption survival: with ``checkpoint_path`` set, the (padded) world
    state is written every ``checkpoint_every_chunks`` chunks (and at the
    end); with ``resume=True`` an existing checkpoint is loaded instead of
    re-initializing, and the sweep continues bit-exactly where it stopped —
    resumed trajectories equal an unbroken run's (the state carries every
    RNG cursor and queue). ``max_steps`` counts steps issued by THIS call.

    Donation caveat: without checkpointing, the chunk runner DONATES its
    input state (XLA steps the batch in place — roughly double the W per
    HBM; a donated state is dead after the call). Checkpointing turns
    donation off, because the async writer still reads the submitted
    pre-chunk state while the next chunk runs — so a checkpointed sweep
    keeps the old double-buffered peak. Budget W accordingly when
    enabling ``checkpoint_path``.

    ``compact``: straggler compaction (docs/perf.md "the straggler
    tail"). A chunked batch runs until its SLOWEST world finishes, so
    once most worlds are done the chip mostly advances frozen state.
    When the active count drops below half the batch, the sweep gathers
    the active worlds to the front — a stable active-first ``argsort``
    computed INSIDE a jitted, mesh-resident program, so no per-world
    state (not even ``state.active``) crosses to the host and no reshard
    round trip follows — retires the frozen tail (its observations are
    pulled exactly once, as the final observe would have), and continues
    on a power-of-two-smaller batch. Worlds' trajectories are
    position-independent, so results are bitwise identical to the
    uncompacted run (tested). Disabled automatically when checkpointing
    (a shrunken state cannot resume into the full-shape contract).

    ``recycle`` + ``batch_worlds``: world recycling / seed streaming
    (docs/perf.md "world recycling"). Instead of only shrinking, retired
    slots are REFILLED with freshly initialized worlds for the next
    seeds from a host-side cursor: the sweep holds ``batch_worlds``
    slots (rounded to the mesh) and streams the full seed list through
    them, keeping utilization near 100% while any seeds remain; once the
    cursor is dry it falls back to shrink compaction for the tail. Each
    refilled world is bit-identical to an independent run of its seed
    (tested). This is the shape for open-ended hunts —
    ``stop_on_first_bug`` sweeps over huge seed spaces on a bounded
    memory footprint. On an early stop, seeds never admitted report
    zeroed observations (``bug=False``). Incompatible with
    checkpointing: the seed cursor and retired observations are host
    state a resume could not re-attribute (raises ``ValueError``).

    Occupancy telemetry rides the result: ``SweepResult.n_active_history``
    (per-chunk active counts) and ``SweepResult.world_utilization``
    (live-world steps / issued slot-steps, mesh padding included).
    """
    from ..engine import checkpoint as ckpt

    eng = engine if engine is not None else DeviceEngine(actor, cfg)
    mesh = mesh if mesh is not None else seed_mesh()
    n_dev = mesh.devices.size
    seeds = np.asarray(seeds, np.uint64)
    n = seeds.shape[0]

    if recycle and checkpoint_path:
        raise ValueError(
            "recycle=True cannot be combined with checkpointing: the seed "
            "cursor and retired observations live on the host, so a "
            "resumed sweep could not re-attribute recycled slots")

    # Batch width: a multiple of the mesh. Plain sweeps hold every seed at
    # once; recycled sweeps hold batch_worlds slots and stream the rest.
    full_w = n + ((-n) % n_dev)
    if recycle and batch_worlds is not None:
        w0 = min(max(1, int(batch_worlds)), max(n, 1))
        w0 += (-w0) % n_dev
        w0 = min(w0, full_w)
    else:
        w0 = full_w
    # Pad the seed-id space to the batch width (padded worlds are real
    # simulations of dummy seeds; their results are sliced off below).
    n_ids = max(n, w0)
    seeds_p = (np.concatenate([seeds, seeds[:1].repeat(n_ids - n)])
               if n_ids > n else seeds)

    faults_p = faults
    per_world_faults = False
    if faults is not None:
        faults_p = np.asarray(faults, np.int32)
        if faults_p.ndim == 2:
            if faults_p.shape[-1] != 4:
                raise ValueError(
                    f"shared fault schedule must be (F, 4) rows of "
                    f"[time_us, op, a, b]; got shape {faults_p.shape}")
        elif faults_p.ndim == 3:
            if faults_p.shape[0] != n or faults_p.shape[-1] != 4:
                raise ValueError(
                    f"per-world fault schedules must be (n_seeds, F, 4) "
                    f"with n_seeds={n}; got shape {faults_p.shape}")
            per_world_faults = True
            if n_ids > n:
                faults_p = np.concatenate(
                    [faults_p, faults_p[:1].repeat(n_ids - n, axis=0)],
                    axis=0)
        else:
            raise ValueError(
                f"faults must be (F, 4) or (n_seeds, F, 4); got "
                f"{faults_p.ndim}-D shape {faults_p.shape}")

    def batch_faults(ids: np.ndarray):
        """Fault rows for the worlds holding the given seed ids."""
        if faults_p is None:
            return None
        return faults_p[ids] if per_world_faults else faults_p

    import hashlib
    import os

    # World identity travels with the checkpoint: resuming under different
    # seeds OR fault schedules would silently attribute results (repro
    # banners!) to inputs that never produced them.
    faults_key = (np.ascontiguousarray(faults_p).tobytes()
                  if faults_p is not None else b"none")
    seeds_meta = {
        "seeds_sha256": hashlib.sha256(seeds_p.tobytes()).hexdigest(),
        "faults_sha256": hashlib.sha256(faults_key).hexdigest(),
    }

    if resume and checkpoint_path and os.path.exists(checkpoint_path):
        state = ckpt.load(eng, checkpoint_path, expect_extra=seeds_meta)
        if np.asarray(state.now).shape[0] != seeds_p.shape[0]:
            raise ckpt.CheckpointError(
                f"checkpoint holds {np.asarray(state.now).shape[0]} worlds, "
                f"sweep expects {seeds_p.shape[0]} (seeds + mesh padding)")
        state = shard_worlds(state, mesh)
    else:
        state = shard_worlds(
            eng.init(seeds_p[:w0], faults=batch_faults(np.arange(w0))), mesh)

    writer = (_AsyncCheckpointer(eng, checkpoint_path, seeds_meta)
              if checkpoint_path else None)
    # Donate the chunk state unless a checkpoint writer holds references
    # to it between chunks (the writer reads the submitted pytree from a
    # background thread; donating would hand XLA its buffers mid-read).
    runner = sharded_engine(eng, mesh, chunk_steps, donate=writer is None)
    compact = compact and writer is None  # shrunken state cannot resume
    steps = 0
    chunks = 0
    submitted_at = -1  # chunk counter, not an object ref: a pytree ref
    # here would pin a full extra device state between checkpoints.
    w_cur = w0                         # current batch width (slot count)
    cursor = w0                        # next seed id the stream admits
    # Slot→seed-id map, DEVICE-resident: compaction permutes it with the
    # state in the same on-device program, so the host never needs the
    # permutation (or state.active) to keep attribution straight. -1
    # marks a dead slot (retired world still riding in the batch).
    idx = shard_worlds(jnp.arange(w_cur, dtype=jnp.int32), mesh)
    reordered = False                  # batch rows still == seed order?
    retired: Dict[str, list] = {}      # field → retired observation batches
    retired_rows: List[np.ndarray] = []
    n_active_hist: List[int] = []
    issued_slot_steps = 0              # sum over chunks of width*chunk_steps
    live_world_steps = 0               # steps that advanced a live world

    def retire(obs_slice: Dict[str, np.ndarray], rows: np.ndarray) -> None:
        """Record final observations for rows leaving the batch (dead
        slots — already retired earlier — are filtered out by idx)."""
        nonlocal live_world_steps
        keep = rows >= 0
        if not keep.all():
            rows = rows[keep]
            obs_slice = {k: np.asarray(v)[keep] for k, v in obs_slice.items()}
        if rows.size == 0:
            return
        live_world_steps += int(np.asarray(obs_slice["steps"]).sum())
        retired_rows.append(rows)
        for k, v in obs_slice.items():
            retired.setdefault(k, []).append(np.asarray(v))

    try:
        while steps < max_steps:
            state, any_bug, n_active = runner(state)
            steps += chunk_steps
            chunks += 1
            issued_slot_steps += w_cur * chunk_steps
            if writer is not None and checkpoint_every_chunks and \
                    chunks % checkpoint_every_chunks == 0:
                # Async: the pull + write overlap the next chunk's device
                # work; the loop never blocks on the filesystem.
                writer.submit(state)
                submitted_at = chunks
            n_act = int(n_active)
            n_active_hist.append(n_act)
            more_seeds = cursor < n_ids
            if n_act == 0 and not more_seeds:
                break
            if stop_on_first_bug and bool(any_bug):
                break
            if recycle and more_seeds and n_act <= w_cur // 2:
                # World recycling: stable active-first partition on
                # device, retire the frozen tail, refill it with the next
                # seeds from the cursor. Only the n_active scalar (already
                # on host) shapes the refill mask.
                state, idx = _compactor(eng, mesh, w_cur, w_cur)(state, idx)
                reordered = True
                obs_full = eng.observe(state)
                idx_h = np.asarray(jax.device_get(idx))
                retire({k: v[n_act:] for k, v in obs_full.items()},
                       idx_h[n_act:])
                take = min(w_cur - n_act, n_ids - cursor)
                repl = np.full(w_cur, -1, np.int32)
                repl[n_act:n_act + take] = np.arange(
                    cursor, cursor + take, dtype=np.int32)
                cursor += take
                mask = np.zeros(w_cur, bool)
                mask[n_act:n_act + take] = True
                fill_ids = np.maximum(repl, 0)
                state = shard_worlds(
                    eng.refill(state, mask, seeds_p[fill_ids],
                               faults=batch_faults(fill_ids)), mesh)
                idx = jnp.where(jnp.asarray(np.arange(w_cur) >= n_act),
                                jnp.asarray(repl), idx)
                continue
            new_w = _compact_bucket(n_act, w_cur, n_dev)
            if (compact or (recycle and not more_seeds)) and new_w < w_cur:
                # Shrink compaction, fully on device: permutation, split,
                # and the live batch's mesh placement all happen inside
                # one jitted program (out_shardings = the world sharding).
                (state, idx), (frozen, fidx) = \
                    _compactor(eng, mesh, w_cur, new_w)(state, idx)
                reordered = True
                retire(eng.observe(frozen), np.asarray(jax.device_get(fidx)))
                w_cur = new_w
        if writer is not None and submitted_at != chunks:
            writer.submit(state)  # the final state is always durable
        if writer is not None:
            writer.flush_and_close()
            writer = None
    finally:
        if writer is not None:  # exception path: don't mask it
            writer.flush_and_close(suppress_errors=True)

    obs_live = eng.observe(state)
    idx_h = np.asarray(jax.device_get(idx))
    live_keep = idx_h >= 0
    live_world_steps += int(np.asarray(obs_live["steps"])[live_keep].sum())
    # Scatter whenever the live batch does not cover the full id space in
    # seed order — after any reorder/retirement, OR when a recycled sweep
    # exited (stop_on_first_bug / max_steps) before its first refill, so
    # only the first w0 < n_ids seeds were ever admitted.
    if reordered or retired_rows or w0 < n_ids:
        rows = np.concatenate(retired_rows + [idx_h[live_keep]])
        obs = {}
        for k, v_live in obs_live.items():
            v_live = np.asarray(v_live)[live_keep]
            merged = np.concatenate(retired.get(k, []) + [v_live], axis=0)
            # Zeros, not empty: an early stop (stop_on_first_bug) can
            # leave streamed seeds never admitted — they report zeroed
            # observations (bug=False) rather than garbage.
            out = np.zeros((n_ids,) + merged.shape[1:], merged.dtype)
            out[rows] = merged
            obs[k] = out
    else:
        obs = obs_live
    obs = {k: v[:n] for k, v in obs.items()}
    util = (live_world_steps / issued_slot_steps if issued_slot_steps
            else 0.0)
    return SweepResult(seeds=seeds, bug=obs["bug"], observations=obs,
                       steps_run=steps, n_devices=n_dev,
                       n_active_history=np.asarray(n_active_hist, np.int64),
                       world_utilization=util)


def _compact_bucket(n_active: int, w_cur: int, n_dev: int) -> int:
    """Largest power-of-two shrink of ``w_cur`` that still holds every
    active world and stays a multiple of the mesh; ``w_cur`` when no
    halving is possible (compaction triggers only below half-occupancy)."""
    w = w_cur
    # w//2 % n_dev == 0 already implies the w//2 >= n_dev floor (any
    # positive value below n_dev fails the modulus test).
    while w % 2 == 0 and w // 2 >= max(n_active, 1) and w // 2 % n_dev == 0:
        w //= 2
    return w


@jax.jit
def _permute_worlds(state, perm):
    """Reorder the world axis of a whole state pytree on device."""
    return jax.tree.map(lambda x: x[perm], state)


def _compactor(eng: DeviceEngine, mesh: Mesh, w: int, new_w: int):
    """Compile (and cache per engine) the on-device compaction program.

    The program computes the stable active-first permutation of a
    width-``w`` batch with ``jnp.argsort`` ON DEVICE, applies it to the
    state and the slot→seed index vector via :func:`_permute_worlds`, and
    (for ``new_w < w``) splits off the frozen tail. ``out_shardings``
    pins every output to the mesh's world sharding, so compaction needs
    no host pull of ``state.active``, no host-built permutation, and no
    ``device_put`` reshard afterwards — the host contributes only the
    ``n_active`` scalar the chunk runner already returned. Shrink widths
    are power-of-two buckets, so at most log2(W) programs compile.

    Deliberately NOT donated: the permutation is a gather, whose output
    XLA can never alias onto its input (an in-place permute would read
    clobbered rows), so donating here frees nothing and trips the
    "donated buffer not usable" warning on every leaf. Compaction
    transiently holds two batches; the chunk runner — where the state
    lives 99% of the time — is the donated path.
    """
    cache = eng.__dict__.setdefault("_compactor_cache", {})
    key = (mesh, w, new_w)
    if key in cache:
        return cache[key]

    def compacted(state, idx):
        order = jnp.argsort((~state.active).astype(jnp.int32), stable=True)
        state, idx = _permute_worlds((state, idx), order)
        if new_w == w:
            return state, idx
        live = jax.tree.map(lambda x: x[:new_w], (state, idx))
        frozen = jax.tree.map(lambda x: x[new_w:], (state, idx))
        return live, frozen

    fn = jax.jit(compacted, out_shardings=world_sharding(mesh))
    cache[key] = fn
    return fn
