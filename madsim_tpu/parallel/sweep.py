"""Sharded multi-seed sweeps: the TPU replacement for MADSIM_TEST_JOBS.

``sweep`` is the device-engine counterpart of the host test driver's seed
loop (`madsim/src/sim/runtime/builder.rs:110-148` / madsim_tpu.testing):
initialize one world per seed, shard the world axis over the mesh, advance
all worlds in fixed-step chunks, and after each chunk reduce two tiny scalars
over ICI — "any bug found?" and "how many worlds still active?" — so the host
loop makes progress/early-exit decisions without ever pulling per-world state
off device. Failing seeds (the repro banner of `runtime/mod.rs:192-199`)
are gathered once, at the end.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from ..engine.core import DeviceEngine, EngineConfig, WorldState
from .mesh import WORLD_AXIS, seed_mesh, shard_worlds


def sharded_engine(eng: DeviceEngine, mesh: Mesh, chunk_steps: int = 512):
    """Compile a chunk runner: state → (state, any_bug, n_active).

    The body is `shard_map`'d so each device advances only its world shard
    (no resharding possible); the two scalar outputs are psum/any reductions
    over the mesh axis — the only cross-chip communication in a sweep.

    Runners are cached per (mesh, chunk_steps) on the engine, so repeated
    sweeps reuse the compiled program instead of paying a fresh XLA compile
    for an identical closure.
    """
    cache = eng.__dict__.setdefault("_sharded_runner_cache", {})
    key = (mesh, chunk_steps)
    if key in cache:
        return cache[key]
    spec = P(WORLD_AXIS)

    def chunk(state: WorldState):
        state = eng._run_steps_impl(state, chunk_steps)
        any_bug = jax.lax.psum(
            jnp.any(state.bug).astype(jnp.int32), WORLD_AXIS) > 0
        n_active = jax.lax.psum(
            jnp.sum(state.active.astype(jnp.int32)), WORLD_AXIS)
        return state, any_bug, n_active

    runner = jax.jit(shard_map(
        chunk, mesh=mesh, in_specs=(spec,),
        out_specs=(spec, P(), P()), check_rep=False))
    cache[key] = runner
    return runner


@dataclasses.dataclass
class SweepResult:
    """Outcome of a sharded seed sweep."""

    seeds: np.ndarray            # the (unpadded) seed vector
    bug: np.ndarray              # per-seed bug flag
    observations: Dict[str, np.ndarray]  # engine + actor metrics, per seed
    steps_run: int               # chunks * chunk_steps issued
    n_devices: int

    @property
    def failing_seeds(self) -> List[int]:
        return [int(s) for s in self.seeds[self.bug]]

    def repro_banner(self) -> Optional[str]:
        """The failing-seed reproduction hint (`runtime/mod.rs:192-199`)."""
        if not self.failing_seeds:
            return None
        return ("note: run with environment variable "
                f"MADSIM_TEST_SEED={self.failing_seeds[0]} to reproduce "
                f"this failure ({len(self.failing_seeds)} failing seeds total)")


def sweep(actor: Any, cfg: EngineConfig, seeds, faults: Optional[np.ndarray] = None,
          mesh: Optional[Mesh] = None, chunk_steps: int = 512,
          max_steps: int = 1_000_000, stop_on_first_bug: bool = False,
          engine: Optional[DeviceEngine] = None) -> SweepResult:
    """Run one simulation per seed, sharded over the mesh, to completion."""
    eng = engine if engine is not None else DeviceEngine(actor, cfg)
    mesh = mesh if mesh is not None else seed_mesh()
    n_dev = mesh.devices.size
    seeds = np.asarray(seeds, np.uint64)
    n = seeds.shape[0]
    # Pad the world axis to a multiple of the mesh (padded worlds are real
    # simulations of dummy seeds; their results are sliced off below).
    pad = (-n) % n_dev
    seeds_p = np.concatenate([seeds, seeds[:1].repeat(pad)]) if pad else seeds
    faults_p = faults
    if faults is not None and pad:
        faults_p = np.asarray(faults, np.int32)
        if faults_p.ndim == 3:
            faults_p = np.concatenate(
                [faults_p, faults_p[:1].repeat(pad, axis=0)], axis=0)

    state = shard_worlds(eng.init(seeds_p, faults=faults_p), mesh)
    runner = sharded_engine(eng, mesh, chunk_steps)

    steps = 0
    while steps < max_steps:
        state, any_bug, n_active = runner(state)
        steps += chunk_steps
        if int(n_active) == 0:
            break
        if stop_on_first_bug and bool(any_bug):
            break

    obs = eng.observe(state)
    obs = {k: v[:n] for k, v in obs.items()}
    return SweepResult(seeds=seeds, bug=obs["bug"], observations=obs,
                       steps_run=steps, n_devices=n_dev)
