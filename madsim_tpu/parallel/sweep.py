"""Sharded multi-seed sweeps: the TPU replacement for MADSIM_TEST_JOBS.

``sweep`` is the device-engine counterpart of the host test driver's seed
loop (`madsim/src/sim/runtime/builder.rs:110-148` / madsim_tpu.testing):
initialize one world per seed, shard the world axis over the mesh, advance
all worlds in fixed-step chunks, and after each chunk reduce two tiny scalars
over ICI — "any bug found?" and "how many worlds still active?" — so the host
loop makes progress/early-exit decisions without ever pulling per-world state
off device. Failing seeds (the repro banner of `runtime/mod.rs:192-199`)
are gathered once, at the end.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8 promotes shard_map out of experimental
    from jax import shard_map
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

from ..engine.core import DeviceEngine, EngineConfig, WorldState
from .mesh import seed_mesh, shard_worlds, world_spec


def sharded_engine(eng: DeviceEngine, mesh: Mesh, chunk_steps: int = 512):
    """Compile a chunk runner: state → (state, any_bug, n_active).

    The body is `shard_map`'d so each device advances only its world shard
    (no resharding possible); the two scalar outputs are psum/any reductions
    over ALL mesh axes — ICI within a host, DCN across hosts on a 2-D
    ``multihost_mesh`` — the only cross-chip communication in a sweep.

    Runners are cached per (mesh, chunk_steps) on the engine, so repeated
    sweeps reuse the compiled program instead of paying a fresh XLA compile
    for an identical closure.
    """
    cache = eng.__dict__.setdefault("_sharded_runner_cache", {})
    key = (mesh, chunk_steps)
    if key in cache:
        return cache[key]
    spec = world_spec(mesh)
    axes = tuple(mesh.axis_names)

    def chunk(state: WorldState):
        state = eng._run_steps_impl(state, chunk_steps)
        any_bug = jax.lax.psum(
            jnp.any(state.bug).astype(jnp.int32), axes) > 0
        n_active = jax.lax.psum(
            jnp.sum(state.active.astype(jnp.int32)), axes)
        return state, any_bug, n_active

    try:  # jax >= 0.8 renamed check_rep -> check_vma
        mapped = shard_map(chunk, mesh=mesh, in_specs=(spec,),
                           out_specs=(spec, P(), P()), check_vma=False)
    except TypeError:  # pragma: no cover — older jax
        mapped = shard_map(chunk, mesh=mesh, in_specs=(spec,),
                           out_specs=(spec, P(), P()), check_rep=False)
    runner = jax.jit(mapped)
    cache[key] = runner
    return runner


@dataclasses.dataclass
class SweepResult:
    """Outcome of a sharded seed sweep."""

    seeds: np.ndarray            # the (unpadded) seed vector
    bug: np.ndarray              # per-seed bug flag
    observations: Dict[str, np.ndarray]  # engine + actor metrics, per seed
    steps_run: int               # chunks * chunk_steps issued
    n_devices: int

    @property
    def failing_seeds(self) -> List[int]:
        return [int(s) for s in self.seeds[self.bug]]

    def repro_banner(self) -> Optional[str]:
        """The failing-seed reproduction hint (`runtime/mod.rs:192-199`)."""
        if not self.failing_seeds:
            return None
        return ("note: run with environment variable "
                f"MADSIM_TEST_SEED={self.failing_seeds[0]} to reproduce "
                f"this failure ({len(self.failing_seeds)} failing seeds total)")


def sweep(actor: Any, cfg: EngineConfig, seeds, faults: Optional[np.ndarray] = None,
          mesh: Optional[Mesh] = None, chunk_steps: int = 512,
          max_steps: int = 1_000_000, stop_on_first_bug: bool = False,
          engine: Optional[DeviceEngine] = None,
          checkpoint_path: Optional[str] = None,
          checkpoint_every_chunks: int = 0,
          resume: bool = False) -> SweepResult:
    """Run one simulation per seed, sharded over the mesh, to completion.

    Preemption survival: with ``checkpoint_path`` set, the (padded) world
    state is written every ``checkpoint_every_chunks`` chunks (and at the
    end); with ``resume=True`` an existing checkpoint is loaded instead of
    re-initializing, and the sweep continues bit-exactly where it stopped —
    resumed trajectories equal an unbroken run's (the state carries every
    RNG cursor and queue). ``max_steps`` counts steps issued by THIS call.
    """
    from ..engine import checkpoint as ckpt

    eng = engine if engine is not None else DeviceEngine(actor, cfg)
    mesh = mesh if mesh is not None else seed_mesh()
    n_dev = mesh.devices.size
    seeds = np.asarray(seeds, np.uint64)
    n = seeds.shape[0]
    # Pad the world axis to a multiple of the mesh (padded worlds are real
    # simulations of dummy seeds; their results are sliced off below).
    pad = (-n) % n_dev
    seeds_p = np.concatenate([seeds, seeds[:1].repeat(pad)]) if pad else seeds
    faults_p = faults
    if faults is not None and pad:
        faults_p = np.asarray(faults, np.int32)
        if faults_p.ndim == 3:
            faults_p = np.concatenate(
                [faults_p, faults_p[:1].repeat(pad, axis=0)], axis=0)

    import hashlib
    import os

    # World identity travels with the checkpoint: resuming under different
    # seeds OR fault schedules would silently attribute results (repro
    # banners!) to inputs that never produced them.
    faults_key = (np.ascontiguousarray(faults_p).tobytes()
                  if faults_p is not None else b"none")
    seeds_meta = {
        "seeds_sha256": hashlib.sha256(seeds_p.tobytes()).hexdigest(),
        "faults_sha256": hashlib.sha256(faults_key).hexdigest(),
    }

    if resume and checkpoint_path and os.path.exists(checkpoint_path):
        state = ckpt.load(eng, checkpoint_path, expect_extra=seeds_meta)
        if np.asarray(state.now).shape[0] != seeds_p.shape[0]:
            raise ckpt.CheckpointError(
                f"checkpoint holds {np.asarray(state.now).shape[0]} worlds, "
                f"sweep expects {seeds_p.shape[0]} (seeds + mesh padding)")
        state = shard_worlds(state, mesh)
    else:
        state = shard_worlds(eng.init(seeds_p, faults=faults_p), mesh)
    runner = sharded_engine(eng, mesh, chunk_steps)

    steps = 0
    chunks = 0
    saved_at_chunk = -1
    while steps < max_steps:
        state, any_bug, n_active = runner(state)
        steps += chunk_steps
        chunks += 1
        if checkpoint_path and checkpoint_every_chunks and \
                chunks % checkpoint_every_chunks == 0:
            ckpt.save(eng, state, checkpoint_path, extra_meta=seeds_meta)
            saved_at_chunk = chunks
        if int(n_active) == 0:
            break
        if stop_on_first_bug and bool(any_bug):
            break
    if checkpoint_path and saved_at_chunk != chunks:
        ckpt.save(eng, state, checkpoint_path, extra_meta=seeds_meta)

    obs = eng.observe(state)
    obs = {k: v[:n] for k, v in obs.items()}
    return SweepResult(seeds=seeds, bug=obs["bug"], observations=obs,
                       steps_run=steps, n_devices=n_dev)
