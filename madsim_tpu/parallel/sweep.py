"""Sharded multi-seed sweeps: the TPU replacement for MADSIM_TEST_JOBS.

``sweep`` is the device-engine counterpart of the host test driver's seed
loop (`madsim/src/sim/runtime/builder.rs:110-148` / madsim_tpu.testing):
initialize one world per seed, shard the world axis over the mesh, advance
all worlds in fixed-step chunks, and after each chunk reduce two tiny scalars
over ICI — "any bug found?" and "how many worlds still active?" — so the host
loop makes progress/early-exit decisions without ever pulling per-world state
off device. Failing seeds (the repro banner of `runtime/mod.rs:192-199`)
are gathered once, at the end.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8 promotes shard_map out of experimental
    from jax import shard_map
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

from ..engine.core import DeviceEngine, EngineConfig, WorldState
from .mesh import seed_mesh, shard_worlds, world_spec


def sharded_engine(eng: DeviceEngine, mesh: Mesh, chunk_steps: int = 512):
    """Compile a chunk runner: state → (state, any_bug, n_active).

    The body is `shard_map`'d so each device advances only its world shard
    (no resharding possible); the two scalar outputs are psum/any reductions
    over ALL mesh axes — ICI within a host, DCN across hosts on a 2-D
    ``multihost_mesh`` — the only cross-chip communication in a sweep.

    Runners are cached per (mesh, chunk_steps) on the engine, so repeated
    sweeps reuse the compiled program instead of paying a fresh XLA compile
    for an identical closure.
    """
    cache = eng.__dict__.setdefault("_sharded_runner_cache", {})
    key = (mesh, chunk_steps)
    if key in cache:
        return cache[key]
    spec = world_spec(mesh)
    axes = tuple(mesh.axis_names)

    def chunk(state: WorldState):
        state = eng._run_steps_impl(state, chunk_steps)
        any_bug = jax.lax.psum(
            jnp.any(state.bug).astype(jnp.int32), axes) > 0
        n_active = jax.lax.psum(
            jnp.sum(state.active.astype(jnp.int32)), axes)
        return state, any_bug, n_active

    try:  # jax >= 0.8 renamed check_rep -> check_vma
        mapped = shard_map(chunk, mesh=mesh, in_specs=(spec,),
                           out_specs=(spec, P(), P()), check_vma=False)
    except TypeError:  # pragma: no cover — older jax
        mapped = shard_map(chunk, mesh=mesh, in_specs=(spec,),
                           out_specs=(spec, P(), P()), check_rep=False)
    runner = jax.jit(mapped)
    cache[key] = runner
    return runner


class _AsyncCheckpointer:
    """Background checkpoint writer: overlaps the device→host pull and the
    npz write with the next chunk's device work (VERDICT r4 item 7 — the
    synchronous save used to block the chunk loop for its full duration).

    Latest-wins coalescing: if the writer is still busy when the next
    snapshot arrives, the queued-but-unstarted one is replaced — for
    preemption survival only the newest durable state matters, and write
    cadence must not backpressure the sweep. Reading completed jax arrays
    from this thread is safe (the runner does not donate its inputs), and
    the on-disk write stays atomic (engine/checkpoint.py tmp+rename).
    """

    def __init__(self, eng, path, extra_meta):
        import threading

        self._eng = eng
        self._path = path
        self._meta = extra_meta
        self._cond = threading.Condition()
        self._pending = None
        self._busy = False
        self._stop = False
        self._error: Optional[BaseException] = None
        # detlint: allow[DET003] — host-side checkpoint writer beside the device sweep
        self._thread = threading.Thread(
            target=self._run, name="madsim-checkpointer", daemon=True)
        self._thread.start()

    def submit(self, state) -> None:
        with self._cond:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            self._pending = state
            self._cond.notify_all()

    def _run(self) -> None:
        import jax as _jax

        from ..engine import checkpoint as ckpt

        while True:
            with self._cond:
                while self._pending is None and not self._stop:
                    self._cond.wait()
                if self._pending is None:
                    return
                state, self._pending = self._pending, None
                self._busy = True
            try:
                # Pull to host FIRST and drop the device reference: holding
                # the device pytree through the disk write would pin up to
                # a full extra state of HBM while the sweep runs ahead.
                host_state = _jax.device_get(state)
                state = None
                ckpt.save(self._eng, host_state, self._path,
                          extra_meta=self._meta)
                exc = None
            except BaseException as e:  # noqa: BLE001 — surfaced at submit/flush
                exc = e
            with self._cond:
                self._busy = False
                if exc is not None:
                    self._error = exc
                self._cond.notify_all()

    def flush_and_close(self, suppress_errors: bool = False) -> None:
        """Wait until every submitted snapshot is durable, then stop.

        ``suppress_errors`` logs a deferred writer failure instead of
        raising — for finally blocks where an in-flight exception must not
        be masked by a checkpoint-write error."""
        with self._cond:
            while self._pending is not None or self._busy:
                self._cond.wait()
            self._stop = True
            self._cond.notify_all()
        self._thread.join()
        if self._error is not None:
            if suppress_errors:
                import logging

                logging.getLogger("madsim_tpu.sweep").warning(
                    "checkpoint write failed during sweep teardown: %r",
                    self._error)
            else:
                raise self._error


@dataclasses.dataclass
class SweepResult:
    """Outcome of a sharded seed sweep."""

    seeds: np.ndarray            # the (unpadded) seed vector
    bug: np.ndarray              # per-seed bug flag
    observations: Dict[str, np.ndarray]  # engine + actor metrics, per seed
    steps_run: int               # chunks * chunk_steps issued
    n_devices: int

    @property
    def failing_seeds(self) -> List[int]:
        return [int(s) for s in self.seeds[self.bug]]

    def repro_banner(self) -> Optional[str]:
        """The failing-seed reproduction hint (`runtime/mod.rs:192-199`)."""
        if not self.failing_seeds:
            return None
        return ("note: run with environment variable "
                f"MADSIM_TEST_SEED={self.failing_seeds[0]} to reproduce "
                f"this failure ({len(self.failing_seeds)} failing seeds total)")


def sweep(actor: Any, cfg: EngineConfig, seeds, faults: Optional[np.ndarray] = None,
          mesh: Optional[Mesh] = None, chunk_steps: int = 512,
          max_steps: int = 1_000_000, stop_on_first_bug: bool = False,
          engine: Optional[DeviceEngine] = None,
          checkpoint_path: Optional[str] = None,
          checkpoint_every_chunks: int = 0,
          resume: bool = False,
          compact: bool = False) -> SweepResult:
    """Run one simulation per seed, sharded over the mesh, to completion.

    Preemption survival: with ``checkpoint_path`` set, the (padded) world
    state is written every ``checkpoint_every_chunks`` chunks (and at the
    end); with ``resume=True`` an existing checkpoint is loaded instead of
    re-initializing, and the sweep continues bit-exactly where it stopped —
    resumed trajectories equal an unbroken run's (the state carries every
    RNG cursor and queue). ``max_steps`` counts steps issued by THIS call.

    ``compact``: straggler compaction (docs/perf.md "the straggler
    tail"). A chunked batch runs until its SLOWEST world finishes, so
    once most worlds are done the chip mostly advances frozen state.
    When the active count drops below half the batch, the sweep gathers
    the active worlds to the front (one on-device permutation), retires
    the frozen ones (their observations are pulled exactly once, as the
    final observe would have), and continues on a power-of-two-smaller
    batch — worlds' trajectories are position-independent, so results
    are bitwise identical to the uncompacted run (tested). Off by
    default: each compaction adds host↔device round trips, which on a
    co-located chip cost microseconds but on a TUNNELED device (this
    repo's bench machine) cost more than the masked straggler steps they
    save — measured in docs/perf.md. Enable on co-located hardware with
    long tails. Disabled automatically when checkpointing (a shrunken
    state cannot resume into the full-shape contract).
    """
    from ..engine import checkpoint as ckpt

    eng = engine if engine is not None else DeviceEngine(actor, cfg)
    mesh = mesh if mesh is not None else seed_mesh()
    n_dev = mesh.devices.size
    seeds = np.asarray(seeds, np.uint64)
    n = seeds.shape[0]
    # Pad the world axis to a multiple of the mesh (padded worlds are real
    # simulations of dummy seeds; their results are sliced off below).
    pad = (-n) % n_dev
    seeds_p = np.concatenate([seeds, seeds[:1].repeat(pad)]) if pad else seeds
    faults_p = faults
    if faults is not None and pad:
        faults_p = np.asarray(faults, np.int32)
        if faults_p.ndim == 3:
            faults_p = np.concatenate(
                [faults_p, faults_p[:1].repeat(pad, axis=0)], axis=0)

    import hashlib
    import os

    # World identity travels with the checkpoint: resuming under different
    # seeds OR fault schedules would silently attribute results (repro
    # banners!) to inputs that never produced them.
    faults_key = (np.ascontiguousarray(faults_p).tobytes()
                  if faults_p is not None else b"none")
    seeds_meta = {
        "seeds_sha256": hashlib.sha256(seeds_p.tobytes()).hexdigest(),
        "faults_sha256": hashlib.sha256(faults_key).hexdigest(),
    }

    if resume and checkpoint_path and os.path.exists(checkpoint_path):
        state = ckpt.load(eng, checkpoint_path, expect_extra=seeds_meta)
        if np.asarray(state.now).shape[0] != seeds_p.shape[0]:
            raise ckpt.CheckpointError(
                f"checkpoint holds {np.asarray(state.now).shape[0]} worlds, "
                f"sweep expects {seeds_p.shape[0]} (seeds + mesh padding)")
        state = shard_worlds(state, mesh)
    else:
        state = shard_worlds(eng.init(seeds_p, faults=faults_p), mesh)
    runner = sharded_engine(eng, mesh, chunk_steps)

    writer = (_AsyncCheckpointer(eng, checkpoint_path, seeds_meta)
              if checkpoint_path else None)
    compact = compact and writer is None  # shrunken state cannot resume
    steps = 0
    chunks = 0
    submitted_at = -1  # chunk counter, not an object ref: a pytree ref
    # here would pin a full extra device state between checkpoints.
    w_cur = seeds_p.shape[0]           # current (compacted) batch width
    orig_idx = np.arange(w_cur)        # row i of state ↔ seeds_p[orig_idx[i]]
    retired: Dict[str, list] = {}      # field → retired observation batches
    retired_rows: List[np.ndarray] = []

    def retire(obs_slice: Dict[str, np.ndarray], rows: np.ndarray) -> None:
        retired_rows.append(rows)
        for k, v in obs_slice.items():
            retired.setdefault(k, []).append(v)

    try:
        while steps < max_steps:
            state, any_bug, n_active = runner(state)
            steps += chunk_steps
            chunks += 1
            if writer is not None and checkpoint_every_chunks and \
                    chunks % checkpoint_every_chunks == 0:
                # Async: the pull + write overlap the next chunk's device
                # work; the loop never blocks on the filesystem.
                writer.submit(state)
                submitted_at = chunks
            n_act = int(n_active)
            if n_act == 0:
                break
            if stop_on_first_bug and bool(any_bug):
                break
            new_w = _compact_bucket(n_act, w_cur, n_dev)
            if compact and new_w < w_cur:
                active = np.asarray(jax.device_get(state.active))
                # Stable partition: active worlds first, original order
                # preserved either side of the split.
                perm = np.argsort(~active, kind="stable")
                permuted = _permute_worlds(state, jnp.asarray(perm))
                frozen = jax.tree.map(lambda x: x[new_w:], permuted)
                obs_f = eng.observe(frozen)
                retire(obs_f, orig_idx[perm[new_w:]])
                state = shard_worlds(
                    jax.tree.map(lambda x: x[:new_w], permuted), mesh)
                orig_idx = orig_idx[perm[:new_w]]
                w_cur = new_w
        if writer is not None and submitted_at != chunks:
            writer.submit(state)  # the final state is always durable
        if writer is not None:
            writer.flush_and_close()
            writer = None
    finally:
        if writer is not None:  # exception path: don't mask it
            writer.flush_and_close(suppress_errors=True)

    obs_live = eng.observe(state)
    if retired_rows:
        rows = np.concatenate(retired_rows + [orig_idx])
        obs = {}
        for k, v_live in obs_live.items():
            merged = np.concatenate(retired[k] + [np.asarray(v_live)], axis=0)
            out = np.empty_like(merged)
            out[rows] = merged
            obs[k] = out
    else:
        obs = obs_live
    obs = {k: v[:n] for k, v in obs.items()}
    return SweepResult(seeds=seeds, bug=obs["bug"], observations=obs,
                       steps_run=steps, n_devices=n_dev)


def _compact_bucket(n_active: int, w_cur: int, n_dev: int) -> int:
    """Largest power-of-two shrink of ``w_cur`` that still holds every
    active world and stays a multiple of the mesh; ``w_cur`` when no
    halving is possible (compaction triggers only below half-occupancy)."""
    w = w_cur
    # w//2 % n_dev == 0 already implies the w//2 >= n_dev floor (any
    # positive value below n_dev fails the modulus test).
    while w % 2 == 0 and w // 2 >= max(n_active, 1) and w // 2 % n_dev == 0:
        w //= 2
    return w


@jax.jit
def _permute_worlds(state, perm):
    """Reorder the world axis of a whole state pytree on device."""
    return jax.tree.map(lambda x: x[perm], state)
