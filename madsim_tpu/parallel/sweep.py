"""Sharded multi-seed sweeps: the TPU replacement for MADSIM_TEST_JOBS.

``sweep`` is the device-engine counterpart of the host test driver's seed
loop (`madsim/src/sim/runtime/builder.rs:110-148` / madsim_tpu.testing):
initialize one world per seed, shard the world axis over the mesh, advance
all worlds in fixed-step chunks, and after each chunk reduce two tiny scalars
over ICI — "any bug found?" and "how many worlds still active?" — so the host
loop makes progress/early-exit decisions without ever pulling per-world state
off device. Failing seeds (the repro banner of `runtime/mod.rs:192-199`)
are gathered once, at the end.

The loop is a slot-occupancy model (docs/perf.md "World recycling"): the
batch is a fixed set of world slots, compaction is an on-device stable
partition (no host pull of per-world state), and with ``recycle=True``
retired slots are refilled with fresh seeds from a host-side cursor so
the mesh stays full for open-ended hunts. Per-chunk occupancy telemetry
(``n_active_history`` / ``world_utilization``) rides every result.

Orchestration is *pipelined and superstepped* by default (docs/perf.md
"Pipelined orchestration"): up to ``superstep_max`` chunks fold into one
jitted ``lax.while_loop`` dispatch whose early-exit decisions (all
retired / occupancy at the recycle threshold / bug under
``stop_on_first_bug``) run ON DEVICE, and the host issues superstep k+1
before reading superstep k's scalars, so the device queue stays non-empty
while the host decides. A superstep dispatched past a stop/recycle point
is a bitwise pass-through (its entry condition is already false), which is
what makes one-dispatch-stale decisions exact rather than approximate:
results are bit-identical to the serial per-chunk loop (``pipeline=False``,
kept as the equivalence reference and tier-1-tested against).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

try:  # jax >= 0.8 promotes shard_map out of experimental
    from jax import shard_map
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

from ..engine.core import DeviceEngine, EngineConfig, WorldState
from .mesh import (
    scalar_spec,
    seed_mesh,
    shard_worlds,
    world_sharding,
    world_spec,
)

# Every device→host pull the sweep loop makes goes through this hook, so
# the tier-1 sync-discipline test (tests/test_sweep_pipeline.py) can count
# host-boundary crossings per superstep by monkeypatching it. Semantics:
# jax.device_get of an arbitrary pytree.
_fetch = jax.device_get


def sharded_engine(eng: DeviceEngine, mesh: Mesh, chunk_steps: int = 512,
                   donate: bool = False):
    """Compile a chunk runner: state → (state, any_bug, n_active).

    The body is `shard_map`'d so each device advances only its world shard
    (no resharding possible); the two scalar outputs are psum/any reductions
    over ALL mesh axes — ICI within a host, DCN across hosts on a 2-D
    ``multihost_mesh`` — the only cross-chip communication in a sweep.

    ``donate=True`` donates the input state: XLA updates the sharded
    batch in place instead of double-buffering it, which roughly doubles
    the W that fits in HBM — but the caller's reference is DEAD after
    each call. The sweep enables this exactly when no checkpoint writer
    is attached: the async checkpointer reads the pre-chunk state from a
    background thread, which donation would invalidate.

    Runners are cached per (mesh, chunk_steps, donate) on the engine, so
    repeated sweeps reuse the compiled program instead of paying a fresh
    XLA compile for an identical closure.
    """
    cache = eng.__dict__.setdefault("_sharded_runner_cache", {})
    key = (mesh, chunk_steps, donate)
    if key in cache:
        return cache[key]
    spec = world_spec(mesh)
    axes = tuple(mesh.axis_names)
    sp = scalar_spec()

    def chunk(state: WorldState):
        state = eng._run_steps_impl(state, chunk_steps)
        any_bug = jax.lax.psum(
            jnp.any(state.bug).astype(jnp.int32), axes) > 0
        n_active = jax.lax.psum(
            jnp.sum(state.active.astype(jnp.int32)), axes)
        return state, any_bug, n_active

    try:  # jax >= 0.8 renamed check_rep -> check_vma
        mapped = shard_map(chunk, mesh=mesh, in_specs=(spec,),
                           out_specs=(spec, sp, sp), check_vma=False)
    except TypeError:  # pragma: no cover — older jax
        mapped = shard_map(chunk, mesh=mesh, in_specs=(spec,),
                           out_specs=(spec, sp, sp), check_rep=False)
    runner = jax.jit(mapped, donate_argnums=(0,) if donate else ())
    cache[key] = runner
    return runner


def sharded_superstep(eng: DeviceEngine, mesh: Mesh, chunk_steps: int,
                      k_max: int, donate: bool = False,
                      min_one: bool = False):
    """Compile a superstep runner:
    ``(state, stop_threshold, stop_on_bug, k_chunks) → (state, any_bug,
    n_active, k_done, hist)``.

    The superstep folds up to ``k_chunks`` chunk bodies into ONE jitted
    dispatch (`DeviceEngine._superstep_impl`): a ``lax.while_loop`` whose
    condition re-checks the psum'd occupancy/bug scalars after every
    chunk, so the early exits the serial loop made from the host run on
    device and the host pays one dispatch + one scalar read per K chunks.
    ``stop_threshold`` / ``stop_on_bug`` / ``k_chunks`` are traced
    scalars — ONE compiled program per (mesh, chunk_steps, k_max,
    donate, min_one) serves every threshold and superstep length the
    adaptive schedule cycles through; only the (k_max,)-shaped history
    buffer is compile-time static.

    ``hist[j]`` is the post-chunk active count for each chunk actually
    run (-1 beyond ``k_done``) — the same per-chunk sequence the serial
    loop's ``n_active_history`` records. ``min_one`` forces the first
    chunk regardless of the entry condition (the serial loop's cadence
    right after a refill/shrink — see ``_superstep_impl``). Donation
    follows :func:`sharded_engine` (on exactly when no checkpoint writer
    holds state references between dispatches).
    """
    cache = eng.__dict__.setdefault("_sharded_superstep_cache", {})
    key = (mesh, chunk_steps, k_max, donate, min_one)
    if key in cache:
        return cache[key]
    spec = world_spec(mesh)
    axes = tuple(mesh.axis_names)
    sp = scalar_spec()

    def sstep(state: WorldState, stop_threshold, stop_on_bug, k_chunks):
        return eng._superstep_impl(
            state, stop_threshold, stop_on_bug, k_chunks,
            chunk_steps=chunk_steps, k_max=k_max,
            reduce_sum=lambda x: jax.lax.psum(x, axes), min_one=min_one)

    try:  # jax >= 0.8 renamed check_rep -> check_vma
        mapped = shard_map(sstep, mesh=mesh, in_specs=(spec, sp, sp, sp),
                           out_specs=(spec, sp, sp, sp, sp),
                           check_vma=False)
    except TypeError:  # pragma: no cover — older jax
        mapped = shard_map(sstep, mesh=mesh, in_specs=(spec, sp, sp, sp),
                           out_specs=(spec, sp, sp, sp, sp),
                           check_rep=False)
    runner = jax.jit(mapped, donate_argnums=(0,) if donate else ())
    cache[key] = runner
    return runner


class _Flight(NamedTuple):
    """One dispatched-but-unread superstep: its scalar futures plus the
    host-side facts (plan, width, epoch) needed to interpret them."""

    any_bug: Any
    n_active: Any
    k_done: Any
    hist: Any
    planned: int          # chunks this dispatch may run (its K)
    w: int                # batch width at dispatch time
    epoch: int            # occupancy epoch at dispatch time
    out_state: Any        # output state ref — kept ONLY for the writer


class _AsyncCheckpointer:
    """Background checkpoint writer: overlaps the device→host pull and the
    npz write with the next chunk's device work (VERDICT r4 item 7 — the
    synchronous save used to block the chunk loop for its full duration).

    Latest-wins coalescing: if the writer is still busy when the next
    snapshot arrives, the queued-but-unstarted one is replaced — for
    preemption survival only the newest durable state matters, and write
    cadence must not backpressure the sweep. Reading completed jax arrays
    from this thread is safe: whenever a writer is attached the sweep
    compiles its chunk runner WITHOUT input donation (donation would hand
    XLA the submitted buffers mid-read — see ``sharded_engine``), and
    the on-disk write stays atomic (engine/checkpoint.py tmp+rename).
    """

    def __init__(self, eng, path, extra_meta):
        import threading

        self._eng = eng
        self._path = path
        self._meta = extra_meta
        self._cond = threading.Condition()
        self._pending = None
        self._busy = False
        self._stop = False
        self._error: Optional[BaseException] = None
        # detlint: allow[DET003] — host-side checkpoint writer beside the device sweep
        self._thread = threading.Thread(
            target=self._run, name="madsim-checkpointer", daemon=True)
        self._thread.start()

    def submit(self, state) -> None:
        with self._cond:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            self._pending = state
            self._cond.notify_all()

    def _run(self) -> None:
        import jax as _jax

        from ..engine import checkpoint as ckpt

        while True:
            with self._cond:
                while self._pending is None and not self._stop:
                    self._cond.wait()
                if self._pending is None:
                    return
                state, self._pending = self._pending, None
                self._busy = True
            try:
                # Pull to host FIRST and drop the device reference: holding
                # the device pytree through the disk write would pin up to
                # a full extra state of HBM while the sweep runs ahead.
                host_state = _jax.device_get(state)
                state = None
                ckpt.save(self._eng, host_state, self._path,
                          extra_meta=self._meta)
                exc = None
            except BaseException as e:  # noqa: BLE001 — surfaced at submit/flush
                exc = e
            with self._cond:
                self._busy = False
                if exc is not None:
                    self._error = exc
                self._cond.notify_all()

    def flush_and_close(self, suppress_errors: bool = False) -> None:
        """Wait until every submitted snapshot is durable, then stop.

        ``suppress_errors`` logs a deferred writer failure instead of
        raising — for finally blocks where an in-flight exception must not
        be masked by a checkpoint-write error."""
        with self._cond:
            while self._pending is not None or self._busy:
                self._cond.wait()
            self._stop = True
            self._cond.notify_all()
        self._thread.join()
        if self._error is not None:
            if suppress_errors:
                import logging

                logging.getLogger("madsim_tpu.sweep").warning(
                    "checkpoint write failed during sweep teardown: %r",
                    self._error)
            else:
                raise self._error


@dataclasses.dataclass
class SweepResult:
    """Outcome of a sharded seed sweep."""

    seeds: np.ndarray            # the (unpadded) seed vector
    bug: np.ndarray              # per-seed bug flag
    observations: Dict[str, np.ndarray]  # engine + actor metrics, per seed
    steps_run: int               # executed chunks * chunk_steps
    n_devices: int
    # Occupancy telemetry (docs/perf.md "world recycling"): the active
    # world count after each chunk, and the fraction of issued slot-steps
    # that advanced a live world — useful/(sum over chunks of
    # batch_width*chunk_steps). Frozen worlds riding masked in the batch
    # are the difference; 1.0 means the mesh never ran a frozen slot.
    n_active_history: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.int64))
    world_utilization: float = 0.0
    # The chunk index each ``n_active_history`` entry was MEASURED at
    # (0-based count of executed chunks, aligned entrywise). Under the
    # pipelined loop the host reads a measurement only after dispatching
    # the next superstep, so the decision taken at dispatch d is based on
    # the entry measured at some chunk < d — up to one superstep behind.
    # The measurement sequence itself is per-chunk and identical to the
    # serial loop's; entries are strictly increasing (tier-1-tested).
    n_active_chunks: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.int64))
    # Orchestration telemetry (docs/perf.md "Pipelined orchestration"):
    # dispatch counts, superstep fan-in, and the host/device wall split
    # of the chunk loop. Recorded into bench_results.json under
    # configs.*.sweep_loop. Keys: pipelined, chunks, dispatches,
    # chunks_per_dispatch, dispatches_per_seed, dispatch_depth,
    # device_wait_s, host_decision_s, dispatch_s, retire_wait_s,
    # scalar_fetches, retire_fetches, loop_wall_s, superstep_max,
    # chunk_steps.
    loop_stats: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Fault-schedule fingerprint (sha256 over the padded rows, or of
    # b"none"): rides the result so repro banners and bundles can assert
    # the replay used the same schedule — a seed alone does not pin the
    # trajectory when schedules vary per run.
    faults_sha256: Optional[str] = None

    @property
    def failing_seeds(self) -> List[int]:
        return [int(s) for s in self.seeds[self.bug]]

    @property
    def metrics(self) -> Optional[Dict[str, Any]]:
        """Simulation metrics frames (docs/observability.md), or ``None``
        when the sweep ran metrics-off: ``{"per_seed": {field: (n, ...)
        array}, "aggregate": {field: int | [int]}}``. Per-seed rows are
        attributed through the same slot→seed machinery as every other
        observation, so they survive recycling/compaction; the aggregate
        is the fleet sum (``bench.py`` records it as ``sim_metrics``)."""
        from ..obs.metrics import aggregate_metrics, metrics_from_observations

        per_seed = metrics_from_observations(self.observations)
        if per_seed is None:
            return None
        return {"per_seed": per_seed, "aggregate": aggregate_metrics(per_seed)}

    def repro_banner(self) -> Optional[str]:
        """The failing-seed reproduction hint (`runtime/mod.rs:192-199`)."""
        if not self.failing_seeds:
            return None
        banner = ("note: run with environment variable "
                  f"MADSIM_TEST_SEED={self.failing_seeds[0]} to reproduce "
                  f"this failure ({len(self.failing_seeds)} failing seeds "
                  "total)")
        if self.faults_sha256 is not None:
            banner += (f"\nnote: fault-schedule sha256: "
                       f"{self.faults_sha256[:16]} (replay must use the "
                       "same schedule)")
        return banner


def sweep(actor: Any, cfg: EngineConfig, seeds, faults: Optional[np.ndarray] = None,
          mesh: Optional[Mesh] = None, chunk_steps: int = 512,
          max_steps: int = 1_000_000, stop_on_first_bug: bool = False,
          engine: Optional[DeviceEngine] = None,
          checkpoint_path: Optional[str] = None,
          checkpoint_every_chunks: int = 0,
          resume: bool = False,
          compact: bool = False,
          recycle: bool = False,
          batch_worlds: Optional[int] = None,
          pipeline: bool = True,
          superstep_max: int = 16) -> SweepResult:
    """Run one simulation per seed, sharded over the mesh, to completion.

    The loop is a slot-occupancy model: the device batch is a fixed set of
    world *slots*, each holding a live world, a finished one awaiting
    retirement, or (after retirement) a recycled world for a fresh seed.
    Per chunk the host learns exactly two scalars — "any bug?" and "how
    many slots are active?" — and every occupancy decision (shrink,
    retire, refill) runs as an on-device program keyed off that count.

    ``pipeline`` (default True): dispatch-ahead, superstepped
    orchestration (docs/perf.md "Pipelined orchestration"). Up to
    ``superstep_max`` chunks fold into one jitted dispatch whose early
    exits (all retired, occupancy at the recycle/compact threshold, bug
    under ``stop_on_first_bug``) run on device, and the host issues the
    next superstep BEFORE reading the previous one's scalars, so XLA's
    async dispatch keeps the device queue non-empty while the host
    decides. K adapts to the observed retirement rate: it doubles
    (capped at ``superstep_max``) while supersteps run to plan and
    settles to the chunks a cut-short superstep actually ran — all
    inputs are sim outputs, so the dispatch schedule is deterministic
    per (seeds, config), and K rides as a traced scalar so the schedule
    never recompiles. A superstep dispatched past a stop/threshold point runs
    ZERO chunks (its entry condition is false), so one-dispatch-stale
    occupancy reads never advance, retire, or refill a world the serial
    loop would not have: results — including retirement attribution —
    are bitwise identical to ``pipeline=False`` (the serial per-chunk
    reference loop, tier-1-tested for every actor family). Decisions are
    additionally epoch-guarded: after a refill/shrink, occupancy reads
    from supersteps dispatched before it are ignored (they ran zero
    chunks), so a stale trigger can never re-fire on the slots it just
    refilled.

    Preemption survival: with ``checkpoint_path`` set, the (padded) world
    state is written every ``checkpoint_every_chunks`` chunks (and at the
    end); with ``resume=True`` an existing checkpoint is loaded instead of
    re-initializing, and the sweep continues bit-exactly where it stopped —
    resumed trajectories equal an unbroken run's (the state carries every
    RNG cursor and queue). ``max_steps`` counts steps issued by THIS call.
    Under pipelining the snapshot cadence is superstep-granular (K caps at
    ``checkpoint_every_chunks``), and the submitted state is always a
    COMPLETED superstep output the writer can read while later supersteps
    run — donation stays disabled whenever a writer is attached, exactly
    as in the serial loop.

    Donation caveat: without checkpointing, the chunk runner DONATES its
    input state (XLA steps the batch in place — roughly double the W per
    HBM; a donated state is dead after the call). Checkpointing turns
    donation off, because the async writer still reads the submitted
    pre-chunk state while the next chunk runs — so a checkpointed sweep
    keeps the old double-buffered peak. Budget W accordingly when
    enabling ``checkpoint_path``.

    ``compact``: straggler compaction (docs/perf.md "the straggler
    tail"). A chunked batch runs until its SLOWEST world finishes, so
    once most worlds are done the chip mostly advances frozen state.
    When the active count drops below half the batch, the sweep gathers
    the active worlds to the front — a stable active-first ``argsort``
    computed INSIDE a jitted, mesh-resident program, so no per-world
    state (not even ``state.active``) crosses to the host and no reshard
    round trip follows — retires the frozen tail (its observations are
    sliced out ON DEVICE and pulled alone, never the full batch), and
    continues on a power-of-two-smaller batch. Worlds' trajectories are
    position-independent, so results are bitwise identical to the
    uncompacted run (tested). Disabled automatically when checkpointing
    (a shrunken state cannot resume into the full-shape contract).

    ``recycle`` + ``batch_worlds``: world recycling / seed streaming
    (docs/perf.md "world recycling"). Instead of only shrinking, retired
    slots are REFILLED with freshly initialized worlds for the next
    seeds from a host-side cursor: the sweep holds ``batch_worlds``
    slots (rounded to the mesh) and streams the full seed list through
    them, keeping utilization near 100% while any seeds remain; once the
    cursor is dry it falls back to shrink compaction for the tail. Each
    refilled world is bit-identical to an independent run of its seed
    (tested). This is the shape for open-ended hunts —
    ``stop_on_first_bug`` sweeps over huge seed spaces on a bounded
    memory footprint. On an early stop, seeds never admitted report
    zeroed observations (``bug=False``). Incompatible with
    checkpointing: the seed cursor and retired observations are host
    state a resume could not re-attribute (raises ``ValueError``).

    Occupancy telemetry rides the result: ``SweepResult.n_active_history``
    (per-chunk active counts, with ``n_active_chunks`` recording the
    chunk index each entry was measured at), ``world_utilization``
    (live-world steps / issued slot-steps, mesh padding included), and
    ``loop_stats`` (the dispatch-count / host-stall breakdown of the
    orchestration loop).
    """
    from ..engine import checkpoint as ckpt

    eng = engine if engine is not None else DeviceEngine(actor, cfg)
    mesh = mesh if mesh is not None else seed_mesh()
    n_dev = mesh.devices.size
    seeds = np.asarray(seeds, np.uint64)
    n = seeds.shape[0]

    if recycle and checkpoint_path:
        raise ValueError(
            "recycle=True cannot be combined with checkpointing: the seed "
            "cursor and retired observations live on the host, so a "
            "resumed sweep could not re-attribute recycled slots")
    if superstep_max < 1:
        raise ValueError("superstep_max must be >= 1")

    # Batch width: a multiple of the mesh. Plain sweeps hold every seed at
    # once; recycled sweeps hold batch_worlds slots and stream the rest.
    full_w = n + ((-n) % n_dev)
    if recycle and batch_worlds is not None:
        w0 = min(max(1, int(batch_worlds)), max(n, 1))
        w0 += (-w0) % n_dev
        w0 = min(w0, full_w)
    else:
        w0 = full_w
    # Pad the seed-id space to the batch width (padded worlds are real
    # simulations of dummy seeds; their results are sliced off below).
    n_ids = max(n, w0)
    seeds_p = (np.concatenate([seeds, seeds[:1].repeat(n_ids - n)])
               if n_ids > n else seeds)

    faults_p = faults
    per_world_faults = False
    if faults is not None:
        faults_p = np.asarray(faults, np.int32)
        if faults_p.ndim == 2:
            if faults_p.shape[-1] != 4:
                raise ValueError(
                    f"shared fault schedule must be (F, 4) rows of "
                    f"[time_us, op, a, b]; got shape {faults_p.shape}")
        elif faults_p.ndim == 3:
            if faults_p.shape[0] != n or faults_p.shape[-1] != 4:
                raise ValueError(
                    f"per-world fault schedules must be (n_seeds, F, 4) "
                    f"with n_seeds={n}; got shape {faults_p.shape}")
            per_world_faults = True
            if n_ids > n:
                faults_p = np.concatenate(
                    [faults_p, faults_p[:1].repeat(n_ids - n, axis=0)],
                    axis=0)
        else:
            raise ValueError(
                f"faults must be (F, 4) or (n_seeds, F, 4); got "
                f"{faults_p.ndim}-D shape {faults_p.shape}")

    def batch_faults(ids: np.ndarray):
        """Fault rows for the worlds holding the given seed ids."""
        if faults_p is None:
            return None
        return faults_p[ids] if per_world_faults else faults_p

    import hashlib
    import os
    from time import perf_counter

    def _clk() -> float:
        # Wall-clock telemetry of the orchestration loop itself (host
        # side); never feeds a simulation decision.
        return perf_counter()  # detlint: allow[DET001]

    # World identity travels with the checkpoint: resuming under different
    # seeds OR fault schedules would silently attribute results (repro
    # banners!) to inputs that never produced them.
    faults_key = (np.ascontiguousarray(faults_p).tobytes()
                  if faults_p is not None else b"none")
    seeds_meta = {
        "seeds_sha256": hashlib.sha256(seeds_p.tobytes()).hexdigest(),
        "faults_sha256": hashlib.sha256(faults_key).hexdigest(),
    }

    if resume and checkpoint_path and os.path.exists(checkpoint_path):
        state = ckpt.load(eng, checkpoint_path, expect_extra=seeds_meta)
        if np.asarray(state.now).shape[0] != seeds_p.shape[0]:
            raise ckpt.CheckpointError(
                f"checkpoint holds {np.asarray(state.now).shape[0]} worlds, "
                f"sweep expects {seeds_p.shape[0]} (seeds + mesh padding)")
        state = shard_worlds(state, mesh)
    else:
        state = shard_worlds(
            eng.init(seeds_p[:w0], faults=batch_faults(np.arange(w0))), mesh)

    writer = (_AsyncCheckpointer(eng, checkpoint_path, seeds_meta)
              if checkpoint_path else None)
    # Donate the chunk state unless a checkpoint writer holds references
    # to it between chunks (the writer reads the submitted pytree from a
    # background thread; donating would hand XLA its buffers mid-read).
    donate = writer is None
    compact = compact and writer is None  # shrunken state cannot resume
    steps = 0
    chunks = 0                         # executed chunk bodies
    c_max = -(-max_steps // chunk_steps)  # serial loop's chunk budget
    # Chunk counter at the last writer submission — a counter, not an
    # object ref: a pytree ref here would pin a full extra device state
    # between checkpoints. Chunk-count identity implies state identity
    # under a writer, because recycle is rejected and compact disabled
    # whenever one is attached (no state change without a chunk).
    submitted_chunks = -1
    w_cur = w0                         # current batch width (slot count)
    cursor = w0                        # next seed id the stream admits
    # Slot→seed-id map, DEVICE-resident: compaction permutes it with the
    # state in the same on-device program, so the host never needs the
    # permutation (or state.active) to keep attribution straight. -1
    # marks a dead slot (retired world still riding in the batch).
    idx = shard_worlds(jnp.arange(w_cur, dtype=jnp.int32), mesh)
    reordered = False                  # batch rows still == seed order?
    retired: Dict[str, list] = {}      # field → retired observation batches
    retired_rows: List[np.ndarray] = []
    n_active_hist: List[int] = []
    n_active_chunk: List[int] = []     # chunk index each entry measured at
    issued_slot_steps = 0              # sum over chunks of width*chunk_steps
    live_world_steps = 0               # steps that advanced a live world
    perf = {"device_wait_s": 0.0, "host_decision_s": 0.0, "dispatch_s": 0.0,
            "retire_wait_s": 0.0, "scalar_fetches": 0, "retire_fetches": 0,
            "dispatches": 0, "dispatch_depth": 0}
    t_loop0 = _clk()

    def retire(obs_slice: Dict[str, np.ndarray], rows: np.ndarray) -> None:
        """Record final observations for rows leaving the batch (dead
        slots — already retired earlier — are filtered out by idx)."""
        nonlocal live_world_steps
        keep = rows >= 0
        if not keep.all():
            rows = rows[keep]
            obs_slice = {k: np.asarray(v)[keep] for k, v in obs_slice.items()}
        if rows.size == 0:
            return
        live_world_steps += int(np.asarray(obs_slice["steps"]).sum())
        retired_rows.append(rows)
        for k, v in obs_slice.items():
            retired.setdefault(k, []).append(np.asarray(v))

    def fetch_retire(handles) -> None:
        """Materialize a deferred on-device retirement slice and record
        it. The pull covers ONLY the (bucketed) frozen-tail rows — the
        full per-world observation arrays never cross to the host."""
        obs_t, idx_t, tail_len = handles
        t0 = _clk()
        obs_h, idx_h = _fetch((obs_t, idx_t))
        perf["retire_wait_s"] += _clk() - t0
        perf["retire_fetches"] += 1
        retire({k: np.asarray(v)[:tail_len] for k, v in obs_h.items()},
               np.asarray(idx_h)[:tail_len])

    def do_refill(n_act: int):
        """World recycling: stable active-first partition on device,
        retire the frozen tail, refill it with the next seeds from the
        cursor. Only the n_active scalar (already on host) shapes the
        refill mask; the tail observations are sliced on device and
        returned as un-fetched handles so the pull can overlap later
        dispatches."""
        nonlocal state, idx, cursor, reordered
        state, idx = _compactor(eng, mesh, w_cur, w_cur)(state, idx)
        reordered = True
        tail_len = w_cur - n_act
        rows = min(_pow2_at_least(tail_len), _pow2_at_least(w_cur))
        obs_t, idx_t = _tail_observer(eng, mesh, w_cur, rows)(
            state, idx, jnp.int32(n_act))
        take = min(tail_len, n_ids - cursor)
        repl = np.full(w_cur, -1, np.int32)
        repl[n_act:n_act + take] = np.arange(
            cursor, cursor + take, dtype=np.int32)
        cursor += take
        mask = np.zeros(w_cur, bool)
        mask[n_act:n_act + take] = True
        fill_ids = np.maximum(repl, 0)
        state = shard_worlds(
            eng.refill(state, mask, seeds_p[fill_ids],
                       faults=batch_faults(fill_ids)), mesh)
        idx = jnp.where(jnp.asarray(np.arange(w_cur) >= n_act),
                        jnp.asarray(repl), idx)
        return obs_t, idx_t, tail_len

    def do_shrink(new_w: int):
        """Shrink compaction, fully on device: permutation, split, and
        the live batch's mesh placement all happen inside one jitted
        program (out_shardings = the world sharding). Returns the frozen
        tail's observation handles, un-fetched."""
        nonlocal state, idx, reordered, w_cur
        (state, idx), (frozen, fidx) = \
            _compactor(eng, mesh, w_cur, new_w)(state, idx)
        reordered = True
        tail_len = w_cur - new_w
        w_cur = new_w
        obs_t, idx_t = _observer(eng)(frozen, fidx)
        return obs_t, idx_t, tail_len

    try:
        if pipeline:
            # -- pipelined, superstepped orchestration ---------------------
            k_cur = 1                  # adaptive superstep size (chunks)
            epoch = 0                  # bumps on every refill/shrink
            epoch_fresh = True         # next dispatch is its epoch's first
            ckpt_mark = 0              # checkpoint cadence periods covered
            inflight: Optional[_Flight] = None
            pending_retires: list = []
            stop = False

            def threshold() -> int:
                """The on-device early-exit occupancy for the NEXT
                dispatch: the serial loop's trigger boundary (half the
                batch) whenever a refill or shrink could actually fire,
                else 0 (run until all retired)."""
                if recycle and cursor < n_ids:
                    return w_cur // 2
                if ((compact or recycle) and w_cur % 2 == 0
                        and (w_cur // 2) % n_dev == 0):
                    return w_cur // 2
                return 0

            def dispatch(reserve: int = 0) -> None:
                """Issue one superstep on the CURRENT state (enqueue
                only — never blocks on device results). ``reserve`` is
                the planned chunk count of a superstep already in the
                device queue but not yet read: those chunks may still
                execute, so the budget must treat them as spent or a
                binding ``max_steps`` overruns the serial loop's
                ``c_max`` chunk ceiling."""
                nonlocal state, inflight, epoch_fresh
                budget = c_max - chunks - reserve
                k = max(1, min(k_cur, budget, superstep_max))
                if writer is not None and checkpoint_every_chunks:
                    k = min(k, checkpoint_every_chunks)
                # The first dispatch of each occupancy epoch mirrors the
                # serial cadence exactly: one chunk runs before occupancy
                # is re-evaluated, even if a refill landed at/below the
                # threshold. Speculative dispatches keep min_one=False
                # so a stale one stays a pass-through no-op. K itself is
                # a traced scalar of the (per min_one variant) single
                # compiled runner, not a compile key.
                if epoch_fresh:
                    k = 1
                runner = sharded_superstep(eng, mesh, chunk_steps,
                                           superstep_max, donate,
                                           min_one=epoch_fresh)
                epoch_fresh = False
                t0 = _clk()
                state, any_bug, n_active, k_done, hist = runner(
                    state, jnp.int32(threshold()),
                    jnp.asarray(bool(stop_on_first_bug)), jnp.int32(k))
                perf["dispatch_s"] += _clk() - t0
                perf["dispatches"] += 1
                inflight = _Flight(
                    any_bug, n_active, k_done, hist, k, w_cur, epoch,
                    state if writer is not None else None)

            # max_steps <= 0 means a zero-chunk budget: the serial loop
            # never enters its body, so the pipelined loop must not
            # force a min_one first chunk either.
            if c_max > 0:
                dispatch()
            while inflight is not None:
                prev, inflight = inflight, None
                # Dispatch-ahead: superstep k+1 enters the device queue
                # BEFORE superstep k's scalars are read, so the device
                # never idles on host decision latency. If k's scalars
                # turn out to demand a stop/refill, k+1 is a bitwise
                # no-op (its entry condition is already false).
                if not stop and chunks + prev.planned < c_max:
                    dispatch(reserve=prev.planned)
                t0 = _clk()
                bug_h, n_act_h, k_done_h, hist_h = _fetch(
                    (prev.any_bug, prev.n_active, prev.k_done, prev.hist))
                perf["device_wait_s"] += _clk() - t0
                perf["scalar_fetches"] += 1
                perf["dispatch_depth"] = max(
                    perf["dispatch_depth"], 1 if inflight is not None else 0)
                # Retirement pulls deferred from earlier refills/shrinks:
                # drain them here, where the loop blocks anyway.
                while pending_retires:
                    fetch_retire(pending_retires.pop(0))
                t0 = _clk()
                k_done = int(k_done_h)
                n_act = int(n_act_h)
                hist_np = np.asarray(hist_h)
                for j in range(k_done):
                    n_active_hist.append(int(hist_np[j]))
                    n_active_chunk.append(chunks + j)
                chunks += k_done
                steps = chunks * chunk_steps
                issued_slot_steps += prev.w * chunk_steps * k_done
                if prev.epoch == epoch:
                    # Superstep sizing adapts to the observed retirement
                    # rate: double while supersteps run to plan (slow
                    # start), and after an early exit settle on the
                    # chunks it actually ran — the measured
                    # chunks-per-decision of this workload. Deterministic
                    # — every input is a sim output; and since K is a
                    # traced scalar, the schedule costs no recompiles.
                    if k_done == prev.planned:
                        k_cur = min(k_cur * 2, superstep_max)
                    else:
                        k_cur = max(k_done, 1)
                if writer is not None and checkpoint_every_chunks and \
                        chunks // checkpoint_every_chunks > ckpt_mark:
                    # Async: the pull + write overlap later supersteps'
                    # device work; the submitted state is a COMPLETED
                    # superstep output (donation is off with a writer).
                    writer.submit(prev.out_state)
                    submitted_chunks = chunks
                    ckpt_mark = chunks // checkpoint_every_chunks
                if prev.epoch == epoch and not stop:
                    more_seeds = cursor < n_ids
                    if n_act == 0 and not more_seeds:
                        stop = True
                    elif stop_on_first_bug and bool(bug_h):
                        stop = True
                    elif recycle and more_seeds and n_act <= w_cur // 2:
                        pending_retires.append(do_refill(n_act))
                        epoch += 1
                        epoch_fresh = True
                    else:
                        new_w = _compact_bucket(n_act, w_cur, n_dev)
                        if (compact or (recycle and not more_seeds)) \
                                and new_w < w_cur:
                            pending_retires.append(do_shrink(new_w))
                            epoch += 1
                            epoch_fresh = True
                perf["host_decision_s"] += _clk() - t0
                if stop:
                    break
                if inflight is None and chunks < c_max:
                    dispatch()
            while pending_retires:
                fetch_retire(pending_retires.pop(0))
        else:
            # -- serial per-chunk reference loop ---------------------------
            runner = sharded_engine(eng, mesh, chunk_steps, donate=donate)
            while steps < max_steps:
                t0 = _clk()
                state, any_bug, n_active = runner(state)
                perf["dispatch_s"] += _clk() - t0
                perf["dispatches"] += 1
                steps += chunk_steps
                chunks += 1
                issued_slot_steps += w_cur * chunk_steps
                if writer is not None and checkpoint_every_chunks and \
                        chunks % checkpoint_every_chunks == 0:
                    # Async: the pull + write overlap the next chunk's
                    # device work; the loop never blocks on the filesystem.
                    writer.submit(state)
                    submitted_chunks = chunks
                t0 = _clk()
                n_act_h, bug_h = _fetch((n_active, any_bug))
                perf["device_wait_s"] += _clk() - t0
                perf["scalar_fetches"] += 1
                t0 = _clk()
                n_act = int(n_act_h)
                n_active_hist.append(n_act)
                n_active_chunk.append(chunks - 1)
                more_seeds = cursor < n_ids
                if n_act == 0 and not more_seeds:
                    perf["host_decision_s"] += _clk() - t0
                    break
                if stop_on_first_bug and bool(bug_h):
                    perf["host_decision_s"] += _clk() - t0
                    break
                if recycle and more_seeds and n_act <= w_cur // 2:
                    handles = do_refill(n_act)
                    perf["host_decision_s"] += _clk() - t0
                    fetch_retire(handles)
                    continue
                new_w = _compact_bucket(n_act, w_cur, n_dev)
                if (compact or (recycle and not more_seeds)) \
                        and new_w < w_cur:
                    handles = do_shrink(new_w)
                    perf["host_decision_s"] += _clk() - t0
                    fetch_retire(handles)
                else:
                    perf["host_decision_s"] += _clk() - t0
        if writer is not None and submitted_chunks != chunks:
            writer.submit(state)  # the final state is always durable
        if writer is not None:
            writer.flush_and_close()
            writer = None
    finally:
        if writer is not None:  # exception path: don't mask it
            writer.flush_and_close(suppress_errors=True)

    obs_live = eng.observe(state)
    idx_h = np.asarray(_fetch(idx))
    live_keep = idx_h >= 0
    live_world_steps += int(np.asarray(obs_live["steps"])[live_keep].sum())
    # Scatter whenever the live batch does not cover the full id space in
    # seed order — after any reorder/retirement, OR when a recycled sweep
    # exited (stop_on_first_bug / max_steps) before its first refill, so
    # only the first w0 < n_ids seeds were ever admitted.
    if reordered or retired_rows or w0 < n_ids:
        rows = np.concatenate(retired_rows + [idx_h[live_keep]])
        obs = {}
        for k, v_live in obs_live.items():
            v_live = np.asarray(v_live)[live_keep]
            merged = np.concatenate(retired.get(k, []) + [v_live], axis=0)
            # Zeros, not empty: an early stop (stop_on_first_bug) can
            # leave streamed seeds never admitted — they report zeroed
            # observations (bug=False) rather than garbage.
            out = np.zeros((n_ids,) + merged.shape[1:], merged.dtype)
            out[rows] = merged
            obs[k] = out
    else:
        obs = obs_live
    obs = {k: v[:n] for k, v in obs.items()}
    util = (live_world_steps / issued_slot_steps if issued_slot_steps
            else 0.0)
    loop_stats = {
        "pipelined": bool(pipeline),
        "superstep_max": int(superstep_max) if pipeline else 1,
        "chunk_steps": int(chunk_steps),
        "chunks": int(chunks),
        "dispatches": int(perf["dispatches"]),
        "chunks_per_dispatch": round(
            chunks / max(perf["dispatches"], 1), 3),
        "dispatches_per_seed": round(
            perf["dispatches"] / max(n, 1), 6),
        "dispatch_depth": int(perf["dispatch_depth"]),
        "device_wait_s": round(perf["device_wait_s"], 6),
        "host_decision_s": round(perf["host_decision_s"], 6),
        "dispatch_s": round(perf["dispatch_s"], 6),
        "retire_wait_s": round(perf["retire_wait_s"], 6),
        "scalar_fetches": int(perf["scalar_fetches"]),
        "retire_fetches": int(perf["retire_fetches"]),
        "loop_wall_s": round(_clk() - t_loop0, 6),
    }
    return SweepResult(seeds=seeds, bug=obs["bug"], observations=obs,
                       steps_run=steps, n_devices=n_dev,
                       n_active_history=np.asarray(n_active_hist, np.int64),
                       world_utilization=util,
                       n_active_chunks=np.asarray(n_active_chunk, np.int64),
                       loop_stats=loop_stats,
                       faults_sha256=(seeds_meta["faults_sha256"]
                                      if faults is not None else None))


def _compact_bucket(n_active: int, w_cur: int, n_dev: int) -> int:
    """Largest power-of-two shrink of ``w_cur`` that still holds every
    active world and stays a multiple of the mesh; ``w_cur`` when no
    halving is possible (compaction triggers only below half-occupancy)."""
    w = w_cur
    # w//2 % n_dev == 0 already implies the w//2 >= n_dev floor (any
    # positive value below n_dev fails the modulus test).
    while w % 2 == 0 and w // 2 >= max(n_active, 1) and w // 2 % n_dev == 0:
        w //= 2
    return w


def _pow2_at_least(n: int) -> int:
    """Smallest power of two >= n (>= 1): bucketed retirement-gather
    widths, so the tail observer compiles at most log2(W) programs."""
    b = 1
    while b < n:
        b <<= 1
    return b


@jax.jit
def _permute_worlds(state, perm):
    """Reorder the world axis of a whole state pytree on device."""
    return jax.tree.map(lambda x: x[perm], state)


def _compactor(eng: DeviceEngine, mesh: Mesh, w: int, new_w: int):
    """Compile (and cache per engine) the on-device compaction program.

    The program computes the stable active-first permutation of a
    width-``w`` batch with ``jnp.argsort`` ON DEVICE, applies it to the
    state and the slot→seed index vector via :func:`_permute_worlds`, and
    (for ``new_w < w``) splits off the frozen tail. ``out_shardings``
    pins every output to the mesh's world sharding, so compaction needs
    no host pull of ``state.active``, no host-built permutation, and no
    ``device_put`` reshard afterwards — the host contributes only the
    ``n_active`` scalar the chunk runner already returned. Shrink widths
    are power-of-two buckets, so at most log2(W) programs compile.

    Deliberately NOT donated: the permutation is a gather, whose output
    XLA can never alias onto its input (an in-place permute would read
    clobbered rows), so donating here frees nothing and trips the
    "donated buffer not usable" warning on every leaf. Compaction
    transiently holds two batches; the chunk runner — where the state
    lives 99% of the time — is the donated path.
    """
    cache = eng.__dict__.setdefault("_compactor_cache", {})
    key = (mesh, w, new_w)
    if key in cache:
        return cache[key]

    def compacted(state, idx):
        order = jnp.argsort((~state.active).astype(jnp.int32), stable=True)
        state, idx = _permute_worlds((state, idx), order)
        if new_w == w:
            return state, idx
        live = jax.tree.map(lambda x: x[:new_w], (state, idx))
        frozen = jax.tree.map(lambda x: x[new_w:], (state, idx))
        return live, frozen

    fn = jax.jit(compacted, out_shardings=world_sharding(mesh))
    cache[key] = fn
    return fn


def _tail_observer(eng: DeviceEngine, mesh: Mesh, w: int, rows: int):
    """Compile (and cache per engine) the frozen-tail retirement gather.

    One jitted program slices ``rows`` observation rows starting at a
    dynamic ``start`` out of a width-``w`` batch — gathering INSIDE the
    device program via ``DeviceEngine.observe_device`` — so retirement
    pulls only the (bucketed) frozen-tail rows across the host boundary
    instead of the full per-world observation arrays. ``rows`` is a
    power-of-two bucket (bounded compiles); indices past the batch clamp
    to the last row and the caller slices the pull to the true tail
    length. The slot→seed index vector rides the same gather so
    attribution needs no second pull.
    """
    cache = eng.__dict__.setdefault("_tail_observer_cache", {})
    key = (mesh, w, rows)
    if key in cache:
        return cache[key]

    def tail(state, idx, start):
        take = jnp.clip(start + jnp.arange(rows, dtype=jnp.int32), 0, w - 1)
        obs = {k: jnp.take(v, take, axis=0)
               for k, v in eng.observe_device(state).items()}
        return obs, jnp.take(idx, take, axis=0)

    fn = jax.jit(tail)
    cache[key] = fn
    return fn


def _observer(eng: DeviceEngine):
    """Cached jit of ``observe_device`` for an already-split frozen batch
    (the shrink-compaction tail): builds the observation dict on device
    so the host pull covers exactly the retiring rows."""
    fn = eng.__dict__.get("_observer_fn")
    if fn is None:
        fn = jax.jit(lambda s, i: (eng.observe_device(s), i))
        eng.__dict__["_observer_fn"] = fn
    return fn
