"""Mesh construction and world-state sharding helpers."""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

WORLD_AXIS = "worlds"


def seed_mesh(devices: Optional[Sequence[jax.Device]] = None,
              n_devices: Optional[int] = None) -> Mesh:
    """A 1-D mesh over ``devices`` with the world axis as its only dim.

    Seed-sweep state has no model axes to shard — worlds are independent —
    so a flat mesh is the right topology; on a pod slice the axis simply
    spans all chips (and all hosts under multi-process JAX).
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (WORLD_AXIS,))


def shard_worlds(state, mesh: Mesh):
    """Place a batched WorldState so its leading axis is split over the mesh.

    Every leaf of the engine state carries the world axis first, so a single
    `PartitionSpec(WORLD_AXIS)` shards the entire pytree; XLA then runs the
    vmapped step on each shard with no cross-chip traffic.
    """
    sharding = NamedSharding(mesh, P(WORLD_AXIS))
    return jax.device_put(state, sharding)
