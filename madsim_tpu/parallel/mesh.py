"""Mesh construction and world-state sharding helpers.

Two topologies, one sharding rule:

- :func:`seed_mesh` — 1-D ``(worlds,)``: all chips on one interconnect
  domain (single host / single pod slice over ICI).
- :func:`multihost_mesh` — 2-D ``(dcn, worlds)``: the outer axis spans
  hosts (slow DCN links between machines), the inner axis the chips
  within each host (fast ICI). This is the scale-out analog of the
  reference's MADSIM_TEST_JOBS across machines: worlds are independent,
  so the world dimension simply flattens over BOTH axes — and the only
  cross-host traffic is the tiny psum'd bug/active scalars, which ride
  DCN once per chunk while all per-shard stepping stays chip-local.

Every helper (and :func:`madsim_tpu.parallel.sweep.sharded_engine`) keys
off ``mesh.axis_names`` rather than a fixed name, so the same sweep code
runs on either topology unchanged.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

WORLD_AXIS = "worlds"
DCN_AXIS = "dcn"


def seed_mesh(devices: Optional[Sequence[jax.Device]] = None,
              n_devices: Optional[int] = None) -> Mesh:
    """A 1-D mesh over ``devices`` with the world axis as its only dim.

    Seed-sweep state has no model axes to shard — worlds are independent —
    so a flat mesh is the right topology; on a pod slice the axis simply
    spans all chips (and all hosts under multi-process JAX).
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (WORLD_AXIS,))


def multihost_mesh(devices: Optional[Sequence[jax.Device]] = None,
                   n_hosts: Optional[int] = None) -> Mesh:
    """A 2-D ``(dcn, worlds)`` mesh: hosts × chips-per-host.

    Under real multi-process JAX the host grouping comes from each
    device's ``process_index``; otherwise (single process, e.g. the
    virtual CPU mesh) the device list is split evenly into ``n_hosts``
    groups so the DCN axis — and the cross-"host" reduction path — is
    exercised without multi-host hardware.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    by_proc: dict = {}
    for d in devices:
        by_proc.setdefault(d.process_index, []).append(d)
    if len(by_proc) > 1:
        if n_hosts is not None and n_hosts != len(by_proc):
            raise ValueError(
                f"n_hosts={n_hosts} but devices span {len(by_proc)} "
                "processes — the DCN axis is fixed by the real topology")
        groups = [by_proc[p] for p in sorted(by_proc)]
    else:
        n_hosts = n_hosts or 2
        if len(devices) % n_hosts != 0:
            raise ValueError(
                f"{len(devices)} devices do not split over {n_hosts} hosts")
        per = len(devices) // n_hosts
        groups = [devices[i * per:(i + 1) * per] for i in range(n_hosts)]
    if len({len(g) for g in groups}) != 1:
        raise ValueError("hosts expose unequal device counts")
    grid = np.asarray(groups)  # (hosts, chips_per_host)
    return Mesh(grid, (DCN_AXIS, WORLD_AXIS))


def world_spec(mesh: Mesh) -> P:
    """PartitionSpec flattening the world axis over every mesh axis."""
    return P(tuple(mesh.axis_names))


def scalar_spec() -> P:
    """PartitionSpec for mesh-replicated scalars.

    The sweep's chunk/superstep runners reduce their control scalars
    (any-bug, active count, chunks-run) with ``psum`` over every mesh
    axis, so each comes back identical on all devices; likewise the
    occupancy threshold and stop flag ride IN replicated. One named
    helper keeps the in/out specs of both runner flavors in sync.
    """
    return P()


def world_sharding(mesh: Mesh) -> NamedSharding:
    """The NamedSharding splitting the leading world axis over the mesh.

    One sharding covers every leaf of a batched WorldState (trailing axes
    stay unsharded), so it doubles as the ``out_shardings`` of jitted
    programs that must hand back mesh-resident state — e.g. the on-device
    sweep compaction (`parallel/sweep.py`), which would otherwise need a
    host round trip to re-place its permuted output.
    """
    return NamedSharding(mesh, world_spec(mesh))


def shard_worlds(state, mesh: Mesh):
    """Place a batched WorldState so its leading axis is split over the mesh.

    Every leaf of the engine state carries the world axis first, so one
    PartitionSpec over all mesh axes shards the entire pytree; XLA then
    runs the vmapped step on each shard with no cross-chip traffic.
    """
    return jax.device_put(state, world_sharding(mesh))
