"""madsim_tpu — a TPU-native deterministic simulation testing framework.

Capabilities of madsim (the Rust Magical Deterministic Simulator): seeded,
bit-reproducible discrete-event simulation of distributed systems — virtual
time, a simulated network with latency/loss/partition fault injection, node
kill/restart/pause, deterministic RNG, drop-in shims for real async/RPC APIs,
and a multi-seed test harness with a determinism checker.

TPU-native architecture: the host engine (this package's ``core``/``net``)
runs arbitrary Python coroutines one seed at a time; the batched device
engine (``engine``) lifts the decision kernel — next-event selection,
virtual-clock advance, RNG draws, link sampling, fault schedules — into a JAX
step function vmapped over thousands of seeds and sharded across a TPU mesh
(``parallel``). Both draw from the same counter-based Threefry stream
(``ops.threefry``), so randomness is a pure function of (seed, stream, index)
on every backend.
"""
from .core.config import Config, FsConfig, NetConfig, TcpConfig
from .core.context import NoRuntimeError
from .core.futures import Cancelled, ChannelClosed
from .core.rng import DeterminismError
from .core.runtime import Handle, NodeHandle, Runtime, init_logger
from .core.task import Deadlock, JoinHandle, TimeLimitExceeded
from .core.plugin import Simulator, simulator

from .testing import Builder, main, run, test

from . import fs, net, rand, sync, task, time

# Persistent XLA compilation cache opt-in (parallel/compile_cache.py):
# honored at package import so every entry point — bench, tools/, fleet
# worker processes, `make check` — gets it from one env var. Gated so
# the host-only import path stays jax-free when the var is unset. Loaded
# by file path, NOT `from .parallel import ...`: the parallel package
# init pulls in engine.core, which compiles programs at import time —
# jax initializes its cache at the first compile, so the dir must be
# configured before that chain ever starts.
import os as _os

if _os.environ.get("MADSIM_COMPILE_CACHE"):
    from importlib import util as _ilu

    _spec = _ilu.spec_from_file_location(
        "madsim_tpu._compile_cache_boot",
        _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                      "parallel", "compile_cache.py"))
    _mod = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_mod)
    _mod.enable_from_env()

__version__ = "0.1.0"

__all__ = [
    "Config", "NetConfig", "TcpConfig", "FsConfig",
    "Runtime", "Handle", "NodeHandle", "init_logger",
    "Deadlock", "TimeLimitExceeded", "DeterminismError", "NoRuntimeError",
    "Cancelled", "ChannelClosed",
    "Builder", "main", "run", "test",
    "Simulator", "simulator",
    "fs", "net", "rand", "sync", "task", "time",
]
