"""Deterministic global RNG for the host engine.

Reference semantics: one global seeded RNG behind a lock, drawn by every
scheduler/simulator decision (`madsim/src/sim/rand.rs:50-108`), plus a
determinism log/check facility used by ``Runtime.check_determinism``
(`rand.rs:84-107`).

TPU-first redesign: instead of a stateful SmallRng, this is a thin stateful
*cursor* over the counter-based Threefry stream in
:mod:`madsim_tpu.ops.threefry`. The cursor (draw index) is the only mutable
state, so any draw can be replayed or re-derived as a pure function of
``(seed, stream, index)`` — the property the batched device engine relies on.
"""
from __future__ import annotations

import zlib
from typing import Callable, List, Optional

import numpy as np

from ..ops.threefry import (derive_stream_np, seed_to_key,
                            threefry2x32_scalar)

# Named stream ids. The reference draws everything from one SmallRng
# (`rand.rs:50-81`); here each purpose owns an independent Threefry stream so
# any framework decision is addressable as (seed, purpose, draw-index) — the
# property that lets the batched device kernel reproduce host draws exactly
# (SURVEY §7 "bit-exact determinism across backends"). STREAM_GLOBAL is the
# user-visible rng (thread_rng); the others are framework-internal.
STREAM_GLOBAL = 0
STREAM_TIME_BASE = 1
STREAM_SCHED = 2   # executor: ready-pick + per-poll jitter
STREAM_NET = 3     # network: per-message delay, loss, latency
STREAM_FS = 4      # filesystem: I/O latency


def loss_threshold(p: float) -> int:
    """Packet-loss probability → u64 threshold: lost iff draw < threshold.

    Integer compare instead of float ``random() < p`` so the device kernel
    reproduces the decision with pure uint64 ops (no float rounding drift
    between host Python and XLA)."""
    if p <= 0.0:
        return 0
    if p >= 1.0:
        return 1 << 64  # above any u64 draw: always lost
    return int(p * 18446744073709551616.0)  # p * 2**64


class DeterminismError(Exception):
    """Raised by check-mode replay on the first divergent RNG access."""


class GlobalRng:
    """Seeded deterministic RNG with an optional access log for the checker."""

    def __init__(self, seed: int, stream: int = STREAM_GLOBAL):
        self.seed = seed & ((1 << 64) - 1)
        # Scalar-int derive (bit-identical to derive_stream_np, which stays
        # for array callers): four GlobalRngs exist per world, and numpy
        # scalar threefry was a measurable slice of batched world setup.
        stream &= (1 << 64) - 1
        self._k0, self._k1 = threefry2x32_scalar(
            self.seed & 0xFFFFFFFF, self.seed >> 32,
            stream & 0xFFFFFFFF, stream >> 32)
        self._counter = 0
        self._buf: Optional[int] = None
        # Draw backend: native C++ core when built, else scalar Python —
        # both bit-exact with the numpy/jax array paths. The native path
        # keeps the whole cursor (counter + u32 buffer) in a C object so a
        # scheduler decision is one native call (SURVEY §2 ⚙).
        from .. import native as _native

        self._lib = _native.get_lib()
        self._st = (self._lib.rng_new(self._k0, self._k1, 0)
                    if self._lib is not None else None)
        # Determinism checker state (`rand.rs:84-107`): in 'log' mode every
        # access appends hash(value ^ hash(elapsed)); in 'check' mode accesses
        # are compared against the recorded log and the first divergence panics
        # with its virtual timestamp.
        self._mode: Optional[str] = None
        self._log: List[int] = []
        self._check_pos = 0
        self._clock_ns: Callable[[], int] = lambda: 0

    # -- wiring ------------------------------------------------------------
    def set_clock(self, clock_ns: Callable[[], int]) -> None:
        """Install the virtual-clock reader used to timestamp log entries."""
        self._clock_ns = clock_ns

    # -- determinism log ---------------------------------------------------
    def enable_log(self) -> None:
        self._mode = "log"
        self._log = []

    def enable_check(self, log: List[int]) -> None:
        self._mode = "check"
        self._log = log
        self._check_pos = 0

    def take_log(self) -> List[int]:
        log, self._log = self._log, []
        self._mode = None
        return log

    def _observe(self, value: int) -> None:
        if self._mode is None:
            return
        t = self._clock_ns()
        entry = zlib.crc32((value & 0xFFFFFFFF).to_bytes(4, "little") + t.to_bytes(16, "little", signed=True))
        if self._mode == "log":
            self._log.append(entry)
        else:
            if self._check_pos >= len(self._log) or self._log[self._check_pos] != entry:
                raise DeterminismError(
                    f"non-determinism detected at {t / 1e9:.9f}s "
                    f"(RNG access #{self._check_pos} diverged from the recorded run)"
                )
            self._check_pos += 1

    # -- raw draws ---------------------------------------------------------
    def _draw(self) -> int:
        """One u64 Threefry block at the current counter (pure-Python
        cursor; the native cursor advances inside the C object)."""
        x0, x1 = threefry2x32_scalar(
            self._k0, self._k1,
            self._counter & 0xFFFFFFFF, self._counter >> 32)
        self._counter += 1
        return (x1 << 32) | x0

    def next_u32(self) -> int:
        if self._st is not None:
            v = self._lib.rng_next_u32(self._st)
        elif self._buf is not None:
            v, self._buf = self._buf, None
        else:
            block = self._draw()
            v, self._buf = block & 0xFFFFFFFF, block >> 32
        if self._mode is not None:
            self._observe(v)
        return v

    def next_u64(self) -> int:
        if self._st is not None:
            v = self._lib.rng_next_u64(self._st)
        else:
            v = self._draw()
            self._buf = None
        if self._mode is not None:
            self._observe(v)
        return v

    def reserve(self, n: int) -> int:
        """Consume ``n`` whole u64 blocks and return the first counter.

        The bridge backend reserves draw indices at the event's host-side
        program point; the device kernel later evaluates
        ``threefry(key, base..base+n-1)`` — the same values sequential
        :meth:`next_u64` calls would have produced here."""
        if self._st is not None:
            base, _buf = self._lib.rng_get_state(self._st)
            for _ in range(n):
                self._lib.rng_next_u64(self._st)
        else:
            base = self._counter
            self._counter += n
            self._buf = None
        return base

    @property
    def key(self) -> tuple:
        """The derived (k0, k1) stream key (device-kernel parity hook)."""
        return self._k0, self._k1

    # -- distribution helpers (rand-crate-style surface) -------------------
    def gen_range(self, low: int, high: int) -> int:
        """Uniform integer in [low, high). high must be > low."""
        if self._st is not None and self._mode is None:
            try:
                return self._lib.rng_gen_range(self._st, low, high)
            except OverflowError:
                pass  # bounds beyond i64: draw below (no counter consumed)
        width = high - low
        if width <= 0:
            raise ValueError(f"empty range [{low}, {high})")
        return low + self.next_u64() % width

    def random(self) -> float:
        """Uniform float in [0, 1) with 53 bits of precision."""
        if self._st is not None and self._mode is None:
            return self._lib.rng_random(self._st)
        return (self.next_u64() >> 11) * (2.0 ** -53)

    def gen_bool(self, p: float) -> bool:
        if p <= 0.0:
            # Still consume a draw so control flow doesn't change the stream.
            self.random()
            return False
        if p >= 1.0:
            self.random()
            return True
        return self.random() < p

    def gen_range_f64(self, low: float, high: float) -> float:
        return low + self.random() * (high - low)

    def shuffle(self, seq: list) -> None:
        """In-place Fisher-Yates shuffle."""
        for i in range(len(seq) - 1, 0, -1):
            j = self.gen_range(0, i + 1)
            seq[i], seq[j] = seq[j], seq[i]

    def choice(self, seq):
        return seq[self.gen_range(0, len(seq))]

    def gen_bytes(self, n: int) -> bytes:
        words = []
        for _ in range((n + 3) // 4):
            words.append(self.next_u32().to_bytes(4, "little"))
        return b"".join(words)[:n]


def make_numpy_generator(seed: int, stream: int) -> np.random.Generator:
    """A numpy Generator seeded deterministically from (seed, stream).

    For bulk host-side sampling where bit-parity with the device engine is not
    required (e.g. test data generation). The simulation decision path never
    uses this — it draws from :class:`GlobalRng` only.
    """
    k0, k1 = derive_stream_np(*seed_to_key(seed), stream)
    return np.random.Generator(np.random.Philox((int(k0) << 32) | int(k1)))
