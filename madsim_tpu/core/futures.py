"""Future/channel primitives for the deterministic executor.

These are the host-engine analogs of the oneshot/mpsc channels the reference
builds its endpoint mailboxes and relay tasks from (`net/endpoint.rs:241-306`,
`net/mod.rs:224-260`). They are deliberately *not* asyncio futures: wakeups
must route through the simulation's ready queue so the seeded random scheduler
stays the single source of interleaving.

Real-mode bridge: when a SimFuture is awaited while an asyncio event loop is
running (production backend, ``MADSIM_BACKEND=real`` — the sim executor
drives coroutines directly and never has a running loop), ``__await__``
parks on an asyncio future instead of yielding itself. This one hook makes
every primitive built on SimFuture — Channel, Event, Lock, Semaphore,
Notify, oneshot — work unchanged on the real backend, the analog of the
reference passing tokio::sync straight through in std mode
(`madsim-tokio/src/lib.rs:40-52`).
"""
from __future__ import annotations

import asyncio
from collections import deque
from typing import Any, Callable, Deque, List, Optional


class Cancelled(BaseException):
    """Raised when awaiting a future that was cancelled / a closed channel.

    A BaseException subclass for the same reason asyncio.CancelledError is
    (bpo-32528): unmodified code's broad ``except Exception:`` retry loops
    must not be able to swallow cancellation, or timeout scopes and task
    aborts could never tear such code down."""


_PENDING = object()


class SimFuture:
    """A one-shot value container awaitable from simulation coroutines."""

    __slots__ = ("_result", "_exception", "_callbacks")

    def __init__(self):
        self._result: Any = _PENDING
        self._exception: Optional[BaseException] = None
        self._callbacks: List[Callable[["SimFuture"], None]] = []

    def done(self) -> bool:
        return self._result is not _PENDING or self._exception is not None

    def set_result(self, value: Any) -> None:
        if self.done():
            return
        self._result = value
        self._wake()

    def set_exception(self, exc: BaseException) -> None:
        if self.done():
            return
        self._exception = exc
        self._wake()

    def cancel(self) -> None:
        self.set_exception(Cancelled())

    def result(self) -> Any:
        if self._exception is not None:
            raise self._exception
        if self._result is _PENDING:
            raise RuntimeError("future is not done")
        return self._result

    def add_done_callback(self, cb: Callable[["SimFuture"], None]) -> None:
        if self.done():
            cb(self)
        else:
            self._callbacks.append(cb)

    def _wake(self) -> None:
        callbacks, self._callbacks = self._callbacks, []
        for cb in callbacks:
            cb(self)

    def __await__(self):
        if not self.done():
            # Fast probe first: _get_running_loop is a C call returning
            # None outside asyncio — the overwhelmingly common sim case
            # never touches the TLS. The sim context wins unconditionally
            # when both are present: under aio.patched() the shim
            # substitutes asyncio.get_running_loop, so the loop probe alone
            # cannot distinguish the backends.
            loop = asyncio._get_running_loop()
            if loop is not None:
                from . import context

                if context.try_current_handle() is not None:
                    loop = None
            if loop is None:
                yield self  # sim executor: wake via the random scheduler
            else:
                bridge = loop.create_future()

                def _complete(_f, loop=loop, bridge=bridge):
                    # set_result may fire from a worker thread (e.g.
                    # spawn_blocking); only call_soon_threadsafe wakes the
                    # loop's selector from a foreign thread.
                    loop.call_soon_threadsafe(
                        lambda: bridge.set_result(None)
                        if not bridge.done() else None)

                self.add_done_callback(_complete)
                yield from bridge.__await__()
        return self.result()


class ChannelClosed(Exception):
    pass


class Channel:
    """Unbounded FIFO channel (mpsc-style) for simulation coroutines.

    FIFO delivery order is intentional: nondeterminism comes from the
    scheduler's random task pick, never from data structures.
    """

    __slots__ = ("_items", "_waiters", "_closed")

    def __init__(self):
        self._items: Deque[Any] = deque()
        self._waiters: Deque[SimFuture] = deque()
        self._closed = False

    def send(self, item: Any) -> None:
        if self._closed:
            raise ChannelClosed("send on closed channel")
        while self._waiters:
            waiter = self._waiters.popleft()
            if not waiter.done():
                waiter.set_result(item)
                return
        self._items.append(item)

    def try_recv(self):
        if self._items:
            return True, self._items.popleft()
        return False, None

    async def recv(self) -> Any:
        """Receive the next item; raises ChannelClosed when drained+closed."""
        if self._items:
            return self._items.popleft()
        if self._closed:
            raise ChannelClosed()
        fut = SimFuture()
        self._waiters.append(fut)
        try:
            return await fut
        except BaseException:
            # Cancelled receiver (task abort / timeout): give an already-
            # delivered item back to the queue head, or unregister, so the
            # message is not swallowed.
            if fut.done() and fut._exception is None:
                self._items.appendleft(fut._result)
            else:
                try:
                    self._waiters.remove(fut)
                except ValueError:
                    pass
            raise

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        while self._waiters:
            waiter = self._waiters.popleft()
            if not waiter.done():
                waiter.set_exception(ChannelClosed())

    @property
    def closed(self) -> bool:
        return self._closed

    def __len__(self) -> int:
        return len(self._items)
