"""Deterministic single-threaded task executor + node manager.

Reference semantics (`madsim/src/sim/task.rs`):
- Run-to-completion executor whose ready queue is consumed by picking a
  *uniformly random* element (`utils/mpsc.rs:73-83`) — randomized interleaving
  is the chaos amplifier that explores schedules.
- Each task poll advances virtual time by a random 50-100 ns (`task.rs:176-178`).
- Nodes own tasks; kill swaps in a fresh NodeInfo and flags the old one so
  queued runnables are lazily dropped (`task.rs:211-226`); restart re-runs the
  node's init closure (`task.rs:229-240`); pause parks runnables
  (`task.rs:243-261`).
- The block_on loop: drain ready tasks → check root → advance clock to next
  timer, panic on deadlock (`task.rs:121-153`).

Host redesign notes: tasks are Python coroutines driven directly (no asyncio).
Awaitables must bottom out in :class:`~madsim_tpu.core.futures.SimFuture` so
every wakeup routes through this executor's seeded scheduler. A task failure
(other than cancellation) aborts the whole simulation, matching the
reference where a task panic unwinds the single-threaded executor.
"""
from __future__ import annotations

from typing import Any, Callable, Coroutine, Dict, List, Optional

from . import context
from .futures import Cancelled, SimFuture
from .rng import GlobalRng
from .timewheel import TimeRuntime, to_ns

MAIN_NODE_ID = 0


class Deadlock(RuntimeError):
    """All tasks are blocked and no timers are pending."""


class TimeLimitExceeded(RuntimeError):
    pass


class NodeInfo:
    """One generation of a node. Kill creates a fresh generation so stale
    queued tasks (still pointing at the old info) are lazily dropped."""

    __slots__ = ("id", "name", "cores", "killed", "paused", "tasks", "paused_tasks", "restarted_count")

    def __init__(self, node_id: int, name: str, cores: int, restarted_count: int = 0):
        self.id = node_id
        self.name = name
        self.cores = cores
        self.killed = False
        self.paused = False
        # Ordered set (dict keys): kill() iterates this to drop tasks, and
        # drop runs coroutine finally-blocks with visible side effects. A
        # plain set would iterate in address order — nondeterministic across
        # processes — breaking the same-seed-same-trajectory contract.
        self.tasks: Dict["Task", None] = {}
        self.paused_tasks: List["Task"] = []
        self.restarted_count = restarted_count

    def __repr__(self):
        return f"NodeInfo(id={self.id}, name={self.name!r}, gen={self.restarted_count})"


# Public alias used by context.current_task()
TaskInfo = NodeInfo  # current_task() yields the Task; node via task.node


class Task:
    __slots__ = ("id", "coro", "node", "join_future", "cancelled",
                 "_scheduled", "_finished", "_pending_exc", "wake_epoch")

    def __init__(self, task_id: int, coro: Coroutine, node: NodeInfo):
        self.id = task_id
        self.coro = coro
        self.node = node
        self.join_future = SimFuture()
        self.cancelled = False
        self._scheduled = False
        self._finished = False
        # Interrupt support (aio.timeout scopes): an exception to throw
        # into the coroutine at its current await instead of resuming it,
        # plus a wake epoch that invalidates the abandoned await's pending
        # done-callback (the awaited future itself is never touched — it
        # may be shared with other waiters).
        self._pending_exc: Optional[BaseException] = None
        self.wake_epoch = 0
        node.tasks[self] = None

    @property
    def done(self) -> bool:
        return self._finished

    def drop(self) -> None:
        """Abandon the task: close its coroutine (runs finally blocks) and
        resolve its join future with Cancelled so joiners never hang."""
        if self._finished:
            return
        self._finished = True
        self.cancelled = True
        try:
            self.coro.close()
        except (RuntimeError, ValueError):
            # RuntimeError: coroutine ignored GeneratorExit (awaited in a
            # finally). ValueError: the coroutine is currently executing —
            # a task killing its own node. Either way the reference's Rust
            # drop would not run it further; we just abandon it.
            pass
        self.node.tasks.pop(self, None)
        self.join_future.set_exception(Cancelled())


class JoinHandle:
    """tokio-style join handle: awaitable, abortable, detach by dropping."""

    __slots__ = ("_task", "_executor")

    def __init__(self, task: Task, executor: "Executor"):
        self._task = task
        self._executor = executor

    def abort(self) -> None:
        self._executor.abort_task(self._task)

    def is_finished(self) -> bool:
        return self._task.done

    @property
    def id(self) -> int:
        return self._task.id

    def __await__(self):
        return self._task.join_future.__await__()


class Executor:
    """Single-threaded deterministic executor over all simulated nodes."""

    def __init__(self, rng: GlobalRng, time: TimeRuntime):
        self.rng = rng
        self.time = time
        self.queue: List[Task] = []
        self._yields: List[SimFuture] = []
        self.poll_count = 0  # lifetime task polls (events/s observability)
        self.nodes: Dict[int, "Node"] = {}
        self._next_node_id = MAIN_NODE_ID
        self._next_task_id = 0
        self.time_limit_ns: Optional[int] = None
        self._uncaught: Optional[BaseException] = None
        # Optional per-poll trace sink: (task_id, elapsed_ns) tuples. Used
        # by the bridge-equality tests to prove two engines walked the same
        # trajectory; None (the default) costs one attribute check per poll.
        self.trace: Optional[List] = None
        self.main_node = self.create_node(name="main", cores=1, init=None)
        # Hooks the Runtime installs so node lifecycle reaches simulators.
        self.on_reset_node: Optional[Callable[[int], None]] = None
        # Native poll loop (run_all_ready in C, native/madsim_core.cpp):
        # used when nothing needs the Python loop's observability hooks
        # (trace, determinism log) — bit-identical either way.
        from .. import native as _native
        from .futures import _PENDING

        lib = _native.get_lib()
        self._native_ready = getattr(lib, "run_ready", None)
        self._pending_sentinel = _PENDING
        self.running_thread: Optional[int] = None  # set for block_on's span
        self._noop_waiting = False  # a bare-None yield is parked

    # ------------------------------------------------------------------
    # Node management
    # ------------------------------------------------------------------
    def create_node(self, name: Optional[str], cores: int, init) -> "Node":
        node_id = self._next_node_id
        self._next_node_id += 1
        node = Node(node_id, name or str(node_id), cores, init, self)
        self.nodes[node_id] = node
        return node

    def kill(self, node_id: int) -> None:
        node = self._get_node(node_id)
        node.alive = False
        old = node.info
        old.killed = True
        for task in list(old.tasks):
            task.drop()
        old.tasks.clear()
        old.paused_tasks.clear()
        node.info = NodeInfo(old.id, old.name, old.cores, old.restarted_count + 1)
        if self.on_reset_node is not None:
            self.on_reset_node(node_id)

    def restart(self, node_id: int) -> None:
        self.kill(node_id)
        node = self._get_node(node_id)
        node.alive = True
        if node.init is not None:
            self.spawn(node.init(), node.info)

    def pause(self, node_id: int) -> None:
        self._get_node(node_id).info.paused = True

    def resume(self, node_id: int) -> None:
        info = self._get_node(node_id).info
        if not info.paused:
            return
        info.paused = False
        parked, info.paused_tasks = info.paused_tasks, []
        for task in parked:
            self._enqueue(task)

    def _get_node(self, node_id: int) -> "Node":
        try:
            return self.nodes[node_id]
        except KeyError:
            raise KeyError(f"no node with id {node_id}") from None

    # ------------------------------------------------------------------
    # Task management
    # ------------------------------------------------------------------
    def spawn(self, coro: Coroutine, node: Optional[NodeInfo] = None) -> JoinHandle:
        if node is None:
            current = context.try_current_task()
            node = current.node if current is not None else self.main_node.info
        task = Task(self._next_task_id, coro, node)
        self._next_task_id += 1
        self._enqueue(task)
        return JoinHandle(task, self)

    def abort_task(self, task: Task) -> None:
        task.drop()

    def interrupt(self, task: Task, exc: BaseException) -> None:
        """Deliver ``exc`` at the task's current (or next) await point —
        the asyncio task-cancellation model: the WAITER is interrupted,
        the awaited future is untouched (it may be shared). The stale
        await's wakeup is invalidated via the task's wake epoch."""
        if task._finished:
            return
        task._pending_exc = exc
        task.wake_epoch += 1
        self._enqueue(task)

    def _enqueue(self, task: Task) -> None:
        if task._scheduled or task._finished:
            return
        task._scheduled = True
        self.queue.append(task)

    def _wake(self, task: Task) -> None:
        self._enqueue(task)

    def yield_now(self) -> SimFuture:
        """A suspension point without a timer: the awaiting task re-enters
        the ready queue on the scheduler's next turn. Semantically a
        zero-delay sleep (same one-poll scheduling point, same random
        re-pick) at a fraction of the timer heap's cost — the fast path
        under NetSim's per-message processing delay."""
        fut = SimFuture()
        self._yields.append(fut)
        return fut

    def noop_yield(self) -> SimFuture:
        """yield_now for a bare-None yield from third-party code. Marked so
        the drain path also fires due timers and enforces the time limit:
        a loop spin-waiting on bare yields for a timer-driven event would
        otherwise keep run_all_ready alive forever and starve the timer
        heap. Framework yield_now users never spin-wait, so their
        trajectories are untouched."""
        self._noop_waiting = True
        return self.yield_now()

    def _after_noop_drain(self) -> None:
        """Run when parked bare-None yields were just resolved: deliver any
        timers the spinning polls advanced past (BridgeTime's heap is
        device-resident and empty here — a safe no-op), and enforce the
        time limit, which _block_on alone could never reach mid-spin."""
        self._noop_waiting = False
        self.time._fire_due()
        if self.time_limit_ns is not None and \
                self.time.elapsed_ns >= self.time_limit_ns:
            self._uncaught = TimeLimitExceeded(
                f"time limit ({self.time_limit_ns / 1e9}s) exceeded")

    # ------------------------------------------------------------------
    # The hot loop (`task.rs:121-180`)
    # ------------------------------------------------------------------
    def start_root(self, coro: Coroutine) -> Task:
        """Enqueue a root task without entering the loop (the bridge sweep
        driver owns the loop; ``block_on`` stays the single-world path)."""
        root = Task(self._next_task_id, coro, self.main_node.info)
        self._next_task_id += 1
        self._enqueue(root)
        return root

    def block_on(self, coro: Coroutine) -> Any:
        import threading

        # Which OS thread is executing this world right now (None when
        # idle). The sim event loop's call_soon_threadsafe consults it:
        # arming a timer is safe from the running thread or while the
        # world is idle, and must be refused from a thread racing a live
        # run.
        self.running_thread = threading.get_ident()
        try:
            return self._block_on(self.start_root(coro))
        finally:
            self.running_thread = None

    def _block_on(self, root: Task) -> Any:
        while True:
            self.run_all_ready()
            if self._uncaught is not None:
                exc, self._uncaught = self._uncaught, None
                raise exc
            if root.done:
                return root.join_future.result()
            if not self.time.advance_to_next_event():
                raise Deadlock(
                    f"deadlock detected at t={self.time.elapsed_ns / 1e9:.9f}s: "
                    "all tasks are blocked and no timers are pending"
                )
            if self.time_limit_ns is not None and self.time.elapsed_ns >= self.time_limit_ns:
                raise TimeLimitExceeded(
                    f"time limit ({self.time_limit_ns / 1e9}s) exceeded"
                )

    def run_all_ready(self) -> None:
        if (self._native_ready is not None and self.trace is None
                and self.rng._mode is None and self.rng._st is not None):
            # The C twin of the loop below (same draws, same enqueue order,
            # same exception routing — tests/test_native.py crosschecks).
            self._native_ready(self, context._tls, SimFuture, Cancelled,
                               self._pending_sentinel, self.rng._st)
            return
        while (self.queue or self._yields) and self._uncaught is None:
            if not self.queue:
                # Resolve parked yields only once the ready batch drains —
                # exactly when an already-due timer would have fired
                # (advance_to_next_event runs on an empty queue), so
                # yield_now keeps the timer path's "everything currently
                # ready runs first" ordering.
                yields, self._yields = self._yields, []
                for fut in yields:
                    fut.set_result(None)
                if self._noop_waiting:
                    self._after_noop_drain()
                continue
            # Seeded uniform pick + swap-remove: the randomized interleaving.
            idx = self.rng.gen_range(0, len(self.queue))
            self.queue[idx], self.queue[-1] = self.queue[-1], self.queue[idx]
            task = self.queue.pop()
            task._scheduled = False
            info = task.node
            if info.killed or task.cancelled or task._finished:
                task.drop()
                continue
            if info.paused:
                info.paused_tasks.append(task)
                continue
            # Manual task-context push/pop: the contextmanager protocol
            # (generator frame + __enter__/__exit__) costs ~1.5 µs per poll,
            # a measurable slice of the ~10 µs poll budget.
            tls = context._tls
            prev_task = getattr(tls, "task", None)
            tls.task = task
            self.poll_count += 1
            if self.trace is not None:
                self.trace.append((task.id, self.time.elapsed_ns))
            try:
                self._poll(task)
            finally:
                tls.task = prev_task
            # Random 50-100 ns per poll keeps timestamps distinct across
            # interleavings (`task.rs:176-178`).
            self.time.advance(self.rng.gen_range(50, 100))

    def _poll(self, task: Task) -> None:
        try:
            exc = task._pending_exc
            if exc is not None:
                task._pending_exc = None
                yielded = task.coro.throw(exc)
            else:
                yielded = task.coro.send(None)
        except StopIteration as stop:
            task._finished = True
            task.node.tasks.pop(task, None)
            task.join_future.set_result(stop.value)
        except Cancelled:
            task.drop()
        except BaseException as exc:  # noqa: BLE001 — any task failure fails the sim
            task._finished = True
            task.node.tasks.pop(task, None)
            task.join_future.set_exception(exc)
            self._uncaught = exc
        else:
            if not isinstance(yielded, SimFuture):
                if yielded is None:
                    # Stdlib Task semantics: a bare None yield means
                    # "resume me on the next loop iteration" (asyncio
                    # reschedules via call_soon). The sim analog is
                    # yield_now's scheduling point — this is how
                    # hand-rolled awaitables like aiohttp's helpers.noop
                    # suspend.
                    yielded = self.noop_yield()
                else:
                    self._foreign_yield(task, yielded)
                    return
            epoch = task.wake_epoch
            yielded.add_done_callback(
                lambda _fut, t=task, e=epoch:
                self._wake(t) if t.wake_epoch == e else None)

    def _foreign_yield(self, task: Task, yielded: Any) -> None:
        """A non-SimFuture suspended the task (drop-in gap): fail the sim
        with a diagnostic naming the frame. Shared by both poll loops."""
        # Name the frame that suspended so drop-in gaps (a stdlib
        # awaitable reaching the sim executor) are diagnosable.
        frame = getattr(task.coro, "cr_frame", None)
        inner = task.coro
        while (aw := getattr(inner, "cr_await", None)) is not None:
            inner = aw
            frame = getattr(inner, "cr_frame", frame) or frame
        at = (f" at {frame.f_code.co_filename}:{frame.f_lineno} "
              f"({frame.f_code.co_name})" if frame is not None else "")
        err = TypeError(
            f"task awaited a foreign awaitable (yielded a "
            f"{type(yielded).__name__}){at}; only madsim_tpu futures "
            "(sleep, channels, endpoints, ...) can suspend a "
            "simulation task"
        )
        task._finished = True
        task.node.tasks.pop(task, None)
        task.join_future.set_exception(err)
        self._uncaught = err


class Node:
    """A simulated machine: a stream of NodeInfo generations + init closure."""

    __slots__ = ("id", "name", "cores", "init", "info", "alive", "_executor")

    def __init__(self, node_id: int, name: str, cores: int, init, executor: Executor):
        self.id = node_id
        self.name = name
        self.cores = cores
        self.init = init
        self.info = NodeInfo(node_id, name, cores)
        self.alive = True
        self._executor = executor

    def spawn(self, coro: Coroutine) -> JoinHandle:
        return self._executor.spawn(coro, self.info)

    def __repr__(self):
        return f"Node(id={self.id}, name={self.name!r})"
