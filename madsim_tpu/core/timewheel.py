"""Virtual time: mock clock + timer wheel.

Reference semantics (`madsim/src/sim/time/mod.rs:21-72,159-214`):
- A ``Clock`` holds a randomized base wall-clock time (within year 2022,
  derived from the seed, `time/mod.rs:27-32`) plus monotonic elapsed ns.
- A timer wheel orders pending callbacks; ``advance_to_next_event`` pops the
  earliest deadline, adds a 50 ns epsilon (`time/mod.rs:46-56`), expires all
  due callbacks and sets elapsed time.

Host implementation: a binary heap keyed by (deadline_ns, seq). Timer handles
support cancellation (a dropped Sleep must not fire its waker). Time is kept
as integer nanoseconds (Python ints — unbounded, no overflow); the public API
speaks float seconds.

Two interchangeable heap backends with identical ordering semantics: the C++
native core (native/madsim_core.cpp, the reference's ⚙ naive_timer analog)
when built, else Python heapq.
"""
from __future__ import annotations

import heapq
from typing import Callable, Dict, List, Optional, Tuple

from .. import native as _native
from .rng import GlobalRng, STREAM_TIME_BASE

NANOS_PER_SEC = 1_000_000_000
# Epsilon added when advancing to a timer deadline; mirrors the monotonicity
# workaround at `time/mod.rs:46-56`.
ADVANCE_EPSILON_NS = 50
# Largest storable deadline (~146 sim-years). One clamp shared by every
# backend — Python heap, native int64 heap, and the bridge's device lanes
# (whose empty-lane sentinel is i64 max, kept 2^61 ns above this horizon
# so a clock that creeps past a clamped deadline can never reach it) — so
# an over-range timer fires at the same virtual instant on all of them.
TIMER_MAX_NS = (1 << 62) - 1

_UNIX_2022 = 1_640_995_200  # 2022-01-01T00:00:00Z
_SECS_IN_2022 = 365 * 24 * 3600


class TimerEntry:
    __slots__ = ("deadline_ns", "seq", "callback", "cancelled")

    def __init__(self, deadline_ns: int, seq: int, callback: Callable[[], None]):
        self.deadline_ns = deadline_ns
        self.seq = seq
        self.callback = callback
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True

    def __lt__(self, other: "TimerEntry") -> bool:
        return (self.deadline_ns, self.seq) < (other.deadline_ns, other.seq)


class _NativeTimerEntry:
    """Cancellation handle for a timer living in the native heap."""

    __slots__ = ("seq", "_wheel")

    def __init__(self, seq: int, wheel: "TimeRuntime"):
        self.seq = seq
        self._wheel = wheel

    def cancel(self) -> None:
        # Only mark live timers: cancelling after the pop (timeout's finally
        # path) must not grow the native cancelled-set unboundedly.
        if self._wheel._native_callbacks.pop(self.seq, None) is not None:
            self._wheel._native_heap.cancel(self.seq)


class TimeRuntime:
    """Simulated clock + timer wheel driven by the executor loop."""

    def __init__(self, rng: GlobalRng):
        # Base wall-clock time randomized within 2022 from the seed, drawn
        # from a dedicated stream so it never perturbs the scheduler stream.
        base_rng = GlobalRng(rng.seed, stream=STREAM_TIME_BASE)
        self.base_time_ns = (_UNIX_2022 + base_rng.gen_range(0, _SECS_IN_2022)) * NANOS_PER_SEC
        self.elapsed_ns = 0
        # Per-node wall-clock skew (ns), the fault knob for clock-skew
        # chaos: skews the *system* clock a node observes, never the
        # monotonic clock or timer order (real skewed machines still have
        # monotonic local timers). BASELINE config 4's injection point.
        self.node_skew_ns: Dict[int, int] = {}
        self._heap: List[TimerEntry] = []
        self._seq = 0
        lib = _native.get_lib()
        self._native_heap = _native.NativeTimerHeap(lib) if lib is not None else None
        self._native_callbacks: Dict[int, Callable[[], None]] = {}

    # -- clock reads -------------------------------------------------------
    def now_ns(self) -> int:
        """Monotonic elapsed virtual nanoseconds since runtime start."""
        return self.elapsed_ns

    def system_time_ns(self, node_id: Optional[int] = None) -> int:
        """Simulated wall-clock (unix epoch) nanoseconds, as observed by
        ``node_id`` (applying its configured skew)."""
        skew = self.node_skew_ns.get(node_id, 0) if node_id is not None else 0
        return self.base_time_ns + self.elapsed_ns + skew

    def set_clock_skew(self, node_id: int, skew_ns: int) -> None:
        """Skew a node's wall clock by ``skew_ns`` (positive = fast)."""
        self.node_skew_ns[node_id] = skew_ns

    # -- clock writes ------------------------------------------------------
    def advance(self, delta_ns: int) -> None:
        """Advance elapsed time (used for the per-poll random 50-100 ns tick)."""
        self.elapsed_ns += delta_ns

    # -- timers ------------------------------------------------------------
    def add_timer_at(self, deadline_ns: int, callback: Callable[[], None]):
        # Clamp before the backend split: the native heap stores int64
        # deadlines while Python ints are unbounded, and both backends must
        # fire an over-range timer at the *same* (clamped) virtual time or
        # determinism logs recorded on one backend fail replay on the other.
        deadline_ns = min(max(deadline_ns, self.elapsed_ns), TIMER_MAX_NS)
        seq = self._seq
        self._seq += 1
        if self._native_heap is not None:
            self._native_heap.push(deadline_ns, seq)
            self._native_callbacks[seq] = callback
            return _NativeTimerEntry(seq, self)
        entry = TimerEntry(deadline_ns, seq, callback)
        heapq.heappush(self._heap, entry)
        return entry

    def add_timer(self, delay_ns: int, callback: Callable[[], None]):
        return self.add_timer_at(self.elapsed_ns + max(0, delay_ns), callback)

    def next_deadline_ns(self) -> Optional[int]:
        if self._native_heap is not None:
            return self._native_heap.peek()
        while self._heap and self._heap[0].cancelled:
            heapq.heappop(self._heap)
        return self._heap[0].deadline_ns if self._heap else None

    def advance_to_next_event(self) -> bool:
        """Jump the clock to the earliest pending timer and fire all due
        callbacks. Returns False if no timers are pending (deadlock)."""
        deadline = self.next_deadline_ns()
        if deadline is None:
            return False
        target = max(deadline + ADVANCE_EPSILON_NS, self.elapsed_ns)
        self.elapsed_ns = target
        self._fire_due()
        return True

    def _fire_due(self) -> None:
        if self._native_heap is not None:
            while (seq := self._native_heap.pop_due(self.elapsed_ns)) is not None:
                cb = self._native_callbacks.pop(seq, None)
                if cb is not None:
                    cb()
            return
        while self._heap:
            head = self._heap[0]
            if head.cancelled:
                heapq.heappop(self._heap)
                continue
            if head.deadline_ns > self.elapsed_ns:
                break
            heapq.heappop(self._heap)
            head.callback()


def to_ns(seconds: float) -> int:
    """Convert a float-seconds duration to integer nanoseconds."""
    if seconds < 0:
        raise ValueError("duration must be non-negative")
    return round(seconds * NANOS_PER_SEC)
