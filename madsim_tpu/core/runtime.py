"""Runtime / Handle / NodeBuilder — the composition root.

Reference: `madsim/src/sim/runtime/mod.rs` — ``Runtime`` wires rng + executor
+ time + default simulators (`:50-64`); ``Handle`` is the cloneable supervisor
(seed, kill/restart/pause/resume, create_node, simulator registry, config;
`:201-279`); ``NodeBuilder`` configures name/ip/cores/init with init re-run on
crash-restart (`:282-355`); ``check_determinism`` runs a test twice with RNG
log/replay (`:164-189`).
"""
from __future__ import annotations

import inspect
from typing import Any, Callable, Coroutine, Optional, Union

from . import context
from .config import Config
from .plugin import Simulator, SimulatorRegistry
from .rng import STREAM_SCHED, GlobalRng
from .task import Executor, Node, TimeLimitExceeded  # noqa: F401 (re-export)
from .timewheel import TimeRuntime, to_ns


class Handle:
    """Cloneable supervisor handle over one simulation world."""

    def __init__(self, seed: int, config: Config, rng: GlobalRng, time: TimeRuntime, executor: Executor):
        self.seed = seed
        self.config = config
        self.rand = rng
        self.time = time
        self.task = executor
        self.sims = SimulatorRegistry()

    @staticmethod
    def current() -> "Handle":
        return context.current_handle()

    # -- fault injection (`runtime/mod.rs:241-268`) ------------------------
    def kill(self, node: Union[int, "NodeHandle"]) -> None:
        self.task.kill(_node_id(node))

    def restart(self, node: Union[int, "NodeHandle"]) -> None:
        self.task.restart(_node_id(node))

    def pause(self, node: Union[int, "NodeHandle"]) -> None:
        self.task.pause(_node_id(node))

    def resume(self, node: Union[int, "NodeHandle"]) -> None:
        self.task.resume(_node_id(node))

    def set_clock_skew(self, node: Union[int, "NodeHandle"], seconds: float) -> None:
        """Skew a node's observed wall clock (system_time) by ``seconds``
        (positive = that node's clock runs ahead). Monotonic time and timer
        ordering are unaffected, as on real skewed machines."""
        self.time.set_clock_skew(_node_id(node), round(seconds * 1e9))

    # -- topology ----------------------------------------------------------
    def create_node(self, name: Optional[str] = None, ip: Optional[str] = None,
                    cores: int = 1, init: Optional[Callable[[], Coroutine]] = None) -> "NodeHandle":
        node = self.task.create_node(name=name, cores=cores, init=init)
        for sim in self.sims.all():
            sim.create_node(node.id)
        if ip is not None:
            from ..net import NetSim  # late import: net layers above core

            if self.sims.contains(NetSim):
                self.sims.get(NetSim).set_ip(node.id, ip)
        if init is not None:
            node.spawn(init())
        return NodeHandle(node, self)

    def get_node(self, node_id: int) -> "NodeHandle":
        return NodeHandle(self.task._get_node(node_id), self)


class NodeHandle:
    """Handle to a simulated machine: spawn tasks on it, inspect identity."""

    def __init__(self, node: Node, handle: Handle):
        self._node = node
        self._handle = handle

    @property
    def id(self) -> int:
        return self._node.id

    @property
    def name(self) -> str:
        return self._node.name

    def is_alive(self) -> bool:
        """False between kill() and restart() (true liveness, not the
        per-generation killed flag)."""
        return self._node.alive

    def spawn(self, coro: Coroutine):
        return self._node.spawn(coro)

    def __repr__(self):
        return f"NodeHandle(id={self.id}, name={self.name!r})"


def _node_id(node: Union[int, NodeHandle]) -> int:
    return node.id if isinstance(node, NodeHandle) else int(node)


class Runtime:
    """One seeded simulation world.

    ``Runtime(seed)`` builds the deterministic rng, virtual clock, executor,
    and registers the default simulators (NetSim, FsSim), mirroring
    `runtime/mod.rs:50-64`.
    """

    def __init__(self, seed: int = 0, config: Optional[Config] = None):
        self.seed = seed
        self.config = config or Config()
        self.rand = GlobalRng(seed)
        self.time = self._make_time()
        self.rand.set_clock(self.time.now_ns)
        # The scheduler draws (ready-pick, poll jitter) come from their own
        # stream so they are addressable by poll index — user-code draws on
        # the GLOBAL stream can no longer shift them (and vice versa).
        self.task = Executor(GlobalRng(seed, stream=STREAM_SCHED), self.time)
        self.handle = Handle(seed, self.config, self.rand, self.time, self.task)
        self.task.on_reset_node = self._reset_node_in_sims
        for sim_cls in self._default_simulators():
            self.add_simulator(sim_cls)

    # Overridable wiring (the bridge backend substitutes a device-backed
    # timer wheel and a device-sampling NetSim, keeping everything else).
    def _make_time(self) -> TimeRuntime:
        return TimeRuntime(self.rand)

    def _default_simulators(self) -> tuple:
        # Late imports keep core free of upper layers.
        from ..fs import FsSim
        from ..net import NetSim

        return (NetSim, FsSim)

    def _reset_node_in_sims(self, node_id: int) -> None:
        for sim in self.handle.sims.all():
            sim.reset_node(node_id)

    def add_simulator(self, sim_cls: type) -> None:
        if not (inspect.isclass(sim_cls) and issubclass(sim_cls, Simulator)):
            raise TypeError("add_simulator expects a Simulator subclass")
        with context.enter_handle(self.handle):
            sim = sim_cls(self.handle)
            self.handle.sims.add(sim)
            # Back-fill nodes created before this simulator was registered
            # (at minimum the main node, which exists from executor init).
            for node_id in self.task.nodes:
                sim.create_node(node_id)

    # -- node & time config ------------------------------------------------
    def create_node(self, name: Optional[str] = None, ip: Optional[str] = None,
                    cores: int = 1, init: Optional[Callable[[], Coroutine]] = None) -> NodeHandle:
        with context.enter_handle(self.handle):
            return self.handle.create_node(name=name, ip=ip, cores=cores, init=init)

    def set_time_limit(self, seconds: float) -> None:
        self.task.time_limit_ns = to_ns(seconds)

    # -- execution ---------------------------------------------------------
    def block_on(self, coro: Coroutine) -> Any:
        with context.enter_handle(self.handle):
            return self.task.block_on(coro)

    # -- determinism checking (`runtime/mod.rs:164-189`) --------------------
    @staticmethod
    def check_determinism(seed: int, config: Optional[Config], make_coro: Callable[[], Coroutine],
                          time_limit: Optional[float] = None) -> Any:
        """Run the simulation twice: first logging every RNG access, then
        replaying with comparison. Raises DeterminismError on divergence."""
        import threading

        import copy

        results: list = [None, None]
        errors: list = [None, None]
        log_holder: list = [None]

        def run(which: int) -> None:
            try:
                # Fresh config per run: in-sim config mutations (e.g.
                # NetSim.update_config chaos) must not leak into the replay.
                rt = Runtime(seed=seed, config=copy.deepcopy(config) if config else None)
                if time_limit is not None:
                    rt.set_time_limit(time_limit)
                if which == 0:
                    rt.rand.enable_log()
                else:
                    rt.rand.enable_check(log_holder[0])
                results[which] = rt.block_on(make_coro())
                if which == 0:
                    log_holder[0] = rt.rand.take_log()
            except BaseException as exc:  # noqa: BLE001
                errors[which] = exc

        # Fresh threads for thread-local isolation, like the reference's
        # per-simulation thread spawn (`builder.rs:123`).
        for which in (0, 1):
            # detlint: allow[DET003] — the driver wrapping simulations, not code inside one
            t = threading.Thread(target=run, args=(which,), daemon=True)
            t.start()
            t.join()
            if errors[which] is not None:
                raise errors[which]
        return results[1]


def sim_span() -> str:
    """The current simulation span — ``t=<vtime> node=<id>/<name>
    task=<id>`` — or '' outside a simulation.

    The analog of the reference's per-node/per-task tracing spans that wrap
    every poll (`madsim/src/sim/task.rs:58-82,100`): every in-sim log line
    carries who emitted it and at what virtual time, which is what makes a
    seed-replayed trace navigable."""
    handle = context.try_current_handle()
    if handle is None:
        return ""
    t = handle.time.now_ns() / 1e9
    task = context.try_current_task()
    if task is None:
        return f"[t={t:.9f}s]"
    node = task.node
    return f"[t={t:.9f}s node={node.id}/{node.name} task={task.id}]"


class _SpanFilter:
    """logging filter injecting the sim span into every record (attribute
    ``sim``, used by the default format; safe no-op outside a sim)."""

    def filter(self, record) -> bool:
        span = sim_span()
        record.sim = (span + " ") if span else ""
        return True


def init_logger() -> None:
    """Install the logging config once (`runtime/mod.rs:380-384` analog):
    MADSIM_LOG sets the level, and every record carries the structured
    simulation span (virtual time + node + task identity) as the ``sim``
    attribute. When logging was already configured elsewhere (basicConfig
    no-ops), the span attribute is still injected so custom formats can
    include ``%(sim)s`` — but the preexisting format string is left alone."""
    import logging
    import os

    if getattr(init_logger, "_done", False):
        return
    init_logger._done = True  # type: ignore[attr-defined]
    level = os.environ.get("MADSIM_LOG", "WARNING").upper()
    root = logging.getLogger()
    preconfigured = bool(root.handlers)
    logging.basicConfig(level=getattr(logging, level, logging.WARNING),
                        format="%(levelname)s %(sim)s%(name)s: %(message)s")
    for handler in root.handlers:
        handler.addFilter(_SpanFilter())
    if preconfigured:
        logging.getLogger(__name__).debug(
            "logging was configured before init_logger: %s span attribute "
            "injected, existing format preserved", "%(sim)s")
