"""Backend-mode resolution: simulation vs production ("real") execution.

The reference compiles every API twice — sim under ``--cfg madsim``, real
otherwise — and switches at build time (`madsim/src/lib.rs:14-23`). Python
has no build cfg, so the switch is resolved at call time:

- inside a :class:`~madsim_tpu.core.runtime.Runtime` context (a simulation
  is running on this thread) → **sim**, always;
- otherwise, ``MADSIM_BACKEND=real`` in the environment → **real**: the
  same facades (Endpoint, rpc, time, task, rand, fs, sync) execute over
  asyncio, framed TCP sockets, the OS clock, and OS entropy
  (`madsim/src/std/mod.rs:1-7` analog);
- otherwise → **sim-required**: the APIs raise
  :class:`~madsim_tpu.core.context.NoRuntimeError` as before, so test code
  cannot silently run unsimulated.

The same application code therefore runs in both modes unchanged — the
"same binary, sim for tests, real for prod" contract.
"""
from __future__ import annotations

import os

from . import context


def is_real() -> bool:
    """True when APIs should execute on the production (asyncio) backend."""
    if context.try_current_handle() is not None:
        return False
    return os.environ.get("MADSIM_BACKEND", "sim").lower() == "real"
