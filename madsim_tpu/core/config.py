"""Simulation configuration.

Reference: `madsim/src/sim/config.rs` — ``Config{net, tcp}`` with TOML
(de)serialization and a stable hash printed alongside the failing seed so
repros verify they ran the same config (`config.rs:25-31`,
`runtime/mod.rs:192-199`).
"""
from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Tuple


@dataclass
class NetConfig:
    """Network fault model (`net/network.rs:74-94`): Bernoulli packet loss +
    uniform per-message latency, defaults 0% loss and 1-10 ms."""

    packet_loss_rate: float = 0.0
    send_latency: Tuple[float, float] = (0.001, 0.010)  # seconds, [min, max)


@dataclass
class TcpConfig:
    """Placeholder mirroring the reference's empty TcpConfig
    (`net/tcp/config.rs:7-13`)."""


@dataclass
class FsConfig:
    """Fault model for the simulated fs (reference leaves these as TODOs at
    `fs.rs:51-53,183` — implemented for real here)."""

    # Uniform extra latency per I/O op, seconds.
    io_latency: Tuple[float, float] = (0.0, 0.0)


@dataclass
class Config:
    net: NetConfig = field(default_factory=NetConfig)
    tcp: TcpConfig = field(default_factory=TcpConfig)
    fs: FsConfig = field(default_factory=FsConfig)

    @staticmethod
    def from_dict(d: dict) -> "Config":
        cfg = Config()
        net = d.get("net", {})
        if "packet_loss_rate" in net:
            cfg.net.packet_loss_rate = float(net["packet_loss_rate"])
        if "send_latency" in net:
            lo, hi = net["send_latency"]
            cfg.net.send_latency = (float(lo), float(hi))
        fs = d.get("fs", {})
        if "io_latency" in fs:
            lo, hi = fs["io_latency"]
            cfg.fs.io_latency = (float(lo), float(hi))
        return cfg

    @staticmethod
    def from_toml(text: str) -> "Config":
        try:
            import tomllib
        except ModuleNotFoundError:  # 3.10: the identical backport
            import tomli as tomllib

        return Config.from_dict(tomllib.loads(text))

    def to_dict(self) -> dict:
        return {
            "net": {
                "packet_loss_rate": self.net.packet_loss_rate,
                "send_latency": list(self.net.send_latency),
            },
            "tcp": {},
            "fs": {"io_latency": list(self.fs.io_latency)},
        }

    def hash(self) -> str:
        """Stable fingerprint for repro banners (`config.rs:27-31` analog)."""
        canon = json.dumps(self.to_dict(), sort_keys=True)
        return hashlib.sha256(canon.encode()).hexdigest()[:16]
