"""Deterministic host-engine core: rng, virtual time, executor, runtime."""
