"""Per-subsystem simulator plugin registry.

Reference: `madsim/src/sim/plugin.rs:18-54` — a ``Simulator`` trait
(constructed with rand/time/config handles, notified on node create/reset)
and a global TypeId→instance lookup. Users register their own subsystem
simulators via ``Runtime.add_simulator`` (e.g. a storage-service simulator),
exactly like RisingWave does on the reference.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Dict, Type, TypeVar

if TYPE_CHECKING:
    from .runtime import Handle

S = TypeVar("S", bound="Simulator")


class Simulator:
    """Base class for subsystem simulators (network, fs, user-defined).

    Subclasses get the full runtime handle at construction so they can reach
    the deterministic rng, virtual clock, executor and config.
    """

    def __init__(self, handle: "Handle"):
        self.handle = handle

    def create_node(self, node_id: int) -> None:
        """Called when a node is created."""

    def reset_node(self, node_id: int) -> None:
        """Called on node kill/restart: drop all node state (sockets, files
        that weren't synced, ...)."""


class SimulatorRegistry:
    def __init__(self):
        self._sims: Dict[type, Simulator] = {}

    def add(self, sim: Simulator) -> None:
        self._sims[type(sim)] = sim

    def get(self, cls: Type[S]) -> S:
        sim = self._sims.get(cls)
        if sim is None:
            # A registered subclass satisfies lookups by its base (e.g. the
            # bridge backend registers a NetSim subclass; user code keeps
            # asking for NetSim).
            for s in self._sims.values():
                if isinstance(s, cls):
                    return s  # type: ignore[return-value]
            raise KeyError(f"simulator {cls.__name__} is not registered")
        return sim  # type: ignore[return-value]

    def contains(self, cls: type) -> bool:
        return cls in self._sims or any(
            isinstance(s, cls) for s in self._sims.values())

    def all(self):
        return list(self._sims.values())


def simulator(cls: Type[S]) -> S:
    """Look up a registered simulator on the current runtime
    (`plugin.rs:45-54` analog)."""
    from . import context

    return context.current_handle().sims.get(cls)
