"""Thread-local simulation context.

Reference: `madsim/src/sim/runtime/context.rs` — two thread-locals (current
runtime Handle, current TaskInfo) with RAII enter/exit guards; net/fs calls
resolve their node implicitly through them.

Thread-local (not plain module globals) because the multi-seed test driver
runs each simulation on its own OS thread (`builder.rs:118-136` analog), and
threads must not see each other's runtime.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import TYPE_CHECKING, Optional

if TYPE_CHECKING:
    from .runtime import Handle
    from .task import TaskInfo

_tls = threading.local()


class NoRuntimeError(RuntimeError):
    pass


def current_handle() -> "Handle":
    handle = getattr(_tls, "handle", None)
    if handle is None:
        raise NoRuntimeError(
            "there is no simulation running: this API must be called from "
            "within a madsim_tpu Runtime (e.g. inside Runtime.block_on)"
        )
    return handle


def try_current_handle() -> Optional["Handle"]:
    return getattr(_tls, "handle", None)


def current_task() -> "TaskInfo":
    task = getattr(_tls, "task", None)
    if task is None:
        raise NoRuntimeError("not inside a simulation task")
    return task


def try_current_task() -> Optional["TaskInfo"]:
    return getattr(_tls, "task", None)


def current_node_id() -> int:
    return current_task().node.id


@contextmanager
def enter_handle(handle: "Handle"):
    prev = getattr(_tls, "handle", None)
    _tls.handle = handle
    try:
        yield
    finally:
        _tls.handle = prev


@contextmanager
def enter_task(task: "TaskInfo"):
    prev = getattr(_tls, "task", None)
    _tls.task = task
    try:
        yield
    finally:
        _tls.task = prev
