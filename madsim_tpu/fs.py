"""Simulated per-node file system.

Reference: `madsim/src/sim/fs.rs` — per-node in-memory FS
(`HashMap<PathBuf, INode>`, `fs.rs:67-145`), positional-I/O ``File`` API
(`fs.rs:161-229`), module-level ``read``/``metadata`` (`fs.rs:232-244`).

The reference leaves ``power_fail`` (lose unflushed data on crash), write
buffering and random I/O delays as TODOs (`fs.rs:38-41,51-53,183,203-205`);
they are implemented for real here: writes land in a volatile buffer,
``sync_all`` commits to durable storage, and node reset (kill/restart) rolls
every file back to its last synced content. Disk contents survive node
restarts (stable storage), enabling crash-recovery workloads.
"""
from __future__ import annotations

from typing import Dict, Optional

from .core import context
from .core.plugin import Simulator


class FsError(OSError):
    pass


class _INode:
    __slots__ = ("data", "synced")

    def __init__(self):
        self.data = bytearray()    # volatile (page-cache) content
        self.synced = bytearray()  # durable content as of last sync_all

    def power_fail(self) -> None:
        self.data = bytearray(self.synced)

    def sync(self) -> None:
        self.synced = bytearray(self.data)


class FsSim(Simulator):
    """File-system simulator plugin. Storage is keyed by node id and
    survives kill/restart; only unsynced data is lost (power failure)."""

    def __init__(self, handle):
        super().__init__(handle)
        self._disks: Dict[int, Dict[str, _INode]] = {}
        # I/O latency draws live on the FS stream (core/rng.py stream map)
        # so disk activity never shifts scheduler/network/user draw indices.
        from .core.rng import STREAM_FS, GlobalRng

        self._rand = GlobalRng(handle.seed, stream=STREAM_FS)

    def create_node(self, node_id: int) -> None:
        self._disks.setdefault(node_id, {})

    def reset_node(self, node_id: int) -> None:
        # Crash = power failure: every file loses its unflushed writes.
        for inode in self._disks.get(node_id, {}).values():
            inode.power_fail()

    # -- helpers -----------------------------------------------------------
    def _disk(self, node_id: Optional[int] = None) -> Dict[str, _INode]:
        if node_id is None:
            node_id = context.current_node_id()
        return self._disks.setdefault(node_id, {})

    async def _io_delay(self) -> None:
        lo, hi = self.handle.config.fs.io_latency
        if hi > 0:
            from . import time as vtime

            await vtime.sleep(self._rand.gen_range_f64(lo, hi))


def _fs() -> FsSim:
    return context.current_handle().sims.get(FsSim)


class Metadata:
    __slots__ = ("len",)

    def __init__(self, length: int):
        self.len = length


def _real_fs():
    """Real-backend twin (``std/fs.rs`` analog) or None when simulating."""
    from .core.backend import is_real

    if is_real():
        from .real import fs as real_fs

        return real_fs
    return None


class File:
    """Positional-I/O file handle (`fs.rs:161-229`)."""

    def __init__(self, inode: _INode, path: str):
        self._inode = inode
        self.path = path

    @staticmethod
    async def create(path: str) -> "File":
        real = _real_fs()
        if real is not None:
            return await real.RealFile.create(path)
        sim = _fs()
        await sim._io_delay()
        inode = _INode()
        sim._disk()[str(path)] = inode
        return File(inode, str(path))

    @staticmethod
    async def open(path: str) -> "File":
        real = _real_fs()
        if real is not None:
            return await real.RealFile.open(path)
        sim = _fs()
        await sim._io_delay()
        inode = sim._disk().get(str(path))
        if inode is None:
            raise FileNotFoundError(f"no such file: {path}")
        return File(inode, str(path))

    @staticmethod
    async def open_or_create(path: str) -> "File":
        real = _real_fs()
        if real is not None:
            return await real.RealFile.open_or_create(path)
        sim = _fs()
        await sim._io_delay()
        inode = sim._disk().setdefault(str(path), _INode())
        return File(inode, str(path))

    async def read_at(self, offset: int, length: int) -> bytes:
        await _fs()._io_delay()
        data = self._inode.data
        if offset >= len(data):
            return b""
        return bytes(data[offset:offset + length])

    async def read_all(self) -> bytes:
        await _fs()._io_delay()
        return bytes(self._inode.data)

    async def write_all_at(self, data: bytes, offset: int) -> None:
        """Write into the volatile buffer; durable only after sync_all."""
        await _fs()._io_delay()
        buf = self._inode.data
        end = offset + len(data)
        if len(buf) < end:
            buf.extend(b"\x00" * (end - len(buf)))
        buf[offset:end] = data

    async def set_len(self, length: int) -> None:
        await _fs()._io_delay()
        buf = self._inode.data
        if length <= len(buf):
            del buf[length:]
        else:
            buf.extend(b"\x00" * (length - len(buf)))

    async def sync_all(self) -> None:
        """Commit the volatile buffer to durable storage."""
        await _fs()._io_delay()
        self._inode.sync()

    async def metadata(self) -> Metadata:
        return Metadata(len(self._inode.data))

    def close(self) -> None:
        """Sim/real parity with :meth:`RealFile.close` (detlint PAR001):
        the sim inode holds no OS fd, so there is nothing to release, but
        programs that close their files must run on both backends."""


async def read(path: str) -> bytes:
    """Read a whole file (`fs.rs:232-238`)."""
    real = _real_fs()
    if real is not None:
        return await real.read(path)
    f = await File.open(path)
    return await f.read_all()

async def write(path: str, data: bytes) -> None:
    real = _real_fs()
    if real is not None:
        return await real.write(path, data)
    f = await File.open_or_create(path)
    await f.set_len(0)
    await f.write_all_at(bytes(data), 0)

async def metadata(path: str) -> Metadata:
    real = _real_fs()
    if real is not None:
        return await real.metadata(path)
    f = await File.open(path)
    return await f.metadata()

async def remove_file(path: str) -> None:
    real = _real_fs()
    if real is not None:
        return await real.remove_file(path)
    sim = _fs()
    await sim._io_delay()
    if sim._disk().pop(str(path), None) is None:
        raise FileNotFoundError(f"no such file: {path}")
