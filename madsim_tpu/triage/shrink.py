"""Schedule algebra: the candidate generators of the failure minimizer.

A fault schedule is an ``(F, 4)`` int32 array of rows ``[time_us, op, a,
b]`` (engine/core.py ``DeviceEngine.init``); rows with ``time_us < 0``
are disabled — which is the representation trick the whole batched
minimizer rests on: every candidate shrink of a schedule keeps the SAME
static ``(F, 4)`` shape (dropping a row means disabling it), so hundreds
of candidates stack into one ``(C, F, 4)`` per-world faults array and
evaluate as ONE compiled sweep (triage/minimize.py), with zero
recompiles across rounds beyond the log2-bucketed batch widths.

Three candidate families (ISSUE: the ddmin / delta-debugging algebra):

- **Row subsets** (:func:`subset_candidates`): ddmin-style chunk
  subsets and complements over the live rows at a granularity ``k`` —
  "keep only chunk i" and "drop chunk i".
- **Fire-time tightening** (:func:`tighten_candidates`): per live row,
  halve its fire time (monotone toward 0, so the phase terminates).
- **Severity weakening** (:func:`weaken_candidates`): per live row,
  replace the fault with a strictly weaker one — ``KILL`` → ``PAUSE``,
  ``SET_LOSS ppm`` → 0, ``SET_LATENCY [a, b]`` → the narrowest legal
  window ``[a, a+1]``.

Everything here is host-side numpy and PURE: candidate generation is a
deterministic function of the current schedule alone (canonical chunk
split, canonical emission order, canonical disabled-row sentinel), which
is half of the minimizer's bitwise-reproducibility contract — the other
half is the sweep oracle's own determinism.

"Smaller" is a total order, :func:`schedule_cost`: fewest live rows
first, then the summed severity weight (kills cost more than pauses;
loss/latency rows carry their parameter magnitude), then the summed
fire time, then the lexicographic row tuple as the final tie-break — so
a round's winner among still-failing candidates is unique.
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ..engine.core import (
    FAULT_CLOG_LINK,
    FAULT_CLOG_NODE,
    FAULT_KILL,
    FAULT_PAUSE,
    FAULT_RESTART,
    FAULT_RESUME,
    FAULT_SET_LATENCY,
    FAULT_SET_LOSS,
    FAULT_UNCLOG_LINK,
    FAULT_UNCLOG_NODE,
)

# The canonical disabled row: every dropped row is rewritten to exactly
# this, so two schedules with the same live rows are bitwise equal no
# matter which candidate path produced them (the lexicographic tie-break
# and the "re-run yields the identical array" gate both rely on it).
DISABLED_ROW = np.array([-1, 0, 0, 0], np.int32)

# Relative severity of a fault op (the weakening partial order's weight):
# a kill is worse than a clog is worse than a net-model change is worse
# than a pause/restart is worse than an un-fault. Scaled by 1e6 so the
# per-row parameter magnitude (loss ppm, latency window width) breaks
# ties WITHIN an op without ever outranking an op change.
_SEVERITY_BASE = {
    FAULT_KILL: 40,
    FAULT_CLOG_LINK: 30,
    FAULT_CLOG_NODE: 30,
    FAULT_SET_LOSS: 20,
    FAULT_SET_LATENCY: 20,
    FAULT_PAUSE: 10,
    FAULT_RESTART: 10,
    FAULT_UNCLOG_LINK: 5,
    FAULT_UNCLOG_NODE: 5,
    FAULT_RESUME: 5,
}


def as_schedule(rows) -> np.ndarray:
    """Coerce to a normalized ``(F, 4)`` int32 schedule (``None`` and
    ``(0, 4)`` both mean "no faults")."""
    if rows is None:
        return np.zeros((0, 4), np.int32)
    arr = np.asarray(rows, np.int32)
    if arr.ndim != 2 or arr.shape[-1] != 4:
        raise ValueError(
            f"a fault schedule is (F, 4) rows of [time_us, op, a, b]; "
            f"got shape {arr.shape}")
    return normalize(arr)


def normalize(sched: np.ndarray) -> np.ndarray:
    """Rewrite every disabled row (time < 0) to :data:`DISABLED_ROW`."""
    out = np.array(sched, np.int32, copy=True)
    out[out[:, 0] < 0] = DISABLED_ROW
    return out


def live_indices(sched: np.ndarray) -> np.ndarray:
    """Indices of the enabled rows, ascending."""
    return np.flatnonzero(sched[:, 0] >= 0)


def n_live(sched: np.ndarray) -> int:
    return int((sched[:, 0] >= 0).sum())


def keep_rows(sched: np.ndarray, keep: np.ndarray) -> np.ndarray:
    """The candidate with ONLY the given (live) row indices enabled."""
    out = np.broadcast_to(DISABLED_ROW, sched.shape).copy()
    keep = np.asarray(keep, np.int64)
    out[keep] = sched[keep]
    return out


def compact(sched: np.ndarray) -> np.ndarray:
    """The live rows alone, original order — the ``(L, 4)`` array a
    repro bundle records."""
    return np.array(sched[sched[:, 0] >= 0], np.int32, copy=True)


def row_severity(row: np.ndarray) -> int:
    """Severity weight of one enabled row (see ``_SEVERITY_BASE``)."""
    op = int(row[1])
    base = _SEVERITY_BASE.get(op, 50)  # unknown ops sort worst
    extra = 0
    if op == FAULT_SET_LOSS:
        extra = int(row[2])                     # ppm
    elif op == FAULT_SET_LATENCY:
        extra = int(row[3]) - int(row[2])       # window width, µs
    return base * 1_000_000 + extra


def schedule_cost(sched: np.ndarray) -> Tuple:
    """The total "smaller-than" order of the minimizer.

    ``(n_live_rows, severity_sum, time_sum, row_tuple)`` — compared
    left to right, so fewest rows always wins, then weakest, then
    earliest-firing, then the unique lexicographic tie-break over the
    normalized array (DISABLED_ROW canonicalization makes it total).
    """
    live = sched[sched[:, 0] >= 0]
    return (
        int(live.shape[0]),
        int(sum(row_severity(r) for r in live)),
        int(live[:, 0].sum()) if live.size else 0,
        tuple(int(x) for x in sched.reshape(-1)),
    )


def split_chunks(live: np.ndarray, k: int) -> List[np.ndarray]:
    """Canonical ddmin chunking: ``k`` nearly-equal contiguous slices of
    the live-row index vector (numpy's array_split order)."""
    k = max(1, min(int(k), live.size))
    return [c for c in np.array_split(live, k) if c.size]


def subset_candidates(sched: np.ndarray, k: int
                      ) -> List[Tuple[str, np.ndarray]]:
    """ddmin row-subset candidates at granularity ``k``.

    Emission order is canonical: every "keep only chunk i" subset first
    (i ascending), then — for ``k > 2``, where they differ from the
    subsets — every "drop chunk i" complement. At ``k == L`` the
    complements are exactly the single-row drops, which is why the row
    phase's no-progress fixpoint certifies 1-minimality.
    """
    live = live_indices(sched)
    if live.size <= 1:
        # Terminal granularity: the only strictly smaller candidate is
        # the empty schedule.
        return ([("drop:all", keep_rows(sched, np.zeros(0, np.int64)))]
                if live.size else [])
    chunks = split_chunks(live, k)
    out: List[Tuple[str, np.ndarray]] = []
    for i, c in enumerate(chunks):
        out.append((f"subset:{i}/{len(chunks)}", keep_rows(sched, c)))
    if len(chunks) > 2:
        for i, c in enumerate(chunks):
            keep = np.setdiff1d(live, c, assume_unique=True)
            out.append((f"complement:{i}/{len(chunks)}",
                        keep_rows(sched, keep)))
    return out


def single_drop_candidates(sched: np.ndarray
                           ) -> List[Tuple[str, np.ndarray]]:
    """One candidate per live row, that row disabled — the 1-minimality
    verification set (every one must STOP failing)."""
    live = live_indices(sched)
    return [(f"drop:{int(i)}",
             keep_rows(sched, np.setdiff1d(live, [i], assume_unique=True)))
            for i in live]


def weaken_candidates(sched: np.ndarray) -> List[Tuple[str, np.ndarray]]:
    """Per-row severity weakenings, canonical order (row index ascending,
    one candidate per applicable weakening). Each is strictly cheaper
    under :func:`schedule_cost`, so the weakening phase terminates."""
    out: List[Tuple[str, np.ndarray]] = []
    for i in live_indices(sched):
        row = sched[i]
        op = int(row[1])
        if op == FAULT_KILL:
            cand = np.array(sched, np.int32, copy=True)
            cand[i, 1] = FAULT_PAUSE
            cand[i, 3] = 0
            out.append((f"weaken:{int(i)}:kill->pause", cand))
        elif op == FAULT_SET_LOSS and int(row[2]) > 0:
            cand = np.array(sched, np.int32, copy=True)
            cand[i, 2] = 0
            out.append((f"weaken:{int(i)}:loss->0", cand))
        elif op == FAULT_SET_LATENCY and int(row[3]) > int(row[2]) + 1:
            cand = np.array(sched, np.int32, copy=True)
            cand[i, 3] = cand[i, 2] + 1  # narrowest legal window
            out.append((f"weaken:{int(i)}:latency-narrow", cand))
    return out


def tighten_candidates(sched: np.ndarray) -> List[Tuple[str, np.ndarray]]:
    """Per-row fire-time tightening: halve the row's time (toward 0).
    Strictly reduces the cost tuple's time_sum, so repeated tightening
    converges; opt-in in the minimizer (it rewrites row values, which
    trades row identity for an earlier, denser repro)."""
    out: List[Tuple[str, np.ndarray]] = []
    for i in live_indices(sched):
        t = int(sched[i, 0])
        if t > 0:
            cand = np.array(sched, np.int32, copy=True)
            cand[i, 0] = t // 2
            out.append((f"tighten:{int(i)}:t{t}->{t // 2}", cand))
    return out
