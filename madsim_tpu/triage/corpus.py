"""Deduplicated bug corpus: failure classes + minimized repro bundles.

PRISM's point (PAPERS.md) applies verbatim to an always-on device hunt:
a raw stream of failing seeds is useless until it is *deduplicated and
attributed*. This module buckets a sweep's failures into failure
classes keyed by the PR 6 behavior signature (obs/coverage.py — the
same bucketed-histogram FNV hash the on-device coverage ledger folds,
recomputed here bit-identically from the per-seed metrics frames) plus
the actor's invariant id, minimizes ONE representative per class
(triage/minimize.py — not one per failing seed), and emits each as an
obs/bundle.py repro bundle extended with the ``minimization``
provenance block that the replay CLI verifies end to end.

Requires ``EngineConfig(metrics=True)``: the behavior signature is a
hash of the MetricsBlock histograms, so a metrics-off sweep has no
class key to bucket by (the same precondition as ``SweepResult.coverage``).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import numpy as np

from ..obs.coverage import _FNV_PRIME, _FNV_SEED
from ..obs.metrics import BLOCK_FIELDS  # noqa: F401  (schema cross-ref)
from .minimize import MinimizeResult, TriageError


def _np_bit_length(col: np.ndarray) -> np.ndarray:
    """Per-element ``int.bit_length`` over a non-negative int column —
    the numpy twin of obs/coverage.py ``_bit_length_u32`` (same binary
    shift loop, so signatures match the device fold bit for bit)."""
    x = np.asarray(col, np.uint32).copy()
    n = np.zeros(x.shape, np.uint32)
    for s in (16, 8, 4, 2, 1):
        hi = x >> np.uint32(s)
        move = hi > 0
        n[move] += np.uint32(s)
        x[move] = hi[move]
    return n + (x > 0).astype(np.uint32)


def behavior_signatures(per_seed: Dict[str, np.ndarray]) -> np.ndarray:
    """u32 behavior signature per seed from the per-seed metrics frames
    (``SweepResult.metrics["per_seed"]``).

    Column order and bucketing mirror obs/coverage.py
    ``behavior_signature`` EXACTLY — kind_hist columns, fault_hist
    columns, then the six drop causes, each quantized to its power-of-
    two bucket and FNV-1a-folded — so the host-side corpus key equals
    the device-side coverage-bucket preimage (tier-1-tested parity).
    """
    kind = np.asarray(per_seed["kind_hist"])
    fault = np.asarray(per_seed["fault_hist"])
    cols = [kind[:, j] for j in range(kind.shape[1])]
    cols += [fault[:, j] for j in range(fault.shape[1])]
    cols += [np.asarray(per_seed[k]) for k in
             ("drop_loss", "drop_stale", "drop_dead",
              "drop_out_of_time", "drop_overflow", "drop_inf")]
    h = np.full(cols[0].shape, _FNV_SEED, np.uint32)
    for c in cols:
        h = (h ^ _np_bit_length(c)) * np.uint32(_FNV_PRIME)
    return h


@dataclasses.dataclass
class FailureClass:
    """One distinct failure class of a sweep."""

    signature: int               # u32 behavior signature (the bucket key)
    invariant_id: str            # which invariant raised (actor-declared)
    seeds: np.ndarray            # failing seed ids in this class, ascending

    @property
    def representative(self) -> int:
        """Lowest failing seed — deterministic, and the cheapest banner
        line (matches the coverage ledger's lowest-seed attribution)."""
        return int(self.seeds[0])

    @property
    def count(self) -> int:
        return int(self.seeds.size)

    @property
    def key(self) -> str:
        return f"{self.invariant_id}:{self.signature:08x}"


def _invariant_id(result) -> str:
    ctx = getattr(result, "triage_ctx", None)
    actor = getattr(getattr(ctx, "engine", None), "actor", None)
    if actor is None:
        return "unknown"
    return getattr(actor, "invariant_id", type(actor).__name__)


def failure_classes(result) -> List[FailureClass]:
    """Bucket a sweep's failing seeds into distinct failure classes.

    Classes are keyed by (behavior signature, invariant id) and returned
    sorted by representative seed — deterministic for a deterministic
    sweep. Raises ``ValueError`` on a metrics-off sweep (no signature to
    bucket by; run with ``EngineConfig(metrics=True)``).
    """
    m = result.metrics
    if m is None:
        raise ValueError(
            "failure triage needs EngineConfig(metrics=True): failure "
            "classes bucket by the behavior signature of the per-seed "
            "MetricsBlock histograms (docs/triage.md)")
    failing = np.flatnonzero(np.asarray(result.bug))
    if failing.size == 0:
        return []
    sigs = behavior_signatures(m["per_seed"])[failing]
    seeds = np.asarray(result.seeds)[failing].astype(np.int64)
    inv = _invariant_id(result)
    classes = []
    for sig in np.unique(sigs):
        mine = np.sort(seeds[sigs == sig])
        classes.append(FailureClass(signature=int(sig), invariant_id=inv,
                                    seeds=mine))
    classes.sort(key=lambda c: c.representative)
    return classes


def _actor_bundle_info(actor) -> Optional[Dict[str, Any]]:
    """Replay-registry name + config for a bundle, or None when the
    actor type is not registered (the bundle would not replay)."""
    from ..obs.cli import _actor_registry

    for name, (cls, cfg_cls) in _actor_registry().items():
        if type(actor) is cls:
            acfg = next((v for v in vars(actor).values()
                         if isinstance(v, cfg_cls)), None)
            return {"actor": name, "actor_config": acfg}
    return None


@dataclasses.dataclass
class TriageReport:
    """Outcome of :func:`triage`: the deduplicated, minimized corpus."""

    classes: List[FailureClass]
    minimized: Dict[str, MinimizeResult]   # class key → minimization
    bundles: Dict[str, str]                # class key → bundle path

    def summary(self) -> str:
        if not self.classes:
            return "triage: no failing seeds."
        lines = [f"triage: {sum(c.count for c in self.classes)} failing "
                 f"seed(s) in {len(self.classes)} distinct failure "
                 f"class(es)"]
        for c in self.classes:
            line = (f"  class {c.key}: {c.count} seed(s), "
                    f"representative {c.representative}")
            mr = self.minimized.get(c.key)
            if mr is not None:
                line += (f", schedule {mr.original_rows} -> "
                         f"{mr.final_rows} rows")
            if c.key in self.bundles:
                line += f", bundle {self.bundles[c.key]}"
            lines.append(line)
        return "\n".join(lines)


def triage(result, out_dir: Optional[str] = None, *,
           minimize: bool = True, max_steps: int = 20_000,
           **minimize_kw) -> TriageReport:
    """Triage a sweep: dedupe failures into classes, minimize one
    representative per class, optionally emit repro bundles.

    ``result`` is a :class:`~madsim_tpu.parallel.sweep.SweepResult` from
    a metrics-on sweep. With ``minimize=True`` (default) each class's
    representative (lowest failing seed) runs the batched ddmin loop
    against its own fault schedule via ``result.minimize`` — requiring
    the sweep's triage context (engine + schedule refs); pass
    ``minimize=False`` to only bucket. With ``out_dir`` set, one
    ``device_sweep`` repro bundle per class is written there, carrying
    the MINIMIZED schedule rows and the ``minimization`` provenance
    block, replayable via ``python -m madsim_tpu.obs replay --bundle``.
    ``minimize_kw`` forwards to :func:`~.minimize.minimize`
    (``pipeline``, ``weaken``, ``tighten``, ``chunk_steps``, ...).
    """
    classes = failure_classes(result)
    minimized: Dict[str, MinimizeResult] = {}
    bundles: Dict[str, str] = {}
    ctx = getattr(result, "triage_ctx", None)
    if minimize and classes and ctx is None:
        raise TriageError(
            "this SweepResult carries no triage context (it was merged "
            "or reconstructed): re-run the sweep, or call "
            "triage(result, minimize=False) to only bucket failures")
    for fc in classes:
        mr = None
        if minimize:
            mr = result.minimize(seed=fc.representative,
                                 max_steps=max_steps, **minimize_kw)
            minimized[fc.key] = mr
        if out_dir is None:
            continue
        from ..obs.bundle import write_sweep_bundle

        info = (_actor_bundle_info(ctx.engine.actor)
                if ctx is not None else None) or \
            {"actor": _invariant_id(result), "actor_config": None}
        ecfg = ctx.engine.cfg if ctx is not None else None
        frows = (mr.schedule if mr is not None
                 else _class_schedule(result, fc))
        extra: Dict[str, Any] = {
            "failure_class": fc.key, "n_seeds": fc.count,
            "seeds_sample": [int(s) for s in fc.seeds[:16]]}
        spec = (getattr(ctx.engine.actor, "spec", None)
                if ctx is not None else None)
        if spec is not None:
            # Spec-backed (actorc) actor: the bundle carries its
            # protocol card — the speclint static profile (kinds x
            # handlers, timer graph, lane budgets) — so a minimized
            # bug documents the protocol shape it was found against.
            from ..analysis.speclint import protocol_card

            extra["protocol_card"] = protocol_card(spec)
        bb = _class_blackbox(result, fc)
        if bb is not None:
            # Blackbox-on sweep: attach the representative's decoded
            # flight-recorder ring (madsim.blackbox/1). The block
            # carries its OWN replay recipe — the ORIGINAL schedule
            # rows the ring was recorded under plus the world's final
            # step count — because the bundle's top-level rows are the
            # MINIMIZED schedule, which replays the bug but not the
            # recorded execution. `obs replay --crosscheck` uses the
            # block's recipe to verify ring == trace suffix, bitwise.
            extra["blackbox"] = bb
        bundles[fc.key] = write_sweep_bundle(
            out_dir, seed=fc.representative, actor=info["actor"],
            actor_config=info["actor_config"], engine_config=ecfg,
            faults=frows if frows is not None and len(frows) else None,
            max_steps=max_steps,
            error=(f"invariant violation: {fc.invariant_id} "
                   f"(failure class {fc.key})"),
            minimization=(mr.provenance() if mr is not None else None),
            lineage=_class_lineage(result, fc),
            extra=extra)
    return TriageReport(classes=classes, minimized=minimized,
                        bundles=bundles)


def _class_lineage(result, fc: FailureClass) -> Optional[Dict[str, Any]]:
    """The ``madsim.search.lineage/1`` provenance block for a guided
    find (obs/lineage.py): the representative's ancestry chain plus the
    hunt's operator outcome table, so a minimized bundle documents its
    own derivation. None on non-guided sweeps or lineage-off hunts."""
    from ..obs.lineage import lineage_block

    rep = getattr(result, "search", None)
    lin = getattr(rep, "lineage", None) if rep is not None else None
    if lin is None:
        return None
    rows = np.flatnonzero(
        np.asarray(result.seeds) == np.uint64(fc.representative))
    if rows.size == 0:
        return None
    return lineage_block(lin, int(rows[0]), seeds=np.asarray(result.seeds),
                         stats=rep.operator_stats)


def _class_blackbox(result, fc: FailureClass) -> Optional[Dict[str, Any]]:
    """The representative's ``madsim.blackbox/1`` block (obs/blackbox.py)
    for a blackbox-on sweep: the decoded in-situ event ring plus the
    self-contained replay recipe (RAW original schedule rows — NOT
    compacted/normalized, which could reorder equal-time pushes and
    break the bitwise ring == trace-suffix contract — and the world's
    final step count). None when the sweep ran blackbox-off."""
    from ..obs.blackbox import blackbox_block, ring_depth

    obs = result.observations
    k = ring_depth(obs)
    if k is None:
        return None
    rows = np.flatnonzero(
        np.asarray(result.seeds) == np.uint64(fc.representative))
    if rows.size == 0:
        return None
    row = int(rows[0])
    ctx = getattr(result, "triage_ctx", None)
    frows = None
    if ctx is not None and ctx.faults is not None:
        frows = np.asarray(ctx.faults, np.int32)
        if frows.ndim == 3:
            frows = frows[row]
    entries = result.blackbox(seed=fc.representative)
    return blackbox_block(
        entries, seed=fc.representative, k=k,
        pos=int(np.asarray(obs["bb_pos"])[row]),
        steps=int(np.asarray(obs["steps"])[row]), faults=frows)


def _class_schedule(result, fc: FailureClass) -> Optional[np.ndarray]:
    """The representative's ORIGINAL schedule rows, compacted to the
    live rows (minimize=False path)."""
    from .shrink import compact, normalize

    ctx = getattr(result, "triage_ctx", None)
    if ctx is None or ctx.faults is None:
        return None
    faults = np.asarray(ctx.faults, np.int32)
    if faults.ndim == 3:
        row = int(np.flatnonzero(
            np.asarray(result.seeds) == fc.representative)[0])
        faults = faults[row]
    return compact(normalize(faults))
