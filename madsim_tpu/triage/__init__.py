"""Failure triage: batched schedule minimization + deduplicated corpus.

The last mile of the FoundationDB-style hunt (PAPER.md, ROADMAP item 2):
the sweep hands back failing seeds and fault schedules; this package
turns them into artifacts a human can act on —

- :mod:`.shrink` — the schedule algebra: deterministic candidate
  generators over ``(F, 4)`` fault schedules (ddmin row subsets,
  severity weakening, fire-time tightening) and the total
  ``schedule_cost`` order that makes every round's winner unique.
- :mod:`.minimize` — the batched delta-debugging loop: each round's
  candidates run as ONE per-world ``(C, F, 4)`` pipelined sweep against
  the pinned seed (the exact deterministic oracle), to a 1-minimal
  fixpoint. ``minimize(actor, cfg, seed, faults)`` is the entry;
  ``SweepResult.minimize(seed)`` wraps it with the sweep's own context.
- :mod:`.corpus` — the deduplicated bug corpus: failures bucketed into
  classes by behavior signature (obs/coverage.py) + invariant id, one
  representative minimized per class, each emitted as an obs/bundle.py
  repro bundle with a ``minimization`` provenance block.
  ``triage(result)`` is the entry.
- :mod:`.synthetic` — the known-minimal-repro fixture actor
  (``PairRestartActor``) used by tests, ``make triage-demo``, and
  ``bench.py minimize_bug``.

See docs/triage.md for the algebra, the oracle contract, and the bundle
schema; determinism (same inputs → bitwise-identical minimized
schedule, serial == pipelined) is tier-1-gated in tests/test_triage.py.
"""
from .corpus import (
    FailureClass,
    TriageReport,
    behavior_signatures,
    failure_classes,
    triage,
)
from .minimize import (
    MINIMIZATION_SCHEMA,
    MinimizeResult,
    TriageError,
    minimize,
    minimize_rows,
)
from .shrink import as_schedule, compact, n_live, schedule_cost
from .synthetic import PairRestartActor, PairRestartConfig, pair_schedule

__all__ = [
    "minimize", "minimize_rows", "MinimizeResult", "TriageError",
    "MINIMIZATION_SCHEMA",
    "triage", "failure_classes", "FailureClass", "TriageReport",
    "behavior_signatures",
    "as_schedule", "compact", "n_live", "schedule_cost",
    "PairRestartActor", "PairRestartConfig", "pair_schedule",
]
