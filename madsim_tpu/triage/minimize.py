"""Batched delta-debugging: minimize a failing fault schedule.

The FoundationDB-style hunt (PAPER.md) ends with a seed and a fault
schedule that *fail* — this module turns that into a repro a human can
act on: the smallest still-failing schedule, found by running every
candidate shrink of a ddmin round as ONE recycled pipelined sweep
(the DrJAX MapReduce-primitive shape, PAPERS.md: map the oracle over a
``(C, F, 4)`` candidate batch, reduce the per-world bug flags).

Why this is cheap here and expensive everywhere else: deterministic
re-execution makes the "does it still fail?" oracle EXACT — no flaky
retries, no statistical voting — and the batched engine makes evaluating
300 candidates cost the same dispatch count as evaluating one. A classic
host ddmin pays one process run per candidate; this one pays one sweep
per *round*.

Structure:

- :func:`minimize_rows` — the oracle-agnostic ddmin fixpoint loop over
  ``(F, 4)`` schedules (triage/shrink.py generates candidates, the
  caller supplies ``evaluate(candidates) -> still_fails`` over a whole
  round's batch). testing.py reuses it with a host re-run oracle.
- :func:`minimize` — the device entry: pins (actor, config, seed),
  builds the one-sweep-per-round oracle (candidate batches padded to
  power-of-two world counts so compiles stay log-bounded), and runs the
  loop to a 1-minimal fixpoint.

Determinism contract (tier-1, tests/test_triage.py): the same
``(seed, schedule)`` minimizes to a bitwise-identical schedule across
runs and across ``pipeline=True/False`` — candidate generation is a
pure function of the current schedule, the winner tie-break is total
(shrink.schedule_cost), and the sweep oracle itself is the bitwise
serial/pipelined contract of parallel/sweep.py.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .shrink import (
    as_schedule,
    compact,
    n_live,
    normalize,
    schedule_cost,
    single_drop_candidates,
    subset_candidates,
    tighten_candidates,
    weaken_candidates,
)

MINIMIZATION_SCHEMA = "madsim.triage.minimization/1"


class TriageError(RuntimeError):
    """Raised when the minimizer's preconditions fail: the original
    schedule does not fail, or the loop exceeds ``max_rounds``."""


@dataclasses.dataclass
class MinimizeResult:
    """Outcome of one schedule minimization.

    ``schedule`` is the compacted ``(L, 4)`` minimized rows (the array a
    repro bundle records); ``full`` keeps the original ``(F, 4)`` shape
    with dropped rows as DISABLED_ROW sentinels (row positions intact,
    so "which original rows survived" is readable). ``one_minimal``
    certifies the final verification round: dropping any single
    remaining row made the failure disappear.
    """

    seed: int
    original: np.ndarray          # (F, 4) normalized input schedule
    full: np.ndarray              # (F, 4) minimized, positions preserved
    schedule: np.ndarray          # (L, 4) compacted minimized rows
    rounds: int                   # candidate-batch evaluations (sweeps)
    candidates_evaluated: int     # total candidates across all rounds
    weakenings: List[str]         # severity/tightening labels applied
    one_minimal: bool
    history: List[Dict[str, Any]]  # per-round {phase, candidates, ...}
    params: Dict[str, Any]        # oracle knobs (chunk_steps, ...)

    @property
    def original_rows(self) -> int:
        return n_live(self.original)

    @property
    def final_rows(self) -> int:
        return int(self.schedule.shape[0])

    def provenance(self) -> Dict[str, Any]:
        """The ``minimization`` block a repro bundle embeds
        (obs/bundle.py; schema documented in docs/triage.md)."""
        return {
            "schema": MINIMIZATION_SCHEMA,
            "seed": int(self.seed),
            "rounds": int(self.rounds),
            "candidates_evaluated": int(self.candidates_evaluated),
            "original_rows": self.original_rows,
            "final_rows": self.final_rows,
            "weakenings": list(self.weakenings),
            "one_minimal": bool(self.one_minimal),
            "params": dict(self.params),
        }

    def summary(self) -> str:
        w = (f", {len(self.weakenings)} weakening(s)"
             if self.weakenings else "")
        return (f"minimized seed {self.seed}: {self.original_rows} -> "
                f"{self.final_rows} fault rows in {self.rounds} rounds "
                f"({self.candidates_evaluated} candidates{w}; "
                f"1-minimal={'yes' if self.one_minimal else 'no'})")


def minimize_rows(sched0: np.ndarray,
                  evaluate: Callable[[List[np.ndarray]], np.ndarray],
                  *, weaken: bool = True, tighten: bool = False,
                  max_rounds: int = 128
                  ) -> Tuple[np.ndarray, Dict[str, Any]]:
    """The oracle-agnostic batched-ddmin fixpoint loop.

    ``evaluate`` receives one ROUND's candidate schedules (a list of
    ``(F, 4)`` arrays) and returns a bool vector — True where the
    candidate STILL FAILS. It is called once per round; how a round is
    executed (one device sweep, sequential host re-runs) is entirely the
    caller's. Returns ``(minimized_full_schedule, stats)`` where stats
    carries rounds / candidates / history / weakenings / one_minimal.

    Phases: (1) verify the input fails (and try the empty schedule — a
    schedule-independent failure short-circuits to zero rows); (2) ddmin
    row reduction to a fixpoint where no subset/complement at any
    granularity still fails; (3) optional severity weakening (and
    opt-in fire-time tightening), greedily adopting the cheapest
    still-failing candidate per round; (4) 1-minimality verification —
    the final schedule must fail and every single-row drop must not;
    a drop that still fails (weakening can shift dynamics) is adopted
    and the loop re-verifies, so the result is a true fixpoint.
    """
    cur = normalize(np.asarray(sched0, np.int32))
    rounds = 0
    cands_total = 0
    history: List[Dict[str, Any]] = []
    weakenings: List[str] = []

    def run_round(phase: str, pairs: List[Tuple[str, np.ndarray]]
                  ) -> np.ndarray:
        nonlocal rounds, cands_total
        if rounds >= max_rounds:
            raise TriageError(
                f"minimization did not converge in {max_rounds} rounds "
                f"({cands_total} candidates evaluated) — raise max_rounds")
        fails = np.asarray(evaluate([p[1] for p in pairs]), bool)
        assert fails.shape == (len(pairs),), \
            f"oracle returned {fails.shape} for {len(pairs)} candidates"
        rounds += 1
        cands_total += len(pairs)
        history.append({"phase": phase, "candidates": len(pairs),
                        "failing": int(fails.sum())})
        return fails

    def pick_winner(pairs, fails) -> Optional[int]:
        """Deterministic round winner: the cheapest still-failing
        candidate under shrink.schedule_cost (a total order)."""
        win = [i for i in range(len(pairs)) if fails[i]]
        if not win:
            return None
        return min(win, key=lambda i: schedule_cost(pairs[i][1]))

    # -- phase 1: verify the failure (and the empty short-circuit) -------
    empty = np.broadcast_to(np.array([-1, 0, 0, 0], np.int32),
                            cur.shape).copy()
    pairs0: List[Tuple[str, np.ndarray]] = [("original", cur)]
    if n_live(cur):
        pairs0.append(("empty", empty))
    fails = run_round("verify-original", pairs0)
    if not fails[0]:
        raise TriageError(
            "the seed does not fail under the original schedule — "
            "nothing to minimize (check seed/config/schedule drift)")
    if len(pairs0) > 1 and fails[1]:
        # Failure is schedule-independent: the minimal schedule is empty.
        cur = empty

    # -- phase 2: ddmin row reduction ------------------------------------
    k = 2
    while n_live(cur):
        pairs = subset_candidates(cur, k)
        fails = run_round(f"ddmin:k={min(k, n_live(cur))}", pairs)
        best = pick_winner(pairs, fails)
        if best is not None:
            label = pairs[best][0]
            history[-1]["adopted"] = label
            cur = normalize(pairs[best][1])
            # Classic ddmin schedule: reduce-to-subset restarts at the
            # coarsest granularity; reduce-to-complement refines by one.
            k = 2 if label.startswith(("subset", "drop")) else max(k - 1, 2)
        else:
            if k >= n_live(cur):
                break  # tested every single-row drop: row-phase fixpoint
            k = min(2 * k, n_live(cur))

    # -- phase 3: severity weakening / fire-time tightening --------------
    while weaken or tighten:
        pairs = ((weaken_candidates(cur) if weaken else [])
                 + (tighten_candidates(cur) if tighten else []))
        if not pairs:
            break
        fails = run_round("weaken", pairs)
        best = pick_winner(pairs, fails)
        if best is None:
            break
        history[-1]["adopted"] = pairs[best][0]
        weakenings.append(pairs[best][0])
        cur = normalize(pairs[best][1])

    # -- phase 4: 1-minimality verification (a true fixpoint) ------------
    one_minimal = False
    while True:
        pairs = [("final", cur)] + single_drop_candidates(cur)
        fails = run_round("verify-1min", pairs)
        if not fails[0]:
            raise TriageError(
                "the minimized schedule stopped failing at verification "
                "— the oracle is not deterministic?")
        best = pick_winner(pairs[1:], fails[1:])
        if best is None:
            one_minimal = True
            break
        # A single-row drop still fails (weakening shifted the dynamics):
        # adopt it — the verify round doubles as a reduction round — and
        # go around again until the drop set is clean.
        history[-1]["adopted"] = pairs[1 + best][0]
        cur = normalize(pairs[1 + best][1])

    stats = {"rounds": rounds, "candidates_evaluated": cands_total,
             "history": history, "weakenings": weakenings,
             "one_minimal": one_minimal}
    return cur, stats


def minimize(actor: Any, cfg: Any, seed: int, faults,
             *, engine: Any = None, mesh: Any = None,
             chunk_steps: int = 64, max_steps: int = 20_000,
             pipeline: bool = True, weaken: bool = True,
             tighten: bool = False, max_rounds: int = 128
             ) -> MinimizeResult:
    """Minimize a failing ``(seed, fault schedule)`` on the device engine.

    Each round's candidates are stacked into ONE per-world ``(C, F, 4)``
    faults array and evaluated as a single pipelined sweep against the
    pinned seed (every world simulates the same seed under a different
    candidate schedule); the round's winner is the cheapest still-failing
    candidate under the deterministic :func:`~.shrink.schedule_cost`
    order. Candidate batches are padded to power-of-two world counts
    (replicating candidate 0, whose verdict is already known), so the
    sweep programs compile for at most log2 batch widths per call.

    ``engine`` (optional) reuses an existing ``DeviceEngine`` — and its
    compiled programs — for ``(actor, cfg)``; ``pipeline`` selects the
    sweep orchestration path and MUST NOT change the result (bitwise,
    tier-1). ``weaken`` enables the severity-weakening phase;
    ``tighten`` opts into fire-time halving (it rewrites row times, so
    the minimized rows are no longer a subset of the originals —
    off by default). Raises :class:`TriageError` if the seed does not
    fail under the original schedule or the loop exceeds ``max_rounds``.
    """
    from ..engine.core import DeviceEngine
    from ..parallel.mesh import seed_mesh
    from ..parallel.sweep import _pow2_at_least, sweep

    eng = engine if engine is not None else DeviceEngine(actor, cfg)
    mesh = mesh if mesh is not None else seed_mesh()
    n_dev = int(mesh.devices.size)
    sched0 = as_schedule(faults)

    def evaluate(cands: List[np.ndarray]) -> np.ndarray:
        c = len(cands)
        # Pad the batch to a power-of-two width (>= the mesh): bounded
        # compiles across rounds of varying candidate counts. Pad rows
        # replicate candidate 0 and are sliced off the verdict.
        w = max(_pow2_at_least(c), n_dev)
        arr = np.stack(list(cands) + [cands[0]] * (w - c)) \
            .astype(np.int32, copy=False)
        res = sweep(None, eng.cfg, np.full(w, seed, np.uint64),
                    faults=arr, engine=eng, mesh=mesh,
                    chunk_steps=chunk_steps, max_steps=max_steps,
                    pipeline=pipeline)
        return np.asarray(res.bug[:c], bool)

    final, stats = minimize_rows(sched0, evaluate, weaken=weaken,
                                 tighten=tighten, max_rounds=max_rounds)
    return MinimizeResult(
        seed=int(seed), original=sched0, full=final,
        schedule=compact(final),
        rounds=stats["rounds"],
        candidates_evaluated=stats["candidates_evaluated"],
        weakenings=stats["weakenings"],
        one_minimal=stats["one_minimal"],
        history=stats["history"],
        params={"chunk_steps": int(chunk_steps),
                "max_steps": int(max_steps),
                "pipeline": bool(pipeline), "weaken": bool(weaken),
                "tighten": bool(tighten)},
    )
