"""Synthetic minimal-repro actor: a bug with a KNOWN minimal schedule.

``PairRestartActor`` raises its invariant iff BOTH of two designated
nodes (``node_a``, ``node_b``) have been restarted at least once — a
conjunction over fault-schedule rows, so a schedule's minimal failing
subset is exactly {the one row restarting ``node_a``, the one row
restarting ``node_b``} when every other row restarts filler nodes.

That known answer is what makes it the triage test fixture, the
``make triage-demo`` workload, and the ``bench.py minimize_bug``
config: the batched ddmin loop (triage/minimize.py) must converge to
exactly those two rows, bitwise-identically across runs and across the
serial/pipelined sweep paths, and the 1-minimality verification has
ground truth to be checked against.

It is also registered in the replay registry (obs/cli.py, actor name
``pair_restart``), so minimized repro bundles emitted by the corpus
replay end to end through ``python -m madsim_tpu.obs replay``.
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

import jax.numpy as jnp
import numpy as np

from ..engine.core import FAULT_RESTART, EngineConfig, Outbox
from ..engine.lanes import take_small, upd
from ..engine.queue import Event


@dataclasses.dataclass(frozen=True)
class PairRestartConfig:
    """Static parameters of the synthetic pair-restart bug."""

    n: int = 4        # nodes per world (engine n_nodes must match)
    node_a: int = 1   # the invariant fires when BOTH of these nodes
    node_b: int = 2   # have been restarted at least once


class PairRestartActor:
    """Counts per-node restarts; the bug is ``restarts[a] & restarts[b]``.

    Deliberately minimal: one seed message keeps the world alive for a
    first delivered step, every fault row in the schedule is an engine-
    level ``FAULT_RESTART`` whose ``on_restart`` hook bumps the counter,
    and the invariant is a pure conjunction over the counter lane — no
    timing, no randomness, so the failure depends ONLY on which schedule
    rows are enabled (the property the ddmin convergence tests pin).
    """

    num_kinds = 1
    invariant_id = "pair_restart_conjunction"

    def __init__(self, acfg: PairRestartConfig = PairRestartConfig()):
        self.acfg = acfg

    def init(self, cfg: EngineConfig, rng):
        s = {"restarts": jnp.zeros((cfg.n_nodes,), jnp.int32)}
        # One seed message so even an empty-schedule world delivers a
        # step (and the world's step/delivery observations are nonzero).
        evs = [Event.make(time=1, kind=0,
                          payload_words=cfg.payload_words)]
        return s, evs, rng

    def handle(self, cfg, s, ev, now, rng):
        return s, Outbox.empty(cfg), rng, jnp.asarray(False)

    def on_restart(self, cfg, s, node, now, rng):
        restarts = upd(s["restarts"], node,
                       take_small(s["restarts"], node) + 1)
        return {"restarts": restarts}, Outbox.empty(cfg), rng

    def invariant(self, cfg, s):
        a, b = self.acfg.node_a, self.acfg.node_b
        return (s["restarts"][..., a] > 0) & (s["restarts"][..., b] > 0)

    def observe(self, cfg, s):
        a, b = self.acfg.node_a, self.acfg.node_b
        return {
            "restarts_a": s["restarts"][..., a],
            "restarts_b": s["restarts"][..., b],
            # dtype-pinned sum: a bare jnp.sum widens to i64 under the
            # x64 flag (tracelint TRC003).
            "restarts_total": jnp.sum(s["restarts"], axis=-1,
                                      dtype=jnp.int32),
        }


def pair_schedule(n_rows: int = 32, need: Tuple[int, int] = (5, 20),
                  acfg: PairRestartConfig = PairRestartConfig(),
                  filler_node: int = 0, t0_us: int = 10_000,
                  dt_us: int = 10_000) -> np.ndarray:
    """A ``(n_rows, 4)`` restart schedule whose minimal failing subset
    is exactly rows ``need``: row ``need[0]`` restarts ``node_a``, row
    ``need[1]`` restarts ``node_b``, every other row restarts
    ``filler_node`` (times strictly increasing, so rows are distinct)."""
    i, j = need
    if not (0 <= i < n_rows and 0 <= j < n_rows and i != j):
        raise ValueError(f"need rows must be two distinct indices in "
                         f"[0, {n_rows}); got {need}")
    rows = np.zeros((n_rows, 4), np.int32)
    rows[:, 0] = t0_us + dt_us * np.arange(n_rows)
    rows[:, 1] = FAULT_RESTART
    rows[:, 2] = filler_node
    rows[i, 2] = acfg.node_a
    rows[j, 2] = acfg.node_b
    return rows


def engine_config(acfg: PairRestartConfig = PairRestartConfig(),
                  metrics: bool = False) -> EngineConfig:
    """The canonical engine config for this actor (small queue — the
    schedule is the only event source beyond the seed message)."""
    return EngineConfig(n_nodes=acfg.n, outbox_cap=2, queue_cap=64,
                        t_limit_us=2_000_000, metrics=metrics)
