"""The actor-compiler spec model: protocol state machines as data.

A :class:`ActorSpec` names everything the device engine needs to know
about a protocol family — per-node state lanes with *declared value
ranges*, messages and timers with *typed payload words*, guarded
transitions as restricted pure expressions, invariants, and
restart (disk-vs-memory) annotations — and everything it deliberately
does NOT let you say: no Python control flow on traced values, no raw
``x[i]`` indexing, no unbounded RNG draws. The compiler
(:mod:`madsim_tpu.actorc.compile`) lowers a validated spec to a
DeviceEngine actor with the packed-lane layout, a single
``actor_util.make_outbox`` assembly and ``widen``-on-read /
saturating-``narrow``-on-write boundaries placed by construction, while
:mod:`madsim_tpu.actorc.host` generates a plain-Python reference
interpreter from the *same* spec for conformance crosscheck
(docs/actorc.md).

Validation happens at two points, both BEFORE any deep trace-time
failure could occur:

- spec-internal checks (:func:`validate_spec` with no config): duplicate
  names, inverted ranges, unknown handler names, kind-count limits;
- config-facing checks (:func:`validate_spec` with an ``EngineConfig``):
  the packed-width guards — ``n_nodes`` vs the int8 node lane, declared
  payload-word ranges vs the int16 at-rest payload lane, outbox
  capacity vs the (N peers + 1 timer) layout — re-raised as
  :class:`SpecError` with pointed spec-line messages naming the lane /
  message / word that violates, instead of an opaque XLA shape error.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping, Optional, Tuple

import jax.numpy as jnp

__all__ = [
    "ActorSpec", "Lane", "Message", "Word", "SpecError", "validate_spec",
    "lane_dtype",
]


class SpecError(ValueError):
    """A spec that cannot compile, with the offending declaration named."""


# Lane scopes: the array shape a lane lowers to (N = spec.n_nodes,
# K = Lane.cols, leading world axis added by the engine's vmap).
SCOPE_NODE = "node"              # (N,)   one value per node
SCOPE_NODE_TABLE = "node_table"  # (N, K) one row per node
SCOPE_WORLD_VEC = "world_vec"    # (K,)   one world-global vector
SCOPE_WORLD = "world"            # ()     one world-global scalar
_SCOPES = (SCOPE_NODE, SCOPE_NODE_TABLE, SCOPE_WORLD_VEC, SCOPE_WORLD)

# Lane kinds: how the declared range maps to a dtype.
KIND_VALUE = "value"      # range-narrowed (i8/i16/i32 from [lo, hi])
KIND_BITMASK = "bitmask"  # always int32: width is bit capacity, not range
KIND_COUNTER = "counter"  # always int32 world scalar; auto-observed
_KINDS = (KIND_VALUE, KIND_BITMASK, KIND_COUNTER)


@dataclasses.dataclass(frozen=True)
class Lane:
    """One state lane: a named, range-declared array of the actor state.

    ``lo``/``hi`` are the *inclusive* declared value range; the compiler
    selects the at-rest dtype from it (:func:`lane_dtype`) — the
    PR 10 packing discipline applied by construction rather than by
    hand. ``durable=False`` marks the lane volatile across a node
    restart (the disk-vs-memory annotation): the restarting node's row
    resets to ``reset`` before the spec's ``on_restart`` hook runs.
    World-scoped lanes must stay durable — a single node's restart has
    no business wiping world-global state; express partial resets in
    the ``on_restart`` hook instead (the tpc spec does).
    """

    name: str
    hi: int
    lo: int = 0
    scope: str = SCOPE_NODE
    cols: int = 0                # required for *_TABLE / *_VEC scopes
    kind: str = KIND_VALUE
    durable: bool = True
    reset: int = 0
    init: int = 0


@dataclasses.dataclass(frozen=True)
class Word:
    """One typed payload word of a message/timer, with its declared
    (inclusive) value range — the packed int16 at-rest payload guard
    reads these."""

    name: str
    lo: int
    hi: int


@dataclasses.dataclass(frozen=True)
class Message:
    """One event kind. Kind codes are positional: ``spec.messages[k]``
    is kind ``k``, and ``kind_names`` falls out for free — generated
    families always render readably in ``DeviceEngine.trace()`` and the
    timeline export."""

    name: str
    words: Tuple[Word, ...] = ()
    timer: bool = False


@dataclasses.dataclass(frozen=True)
class ActorSpec:
    """A complete protocol-state-machine description (module docstring).

    ``handlers`` maps message names to transition callables ``fn(t)``
    written against the restricted :class:`~madsim_tpu.actorc.compile.Ctx`
    expression surface — the same callable is evaluated by the device
    compiler (jnp values) and the host interpreter (plain ints), which
    is what makes the host twin a *generated* artifact rather than a
    second implementation. ``init`` seeds the world's events;
    ``on_restart`` (optional) runs after the volatile-lane resets;
    ``invariant`` is the per-step bug predicate over full lane views;
    ``observe`` adds derived metrics beyond the auto-exported counters.

    The last three fields feed pass 4 of the analysis stack
    (:mod:`madsim_tpu.analysis.speclint`), which gates compilation:
    ``ignore`` lists message kinds a node may legitimately receive and
    drop (exhaustiveness rule SPC011 demands every other kind be
    handled); ``terminal`` lists kinds whose handlers absorb without
    emitting (declared dead ends — an undeclared no-op transition is
    SPC012, a terminal kind that still emits is SPC013); ``lint_allow``
    names SPC codes this spec deliberately trips (the intentionally
    buggy experiment variants), with ``("*",)`` as the fixture escape
    hatch that waives the pass entirely. A ``lint_allow`` code that
    suppresses nothing is itself a finding (SPC900), so allowances
    cannot go stale.
    """

    name: str
    n_nodes: int
    lanes: Tuple[Lane, ...]
    messages: Tuple[Message, ...]
    handlers: Mapping[str, Callable[[Any], None]]
    init: Callable[[Any], None]
    invariant: Callable[[Any], Any]
    on_restart: Optional[Callable[[Any], None]] = None
    observe: Mapping[str, Callable[[Any], Any]] = \
        dataclasses.field(default_factory=dict)
    invariant_id: str = ""
    ignore: Tuple[str, ...] = ()
    terminal: Tuple[str, ...] = ()
    lint_allow: Tuple[str, ...] = ()

    def lane(self, name: str) -> Lane:
        for ln in self.lanes:
            if ln.name == name:
                return ln
        raise SpecError(f"spec {self.name!r}: unknown lane {name!r} "
                        f"(declared: {[x.name for x in self.lanes]})")

    def kind_of(self, msg_name: str) -> int:
        for k, m in enumerate(self.messages):
            if m.name == msg_name:
                return k
        raise SpecError(f"spec {self.name!r}: unknown message "
                        f"{msg_name!r} (declared: "
                        f"{[m.name for m in self.messages]})")

    def message(self, msg_name: str) -> Message:
        return self.messages[self.kind_of(msg_name)]


def _fits(lo: int, hi: int, bits: int) -> bool:
    return lo >= -(1 << (bits - 1)) and hi <= (1 << (bits - 1)) - 1


def lane_dtype(lane: Lane, lanes) -> Any:
    """The at-rest dtype of ``lane`` under a
    :class:`~madsim_tpu.engine.lanes.Lanes` profile: the narrowest
    registry category the declared range fits — i8 via the code lane,
    i16 via the slot lane, else wide — so packing decisions are a pure
    function of the declaration (under the WIDE profile every category
    is int32 and this degrades to the reference layout for free).
    Bitmask and counter lanes stay int32 in both profiles, exactly like
    the hand-written actors' vote/ack masks and counters."""
    if lane.kind in (KIND_BITMASK, KIND_COUNTER):
        return jnp.int32
    if _fits(lane.lo, lane.hi, 8):
        return lanes.code
    if _fits(lane.lo, lane.hi, 16):
        return lanes.slot
    return jnp.int32


def _check(ok: bool, msg: str) -> None:
    if not ok:
        raise SpecError(msg)


def validate_spec(spec: ActorSpec, cfg=None) -> None:
    """Validate ``spec`` — alone, or against an ``EngineConfig``.

    Raises :class:`SpecError` with a message naming the offending
    declaration. The config-facing half re-raises the engine's packed
    width limits at the *spec* level: by the time a bad spec would have
    failed deep inside a trace (an int8 node id aliasing, a payload
    word saturating silently), the error here has already named the
    exact lane or message word to fix.
    """
    who = f"spec {spec.name!r}"
    _check(bool(spec.messages), f"{who}: declares no messages")
    _check(len(spec.messages) <= 64,
           f"{who}: declares {len(spec.messages)} event kinds; the packed "
           "event queue carries kinds in 6 bits (max 64)")
    names = [m.name for m in spec.messages]
    _check(len(set(names)) == len(names),
           f"{who}: duplicate message names {sorted(names)}")
    lnames = [x.name for x in spec.lanes]
    _check(len(set(lnames)) == len(lnames),
           f"{who}: duplicate lane names {sorted(lnames)}")
    _check(spec.n_nodes >= 1, f"{who}: n_nodes must be >= 1")
    for h in spec.handlers:
        _check(h in names,
               f"{who}: handler for unknown message {h!r} "
               f"(declared: {names})")
    for ln in spec.lanes:
        w = f"{who}: lane {ln.name!r}"
        _check(ln.scope in _SCOPES, f"{w}: unknown scope {ln.scope!r}")
        _check(ln.kind in _KINDS, f"{w}: unknown kind {ln.kind!r}")
        _check(ln.lo <= ln.hi,
               f"{w}: declared range [{ln.lo}, {ln.hi}] is inverted")
        if ln.scope in (SCOPE_NODE_TABLE, SCOPE_WORLD_VEC):
            _check(ln.cols >= 1, f"{w}: scope {ln.scope!r} needs cols >= 1")
        if ln.kind == KIND_COUNTER:
            _check(ln.scope == SCOPE_WORLD,
                   f"{w}: counters are world scalars (scope='world')")
        if ln.kind == KIND_BITMASK:
            _check(spec.n_nodes <= 31,
                   f"{w}: int32 bitmask lanes hold at most 31 node bits "
                   f"(n_nodes={spec.n_nodes})")
        if not ln.durable:
            _check(ln.scope in (SCOPE_NODE, SCOPE_NODE_TABLE),
                   f"{w}: durable=False (volatile across restart) is "
                   "only meaningful for per-node lanes; reset "
                   "world-scoped state in the on_restart hook instead")
            _check(ln.lo <= ln.reset <= ln.hi,
                   f"{w}: restart reset value {ln.reset} is outside the "
                   f"declared range [{ln.lo}, {ln.hi}]")
        _check(ln.lo <= ln.init <= ln.hi,
               f"{w}: init value {ln.init} is outside the declared "
               f"range [{ln.lo}, {ln.hi}]")
    for m in spec.messages:
        wnames = [x.name for x in m.words]
        _check(len(set(wnames)) == len(wnames),
               f"{who}: message {m.name!r} has duplicate word names "
               f"{sorted(wnames)}")
        for wd in m.words:
            _check(wd.lo <= wd.hi,
                   f"{who}: message {m.name!r} word {wd.name!r} declares "
                   f"an inverted range [{wd.lo}, {wd.hi}]")

    if cfg is None:
        return
    _check(cfg.n_nodes == spec.n_nodes,
           f"{who}: declares n_nodes={spec.n_nodes} but "
           f"EngineConfig.n_nodes={cfg.n_nodes}")
    if cfg.packed and spec.n_nodes > 127:
        raise SpecError(
            f"{who}: n_nodes={spec.n_nodes} exceeds the packed int8 node "
            "lane (max 127). Compile against EngineConfig(packed=False) "
            "— the int32 reference profile — or shrink the cluster.")
    _check(cfg.m == spec.n_nodes + 1,
           f"{who}: compiled actors use the (N peers + 1 timer) "
           f"actor_util.make_outbox layout — EngineConfig outbox "
           f"capacity must be n_nodes + 1 = {spec.n_nodes + 1}, got "
           f"{cfg.m}")
    need_words = max((len(m.words) for m in spec.messages), default=0)
    _check(cfg.payload_words >= need_words,
           f"{who}: message payloads declare up to {need_words} words "
           f"but EngineConfig.payload_words={cfg.payload_words}")
    if cfg.packed:
        for m in spec.messages:
            for wd in m.words:
                if not _fits(wd.lo, wd.hi, 16):
                    raise SpecError(
                        f"{who}: message {m.name!r} word {wd.name!r} "
                        f"declares range [{wd.lo}, {wd.hi}], which "
                        "overflows the packed int16 at-rest payload "
                        "lane — a value past +-32767 would saturate "
                        "silently in the queue. Narrow the declared "
                        "range, split the value across two words, or "
                        "compile with packed=False.")
