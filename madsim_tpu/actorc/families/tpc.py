"""Two-phase commit as an actorc spec — the migrated third family.

A 1:1 transliteration of the formerly hand-written
:mod:`madsim_tpu.engine.tpc_actor` merged handler into the DSL: same
lanes (at the same packed dtypes, now derived from declared ranges
instead of hand-picked), same message payload words, same single
RNG draw consumed only by PREPARE, same coordinator-volatile restart
semantics — so trajectories are bit-identical to the hand-written
actor and the original test suite (tests/test_tpc_actor.py) passes
unchanged against the compiled build. See the module docstring of the
old implementation (now in the spec comments below) for the protocol
itself: node 0 coordinates textbook 2PC over ``n_txns`` scheduled
transactions; the atomicity invariant is the bug flag, and
``buggy_presumed_commit`` decides COMMIT on vote timeout — the unsound
shortcut seed sweeps catch at apply time.
"""
from __future__ import annotations

from ..spec import ActorSpec, Lane, Message, Word

# Decision codes.
NONE, COMMIT, ABORT = 0, 1, 2

COORD = 0  # node 0 coordinates; 1..n-1 participate


def tpc_spec(tcfg) -> ActorSpec:
    """Build the 2PC spec from a
    :class:`~madsim_tpu.engine.tpc_actor.TPCDeviceConfig`."""
    t = tcfg
    n, T = t.n, t.n_txns
    if n < 2 or n > 31:
        from ..spec import SpecError

        raise SpecError("tpc spec needs 2..31 nodes (int32 vote bitmask)")

    lanes = (
        # Applied outcome per (node, txn) — the 2PC write-ahead record.
        Lane("decision", hi=2, scope="node_table", cols=T),
        # Participant's sent vote (NONE / COMMIT=yes / ABORT=no).
        Lane("voted", hi=2, scope="node_table", cols=T),
        # Coordinator's yes bitmask per txn: volatile in PRINCIPLE but
        # world-scoped (only the coordinator writes it), so the
        # conditional reset lives in the on_restart hook below.
        Lane("votes_yes", hi=(1 << 31) - 1, scope="world_vec", cols=T,
             kind="bitmask"),
        # Coordinator's decision record (durable).
        Lane("decided", hi=2, scope="world_vec", cols=T),
        Lane("txns_seen", hi=(1 << 31) - 1, scope="world", kind="counter"),
        Lane("commits", hi=(1 << 31) - 1, scope="world", kind="counter"),
        Lane("aborts", hi=(1 << 31) - 1, scope="world", kind="counter"),
    )

    messages = (
        Message("Txn", (Word("txn", 0, T - 1),)),
        Message("Prepare", (Word("txn", 0, T - 1),)),
        Message("Vote", (Word("txn", 0, T - 1), Word("yes", 0, 1),
                         Word("voter", 0, n - 1))),
        Message("Decide", (Word("txn", 0, T - 1),
                           Word("decision", 0, 2))),
        Message("Timeout", (Word("txn", 0, T - 1),), timer=True),
    )

    # -- transitions ---------------------------------------------------
    def h_txn(c):
        """Coordinator: start 2PC for a scheduled transaction."""
        txn = c.clip(c.arg("txn"), 0, T - 1)
        start = (c.me == COORD) & (c.read_vec_at("decided", txn) == NONE)
        c.count("txns_seen", when=start)
        c.broadcast("Prepare", [txn], when=start,
                    to=c.arange(n) != COORD)
        c.arm("Timeout", delay=t.vote_timeout_us, words=[txn],
              when=start, dst=COORD)

    def h_prepare(c):
        """Participant: vote once; a no-voter aborts unilaterally (it
        holds no locks for a transaction it rejected)."""
        txn = c.clip(c.arg("txn"), 0, T - 1)
        my_vote = c.read_at("voted", txn)
        fresh = (c.me != COORD) & (my_vote == NONE) & \
            (c.read_at("decision", txn) == NONE)
        vote_no = (c.u32() % 256) < t.no_vote_num
        vote_val = c.where(vote_no, ABORT, COMMIT)  # ABORT code == "no"
        c.write_at("voted", txn, vote_val, when=fresh)
        c.write_at("decision", txn, ABORT, when=fresh & vote_no)
        c.send("Vote", dst=COORD,
               words=[txn, c.where(vote_val == COMMIT, 1, 0), c.me],
               when=fresh)

    def h_vote(c):
        """Coordinator: collect votes; all-yes => COMMIT, any-no =>
        ABORT, immediately."""
        txn = c.clip(c.arg("txn"), 0, T - 1)
        decided_t = c.read_vec_at("decided", txn)
        live = (c.me == COORD) & (decided_t == NONE)
        voter = c.clip(c.arg("voter"), 0, n - 1)
        yes = c.arg("yes") == 1
        mask_all = (1 << n) - 2  # bits 1..n-1
        yes2 = c.read_vec_at("votes_yes", txn) | \
            c.where(live & yes, 1 << voter, 0)
        c.write_vec_at("votes_yes", txn, yes2)
        all_yes = live & (yes2 == mask_all)
        any_no = live & ~yes
        decide = all_yes | any_no
        val = c.where(all_yes, COMMIT, ABORT)
        _decide(c, txn, decide, val)

    def h_timeout(c):
        """Coordinator: decide for the stragglers on vote timeout —
        ABORT, or COMMIT under the injected presumed-commit bug."""
        txn = c.clip(c.arg("txn"), 0, T - 1)
        fire = (c.me == COORD) & (c.read_vec_at("decided", txn) == NONE)
        val = COMMIT if t.buggy_presumed_commit else ABORT
        _decide(c, txn, fire, val)

    def _decide(c, txn, decide, val):
        """Shared coordinator decision tail: record, count, broadcast."""
        c.write_vec_at("decided", txn, val, when=decide)
        c.write_at("decision", txn, val, when=decide)
        c.count("commits", when=decide & (val == COMMIT))
        c.count("aborts", when=decide & (val == ABORT))
        c.broadcast("Decide", [txn, val], when=decide,
                    to=c.arange(n) != COORD)

    def h_decide(c):
        """Participant: apply the coordinator's decision — unless it
        aborted unilaterally and the coordinator says COMMIT; that
        conflict IS the apply-time state the invariant reads."""
        txn = c.clip(c.arg("txn"), 0, T - 1)
        applied = c.read_at("decision", txn)
        apply_dec = (c.me != COORD) & (applied == NONE)
        c.write_at("decision", txn, c.arg("decision"), when=apply_dec)

    # -- init / restart / invariant ------------------------------------
    def init(c):
        for i in range(t.n_txns):
            c.event("Txn", time=t.txn_start_us + i * t.txn_interval_us,
                    dst=COORD, words=[i])

    def on_restart(c):
        """Decisions, votes and the decision log are durable (the 2PC
        write-ahead records); the coordinator's in-flight yes bitmasks
        for UNdecided txns are volatile — those txns stay pending until
        their timeout fires (or forever: the blocking window)."""
        volatile = c.read_vec("decided") == NONE
        c.write_vec("votes_yes",
                    c.where((c.me == COORD) & volatile, 0,
                            c.read_vec("votes_yes")))

    def invariant(v):
        """Atomicity: no txn both committed and aborted across nodes."""
        dec = v.lane("decision")
        committed = v.np.any(dec == COMMIT, axis=0)  # (T,)
        aborted = v.np.any(dec == ABORT, axis=0)
        return v.np.any(committed & aborted)

    def obs_blocked(o):
        # Batched state: node axis is -2, txn axis is -1. Yes-voters
        # still waiting for a decision — 2PC's blocking window.
        import jax.numpy as jnp

        applied = o.raw("decision")[..., 1:, :]  # participants only
        return jnp.sum(
            jnp.any((o.raw("voted")[..., 1:, :] == COMMIT)
                    & (applied == NONE), axis=-2).astype(jnp.int32),
            axis=-1)

    return ActorSpec(
        name="tpc",
        n_nodes=n,
        lanes=lanes,
        messages=messages,
        handlers={"Txn": h_txn, "Prepare": h_prepare, "Vote": h_vote,
                  "Decide": h_decide, "Timeout": h_timeout},
        init=init,
        on_restart=on_restart,
        invariant=invariant,
        observe={"blocked": obs_blocked},
        invariant_id="tpc_atomicity",
        terminal=("Decide",),
    )
