"""Primary-backup replication as an actorc spec — the migrated second
family.

A 1:1 transliteration of the formerly hand-written
:mod:`madsim_tpu.engine.pb_actor` merged handler: a view-based
primary-backup log (VR/chain-replication style) — the primary of view v
is node ``v % n``; the primary replicates client writes to every backup
and commits an entry once EVERY replica acked it. Backups that miss the
primary's heartbeat long enough start a view change. There is
deliberately no retransmission or log repair: safety is the subject
under test, not liveness. The durability invariant (every entry ever
reported committed must exist in the current primary's log) is the bug
flag; ``buggy_commit_early`` commits after the FIRST ack — a fault
schedule that kills the primary mid-window then loses a committed write
at failover.

Restart semantics exercise the DSL's disk-vs-memory annotations: log,
commit index, view and epoch are durable; the ack bookkeeping is
``durable=False`` (auto-reset), and the ``on_restart`` hook bumps the
watchdog epoch and re-arms the watchdog timer with a fresh random
delay. Trajectories are bit-identical to the retired hand-written
actor; tests/test_pb_actor.py runs unchanged against this build.
"""
from __future__ import annotations

from ..spec import ActorSpec, Lane, Message, Word

I16 = 32767


def pb_spec(pcfg) -> ActorSpec:
    """Build the primary-backup spec from a
    :class:`~madsim_tpu.engine.pb_actor.PBDeviceConfig`."""
    p = pcfg
    n, L = p.n, p.log_cap

    lanes = (
        # view/log_len/wd_epoch stop short of the int16 rail: their
        # transitions bump by +1 (or +n for the view-change candidate),
        # and speclint's capacity proof (SPC030) demands the bumped
        # value still fit the packed lane the declaration selects.
        Lane("view", hi=32000),                    # current view per node
        Lane("log_len", hi=32000),
        Lane("log_cmd", hi=I16, scope="node_table", cols=L),
        Lane("commit", hi=I16),                    # known-committed index
        Lane("acks", hi=(1 << 31) - 1, scope="node_table", cols=L,
             kind="bitmask", durable=False),       # volatile bookkeeping
        Lane("wd_epoch", hi=32000),                # stale-watchdog guard
        Lane("committed_cmd", hi=I16, scope="world_vec", cols=L),
        Lane("committed_max", hi=I16, scope="world"),
        Lane("views_changed", hi=(1 << 31) - 1, scope="world",
             kind="counter"),
        Lane("writes_done", hi=(1 << 31) - 1, scope="world",
             kind="counter"),
    )

    messages = (
        Message("Write", (Word("cmd", 0, I16),)),
        Message("Replicate", (Word("view", 0, I16), Word("idx", 0, I16),
                              Word("cmd", 0, I16))),
        Message("Ack", (Word("view", 0, I16), Word("idx", 0, I16),
                        Word("backup", 0, n - 1))),
        Message("Commit", (Word("view", 0, I16),
                           Word("commit_idx", 0, I16))),
        Message("Heartbeat", (Word("view", 0, I16),
                              Word("epoch", 0, I16)), timer=True),
        Message("Watchdog", (Word("view", 0, I16),
                             Word("epoch", 0, I16)), timer=True),
    )

    def primary_of(view):
        return view % n

    # -- transitions ---------------------------------------------------
    def h_write(c):
        """Client write (broadcast-scheduled; only the primary acts):
        append and replicate."""
        view_me = c.read("view")
        llen = c.read("log_len")
        accept = (c.me == primary_of(view_me)) & (llen < L)
        pos_w = c.clip(llen, 0, L - 1)
        llen_w = llen + c.where(accept, 1, 0)
        cmd = c.arg("cmd")
        c.write("log_len", llen_w, when=accept)
        c.write_at("log_cmd", pos_w, cmd, when=accept)
        c.write_at("acks", pos_w, 1 << c.me, when=accept)
        c.count("writes_done", when=accept)
        c.broadcast("Replicate", [view_me, llen_w, cmd], when=accept)

    def h_replicate(c):
        """Backup appends in order, adopts the view, re-arms the
        watchdog (Replicate doubles as the heartbeat carrier)."""
        view_me = c.read("view")
        llen = c.read("log_len")
        epoch_me = c.read("wd_epoch")
        v_rep, idx_rep, cmd_rep = c.arg("view"), c.arg("idx"), c.arg("cmd")
        current = v_rep >= view_me
        view_rep = c.maximum(view_me, v_rep)
        in_order = current & (idx_rep == llen + 1) & (idx_rep <= L)
        pos_r = c.clip(idx_rep - 1, 0, L - 1)
        epoch2 = epoch_me + c.where(current, 1, 0)
        c.write("view", view_rep)
        c.write_at("log_cmd", pos_r, cmd_rep, when=in_order)
        c.write("log_len", idx_rep, when=in_order)
        c.write("wd_epoch", epoch2)
        c.send("Ack", dst=primary_of(view_rep),
               words=[view_rep, idx_rep, c.me], when=in_order)
        c.arm("Watchdog", delay=c.uniform(p.watchdog_min_us,
                                          p.watchdog_max_us),
              words=[view_rep, epoch2], when=current)

    def h_ack(c):
        """Primary counts acks; commit on quorum (ALL replicas — or,
        under the injected bug, any two)."""
        view_me = c.read("view")
        commit_me = c.read("commit")
        live = (c.arg("view") == view_me) & \
            (c.me == primary_of(view_me)) & \
            (c.arg("idx") >= 1) & (c.arg("idx") <= L)
        pos_a = c.clip(c.arg("idx") - 1, 0, L - 1)
        backup = c.clip(c.arg("backup"), 0, n - 1)
        acks2 = c.read_at("acks", pos_a) | c.where(live, 1 << backup, 0)
        if p.buggy_commit_early:
            # THE BUG: one ack is "enough". A fault schedule that kills
            # the primary before the rest replicate loses the entry.
            quorum = c.popcount(acks2) >= 2
        else:
            quorum = acks2 == (1 << n) - 1
        committed = live & quorum & (c.arg("idx") > commit_me)
        commit_a = c.where(committed, c.arg("idx"), commit_me)
        krange = c.arange(L)
        fill = committed & (krange >= commit_me) & (krange < c.arg("idx"))
        c.write_at("acks", pos_a, acks2)
        c.write("commit", commit_a)
        c.write_vec("committed_cmd", c.read_row("log_cmd"), when=fill)
        c.write_scalar("committed_max",
                       c.maximum(c.read_scalar("committed_max"),
                                 c.where(committed, c.arg("idx"), 0)))
        c.broadcast("Commit", [view_me, commit_a], when=committed)

    def h_commit(c):
        """Backup adopts the commit index (capped at its log length)."""
        view_me = c.read("view")
        llen = c.read("log_len")
        commit_me = c.read("commit")
        cm_current = c.arg("view") >= view_me
        c.write("commit", c.where(
            cm_current,
            c.maximum(commit_me, c.minimum(c.arg("commit_idx"), llen)),
            commit_me))

    def h_heartbeat(c):
        """Primary's liveness beacon: an idx-0 Replicate every
        heartbeat interval (backups adopt the view + re-arm watchdogs
        through h_replicate)."""
        view_me = c.read("view")
        live = (c.arg("view") == view_me) & (c.me == primary_of(view_me))
        c.broadcast("Replicate", [view_me, 0, 0], when=live)
        c.arm("Heartbeat", delay=p.heartbeat_us, words=[view_me, 0],
              when=live)

    def h_watchdog(c):
        """Primary-silence detector: a backup whose watchdog epoch is
        still current starts the next view that makes IT primary."""
        view_me = c.read("view")
        epoch_me = c.read("wd_epoch")
        epoch_ok = c.arg("epoch") == epoch_me
        fire = epoch_ok & ~(c.arg("view") < view_me) & \
            ~(c.me == primary_of(view_me))
        cand = view_me + ((c.me - primary_of(view_me)) % n + n) % n
        view_wd = c.where(fire, c.maximum(cand, view_me + 1), view_me)
        became_primary = fire & (c.me == primary_of(view_wd))
        epoch2 = epoch_me + c.where(fire, 1, 0)
        delay = c.uniform(p.watchdog_min_us, p.watchdog_max_us)
        c.write("view", view_wd)
        c.write("wd_epoch", epoch2)
        c.count("views_changed", when=fire)
        c.broadcast("Replicate", [view_wd, 0, 0], when=became_primary)
        c.arm("Watchdog", delay=delay, words=[view_wd, epoch2],
              when=epoch_ok & ~became_primary)
        c.arm("Heartbeat", delay=p.heartbeat_us, words=[view_wd, epoch2],
              when=became_primary)

    # -- init / restart / invariant / observe --------------------------
    def init(c):
        # Primary of view 0 (node 0) heartbeats; backups watch.
        c.event("Heartbeat", time=p.heartbeat_us, dst=0, words=[0, 0])
        for i in range(1, n):
            c.event("Watchdog", time=c.uniform(p.watchdog_min_us,
                                               p.watchdog_max_us),
                    dst=i, words=[0, 0])
        for w in range(p.n_writes):
            t = p.write_start_us + w * p.write_interval_us
            for i in range(n):  # broadcast; only the current primary acts
                c.event("Write", time=t, dst=i, words=[w + 1])

    def on_restart(c):
        """Log, commit and view are persistent (disk); the ack
        bookkeeping lane is declared volatile (auto-reset before this
        hook). Bump the epoch so pending watchdogs go stale, re-arm."""
        epoch2 = c.read("wd_epoch") + 1
        c.write("wd_epoch", epoch2)
        c.arm("Watchdog", delay=c.uniform(p.watchdog_min_us,
                                          p.watchdog_max_us),
              words=[c.read("view"), epoch2])

    def invariant(v):
        """Durability: the current primary's log must contain every
        entry ever reported committed, verbatim."""
        view = v.lane("view")
        primary = v.np.max(view) % n
        k = v.np.arange(L)
        mask = k < v.lane("committed_max")
        plog = v.sel("log_cmd", primary)
        plen = v.sel("log_len", primary)
        return v.np.any(mask & ((k >= plen)
                                | (plog != v.lane("committed_cmd"))))

    def obs(name, red):
        def fn(o):
            import jax.numpy as jnp

            return getattr(jnp, red)(o.raw(name), axis=-1) if red \
                else o.raw(name)
        return fn

    return ActorSpec(
        name="pb",
        n_nodes=n,
        lanes=lanes,
        messages=messages,
        handlers={"Write": h_write, "Replicate": h_replicate,
                  "Ack": h_ack, "Commit": h_commit,
                  "Heartbeat": h_heartbeat, "Watchdog": h_watchdog},
        init=init,
        on_restart=on_restart,
        invariant=invariant,
        observe={"max_view": obs("view", "max"),
                 "committed_max": obs("committed_max", None),
                 "min_commit": obs("commit", "min")},
        invariant_id="pb_durability",
        terminal=("Commit",),
    )
