"""Spec-defined protocol families (docs/actorc.md).

``tpc`` and ``pb`` are the migrated families: their specs transliterate
the formerly hand-written merged handlers 1:1 and the original test
suites (tests/test_tpc_actor.py, tests/test_pb_actor.py) run unchanged
against the compiled actors. ``paxos`` is the first DSL-only family —
multi-decree Paxos with a forgetful-acceptor bug switch for the guided
hunt (search/hunts.py ``paxos_hunt``). The raft actor deliberately
stays hand-written in :mod:`madsim_tpu.engine.raft_actor` as the craft
reference the compiler's output is compared against.
"""
