"""Multi-decree Paxos — the first DSL-only protocol family.

No hand-written twin exists or ever will: this spec is the proof that
the actor compiler makes new scenario families cheap (ROADMAP item 3).
Every node is proposer, acceptor and learner; decrees (slots) are
pre-assigned to proposers by the command schedule, with one *contended*
slot proposed by two different nodes at close times — the ballot race
classic Paxos resolves safely through promise/adoption, and the race
the guided hunt weaponizes.

Protocol (per slot): a command starts ballot ``round*n + me + 1`` —
PREPARE broadcast, acceptors PROMISE (reporting any accepted
(ballot, value)), on promise quorum the proposer ACCEPTs the
highest-ballot reported value (or its own), acceptors ACCEPTED, on
accepted quorum the value is CHOSEN and broadcast to the learners. A
retry timer re-prepares with a higher ballot while the slot is
undecided.

Invariant: **consistency** — no two nodes may learn different values
for the same slot (event-time check in the Chosen handler + a
state-scan over the learned table). The injected bug,
``buggy_forgetful_acceptor``, marks the acceptor lanes
(``promised``/``acc_bal``/``acc_val``) volatile across restart — the
textbook "Paxos requires stable storage" violation, expressed as ONE
flipped ``durable`` annotation. A restart of the right acceptor in the
window between one proposer's accept-quorum and the rival's re-prepare
erases the only memory forcing value adoption, and the rival drives a
second value to quorum: both values chosen, the hunt's target.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from ...engine.core import EngineConfig, FAULT_RESTART
from ..compile import CompiledActor
from ..spec import ActorSpec, Lane, Message, Word

I16 = 32767

# Kind codes (spec declaration order).
K_CMD, K_PREPARE, K_PROMISE, K_ACCEPT, K_ACCEPTED, K_CHOSEN, K_RETRY = \
    range(7)


@dataclasses.dataclass(frozen=True)
class PaxosConfig:
    """Static multi-decree Paxos parameters."""

    n: int = 5                    # nodes (proposer+acceptor+learner each)
    n_slots: int = 3              # decrees
    cmd_start_us: int = 40_000
    cmd_interval_us: int = 30_000
    # The contended decree: proposed by BOTH node (slot % n) and node
    # ((slot + 2) % n), the second ``contend_gap_us`` later.
    contend_slot: int = 1
    contend_gap_us: int = 5_000
    # Contend EVERY decree instead of just one — the guided-hunt shape:
    # each slot's ballot race opens its own amnesia window, so the
    # violating restart band spans the whole command schedule instead
    # of one ~20 ms notch.
    contend_all: bool = False
    retry_min_us: int = 150_000
    retry_max_us: int = 400_000
    # Injected bug: acceptor state on memory instead of disk — restarts
    # forget promises and accepted values (see module docstring).
    buggy_forgetful_acceptor: bool = False


def paxos_spec(xcfg: PaxosConfig) -> ActorSpec:
    """Build the multi-decree Paxos spec from a :class:`PaxosConfig`."""
    x = xcfg
    n, S = x.n, x.n_slots
    q = n // 2 + 1
    durable_acc = not x.buggy_forgetful_acceptor

    lanes = (
        # Acceptor lanes — THE disk-vs-memory decision of Paxos: the
        # protocol is only safe if these survive restarts.
        Lane("promised", hi=I16, scope="node_table", cols=S,
             durable=durable_acc),
        Lane("acc_bal", hi=I16, scope="node_table", cols=S,
             durable=durable_acc),
        Lane("acc_val", hi=I16, scope="node_table", cols=S,
             durable=durable_acc),
        # Proposer lanes. prop_bal stops short of the int16 rail: the
        # retry path bids prop_bal + n, and speclint's capacity proof
        # (SPC030) demands the bumped ballot still fit the packed lane.
        Lane("prop_bal", hi=32000, scope="node_table", cols=S),
        Lane("prop_val", hi=I16, scope="node_table", cols=S),
        Lane("promises", hi=(1 << 31) - 1, scope="node_table", cols=S,
             kind="bitmask"),
        Lane("accepts", hi=(1 << 31) - 1, scope="node_table", cols=S,
             kind="bitmask"),
        Lane("seen_bal", hi=I16, scope="node_table", cols=S),
        Lane("seen_val", hi=I16, scope="node_table", cols=S),
        # Learner lane: 0 = undecided, else the chosen value.
        Lane("chosen", hi=I16, scope="node_table", cols=S),
        Lane("proposals", hi=(1 << 31) - 1, scope="world",
             kind="counter"),
        Lane("retries", hi=(1 << 31) - 1, scope="world", kind="counter"),
        Lane("chosen_count", hi=(1 << 31) - 1, scope="world",
             kind="counter"),
    )

    messages = (
        Message("Cmd", (Word("slot", 0, S - 1), Word("val", 1, I16))),
        Message("Prepare", (Word("bal", 1, I16), Word("slot", 0, S - 1))),
        Message("Promise", (Word("bal", 1, I16), Word("slot", 0, S - 1),
                            Word("abal", 0, I16), Word("aval", 0, I16),
                            Word("voter", 0, n - 1))),
        # val words admit 0 here: the adopted value is where(seen, seen,
        # own) whose static lower bound is the lanes' 0 floor, and the
        # payload-bound proof (SPC031) holds sends to declared ranges.
        Message("Accept", (Word("bal", 1, I16), Word("slot", 0, S - 1),
                           Word("val", 0, I16))),
        Message("Accepted", (Word("bal", 1, I16), Word("slot", 0, S - 1),
                             Word("voter", 0, n - 1),
                             Word("val", 0, I16))),
        Message("Chosen", (Word("slot", 0, S - 1), Word("val", 0, I16))),
        Message("Retry", (Word("slot", 0, S - 1),), timer=True),
    )

    def proposer_of(bal):
        return (bal - 1) % n

    def _start_round(c, slot, bal, when):
        """Shared proposer round start: self-promise (when still
        allowed), fresh vote books, PREPARE broadcast."""
        promised_me = c.read_at("promised", slot)
        self_ok = bal > promised_me
        c.write_at("promised", slot, bal, when=when & self_ok)
        c.write_at("prop_bal", slot, bal, when=when)
        c.write_at("promises", slot, c.where(self_ok, 1 << c.me, 0),
                   when=when)
        c.write_at("accepts", slot, 0, when=when)
        # The proposer's own promise reports its own accepted state.
        c.write_at("seen_bal", slot,
                   c.where(self_ok, c.read_at("acc_bal", slot), 0),
                   when=when)
        c.write_at("seen_val", slot,
                   c.where(self_ok, c.read_at("acc_val", slot), 0),
                   when=when)
        c.broadcast("Prepare", [bal, slot], when=when)
        c.arm("Retry", delay=c.uniform(x.retry_min_us, x.retry_max_us),
              words=[slot], when=when)

    # -- transitions ---------------------------------------------------
    def h_cmd(c):
        """A scheduled client command reaches its proposer: start
        ballot me+1 (round 0) for the assigned slot."""
        slot = c.clip(c.arg("slot"), 0, S - 1)
        go = (c.read_at("prop_bal", slot) == 0) & \
            (c.read_at("chosen", slot) == 0)
        c.write_at("prop_val", slot, c.arg("val"), when=go)
        c.count("proposals", when=go)
        _start_round(c, slot, c.me + 1, go)

    def h_prepare(c):
        """Acceptor: promise a higher ballot, reporting any accepted
        (ballot, value) — the memory that forces value adoption."""
        slot = c.clip(c.arg("slot"), 0, S - 1)
        bal = c.arg("bal")
        ok = bal > c.read_at("promised", slot)
        c.write_at("promised", slot, bal, when=ok)
        c.send("Promise", dst=proposer_of(bal),
               words=[bal, slot, c.read_at("acc_bal", slot),
                      c.read_at("acc_val", slot), c.me], when=ok)

    def h_promise(c):
        """Proposer: collect promises; on quorum, ACCEPT the
        highest-ballot reported value (or our own)."""
        slot = c.clip(c.arg("slot"), 0, S - 1)
        bal = c.arg("bal")
        live = (bal == c.read_at("prop_bal", slot)) & \
            (c.read_at("chosen", slot) == 0)
        voter = c.clip(c.arg("voter"), 0, n - 1)
        pm = c.read_at("promises", slot)
        pm2 = pm | c.where(live, 1 << voter, 0)
        sb, sv = c.read_at("seen_bal", slot), c.read_at("seen_val", slot)
        better = live & (c.arg("abal") > sb)
        sb2 = c.where(better, c.arg("abal"), sb)
        sv2 = c.where(better, c.arg("aval"), sv)
        cross = live & (c.popcount(pm2) >= q) & (c.popcount(pm) < q)
        val = c.where(sb2 > 0, sv2, c.read_at("prop_val", slot))
        c.write_at("promises", slot, pm2, when=live)
        c.write_at("seen_bal", slot, sb2, when=live)
        c.write_at("seen_val", slot, sv2, when=live)
        c.write_at("prop_val", slot, val, when=cross)
        # Self-accept (the proposer is an acceptor too), if no higher
        # prepare has arrived in the meantime.
        sok = cross & (bal >= c.read_at("promised", slot))
        c.write_at("promised", slot, bal, when=sok)
        c.write_at("acc_bal", slot, bal, when=sok)
        c.write_at("acc_val", slot, val, when=sok)
        c.write_at("accepts", slot, c.where(sok, 1 << c.me, 0),
                   when=cross)
        c.broadcast("Accept", [bal, slot, val], when=cross)

    def h_accept(c):
        """Acceptor: accept a value at or above the promised ballot."""
        slot = c.clip(c.arg("slot"), 0, S - 1)
        bal = c.arg("bal")
        ok = bal >= c.read_at("promised", slot)
        c.write_at("promised", slot, bal, when=ok)
        c.write_at("acc_bal", slot, bal, when=ok)
        c.write_at("acc_val", slot, c.arg("val"), when=ok)
        c.send("Accepted", dst=proposer_of(bal),
               words=[bal, slot, c.me, c.arg("val")], when=ok)

    def h_accepted(c):
        """Proposer: on accepted-quorum the value is chosen — learn it
        and tell everyone."""
        slot = c.clip(c.arg("slot"), 0, S - 1)
        bal = c.arg("bal")
        live = (bal == c.read_at("prop_bal", slot)) & \
            (c.read_at("chosen", slot) == 0)
        voter = c.clip(c.arg("voter"), 0, n - 1)
        am = c.read_at("accepts", slot)
        am2 = am | c.where(live, 1 << voter, 0)
        cross = live & (c.popcount(am2) >= q) & (c.popcount(am) < q)
        c.write_at("accepts", slot, am2, when=live)
        c.write_at("chosen", slot, c.arg("val"), when=cross)
        c.count("chosen_count", when=cross)
        c.broadcast("Chosen", [slot, c.arg("val")], when=cross)

    def h_chosen(c):
        """Learner: adopt the chosen value — and flag the consistency
        violation the moment a CONFLICTING choice arrives (the
        event-time invariant form)."""
        slot = c.clip(c.arg("slot"), 0, S - 1)
        cur = c.read_at("chosen", slot)
        c.bug((cur > 0) & (cur != c.arg("val")))
        c.write_at("chosen", slot, c.arg("val"), when=cur == 0)

    def h_retry(c):
        """Proposer liveness: while the slot is undecided, re-prepare
        with the next ballot in our residue class."""
        slot = c.clip(c.arg("slot"), 0, S - 1)
        started = c.read_at("prop_bal", slot) > 0
        go = started & (c.read_at("chosen", slot) == 0)
        c.count("retries", when=go)
        _start_round(c, slot, c.read_at("prop_bal", slot) + n, go)

    # -- init / invariant / observe ------------------------------------
    def init(c):
        for s in range(S):
            p = s % n
            c.event("Cmd", time=x.cmd_start_us + s * x.cmd_interval_us,
                    dst=p, words=[s, s * 8 + p + 1])
        # The contended decree(s): a second proposer, a beat later,
        # with a different value — the ballot race.
        contended = range(S) if x.contend_all else [x.contend_slot % S]
        for s in contended:
            p2 = (s + 2) % n
            c.event("Cmd",
                    time=x.cmd_start_us + s * x.cmd_interval_us
                    + x.contend_gap_us,
                    dst=p2, words=[s, s * 8 + p2 + 1])

    def invariant(v):
        """Consistency: all nonzero learned values per slot agree."""
        ch = v.lane("chosen")                    # (N, S)
        mx = v.np.max(ch, axis=0)                # (S,)
        return v.np.any((ch > 0) & (ch != mx[None, :]))

    def obs_slots_decided(o):
        import jax.numpy as jnp

        return jnp.sum(jnp.any(o.raw("chosen") > 0, axis=-2)
                       .astype(jnp.int32), axis=-1)

    def obs_max_ballot(o):
        import jax.numpy as jnp

        return jnp.max(o.raw("prop_bal"), axis=(-2, -1))

    return ActorSpec(
        name="paxos",
        n_nodes=n,
        lanes=lanes,
        messages=messages,
        handlers={"Cmd": h_cmd, "Prepare": h_prepare,
                  "Promise": h_promise, "Accept": h_accept,
                  "Accepted": h_accepted, "Chosen": h_chosen,
                  "Retry": h_retry},
        init=init,
        on_restart=None,
        invariant=invariant,
        observe={"slots_decided": obs_slots_decided,
                 "max_ballot": obs_max_ballot},
        invariant_id="paxos_chosen_conflict",
        terminal=("Chosen",),
        # The forgetful-acceptor variant deliberately trips speclint's
        # durability rule — the amnesia IS the experiment (the lanes go
        # volatile with nothing to reconstruct them).
        lint_allow=("SPC050",) if x.buggy_forgetful_acceptor else (),
    )


class PaxosActor(CompiledActor):
    """Multi-decree Paxos, compiled from its actorc spec — registered
    in the obs replay registry and the actor-family registry like any
    hand-written family."""

    def __init__(self, xcfg: PaxosConfig = PaxosConfig()):
        super().__init__(paxos_spec(xcfg))
        self.xcfg = xcfg


def engine_config(xcfg: PaxosConfig = PaxosConfig(),
                  metrics: bool = False) -> EngineConfig:
    """The canonical engine shape for this family (PROMISE carries five
    payload words)."""
    return EngineConfig(n_nodes=xcfg.n, outbox_cap=xcfg.n + 1,
                        queue_cap=128, payload_words=5,
                        t_limit_us=2_000_000, metrics=metrics)


def hunt_template(xcfg: PaxosConfig = PaxosConfig(),
                  n_rows: int = 6) -> np.ndarray:
    """The benign fault-schedule template of the guided Paxos hunt:
    restarts at EARLY times — all before ``cmd_start_us``, when no
    acceptor state exists yet — so no subset of the template can
    trigger the forgetful-acceptor bug. The violation needs TWO
    restarts jittered forward into the ~20 ms amnesia window between
    the first proposer's accept-quorum and the rival's promise-quorum
    on the contended decree (measured: one in-window restart violates
    ~1% of seeds, two violate up to ~7%). One in-window restart is
    behaviorally visible (perturbed rounds, retries), so the guided
    corpus keeps it as a parent and the second hop — another jitter or
    a splice of two one-hit parents — reaches the conjunction; a
    random single-pass mutation of this template must land both rows
    at once (docs/search.md "when guided beats random")."""
    rows = np.zeros((n_rows, 4), np.int32)
    rows[:, 0] = 4_000 * (1 + np.arange(n_rows))
    rows[:, 1] = FAULT_RESTART
    rows[:, 2] = [(i * 2) % xcfg.n for i in range(n_rows)]
    return rows
