"""Host-twin conformance: lockstep crosscheck of a compiled actor
against its generated plain-Python reference interpreter.

The oracle pattern (PR 9's FNV twin, PR 12's corpus-merge twin) applied
to the actor compiler: one device pass records, per step of a real
trajectory, the popped event, the engine's deliver/fault gates, the raw
entropy the handler would draw, the handler's outbox, and the post-step
actor state; the host twin (:mod:`madsim_tpu.actorc.host`) then replays
the SAME event stream through the shared transition callables and every
per-event state lane, outbox row and bug decision is compared bitwise.
A mismatch is a compiler bug or a spec stepping outside the restricted
expression surface — either way it surfaces here, with the seed, step,
event and lane named, instead of as silent divergence deep inside a
million-seed sweep.

The recorder is one jitted scan vmapped over the seed axis (one compile
per engine, all sampled seeds in one dispatch); the comparison loop is
host-side Python over the pulled arrays.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.core import DeviceEngine, EngineConfig, FAULT_RESTART
from ..engine.lanes import take_small, widen
from ..engine.queue import FLAG_FAULT, FLAG_TIMER, GEN_MASK, eligible_mask, pop
from ..ops.threefry import threefry2x32_jax
from .compile import CompiledActor
from .host import HostActor
from .spec import ActorSpec

__all__ = ["crosscheck", "HostTwinMismatch", "ENTROPY_WORDS"]

# Raw u32 words recorded per step: the handler draws at most one (the
# compiler's static-draw rule), restart hooks may draw a few more —
# sequential next_u32 values ARE the Threefry stream at consecutive
# counters, so recording a block covers both.
ENTROPY_WORDS = 4


class HostTwinMismatch(AssertionError):
    """Device actor and generated host twin disagreed on an event."""


def _recorder(eng: DeviceEngine, max_steps: int):
    """One world's instrumented replay: scan ``max_steps`` engine steps,
    recording the per-step event, gates, entropy, handler/restart
    outputs and post-step state. Vmapped over worlds by the caller."""
    cfg = eng.cfg
    actor = eng.actor
    n = cfg.n_nodes

    def body(s, _):
        # The same peek + gate derivation DeviceEngine.trace uses: the
        # step's own pop happens inside _step_one below.
        _q, ev, found = pop(
            s.queue, eligible_mask(s.queue, s.paused, n) & s.active)
        now = jnp.where(found, jnp.maximum(s.now, ev.time), s.now)
        in_time = now < jnp.int32(cfg.t_limit_us)
        dst = jnp.clip(ev.dst, 0, n - 1)
        is_fault = (ev.flags & FLAG_FAULT) != 0
        is_timer = (ev.flags & FLAG_TIMER) != 0
        stale = is_timer & (ev.gen != (widen(take_small(s.gen, dst))
                                       & GEN_MASK))
        dead = ~take_small(s.alive, dst)
        deliver = found & in_time & ~is_fault & ~stale & ~dead
        do_fault = found & in_time & is_fault
        restart = do_fault & (ev.kind == FAULT_RESTART)
        rnode = jnp.clip(ev.src, 0, n - 1)

        # The entropy block the handler/restart hook would consume:
        # consecutive counters from the current cursor.
        ctrs = s.rng.counter + jnp.arange(ENTROPY_WORDS, dtype=jnp.uint32)
        entropy, _ = threefry2x32_jax(
            s.rng.k0, s.rng.k1, ctrs,
            jnp.zeros((ENTROPY_WORDS,), jnp.uint32))

        # What the step WILL do, recorded from the same calls it makes.
        _sh, ob_h, _rh, hbug = actor.handle(cfg, s.astate, ev, now, s.rng)
        _sr, ob_r, _rr = actor.on_restart(cfg, s.astate, rnode, now, s.rng)

        s2 = eng._step_one(s)
        rec = dict(
            found=found, deliver=deliver, restart=restart, rnode=rnode,
            now=now, kind=ev.kind, dst=ev.dst, src=ev.src,
            payload=ev.payload, entropy=entropy, hbug=hbug,
            ob_h=ob_h, ob_r=ob_r, astate=s2.astate, bug=s2.bug)
        return s2, rec

    def run(state0):
        _final, recs = jax.lax.scan(body, state0, None, length=max_steps)
        return recs

    return run


def _neq(a, b) -> bool:
    return not np.array_equal(np.asarray(a), np.asarray(b))


def _cmp_state(where: str, dev: Dict[str, Any], host: Dict[str, Any]):
    for name in host:
        if _neq(dev[name], host[name]):
            raise HostTwinMismatch(
                f"{where}: lane {name!r} diverged\n  device: "
                f"{np.asarray(dev[name])!r}\n  host:   "
                f"{np.asarray(host[name])!r}")


def _cmp_outbox(where: str, dev, host):
    for field in ("valid", "is_timer", "kind", "dst", "delay_us",
                  "payload"):
        d, h = getattr(dev, field), getattr(host, field)
        if _neq(d, h):
            raise HostTwinMismatch(
                f"{where}: outbox field {field!r} diverged\n  device: "
                f"{np.asarray(d)!r}\n  host:   {np.asarray(h)!r}")


def crosscheck(spec: ActorSpec, cfg: EngineConfig,
               seeds: Sequence[int], faults: Optional[np.ndarray] = None,
               max_steps: int = 400,
               engine: Optional[DeviceEngine] = None) -> Dict[str, Any]:
    """Crosscheck compiled-vs-host on real trajectories; see module
    docstring. Raises :class:`HostTwinMismatch` on the first
    divergence; returns an accounting report otherwise."""
    eng = engine or DeviceEngine(CompiledActor(spec), cfg)
    host = HostActor(spec, packed=cfg.packed,
                     payload_words=cfg.payload_words)
    seeds = np.asarray(seeds, np.uint64)
    states = eng.init(seeds, faults=faults)
    recs = jax.jit(jax.vmap(_recorder(eng, max_steps)))(states)
    recs = jax.device_get(recs)

    lanes = [ln.name for ln in spec.lanes]
    delivered = restarts = checked = 0
    for w, seed in enumerate(seeds):
        hstate = host.init_state()
        tag0 = f"spec {spec.name!r} seed {int(seed)}"
        _cmp_state(f"{tag0} initial state",
                   {k: np.asarray(states.astate[k])[w] for k in lanes},
                   hstate)
        hlatch = False
        for i in range(max_steps):
            tag = f"{tag0} step {i}"
            ent = [int(x) for x in recs["entropy"][w, i]]
            if recs["deliver"][w, i]:
                hstate, hob, hbug = host.handle(
                    hstate, kind=int(recs["kind"][w, i]),
                    dst=int(recs["dst"][w, i]),
                    src=int(recs["src"][w, i]),
                    payload=[int(x) for x in recs["payload"][w, i]],
                    now=int(recs["now"][w, i]), entropy=ent)
                _cmp_outbox(
                    f"{tag} (deliver kind "
                    f"{eng.actor.kind_names[int(recs['kind'][w, i]) % len(eng.actor.kind_names)]})",
                    jax.tree.map(lambda x: x[w, i], recs["ob_h"]), hob)
                if bool(recs["hbug"][w, i]) != hbug:
                    raise HostTwinMismatch(
                        f"{tag}: handler bug flag diverged (device "
                        f"{bool(recs['hbug'][w, i])}, host {hbug})")
                hlatch = hlatch or hbug
                delivered += 1
            elif recs["restart"][w, i]:
                hstate, hob = host.on_restart(
                    hstate, node=int(recs["rnode"][w, i]),
                    now=int(recs["now"][w, i]), entropy=ent)
                _cmp_outbox(f"{tag} (restart node "
                            f"{int(recs['rnode'][w, i])})",
                            jax.tree.map(lambda x: x[w, i], recs["ob_r"]),
                            hob)
                restarts += 1
            _cmp_state(tag,
                       {k: np.asarray(recs["astate"][k])[w, i]
                        for k in lanes}, hstate)
            hlatch = hlatch or host.invariant(hstate)
            if bool(recs["bug"][w, i]) != bool(hlatch):
                raise HostTwinMismatch(
                    f"{tag}: bug decision diverged (device "
                    f"{bool(recs['bug'][w, i])}, host twin {hlatch})")
            checked += 1
    return {"n_seeds": len(seeds), "steps_checked": checked,
            "events_delivered": delivered, "restarts": restarts,
            "max_steps": max_steps}
