"""The actor compiler: lower an :class:`~madsim_tpu.actorc.spec.ActorSpec`
to a DeviceEngine actor.

What the compiler owns — the craft that previously had to be re-threaded
by hand through every actor family (ROADMAP item 3):

- **Lane layout**: at-rest dtypes selected from the declared value
  ranges (:func:`~madsim_tpu.actorc.spec.lane_dtype`), so the PR 10
  wide-in-flight/narrow-at-rest packing discipline holds by
  construction. Every lane *read* passes through ``lanes.widen`` (the
  one sanctioned narrow-to-wide site, tracelint TRC005) and every
  *write* through the saturating ``narrow`` inside ``upd``/``upd2`` —
  a compiled family cannot leak a narrow dtype into handler arithmetic
  even if its author has never heard of the discipline.
- **Merged-handler dispatch** (docs/ACTORS.md "write them merged"):
  every kind's transition is evaluated once per step against shared
  reads, writes are combined with kind-predicate ``where`` chains, and
  the whole outbox is assembled through ONE ``actor_util.make_outbox``
  call — the (N peers + 1 timer) layout all families share.
- **The bounded-RNG-draw discipline** ``engine/conformance.py``
  checks: exactly one u32 is drawn per step; transitions that consume
  it advance the counter conditionally, so draw counts are static and
  trajectories replay bit-exactly (the ``rng._replace(counter=...)``
  pattern, generated instead of hand-written).
- **Restart semantics** from the ``durable`` annotations: volatile
  lanes reset for the restarting node before the spec's ``on_restart``
  hook runs — the disk-vs-memory decision is a declaration, not code.
- **Observability**: ``kind_names`` always populated from the message
  declarations, counters auto-exported through ``observe()``.

The same spec feeds :mod:`madsim_tpu.actorc.host`, the plain-Python
reference interpreter used as a conformance oracle
(:mod:`madsim_tpu.actorc.conformance`).
"""
from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Tuple

import jax.numpy as jnp
from jax import lax

from ..engine.actor_util import make_outbox
from ..engine.core import EngineConfig, Outbox
from ..engine.lanes import sel, sel2, upd, upd2, widen
from ..engine.queue import Event, FLAG_TIMER
from ..engine.rng import DevRng, _u32_to_range, next_u32, uniform_u32
from .spec import (
    ActorSpec,
    KIND_COUNTER,
    Lane,
    SCOPE_NODE,
    SCOPE_NODE_TABLE,
    SCOPE_WORLD,
    SCOPE_WORLD_VEC,
    SpecError,
    lane_dtype,
    validate_spec,
)

__all__ = ["CompiledActor", "Ctx", "compile_actor"]


@dataclasses.dataclass
class _Send:
    msg: str
    dst: Any          # None => broadcast
    to: Any           # broadcast target mask override (None => others)
    words: Tuple[Any, ...]
    when: Any


@dataclasses.dataclass
class _Arm:
    msg: str
    delay: Any
    words: Tuple[Any, ...]
    when: Any
    dst: Any          # None => the handling node


class Ctx:
    """The restricted expression surface a spec transition writes against.

    One instance is passed to each transition callable; the SAME
    callable runs under the device compiler (values are traced jnp
    scalars) and the host interpreter (values are plain ints), so a
    transition body must restrict itself to:

    - arithmetic / comparison / bitwise Python operators on ctx values;
    - the ctx helpers (``where``, ``maximum``, ``minimum``, ``clip``,
      ``popcount``, ``arange``, and ``np`` for vector expressions);
    - reads (``read*``), guarded writes (``write*``, ``count``),
      message/timer emission (``send``/``broadcast``/``arm``), the
      ``bug`` predicate, and at most ONE RNG draw (``u32``/``uniform``).

    No Python ``if`` on ctx values, no raw indexing, no other imports —
    the compiler cannot check Python control flow, but the host-twin
    crosscheck (docs/actorc.md) catches divergence the moment a
    transition steps outside the shared semantics.
    """

    def __init__(self, spec: ActorSpec, cfg_payload_words: int,
                 me, now, src, msg=None):
        self._spec = spec
        self._pw = cfg_payload_words
        self.me = me
        self.now = now
        self.src = src
        self._msg = msg
        self._writes: List[Tuple[str, str, Any, Any, Any]] = []
        self._sends: List[_Send] = []
        self._arms: List[_Arm] = []
        self._bugs: List[Any] = []
        self._drew = False

    # -- payload words -------------------------------------------------
    def arg(self, name: str):
        """The named payload word of the event being handled (wide)."""
        if self._msg is None:
            raise SpecError(f"spec {self._spec.name!r}: arg({name!r}) is "
                            "only available inside a message handler")
        for i, wd in enumerate(self._msg.words):
            if wd.name == name:
                return self._payload_word(i)
        raise SpecError(
            f"spec {self._spec.name!r}: message {self._msg.name!r} has no "
            f"word {name!r} (declared: "
            f"{[w.name for w in self._msg.words]})")

    # -- guarded writes ------------------------------------------------
    def write(self, lane: str, value, when=True) -> None:
        """Write the handling node's value of a per-node lane."""
        self._record(SCOPE_NODE, lane, None, value, when)

    def write_at(self, lane: str, col, value, when=True) -> None:
        """Write the handling node's row of a node-table lane at ``col``
        (clipped into range, like every ctx column index)."""
        self._record(SCOPE_NODE_TABLE, lane, col, value, when)

    def write_vec_at(self, lane: str, idx, value, when=True) -> None:
        self._record(SCOPE_WORLD_VEC, lane, idx, value, when)

    def write_vec(self, lane: str, value, when=True) -> None:
        """Full-vector write of a world-vector lane; ``when`` may be a
        per-element mask."""
        self._record("world_vec_full", lane, None, value, when)

    def write_scalar(self, lane: str, value, when=True) -> None:
        self._record(SCOPE_WORLD, lane, None, value, when)

    def count(self, lane: str, amount=1, when=True) -> None:
        """Increment a counter lane (auto-exported by ``observe()``)."""
        if self._spec.lane(lane).kind != KIND_COUNTER:
            raise SpecError(f"spec {self._spec.name!r}: count() targets "
                            f"counter lanes; {lane!r} is not one")
        self._record("count", lane, None, amount, when)

    def _record(self, op: str, lane: str, idx, value, when) -> None:
        ln = self._spec.lane(lane)
        expect = {SCOPE_NODE: SCOPE_NODE, SCOPE_NODE_TABLE: SCOPE_NODE_TABLE,
                  SCOPE_WORLD_VEC: SCOPE_WORLD_VEC,
                  "world_vec_full": SCOPE_WORLD_VEC,
                  SCOPE_WORLD: SCOPE_WORLD, "count": SCOPE_WORLD}[op]
        if ln.scope != expect:
            raise SpecError(
                f"spec {self._spec.name!r}: lane {lane!r} has scope "
                f"{ln.scope!r}; this write form needs {expect!r}")
        self._writes.append((op, lane, idx, value, when))

    # -- messages / timers --------------------------------------------
    def send(self, msg: str, dst, words=(), when=True) -> None:
        """Send one message to node ``dst``."""
        self._emit_msg(msg, timer=False)
        self._sends.append(_Send(msg, dst, None, tuple(words), when))

    def broadcast(self, msg: str, words=(), when=True, to=None) -> None:
        """Send one message to every other node (or the ``to`` mask)."""
        self._emit_msg(msg, timer=False)
        self._sends.append(_Send(msg, None, to, tuple(words), when))

    def arm(self, timer: str, delay, words=(), when=True, dst=None) -> None:
        """Arm one timer: delivered to ``dst`` (default: this node)
        after ``delay`` µs, generation-checked like every timer."""
        self._emit_msg(timer, timer=True)
        self._arms.append(_Arm(timer, delay, tuple(words), when, dst))

    def _emit_msg(self, name: str, timer: bool) -> None:
        m = self._spec.message(name)
        if m.timer != timer:
            kindw = "a timer" if m.timer else "a message"
            raise SpecError(f"spec {self._spec.name!r}: {name!r} is "
                            f"declared {kindw}; use "
                            f"{'arm' if m.timer else 'send/broadcast'}()")

    def _check_words(self, msg: str, words) -> None:
        m = self._spec.message(msg)
        if len(words) != len(m.words):
            raise SpecError(
                f"spec {self._spec.name!r}: {msg!r} declares "
                f"{len(m.words)} payload words "
                f"({[w.name for w in m.words]}); got {len(words)}")

    # -- the bug flag --------------------------------------------------
    def bug(self, when) -> None:
        """Latch the world's bug flag when ``when`` holds — the
        event-time invariant form docs/ACTORS.md prefers."""
        self._bugs.append(when)

    # -- RNG (at most one draw per transition) -------------------------
    def u32(self):
        """The step's raw u32 draw; marks it consumed."""
        self._mark_draw()
        return self._raw_u32()

    def uniform(self, lo: int, hi: int):
        """The step's draw mapped to [lo, hi) — engine
        ``uniform_u32`` parity, so host and device agree bit-for-bit."""
        self._mark_draw()
        return self._uniform(lo, hi)

    def _mark_draw(self) -> None:
        if self._drew:
            raise SpecError(
                f"spec {self._spec.name!r}: a transition may draw at most "
                "once per event (the static-draw-shape rule, "
                "docs/ACTORS.md); combine draws into one mapped value")
        self._drew = True


class _DeviceCtx(Ctx):
    """Device backend: reads widen, helpers are jnp, writes/sends are
    recorded for the compiler's merge pass."""

    np = jnp

    def __init__(self, spec, cfg: EngineConfig, state, me, now, src,
                 msg=None, ev=None, u=None):
        super().__init__(spec, cfg.payload_words, me, now, src, msg)
        self._cfg = cfg
        self._state = state
        self._ev = ev
        self._u = u

    # reads (widen-on-read: the TRC005 boundary, placed by construction)
    def read(self, lane: str):
        return widen(sel(self._state[self._lane(lane, SCOPE_NODE)], self.me))

    def read_node(self, lane: str, node):
        ln = self._lane(lane, SCOPE_NODE)
        return widen(sel(self._state[ln], self.clip(node, 0,
                                                    self._spec.n_nodes - 1)))

    def read_at(self, lane: str, col):
        ln = self._spec.lane(lane)
        self._lane(lane, SCOPE_NODE_TABLE)
        return widen(sel2(self._state[lane], self.me,
                          self.clip(col, 0, ln.cols - 1)))

    def read_row(self, lane: str):
        self._lane(lane, SCOPE_NODE_TABLE)
        return widen(sel(self._state[lane], self.me))

    def read_vec_at(self, lane: str, idx):
        ln = self._spec.lane(lane)
        self._lane(lane, SCOPE_WORLD_VEC)
        return widen(sel(self._state[lane], self.clip(idx, 0, ln.cols - 1)))

    def read_vec(self, lane: str):
        self._lane(lane, SCOPE_WORLD_VEC)
        return widen(self._state[lane])

    def read_scalar(self, lane: str):
        self._lane(lane, SCOPE_WORLD)
        return widen(self._state[lane])

    def _lane(self, lane: str, scope: str) -> str:
        ln = self._spec.lane(lane)
        if ln.scope != scope:
            raise SpecError(f"spec {self._spec.name!r}: lane {lane!r} has "
                            f"scope {ln.scope!r}; this read form needs "
                            f"{scope!r}")
        return lane

    # expression helpers
    @staticmethod
    def where(c, a, b):
        return jnp.where(c, a, b)

    @staticmethod
    def maximum(a, b):
        return jnp.maximum(a, b)

    @staticmethod
    def minimum(a, b):
        return jnp.minimum(a, b)

    @staticmethod
    def clip(x, lo, hi):
        return jnp.clip(x, lo, hi)

    @staticmethod
    def popcount(x):
        return lax.population_count(jnp.asarray(x, jnp.int32))

    @staticmethod
    def arange(k: int):
        return jnp.arange(k)

    def others(self):
        """(N,) bool: every node but the handling one."""
        return jnp.arange(self._spec.n_nodes) != self.me

    def _payload_word(self, i: int):
        return self._ev.payload[i]

    def _raw_u32(self):
        return self._u

    def _uniform(self, lo, hi):
        return _u32_to_range(self._u, lo, hi)


class _DeviceRestartCtx(_DeviceCtx):
    """on_restart hook backend: draws advance the carried rng cursor
    unconditionally (a restart is one concrete event, not a merged
    kind), matching the hand-written actors' restart hooks."""

    def __init__(self, spec, cfg, state, node, now, rng: DevRng):
        super().__init__(spec, cfg, state, me=node, now=now, src=node)
        self._rng = rng

    def _mark_draw(self) -> None:
        pass  # unconditional draws; each call advances the cursor

    def _raw_u32(self):
        x, self._rng = next_u32(self._rng)
        return x

    def _uniform(self, lo, hi):
        x, self._rng = uniform_u32(self._rng, lo, hi)
        return x


class _InitCtx:
    """Spec ``init`` backend: schedules the world's seed events.

    Draw order is the contract: ``uniform``/``u32`` advance the world
    RNG cursor in call order, exactly like a hand-written ``init``."""

    np = jnp

    def __init__(self, spec: ActorSpec, cfg: EngineConfig, rng: DevRng):
        self._spec = spec
        self._cfg = cfg
        self._rng = rng
        self._events: List[Event] = []

    def event(self, msg: str, time, dst=0, src=None, words=()) -> None:
        """Schedule one seed event (a timer when ``msg`` is declared
        one — timers are generation-checked from the start)."""
        m = self._spec.message(msg)
        if len(words) != len(m.words):
            raise SpecError(
                f"spec {self._spec.name!r}: init event {msg!r} needs "
                f"{len(m.words)} words ({[w.name for w in m.words]}); "
                f"got {len(words)}")
        self._events.append(Event.make(
            time=time, kind=self._spec.kind_of(msg),
            payload_words=self._cfg.payload_words,
            flags=FLAG_TIMER if m.timer else 0,
            src=dst if src is None else src, dst=dst,
            payload=list(words)))

    def uniform(self, lo: int, hi: int):
        x, self._rng = uniform_u32(self._rng, lo, hi)
        return x

    def u32(self):
        x, self._rng = next_u32(self._rng)
        return x


class _VecReader:
    """Full-lane views for ``invariant`` bodies: every lane widened,
    vector helpers through ``np`` (jnp here; numpy in the host twin).
    The widening function is injected — ``lanes.widen`` on device (the
    sanctioned TRC005 site; invariant runs inside the registered
    ``engine.run`` program), a plain numpy cast in the host twin."""

    def __init__(self, spec: ActorSpec, state, np_mod, widen_fn,
                 sel_fn=None):
        self._spec = spec
        self._state = state
        self.np = np_mod
        self._widen = widen_fn
        self._sel = sel_fn

    def lane(self, name: str):
        self._spec.lane(name)
        return self._widen(self._state[name])

    def sel(self, name: str, i):
        """Row ``i`` of a lane, by a possibly-traced index (the one-hot
        ``lanes.sel`` on device; plain indexing in the host twin)."""
        self._spec.lane(name)
        return self._sel(self._state[name], i)

    def n_nodes(self) -> int:
        return self._spec.n_nodes


class _ObsReader:
    """Raw batched lane views for derived ``observe`` entries — device
    only (observations never feed the host twin), so bodies may use
    jnp reductions with the batched axis conventions of
    docs/ACTORS.md (reduce node axes with axis=-1/-2)."""

    np = jnp

    def __init__(self, spec: ActorSpec, state):
        self._spec = spec
        self._state = state

    def raw(self, name: str):
        self._spec.lane(name)
        return self._state[name]


class CompiledActor:
    """An :class:`~madsim_tpu.actorc.spec.ActorSpec` lowered to the
    DeviceEngine actor protocol (docs/ACTORS.md). Use exactly like a
    hand-written actor::

        eng = DeviceEngine(CompiledActor(my_spec), EngineConfig(...))
    """

    def __init__(self, spec: ActorSpec):
        validate_spec(spec)  # spec-internal checks at construction
        # Pass 4 gate: protocol-level verification (reachability,
        # exhaustiveness, timer discipline, capacity/budget proofs)
        # BEFORE any lowering — a spec with speclint findings does not
        # compile. Escape hatch: spec.lint_allow (per code, or "*").
        from ..analysis.speclint import gate_spec
        gate_spec(spec)
        self.spec = spec
        self.num_kinds = len(spec.messages)
        # Generated families always trace/replay readably: the
        # declaration order IS the kind code table.
        self.kind_names = [m.name for m in spec.messages]
        self.invariant_id = spec.invariant_id or spec.name

    # ------------------------------------------------------------------
    def init(self, cfg: EngineConfig, rng: DevRng):
        validate_spec(self.spec, cfg)  # packed-width guards, pointed
        lt = cfg.lanes
        state = {}
        for ln in self.spec.lanes:
            dt = lane_dtype(ln, lt)
            state[ln.name] = jnp.full(self._shape(ln), ln.init, dt)
        ictx = _InitCtx(self.spec, cfg, rng)
        self.spec.init(ictx)
        return state, ictx._events, ictx._rng

    def _shape(self, ln: Lane) -> Tuple[int, ...]:
        n = self.spec.n_nodes
        return {SCOPE_NODE: (n,), SCOPE_NODE_TABLE: (n, ln.cols),
                SCOPE_WORLD_VEC: (ln.cols,), SCOPE_WORLD: ()}[ln.scope]

    # ------------------------------------------------------------------
    def handle(self, cfg: EngineConfig, s, ev: Event, now, rng: DevRng):
        spec = self.spec
        n = spec.n_nodes
        kind = jnp.clip(ev.kind, 0, self.num_kinds - 1)
        me = jnp.clip(ev.dst, 0, n - 1)
        src = jnp.clip(ev.src, 0, n - 1)
        # ONE draw per step, static shape; transitions that consume it
        # advance the counter conditionally (the docs/ACTORS.md rule).
        u, rng_drawn = next_u32(rng)
        gated: List[Tuple[Any, _DeviceCtx]] = []
        for k, msg in enumerate(spec.messages):
            fn = spec.handlers.get(msg.name)
            if fn is None:
                continue
            t = _DeviceCtx(spec, cfg, s, me=me, now=now, src=src,
                           msg=msg, ev=ev, u=u)
            fn(t)
            gated.append((kind == k, t))
        s2 = self._merge_writes(cfg, s, me, gated)
        ob = self._merge_outbox(cfg, me, gated)
        drew = jnp.asarray(False)
        bug = jnp.asarray(False)
        for pred, t in gated:
            if t._drew:
                drew = drew | pred
            for b in t._bugs:
                bug = bug | (pred & b)
        rng_out = rng._replace(counter=jnp.where(
            drew, rng_drawn.counter, rng.counter))
        return s2, ob, rng_out, bug

    # ------------------------------------------------------------------
    def on_restart(self, cfg: EngineConfig, s, node, now, rng: DevRng):
        spec = self.spec
        node = jnp.clip(node, 0, spec.n_nodes - 1)
        s2 = dict(s)
        # The disk-vs-memory annotations: volatile lanes lose the
        # restarting node's row BEFORE the hook runs (fresh NodeInfo
        # semantics, like the reference's task.rs:229-240).
        for ln in spec.lanes:
            if ln.durable:
                continue
            if ln.scope == SCOPE_NODE:
                s2[ln.name] = upd(s2[ln.name], node, jnp.int32(ln.reset))
            else:  # SCOPE_NODE_TABLE (validate_spec enforces per-node)
                s2[ln.name] = upd(s2[ln.name], node,
                                  jnp.full((ln.cols,), ln.reset, jnp.int32))
        if spec.on_restart is None:
            # An empty outbox in the SAME (N peers + 1 timer) layout the
            # merge pass builds (host-twin parity: slot n is the timer
            # row whether or not anything is armed).
            return s2, self._merge_outbox(cfg, node, []), rng
        t = _DeviceRestartCtx(spec, cfg, s2, node, now, rng)
        spec.on_restart(t)
        s3 = self._merge_writes(cfg, s2, node, [(jnp.asarray(True), t)])
        ob = self._merge_outbox(cfg, node, [(jnp.asarray(True), t)])
        return s3, ob, t._rng

    # ------------------------------------------------------------------
    def invariant(self, cfg: EngineConfig, s):
        v = _VecReader(self.spec, s, jnp, widen,
                       lambda arr, i: widen(sel(arr, i)))
        return jnp.asarray(self.spec.invariant(v), bool)

    # ------------------------------------------------------------------
    def observe(self, cfg: EngineConfig, s) -> dict:
        out = {}
        for ln in self.spec.lanes:
            if ln.kind == KIND_COUNTER:
                out[ln.name] = s[ln.name]
        o = _ObsReader(self.spec, s)
        for name, fn in self.spec.observe.items():
            out[name] = fn(o)
        return out

    # ==================================================================
    # Merge passes
    # ==================================================================
    def _merge_writes(self, cfg: EngineConfig, s, me, gated):
        """Fold every transition's recorded writes into one state
        update per lane, gated on (kind predicate & write condition) —
        the compiled form of the hand-written nested-``where`` merge.
        Narrow-write saturation rides ``upd``/``upd2``/``narrow``."""
        from ..engine.lanes import narrow

        spec = self.spec
        s2 = dict(s)
        for ln in spec.lanes:
            writes = [(pred, w) for pred, t in gated for w in t._writes
                      if w[1] == ln.name]
            if not writes:
                continue
            arr = s2[ln.name]
            if ln.scope == SCOPE_NODE:
                val = widen(sel(arr, me))
                for pred, (_op, _l, _i, v, when) in writes:
                    val = jnp.where(pred & when, v, val)
                arr = upd(arr, me, val)
            elif ln.scope == SCOPE_NODE_TABLE:
                for pred, (_op, _l, col, v, when) in writes:
                    c = jnp.clip(col, 0, ln.cols - 1)
                    cur = widen(sel2(arr, me, c))
                    arr = upd2(arr, me, c, jnp.where(pred & when, v, cur))
            elif ln.scope == SCOPE_WORLD_VEC:
                for pred, (op, _l, idx, v, when) in writes:
                    if op == "world_vec_full":
                        g = pred & when  # ``when`` may be a mask
                        arr = jnp.where(g, narrow(v, arr.dtype), arr)
                    else:
                        i = jnp.clip(idx, 0, ln.cols - 1)
                        cur = widen(sel(arr, i))
                        arr = upd(arr, i, jnp.where(pred & when, v, cur))
            else:  # SCOPE_WORLD (scalars and counters)
                if ln.kind == KIND_COUNTER:
                    total = jnp.int32(0)
                    for pred, (_op, _l, _i, amount, when) in writes:
                        total = total + jnp.where(
                            pred & when, jnp.asarray(amount, jnp.int32), 0)
                    arr = arr + total
                else:
                    for pred, (_op, _l, _i, v, when) in writes:
                        arr = jnp.where(pred & when,
                                        narrow(v, arr.dtype), arr)
            s2[ln.name] = arr
        return s2

    def _merge_outbox(self, cfg: EngineConfig, me, gated) -> Outbox:
        """ONE ``make_outbox`` assembly for the whole step: the
        (N peers + 1 timer) layout every family shares, with sends and
        timer arms merged across kinds by predicate chains."""
        spec = self.spec
        n = spec.n_nodes
        arange = jnp.arange(n)
        m_valid = jnp.zeros((n,), bool)
        m_kind = jnp.int32(0)
        m_words = [jnp.int32(0)] * cfg.payload_words
        t_valid = jnp.asarray(False)
        t_kind = jnp.int32(0)
        t_dst = widen(me)
        t_delay = jnp.int32(0)
        t_words = [jnp.int32(0)] * cfg.payload_words

        for pred, t in gated:
            for snd in t._sends:
                t._check_words(snd.msg, snd.words)
                g = pred & snd.when
                if snd.dst is not None:
                    mask = arange == jnp.clip(snd.dst, 0, n - 1)
                elif snd.to is not None:
                    mask = snd.to
                else:
                    mask = arange != me
                m_valid = jnp.where(g, mask, m_valid)
                m_kind = jnp.where(g, jnp.int32(spec.kind_of(snd.msg)),
                                   m_kind)
                for i, w in enumerate(snd.words):
                    m_words[i] = jnp.where(g, jnp.asarray(w, jnp.int32),
                                           m_words[i])
            for a in t._arms:
                t._check_words(a.msg, a.words)
                g = pred & a.when
                t_valid = t_valid | g
                t_kind = jnp.where(g, jnp.int32(spec.kind_of(a.msg)),
                                   t_kind)
                t_dst = jnp.where(
                    g, widen(me) if a.dst is None
                    else jnp.clip(a.dst, 0, n - 1), t_dst)
                t_delay = jnp.where(g, jnp.asarray(a.delay, jnp.int32),
                                    t_delay)
                for i, w in enumerate(a.words):
                    t_words[i] = jnp.where(g, jnp.asarray(w, jnp.int32),
                                           t_words[i])

        msg_payload = jnp.broadcast_to(
            jnp.stack(m_words), (n, cfg.payload_words))
        return make_outbox(
            cfg, n,
            msg_valid=m_valid,
            msg_kind=jnp.full((n,), m_kind, jnp.int32),
            msg_payload=msg_payload,
            timer_valid=t_valid, timer_kind=t_kind, timer_dst=t_dst,
            timer_delay=t_delay,
            timer_payload=jnp.stack(t_words))


def compile_actor(spec: ActorSpec) -> CompiledActor:
    """Compile ``spec`` to a DeviceEngine actor (docs/actorc.md)."""
    return CompiledActor(spec)
