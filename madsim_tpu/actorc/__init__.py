"""actorc — the actor compiler (docs/actorc.md; ROADMAP item 3).

A declarative protocol-state-machine DSL that lowers to the device
engine's packed-lane actor protocol, with a generated plain-Python host
twin for conformance crosscheck:

- :mod:`~madsim_tpu.actorc.spec` — the spec model (lanes with declared
  value ranges, typed messages/timers, guarded transitions, invariants,
  disk-vs-memory restart annotations) and its pointed validation;
- :mod:`~madsim_tpu.actorc.compile` — the device compiler: packed lane
  layout from declared ranges, widen/narrow boundaries by construction
  (TRC005-clean), merged-handler dispatch, one ``make_outbox`` assembly,
  the bounded-draw RNG discipline, generated ``kind_names`` and
  counter-derived ``observe()``;
- :mod:`~madsim_tpu.actorc.host` — the generated host reference
  interpreter (same spec, same transition callables, numpy backend);
- :mod:`~madsim_tpu.actorc.conformance` — the lockstep per-event
  state/outbox/bug crosscheck between the two;
- :mod:`~madsim_tpu.actorc.families` — the shipped spec-defined
  families: tpc and pb (migrated from hand-written actors, their
  original test suites unchanged) and multi-decree Paxos (the first
  DSL-only family).
"""
from .compile import CompiledActor, Ctx, compile_actor
from .conformance import HostTwinMismatch, crosscheck
from .host import HostActor, HostOutbox
from .spec import ActorSpec, Lane, Message, SpecError, Word, validate_spec

__all__ = [
    "ActorSpec", "Lane", "Message", "Word", "SpecError", "validate_spec",
    "CompiledActor", "Ctx", "compile_actor",
    "HostActor", "HostOutbox",
    "crosscheck", "HostTwinMismatch",
]
