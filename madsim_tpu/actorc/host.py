"""The generated host twin: a plain-Python reference interpreter of an
:class:`~madsim_tpu.actorc.spec.ActorSpec`.

Same spec, second backend: where the device compiler
(:mod:`madsim_tpu.actorc.compile`) evaluates transition callables on
traced jnp scalars and merges writes across kinds, the host interpreter
evaluates exactly ONE transition per event — the active kind's — on
plain Python ints and numpy arrays, applies its guarded writes in call
order, and assembles the same (N peers + 1 timer) outbox layout as
host-side numpy rows. Because the transition *callables are shared*,
the twin is a generated artifact, not a second implementation: any
divergence between the two is a compiler bug, a spec stepping outside
the restricted expression surface, or a saturation boundary firing —
precisely the things the lockstep crosscheck
(:mod:`madsim_tpu.actorc.conformance`) exists to catch, the PR 9/12
host-twin pattern applied to the actor compiler.

Entropy is *injected*, not generated: the crosscheck records the
device's raw u32 draws per event and feeds them here, so the twin
checks transition logic, not Threefry (whose device/host parity is
already tier-1-gated in tests/test_search.py).
"""
from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Sequence, Tuple

import numpy as np

from .compile import Ctx
from .spec import (
    ActorSpec,
    KIND_COUNTER,
    SCOPE_NODE,
    SCOPE_NODE_TABLE,
    SCOPE_WORLD,
    SCOPE_WORLD_VEC,
    lane_dtype,
    validate_spec,
)

__all__ = ["HostActor", "HostOutbox"]


class HostOutbox(NamedTuple):
    """Host mirror of the device ``Outbox`` rows ``make_outbox`` builds:
    slots 0..n-1 are the peer messages (dst = slot index), slot n the
    timer. All int32 numpy (wide in flight, like the device)."""

    valid: np.ndarray
    is_timer: np.ndarray
    kind: np.ndarray
    dst: np.ndarray
    delay_us: np.ndarray
    payload: np.ndarray


def _u32_to_range_host(u: int, lo: int, hi: int) -> int:
    """Bit-exact host mirror of ``engine.rng._u32_to_range``: int32
    width, u32 modulo, int32 result."""
    width = (int(hi) - int(lo)) & 0xFFFFFFFF
    r = (int(u) & 0xFFFFFFFF) % width
    if r >= 1 << 31:
        r -= 1 << 32
    return int(lo) + r


class _HostCtx(Ctx):
    """Host backend of the shared :class:`~madsim_tpu.actorc.compile.Ctx`
    surface: values are numpy scalars (int64 reads, so i32-range
    arithmetic never wraps mid-expression), helpers are numpy, and a
    recorded entropy stream stands in for the RNG. Numpy scalars — not
    Python ints — so comparisons yield ``np.bool_`` and the shared
    transition bodies' ``~pred`` / ``&`` / ``|`` keep their elementwise
    meaning on both backends (Python's ``~True`` is ``-2``)."""

    np = np

    def __init__(self, actor: "HostActor", state, me: int, now: int,
                 src: int, msg=None, payload: Sequence[int] = (),
                 entropy: Sequence[int] = ()):
        super().__init__(actor.spec, actor.payload_words,
                         np.int64(int(me)), np.int64(int(now)),
                         np.int64(int(src)), msg)
        self._actor = actor
        self._state = state
        self._payload = [np.int64(int(x)) for x in payload]
        self._entropy = list(entropy)
        self._cursor = 0

    # reads
    def read(self, lane: str):
        return self._state[lane][int(self.me)].astype(np.int64)

    def read_node(self, lane: str, node):
        n = self._spec.n_nodes
        return self._state[lane][min(max(int(node), 0),
                                     n - 1)].astype(np.int64)

    def read_at(self, lane: str, col):
        ln = self._spec.lane(lane)
        return self._state[lane][int(self.me),
                                 min(max(int(col), 0),
                                     ln.cols - 1)].astype(np.int64)

    def read_row(self, lane: str) -> np.ndarray:
        return self._state[lane][int(self.me)].astype(np.int64)

    def read_vec_at(self, lane: str, idx):
        ln = self._spec.lane(lane)
        return self._state[lane][min(max(int(idx), 0),
                                     ln.cols - 1)].astype(np.int64)

    def read_vec(self, lane: str) -> np.ndarray:
        return self._state[lane].astype(np.int64)

    def read_scalar(self, lane: str):
        return np.asarray(self._state[lane]).astype(np.int64)[()]

    # expression helpers (numpy in, numpy out — see class docstring)
    @staticmethod
    def where(c, a, b):
        return np.where(c, a, b)

    @staticmethod
    def maximum(a, b):
        return np.maximum(a, b)

    @staticmethod
    def minimum(a, b):
        return np.minimum(a, b)

    @staticmethod
    def clip(x, lo, hi):
        return np.clip(x, lo, hi)

    @staticmethod
    def popcount(x) -> int:
        return bin(int(x) & 0xFFFFFFFF).count("1")

    @staticmethod
    def arange(k: int) -> np.ndarray:
        return np.arange(k)

    def others(self) -> np.ndarray:
        return np.arange(self._spec.n_nodes) != self.me

    def _payload_word(self, i: int):
        return self._payload[i] if i < len(self._payload) else np.int64(0)

    def _raw_u32(self):
        if self._cursor >= len(self._entropy):
            raise ValueError(
                f"host twin of spec {self._spec.name!r}: transition drew "
                f"more entropy than recorded ({len(self._entropy)} words)")
        x = np.uint32(int(self._entropy[self._cursor]) & 0xFFFFFFFF)
        self._cursor += 1
        return x

    def _uniform(self, lo, hi):
        return np.int64(_u32_to_range_host(self._raw_u32(), lo, hi))


class _HostRestartCtx(_HostCtx):
    def _mark_draw(self) -> None:
        pass  # restart hooks draw unconditionally, like the device side


class HostActor:
    """Single-world plain-Python interpreter of ``spec``.

    State is a dict of numpy arrays at the *device at-rest dtypes*
    (packed or wide), so the saturating-write boundaries land in the
    same places: a value that would pin at an int16 rail on device pins
    here too, and the crosscheck stays bitwise.
    """

    def __init__(self, spec: ActorSpec, packed: bool = True,
                 payload_words: int = 8):
        from ..engine.lanes import PACKED, WIDE

        validate_spec(spec)
        self.spec = spec
        self.payload_words = payload_words
        profile = PACKED if packed else WIDE
        self._dtypes = {ln.name: np.dtype(lane_dtype(ln, profile))
                        for ln in spec.lanes}

    # ------------------------------------------------------------------
    def init_state(self) -> Dict[str, np.ndarray]:
        n = self.spec.n_nodes
        shapes = {SCOPE_NODE: lambda ln: (n,),
                  SCOPE_NODE_TABLE: lambda ln: (n, ln.cols),
                  SCOPE_WORLD_VEC: lambda ln: (ln.cols,),
                  SCOPE_WORLD: lambda ln: ()}
        return {ln.name: np.full(shapes[ln.scope](ln), ln.init,
                                 self._dtypes[ln.name])
                for ln in self.spec.lanes}

    # ------------------------------------------------------------------
    def handle(self, state: Dict[str, np.ndarray], *, kind: int, dst: int,
               payload: Sequence[int], now: int, src: int = 0,
               entropy: Sequence[int] = ()
               ) -> Tuple[Dict[str, np.ndarray], HostOutbox, bool]:
        """Apply ONE delivered event; returns (state', outbox, bug)."""
        spec = self.spec
        n = spec.n_nodes
        kind = min(max(int(kind), 0), len(spec.messages) - 1)
        me = min(max(int(dst), 0), n - 1)
        src = min(max(int(src), 0), n - 1)
        msg = spec.messages[kind]
        fn = spec.handlers.get(msg.name)
        state2 = {k: v.copy() for k, v in state.items()}
        if fn is None:
            return state2, self._outbox([], [], me), False
        t = _HostCtx(self, state2, me, now, src, msg=msg,
                     payload=payload, entropy=entropy)
        fn(t)
        self._apply_writes(state2, t, me)
        bug = any(bool(b) for b in t._bugs)
        return state2, self._outbox(t._sends, t._arms, me, t), bug

    # ------------------------------------------------------------------
    def on_restart(self, state: Dict[str, np.ndarray], node: int, now: int,
                   entropy: Sequence[int] = ()
                   ) -> Tuple[Dict[str, np.ndarray], HostOutbox]:
        spec = self.spec
        node = min(max(int(node), 0), spec.n_nodes - 1)
        state2 = {k: v.copy() for k, v in state.items()}
        for ln in spec.lanes:
            if ln.durable:
                continue
            state2[ln.name][node] = ln.reset  # row or scalar, both index
        if spec.on_restart is None:
            return state2, self._outbox([], [], node)
        t = _HostRestartCtx(self, state2, node, now, node, entropy=entropy)
        spec.on_restart(t)
        self._apply_writes(state2, t, node)
        return state2, self._outbox(t._sends, t._arms, node, t)

    # ------------------------------------------------------------------
    def invariant(self, state: Dict[str, np.ndarray]) -> bool:
        from .compile import _VecReader

        v = _VecReader(self.spec, state, np,
                       lambda a: np.asarray(a, np.int64),
                       lambda a, i: np.asarray(a[int(i)], np.int64))
        return bool(self.spec.invariant(v))

    # ==================================================================
    def _sat(self, lane: str, v):
        dt = self._dtypes[lane]
        info = np.iinfo(dt)
        return np.clip(v, info.min, info.max).astype(dt)

    def _apply_writes(self, state, t: _HostCtx, me: int) -> None:
        for op, lane, idx, v, when in t._writes:
            ln = self.spec.lane(lane)
            if op == "world_vec_full":
                mask = np.broadcast_to(np.asarray(when, bool),
                                       state[lane].shape)
                state[lane] = np.where(mask, self._sat(lane, v),
                                       state[lane]).astype(
                                           self._dtypes[lane])
                continue
            if not bool(when):
                continue
            if ln.scope == SCOPE_NODE:
                state[lane][me] = self._sat(lane, v)
            elif ln.scope == SCOPE_NODE_TABLE:
                c = min(max(int(idx), 0), ln.cols - 1)
                state[lane][me, c] = self._sat(lane, v)
            elif ln.scope == SCOPE_WORLD_VEC:
                i = min(max(int(idx), 0), ln.cols - 1)
                state[lane][i] = self._sat(lane, v)
            elif ln.kind == KIND_COUNTER:
                state[lane] = (state[lane]
                               + np.int32(int(v))).astype(np.int32)
            else:
                state[lane] = np.asarray(self._sat(lane, v))

    def _outbox(self, sends: List, arms: List, me: int,
                t: _HostCtx = None) -> HostOutbox:
        """Host mirror of the compiler's single-``make_outbox`` merge:
        active sends/arms applied in call order (last write wins, the
        same semantics as the device ``where`` chain)."""
        spec = self.spec
        n = spec.n_nodes
        pw = self.payload_words
        valid = np.zeros((n,), bool)
        kindv = 0
        words = [0] * pw
        t_valid, t_kind, t_dst, t_delay = False, 0, me, 0
        t_words = [0] * pw
        for snd in sends:
            t._check_words(snd.msg, snd.words)
            if not bool(snd.when):
                continue
            if snd.dst is not None:
                mask = np.arange(n) == min(max(int(snd.dst), 0), n - 1)
            elif snd.to is not None:
                mask = np.asarray(snd.to, bool)
            else:
                mask = np.arange(n) != me
            valid = mask.copy()
            kindv = spec.kind_of(snd.msg)
            words = [int(w) for w in snd.words] + [0] * (pw - len(snd.words))
        for a in arms:
            t._check_words(a.msg, a.words)
            if not bool(a.when):
                continue
            t_valid = True
            t_kind = spec.kind_of(a.msg)
            t_dst = me if a.dst is None else min(max(int(a.dst), 0), n - 1)
            t_delay = int(a.delay)
            t_words = [int(w) for w in a.words] + [0] * (pw - len(a.words))
        row = np.asarray(words, np.int32)
        return HostOutbox(
            valid=np.concatenate([valid, np.asarray([t_valid])]),
            is_timer=np.concatenate([np.zeros((n,), bool),
                                     np.asarray([True])]),
            kind=np.concatenate([np.full((n,), kindv, np.int32),
                                 np.asarray([t_kind], np.int32)]),
            dst=np.concatenate([np.arange(n, dtype=np.int32),
                                np.asarray([t_dst], np.int32)]),
            delay_us=np.concatenate([np.zeros((n,), np.int32),
                                     np.asarray([t_delay], np.int32)]),
            payload=np.concatenate([np.broadcast_to(row, (n, pw)),
                                    np.asarray([t_words], np.int32)],
                                   axis=0),
        )
