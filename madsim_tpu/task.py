"""Public task API: spawn / spawn_blocking / JoinHandle.

Reference: `madsim/src/sim/task.rs:369-459` — tokio-style spawn returning an
abortable, awaitable JoinHandle. ``spawn_local`` is an alias (the whole world
is one thread); ``spawn_blocking`` wraps a sync callable as a task that runs
to completion at its scheduling point.
"""
from __future__ import annotations

from typing import Any, Callable, Coroutine

from .core import context
from .core.task import JoinHandle  # noqa: F401 (re-export)

__all__ = ["spawn", "spawn_local", "spawn_blocking", "JoinHandle",
           "available_parallelism", "current_node"]


def spawn(coro: Coroutine) -> JoinHandle:
    """Spawn a coroutine as a task on the current node."""
    return context.current_handle().task.spawn(coro)


def spawn_local(coro: Coroutine) -> JoinHandle:
    return spawn(coro)


def spawn_blocking(fn: Callable[[], Any]) -> JoinHandle:
    async def _runner():
        return fn()

    return spawn(_runner())


def available_parallelism() -> int:
    """The current node's configured core count (the analog of the
    sched_getaffinity/sysconf interception at `task.rs:508-560`)."""
    return context.current_task().node.cores


def current_node():
    """The NodeHandle of the node the current task runs on."""
    handle = context.current_handle()
    return handle.get_node(context.current_task().node.id)
