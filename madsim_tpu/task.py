"""Public task API: spawn / spawn_blocking / JoinHandle.

Reference: `madsim/src/sim/task.rs:369-459` — tokio-style spawn returning an
abortable, awaitable JoinHandle. ``spawn_local`` is an alias (the whole world
is one thread); ``spawn_blocking`` wraps a sync callable as a task that runs
to completion at its scheduling point.

Real backend: spawn delegates to asyncio tasks (the reference's std mode
re-exporting tokio::task, `std/mod.rs:7`); spawn_blocking uses a worker
thread, like tokio's.
"""
from __future__ import annotations

from typing import Any, Callable, Coroutine

from .core import context
from .core.backend import is_real
from .core.task import JoinHandle  # noqa: F401 (re-export)

__all__ = ["spawn", "spawn_local", "spawn_blocking", "JoinHandle",
           "available_parallelism", "current_node"]


class RealJoinHandle:
    """JoinHandle surface over an asyncio task (abort/detach/await)."""

    __slots__ = ("_task",)

    def __init__(self, task):
        self._task = task

    def abort(self) -> None:
        self._task.cancel()

    def detach(self) -> None:
        pass

    def done(self) -> bool:
        return self._task.done()

    def __await__(self):
        return self._task.__await__()


def spawn(coro: Coroutine) -> "JoinHandle | RealJoinHandle":
    """Spawn a coroutine as a task on the current node."""
    if is_real():
        import asyncio

        return RealJoinHandle(asyncio.get_running_loop().create_task(coro))
    return context.current_handle().task.spawn(coro)


def spawn_local(coro: Coroutine) -> "JoinHandle | RealJoinHandle":
    return spawn(coro)


def spawn_blocking(fn: Callable[[], Any]) -> "JoinHandle | RealJoinHandle":
    if is_real():
        import asyncio

        return RealJoinHandle(
            asyncio.get_running_loop().create_task(asyncio.to_thread(fn)))

    async def _runner():
        return fn()

    return spawn(_runner())


def available_parallelism() -> int:
    """The current node's configured core count (the analog of the
    sched_getaffinity/sysconf interception at `task.rs:508-560`)."""
    if is_real():
        import os

        return os.cpu_count() or 1  # detlint: allow[DET004] — real backend
    return context.current_task().node.cores


def current_node():
    """The NodeHandle of the node the current task runs on."""
    handle = context.current_handle()
    return handle.get_node(context.current_task().node.id)
